"""AuthConfig API conversion: v1beta1 (storage/hub shape, named lists) ↔
v1beta2 (user-facing shape, named maps)
(semantics: ref api/v1beta2/auth_config_conversion.go:15-1080; the mapping
tables below follow the same field correspondences).

Specs are plain dicts (parsed YAML/JSON); the framework's native shape is
v1beta2 — v1beta1 resources convert on ingest like the reference's
conversion webhook."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["to_v1beta2", "to_v1beta1", "API_VERSION_V1BETA1", "API_VERSION_V1BETA2"]


def _clean(d: dict) -> dict:
    """Drop None-valued keys: omitted optionals (sharedSecretRef,
    credentialsRef, audiences…) must stay omitted through a round-trip —
    injecting explicit nulls rewrites the stored resource on every webhook
    conversion."""
    return {k: v for k, v in d.items() if v is not None}

API_VERSION_V1BETA1 = "authorino.kuadrant.io/v1beta1"
API_VERSION_V1BETA2 = "authorino.kuadrant.io/v1beta2"


# ---------------------------------------------------------------------------
# value / pattern helpers
# ---------------------------------------------------------------------------

def _v1_static_or_selector(value: Any = None, value_from: Optional[dict] = None) -> dict:
    """v1beta1 {value | valueFrom.authJSON} → v1beta2 {value | selector}"""
    if value_from and value_from.get("authJSON"):
        return {"selector": value_from["authJSON"]}
    return {"value": value}


def _v2_to_v1_value(vs: Optional[dict]) -> Dict[str, Any]:
    """v1beta2 {value | selector} → v1beta1 {value | valueFrom.authJSON}"""
    if not vs:
        return {}
    if vs.get("selector"):
        return {"valueFrom": {"authJSON": vs["selector"]}}
    return {"value": vs.get("value")}


def _v1_props_to_v2(props: Optional[List[dict]]) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for p in props or []:
        out[p.get("name", "")] = _v1_static_or_selector(p.get("value"), p.get("valueFrom"))
    return out


def _v2_props_to_v1(named: Optional[Dict[str, dict]]) -> List[dict]:
    out = []
    for name, vs in (named or {}).items():
        out.append({"name": name, **_v2_to_v1_value(vs)})
    return out


def _v1_pattern_to_v2(p: dict) -> dict:
    out: Dict[str, Any] = {}
    if p.get("patternRef"):
        out["patternRef"] = p["patternRef"]
    if p.get("all") is not None:
        out["all"] = [_v1_pattern_to_v2(x) for x in p["all"]]
    if p.get("any") is not None:
        out["any"] = [_v1_pattern_to_v2(x) for x in p["any"]]
    for k in ("selector", "operator", "value"):
        if p.get(k) is not None and k not in out:
            out[k] = p[k]
    return out


_v2_pattern_to_v1 = _v1_pattern_to_v2  # same wire shape for pattern nodes


def _v1_credentials_to_v2(c: Optional[dict]) -> dict:
    if not c:
        return {}
    loc = c.get("in", "authorization_header")
    key = c.get("keySelector", "")
    if loc == "authorization_header":
        return {"authorizationHeader": {"prefix": key}}
    if loc == "custom_header":
        return {"customHeader": {"name": key}}
    if loc == "query":
        return {"queryString": {"name": key}}
    if loc == "cookie":
        return {"cookie": {"name": key}}
    return {}


def _v2_credentials_to_v1(c: Optional[dict]) -> dict:
    if not c:
        return {}
    if c.get("authorizationHeader") is not None:
        return {"in": "authorization_header", "keySelector": c["authorizationHeader"].get("prefix", "")}
    if c.get("customHeader") is not None:
        return {"in": "custom_header", "keySelector": c["customHeader"].get("name", "")}
    if c.get("queryString") is not None:
        return {"in": "query", "keySelector": c["queryString"].get("name", "")}
    if c.get("cookie") is not None:
        return {"in": "cookie", "keySelector": c["cookie"].get("name", "")}
    return {}


def _v1_http_to_v2(h: dict) -> dict:
    out: Dict[str, Any] = {"url": h.get("endpoint", "")}
    if h.get("method"):
        out["method"] = h["method"]
    if h.get("body") is not None or h.get("bodyParameters") is not None:
        if h.get("body") is not None:
            b = h["body"]
            out["body"] = _v1_static_or_selector(b.get("value"), b.get("valueFrom"))
        if h.get("bodyParameters"):
            out["bodyParameters"] = _v1_props_to_v2(h["bodyParameters"])
    if h.get("contentType"):
        out["contentType"] = h["contentType"]
    if h.get("headers"):
        out["headers"] = _v1_props_to_v2(h["headers"])
    if h.get("sharedSecretRef"):
        out["sharedSecretRef"] = h["sharedSecretRef"]
    if h.get("oauth2"):
        out["oauth2"] = h["oauth2"]
    if h.get("credentials"):
        out["credentials"] = _v1_credentials_to_v2(h["credentials"])
    return out


def _v2_http_to_v1(h: dict) -> dict:
    out: Dict[str, Any] = {"endpoint": h.get("url", "")}
    if h.get("method"):
        out["method"] = h["method"]
    if h.get("body") is not None:
        out["body"] = _v2_to_v1_value(h["body"])
    if h.get("bodyParameters"):
        out["bodyParameters"] = _v2_props_to_v1(h["bodyParameters"])
    if h.get("contentType"):
        out["contentType"] = h["contentType"]
    if h.get("headers"):
        out["headers"] = _v2_props_to_v1(h["headers"])
    for k in ("sharedSecretRef", "oauth2"):
        if h.get(k):
            out[k] = h[k]
    if h.get("credentials"):
        out["credentials"] = _v2_credentials_to_v1(h["credentials"])
    return out


# ---------------------------------------------------------------------------
# v1beta1 → v1beta2
# ---------------------------------------------------------------------------

def to_v1beta2(resource: dict) -> dict:
    """Convert a v1beta1 AuthConfig resource dict to v1beta2 shape
    (ref: ConvertFrom, api/v1beta2/auth_config_conversion.go:96)."""
    if resource.get("apiVersion") == API_VERSION_V1BETA2:
        return resource
    spec1 = resource.get("spec") or {}
    spec2: Dict[str, Any] = {"hosts": spec1.get("hosts") or []}
    if spec1.get("patterns"):
        spec2["patterns"] = {
            name: [_v1_pattern_to_v2(p) for p in patterns]
            for name, patterns in spec1["patterns"].items()
        }
    if spec1.get("when"):
        spec2["when"] = [_v1_pattern_to_v2(p) for p in spec1["when"]]

    authentication: Dict[str, dict] = {}
    for ident in spec1.get("identity") or []:
        a: Dict[str, Any] = {}
        _copy_common_v1_to_v2(ident, a)
        if ident.get("credentials"):
            a["credentials"] = _v1_credentials_to_v2(ident["credentials"])
        ext_defaults, ext_overrides = {}, {}
        for prop in ident.get("extendedProperties") or []:
            target = ext_overrides if prop.get("overwrite") else ext_defaults
            target[prop.get("name", "")] = _v1_static_or_selector(prop.get("value"), prop.get("valueFrom"))
        if ext_defaults:
            a["defaults"] = ext_defaults
        if ext_overrides:
            a["overrides"] = ext_overrides
        if ident.get("apiKey") is not None:
            a["apiKey"] = {
                "selector": ident["apiKey"].get("selector"),
                "allNamespaces": ident["apiKey"].get("allNamespaces", False),
            }
        elif ident.get("oidc") is not None:
            a["jwt"] = {
                "issuerUrl": ident["oidc"].get("endpoint", ""),
                "ttl": ident["oidc"].get("ttl", 0),
            }
        elif ident.get("oauth2") is not None:
            a["oauth2Introspection"] = _clean({
                "endpoint": ident["oauth2"].get("tokenIntrospectionUrl", ""),
                "tokenTypeHint": ident["oauth2"].get("tokenTypeHint", ""),
                "credentialsRef": ident["oauth2"].get("credentialsRef"),
            })
        elif ident.get("mtls") is not None:
            a["x509"] = {
                "selector": ident["mtls"].get("selector"),
                "allNamespaces": ident["mtls"].get("allNamespaces", False),
            }
        elif ident.get("kubernetes") is not None:
            a["kubernetesTokenReview"] = _clean({"audiences": ident["kubernetes"].get("audiences")})
        elif ident.get("plain") is not None:
            a["plain"] = {"selector": ident["plain"].get("authJSON", "")}
        elif ident.get("anonymous") is not None:
            a["anonymous"] = {}
        authentication[ident.get("name", "")] = a
    if authentication:
        spec2["authentication"] = authentication

    metadata: Dict[str, dict] = {}
    for md in spec1.get("metadata") or []:
        m: Dict[str, Any] = {}
        _copy_common_v1_to_v2(md, m)
        if md.get("http") is not None:
            m["http"] = _v1_http_to_v2(md["http"])
        elif md.get("userInfo") is not None:
            m["userInfo"] = {"identitySource": md["userInfo"].get("identitySource", "")}
        elif md.get("uma") is not None:
            m["uma"] = md["uma"]
        metadata[md.get("name", "")] = m
    if metadata:
        spec2["metadata"] = metadata

    authorization: Dict[str, dict] = {}
    for az in spec1.get("authorization") or []:
        z: Dict[str, Any] = {}
        _copy_common_v1_to_v2(az, z)
        if az.get("json") is not None:
            z["patternMatching"] = {
                "patterns": [_v1_pattern_to_v2(p) for p in az["json"].get("rules") or []]
            }
        elif az.get("opa") is not None:
            o = az["opa"]
            z["opa"] = {
                "rego": o.get("inlineRego", ""),
                "allValues": o.get("allValues", False),
            }
            if o.get("externalRegistry"):
                er = o["externalRegistry"]
                z["opa"]["externalPolicy"] = _clean({
                    "url": er.get("endpoint", ""),
                    "sharedSecretRef": er.get("sharedSecretRef"),
                    "ttl": er.get("ttl", 0),
                })
                if er.get("credentials"):
                    z["opa"]["externalPolicy"]["credentials"] = _v1_credentials_to_v2(er["credentials"])
        elif az.get("kubernetes") is not None:
            k = az["kubernetes"]
            z["kubernetesSubjectAccessReview"] = _clean({
                "user": _v1_static_or_selector((k.get("user") or {}).get("value"), (k.get("user") or {}).get("valueFrom")),
                "groups": k.get("groups"),
            })
            if k.get("resourceAttributes"):
                z["kubernetesSubjectAccessReview"]["resourceAttributes"] = {
                    key: _v1_static_or_selector(v.get("value"), v.get("valueFrom"))
                    for key, v in k["resourceAttributes"].items()
                }
        elif az.get("authzed") is not None:
            s = az["authzed"]
            z["spicedb"] = _clean({
                "endpoint": s.get("endpoint", ""),
                "insecure": s.get("insecure", False),
                "sharedSecretRef": s.get("sharedSecretRef"),
                "subject": _v1_authzed_obj(s.get("subject")),
                "resource": _v1_authzed_obj(s.get("resource")),
                "permission": _v1_static_or_selector(
                    (s.get("permission") or {}).get("value"),
                    (s.get("permission") or {}).get("valueFrom"),
                ),
            })
        authorization[az.get("name", "")] = z
    if authorization:
        spec2["authorization"] = authorization

    response: Dict[str, Any] = {}
    deny_with = spec1.get("denyWith") or {}
    for key in ("unauthenticated", "unauthorized"):
        d = deny_with.get(key)
        if d:
            response[key] = {
                "code": d.get("code", 0),
                "message": _v1_static_or_selector((d.get("message") or {}).get("value"), (d.get("message") or {}).get("valueFrom")) if d.get("message") else None,
                "headers": _v1_props_to_v2(d.get("headers")),
                "body": _v1_static_or_selector((d.get("body") or {}).get("value"), (d.get("body") or {}).get("valueFrom")) if d.get("body") else None,
            }
            response[key] = {k: v for k, v in response[key].items() if v}
    headers_out: Dict[str, dict] = {}
    dyn_out: Dict[str, dict] = {}
    for resp in spec1.get("response") or []:
        r: Dict[str, Any] = {}
        _copy_common_v1_to_v2(resp, r)
        if resp.get("wristband") is not None:
            r["wristband"] = resp["wristband"]
        elif resp.get("json") is not None:
            r["json"] = {"properties": _v1_props_to_v2(resp["json"].get("properties"))}
        elif resp.get("plain") is not None:
            p = resp["plain"]
            r["plain"] = _v1_static_or_selector(p.get("value"), p.get("valueFrom"))
        if resp.get("wrapperKey"):
            r["key"] = resp["wrapperKey"]
        if resp.get("wrapper") == "envoyDynamicMetadata":
            dyn_out[resp.get("name", "")] = r
        else:
            headers_out[resp.get("name", "")] = r
    if headers_out or dyn_out:
        response["success"] = {}
        if headers_out:
            response["success"]["headers"] = headers_out
        if dyn_out:
            response["success"]["dynamicMetadata"] = dyn_out
    if response:
        spec2["response"] = response

    callbacks: Dict[str, dict] = {}
    for cb in spec1.get("callbacks") or []:
        c: Dict[str, Any] = {}
        _copy_common_v1_to_v2(cb, c)
        if cb.get("http") is not None:
            c["http"] = _v1_http_to_v2(cb["http"])
        callbacks[cb.get("name", "")] = c
    if callbacks:
        spec2["callbacks"] = callbacks

    return {
        "apiVersion": API_VERSION_V1BETA2,
        "kind": "AuthConfig",
        "metadata": resource.get("metadata") or {},
        "spec": spec2,
    }


def _v1_authzed_obj(obj: Optional[dict]) -> Optional[dict]:
    if not obj:
        return None
    out = {}
    for k in ("name", "kind"):
        v = obj.get(k)
        if isinstance(v, dict):
            out[k] = _v1_static_or_selector(v.get("value"), v.get("valueFrom"))
        elif v is not None:
            out[k] = {"value": v}
    return out


def _copy_common_v1_to_v2(src: dict, dst: dict) -> None:
    if src.get("priority"):
        dst["priority"] = src["priority"]
    if src.get("metrics"):
        dst["metrics"] = src["metrics"]
    if src.get("when"):
        dst["when"] = [_v1_pattern_to_v2(p) for p in src["when"]]
    if src.get("cache"):
        c = src["cache"]
        key = c.get("key") or {}
        dst["cache"] = {
            "key": _v1_static_or_selector(key.get("value"), key.get("valueFrom")),
            "ttl": c.get("ttl", 60),
        }


# ---------------------------------------------------------------------------
# v1beta2 → v1beta1 (round-trip support; ref ConvertTo :15)
# ---------------------------------------------------------------------------

def to_v1beta1(resource: dict) -> dict:
    if resource.get("apiVersion") == API_VERSION_V1BETA1:
        return resource
    spec2 = resource.get("spec") or {}
    spec1: Dict[str, Any] = {"hosts": spec2.get("hosts") or []}
    if spec2.get("patterns"):
        spec1["patterns"] = spec2["patterns"]
    if spec2.get("when"):
        spec1["when"] = spec2["when"]

    identity = []
    for name, a in (spec2.get("authentication") or {}).items():
        i: Dict[str, Any] = {"name": name}
        _copy_common_v2_to_v1(a, i)
        if a.get("credentials"):
            i["credentials"] = _v2_credentials_to_v1(a["credentials"])
        ext = []
        for prop, vs in (a.get("defaults") or {}).items():
            ext.append({"name": prop, "overwrite": False, **_v2_to_v1_value(vs)})
        for prop, vs in (a.get("overrides") or {}).items():
            ext.append({"name": prop, "overwrite": True, **_v2_to_v1_value(vs)})
        if ext:
            i["extendedProperties"] = ext
        if a.get("apiKey") is not None:
            i["apiKey"] = a["apiKey"]
        elif a.get("jwt") is not None:
            i["oidc"] = {"endpoint": a["jwt"].get("issuerUrl", ""), "ttl": a["jwt"].get("ttl", 0)}
        elif a.get("oauth2Introspection") is not None:
            o = a["oauth2Introspection"]
            i["oauth2"] = _clean({
                "tokenIntrospectionUrl": o.get("endpoint", ""),
                "tokenTypeHint": o.get("tokenTypeHint", ""),
                "credentialsRef": o.get("credentialsRef"),
            })
        elif a.get("x509") is not None:
            i["mtls"] = a["x509"]
        elif a.get("kubernetesTokenReview") is not None:
            i["kubernetes"] = _clean({"audiences": a["kubernetesTokenReview"].get("audiences")})
        elif a.get("plain") is not None:
            i["plain"] = {"authJSON": a["plain"].get("selector", "")}
        elif a.get("anonymous") is not None:
            i["anonymous"] = {}
        identity.append(i)
    if identity:
        spec1["identity"] = identity

    metadata = []
    for name, m in (spec2.get("metadata") or {}).items():
        d: Dict[str, Any] = {"name": name}
        _copy_common_v2_to_v1(m, d)
        if m.get("http") is not None:
            d["http"] = _v2_http_to_v1(m["http"])
        elif m.get("userInfo") is not None:
            d["userInfo"] = m["userInfo"]
        elif m.get("uma") is not None:
            d["uma"] = m["uma"]
        metadata.append(d)
    if metadata:
        spec1["metadata"] = metadata

    authorization = []
    for name, z in (spec2.get("authorization") or {}).items():
        d = {"name": name}
        _copy_common_v2_to_v1(z, d)
        if z.get("patternMatching") is not None:
            d["json"] = {"rules": z["patternMatching"].get("patterns") or []}
        elif z.get("opa") is not None:
            o = z["opa"]
            d["opa"] = {"inlineRego": o.get("rego", ""), "allValues": o.get("allValues", False)}
            if o.get("externalPolicy"):
                ep = o["externalPolicy"]
                d["opa"]["externalRegistry"] = _clean({
                    "endpoint": ep.get("url", ""),
                    "sharedSecretRef": ep.get("sharedSecretRef"),
                    "ttl": ep.get("ttl", 0),
                })
                if ep.get("credentials"):
                    d["opa"]["externalRegistry"]["credentials"] = _v2_credentials_to_v1(ep["credentials"])
        elif z.get("kubernetesSubjectAccessReview") is not None:
            k = z["kubernetesSubjectAccessReview"]
            d["kubernetes"] = _clean({
                "user": _v2_to_v1_value(k.get("user")),
                "groups": k.get("groups"),
            })
            if k.get("resourceAttributes"):
                d["kubernetes"]["resourceAttributes"] = {
                    key: _v2_to_v1_value(v) for key, v in k["resourceAttributes"].items()
                }
        elif z.get("spicedb") is not None:
            s = z["spicedb"]
            d["authzed"] = _clean({
                "endpoint": s.get("endpoint", ""),
                "insecure": s.get("insecure", False),
                "sharedSecretRef": s.get("sharedSecretRef"),
                "subject": {k: _v2_to_v1_value(v) for k, v in (s.get("subject") or {}).items()},
                "resource": {k: _v2_to_v1_value(v) for k, v in (s.get("resource") or {}).items()},
                "permission": _v2_to_v1_value(s.get("permission")),
            })
        authorization.append(d)
    if authorization:
        spec1["authorization"] = authorization

    response2 = spec2.get("response") or {}
    deny_with = {}
    for key in ("unauthenticated", "unauthorized"):
        d = response2.get(key)
        if d:
            deny_with[key] = {
                "code": d.get("code", 0),
                "message": _v2_to_v1_value(d.get("message")) if d.get("message") else None,
                "headers": _v2_props_to_v1(d.get("headers")),
                "body": _v2_to_v1_value(d.get("body")) if d.get("body") else None,
            }
            deny_with[key] = {k: v for k, v in deny_with[key].items() if v}
    if deny_with:
        spec1["denyWith"] = deny_with

    responses = []
    success = response2.get("success") or {}
    for wrapper, group in (("httpHeader", success.get("headers")), ("envoyDynamicMetadata", success.get("dynamicMetadata"))):
        for name, r in (group or {}).items():
            d = {"name": name, "wrapper": wrapper}
            _copy_common_v2_to_v1(r, d)
            if r.get("key"):
                d["wrapperKey"] = r["key"]
            if r.get("wristband") is not None:
                d["wristband"] = r["wristband"]
            elif r.get("json") is not None:
                d["json"] = {"properties": _v2_props_to_v1(r["json"].get("properties"))}
            elif r.get("plain") is not None:
                d["plain"] = _v2_to_v1_value(r["plain"])
            responses.append(d)
    if responses:
        spec1["response"] = responses

    callbacks = []
    for name, c in (spec2.get("callbacks") or {}).items():
        d = {"name": name}
        _copy_common_v2_to_v1(c, d)
        if c.get("http") is not None:
            d["http"] = _v2_http_to_v1(c["http"])
        callbacks.append(d)
    if callbacks:
        spec1["callbacks"] = callbacks

    return {
        "apiVersion": API_VERSION_V1BETA1,
        "kind": "AuthConfig",
        "metadata": resource.get("metadata") or {},
        "spec": spec1,
    }


def _copy_common_v2_to_v1(src: dict, dst: dict) -> None:
    if src.get("priority"):
        dst["priority"] = src["priority"]
    if src.get("metrics"):
        dst["metrics"] = src["metrics"]
    if src.get("when"):
        dst["when"] = src["when"]
    if src.get("cache"):
        dst["cache"] = {
            "key": _v2_to_v1_value(src["cache"].get("key")),
            "ttl": src["cache"].get("ttl", 60),
        }
