"""OAuth2 client-credentials token source with validity-aware caching
(ref: pkg/oauth2/client_credentials.go:35-52)."""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional

from . import http as http_util

__all__ = ["ClientCredentials"]


class ClientCredentials:
    def __init__(self, token_url: str, client_id: str, client_secret: str, scopes: Optional[List[str]] = None):
        self.token_url = token_url
        self.client_id = client_id
        self.client_secret = client_secret
        self.scopes = scopes or []
        self._token: Optional[str] = None
        self._expires_at: float = 0.0
        self._lock = asyncio.Lock()

    async def token(self, force: bool = False) -> str:
        async with self._lock:
            if not force and self._token and time.time() < self._expires_at - 10:
                return self._token
            sess = http_util.get_session()
            data = {"grant_type": "client_credentials"}
            if self.scopes:
                data["scope"] = " ".join(self.scopes)
            async with sess.post(
                self.token_url,
                data=data,
                auth=__import__("aiohttp").BasicAuth(self.client_id, self.client_secret),
            ) as resp:
                payload = await http_util.parse_response(resp)
            if not isinstance(payload, dict) or "access_token" not in payload:
                raise http_util.HttpError(500, f"invalid token response: {payload!r}")
            self._token = payload["access_token"]
            self._expires_at = time.time() + float(payload.get("expires_in", 60))
            return self._token
