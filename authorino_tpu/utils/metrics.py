"""Prometheus metrics — same metric names/labels as the reference
(ref: pkg/service/auth_pipeline.go:26-36, pkg/metrics/metrics.go).

Per-evaluator (deep) metrics are gated by the evaluator's ``metrics: true``
flag or the global DEEP_METRICS_ENABLED (ref: pkg/metrics/metrics.go:86-96,
main.go:182) — the gate is applied by callers via the ``labels()`` helpers
always being cheap; recording is unconditional on the aggregate metrics."""

from __future__ import annotations

import logging

try:
    from prometheus_client import Counter, Gauge, Histogram, REGISTRY

    _PROM = True
except Exception:  # pragma: no cover - prometheus is baked in, but stay safe
    _PROM = False

DEEP_METRICS_ENABLED = False

_EVAL_LABELS = ("namespace", "authconfig", "evaluator_type", "evaluator_name")
_CONF_LABELS = ("namespace", "authconfig")


class _NoopMetric:
    def labels(self, *a, **k):
        return self

    def inc(self, *a):
        pass

    def set(self, *a):
        pass

    def observe(self, *a):
        pass

    def time(self):
        import contextlib

        return contextlib.nullcontext()


def _existing_collector(name):
    """The already-registered collector for ``name``, or None.  On module
    re-import (tests, importlib.reload) the constructor raises ValueError —
    returning a fresh _NoopMetric there would silently detach the process's
    real series, so the duplicate resolves to the ORIGINAL collector."""
    try:
        by_name = REGISTRY._names_to_collectors
    except AttributeError:  # pragma: no cover - library internals changed
        return None
    for candidate in (name, name + "_total", name + "_count"):
        col = by_name.get(candidate)
        if col is not None:
            return col
    return None


def _counter(name, doc, labels):
    if not _PROM:
        return _NoopMetric()
    try:
        return Counter(name, doc, labels)
    except ValueError:  # already registered (module re-import in tests)
        return _existing_collector(name) or _NoopMetric()


def _histogram(name, doc, labels, buckets=None):
    if not _PROM:
        return _NoopMetric()
    try:
        if buckets is not None:
            return Histogram(name, doc, labels, buckets=buckets)
        return Histogram(name, doc, labels)
    except ValueError:
        return _existing_collector(name) or _NoopMetric()


def _gauge(name, doc, labels):
    if not _PROM:
        return _NoopMetric()
    try:
        return Gauge(name, doc, labels)
    except ValueError:
        return _existing_collector(name) or _NoopMetric()


evaluator_total = _counter(
    "auth_server_evaluator_total",
    "Total number of evaluations of individual authconfig rule performed by the auth server.",
    _EVAL_LABELS,
)
evaluator_cancelled = _counter(
    "auth_server_evaluator_cancelled",
    "Number of evaluations of individual authconfig rule cancelled by the auth server.",
    _EVAL_LABELS,
)
evaluator_ignored = _counter(
    "auth_server_evaluator_ignored",
    "Number of evaluations of individual authconfig rule ignored by the auth server.",
    _EVAL_LABELS,
)
evaluator_denied = _counter(
    "auth_server_evaluator_denied",
    "Number of denials from individual authconfig rule evaluated by the auth server.",
    _EVAL_LABELS,
)
evaluator_duration = _histogram(
    "auth_server_evaluator_duration_seconds",
    "Response latency of individual authconfig rule evaluated by the auth server (in seconds).",
    _EVAL_LABELS,
)
authconfig_total = _counter(
    "auth_server_authconfig_total",
    "Total number of authconfigs enforced by the auth server, partitioned by authconfig.",
    _CONF_LABELS,
)
authconfig_response_status = _counter(
    "auth_server_authconfig_response_status",
    "Response status of authconfigs sent by the auth server, partitioned by authconfig.",
    _CONF_LABELS + ("status",),
)
authconfig_duration = _histogram(
    "auth_server_authconfig_duration_seconds",
    "Response latency of authconfig enforced by the auth server (in seconds).",
    _CONF_LABELS,
)
response_status = _counter(
    "auth_server_response_status",
    "Status of HTTP response sent by the auth server.",
    ("status",),
)
# µs-scale on-box stage bounds — MUST match native/frontend.cpp
# STAGE_BOUNDS_NS (the C++ frontend buckets in ns; drains map 1:1)
STAGE_BUCKETS = (
    10e-6, 25e-6, 50e-6, 100e-6, 250e-6, 500e-6,
    1e-3, 2.5e-3, 5e-3, 10e-3, 25e-3, 50e-3, 100e-3, 250e-3, 1.0,
)
frontend_stage_duration = _histogram(
    "auth_server_frontend_stage_duration_seconds",
    "On-box per-request stage latency of the native frontend (queue-wait: "
    "encode to batch flush; execute: flush to verdict; respond: verdict to "
    "HTTP/2 submit).",
    ("stage",),
    buckets=STAGE_BUCKETS,
)


_bucketed_fallback_warned = False


def observe_bucketed(hist_child, bucket_counts, sum_seconds) -> None:
    """Fold pre-bucketed counts (non-cumulative per-le, same bounds as the
    histogram) into a prometheus_client Histogram child in O(buckets) —
    per-request observe() calls cannot keep up with the native frontend's
    rates.  Uses the documented-stable internals (`_buckets`/`_sum`, probed
    here so a library change degrades loudly, not silently); the fallback
    preserves the distribution shape by spreading observes across each
    bucket's midpoint instead of collapsing everything into one mean."""
    try:
        # resolve EVERY internal before mutating anything: a partial apply
        # (buckets bumped, then _sum missing) followed by the fallback
        # would double-count the whole drained distribution
        bucket_incs = [b.inc for b in hist_child._buckets]
        sum_inc = hist_child._sum.inc
    except (AttributeError, TypeError):
        bucket_incs = None
    if bucket_incs is not None:
        for i, n in enumerate(bucket_counts):
            if n:
                bucket_incs[i](n)
        if sum_seconds:
            sum_inc(sum_seconds)
        return
    global _bucketed_fallback_warned
    if not _bucketed_fallback_warned:
        _bucketed_fallback_warned = True
        logging.getLogger(__name__).warning(
            "prometheus_client histogram internals changed "
            "(_buckets/_sum missing) — falling back to per-bucket midpoint "
            "observes for drained native-frontend histograms")
    if not hasattr(hist_child, "observe"):
        return
    bounds = list(getattr(hist_child, "_upper_bounds", ()))[:len(bucket_counts)]
    total = sum(bucket_counts)
    if not total:
        return
    if not bounds:
        hist_child.observe(sum_seconds / total)
        return
    import math

    # per-observe cost is the very thing this function exists to avoid — a
    # huge drained backlog must not stall the drain thread for seconds, so
    # counts above the cap are proportionally thinned (logged: rate(count)
    # dashboards undercount while the fallback is active)
    cap = 200_000
    scale = 1.0
    if total > cap:
        scale = cap / total
        logging.getLogger(__name__).warning(
            "histogram fallback drain thinned %d observations to %d "
            "(per-observe fallback cannot keep up with native rates)",
            total, cap)
    counts: list = []
    values: list = []
    lo = 0.0
    for i, n in enumerate(bucket_counts):
        hi = bounds[i] if i < len(bounds) else float("inf")
        if hi == float("inf"):
            # strictly above the last finite bound, else observe() bins
            # these overflow counts into the last finite bucket (le is <=)
            v = math.nextafter(lo, math.inf)
        else:
            v = (lo + hi) / 2.0
        if n:
            counts.append((int(round(n * scale)), len(values)))
            values.append((v, lo, hi))
        if hi != float("inf"):
            lo = hi
    # match the drained sum by shifting values inside their buckets
    # (midpoints alone misstate rate(sum)/rate(count) averages): walk from
    # the top bucket down, absorbing the residual within each bucket's
    # bounds — exact whenever the target sum is consistent with the shape
    # (the +Inf bucket is unbounded above)
    residual = sum_seconds * scale - sum(n * values[j][0] for n, j in counts)
    for n, j in reversed(counts):
        if not n or abs(residual) <= 1e-12:
            continue
        v, b_lo, b_hi = values[j]
        want = v + residual / n
        got = max(want, math.nextafter(b_lo, math.inf))
        if b_hi != float("inf"):
            got = min(got, b_hi)
        values[j] = (got, b_lo, b_hi)
        residual -= (got - v) * n
    for n, j in counts:
        v = values[j][0]
        for _ in range(n):
            hist_child.observe(v)


# ---------------------------------------------------------------------------
# Batch-aware device/engine telemetry.  Everything here is recorded ONCE PER
# MICRO-BATCH (or folded in bulk by a drain), never per request: the native
# fast lane touches Python exactly once per kernel launch, and these series
# ride that touch.  ``lane`` distinguishes the asyncio engine queue
# (runtime/engine.py submit/_flush) from the C++ device-owner frontend's
# dispatcher (runtime/native_frontend.py _dispatch).
# ---------------------------------------------------------------------------

_LANE_LABELS = ("lane",)

# powers of two: batches pad to pow2 buckets (utils.bucket_pow2), so these
# bounds land exactly on the pad grid
BATCH_SIZE_BUCKETS = tuple(float(1 << i) for i in range(13))  # 1 .. 4096
batch_size = _histogram(
    "auth_server_batch_size",
    "Requests per micro-batch at kernel launch (before padding).",
    _LANE_LABELS,
    buckets=BATCH_SIZE_BUCKETS,
)
OCCUPANCY_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9,
                     0.95, 1.0)
batch_pad_occupancy = _histogram(
    "auth_server_batch_pad_occupancy",
    "Per-batch occupancy of the chosen jit pad bucket (batch size / pad): "
    "1.0 = a full bucket, low values = pad waste (device cycles spent on "
    "discarded rows).",
    _LANE_LABELS,
    buckets=OCCUPANCY_BUCKETS,
)
batch_queue_wait = _histogram(
    "auth_server_batch_queue_wait_seconds",
    "Per-request queue wait (enqueue to dispatch cut), engine lane only — "
    "every member's wait is folded per batch (bucketed, O(buckets)/batch).  "
    "The native lane's queue wait is C++-clocked instead: see "
    "auth_server_frontend_stage_duration_seconds{stage=\"wait\"}.",
    _LANE_LABELS,
    buckets=STAGE_BUCKETS,
)
device_dispatch_duration = _histogram(
    "auth_server_device_dispatch_seconds",
    "Wall time of one kernel launch: operand upload + device execute + "
    "verdict readback (on a tunneled device this is dominated by link RTT).",
    _LANE_LABELS,
    buckets=STAGE_BUCKETS,
)
FALLBACK_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                    512.0, 1024.0)
batch_host_fallback = _histogram(
    "auth_server_batch_host_fallback",
    "Host-oracle fallback requests (membership overflow) per micro-batch.",
    _LANE_LABELS,
    buckets=FALLBACK_BUCKETS,
)
jit_warm_cache = _counter(
    "auth_server_jit_warm_cache_total",
    "Warm-compile cache consultations per kernel launch, by the (pad, eff) "
    "variant served: hit = exact shape was pre-compiled, rounded = a larger "
    "warmed shape absorbed the batch, miss = inline XLA compile landed on "
    "live requests (cold start only).",
    ("pad", "eff", "outcome"),
)
snapshot_generation = _gauge(
    "auth_server_snapshot_generation",
    "Monotonic generation of the serving snapshot, per component (engine = "
    "compiled-corpus swaps via apply_snapshot; native_frontend = C++ "
    "fe_swap snapshot id).",
    ("component",),
)
inflight_batches = _gauge(
    "auth_server_inflight_batches",
    "Micro-batches currently in flight on the device (launched, readback "
    "not yet resolved).  The dispatch window bounds this at "
    "max_inflight_batches; sustained values near the bound mean the device "
    "link, not the host, is the ceiling (throughput ≈ window × batch / RTT).",
    _LANE_LABELS,
)
dispatch_queue_depth = _gauge(
    "auth_server_dispatch_queue_depth",
    "Requests queued for the next micro-batch cut (global dispatcher "
    "backlog, sampled at each dispatch/completion).",
    _LANE_LABELS,
)
pipeline_stage_duration = _histogram(
    "auth_server_pipeline_stage_seconds",
    "Per-batch wall time of each async-dispatch pipeline stage: encode = "
    "host encode/pack + fused staging build; launch = non-blocking kernel "
    "dispatch call (operand H2D enqueue); device = launch to readback "
    "arrival (link RTT + kernel); resolve = readback to future resolution.",
    _LANE_LABELS + ("stage",),
    buckets=STAGE_BUCKETS,
)

# ---------------------------------------------------------------------------
# Batch row dedup + snapshot-scoped verdict cache (ISSUE 3): the device
# evaluates only UNIQUE rows per micro-batch, and rows whose (generation,
# row-digest) verdict is already cached skip the device entirely.
# ---------------------------------------------------------------------------

batch_dedup_ratio = _histogram(
    "auth_server_batch_dedup_ratio",
    "Per-micro-batch fraction of rows collapsed before device dispatch "
    "(1 - unique_rows / rows, cache-resolved rows included): 0 = all rows "
    "shipped, 0.9 = the device evaluated one row in ten.",
    _LANE_LABELS,
    buckets=OCCUPANCY_BUCKETS,
)
verdict_cache_hits = _counter(
    "auth_server_verdict_cache_hits_total",
    "Rows resolved from the snapshot-scoped verdict cache without touching "
    "the device (keyed by generation + encoded-row digest).",
    _LANE_LABELS,
)
verdict_cache_misses = _counter(
    "auth_server_verdict_cache_misses_total",
    "Cache-eligible rows whose verdict was not cached (evaluated on device, "
    "then inserted).",
    _LANE_LABELS,
)
verdict_cache_evictions = _counter(
    "auth_server_verdict_cache_evictions_total",
    "Verdict-cache entries dropped by the LRU bound (raise "
    "--verdict-cache-size if this grows at steady state).",
    _LANE_LABELS,
)

_dedup_children: dict = {}


def observe_dedup(lane, n_rows, n_device_rows, cache_hits, cache_misses,
                  evictions_delta=0) -> None:
    """Fold one micro-batch's dedup/cache outcome: ``n_device_rows`` of
    ``n_rows`` actually shipped (after cache hits AND within-batch
    collapse).  Cached label children — runs once per micro-batch."""
    ch = _dedup_children.get(lane)
    if ch is None:
        ch = _dedup_children[lane] = (
            batch_dedup_ratio.labels(lane),
            verdict_cache_hits.labels(lane),
            verdict_cache_misses.labels(lane),
            verdict_cache_evictions.labels(lane),
        )
    if n_rows:
        ch[0].observe(1.0 - n_device_rows / n_rows)
    if cache_hits:
        ch[1].inc(cache_hits)
    if cache_misses:
        ch[2].inc(cache_misses)
    if evictions_delta:
        ch[3].inc(evictions_delta)


_batch_children: dict = {}
_stage_children: dict = {}


def observe_pipeline_stage(lane, stage, seconds) -> None:
    """Record one pipeline-stage wall-time sample (cached label children:
    this runs up to four times per micro-batch)."""
    ch = _stage_children.get((lane, stage))
    if ch is None:
        ch = _stage_children[(lane, stage)] = (
            pipeline_stage_duration.labels(lane, stage))
    ch.observe(seconds)


def fold_queue_waits(lane, waits) -> None:
    """Fold TRUE per-request queue waits (seconds, array-like) into the
    batch_queue_wait histogram in O(buckets) via observe_bucketed — a
    per-request observe() loop would put Python back on the per-request
    path the batch design exists to avoid."""
    import numpy as np

    waits = np.asarray(waits, dtype=np.float64)
    if waits.size == 0:
        return
    ch = _batch_children.get(lane)
    if ch is None:
        ch = _ensure_batch_children(lane)
    edges = [0.0] + list(STAGE_BUCKETS) + [np.inf]
    counts, _ = np.histogram(np.clip(waits, 0.0, None), bins=edges)
    observe_bucketed(ch[2], counts.tolist(), float(waits.sum()))


def _ensure_batch_children(lane):
    ch = _batch_children.get(lane)
    if ch is None:
        ch = _batch_children[lane] = (
            batch_size.labels(lane),
            batch_pad_occupancy.labels(lane),
            batch_queue_wait.labels(lane),
            device_dispatch_duration.labels(lane),
            batch_host_fallback.labels(lane),
        )
    return ch


def observe_batch(lane, n, pad, queue_wait_s, dispatch_s,
                  fallback_n=None, device_rows=None) -> None:
    """Record one kernel launch's batch telemetry (size, pad occupancy,
    queue wait, dispatch wall time, host-fallback rows).  ``queue_wait_s``
    may be a scalar (one representative wait) or an array of TRUE
    per-request waits (folded in O(buckets), not O(batch)).
    ``device_rows`` is the row count that actually shipped after batch
    dedup / verdict-cache hits (defaults to ``n``): occupancy stays the
    device-true ratio ≤ 1 — the dedup win is its own series
    (auth_server_batch_dedup_ratio).  Label children are cached: this runs
    on every micro-batch."""
    ch = _ensure_batch_children(lane)
    ch[0].observe(n)
    if pad:
        ch[1].observe((n if device_rows is None else device_rows) / pad)
    if queue_wait_s is not None:
        if hasattr(queue_wait_s, "__len__"):
            fold_queue_waits(lane, queue_wait_s)
        else:
            ch[2].observe(queue_wait_s)
    ch[3].observe(dispatch_s)
    if fallback_n is not None:
        ch[4].observe(fallback_n)


# ---------------------------------------------------------------------------
# Native-frontend fe_stats() drain: the C++ server counts events in atomics
# (native/frontend.cpp Server::n_*); a periodic drain folds the DELTAS into
# one labelled counter family so /metrics finally tells the fast lane's
# story without any per-request Python work.
# ---------------------------------------------------------------------------

# fe_stats() keys that are live backlog gauges, not monotonic counters
NATIVE_QUEUE_KEYS = ("slow_pending", "slow_queued")

# event keys whose labelled series must EXIST on /metrics even before they
# first move (the drain otherwise skips zero-delta keys, which is how the
# credential-cache counters stayed invisible across 3.9M requests): the
# C++ credential cache's dyn_* counters plus the Python-side verdict-cache
# traffic the native frontend folds into the same drain
NATIVE_ENSURE_KEYS = ("dyn_hit", "dyn_miss", "dyn_add",
                      "vdict_hit", "vdict_miss", "vdict_add", "vdict_evict")

native_frontend_events = _counter(
    "auth_server_native_frontend_events_total",
    "Native (C++) frontend event counters drained from fe_stats(): "
    "fast/slow lane decisions, shed work, credential-cache traffic, "
    "trace sampling, parse errors.",
    ("event",),
)
native_frontend_queue_depth = _gauge(
    "auth_server_native_frontend_queue_depth",
    "Live backlog of the native frontend's slow lane (queued = awaiting "
    "Python pickup, pending = in the pipeline).",
    ("queue",),
)


class NativeStatsDrain:
    """Folds successive fe_stats() snapshots into Prometheus as deltas.
    Single-owner: exactly one thread may fold a given instance (delta state
    is unsynchronized by design — the native frontend's drain thread)."""

    def __init__(self):
        self._last: dict = {}
        self._children: dict = {}

    def fold(self, stats) -> None:
        if not stats:
            return
        for key in NATIVE_ENSURE_KEYS:
            # materialize the labelled series at 0 so dashboards see the
            # cache counters from the first scrape, not the first hit
            if key not in self._children:
                self._children[key] = native_frontend_events.labels(key)
        for key, value in stats.items():
            if key in NATIVE_QUEUE_KEYS:
                child = self._children.get(key)
                if child is None:
                    child = self._children[key] = (
                        native_frontend_queue_depth.labels(key))
                child.set(value)
                continue
            delta = value - self._last.get(key, 0)
            if delta > 0:
                child = self._children.get(key)
                if child is None:
                    child = self._children[key] = (
                        native_frontend_events.labels(key))
                child.inc(delta)
            self._last[key] = value


# ---------------------------------------------------------------------------
# Compile-time verification (analysis/): snapshot rejection under
# --strict-verify and reconcile-time policy semantic findings.
# ---------------------------------------------------------------------------

snapshot_rejected = _counter(
    "auth_server_snapshot_rejected_total",
    "Compiled snapshots rejected by --strict-verify tensor lint at swap "
    "time, per component (engine = apply_snapshot, native_frontend = C++ "
    "fe_swap refresh).  The previously-serving snapshot stays live.",
    ("component",),
)
policy_analysis_findings = _counter(
    "auth_server_policy_analysis_findings_total",
    "Reconcile-time policy semantic-analysis findings (Cedar-style): "
    "constant-allow/constant-deny rules, shadowed/duplicate rules, hosts "
    "routed to more than one AuthConfig.  Recorded once per reconcile, "
    "never per request.",
    ("kind", "authconfig"),
)
policy_analysis_skipped = _counter(
    "auth_server_policy_analysis_skipped_total",
    "Evaluators the semantic analyzer SKIPPED because their operand "
    "support exceeds the bounded-evaluation limit (MAX_ATOMS).  Skipped "
    "rules are listed on /debug/vars under policy_analysis.summary.skipped "
    "— they still serve, they are just unanalyzed.",
    ("authconfig",),
)
translation_validate = _counter(
    "auth_server_translation_validate_total",
    "Per-config translation-validation outcomes at reconcile time "
    "(analysis/translation_validate.py): validated = certified against "
    "the host expression oracle this reconcile, cache_hit = unchanged "
    "fingerprint served from the process-wide certificate cache, failed = "
    "certification failure (under --strict-verify the snapshot is "
    "rejected and the old one keeps serving).",
    ("result",),
)
lowerability_configs = _counter(
    "auth_server_lowerability_configs_total",
    "Per-reconcile lowerability classification: lane = fast (verdict "
    "rides the kernel) or slow (interpreter path), reason = the reason "
    "code ('' for configs with no reason; catalogue in "
    "docs/static_analysis.md).  Incremented once per (config, reason) "
    "pair per reconcile — a config with N reason codes lands in N series, "
    "so sum by lane over-counts multi-reason configs; /debug/vars "
    "engine.lowerability carries the exact per-lane config counts.",
    ("lane", "reason"),
)
lowerability_blocking = _gauge(
    "auth_server_lowerability_blocking_configs",
    "Would-be-fast-if-fixed rollup per slow-lane reason code (ISSUE 14): "
    "kind = 'configs' (every slow config carrying the reason) or "
    "'sole_blocker' (configs this reason ALONE exiles — fixing it moves "
    "exactly that many to the fast lane).  Set once per reconcile from "
    "the lowerability report's blocking_reasons block, so per-reason "
    "progress trends across reconciles.",
    ("reason", "kind"),
)
relation_table_rows = _gauge(
    "auth_server_relation_table_rows",
    "Entity rows of the compiled relation bitmatrix (ISSUE 14, "
    "relations/closure.py): the per-snapshot ancestor-closure table the "
    "kernel's OP_RELATION bitmask gather reads.  0 when the corpus "
    "declares no relations.",
    (),
)
relation_table_bytes = _gauge(
    "auth_server_relation_table_bytes",
    "Bytes of the compiled relation bitmatrix uploaded with the snapshot "
    "(rows x ceil(queried-group columns / 8)).",
    (),
)
metadata_prefetch = _counter(
    "auth_server_metadata_prefetch_total",
    "Metadata prefetch cache outcomes (ISSUE 14, relations/prefetch.py): "
    "hit = pinned document served with zero network I/O, miss = no pin "
    "yet (live fetch fall-through), stale = pin older than the staleness "
    "bound (live fetch fall-through), refresh = background re-pin "
    "completed, error = a re-pin fetch failed (the previous pin, if any, "
    "keeps serving until stale).",
    ("result",),
)
metadata_prefetch_docs = _gauge(
    "auth_server_metadata_prefetch_docs",
    "Currently pinned (healthy) prefetched metadata documents.",
    (),
)

# ---------------------------------------------------------------------------
# Fault-injected graceful degradation (ISSUE 5): device circuit breaker,
# per-batch retry + host-oracle degrade, deadline-aware shedding, completer
# watchdog, and the injectable fault plane's own evidence counter.
# ---------------------------------------------------------------------------

circuit_state = _gauge(
    "auth_server_circuit_state",
    "Device circuit-breaker state per lane: 0 = closed (device serving), "
    "1 = half-open (one probe batch in flight), 2 = open (batches decided "
    "host-side until the cooldown probe succeeds).",
    _LANE_LABELS,
)
circuit_transitions = _counter(
    "auth_server_circuit_transitions_total",
    "Circuit-breaker state transitions per lane (state = the state entered).",
    _LANE_LABELS + ("state",),
)
batch_retries = _counter(
    "auth_server_batch_retries_total",
    "Failed in-flight micro-batches retried once on a fresh device dispatch "
    "before degrading to the host oracle.",
    _LANE_LABELS,
)
degraded_decisions = _counter(
    "auth_server_degraded_decisions_total",
    "Requests decided host-side because the device path failed (retry "
    "exhausted) or the circuit breaker was open.  Engine lane: exact "
    "re-decision via the expression oracle; native lane: the same kernel "
    "on the CPU backend.",
    _LANE_LABELS,
)
deadline_shed = _counter(
    "auth_server_deadline_shed_total",
    "Requests failed fast (DEADLINE_EXCEEDED) before encode because their "
    "propagated Check() deadline could not be met (queue wait + estimated "
    "device RTT exceed the time remaining).",
    _LANE_LABELS,
)
watchdog_timeouts = _counter(
    "auth_server_device_watchdog_timeouts_total",
    "In-flight micro-batches abandoned by the completer watchdog because "
    "their readback never arrived within --device-timeout (counted as "
    "circuit-breaker failures; the batch retries/degrades).",
    _LANE_LABELS,
)
injected_faults = _counter(
    "auth_server_injected_faults_total",
    "Faults fired by the injection plane (runtime/faults.py) — non-zero "
    "only under --fault-profile / bench --chaos / tests.",
    ("stage", "mode", "lane"),
)

# ---------------------------------------------------------------------------
# Overload resilience (ISSUE 7): CoDel-style admission control, the adaptive
# window controller, and host-lane brownout under sustained open-loop
# traffic.  See runtime/admission.py + docs/robustness.md.
# ---------------------------------------------------------------------------

admission_state = _gauge(
    "auth_server_admission_state",
    "Admission-control state per lane: 0 = admitting, 1 = overloaded (the "
    "minimum queue wait stayed above the CoDel target for a full interval "
    "— a standing queue, not a transient burst; arrivals beyond the "
    "wait-targeted cap are rejected typed RESOURCE_EXHAUSTED).",
    _LANE_LABELS,
)
admission_rejected = _counter(
    "auth_server_admission_rejected_total",
    "Requests rejected at admission (before queueing, before encode): "
    "queue-full = hard queue cap, overload = wait-targeted effective cap, "
    "doomed-deadline = the propagated deadline lands inside the predicted "
    "queue wait + device RTT (typed DEADLINE_EXCEEDED; the others are "
    "typed RESOURCE_EXHAUSTED).",
    _LANE_LABELS + ("reason",),
)
admission_queue_wait = _gauge(
    "auth_server_admission_queue_wait_ewma_seconds",
    "EWMA of the per-request submit-queue wait the admission controller "
    "tracks (the CoDel signal's mean companion; the state flips on the "
    "interval MINIMUM).",
    _LANE_LABELS,
)
adaptive_window = _gauge(
    "auth_server_adaptive_window",
    "Live in-flight window chosen by the adaptive controller (Little's "
    "law: arrival rate x device RTT / batch cut, clamped to [1, "
    "max_inflight_batches]).  Replaces the static --max-inflight-batches "
    "guess; the flag is now the cap.",
    _LANE_LABELS,
)
adaptive_batch_cut = _gauge(
    "auth_server_adaptive_batch_cut",
    "Live batch-cut target chosen by the adaptive controller (pow2 bucket "
    "of arrival rate x RTT / window, clamped to [1, max_batch]).",
    _LANE_LABELS,
)
brownout_decisions = _counter(
    "auth_server_brownout_decisions_total",
    "Requests decided on the exact host lane because the device pipeline "
    "was saturated (window full + standing queue): overload degrades "
    "throughput, never correctness.  Engine lane: the host expression "
    "oracle; native lane: the same kernel on the CPU backend.",
    _LANE_LABELS,
)
brownout_batches = _counter(
    "auth_server_brownout_batches_total",
    "Micro-batches spilled to the host lane under device-pipeline "
    "saturation (the per-batch companion of "
    "auth_server_brownout_decisions_total).",
    _LANE_LABELS,
)

# ---------------------------------------------------------------------------
# Lane selection (ISSUE 12): the host twin as a first-class serving lane —
# per-batch-cut cost-model decisions and speculative dual-dispatch while a
# lane breaker is half-open.  See runtime/lane_select.py +
# docs/performance.md "Lane selection".
# ---------------------------------------------------------------------------

lane_decisions = _counter(
    "auth_server_lane_decisions_total",
    "Batch-cut lane decisions by the cost model (runtime/lane_select.py): "
    "lane = <serving lane>-host / <serving lane>-device, reason = "
    "cost-model (the winning cost estimate), deadline (latency-critical "
    "head rescued host-side), speculative (dual-dispatch twin while the "
    "breaker is half-open), batch (cut too large for the host lane), "
    "host-busy (host concurrency cap), slo-burn (burn bias flipped the "
    "raw cost verdict), explore (periodic device probe keeping the RTT "
    "EWMA fresh during host-only regimes), disabled.",
    _LANE_LABELS + ("reason",),
)
lane_cost_ewma = _gauge(
    "auth_server_lane_cost_ewma_seconds",
    "Live cost-model EWMAs per lane: host = seconds per host-decided ROW, "
    "device = seconds per device batch round trip.  The decision law "
    "compares host_row x cut_size against device_rtt x (1 + occupancy).",
    _LANE_LABELS + ("which",),
)
speculative_dispatch = _counter(
    "auth_server_speculative_dispatch_total",
    "Speculative dual-dispatch outcomes (breaker half-open): launched = "
    "one batch sent to BOTH lanes, host-win / device-win = which lane "
    "resolved the futures first (the loser's work is ignored — verdicts "
    "are bit-identical by construction), host-fail = the host twin "
    "raised or partially failed (the device half owns the batch), "
    "device-fail = the device half failed while the host half answered "
    "(the probe's breaker verdict).",
    ("outcome",),
)

host_fallback_total = _counter(
    "auth_server_host_fallback_total",
    "Requests re-decided by the host expression oracle because the compact "
    "device payload was lossy for them (membership overflow past members_k).",
    (),
)
host_fallback_shed_total = _counter(
    "auth_server_host_fallback_shed_total",
    "Fallback requests denied (fail closed) because the per-batch host "
    "fallback cap was exceeded.",
    (),
)

# ---------------------------------------------------------------------------
# Incremental control plane (ISSUE 8, authorino_tpu/snapshots/): per-phase
# reconcile timing, the compile cache's hit evidence, delta-upload traffic,
# and leader/replica snapshot distribution outcomes.
# ---------------------------------------------------------------------------

reconcile_phase = _histogram(
    "auth_server_reconcile_phase_seconds",
    "Per-phase reconcile timing on the engine lane: compile (incremental "
    "corpus compile through the per-config artifact cache), validate "
    "(--strict-verify tensor lint + translation certification), diff "
    "(delta plan between the old and new host operand views), upload "
    "(H2D staging — delta rows or full re-stage).  The sum is what a "
    "reconcile costs the control plane; docs/control_plane.md.",
    ("phase",),
    buckets=(.0005, .002, .01, .05, .1, .25, .5, 1.0, 2.5, 5.0, 10.0),
)
compile_cache_events = _counter(
    "auth_server_compile_cache_events_total",
    "Per-config compile-cache outcomes per reconcile: hit = the config's "
    "source fingerprint matched a cached artifact (no re-lowering, no "
    "re-determinization), miss = the config was actually compiled.  An "
    "unchanged corpus is all hits; mutating one config is exactly one "
    "miss (ISSUE 8 churn property).",
    ("outcome",),
)
delta_upload_bytes = _counter(
    "auth_server_delta_upload_bytes_total",
    "Operand bytes actually shipped to the device per reconcile upload "
    "(changed rows + scatter indices on the delta path; whole tensors on "
    "a full re-stage).  Compare against "
    "auth_server_full_upload_bytes_total for the avoided traffic.",
    ("lane",),
)
full_upload_bytes = _counter(
    "auth_server_full_upload_bytes_total",
    "Operand bytes a FULL re-stage of each reconciled snapshot would have "
    "shipped (the delta baseline; the monolithic pre-ISSUE-8 behavior).",
    ("lane",),
)
# ---------------------------------------------------------------------------
# Multi-chip mesh lane (ISSUE 11, docs/performance.md "Multi-chip mesh"):
# per-device occupancy, breaker-aware failover, and per-shard delta bytes.
# ---------------------------------------------------------------------------

mesh_shard_occupancy = _gauge(
    "auth_server_mesh_shard_occupancy",
    "In-flight micro-batches currently occupying one mesh device (full-mesh "
    "launches count on every device; failover single-device dispatches on "
    "their target only).  The occupancy-aware router sends failover batches "
    "to the emptiest window.",
    ("device",),
)
device_failover = _counter(
    "auth_server_device_failover_total",
    "Micro-batches re-dispatched AWAY from one mesh device after it failed "
    "a launch/probe (per-device circuit breaker attribution) — the batch "
    "resolved on a healthy device, not the host oracle.  device = the "
    "device that FAILED.",
    ("device",),
)
mesh_shard_upload_bytes = _counter(
    "auth_server_mesh_shard_upload_bytes_total",
    "Reconcile upload bytes shipped to each mesh shard (the 'mp' rule "
    "slice).  A one-config mutation ships rows only to the shard(s) owning "
    "it; unchanged shards receive zero bytes (per-shard delta uploads, "
    "ISSUE 11).",
    ("shard",),
)

snapshot_distribution = _counter(
    "auth_server_snapshot_distribution_total",
    "Leader/replica snapshot distribution outcomes: role = leader | "
    "replica; result = published | applied | rejected (admission gate: "
    "uncertified or locally-failing snapshot, old snapshot keeps serving) "
    "| error (unreadable/corrupt source) | retry (a poll retried after a "
    "load failure under exponential backoff — a dead leader backs the "
    "replica's polling off instead of flooding its log).",
    ("role", "result"),
)

# ---------------------------------------------------------------------------
# Decision provenance + SLO + flight recorder (ISSUE 9,
# docs/observability.md "Decision provenance"): which-rule-fired attribution
# decoded per BATCH from the bitpacked readback's rule columns, the runtime
# rule heat map, the multi-window SLO burn-rate tracker, and the black-box
# lifecycle flight recorder.  Nothing here is per-request Python on the
# native fast lane: attribution is a per-batch column fold, decision records
# are head-sampled.
# ---------------------------------------------------------------------------

rule_fired = _counter(
    "auth_server_rule_fired_total",
    "Denials attributed to one compiled authorization rule (the FIRST "
    "evaluator column that evaluated false and was not condition-skipped — "
    "the same short-circuit order the reference's pipeline denies in).  "
    "rule = '<evaluator idx>:<rule source>' (truncated); folded once per "
    "micro-batch from the readback's rule columns on every lane — device, "
    "cached, deduped, degraded, brownout.  The runtime rule heat map: "
    "never-incremented rules cross-reference the static constant/shadowed "
    "findings in the /debug/vars dead-rule report.",
    ("authconfig", "rule"),
)
decision_records = _counter(
    "auth_server_decision_records_total",
    "Head-sampled structured decision records appended to the bounded "
    "decision log (served on /debug/decisions; one record at most per "
    "micro-batch, sampled 1-in-N decisions).",
    _LANE_LABELS,
)
slo_burn_rate = _gauge(
    "auth_server_slo_burn_rate",
    "Multi-window SLO burn rate per lane: (bad fraction in the window) / "
    "(error budget fraction), where bad = latency over --slo-ms or a "
    "non-deadline serving error.  1.0 = burning exactly the budget; "
    "sustained values over ~14 on the short window are page-worthy "
    "(multi-window multi-burn alerting).",
    _LANE_LABELS + ("window",),
)
slo_bad_total = _counter(
    "auth_server_slo_bad_total",
    "Requests counted against the SLO error budget (latency over --slo-ms "
    "or a serving error), per lane.  The companion total rides "
    "auth_server_slo_observed_total.",
    _LANE_LABELS,
)
slo_observed_total = _counter(
    "auth_server_slo_observed_total",
    "Requests observed by the SLO burn-rate tracker, per lane (the "
    "denominator for auth_server_slo_bad_total).",
    _LANE_LABELS,
)
flight_events = _counter(
    "auth_server_flight_recorder_events_total",
    "Lifecycle events appended to the flight-recorder ring (breaker "
    "transitions, watchdog fires, snapshot swaps/rejections, admission "
    "flips, reconcile phases, drain).",
    ("kind",),
)
flight_dumps = _counter(
    "auth_server_flight_recorder_dumps_total",
    "Diagnostic bundles auto-dumped by the flight recorder on anomaly "
    "triggers (breaker OPEN, watchdog fire, snapshot rejection, admission "
    "OVERLOADED, snapshot rollback), by the anomaly kind that triggered "
    "the dump.",
    ("trigger",),
)

# ---------------------------------------------------------------------------
# Change safety (ISSUE 10, docs/robustness.md "Change safety"): canary
# snapshot swaps, guard-breach auto-rollback, and poison-config quarantine.
# ---------------------------------------------------------------------------

canary_state = _gauge(
    "auth_server_canary_state",
    "Canary swap state per lane: 0 = no canary in progress, 1 = a newly "
    "reconciled snapshot is serving only its deterministic hash-fraction "
    "cohort (--canary-fraction) while the previous generation serves the "
    "rest; a clean --canary-window promotes to 100%, a guard breach "
    "auto-rolls-back.",
    _LANE_LABELS,
)
snapshot_rollbacks = _counter(
    "auth_server_snapshot_rollbacks_total",
    "Snapshot generations rolled back, by reason: guard-breach (a canary "
    "guard tripped inside the window — deny-rate/error-rate/SLO delta "
    "canary vs baseline), superseded (a newer reconcile landed before the "
    "canary concluded), manual (operator override via the analysis CLI / "
    "debug endpoint).  Rollback is a pointer swap to the retained "
    "previous generation — old device buffers are double-buffer safe.",
    ("reason",),
)
quarantined_configs = _gauge(
    "auth_server_quarantined_configs",
    "AuthConfigs currently quarantined per lane: after a guard-breach "
    "rollback, the reconcile is re-applied with these configs reverted to "
    "their prior compiled artifacts (the rest of the change still lands). "
    "Quarantine clears when the operator ships a FIXED config (changed "
    "fingerprint) or overrides via clear-quarantine.",
    _LANE_LABELS,
)
canary_guard_delta = _gauge(
    "auth_server_canary_guard_delta",
    "Live canary-vs-baseline guard deltas during a canary window: "
    "deny-rate (overall), config-deny-rate (worst per-authconfig delta), "
    "error-rate (typed serving errors), slo-bad-rate (SLO bad fraction). "
    "A delta past its threshold (docs/robustness.md) breaches the guard "
    "and triggers automatic rollback.",
    ("guard",),
)

# ---------------------------------------------------------------------------
# Traffic replay & what-if preflight (ISSUE 13, docs/replay.md): the opt-in
# full-fidelity capture log, the reconcile replay pregate, and the live
# verdict-diff evidence gauge.
# ---------------------------------------------------------------------------

capture_records = _counter(
    "auth_server_capture_records_total",
    "Sampled full-fidelity capture-log records by result: stored (encoded "
    "into the byte-bounded ring, and persisted when --capture-log-dir is "
    "set) vs dropped (offer-queue overflow or an unencodable document — "
    "capture loss is accounted, never backpressure on the serving path). "
    "Ring evictions against --capture-log-size-mb are normal operation "
    "and ride /debug/replay, not this counter.",
    ("result",),
)
replay_pregate = _counter(
    "auth_server_replay_pregate_total",
    "Reconcile replay preflights by result: pass (verdict diff under the "
    "canary guard thresholds — the swap proceeds to its canary with "
    "tightened guards), breach (the candidate snapshot was REJECTED "
    "before serving any live request; a replay-pregate-breach flight "
    "bundle carries the attributed diff), skipped (capture ring below "
    "min_requests — not enough replay evidence to judge).",
    ("result",),
)
replay_diff_flips = _gauge(
    "auth_server_replay_diff_flips",
    "Verdict flips (allow<->deny, both directions) found by the most "
    "recent replay preflight on this lane — 0 after a clean preflight; a "
    "breach leaves the flip count that rejected the swap standing as "
    "incident evidence until the next preflight.",
    _LANE_LABELS,
)

# ---------------------------------------------------------------------------
# Policy CI decision corpus (ISSUE 19, docs/policy_ci.md): distillation
# accounting, synthesis outcomes, and the corpus pregate verdict counters.
# ---------------------------------------------------------------------------

corpus_records = _counter(
    "auth_server_corpus_records_total",
    "Corpus distillation accounting by result: distilled (distinct "
    "decision rows emitted), deduped (captured records that collapsed "
    "into an existing row — its frequency weight absorbs them), "
    "dropped-unparseable (records with no authconfig or a non-JSON "
    "document — accounted, never silently discarded, so a "
    "segment-pruning byte budget can never quietly eat coverage).",
    ("result",),
)
corpus_rows = _gauge(
    "auth_server_corpus_rows",
    "Rows in the corpus the engine's --corpus-pregate loaded, by origin: "
    "captured (distilled from real traffic, frequency-weighted) vs "
    "synthetic (truth-table witnesses for never-fired rules). A zero "
    "synthetic count with unexercised rules means synthesis could not "
    "cover them — see the corpus block's reason codes on /debug/vars.",
    ("origin",),
)
corpus_pregate = _counter(
    "auth_server_corpus_pregate_total",
    "Corpus preflights by result: pass (weighted verdict diff under the "
    "canary guard thresholds), breach (the candidate snapshot was "
    "REJECTED on corpus evidence — possibly a synthetic-only row, i.e. "
    "zero live traffic ever exercised the breaching rule; a "
    "corpus-pregate-breach flight bundle carries the attributed diff), "
    "skipped (no corpus loaded or below the evidence floor).",
    ("result",),
)
corpus_synth = _counter(
    "auth_server_corpus_synth_total",
    "Truth-table row synthesis outcomes by reason: ok (a verified "
    "witness document was admitted) or a typed uncoverability code "
    "(atom-budget-exceeded, statically-dead, unsatisfiable, "
    "unsupported-selector, selector-conflict, opaque-cpu-tree, "
    "materialization-failed — docs/policy_ci.md lists the semantics). "
    "Uncoverable rules are REPORTED, never silently skipped.",
    ("reason",),
)

# ---------------------------------------------------------------------------
# Tenant QoS plane (ISSUE 15, docs/tenancy.md): per-tenant serving counters,
# tenant-scoped admission rejections, and containment state.
#
# CARDINALITY POLICY: every family carrying a `tenant` label is
# bounded-cardinality BY CONSTRUCTION — the tenancy stats flush assigns real
# tenant names only to the top-K tenants by request volume (K from
# TENANT_LABEL_BOUNDS below, the declared HARD bound) and folds everything
# else into the reserved `other` bucket, so a million-tenant corpus can
# never mint a million label values.  analysis/metrics_catalog.py lints
# that every tenant-labelled family declares its bound here (tier-1 +
# --verify-fixtures, with a planted violation self-test).
# ---------------------------------------------------------------------------

# the reserved fold-over label value for tenants outside the top-K
TENANT_OTHER = "other"

# family (exposition name) -> max distinct real-tenant label values the
# flush may mint (the `other` bucket rides on top).  The metrics-catalog
# lint fails any tenant-labelled family missing from this table.
TENANT_LABEL_BOUNDS = {
    "auth_server_tenant_requests_total": 32,
    "auth_server_tenant_denied_total": 32,
    "auth_server_tenant_slo_bad_total": 32,
    "auth_server_tenant_rejected_total": 32,
    "auth_server_tenant_queue_wait_seconds": 32,
    "auth_server_tenant_contained": 32,
}

tenant_requests = _counter(
    "auth_server_tenant_requests_total",
    "Requests decided per tenant (AuthConfig identity) and lane, folded "
    "once per micro-batch from the tenant axis of the provenance fold — "
    "device, host, brownout and degrade lanes all count (contained and "
    "degraded traffic still burns the right tenant's accounting).  "
    "Bounded cardinality: top-K tenants by volume + the `other` bucket "
    "(docs/tenancy.md).",
    _LANE_LABELS + ("tenant",),
)
tenant_denied = _counter(
    "auth_server_tenant_denied_total",
    "Denials per tenant and lane (the same per-batch fold as "
    "auth_server_tenant_requests_total).  Top-K + `other` bounded.",
    _LANE_LABELS + ("tenant",),
)
tenant_slo_bad = _counter(
    "auth_server_tenant_slo_bad_total",
    "Requests counted against the SLO error budget per tenant (latency "
    "over --slo-ms), the tenant axis of the per-lane burn trackers.  "
    "Top-K + `other` bounded.",
    _LANE_LABELS + ("tenant",),
)
tenant_rejected = _counter(
    "auth_server_tenant_rejected_total",
    "Tenant-SCOPED admission rejections by reason: tenant-quota (the "
    "tenant's token bucket ran dry), tenant-queue-share (the tenant's "
    "standing backlog exceeded its weighted share of the bounded submit "
    "queue while the queue was past half its cap), tenant-contained (the "
    "noisy-neighbor containment paced this tenant's traffic), "
    "doomed-deadline (the tenant-aware shedder — the tenant's own "
    "fair-share wait, not the global queue, doomed the deadline).  The "
    "global OVERLOADED latch is untouched by all of these.  Top-K + "
    "`other` bounded.",
    ("tenant", "reason"),
)
tenant_queue_wait = _gauge(
    "auth_server_tenant_queue_wait_seconds",
    "Per-tenant queue-wait EWMA (the tenant axis of the CoDel wait "
    "signal), refreshed on the tenancy flush cadence for the top-K "
    "tenants by volume.  Top-K bounded (no `other`: a mean over unrelated "
    "tenants is not a wait).",
    ("tenant",),
)
tenant_contained = _gauge(
    "auth_server_tenant_contained",
    "1 while the noisy-neighbor detector has this tenant CONTAINED "
    "(sustained share above weight x threshold with the global queue wait "
    "over target): its rows answer via the exact host-oracle lane or "
    "paced typed rejections instead of flipping the global brownout/"
    "OVERLOADED latch; 0 after auto-release.  Bounded by the containment "
    "cap (far below the declared top-K bound).",
    ("tenant",),
)


# ---------------------------------------------------------------------------
# Kernel cost observatory (ISSUE 16, docs/performance.md "Kernel cost
# model"): structural device-cost counters folded ONCE PER MICRO-BATCH by
# runtime/kernel_cost.py's CostLedger.  Unlike the wall-clock series above,
# these count things that do not swing with the host (launches, bytes,
# rows), so tier-1 perf_guard tests pin them as exact values.
# ---------------------------------------------------------------------------

kernel_launches = _counter(
    "auth_server_kernel_launches_total",
    "Device-computation launches (jitted calls reaching the device) per "
    "lane.  One well-formed micro-batch = ONE launch (ROADMAP item 2's "
    "target); cache/dedup-resolved batches and host/degrade evals count "
    "ZERO.  The mesh lane counts one collective launch per shard-step, "
    "not one per shard.",
    _LANE_LABELS,
)
kernel_h2d_bytes = _counter(
    "auth_server_kernel_h2d_bytes_total",
    "Request-operand bytes staged host-to-device per lane (the fused "
    "staging buffer / per-operand upload sizes of each launch).  Snapshot "
    "upload traffic is accounted separately by "
    "auth_server_{delta,full}_upload_bytes_total — together the two give "
    "total H2D.",
    _LANE_LABELS,
)
kernel_d2h_bytes = _counter(
    "auth_server_kernel_d2h_bytes_total",
    "Verdict readback bytes device-to-host per lane: the bitpacked "
    "[pad, packed_width(1+2E)] uint8 result of each launch.",
    _LANE_LABELS,
)
kernel_pad_waste_rows = _counter(
    "auth_server_kernel_pad_waste_rows_total",
    "Padded-minus-real rows per launch (device cycles spent on discarded "
    "rows), the counter twin of the auth_server_batch_pad_occupancy "
    "ratio.  Eff-column slack rides the ledger's /debug/vars block.",
    _LANE_LABELS,
)
kernel_lane = _counter(
    "auth_server_kernel_lane_total",
    "Batches dispatched per kernel lane (ISSUE 17): fused = the one-launch "
    "mega-kernel, matmul = MXU one-hot lane, gather = jnp.take reference.  "
    "Selection is --kernel-lane / AUTHORINO_TPU_KERNEL_LANE (auto arms "
    "fused only on a real TPU backend).",
    ("lane",),
)
kernel_modeled_flops_per_row = _gauge(
    "auth_server_kernel_modeled_flops_per_row",
    "XLA-modeled FLOPs per padded row of the serving snapshot's kernel "
    "(lower().compile().cost_analysis() at reconcile, representative "
    "(pad, eff) shape).  Modeled, not measured: compare generations, "
    "not wall clock.  A >=2x jump vs the previous generation raises the "
    "cost-regression flight-recorder anomaly.",
    ("entry",),
)

_kernel_children: dict = {}


def observe_kernel_cost(lane, launches, h2d_bytes, d2h_bytes,
                        pad_waste_rows) -> None:
    """Fold one batch's structural device cost (cached label children —
    runs once per micro-batch, zero values skipped)."""
    ch = _kernel_children.get(lane)
    if ch is None:
        ch = _kernel_children[lane] = (
            kernel_launches.labels(lane),
            kernel_h2d_bytes.labels(lane),
            kernel_d2h_bytes.labels(lane),
            kernel_pad_waste_rows.labels(lane),
        )
    if launches:
        ch[0].inc(launches)
    if h2d_bytes:
        ch[1].inc(h2d_bytes)
    if d2h_bytes:
        ch[2].inc(d2h_bytes)
    if pad_waste_rows:
        ch[3].inc(pad_waste_rows)


_kernel_lane_children: dict = {}


def observe_kernel_lane(lane: str) -> None:
    """Count one dispatched batch on its kernel lane (cached label child —
    once per micro-batch)."""
    ch = _kernel_lane_children.get(lane)
    if ch is None:
        ch = _kernel_lane_children[lane] = kernel_lane.labels(lane)
    ch.inc()


# ---------------------------------------------------------------------------
# Fleet serving plane (ISSUE 18, docs/fleet.md): N replicas behind the
# consistent-hash/least-loaded router shim, fleet-wide guard aggregation,
# and the verdict-cache warm-join protocol.  No tenant labels here — the
# tenant axis stays on the per-replica families above; fleet aggregation
# folds tenant evidence in-process, it never re-exports per-tenant series.
# ---------------------------------------------------------------------------

fleet_routed = _counter(
    "auth_server_fleet_routed_total",
    "Routing decisions by the fleet router shim, by outcome: primary (the "
    "rendezvous-hash first choice took it — cache/dedup locality "
    "preserved), spillover (deadline-aware spill to the second-choice "
    "replica: the first choice's predicted wait could not meet the "
    "request deadline), load-shift (least-loaded hybrid: the first "
    "choice's backlog exceeded the second's by the imbalance factor), "
    "unhealthy (the first choice was not ready / draining / breaker-open "
    "and the second took it), failover (the routed replica failed typed "
    "mid-flight and the request re-routed), no-replica (every candidate "
    "was unroutable — the caller saw a typed UNAVAILABLE).",
    ("outcome",),
)
fleet_replicas = _gauge(
    "auth_server_fleet_replicas",
    "Replicas currently registered with the fleet router, by state: "
    "ready (routable), draining (SIGTERM choreography in progress — no "
    "new work), down (crashed/removed but not yet deregistered).",
    ("state",),
)
fleet_warm_join = _counter(
    "auth_server_fleet_warm_join_total",
    "Verdict-cache warm-join outcomes when a replica joins the fleet: "
    "imported (hot-set entries adopted under the local snapshot's cache "
    "tokens), skipped (entries whose config fingerprint the joining "
    "snapshot does not carry — a reconcile moved on), mismatch (the "
    "whole digest refused: interner content or encoding epoch diverged "
    "from the joining replica's snapshot, nothing imported).",
    ("result",),
)
fleet_guard_breach = _counter(
    "auth_server_fleet_guard_breach_total",
    "Fleet-wide guard breaches raised by the fold aggregator, by guard "
    "(the same guard names as auth_server_canary_guard_delta, judged on "
    "GLOBAL cohort counts: the canary replica's fold vs the rest of the "
    "fleet; plus global-tenant-share for the cross-replica containment "
    "check that fires when every per-replica share is individually under "
    "threshold).",
    ("guard",),
)

# ---------------------------------------------------------------------------
# Durable local state plane (ISSUE 20, docs/robustness.md "Crash recovery &
# warm restart"): --state-dir snapshot/hotset persistence, warm-restart
# phases, and the atomic-writer failure ledger.
# ---------------------------------------------------------------------------

warm_restart = _counter(
    "auth_server_warm_restart_total",
    "Warm-restart phase outcomes at boot when --state-dir is set, by phase "
    "(snapshot = load + strict re-lint + apply of the local blob before "
    "the control plane connects; hotset = verdict-cache import from the "
    "local HOTSET.json) and result (ok; stale = served fail-static but "
    "older than --max-snapshot-age, readyz degrades and a stale-snapshot "
    "anomaly fires; miss = no artifact on disk, cold start for that "
    "phase; error = artifact present but rejected typed — corrupt blob, "
    "lint refusal, interner mismatch — also a cold start, never a crash).",
    ("phase", "result"),
)
snapshot_age = _gauge(
    "auth_server_snapshot_age_seconds",
    "Age of the state-dir snapshot being served fail-statically (manifest "
    "published_unix to now), set at warm start and zeroed once a live "
    "control-plane snapshot replaces it.  Nonzero past --max-snapshot-age "
    "is the staleness signal behind the readyz degraded reason.",
    (),
)
state_write_failures = _counter(
    "auth_server_state_write_failures_total",
    "Durable-artifact writes that failed inside the shared atomic writer "
    "(utils/atomicio.py), by artifact kind (snapshot-blob, manifest, "
    "hotset, capture, corpus, flight, bench, ...).  Counts both real "
    "filesystem errors and injected fs-stage faults; the destination is "
    "left old-valid in every case except an injected torn write, whose "
    "whole point is that readers must then reject it typed.",
    ("artifact",),
)
