"""Prometheus metrics — same metric names/labels as the reference
(ref: pkg/service/auth_pipeline.go:26-36, pkg/metrics/metrics.go).

Per-evaluator (deep) metrics are gated by the evaluator's ``metrics: true``
flag or the global DEEP_METRICS_ENABLED (ref: pkg/metrics/metrics.go:86-96,
main.go:182) — the gate is applied by callers via the ``labels()`` helpers
always being cheap; recording is unconditional on the aggregate metrics."""

from __future__ import annotations

import logging

try:
    from prometheus_client import Counter, Histogram, REGISTRY

    _PROM = True
except Exception:  # pragma: no cover - prometheus is baked in, but stay safe
    _PROM = False

DEEP_METRICS_ENABLED = False

_EVAL_LABELS = ("namespace", "authconfig", "evaluator_type", "evaluator_name")
_CONF_LABELS = ("namespace", "authconfig")


class _NoopMetric:
    def labels(self, *a, **k):
        return self

    def inc(self, *a):
        pass

    def observe(self, *a):
        pass

    def time(self):
        import contextlib

        return contextlib.nullcontext()


def _counter(name, doc, labels):
    if not _PROM:
        return _NoopMetric()
    try:
        return Counter(name, doc, labels)
    except ValueError:  # already registered (module re-import in tests)
        return _NoopMetric()


def _histogram(name, doc, labels, buckets=None):
    if not _PROM:
        return _NoopMetric()
    try:
        if buckets is not None:
            return Histogram(name, doc, labels, buckets=buckets)
        return Histogram(name, doc, labels)
    except ValueError:
        return _NoopMetric()


evaluator_total = _counter(
    "auth_server_evaluator_total",
    "Total number of evaluations of individual authconfig rule performed by the auth server.",
    _EVAL_LABELS,
)
evaluator_cancelled = _counter(
    "auth_server_evaluator_cancelled",
    "Number of evaluations of individual authconfig rule cancelled by the auth server.",
    _EVAL_LABELS,
)
evaluator_ignored = _counter(
    "auth_server_evaluator_ignored",
    "Number of evaluations of individual authconfig rule ignored by the auth server.",
    _EVAL_LABELS,
)
evaluator_denied = _counter(
    "auth_server_evaluator_denied",
    "Number of denials from individual authconfig rule evaluated by the auth server.",
    _EVAL_LABELS,
)
evaluator_duration = _histogram(
    "auth_server_evaluator_duration_seconds",
    "Response latency of individual authconfig rule evaluated by the auth server (in seconds).",
    _EVAL_LABELS,
)
authconfig_total = _counter(
    "auth_server_authconfig_total",
    "Total number of authconfigs enforced by the auth server, partitioned by authconfig.",
    _CONF_LABELS,
)
authconfig_response_status = _counter(
    "auth_server_authconfig_response_status",
    "Response status of authconfigs sent by the auth server, partitioned by authconfig.",
    _CONF_LABELS + ("status",),
)
authconfig_duration = _histogram(
    "auth_server_authconfig_duration_seconds",
    "Response latency of authconfig enforced by the auth server (in seconds).",
    _CONF_LABELS,
)
response_status = _counter(
    "auth_server_response_status",
    "Status of HTTP response sent by the auth server.",
    ("status",),
)
# µs-scale on-box stage bounds — MUST match native/frontend.cpp
# STAGE_BOUNDS_NS (the C++ frontend buckets in ns; drains map 1:1)
STAGE_BUCKETS = (
    10e-6, 25e-6, 50e-6, 100e-6, 250e-6, 500e-6,
    1e-3, 2.5e-3, 5e-3, 10e-3, 25e-3, 50e-3, 100e-3, 250e-3, 1.0,
)
frontend_stage_duration = _histogram(
    "auth_server_frontend_stage_duration_seconds",
    "On-box per-request stage latency of the native frontend (queue-wait: "
    "encode to batch flush; execute: flush to verdict; respond: verdict to "
    "HTTP/2 submit).",
    ("stage",),
    buckets=STAGE_BUCKETS,
)


_bucketed_fallback_warned = False


def observe_bucketed(hist_child, bucket_counts, sum_seconds) -> None:
    """Fold pre-bucketed counts (non-cumulative per-le, same bounds as the
    histogram) into a prometheus_client Histogram child in O(buckets) —
    per-request observe() calls cannot keep up with the native frontend's
    rates.  Uses the documented-stable internals (`_buckets`/`_sum`, probed
    here so a library change degrades loudly, not silently); the fallback
    preserves the distribution shape by spreading observes across each
    bucket's midpoint instead of collapsing everything into one mean."""
    try:
        # resolve EVERY internal before mutating anything: a partial apply
        # (buckets bumped, then _sum missing) followed by the fallback
        # would double-count the whole drained distribution
        bucket_incs = [b.inc for b in hist_child._buckets]
        sum_inc = hist_child._sum.inc
    except (AttributeError, TypeError):
        bucket_incs = None
    if bucket_incs is not None:
        for i, n in enumerate(bucket_counts):
            if n:
                bucket_incs[i](n)
        if sum_seconds:
            sum_inc(sum_seconds)
        return
    global _bucketed_fallback_warned
    if not _bucketed_fallback_warned:
        _bucketed_fallback_warned = True
        logging.getLogger(__name__).warning(
            "prometheus_client histogram internals changed "
            "(_buckets/_sum missing) — falling back to per-bucket midpoint "
            "observes for drained native-frontend histograms")
    if not hasattr(hist_child, "observe"):
        return
    bounds = list(getattr(hist_child, "_upper_bounds", ()))[:len(bucket_counts)]
    total = sum(bucket_counts)
    if not total:
        return
    if not bounds:
        hist_child.observe(sum_seconds / total)
        return
    import math

    # per-observe cost is the very thing this function exists to avoid — a
    # huge drained backlog must not stall the drain thread for seconds, so
    # counts above the cap are proportionally thinned (logged: rate(count)
    # dashboards undercount while the fallback is active)
    cap = 200_000
    scale = 1.0
    if total > cap:
        scale = cap / total
        logging.getLogger(__name__).warning(
            "histogram fallback drain thinned %d observations to %d "
            "(per-observe fallback cannot keep up with native rates)",
            total, cap)
    counts: list = []
    values: list = []
    lo = 0.0
    for i, n in enumerate(bucket_counts):
        hi = bounds[i] if i < len(bounds) else float("inf")
        if hi == float("inf"):
            # strictly above the last finite bound, else observe() bins
            # these overflow counts into the last finite bucket (le is <=)
            v = math.nextafter(lo, math.inf)
        else:
            v = (lo + hi) / 2.0
        if n:
            counts.append((int(round(n * scale)), len(values)))
            values.append((v, lo, hi))
        if hi != float("inf"):
            lo = hi
    # match the drained sum by shifting values inside their buckets
    # (midpoints alone misstate rate(sum)/rate(count) averages): walk from
    # the top bucket down, absorbing the residual within each bucket's
    # bounds — exact whenever the target sum is consistent with the shape
    # (the +Inf bucket is unbounded above)
    residual = sum_seconds * scale - sum(n * values[j][0] for n, j in counts)
    for n, j in reversed(counts):
        if not n or abs(residual) <= 1e-12:
            continue
        v, b_lo, b_hi = values[j]
        want = v + residual / n
        got = max(want, math.nextafter(b_lo, math.inf))
        if b_hi != float("inf"):
            got = min(got, b_hi)
        values[j] = (got, b_lo, b_hi)
        residual -= (got - v) * n
    for n, j in counts:
        v = values[j][0]
        for _ in range(n):
            hist_child.observe(v)


host_fallback_total = _counter(
    "auth_server_host_fallback_total",
    "Requests re-decided by the host expression oracle because the compact "
    "device payload was lossy for them (membership overflow past members_k).",
    (),
)
host_fallback_shed_total = _counter(
    "auth_server_host_fallback_shed_total",
    "Fallback requests denied (fail closed) because the per-batch host "
    "fallback cap was exceeded.",
    (),
)
