"""Minimal JOSE (JWS/JWT/JWK) on top of `cryptography` — the image has no
python-jose/pyjwt.  Covers what the framework needs: RS256/384/512,
PS256/384/512, ES256/384/512, HS256/384/512 verification and signing, JWK
parse/export, and JWT claim validation mirroring go-oidc's verifier behavior
(iss, exp, nbf; audience check optional — the reference skips client-id
checks, ref pkg/evaluators/identity/oidc.go)."""

from __future__ import annotations

import base64
import hashlib
import hmac as hmac_mod
import json
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple, Union

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec, padding, rsa, utils as asym_utils

__all__ = [
    "JoseError", "b64url_encode", "b64url_decode", "jwk_from_public_key",
    "public_key_from_jwk", "sign_jwt", "verify_jws", "verify_jwt_claims",
    "decode_unverified",
]


class JoseError(Exception):
    pass


def b64url_encode(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


_HASHES = {"256": hashes.SHA256, "384": hashes.SHA384, "512": hashes.SHA512}
_CURVES = {"ES256": ec.SECP256R1, "ES384": ec.SECP384R1, "ES512": ec.SECP521R1}
_CRV_NAMES = {"ES256": "P-256", "ES384": "P-384", "ES512": "P-521"}
_EC_SIZES = {"ES256": 32, "ES384": 48, "ES512": 66}


def _int_to_b64(n: int, size: Optional[int] = None) -> str:
    length = size or (n.bit_length() + 7) // 8
    return b64url_encode(n.to_bytes(length, "big"))


def jwk_from_public_key(key, kid: str = "", alg: str = "") -> Dict[str, Any]:
    """Public JWK dict for an RSA or EC public key."""
    if isinstance(key, rsa.RSAPublicKey):
        nums = key.public_numbers()
        jwk = {"kty": "RSA", "n": _int_to_b64(nums.n), "e": _int_to_b64(nums.e)}
        jwk["alg"] = alg or "RS256"
    elif isinstance(key, ec.EllipticCurvePublicKey):
        nums = key.public_numbers()
        size = (key.curve.key_size + 7) // 8
        crv = {256: "P-256", 384: "P-384", 521: "P-521"}[key.curve.key_size]
        jwk = {
            "kty": "EC",
            "crv": crv,
            "x": _int_to_b64(nums.x, size),
            "y": _int_to_b64(nums.y, size),
        }
        jwk["alg"] = alg or {"P-256": "ES256", "P-384": "ES384", "P-521": "ES512"}[crv]
    else:
        raise JoseError(f"unsupported key type: {type(key)}")
    jwk["use"] = "sig"
    if kid:
        jwk["kid"] = kid
    return jwk


def public_key_from_jwk(jwk: Dict[str, Any]):
    kty = jwk.get("kty")
    if kty == "RSA":
        n = int.from_bytes(b64url_decode(jwk["n"]), "big")
        e = int.from_bytes(b64url_decode(jwk["e"]), "big")
        return rsa.RSAPublicNumbers(e, n).public_key()
    if kty == "EC":
        crv = {"P-256": ec.SECP256R1(), "P-384": ec.SECP384R1(), "P-521": ec.SECP521R1()}[
            jwk["crv"]
        ]
        x = int.from_bytes(b64url_decode(jwk["x"]), "big")
        y = int.from_bytes(b64url_decode(jwk["y"]), "big")
        return ec.EllipticCurvePublicNumbers(x, y, crv).public_key()
    if kty == "oct":
        return b64url_decode(jwk["k"])
    raise JoseError(f"unsupported kty: {kty}")


def _sign_raw(alg: str, key, signing_input: bytes) -> bytes:
    fam, bits = alg[:2], alg[2:]
    h = _HASHES[bits]()
    if fam == "HS":
        if not isinstance(key, (bytes, bytearray)):
            raise JoseError("HS* needs a bytes key")
        return hmac_mod.new(key, signing_input, getattr(hashlib, f"sha{bits}")).digest()
    if fam == "RS":
        return key.sign(signing_input, padding.PKCS1v15(), h)
    if fam == "PS":
        return key.sign(
            signing_input,
            padding.PSS(mgf=padding.MGF1(h), salt_length=h.digest_size),
            h,
        )
    if fam == "ES":
        der = key.sign(signing_input, ec.ECDSA(h))
        r, s = asym_utils.decode_dss_signature(der)
        size = _EC_SIZES[alg]
        return r.to_bytes(size, "big") + s.to_bytes(size, "big")
    raise JoseError(f"unsupported alg: {alg}")


def _verify_raw(alg: str, key, signing_input: bytes, sig: bytes) -> bool:
    fam, bits = alg[:2], alg[2:]
    h = _HASHES[bits]()
    try:
        if fam == "HS":
            expected = hmac_mod.new(
                key, signing_input, getattr(hashlib, f"sha{bits}")
            ).digest()
            return hmac_mod.compare_digest(expected, sig)
        if fam == "RS":
            key.verify(sig, signing_input, padding.PKCS1v15(), h)
            return True
        if fam == "PS":
            key.verify(
                sig,
                signing_input,
                padding.PSS(mgf=padding.MGF1(h), salt_length=h.digest_size),
                h,
            )
            return True
        if fam == "ES":
            size = _EC_SIZES[alg]
            if len(sig) != 2 * size:
                return False
            r = int.from_bytes(sig[:size], "big")
            s = int.from_bytes(sig[size:], "big")
            der = asym_utils.encode_dss_signature(r, s)
            key.verify(der, signing_input, ec.ECDSA(h))
            return True
    except Exception:
        return False
    raise JoseError(f"unsupported alg: {alg}")


def sign_jwt(claims: Dict[str, Any], key, alg: str, kid: str = "", extra_header: Optional[dict] = None) -> str:
    header: Dict[str, Any] = {"alg": alg, "typ": "JWT"}
    if kid:
        header["kid"] = kid
    if extra_header:
        header.update(extra_header)
    h = b64url_encode(json.dumps(header, separators=(",", ":")).encode())
    p = b64url_encode(json.dumps(claims, separators=(",", ":")).encode())
    signing_input = f"{h}.{p}".encode()
    sig = _sign_raw(alg, key, signing_input)
    return f"{h}.{p}.{b64url_encode(sig)}"


def decode_unverified(token: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    try:
        h, p, _ = token.split(".")
        return json.loads(b64url_decode(h)), json.loads(b64url_decode(p))
    except Exception as e:
        raise JoseError(f"malformed JWT: {e}")


# constructing a public-key object from a JWK costs ~100µs in cryptography —
# on the per-request JWT-verify path that dwarfs the signature check itself.
# Cache by key material (not dict identity: JWKS refreshes rebuild the dicts).
_PUBKEY_CACHE: Dict[Tuple, Any] = {}


def _cached_public_key(jwk: Dict[str, Any]):
    # the tuple must cover EVERY field that determines the key material —
    # omitting "k" would collapse all symmetric (oct) keys onto one entry,
    # verifying HMAC tokens against the wrong secret
    k = (jwk.get("kty"), jwk.get("n"), jwk.get("e"),
         jwk.get("crv"), jwk.get("x"), jwk.get("y"), jwk.get("k"))
    key = _PUBKEY_CACHE.get(k)
    if key is None:
        key = public_key_from_jwk(jwk)
        if len(_PUBKEY_CACHE) > 256:  # bound: rotated keys age out wholesale
            _PUBKEY_CACHE.clear()
        _PUBKEY_CACHE[k] = key
    return key


def verify_jws(token: str, keys: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Verify signature against a JWKS key list; returns the claims."""
    try:
        h_b64, p_b64, s_b64 = token.split(".")
    except ValueError:
        raise JoseError("malformed JWT")
    header = json.loads(b64url_decode(h_b64))
    alg = header.get("alg", "")
    if alg in ("", "none"):
        raise JoseError("unsigned JWTs are rejected")
    kid = header.get("kid")
    signing_input = f"{h_b64}.{p_b64}".encode()
    sig = b64url_decode(s_b64)
    candidates = [k for k in keys if not kid or k.get("kid") in (None, kid)]
    if kid and not candidates:
        candidates = keys  # kid not found: try all (JWKS may have rotated)
    for jwk in candidates:
        if jwk.get("alg") and jwk["alg"] != alg:
            continue
        try:
            key = _cached_public_key(jwk)
        except Exception:
            continue
        if _verify_raw(alg, key, signing_input, sig):
            return json.loads(b64url_decode(p_b64))
    raise JoseError("failed to verify signature against any key")


def verify_jwt_claims(
    claims: Dict[str, Any],
    issuer: Optional[str] = None,
    audience: Optional[str] = None,
    leeway_s: int = 30,
) -> None:
    now = time.time()
    if issuer is not None and claims.get("iss") != issuer:
        raise JoseError(f"id token issued by a different provider: {claims.get('iss')!r}")
    exp = claims.get("exp")
    if exp is not None and now > float(exp) + leeway_s:
        raise JoseError("token is expired")
    nbf = claims.get("nbf")
    if nbf is not None and now < float(nbf) - leeway_s:
        raise JoseError("token not valid yet")
    if audience is not None:
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if audience not in auds:
            raise JoseError("audience mismatch")
