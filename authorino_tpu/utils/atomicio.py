"""One atomic-write discipline for every durable artifact (ISSUE 20).

Every on-disk artifact a restart may read back — snapshot blobs,
MANIFEST.json, HOTSET.json, ``.atpucap`` capture segments, ``.atpucorp``
corpus containers, flight-recorder bundles, bench artifacts — is written
through here: tmp file in the destination directory, write, flush, fsync,
``os.replace``.  A SIGKILL (or power cut, modulo directory fsync) at any
instant therefore leaves the destination either old-valid or new-valid,
never half-written; `analysis/code_lint.py`'s ``non-atomic-write`` kind
pins that no in-package writer hand-rolls an ``open(path, "w")`` into a
durable path outside this discipline.

The writers double as the injection points for the fault plane's ``fs``
stage (runtime/faults.py): when faults are armed, each call consults
``FAULTS.fs_fault(artifact)`` and realizes the matched crash shape —
torn / short / rename-fail / eio / enospc — deterministically (prefix
lengths come from the armed seed).  Zero-cost when off: the hook is one
``sys.modules`` lookup unless the faults module is loaded AND armed.

Failures of any origin (real or injected) increment
``auth_server_state_write_failures_total{artifact}`` and leave no stray
tmp file behind; the one deliberate exception is an injected *torn*
write, which scribbles a prefix over the destination itself — that is
the crash aftermath the container readers' typed-rejection contract is
fuzzed against.
"""

from __future__ import annotations

import errno
import json
import os
import sys
from typing import Any, Optional

from . import metrics as metrics_mod

__all__ = ["atomic_write_bytes", "atomic_write_json", "atomic_write_text"]


def _fs_rule(artifact: str):
    """The armed fs-stage fault rule scoped to ``artifact``, or None.
    Reaches the fault plane through sys.modules so an un-imported (and
    therefore necessarily un-armed) faults module costs one dict get."""
    faults = sys.modules.get("authorino_tpu.runtime.faults")
    if faults is None or not faults.ACTIVE:
        return None
    return faults.FAULTS.fs_fault(artifact)


def _prefix_len(n: int) -> int:
    """Deterministic torn/short prefix length in [0, n): drawn from the
    fault plane's seeded rng so one AUTHORINO_TPU_FAULT_SEED reproduces
    the same crash bytes."""
    faults = sys.modules["authorino_tpu.runtime.faults"]
    if n <= 1:
        return 0
    return int(faults.FAULTS.rand() * n) % n


def _inject(rule, path: str, tmp: str, data: bytes) -> None:
    """Realize one fs crash shape.  Always raises OSError; what is on
    disk afterwards is the point:

    - eio:         nothing written anywhere
    - enospc:      a prefix in tmp (caller unlinks it), destination intact
    - short:       a prefix in tmp (caller unlinks it), destination intact
    - rename-fail: full tmp (caller unlinks it), destination intact
    - torn:        a prefix over the DESTINATION — the simulated aftermath
                   of a crashed non-atomic overwrite; readers must reject
                   it typed
    """
    mode = rule.mode
    if mode == "eio":
        raise OSError(errno.EIO, f"injected fs:eio writing {path}")
    if mode in ("enospc", "short"):
        k = _prefix_len(len(data))
        with open(tmp, "wb") as f:  # lint-ok: non-atomic-write -- injected partial tmp write
            f.write(data[:k])
            f.flush()
            os.fsync(f.fileno())
        if mode == "enospc":
            raise OSError(errno.ENOSPC,
                          f"injected fs:enospc after {k}/{len(data)} bytes "
                          f"of {path}")
        raise OSError(errno.EIO,
                      f"injected fs:short write: {k}/{len(data)} bytes "
                      f"of {path}")
    if mode == "rename-fail":
        with open(tmp, "wb") as f:  # lint-ok: non-atomic-write -- tmp discarded by the caller
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        raise OSError(errno.EIO, f"injected fs:rename-fail replacing {path}")
    if mode == "torn":
        k = _prefix_len(len(data))
        with open(path, "wb") as f:  # lint-ok: non-atomic-write -- injected torn destination
            f.write(data[:k])
            f.flush()
            os.fsync(f.fileno())
        raise OSError(errno.EIO,
                      f"injected fs:torn write: {k}/{len(data)} bytes tore "
                      f"{path}")
    raise OSError(errno.EIO, f"injected fs:{mode} writing {path}")


def atomic_write_bytes(path: str, data: bytes, artifact: str = "artifact",
                       fsync: bool = True) -> str:
    """Write ``data`` to ``path`` atomically (tmp + flush + fsync +
    os.replace).  ``artifact`` names the durable-artifact kind for the
    fs fault plane and the failure metric.  Raises OSError on failure —
    real or injected — with the destination left old-valid (except an
    injected torn write, by design) and the tmp file removed."""
    tmp = path + ".tmp"
    try:
        rule = _fs_rule(artifact)
        if rule is not None:
            _inject(rule, path, tmp, data)
        with open(tmp, "wb") as f:
            f.write(data)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        written = os.path.getsize(tmp)
        if written != len(data):
            raise OSError(errno.EIO,
                          f"short write: {written}/{len(data)} bytes of "
                          f"{path}")
        os.replace(tmp, path)
        return path
    except BaseException:
        metrics_mod.state_write_failures.labels(artifact).inc()
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str, artifact: str = "artifact",
                      fsync: bool = True) -> str:
    return atomic_write_bytes(path, text.encode("utf-8"), artifact=artifact,
                              fsync=fsync)


def atomic_write_json(path: str, obj: Any, artifact: str = "artifact",
                      fsync: bool = True, indent: Optional[int] = None,
                      sort_keys: bool = False, default=None) -> str:
    text = json.dumps(obj, indent=indent, sort_keys=sort_keys,
                      default=default)
    return atomic_write_text(path, text, artifact=artifact, fsync=fsync)
