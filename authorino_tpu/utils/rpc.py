"""gRPC status codes + HTTP status mapping used by the check responses
(codes: google.rpc; mapping: ref pkg/service/auth.go:52-59)."""

from __future__ import annotations

OK = 0
CANCELLED = 1
UNKNOWN = 2
INVALID_ARGUMENT = 3
DEADLINE_EXCEEDED = 4
NOT_FOUND = 5
PERMISSION_DENIED = 7
RESOURCE_EXHAUSTED = 8
FAILED_PRECONDITION = 9
ABORTED = 10
UNIMPLEMENTED = 12
INTERNAL = 13
UNAVAILABLE = 14
UNAUTHENTICATED = 16

# rpc code → HTTP status (ref pkg/service/auth.go:52-59 statusCodeMapping)
HTTP_STATUS = {
    OK: 200,
    FAILED_PRECONDITION: 400,
    INVALID_ARGUMENT: 400,
    UNAUTHENTICATED: 401,
    PERMISSION_DENIED: 403,
    NOT_FOUND: 404,
    RESOURCE_EXHAUSTED: 429,
    INTERNAL: 500,
    UNIMPLEMENTED: 501,
    UNAVAILABLE: 503,
    DEADLINE_EXCEEDED: 504,
}


def http_status_for(code: int, override: int = 0) -> int:
    if override:
        return override
    return HTTP_STATUS.get(code, 403)


class CheckAbort(Exception):
    """Typed fail-closed abort of one Check(): carries the rpc code the
    response must use instead of the generic PERMISSION_DENIED mapping.

    Raised by the serving runtime (engine dispatch failures that could not
    degrade → UNAVAILABLE, deadline-aware shedding → DEADLINE_EXCEEDED,
    drain admission stop → UNAVAILABLE) and resolved into an AuthResult by
    AuthPipeline.evaluate — a raw exception must never leak its repr into
    a deny reason (ISSUE 5)."""

    def __init__(self, code: int, message: str):
        self.code = code
        self.message = message
        super().__init__(message)
