"""Multi-window SLO burn-rate tracking (ISSUE 9).

One tracker per serving lane.  The SLI is request goodness: a request is
*bad* when its observed latency exceeds the ``--slo-ms`` target or it failed
with a serving error (typed deadline/overload rejections are the protection
mechanism working, so callers decide which errors burn budget).  The burn
rate over a window is

    burn = (bad / total in window) / (1 - objective)

i.e. 1.0 means the lane is burning its error budget exactly at the rate
that would exhaust it at the SLO period's end; the Google SRE multi-window
multi-burn rule (alert when BOTH a short and a long window burn hot — fast
detection without flapping) is why several windows are tracked at once.

Implementation: a ring of per-second (total, bad) buckets sized to the
longest window, fed per BATCH (counts, not per-request observes — the
native fast lane's zero-per-request-Python contract), folded into
auth_server_slo_burn_rate{lane,window} gauges at most once per second.
Thread-safe; everything is O(1) per batch plus an O(window) fold on the
1 Hz gauge refresh."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

from . import metrics as metrics_mod

__all__ = ["SloTracker", "KeyedBurn", "DEFAULT_WINDOWS"]

# (seconds, label) — short windows page, long windows confirm
DEFAULT_WINDOWS: Tuple[Tuple[int, str], ...] = (
    (60, "1m"), (300, "5m"), (3600, "1h"))


class SloTracker:
    def __init__(self, lane: str, slo_ms: float, objective: float = 0.999,
                 windows: Sequence[Tuple[int, str]] = DEFAULT_WINDOWS):
        self.lane = lane
        self.slo_ms = float(slo_ms)
        self.slo_s = self.slo_ms / 1e3
        self.objective = min(max(float(objective), 0.0), 0.999999)
        self.budget = 1.0 - self.objective
        self.windows = tuple(windows)
        self._span = max(w for w, _ in self.windows)
        # per-second ring: index = epoch_second % span
        self._totals = [0] * self._span
        self._bad = [0] * self._span
        self._stamp = [0] * self._span   # epoch second each bucket holds
        self._lock = threading.Lock()
        self._last_gauge = 0.0
        self.total = 0
        self.bad_total = 0
        self._g = {label: metrics_mod.slo_burn_rate.labels(lane, label)
                   for _, label in self.windows}
        self._c_bad = metrics_mod.slo_bad_total.labels(lane)
        self._c_total = metrics_mod.slo_observed_total.labels(lane)

    # -- feeding -----------------------------------------------------------

    def observe(self, n: int, n_bad: int,
                now: Optional[float] = None) -> None:
        """Fold one batch: ``n`` requests observed, ``n_bad`` of them over
        the latency target (or errored).  One call per micro-batch."""
        if n <= 0:
            return
        now = time.time() if now is None else now
        sec = int(now)
        i = sec % self._span
        with self._lock:
            if self._stamp[i] != sec:
                self._stamp[i] = sec
                self._totals[i] = 0
                self._bad[i] = 0
            self._totals[i] += n
            self._bad[i] += n_bad
            self.total += n
            self.bad_total += n_bad
        self._c_total.inc(n)
        if n_bad:
            self._c_bad.inc(n_bad)
        if now - self._last_gauge >= 1.0:
            self._last_gauge = now
            self._refresh_gauges(sec)

    def observe_errors(self, n: int, now: Optional[float] = None) -> None:
        """Serving errors burn the whole budget for their requests."""
        self.observe(n, n, now=now)

    # -- reading -----------------------------------------------------------

    def _window_counts(self, window_s: int, sec: int) -> Tuple[int, int]:
        total = bad = 0
        lo = sec - window_s
        for j in range(window_s):
            i = (sec - j) % self._span
            if lo < self._stamp[i] <= sec:
                total += self._totals[i]
                bad += self._bad[i]
        return total, bad

    def window_counts(self, window_s: int,
                      now: Optional[float] = None) -> Tuple[int, int]:
        """(total, bad) observed inside the trailing window — the COUNT
        view of ``burn_rate``, for folds that must aggregate before
        dividing (the fleet aggregator sums per-replica counts and takes
        one global burn; averaging per-replica burn rates would weight an
        idle replica's 0/0 the same as a flooded one's)."""
        sec = int(time.time() if now is None else now)
        with self._lock:
            return self._window_counts(window_s, sec)

    def burn_rate(self, window_s: int, now: Optional[float] = None) -> float:
        sec = int(time.time() if now is None else now)
        with self._lock:
            total, bad = self._window_counts(window_s, sec)
        if not total:
            return 0.0
        return (bad / total) / self.budget

    def _refresh_gauges(self, sec: int) -> None:
        with self._lock:
            counts = {label: self._window_counts(w, sec)
                      for w, label in self.windows}
        for label, (total, bad) in counts.items():
            self._g[label].set((bad / total) / self.budget if total else 0.0)

    def to_json(self, now: Optional[float] = None) -> Dict[str, Any]:
        sec = int(time.time() if now is None else now)
        out: Dict[str, Any] = {
            "slo_ms": self.slo_ms,
            "objective": self.objective,
            "observed_total": self.total,
            "bad_total": self.bad_total,
            "windows": {},
        }
        with self._lock:
            for w, label in self.windows:
                total, bad = self._window_counts(w, sec)
                out["windows"][label] = {
                    "total": total, "bad": bad,
                    "burn_rate": round((bad / total) / self.budget, 4)
                    if total else 0.0,
                }
        return out


class KeyedBurn:
    """Per-KEY SLO burn over one coarse sliding window (ISSUE 15: the
    tenant axis of the burn-rate fold).

    The per-lane :class:`SloTracker` keeps a per-second ring — affordable
    once per lane, not once per tenant.  Here each key holds exactly TWO
    half-window buckets (current + previous) that rotate in place, so the
    whole table is O(live keys) memory and O(1) per fold: burn reads the
    sum of both buckets — a sliding window with half-window granularity,
    plenty for the noisy-neighbor detector and the /debug/tenants view.
    Keys idle past a full window are dropped on the amortized sweep."""

    def __init__(self, window_s: float = 60.0, objective: float = 0.999,
                 max_keys: int = 8192):
        self.window_s = float(window_s)
        self.half_s = self.window_s / 2.0
        self.budget = 1.0 - min(max(float(objective), 0.0), 0.999999)
        self.max_keys = int(max_keys)
        self._lock = threading.Lock()
        # key -> [bucket_start, total, bad, prev_total, prev_bad]
        self._k: Dict[str, list] = {}
        self._last_gc = 0.0

    def _rotate(self, rec: list, now: float) -> None:
        if now - rec[0] < self.half_s:
            return
        if now - rec[0] >= self.window_s:
            rec[3] = rec[4] = 0  # both halves stale
        else:
            rec[3], rec[4] = rec[1], rec[2]
        rec[0], rec[1], rec[2] = now, 0, 0

    def fold(self, key: str, n: int, bad: int,
             now: Optional[float] = None) -> None:
        if n <= 0:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            rec = self._k.get(key)
            if rec is None:
                rec = self._k[key] = [now, 0, 0, 0, 0]
            self._rotate(rec, now)
            rec[1] += int(n)
            rec[2] += int(bad)
            if len(self._k) > self.max_keys or \
                    now - self._last_gc > self.window_s:
                self._last_gc = now
                for k in [k for k, r in self._k.items()
                          if now - r[0] > self.window_s]:
                    self._k.pop(k, None)

    def counts(self, key: str, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        with self._lock:
            rec = self._k.get(key)
            if rec is None:
                return 0, 0
            self._rotate(rec, now)
            return rec[1] + rec[3], rec[2] + rec[4]

    def burn(self, key: str, now: Optional[float] = None) -> float:
        total, bad = self.counts(key, now=now)
        if not total:
            return 0.0
        return (bad / total) / self.budget

    def to_json(self, top: int = 8,
                now: Optional[float] = None) -> Dict[str, Any]:
        now = time.monotonic() if now is None else now
        rows = []
        with self._lock:
            for k, rec in self._k.items():
                total = rec[1] + rec[3]
                bad = rec[2] + rec[4]
                if total:
                    rows.append((k, round((bad / total) / self.budget, 4),
                                 total, bad))
        rows.sort(key=lambda r: -r[1])
        return {
            "window_s": self.window_s,
            "keys": len(rows),
            "top_burn": [{"key": k, "burn_rate": b, "total": t, "bad": d}
                         for k, b, t, d in rows[:top]],
        }
