"""Background refresh workers (ref: pkg/workers/worker.go:10-85 — ticker
goroutine with Stop()); asyncio translation used by OIDC JWKS refresh and
OPA external-registry refresh."""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Optional

__all__ = ["Worker", "start_worker"]

log = logging.getLogger("authorino_tpu.workers")


class Worker:
    def __init__(self, interval_s: float, task: Callable[[], Awaitable[None]]):
        self.interval_s = interval_s
        self.task = task
        self._stopped = asyncio.Event()
        self._runner: Optional[asyncio.Task] = None

    def start(self) -> "Worker":
        self._runner = asyncio.ensure_future(self._run())
        return self

    async def _run(self):
        while not self._stopped.is_set():
            try:
                await asyncio.wait_for(self._stopped.wait(), timeout=self.interval_s)
                break
            except asyncio.TimeoutError:
                pass
            try:
                await self.task()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # refresh failures are logged, not fatal
                log.warning("worker task failed: %s", e)

    async def stop(self):
        self._stopped.set()
        if self._runner is not None:
            self._runner.cancel()
            try:
                await self._runner
            except (asyncio.CancelledError, Exception):
                pass
            self._runner = None


def start_worker(interval_s: float, task: Callable[[], Awaitable[None]]) -> Worker:
    return Worker(interval_s, task).start()
