"""Tracing: W3C TraceContext propagation end-to-end + OTLP span export
(semantics: ref pkg/trace/exporter.go:26-117, trace.go:20-27 — request spans
carry authorino.request_id and propagate x-request-id; W3C headers are
injected into every outbound evaluator HTTP call).

Export has two backends, preferred in order:
  1. the OpenTelemetry SDK + OTLP exporter when installed (endpoint URL
     semantics like the reference: ``rpc://host:port`` → gRPC OTLP,
     ``http(s)://`` → HTTP OTLP, basic-auth from URL userinfo);
  2. a built-in OTLP/HTTP JSON exporter (this module) — the OTLP JSON
     mapping needs no SDK, so ``http(s)://`` endpoints export even on
     images that ship only the OTel API (exercised against a fake
     collector in tests/test_tracing.py).
Propagation always works regardless — that is the part that affects
request correctness."""

from __future__ import annotations

import logging
import os
import random
import re
import secrets
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional
from urllib.parse import urlsplit

log = logging.getLogger("authorino_tpu.trace")

# crypto-seeded PRNG for span/trace ids (GIL-atomic getrandbits)
_ID_RNG = random.Random(secrets.token_bytes(16))

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

_otel_tracer = None
_native_exporter: Optional["NativeOtlpExporter"] = None


class NativeOtlpExporter:
    """SDK-free OTLP/HTTP JSON exporter: finished spans batch into
    ExportTraceServiceRequest JSON (trace/span ids hex per the OTLP JSON
    mapping) POSTed to ``<endpoint>/v1/traces``."""

    def __init__(self, endpoint: str, headers: Dict[str, str],
                 service_name: str = "authorino-tpu",
                 flush_interval_s: float = 2.0, max_queue: int = 4096):
        url = endpoint.rstrip("/")
        self.url = url if url.endswith("/v1/traces") else url + "/v1/traces"
        self.headers = {"content-type": "application/json", **headers}
        self.service_name = service_name
        self.flush_interval_s = flush_interval_s
        self.max_queue = max_queue
        import threading

        self._queue: list = []
        self._task: Any = None
        self._timer: Any = None  # threading.Timer for loop-less enqueues
        # guards the queue swap in flush(): the timer thread and the event
        # loop may both flush — without this, both could LOAD the same
        # span list before either STOREs [] and export it twice
        self._flush_lock = threading.Lock()

    def enqueue(self, span: dict) -> None:
        if len(self._queue) >= self.max_queue:
            return  # shed rather than grow unbounded (collector outage)
        self._queue.append(span)
        if self._task is None or self._task.done():
            import asyncio

            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                # sync caller (dispatch executor, drain threads): no loop to
                # ride.  A later loop-context enqueue flushes these too, but
                # none may ever come — arm a one-shot timer thread so the
                # spans are never stranded.
                self._arm_timer()
                return
            self._task = loop.create_task(self._run())

    def _arm_timer(self) -> None:
        import threading

        t = self._timer
        if t is not None and t.is_alive():
            return
        t = threading.Timer(self.flush_interval_s, self._thread_flush)
        t.daemon = True
        self._timer = t
        t.start()

    def _thread_flush(self) -> None:
        """Timer-thread flush for spans enqueued outside any event loop:
        a throwaway loop + private session (get_session binds sessions per
        loop, which would leak one per flush here)."""
        import asyncio

        # clear the handle FIRST: this method runs on the timer thread, so
        # is_alive() in _arm_timer would see it and skip every re-arm —
        # stranding any span enqueued while the flush is in flight
        self._timer = None
        task = self._task
        if task is not None and not task.done():
            alive = True
            try:
                alive = not task.get_loop().is_closed()
            except RuntimeError:
                alive = False
            if alive:
                return  # a loop-context task owns the queue now
            # the task's loop closed without draining it (embedder teardown
            # skipped shutdown_tracing): it will never run — the timer owns
            # the queue from here on
            self._task = None
        if self._queue:
            try:
                async def go():
                    import aiohttp

                    async with aiohttp.ClientSession() as session:
                        await self.flush(session=session)

                asyncio.run(go())
            except Exception as e:
                log.warning("OTLP timer flush failed: %s", e)
        if self._queue:
            self._arm_timer()  # more loop-less spans arrived meanwhile

    def _payload(self, spans: list) -> dict:
        return {
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.service_name},
                }]},
                "scopeSpans": [{
                    "scope": {"name": "authorino-tpu"},
                    "spans": spans,
                }],
            }]
        }

    async def flush(self, session=None) -> None:
        with self._flush_lock:  # non-async: held only for the list swap
            if not self._queue:
                return
            spans, self._queue = self._queue, []
        if session is None:
            from .http import get_session

            session = get_session()
        try:
            async with session.post(self.url, json=self._payload(spans),
                                    headers=self.headers) as resp:
                await resp.read()
                if resp.status >= 400:
                    log.warning("OTLP export rejected: HTTP %d", resp.status)
        except Exception as e:
            log.warning("OTLP export failed: %s", e)

    async def _run(self) -> None:
        import asyncio

        while self._queue:
            await asyncio.sleep(self.flush_interval_s)
            await self.flush()


def tracing_active() -> bool:
    """True when spans are exported (SDK provider or built-in exporter) —
    serving paths that cannot mint per-request spans (the native fast lane)
    must defer to the Python pipeline while this holds."""
    return _otel_tracer is not None or _native_exporter is not None


async def shutdown_tracing() -> None:
    """Flush the built-in exporter on shutdown (the SDK path gets this via
    BatchSpanProcessor's own shutdown)."""
    if _native_exporter is not None:
        task = _native_exporter._task
        if task is not None and not task.done():
            task.cancel()
        timer = _native_exporter._timer
        if timer is not None:
            timer.cancel()
        await _native_exporter.flush()


def setup_tracing(endpoint: str, insecure: bool = False, service_name: str = "authorino-tpu") -> bool:
    """Configure a real OTel provider when the SDK is available, else the
    built-in OTLP/HTTP JSON exporter.  Returns True when exporting is
    active (ref: CreateTraceProvider)."""
    global _otel_tracer, _native_exporter
    if not endpoint:
        return False
    # endpoint userinfo → basic-auth header, shared by both backends
    split = urlsplit(endpoint)
    headers: Dict[str, str] = {}
    if split.username:
        import base64 as b64

        cred = f"{split.username}:{split.password or ''}"
        headers["authorization"] = "Basic " + b64.b64encode(cred.encode()).decode()
        endpoint = endpoint.replace(f"{split.username}:{split.password or ''}@", "", 1)
    try:
        from opentelemetry import trace as otel_trace
        from opentelemetry.sdk.resources import Resource  # type: ignore
        from opentelemetry.sdk.trace import TracerProvider  # type: ignore
        from opentelemetry.sdk.trace.export import BatchSpanProcessor  # type: ignore

        if split.scheme in ("rpc", "grpc"):
            from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (  # type: ignore
                OTLPSpanExporter,
            )

            exporter = OTLPSpanExporter(
                endpoint=f"{split.hostname}:{split.port or 4317}",
                insecure=insecure,
                headers=headers or None,
            )
        else:
            from opentelemetry.exporter.otlp.proto.http.trace_exporter import (  # type: ignore
                OTLPSpanExporter,
            )

            exporter = OTLPSpanExporter(endpoint=endpoint, headers=headers or None)
        provider = TracerProvider(resource=Resource.create({"service.name": service_name}))
        provider.add_span_processor(BatchSpanProcessor(exporter))
        otel_trace.set_tracer_provider(provider)
        _otel_tracer = otel_trace.get_tracer("authorino-tpu")
        return True
    except ImportError as e:
        if split.scheme in ("rpc", "grpc"):
            log.warning(
                "tracing endpoint %s needs the OTel gRPC exporter, which is not "
                "installed (%s); spans propagate W3C context but are not exported "
                "(use an http(s):// endpoint for the built-in OTLP/JSON exporter)",
                endpoint, e,
            )
            return False
        _native_exporter = NativeOtlpExporter(endpoint, headers, service_name)
        log.info("OTel SDK not installed; using the built-in OTLP/HTTP JSON "
                 "exporter → %s", _native_exporter.url)
        return True


@dataclass
class RequestSpan:
    """Per-request span: parsed-or-minted W3C trace context
    (ref: NewAuthorizationRequestSpan, pkg/trace/trace.go:20-27)."""

    trace_id: str
    span_id: str
    sampled: bool = True
    request_id: str = ""
    start: float = field(default_factory=time.monotonic)
    start_ns: int = field(default_factory=time.time_ns)  # wall clock for OTLP
    _otel_span: Any = None

    @classmethod
    def from_headers(cls, headers: Dict[str, str], request_id: str = "") -> "RequestSpan":
        tp = headers.get("traceparent", "")
        m = _TRACEPARENT_RE.match(tp) if tp else None
        if m:
            trace_id = m.group(2)
            sampled = bool(int(m.group(4), 16) & 1)
        else:
            # PRNG ids, crypto-seeded once: trace ids are correlation
            # handles, not secrets (OTel's own generator is math/rand), and
            # os.urandom per request is measurable at slow-lane rates.
            # `| 1` keeps the all-zero id W3C-invalid case out.
            trace_id = "%032x" % (_ID_RNG.getrandbits(128) | 1)
            sampled = True
        span = cls(
            trace_id=trace_id,
            span_id="%016x" % (_ID_RNG.getrandbits(64) | 1),
            sampled=sampled,
            request_id=request_id,
        )
        if _otel_tracer is not None:
            try:
                span._otel_span = _otel_tracer.start_span(
                    "Check", attributes={"authorino.request_id": request_id}
                )
            except Exception:
                pass
        return span

    def traceparent(self) -> str:
        """Outbound W3C header (new child span id per outbound call is
        overkill for our purposes; the span id uniquely marks this hop)."""
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"

    def inject(self, headers: Dict[str, str]) -> Dict[str, str]:
        headers["traceparent"] = self.traceparent()
        if self.request_id:
            headers["x-request-id"] = self.request_id
        return headers

    def end(self, error: Optional[str] = None) -> None:
        if self._otel_span is not None:
            try:
                if error:
                    self._otel_span.set_attribute("error", error)
                self._otel_span.end()
            except Exception:
                pass
        elif _native_exporter is not None and self.sampled:
            span = {
                "traceId": self.trace_id,
                "spanId": self.span_id,
                "name": "Check",
                "kind": 2,  # SERVER
                "startTimeUnixNano": str(self.start_ns),
                "endTimeUnixNano": str(
                    self.start_ns + int((time.monotonic() - self.start) * 1e9)),
                "attributes": [{
                    "key": "authorino.request_id",
                    "value": {"stringValue": self.request_id},
                }],
                "status": {"code": 2, "message": error} if error else {},
            }
            _native_exporter.enqueue(span)

    def child(self, name: str) -> Optional["PhaseSpan"]:
        """Child span for one pipeline phase (identity/metadata/
        authorization/response).  None when span export is off or this
        request is unsampled — phase spans must never cost an untraced
        request more than this method call."""
        if not self.sampled:
            return None
        if self._otel_span is not None:
            child = PhaseSpan(
                trace_id=self.trace_id,
                span_id="%016x" % (_ID_RNG.getrandbits(64) | 1),
                parent_span_id=self.span_id,
                name=name,
            )
            try:
                from opentelemetry import trace as otel_trace

                child._otel_span = _otel_tracer.start_span(
                    name, context=otel_trace.set_span_in_context(self._otel_span))
            except Exception:
                pass
            return child
        if _native_exporter is not None:
            return PhaseSpan(
                trace_id=self.trace_id,
                span_id="%016x" % (_ID_RNG.getrandbits(64) | 1),
                parent_span_id=self.span_id,
                name=name,
            )
        return None


@dataclass
class PhaseSpan:
    """One pipeline phase under a request span (the span tree the reference
    only approximates with its single Check span — each phase's share of a
    slow request becomes directly visible)."""

    trace_id: str
    span_id: str
    parent_span_id: str
    name: str
    start: float = field(default_factory=time.monotonic)
    start_ns: int = field(default_factory=time.time_ns)
    _otel_span: Any = None

    def end(self, error: Optional[str] = None) -> None:
        if self._otel_span is not None:
            try:
                if error:
                    self._otel_span.set_attribute("error", error)
                self._otel_span.end()
            except Exception:
                pass
        elif _native_exporter is not None:
            _native_exporter.enqueue({
                "traceId": self.trace_id,
                "spanId": self.span_id,
                "parentSpanId": self.parent_span_id,
                "name": self.name,
                "kind": 1,  # INTERNAL
                "startTimeUnixNano": str(self.start_ns),
                "endTimeUnixNano": str(
                    self.start_ns + int((time.monotonic() - self.start) * 1e9)),
                "status": {"code": 2, "message": error} if error else {},
            })


def export_device_batch_span(batch_size: int, pad: int, eff: int,
                             links, start_ns: int,
                             duration_s: float) -> None:
    """One ``DeviceBatch`` span per kernel launch, span-LINKED (not
    parented: a batch belongs to many traces at once) to the request spans
    whose verdicts rode it.  ``links`` is [(trace_id_hex, span_id_hex)].
    Carries batch_size / pad / eff so pad waste and jit-variant choice are
    attributable per launch.  Supported by both export backends."""
    end_ns = start_ns + int(duration_s * 1e9)
    if _otel_tracer is not None:
        try:
            from opentelemetry.trace import Link, SpanContext, TraceFlags

            olinks = [
                Link(SpanContext(
                    trace_id=int(t, 16), span_id=int(s, 16),
                    is_remote=False, trace_flags=TraceFlags(0x01)))
                for t, s in links
            ]
            span = _otel_tracer.start_span(
                "DeviceBatch", links=olinks, start_time=start_ns,
                attributes={"batch.size": int(batch_size),
                            "batch.pad": int(pad),
                            "batch.eff": int(eff)})
            span.end(end_time=end_ns)
        except Exception:
            pass
        return
    if _native_exporter is None:
        return
    _native_exporter.enqueue({
        # fresh trace: the batch is no single request's descendant — the
        # links below stitch it to each constituent request trace
        "traceId": "%032x" % (_ID_RNG.getrandbits(128) | 1),
        "spanId": "%016x" % (_ID_RNG.getrandbits(64) | 1),
        "name": "DeviceBatch",
        "kind": 1,  # INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(end_ns),
        "attributes": [
            {"key": "batch.size", "value": {"intValue": str(int(batch_size))}},
            {"key": "batch.pad", "value": {"intValue": str(int(pad))}},
            {"key": "batch.eff", "value": {"intValue": str(int(eff))}},
        ],
        "links": [{"traceId": t, "spanId": s} for t, s in links],
        "status": {},
    })
