"""Tracing: W3C TraceContext propagation end-to-end + optional OpenTelemetry
SDK export (semantics: ref pkg/trace/exporter.go:26-117, trace.go:20-27 —
request spans carry authorino.request_id and propagate x-request-id; W3C
headers are injected into every outbound evaluator HTTP call).

The image ships only the OTel *API*; when an SDK + OTLP exporter are
installed, ``setup_tracing`` wires a real provider (endpoint URL semantics
like the reference: ``rpc://host:port`` → gRPC OTLP, ``http(s)://`` → HTTP
OTLP, basic-auth from URL userinfo).  Without the SDK, spans are lightweight
native objects and propagation still works — the part that affects request
correctness."""

from __future__ import annotations

import logging
import os
import re
import secrets
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional
from urllib.parse import urlsplit

log = logging.getLogger("authorino_tpu.trace")

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

_otel_tracer = None


def setup_tracing(endpoint: str, insecure: bool = False, service_name: str = "authorino-tpu") -> bool:
    """Configure a real OTel provider when the SDK is available.
    Returns True when exporting is active (ref: CreateTraceProvider)."""
    global _otel_tracer
    if not endpoint:
        return False
    try:
        from opentelemetry import trace as otel_trace
        from opentelemetry.sdk.resources import Resource  # type: ignore
        from opentelemetry.sdk.trace import TracerProvider  # type: ignore
        from opentelemetry.sdk.trace.export import BatchSpanProcessor  # type: ignore

        split = urlsplit(endpoint)
        headers = {}
        if split.username:
            import base64 as b64

            cred = f"{split.username}:{split.password or ''}"
            headers["authorization"] = "Basic " + b64.b64encode(cred.encode()).decode()
        if split.scheme in ("rpc", "grpc"):
            from opentelemetry.exporter.otlp.proto.grpc.trace_exporter import (  # type: ignore
                OTLPSpanExporter,
            )

            exporter = OTLPSpanExporter(
                endpoint=f"{split.hostname}:{split.port or 4317}",
                insecure=insecure,
                headers=headers or None,
            )
        else:
            from opentelemetry.exporter.otlp.proto.http.trace_exporter import (  # type: ignore
                OTLPSpanExporter,
            )

            exporter = OTLPSpanExporter(endpoint=endpoint, headers=headers or None)
        provider = TracerProvider(resource=Resource.create({"service.name": service_name}))
        provider.add_span_processor(BatchSpanProcessor(exporter))
        otel_trace.set_tracer_provider(provider)
        _otel_tracer = otel_trace.get_tracer("authorino-tpu")
        return True
    except ImportError as e:
        log.warning(
            "tracing endpoint configured but the OpenTelemetry SDK/exporter is "
            "not installed (%s); spans propagate W3C context but are not exported",
            e,
        )
        return False


@dataclass
class RequestSpan:
    """Per-request span: parsed-or-minted W3C trace context
    (ref: NewAuthorizationRequestSpan, pkg/trace/trace.go:20-27)."""

    trace_id: str
    span_id: str
    sampled: bool = True
    request_id: str = ""
    start: float = field(default_factory=time.monotonic)
    _otel_span: Any = None

    @classmethod
    def from_headers(cls, headers: Dict[str, str], request_id: str = "") -> "RequestSpan":
        tp = headers.get("traceparent", "")
        m = _TRACEPARENT_RE.match(tp) if tp else None
        if m:
            trace_id = m.group(2)
            sampled = bool(int(m.group(4), 16) & 1)
        else:
            trace_id = secrets.token_hex(16)
            sampled = True
        span = cls(
            trace_id=trace_id,
            span_id=secrets.token_hex(8),
            sampled=sampled,
            request_id=request_id,
        )
        if _otel_tracer is not None:
            try:
                span._otel_span = _otel_tracer.start_span(
                    "Check", attributes={"authorino.request_id": request_id}
                )
            except Exception:
                pass
        return span

    def traceparent(self) -> str:
        """Outbound W3C header (new child span id per outbound call is
        overkill for our purposes; the span id uniquely marks this hop)."""
        flags = "01" if self.sampled else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"

    def inject(self, headers: Dict[str, str]) -> Dict[str, str]:
        headers["traceparent"] = self.traceparent()
        if self.request_id:
            headers["x-request-id"] = self.request_id
        return headers

    def end(self, error: Optional[str] = None) -> None:
        if self._otel_span is not None:
            try:
                if error:
                    self._otel_span.set_attribute("error", error)
                self._otel_span.end()
            except Exception:
                pass
