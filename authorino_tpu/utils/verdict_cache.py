"""Snapshot-scoped verdict cache: bounded LRU over (generation, row digest).

Authorization verdicts are pure functions of (compiled snapshot, encoded
operand row) — Cedar (arxiv 2403.04651) and the microservice-auth survey
(arxiv 2009.02114) both identify decision memoization at the enforcement
point as the standard lever for amortizing authz latency, and on this
architecture every avoided row is bytes that never cross the ~120ms device
link.  Keys fold the snapshot GENERATION in, so invalidation is structural:
a snapshot swap bumps the generation and every old entry becomes
unreachable (then ages out of the LRU) — no TTL races with in-flight
batches, which insert and serve under the generation they were encoded
against.

The row digest is the full canonical operand byte string
(compiler/pack.py row_key_bytes): exact, collision-free, and it already
folds in config_id and the host_fallback flag.  Host-fallback rows must
never be cached by callers — their compact encoding is lossy (membership
overflow past K), so the digest does not determine their verdict.

Thread-safe; counters are plain ints read without the lock (GIL-atomic,
monotonic — consumers fold deltas)."""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Hashable, Optional

__all__ = ["VerdictCache"]


class VerdictCache:
    def __init__(self, max_entries: int = 32768):
        self.max_entries = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Hashable, Any]" = OrderedDict()
        # monotonic counters (GIL-atomic increments under the lock;
        # lock-free reads): hits/misses count get(), adds counts distinct
        # put()s, evictions counts LRU drops
        self.hits = 0
        self.misses = 0
        self.adds = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key: Hashable) -> Optional[Any]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry

    def put(self, key: Hashable, value: Any) -> None:
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self._entries[key] = value
                return
            self._entries[key] = value
            self.adds += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def counts(self) -> dict:
        return {"hits": self.hits, "misses": self.misses,
                "adds": self.adds, "evictions": self.evictions,
                "entries": len(self._entries)}

    def hottest(self, k: int) -> list:
        """Top-``k`` (key, value) pairs, most-recently-used first — the
        fleet warm-join hot-set export (ISSUE 18).  The LRU order IS the
        heat signal this cache keeps: the MRU head is exactly the working
        set a cold replica joining mid-flood would otherwise re-miss.
        Values are returned as stored (callers must not mutate them)."""
        if k <= 0:
            return []
        with self._lock:
            out = []
            for key in reversed(self._entries):
                out.append((key, self._entries[key]))
                if len(out) >= k:
                    break
            return out
