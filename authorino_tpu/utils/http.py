"""Shared outbound HTTP: one aiohttp session per loop, JSON-or-text response
parsing with the reference's tolerance (ref: pkg/json/json.go:63-94
UnmashalJSONResponse), W3C trace-context header injection hook."""

from __future__ import annotations

import asyncio
import json
from typing import Any, Dict, Optional, Tuple

import aiohttp

__all__ = ["get_session", "parse_response", "HttpError", "close_sessions"]

_sessions: Dict[int, aiohttp.ClientSession] = {}


class HttpError(Exception):
    def __init__(self, status: int, body: str):
        self.status = status
        self.body = body
        super().__init__(f"{status}: {body[:200]}")


def get_session() -> aiohttp.ClientSession:
    loop = asyncio.get_running_loop()
    sess = _sessions.get(id(loop))
    if sess is None or sess.closed:
        sess = aiohttp.ClientSession()
        _sessions[id(loop)] = sess
    return sess


async def close_sessions() -> None:
    for sess in list(_sessions.values()):
        if not sess.closed:
            await sess.close()
    _sessions.clear()


async def parse_response(resp: aiohttp.ClientResponse) -> Any:
    """Status must be 200; body decodes as JSON when possible, else returns
    the raw text (ref: pkg/evaluators/metadata/generic_http.go:82-87 parses
    JSON content-type, other content types resolve as plain text)."""
    body = await resp.text()
    if resp.status != 200:
        raise HttpError(resp.status, body)
    ctype = resp.headers.get("Content-Type", "")
    if "application/json" in ctype:
        try:
            return json.loads(body)
        except Exception as e:
            raise HttpError(resp.status, f"got Content-Type = application/json, but could not unmarshal as JSON: {e}")
    try:
        return json.loads(body)
    except Exception:
        return body
