"""Infra utilities: rpc codes, metrics, logging, workers, env."""
