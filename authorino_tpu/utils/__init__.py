"""Infra utilities: rpc codes, metrics, logging, workers, env."""

__all__ = ["bucket_pow2"]


def bucket_pow2(n: int, minimum: int = 16) -> int:
    """Round up to the next power-of-two bucket (≥ minimum) — the shared
    policy that pins jit-variant counts for batch sizes (runtime/engine.py)
    and byte-tensor widths (compiler/pack.py)."""
    b = minimum
    while b < n:
        b *= 2
    return b
