"""PolicyModel — the framework's flagship "model": a compiled rule corpus
plus its batched evaluation function.

The analog of a forward pass here is one micro-batched policy evaluation:
(requests × rules) int32 compares + boolean-circuit reduction → per-request
allow verdicts (SURVEY.md north star; replaces the per-request Go hot loop at
ref: pkg/service/auth_pipeline.go:287-322 + pkg/jsonexp/expressions.go:59).
There is no gradient training in this domain; the "training-step analog" is
corpus compilation (reconcile-time) + this evaluation step (request-time).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.compile import CompiledPolicy, ConfigRules, compile_corpus
from ..compiler.encode import EncodedBatch, encode_batch
from ..ops.pattern_eval import _eval_jit, forward, to_device

__all__ = ["PolicyModel"]


class PolicyModel:
    """Single-corpus model: replicated params, batch (data) parallel only.
    For the rules-axis-sharded variant see parallel/sharded_eval.py."""

    def __init__(self, policy: CompiledPolicy, device=None):
        self.policy = policy
        self.params = to_device(policy, device=device)
        # module-level jit: identical-shape models share one trace cache
        self._apply = _eval_jit

    @classmethod
    def from_configs(cls, configs: Sequence[ConfigRules], members_k: int = 16, device=None) -> "PolicyModel":
        return cls(compile_corpus(configs, members_k=members_k), device=device)

    # ---- request path ----------------------------------------------------

    def encode(self, docs: Sequence[Any], config_rows: Sequence[int], batch_pad: int = 0) -> EncodedBatch:
        return encode_batch(self.policy, docs, config_rows, batch_pad=batch_pad)

    def apply(self, encoded: EncodedBatch) -> Tuple[np.ndarray, np.ndarray]:
        has_dfa = self.params["dfa_tables"] is not None
        own, verdict = self._apply(
            self.params,
            jnp.asarray(encoded.attrs_val),
            jnp.asarray(encoded.attrs_members),
            jnp.asarray(encoded.overflow),
            jnp.asarray(encoded.cpu_lane),
            jnp.asarray(encoded.config_id),
            jnp.asarray(encoded.attr_bytes) if has_dfa else None,
            jnp.asarray(encoded.byte_ovf) if has_dfa else None,
        )
        return np.asarray(own), np.asarray(verdict)

    def decide(self, docs: Sequence[Any], config_names: Sequence[str]) -> List[bool]:
        rows = [self.policy.config_ids[n] for n in config_names]
        own, _ = self.apply(self.encode(docs, rows))
        return [bool(b) for b in own[: len(docs)]]

    # ---- graft-entry support --------------------------------------------

    def forward_fn_and_args(self, batch: int = 64):
        """A jittable forward fn + realistic example args (for compile checks)."""
        enc = encode_batch(self.policy, [], [], batch_pad=batch)
        has_dfa = self.params["dfa_tables"] is not None
        args = (
            self.params,
            jnp.asarray(enc.attrs_val),
            jnp.asarray(enc.attrs_members),
            jnp.asarray(enc.overflow),
            jnp.asarray(enc.cpu_lane),
            jnp.asarray(enc.config_id),
            jnp.asarray(enc.attr_bytes) if has_dfa else None,
            jnp.asarray(enc.byte_ovf) if has_dfa else None,
        )
        return forward, args
