"""PolicyModel — the framework's flagship "model": a compiled rule corpus
plus its batched evaluation function.

The analog of a forward pass here is one micro-batched policy evaluation:
(requests × rules) int32 compares + boolean-circuit reduction → per-request
allow verdicts (SURVEY.md north star; replaces the per-request Go hot loop at
ref: pkg/service/auth_pipeline.go:287-322 + pkg/jsonexp/expressions.go:59).
There is no gradient training in this domain; the "training-step analog" is
corpus compilation (reconcile-time) + this evaluation step (request-time).

Requests whose membership arrays overflow the compact payload (K elements)
are re-decided on host by the expression oracle — `host_results` implements
the exact reference semantics (errors ⇒ False at the root;
ref: pkg/jsonexp/expressions.go:59-100) and is also the differential-test
oracle for the kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.compile import CompiledPolicy, ConfigRules, compile_corpus
from ..compiler.encode import EncodedBatch, encode_batch
from ..compiler.pack import DeviceBatch, pack_batch
from ..ops.pattern_eval import _eval_jit, forward, to_device

__all__ = ["PolicyModel", "host_results", "apply_host_fallback"]


def apply_host_fallback(decide, fb, own_rule, own_skipped, cap) -> None:
    """Shared fallback policy for BOTH serving paths (single-corpus engine +
    mesh ShardedPolicyModel — TestServingPathBitParity holds them identical):
    re-decide up to ``cap`` membership-overflow rows via ``decide(r) ->
    (rule_row, skipped_row)``; rows beyond the cap are denied fail-closed.
    Meters auth_server_host_fallback_{total,shed_total}."""
    from ..utils import metrics as metrics_mod

    decided = fb if cap is None else fb[:cap]
    shed = fb[len(decided):]
    for r in decided:
        own_rule[r], own_skipped[r] = decide(int(r))
    for r in shed:
        own_rule[r] = False
        own_skipped[r] = False
    if len(fb):
        metrics_mod.host_fallback_total.inc(len(decided))
        if len(shed):
            metrics_mod.host_fallback_shed_total.inc(len(shed))


def host_results(
    policy: CompiledPolicy, doc: Any, row: int
) -> Tuple[bool, np.ndarray, np.ndarray]:
    """Exact host-side decision for one request via the expression oracle:
    (own verdict, per-evaluator rule results [E], skipped [E]) with the
    same padding/tail semantics as the kernel's eval_full_jit."""
    E = policy.eval_rule.shape[1]
    rule_res = np.ones((E,), dtype=bool)       # padded cols: TRUE_SLOT
    skipped = np.zeros((E,), dtype=bool)
    for e, (cond, rule) in enumerate(policy.config_exprs[row]):
        if cond is not None:
            try:
                cond_ok = bool(cond.matches(doc))
            except Exception:
                cond_ok = False
            if not cond_ok:
                skipped[e] = True
                continue
        try:
            rule_res[e] = bool(rule.matches(doc))
        except Exception:
            rule_res[e] = False
    own = bool(np.all(skipped | rule_res))
    return own, rule_res, skipped


class PolicyModel:
    """Single-corpus model: replicated params, batch (data) parallel only.
    For the rules-axis-sharded variant see parallel/sharded_eval.py."""

    def __init__(self, policy: CompiledPolicy, device=None):
        self.policy = policy
        self.params = to_device(policy, device=device)
        # module-level jit: identical-shape models share one trace cache
        self._apply = _eval_jit

    @classmethod
    def from_configs(cls, configs: Sequence[ConfigRules], members_k: int = 16, device=None) -> "PolicyModel":
        return cls(compile_corpus(configs, members_k=members_k), device=device)

    # ---- request path ----------------------------------------------------

    def encode(self, docs: Sequence[Any], config_rows: Sequence[int], batch_pad: int = 0) -> DeviceBatch:
        enc = encode_batch(self.policy, docs, config_rows, batch_pad=batch_pad)
        return pack_batch(self.policy, enc)

    def encode_json(self, parts: Sequence[bytes], config_rows: Sequence[int],
                    batch_pad: int = 0) -> DeviceBatch:
        """GIL-free encode from raw authorization-JSON bytes (one UTF-8 blob
        per request — what a wire frontend already holds).  Falls back to
        the Python encoder via json.loads when the native module is
        unavailable."""
        from ..native import get_native_encoder

        nat = get_native_encoder(self.policy)
        if nat is not None:
            enc = nat.encode_json_parts(parts, config_rows, batch_pad)
            if enc is not None:
                return pack_batch(self.policy, enc)
        import json

        return self.encode([json.loads(pt) for pt in parts], config_rows, batch_pad)

    def apply(self, db: DeviceBatch) -> Tuple[np.ndarray, np.ndarray]:
        from ..ops.pattern_eval import _extra_operands

        has_dfa = self.params["dfa_tables"] is not None
        own, verdict = self._apply(
            self.params,
            jnp.asarray(db.attrs_val),
            jnp.asarray(db.members_c),
            jnp.asarray(db.cpu_dense),
            jnp.asarray(db.config_id),
            jnp.asarray(db.attr_bytes) if has_dfa else None,
            jnp.asarray(db.byte_ovf) if has_dfa else None,
            *_extra_operands(db),
        )
        return np.asarray(own), np.asarray(verdict)

    def decide(self, docs: Sequence[Any], config_names: Sequence[str]) -> List[bool]:
        return self.decide_rows(docs, [self.policy.config_ids[n] for n in config_names])

    def decide_rows(self, docs: Sequence[Any], rows: Sequence[int]) -> List[bool]:
        db = self.encode(docs, rows)
        own, _ = self.apply(db)
        out = [bool(b) for b in own[: len(docs)]]
        if db.host_fallback.any():
            for r in np.nonzero(db.host_fallback[: len(docs)])[0]:
                out[r], _, _ = host_results(self.policy, docs[r], rows[r])
        return out

    # ---- graft-entry support --------------------------------------------

    def forward_fn_and_args(self, batch: int = 64):
        """A jittable forward fn + realistic example args (for compile checks)."""
        db = self.encode([], [], batch_pad=batch)
        has_dfa = self.params["dfa_tables"] is not None
        attr_bytes = db.attr_bytes
        if has_dfa:
            # re-pad to the full byte budget: an empty batch trims to the
            # minimum width, but the compile check must cover the widest
            # DFA-scan variant production values can trigger
            from ..compiler.compile import DFA_VALUE_BYTES

            full = np.zeros(attr_bytes.shape[:-1] + (DFA_VALUE_BYTES,), dtype=np.uint8)
            full[..., : attr_bytes.shape[-1]] = attr_bytes
            attr_bytes = full
        from ..ops.pattern_eval import _extra_operands

        args = (
            self.params,
            jnp.asarray(db.attrs_val),
            jnp.asarray(db.members_c),
            jnp.asarray(db.cpu_dense),
            jnp.asarray(db.config_id),
            jnp.asarray(attr_bytes) if has_dfa else None,
            jnp.asarray(db.byte_ovf) if has_dfa else None,
            *_extra_operands(db),
        )
        return forward, args
