"""Policy models: compiled rule corpora + their batched evaluation steps."""

from .policy_model import PolicyModel  # noqa: F401
