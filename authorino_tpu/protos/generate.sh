#!/bin/sh
# Regenerate python gencode from the wire-compatible proto subset.
# grpc_tools is not in the image; service stubs are hand-wired with
# grpc.method_handlers_generic_handler (see service/grpc_server.py).
cd "$(dirname "$0")"
protoc -Isrc \
  src/google/rpc/status.proto \
  src/envoy/type/v3/http_status.proto \
  src/envoy/config/core/v3/base.proto \
  src/envoy/config/core/v3/address.proto \
  src/envoy/service/auth/v3/attribute_context.proto \
  src/envoy/service/auth/v3/external_auth.proto \
  src/grpc/health/v1/health.proto \
  --python_out=gen
mv gen/grpc gen/grpc_health_gen 2>/dev/null || true
