"""Wire-compatible protobuf gencode for Envoy ext_authz v3 + gRPC health.

The .proto sources under ``src/`` are a minimal re-declaration of the public
Envoy/google API message shapes (same packages + field numbers, so byte-level
wire compatibility), NOT copies of the full envoy api tree.  ``generate.sh``
rebuilds ``gen/`` with protoc.

``envoy.*`` and ``google.rpc`` import via namespace-package merging by
putting ``gen/`` on sys.path; the health gencode lives under
``grpc_health_gen`` because grpcio's regular ``grpc`` package cannot merge
namespaces."""

import os
import sys

_GEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "gen")
if _GEN not in sys.path:
    sys.path.insert(0, _GEN)

from envoy.service.auth.v3 import attribute_context_pb2, external_auth_pb2  # noqa: E402,F401
from envoy.config.core.v3 import address_pb2, base_pb2  # noqa: E402,F401
from envoy.type.v3 import http_status_pb2  # noqa: E402,F401
from google.rpc import status_pb2  # noqa: E402,F401
from grpc_health_gen.health.v1 import health_pb2  # noqa: E402,F401
