"""Native (C++) runtime components.

``native/encoder.cpp`` + ``native/pymod.cpp`` build into one extension
module (``_atpuenc``) implementing the host half of the hot path — selector
walk → gjson-String render → intern lookup → tensor scatter — with two
front-ends:

  - ``encode_docs``: walks the Python dict documents directly (no JSON
    round-trip); default.
  - ``encode_json``: parses a JSON blob GIL-free with threads — wins on
    many-core hosts / large batches (AUTHORINO_TPU_ENCODE_MODE=json).

compiler/encode.py's Python implementation is the semantic reference and the
automatic fallback.  Builds on first use with the baked-in g++ (no pip
deps); AUTHORINO_TPU_NATIVE=0 forces the Python path.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sysconfig
import threading

__all__ = ["load_library", "native_enabled", "NativeEncoder", "get_native_encoder"]

log = logging.getLogger("authorino_tpu.native")

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "_atpuenc.so")

_lock = threading.Lock()
_mod = None
_load_failed = False


def native_enabled() -> bool:
    return os.environ.get("AUTHORINO_TPU_NATIVE", "1") not in ("0", "false", "no")


def _build() -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-I", sysconfig.get_paths()["include"],
        os.path.join(_NATIVE_DIR, "pymod.cpp"),
        "-ldl",  # frontend.cpp dlopens libnghttp2 (absent → slow lanes only)
        "-o", _LIB_PATH + ".tmp",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        os.replace(_LIB_PATH + ".tmp", _LIB_PATH)
        return True
    except (subprocess.SubprocessError, OSError) as e:
        detail = getattr(e, "stderr", b"")
        log.warning("native encoder build failed (%s); using Python encoder: %s",
                    e, detail.decode()[:500] if detail else "")
        return False


def load_library():
    """Build (if stale) and import the _atpuenc extension; None on failure."""
    global _mod, _load_failed
    if _mod is not None or _load_failed or not native_enabled():
        return _mod
    with _lock:
        if _mod is not None or _load_failed:
            return _mod
        try:
            srcs = [os.path.join(_NATIVE_DIR, f)
                    for f in ("encoder.cpp", "frontend.cpp", "pymod.cpp")]
            stale = (not os.path.exists(_LIB_PATH)
                     or os.path.getmtime(_LIB_PATH) < max(os.path.getmtime(s) for s in srcs))
        except OSError:
            stale = True
        if stale and not _build():
            _load_failed = True
            return None
        try:
            spec = importlib.util.spec_from_file_location("_atpuenc", _LIB_PATH)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception as e:
            log.warning("native encoder load failed: %s", e)
            _load_failed = True
            return None
        _mod = mod
        return _mod


_LOADGEN_PATH = os.path.join(_BUILD_DIR, "loadgen")


def build_loadgen():
    """Build (if stale) the standalone HTTP/2 load generator
    (native/loadgen.cpp); returns its path or None."""
    src = os.path.join(_NATIVE_DIR, "loadgen.cpp")
    try:
        stale = (not os.path.exists(_LOADGEN_PATH)
                 or os.path.getmtime(_LOADGEN_PATH) < os.path.getmtime(src))
    except OSError:
        stale = True
    if not stale:
        return _LOADGEN_PATH
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-std=c++17", src, "-o", _LOADGEN_PATH + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_LOADGEN_PATH + ".tmp", _LOADGEN_PATH)
        return _LOADGEN_PATH
    except (subprocess.SubprocessError, OSError) as e:
        log.warning("loadgen build failed: %s", e)
        return None


from .encoder import NativeEncoder, get_native_encoder  # noqa: E402,F401
