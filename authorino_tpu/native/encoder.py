"""Wrapper for the _atpuenc extension (native/encoder.cpp + pymod.cpp).

Prepares flattened policy tables once per compiled corpus, then encodes
micro-batches through one C call — walking the Python dict documents
directly by default, or via a GIL-free threaded JSON-blob path
(AUTHORINO_TPU_ENCODE_MODE=json).  Attrs whose selectors use gjson
extensions (``#``, queries, ``@modifiers``) and whole-tree CPU leaves are
finished in Python — exact parity with compiler/encode.py is asserted by
tests/test_native_encoder.py's differential suite.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..authjson import selector as sel
from ..compiler.compile import (
    DFA_VALUE_BYTES,
    OP_CPU,
    OP_ERROR,
    OP_EXCL,
    OP_INCL,
    OP_REGEX_DFA,
    OP_TREE_CPU,
    CompiledPolicy,
)
from ..compiler.encode import EncodedBatch, _MISSING, _render
from ..compiler.intern import EMPTY_ID, PAD
from ..compiler.pack import wire_dtype

__all__ = ["NativeEncoder", "get_native_encoder"]


def _addr(a: np.ndarray) -> int:
    return a.ctypes.data


import re as _re

# the exact grammar the C walkers' array-index parse accepts (encoder.cpp
# walk / pymod.cpp walk_py: ASCII space/tab trim, one sign, ASCII digits)
_C_INT_FORM = _re.compile(r"^[ \t]*[+-]?[0-9]+[ \t]*\Z")  # \Z: '$' would pass '1\n'


def _int_divergent(seg: str) -> bool:
    """True when Python int(seg) accepts a form the C parsers reject
    (underscores, non-ASCII digits, unicode whitespace): the attr must be
    Python-finished or the two paths disagree on list-index segments."""
    try:
        int(seg)
    except (ValueError, TypeError):
        return False
    return _C_INT_FORM.match(seg) is None


class _LazyDocs:
    """Parse a doc from its JSON part only if a finishing task needs it."""

    def __init__(self, parts: Sequence[bytes]):
        self._parts = parts
        self._cache: Dict[int, Any] = {}

    def __getitem__(self, i: int):
        doc = self._cache.get(i)
        if doc is None:
            doc = json.loads(self._parts[i])
            self._cache[i] = doc
        return doc

    def __len__(self):
        return len(self._parts)


def _blob(strings: List[str]):
    """(blob bytes, offs int64[n+1])"""
    parts = [s.encode("utf-8") for s in strings]
    offs = np.zeros(len(parts) + 1, dtype=np.int64)
    np.cumsum([len(p) for p in parts], out=offs[1:])
    return b"".join(parts), offs


class NativeEncoder:
    def __init__(self, mod, policy: CompiledPolicy):
        self._mod = mod
        self.policy = policy
        p = policy

        intern_strings = list(p.interner._table.keys())
        intern_ids = np.fromiter(p.interner._table.values(), dtype=np.int32,
                                 count=len(p.interner._table))
        intern_blob, intern_offs = _blob(intern_strings)

        # per-attr plain dot-paths; anything fancier is Python-finished
        segs: List[str] = []
        attr_seg_offs = np.zeros(p.n_attrs + 1, dtype=np.int32)
        attr_complex = np.zeros(p.n_attrs, dtype=np.uint8)
        self._complex_attrs: List[int] = []
        for a, selector_str in enumerate(p.attr_selectors):
            parsed = sel._parse_path(selector_str) if selector_str else ()
            if (selector_str and all(s.kind == "key" for s in parsed)
                    and not any(_int_divergent(s.key) for s in parsed)):
                segs.extend(s.key for s in parsed)
            else:
                attr_complex[a] = 1
                self._complex_attrs.append(a)
            attr_seg_offs[a + 1] = len(segs)
        seg_blob, seg_offs = _blob(segs)
        self._seg_objs = tuple(segs)  # PyUnicode keys for the dict-walk path

        cfg_attr_offs = np.zeros(p.n_configs + 1, dtype=np.int32)
        cfg_attr_idx: List[int] = []
        cfg_cpu_offs = np.zeros(p.n_configs + 1, dtype=np.int32)
        cfg_cpu_idx: List[int] = []
        for g in range(p.n_configs):
            cfg_attr_idx.extend(p.config_attrs[g])
            cfg_attr_offs[g + 1] = len(cfg_attr_idx)
            cfg_cpu_idx.extend(p.config_cpu_leaves[g])
            cfg_cpu_offs[g + 1] = len(cfg_cpu_idx)
        cfg_attr_idx_np = np.asarray(cfg_attr_idx or [0], dtype=np.int32)
        cfg_cpu_idx_np = np.asarray(cfg_cpu_idx or [0], dtype=np.int32)

        # max CPU tasks per doc of config g + cpu leaves Python must finish
        self._cpu_task_bound = np.zeros(max(p.n_configs, 1), dtype=np.int64)
        complex_set = set(self._complex_attrs)
        self._complex_cpu_leaves: List[List[int]] = []
        for g in range(p.n_configs):
            bound = 0
            cleaves = []
            for leaf in p.config_cpu_leaves[g]:
                op = int(p.leaf_op[leaf])
                is_complex = op != OP_TREE_CPU and int(p.leaf_attr[leaf]) in complex_set
                if op in (OP_TREE_CPU, OP_CPU, OP_REGEX_DFA) or is_complex:
                    bound += 1
                if is_complex:
                    cleaves.append(leaf)
            self._cpu_task_bound[g] = bound
            self._complex_cpu_leaves.append(cleaves)

        leaf_op = np.ascontiguousarray(p.leaf_op, dtype=np.int32)
        leaf_attr = np.ascontiguousarray(p.leaf_attr, dtype=np.int32)
        leaf_const = np.ascontiguousarray(p.leaf_const, dtype=np.int32)
        attr_byte_slot = np.ascontiguousarray(p.attr_byte_slot, dtype=np.int32)

        self._handle = mod.policy_new(
            intern_blob, _addr(intern_offs), _addr(intern_ids), len(intern_strings),
            p.n_attrs, seg_blob, _addr(seg_offs), len(segs), _addr(attr_seg_offs),
            _addr(attr_complex), _addr(attr_byte_slot),
            p.n_leaves, _addr(leaf_op), _addr(leaf_attr), _addr(leaf_const),
            p.n_configs, _addr(cfg_attr_offs), _addr(cfg_attr_idx_np),
            _addr(cfg_cpu_offs), _addr(cfg_cpu_idx_np),
            p.members_k, DFA_VALUE_BYTES, max(p.n_byte_attrs, 1),
        )
        self.mode = os.environ.get("AUTHORINO_TPU_ENCODE_MODE", "object")
        # a few threads beyond the core count wins even on small hosts: the
        # encode slices interleave with (GIL-released) RPC dispatch threads
        # instead of running as one long burst that delays them
        self.n_threads = int(os.environ.get(
            "AUTHORINO_TPU_ENCODE_THREADS", min(8, 4 * (os.cpu_count() or 1))))

    # ------------------------------------------------------------------
    def encode_batch(self, docs: Sequence[Any], config_rows: Sequence[int],
                     batch_pad: int = 0) -> Optional[EncodedBatch]:
        """Returns an EncodedBatch, or None if the native path bailed
        (caller falls back to the Python encoder)."""
        n = len(docs)
        if n and not isinstance(docs, list):
            docs = list(docs)
        if n and self.mode == "json":
            try:
                parts = [json.dumps(d, separators=(",", ":"), ensure_ascii=False).encode("utf-8")
                         for d in docs]
            except (TypeError, ValueError):
                return None  # non-serializable doc → Python path raises the real error
            return self.encode_json_parts(parts, config_rows, batch_pad, docs=docs)
        return self._encode(docs, None, config_rows, batch_pad)

    def encode_json_parts(self, parts: Sequence[bytes], config_rows: Sequence[int],
                          batch_pad: int = 0, docs: Optional[Sequence[Any]] = None,
                          ) -> Optional[EncodedBatch]:
        """GIL-free hot-path entry: ``parts[i]`` is request i's authorization
        JSON as UTF-8 bytes (what a wire frontend already holds).  The C
        side parses + encodes with internal threads while the GIL is
        released.  ``docs`` (parsed dicts) is only needed when the corpus
        has whole-tree CPU leaves or gjson-extended selectors; when absent,
        the rare task that needs one parses it from the blob on demand."""
        return self._encode(docs, parts, config_rows, batch_pad)

    def _encode(self, docs, parts, config_rows: Sequence[int],
                batch_pad: int = 0) -> Optional[EncodedBatch]:
        p = self.policy
        n = len(parts) if parts is not None else len(docs)
        B = max(n, 1)
        if batch_pad and batch_pad > B:
            B = batch_pad
        A, K, L = p.n_attrs, p.members_k, p.n_leaves
        NB = max(p.n_byte_attrs, 1)

        # wire dtype: ids store as int16 when the interner fits — the C
        # encoder writes the narrow type directly, so pack_batch never pays
        # a cast pass over the dominant tensors
        dt = wire_dtype(p)
        attrs_val = np.full((B, A), EMPTY_ID, dtype=dt)
        attrs_members = np.full((B, A, K), PAD, dtype=dt)
        elem16 = 1 if dt == np.int16 else 0
        overflow = np.zeros((B, A), dtype=bool)
        cpu_lane = np.zeros((B, L), dtype=bool)
        config_id = np.zeros((B,), dtype=np.int32)
        attr_bytes = np.zeros((B, NB, DFA_VALUE_BYTES), dtype=np.uint8)
        byte_ovf = np.zeros((B, NB), dtype=bool)

        if n:
            rows = np.asarray(config_rows, dtype=np.int32)
            config_id[:n] = rows
            max_tasks = int(self._cpu_task_bound[rows].sum()) + 1
            arena_cap = max_tasks * (DFA_VALUE_BYTES + 64) + 4096
            task_r = np.zeros(max_tasks, dtype=np.int32)
            task_leaf = np.zeros(max_tasks, dtype=np.int32)
            task_off = np.zeros(max_tasks, dtype=np.int64)
            task_len = np.zeros(max_tasks, dtype=np.int32)
            arena = np.zeros(arena_cap, dtype=np.uint8)

            out_addrs = (
                _addr(attrs_val), _addr(attrs_members), _addr(overflow),
                _addr(cpu_lane), _addr(attr_bytes), _addr(byte_ovf),
                _addr(task_r), _addr(task_leaf), _addr(task_off), _addr(task_len),
            )
            if parts is not None:
                doc_offs = np.zeros(n + 1, dtype=np.int64)
                np.cumsum([len(pt) for pt in parts], out=doc_offs[1:])
                blob = b"".join(parts)
                rc = self._mod.encode_json(
                    self._handle, blob, _addr(doc_offs), n, _addr(rows),
                    A, K, L, NB, DFA_VALUE_BYTES, *out_addrs,
                    max_tasks, _addr(arena), arena_cap, self.n_threads, elem16)
            else:
                try:
                    rc = self._mod.encode_docs(
                        self._handle, self._seg_objs, docs, _addr(rows), n,
                        A, K, L, NB, DFA_VALUE_BYTES, *out_addrs,
                        max_tasks, _addr(arena), arena_cap, elem16)
                except Exception:
                    return None  # render error (non-serializable nested value)
            if rc < 0:
                return None

            need_doc = bool(self._complex_attrs) or rc
            if need_doc and docs is None and parts is not None:
                docs = _LazyDocs(parts)

            # ---- Python finishing: complex attrs + their cpu leaves ----
            if self._complex_attrs:
                self._finish_complex(docs, rows, attrs_val, attrs_members,
                                     overflow, cpu_lane, attr_bytes, byte_ovf)

            # ---- Python finishing: regex / tree tasks ----
            if rc:
                arena_bytes = arena.tobytes()
                for i in range(rc):
                    r, leaf, vlen = int(task_r[i]), int(task_leaf[i]), int(task_len[i])
                    if vlen == -2:
                        continue  # complex-attr leaf, handled above
                    if vlen == -1:
                        expr = p.leaf_tree[leaf]
                        try:
                            v = bool(expr.matches(docs[r])) if expr is not None else False
                        except Exception:
                            v = False
                        cpu_lane[r, leaf] = v
                        continue
                    rx = p.leaf_regex[leaf]
                    if rx is None:
                        cpu_lane[r, leaf] = False
                        continue
                    off = int(task_off[i])
                    text = arena_bytes[off:off + vlen].decode("utf-8", "surrogatepass")
                    cpu_lane[r, leaf] = rx.search(text) is not None

        return EncodedBatch(
            attrs_val=attrs_val,
            attrs_members=attrs_members,
            overflow=overflow,
            cpu_lane=cpu_lane,
            config_id=config_id,
            attr_bytes=attr_bytes,
            byte_ovf=byte_ovf,
        )

    # ------------------------------------------------------------------
    def _finish_complex(self, docs, rows, attrs_val, attrs_members, overflow,
                        cpu_lane, attr_bytes, byte_ovf) -> None:
        """Resolve gjson-extended selectors the C side skipped — same loop
        body as compiler/encode.py restricted to those attrs/leaves."""
        p = self.policy
        lookup = p.interner.lookup
        complex_set = set(self._complex_attrs)
        K = p.members_k
        for r in range(len(docs)):
            row = int(rows[r])
            todo = [a for a in p.config_attrs[row] if a in complex_set]
            if not todo:
                continue
            doc = docs[r]
            res_by_attr: Dict[int, Any] = {}
            for attr in todo:
                res = sel.get(doc, p.attr_selectors[attr])
                v = res.value if res.exists else _MISSING
                res_by_attr[attr] = v
                rendered = _render(v)
                vid = lookup(rendered)
                attrs_val[r, attr] = vid
                slot = int(p.attr_byte_slot[attr])
                if slot >= 0:
                    raw = rendered.encode("utf-8")
                    if len(raw) > DFA_VALUE_BYTES or 0 in raw:
                        byte_ovf[r, slot] = True
                    elif raw:
                        attr_bytes[r, slot, : len(raw)] = np.frombuffer(raw, dtype=np.uint8)
                if isinstance(v, list):
                    for k, e in enumerate(v[:K]):
                        attrs_members[r, attr, k] = lookup(_render(e))
                    if len(v) > K:
                        overflow[r, attr] = True
                elif v is not _MISSING and v is not None:
                    attrs_members[r, attr, 0] = vid
            for leaf in self._complex_cpu_leaves[row]:
                op = int(p.leaf_op[leaf])
                attr = int(p.leaf_attr[leaf])
                if attr not in res_by_attr:
                    continue
                v = res_by_attr[attr]
                if op == OP_REGEX_DFA:
                    slot = int(p.attr_byte_slot[attr])
                    if slot >= 0 and byte_ovf[r, slot]:
                        rx = p.leaf_regex[leaf]
                        cpu_lane[r, leaf] = rx.search(_render(v)) is not None if rx else False
                elif op == OP_CPU:
                    rx = p.leaf_regex[leaf]
                    cpu_lane[r, leaf] = rx.search(_render(v)) is not None if rx else False
                elif op in (OP_INCL, OP_EXCL) and overflow[r, attr]:
                    members = v if isinstance(v, list) else []
                    const = int(p.leaf_const[leaf])
                    is_member = any(lookup(_render(e)) == const for e in members)
                    cpu_lane[r, leaf] = is_member if op == OP_INCL else not is_member


def get_native_encoder(policy: CompiledPolicy) -> Optional[NativeEncoder]:
    """Build (and cache on the policy) a NativeEncoder, or None when the
    native library is unavailable/disabled."""
    cached = getattr(policy, "_native_encoder", None)
    if cached is not None:
        return cached if cached is not False else None
    if (int(getattr(policy, "n_num_attrs", 0) or 0)
            or int(getattr(policy, "n_rel_slots", 0) or 0)
            or getattr(policy, "ovf_assist", False)):
        # the C encoder predates the numeric/relation lanes and the
        # overflow assist (ISSUE 14): corpora using them fall back to the
        # Python encoder until encoder.cpp learns the new operands —
        # exactness over speed, never a partially-filled batch
        policy._native_encoder = False  # type: ignore[attr-defined]
        return None
    from . import load_library

    mod = load_library()
    if mod is None:
        policy._native_encoder = False  # type: ignore[attr-defined]
        return None
    enc = NativeEncoder(mod, policy)
    policy._native_encoder = enc  # type: ignore[attr-defined]
    return enc
