"""Pattern-expression layer: the DSL that compiles to TPU tensors."""

from .ast import (  # noqa: F401
    All,
    And,
    Any_,
    Expression,
    InGroup,
    Operator,
    Or,
    Pattern,
    PatternError,
    TRUE,
    FALSE,
)
