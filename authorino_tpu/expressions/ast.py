"""Pattern-expression AST + CPU reference evaluator.

Semantics mirrored from the reference's pkg/jsonexp
(ref: pkg/jsonexp/expressions.go:53-178):

  - ``Pattern{selector, operator, value}`` with operators
    eq / neq / incl / excl / matches
  - eq/neq compare the gjson-String() rendering of the resolved value
  - incl/excl walk Result.Array() comparing element String() renderings
  - matches applies an RE2-style regex to the String() rendering
  - ``And`` / ``Or`` trees; ``All()`` / ``Any()`` build n-ary combinators;
    an empty And is vacuously true, an empty Or is false
    (ref: pkg/jsonexp/expressions.go:111-125, 136-154)

This CPU evaluator is the correctness oracle for the TPU kernel
(differential-tested in tests/test_compiler_differential.py).  In the
reference the ``matches`` operator recompiles its regex on every call
(ref: pkg/jsonexp/expressions.go:87); here patterns precompile once.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from enum import Enum
from typing import Any, Callable, Tuple, Union

from ..authjson import selector
from ..authjson.selector import WALK_MISS as _MISS
from ..authjson.selector import compile_walk as _compile_walk
from ..authjson.selector import render_value as _render

__all__ = [
    "Operator", "Pattern", "And", "Or", "All", "Any_", "Expression",
    "PatternError", "TRUE", "FALSE",
]


class PatternError(Exception):
    """Evaluation error (e.g. invalid regex) — propagates as a deny in the
    authorization phase, like the reference's error return."""


class Operator(str, Enum):
    EQ = "eq"
    NEQ = "neq"
    INCL = "incl"
    EXCL = "excl"
    MATCHES = "matches"

    @classmethod
    def from_string(cls, s: str) -> "Operator":
        try:
            return cls(s)
        except ValueError:
            raise PatternError(f"unsupported operator for json authorization: {s!r}")


def _compile_pattern(pat: "Pattern") -> Callable[[Any], bool]:
    """Close the selector walk, operator dispatch, and value rendering over
    one function — resolved once at construction instead of per call (the
    reference re-parses its gjson selector and recompiles its regex on every
    Matches, ref: pkg/jsonexp/expressions.go:61,87)."""
    op = pat.operator
    want = pat.value
    walk = _compile_walk(pat.selector)
    if walk is None:
        sel_get = selector.get
        path = pat.selector
        if op is Operator.EQ:
            return lambda doc: want == sel_get(doc, path).string()
        if op is Operator.NEQ:
            return lambda doc: want != sel_get(doc, path).string()
        if op is Operator.INCL:
            return lambda doc: any(
                want == item.string() for item in sel_get(doc, path).array())
        if op is Operator.EXCL:
            return lambda doc: all(
                want != item.string() for item in sel_get(doc, path).array())
        rx = pat._regex  # MATCHES

        def run_rx_slow(doc, _rx=rx, _err=getattr(pat, "_regex_error", "invalid regex")):
            if _rx is None:
                raise PatternError(_err)
            return _rx.search(sel_get(doc, path).string()) is not None

        return run_rx_slow

    if op is Operator.EQ:
        return lambda doc: want == _render(walk(doc))
    if op is Operator.NEQ:
        return lambda doc: want != _render(walk(doc))
    if op is Operator.INCL:
        # gjson array(): list → elements; missing/None → []; scalar → [self]
        def run_incl(doc, _walk=walk, _want=want):
            v = _walk(doc)
            if type(v) is list:
                return any(_want == _render(e) for e in v)
            if v is _MISS or v is None:
                return False
            return _want == _render(v)

        return run_incl
    if op is Operator.EXCL:
        def run_excl(doc, _walk=walk, _want=want):
            v = _walk(doc)
            if type(v) is list:
                return all(_want != _render(e) for e in v)
            if v is _MISS or v is None:
                return True
            return _want != _render(v)

        return run_excl
    rx = pat._regex  # MATCHES

    def run_rx(doc, _walk=walk, _rx=rx, _err=getattr(pat, "_regex_error", "invalid regex")):
        if _rx is None:
            raise PatternError(_err)
        return _rx.search(_render(_walk(doc))) is not None

    return run_rx


@dataclass(frozen=True)
class Pattern:
    selector: str
    operator: Operator
    value: str

    def __post_init__(self):
        # coerce plain-string operators ("eq" == Operator.EQ under str-Enum
        # equality, but dispatch below uses identity) and validate early
        if not isinstance(self.operator, Operator):
            object.__setattr__(self, "operator", Operator.from_string(str(self.operator)))
        if self.operator is Operator.MATCHES:
            try:
                object.__setattr__(self, "_regex", re.compile(self.value))
            except re.error as e:
                object.__setattr__(self, "_regex", None)
                object.__setattr__(self, "_regex_error", str(e))
        else:
            object.__setattr__(self, "_regex", None)
        # shadow the class method with the compiled closure (instance
        # attribute wins on lookup — one call layer, zero per-call dispatch)
        object.__setattr__(self, "matches", _compile_pattern(self))

    def matches(self, doc: Any) -> bool:  # overridden per-instance in __post_init__
        raise AssertionError("unreachable: compiled in __post_init__")

    def __str__(self):
        return f"{self.selector} {self.operator.value} {self.value}"


@dataclass(frozen=True)
class And:
    children: Tuple["Expression", ...] = ()

    def __post_init__(self):
        fns = tuple(c.matches for c in self.children)
        if len(fns) == 1:
            run = fns[0]
        else:
            def run(doc, _fns=fns):
                return all(f(doc) for f in _fns)
        object.__setattr__(self, "matches", run)

    def matches(self, doc: Any) -> bool:  # overridden per-instance
        return all(c.matches(doc) for c in self.children)

    def __str__(self):
        return "(" + " && ".join(str(c) for c in self.children) + ")"


@dataclass(frozen=True)
class Or:
    children: Tuple["Expression", ...] = ()

    def __post_init__(self):
        fns = tuple(c.matches for c in self.children)
        if len(fns) == 1:
            run = fns[0]
        else:
            def run(doc, _fns=fns):
                return any(f(doc) for f in _fns)
        object.__setattr__(self, "matches", run)

    def matches(self, doc: Any) -> bool:  # overridden per-instance
        return any(c.matches(doc) for c in self.children)

    def __str__(self):
        return "(" + " || ".join(str(c) for c in self.children) + ")"


Expression = Union[Pattern, And, Or]

TRUE: Expression = And(())    # empty And — vacuous truth (ref :111-125)
FALSE: Expression = Or(())    # empty Or (ref :136-154)


def All(*expressions: Expression) -> Expression:
    return And(tuple(expressions))


def Any_(*expressions: Expression) -> Expression:
    return Or(tuple(expressions))
