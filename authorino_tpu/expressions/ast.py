"""Pattern-expression AST + CPU reference evaluator.

Semantics mirrored from the reference's pkg/jsonexp
(ref: pkg/jsonexp/expressions.go:53-178):

  - ``Pattern{selector, operator, value}`` with operators
    eq / neq / incl / excl / matches
  - eq/neq compare the gjson-String() rendering of the resolved value
  - incl/excl walk Result.Array() comparing element String() renderings
  - matches applies an RE2-style regex to the String() rendering
  - ``And`` / ``Or`` trees; ``All()`` / ``Any()`` build n-ary combinators;
    an empty And is vacuously true, an empty Or is false
    (ref: pkg/jsonexp/expressions.go:111-125, 136-154)

This CPU evaluator is the correctness oracle for the TPU kernel
(differential-tested in tests/test_compiler_differential.py).  In the
reference the ``matches`` operator recompiles its regex on every call
(ref: pkg/jsonexp/expressions.go:87); here patterns precompile once.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from enum import Enum
from typing import Any, Callable, Optional, Tuple, Union

from ..authjson import selector
from ..authjson.selector import WALK_MISS as _MISS
from ..authjson.selector import compile_walk as _compile_walk
from ..authjson.selector import render_value as _render
from ..relations.closure import RelationClosure

__all__ = [
    "Operator", "Pattern", "And", "Or", "All", "Any_", "Expression",
    "InGroup", "PatternError", "TRUE", "FALSE",
    "parse_int_value", "parse_int_const", "INT32_MIN", "INT32_MAX",
]


class PatternError(Exception):
    """Evaluation error (e.g. invalid regex) — propagates as a deny in the
    authorization phase, like the reference's error return."""


class Operator(str, Enum):
    EQ = "eq"
    NEQ = "neq"
    INCL = "incl"
    EXCL = "excl"
    MATCHES = "matches"
    # numeric comparators (ISSUE 14): integer comparison of the rendered
    # value against a compile-time integer constant — see the numeric
    # semantics note on Pattern below
    GT = "gt"
    GE = "ge"
    LT = "lt"
    LE = "le"

    @classmethod
    def from_string(cls, s: str) -> "Operator":
        try:
            return cls(s)
        except ValueError:
            raise PatternError(f"unsupported operator for json authorization: {s!r}")


NUMERIC_OPERATORS = (Operator.GT, Operator.GE, Operator.LT, Operator.LE)

# the numeric lane is int32-bounded end to end: constants must fold inside
# this range at compile time, and rendered values outside it read as
# non-numeric (False) — "bounded arithmetic" in the Cedar sense, so the
# kernel's int32 compare is exact by construction
INT32_MIN = -(1 << 31)
INT32_MAX = (1 << 31) - 1

_INT_VALUE = re.compile(r"^-?[0-9]+$")
# bounded compile-time arithmetic over the constant: `a`, `a+b`, `a-b`,
# `a*b` of integer literals (whitespace tolerated) — folded once at
# construction, int32-range-checked
_INT_CONST = re.compile(
    r"^\s*(-?[0-9]+)\s*(?:([+*-])\s*(-?[0-9]+)\s*)?$")


def parse_int_value(s: str) -> Optional[int]:
    """The SHARED runtime parse of a rendered value for gt/ge/lt/le: a
    plain base-10 integer, SATURATED to int32 — or None (→ the comparison
    is False).  Host oracle, Python encoder, and native encoder gate must
    all call exactly this.

    Saturation (not rejection) keeps huge integers order-exact: constants
    are bounded STRICTLY inside int32 (parse_int_const), so a value past
    either end compares against every constant exactly as its true
    magnitude would — the invariant the rego_lower int fragment's
    interpreter-equivalence proof relies on."""
    if not _INT_VALUE.match(s):
        return None
    v = int(s)
    if v < INT32_MIN:
        return INT32_MIN
    if v > INT32_MAX:
        return INT32_MAX
    return v


def parse_int_const(s: str) -> int:
    """Fold one numeric-operator constant at compile time: an integer
    literal or one `a+b` / `a-b` / `a*b` of literals, required to land
    STRICTLY inside int32 (the two extreme values are excluded so value
    saturation stays order-exact — see parse_int_value).  Raises
    ValueError otherwise (the pattern then behaves like an invalid regex:
    evaluation error ⇒ deny)."""
    m = _INT_CONST.match(s)
    if not m:
        raise ValueError(
            f"numeric operator constant {s!r} is not an integer "
            "(or a +,-,* of two integers)")
    a = int(m.group(1))
    if m.group(2) is not None:
        b = int(m.group(3))
        a = a + b if m.group(2) == "+" else \
            a - b if m.group(2) == "-" else a * b
    if a <= INT32_MIN or a >= INT32_MAX:
        raise ValueError(
            f"numeric operator constant {s!r} folds to {a}, outside the "
            f"open int32 bound ({INT32_MIN}, {INT32_MAX})")
    return a


def _compile_pattern(pat: "Pattern") -> Callable[[Any], bool]:
    """Close the selector walk, operator dispatch, and value rendering over
    one function — resolved once at construction instead of per call (the
    reference re-parses its gjson selector and recompiles its regex on every
    Matches, ref: pkg/jsonexp/expressions.go:61,87)."""
    op = pat.operator
    want = pat.value
    walk = _compile_walk(pat.selector)
    if op in NUMERIC_OPERATORS:
        # int32 comparison of the rendered value against the folded
        # constant; non-integer (or out-of-range) values compare False for
        # ALL four operators (so ge is deliberately NOT ¬lt), and an
        # unfoldable constant errors like an invalid regex (⇒ deny)
        const = getattr(pat, "_num_const", None)
        err = getattr(pat, "_num_error", "invalid numeric constant")
        cmp_fn = {
            Operator.GT: lambda v, c: v > c,
            Operator.GE: lambda v, c: v >= c,
            Operator.LT: lambda v, c: v < c,
            Operator.LE: lambda v, c: v <= c,
        }[op]
        if walk is None:
            sel_get = selector.get
            path = pat.selector

            def run_num_slow(doc, _c=const, _f=cmp_fn, _e=err):
                if _c is None:
                    raise PatternError(_e)
                v = parse_int_value(sel_get(doc, path).string())
                return v is not None and _f(v, _c)

            return run_num_slow

        def run_num(doc, _walk=walk, _c=const, _f=cmp_fn, _e=err):
            if _c is None:
                raise PatternError(_e)
            v = parse_int_value(_render(_walk(doc)))
            return v is not None and _f(v, _c)

        return run_num
    if walk is None:
        sel_get = selector.get
        path = pat.selector
        if op is Operator.EQ:
            return lambda doc: want == sel_get(doc, path).string()
        if op is Operator.NEQ:
            return lambda doc: want != sel_get(doc, path).string()
        if op is Operator.INCL:
            return lambda doc: any(
                want == item.string() for item in sel_get(doc, path).array())
        if op is Operator.EXCL:
            return lambda doc: all(
                want != item.string() for item in sel_get(doc, path).array())
        rx = pat._regex  # MATCHES

        def run_rx_slow(doc, _rx=rx, _err=getattr(pat, "_regex_error", "invalid regex")):
            if _rx is None:
                raise PatternError(_err)
            return _rx.search(sel_get(doc, path).string()) is not None

        return run_rx_slow

    if op is Operator.EQ:
        return lambda doc: want == _render(walk(doc))
    if op is Operator.NEQ:
        return lambda doc: want != _render(walk(doc))
    if op is Operator.INCL:
        # gjson array(): list → elements; missing/None → []; scalar → [self]
        def run_incl(doc, _walk=walk, _want=want):
            v = _walk(doc)
            if type(v) is list:
                return any(_want == _render(e) for e in v)
            if v is _MISS or v is None:
                return False
            return _want == _render(v)

        return run_incl
    if op is Operator.EXCL:
        def run_excl(doc, _walk=walk, _want=want):
            v = _walk(doc)
            if type(v) is list:
                return all(_want != _render(e) for e in v)
            if v is _MISS or v is None:
                return True
            return _want != _render(v)

        return run_excl
    rx = pat._regex  # MATCHES

    def run_rx(doc, _walk=walk, _rx=rx, _err=getattr(pat, "_regex_error", "invalid regex")):
        if _rx is None:
            raise PatternError(_err)
        return _rx.search(_render(_walk(doc))) is not None

    return run_rx


@dataclass(frozen=True)
class Pattern:
    selector: str
    operator: Operator
    value: str

    def __post_init__(self):
        # coerce plain-string operators ("eq" == Operator.EQ under str-Enum
        # equality, but dispatch below uses identity) and validate early
        if not isinstance(self.operator, Operator):
            object.__setattr__(self, "operator", Operator.from_string(str(self.operator)))
        if self.operator is Operator.MATCHES:
            try:
                object.__setattr__(self, "_regex", re.compile(self.value))
            except re.error as e:
                object.__setattr__(self, "_regex", None)
                object.__setattr__(self, "_regex_error", str(e))
        else:
            object.__setattr__(self, "_regex", None)
        if self.operator in NUMERIC_OPERATORS:
            try:
                object.__setattr__(self, "_num_const",
                                   parse_int_const(self.value))
            except ValueError as e:
                # like an invalid regex: evaluation raises ⇒ deny, and the
                # compiler routes the whole tree to the CPU oracle
                object.__setattr__(self, "_num_const", None)
                object.__setattr__(self, "_num_error", str(e))
        # shadow the class method with the compiled closure (instance
        # attribute wins on lookup — one call layer, zero per-call dispatch)
        object.__setattr__(self, "matches", _compile_pattern(self))

    def matches(self, doc: Any) -> bool:  # overridden per-instance in __post_init__
        raise AssertionError("unreachable: compiled in __post_init__")

    def __str__(self):
        return f"{self.selector} {self.operator.value} {self.value}"


@dataclass(frozen=True)
class And:
    children: Tuple["Expression", ...] = ()

    def __post_init__(self):
        fns = tuple(c.matches for c in self.children)
        if len(fns) == 1:
            run = fns[0]
        else:
            def run(doc, _fns=fns):
                return all(f(doc) for f in _fns)
        object.__setattr__(self, "matches", run)

    def matches(self, doc: Any) -> bool:  # overridden per-instance
        return all(c.matches(doc) for c in self.children)

    def __str__(self):
        return "(" + " && ".join(str(c) for c in self.children) + ")"


@dataclass(frozen=True)
class Or:
    children: Tuple["Expression", ...] = ()

    def __post_init__(self):
        fns = tuple(c.matches for c in self.children)
        if len(fns) == 1:
            run = fns[0]
        else:
            def run(doc, _fns=fns):
                return any(f(doc) for f in _fns)
        object.__setattr__(self, "matches", run)

    def matches(self, doc: Any) -> bool:  # overridden per-instance
        return any(c.matches(doc) for c in self.children)

    def __str__(self):
        return "(" + " || ".join(str(c) for c in self.children) + ")"


@dataclass(frozen=True)
class InGroup:
    """Hierarchical entity/group membership leaf (ISSUE 14, Cedar-style):
    true iff the rendered value of ``selector`` is a member of ``group``
    under the transitive ancestor closure of ``relation`` (entity→group
    edges declared in the AuthConfig spec and closed at reconcile time —
    relations/closure.py).  The compiler lowers this to an OP_RELATION
    bitmask-gather leaf over the per-snapshot relation table; this host
    evaluator is the exactness oracle for that lowering.

    An unknown entity is in no groups; a group never declared as an edge
    parent contains nothing — both sides are constant False, never an
    error."""

    selector: str
    group: str
    relation: RelationClosure

    def __post_init__(self):
        walk = _compile_walk(self.selector)
        rel = self.relation
        group = self.group
        if walk is None:
            sel_get = selector.get
            path = self.selector

            def run(doc, _rel=rel, _g=group):
                return _rel.contains(sel_get(doc, path).string(), _g)
        else:

            def run(doc, _walk=walk, _rel=rel, _g=group):
                return _rel.contains(_render(_walk(doc)), _g)

        object.__setattr__(self, "matches", run)

    def matches(self, doc: Any) -> bool:  # overridden per-instance
        raise AssertionError("unreachable: compiled in __post_init__")

    def __str__(self):
        return (f"{self.selector} ingroup {self.group}"
                f"@{self.relation.digest[:8]}")


Expression = Union[Pattern, And, Or, InGroup]

TRUE: Expression = And(())    # empty And — vacuous truth (ref :111-125)
FALSE: Expression = Or(())    # empty Or (ref :136-154)


def All(*expressions: Expression) -> Expression:
    return And(tuple(expressions))


def Any_(*expressions: Expression) -> Expression:
    return Or(tuple(expressions))
