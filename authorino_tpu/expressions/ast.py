"""Pattern-expression AST + CPU reference evaluator.

Semantics mirrored from the reference's pkg/jsonexp
(ref: pkg/jsonexp/expressions.go:53-178):

  - ``Pattern{selector, operator, value}`` with operators
    eq / neq / incl / excl / matches
  - eq/neq compare the gjson-String() rendering of the resolved value
  - incl/excl walk Result.Array() comparing element String() renderings
  - matches applies an RE2-style regex to the String() rendering
  - ``And`` / ``Or`` trees; ``All()`` / ``Any()`` build n-ary combinators;
    an empty And is vacuously true, an empty Or is false
    (ref: pkg/jsonexp/expressions.go:111-125, 136-154)

This CPU evaluator is the correctness oracle for the TPU kernel
(differential-tested in tests/test_compiler_differential.py).  In the
reference the ``matches`` operator recompiles its regex on every call
(ref: pkg/jsonexp/expressions.go:87); here patterns precompile once.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from enum import Enum
from typing import Any, Tuple, Union

from ..authjson import selector

__all__ = [
    "Operator", "Pattern", "And", "Or", "All", "Any_", "Expression",
    "PatternError", "TRUE", "FALSE",
]


class PatternError(Exception):
    """Evaluation error (e.g. invalid regex) — propagates as a deny in the
    authorization phase, like the reference's error return."""


class Operator(str, Enum):
    EQ = "eq"
    NEQ = "neq"
    INCL = "incl"
    EXCL = "excl"
    MATCHES = "matches"

    @classmethod
    def from_string(cls, s: str) -> "Operator":
        try:
            return cls(s)
        except ValueError:
            raise PatternError(f"unsupported operator for json authorization: {s!r}")


@dataclass(frozen=True)
class Pattern:
    selector: str
    operator: Operator
    value: str

    def __post_init__(self):
        # coerce plain-string operators ("eq" == Operator.EQ under str-Enum
        # equality, but dispatch below uses identity) and validate early
        if not isinstance(self.operator, Operator):
            object.__setattr__(self, "operator", Operator.from_string(str(self.operator)))
        if self.operator is Operator.MATCHES:
            try:
                object.__setattr__(self, "_regex", re.compile(self.value))
            except re.error as e:
                object.__setattr__(self, "_regex", None)
                object.__setattr__(self, "_regex_error", str(e))
        else:
            object.__setattr__(self, "_regex", None)

    def matches(self, doc: Any) -> bool:
        obtained = selector.get(doc, self.selector)
        op = self.operator
        if op is Operator.EQ:
            return self.value == obtained.string()
        if op is Operator.NEQ:
            return self.value != obtained.string()
        if op is Operator.INCL:
            return any(self.value == item.string() for item in obtained.array())
        if op is Operator.EXCL:
            return all(self.value != item.string() for item in obtained.array())
        if op is Operator.MATCHES:
            rx = getattr(self, "_regex", None)
            if rx is None:
                raise PatternError(getattr(self, "_regex_error", "invalid regex"))
            return rx.search(obtained.string()) is not None
        raise PatternError("unsupported operator for json authorization")

    def __str__(self):
        return f"{self.selector} {self.operator.value} {self.value}"


@dataclass(frozen=True)
class And:
    children: Tuple["Expression", ...] = ()

    def matches(self, doc: Any) -> bool:
        return all(c.matches(doc) for c in self.children)

    def __str__(self):
        return "(" + " && ".join(str(c) for c in self.children) + ")"


@dataclass(frozen=True)
class Or:
    children: Tuple["Expression", ...] = ()

    def matches(self, doc: Any) -> bool:
        return any(c.matches(doc) for c in self.children)

    def __str__(self):
        return "(" + " || ".join(str(c) for c in self.children) + ")"


Expression = Union[Pattern, And, Or]

TRUE: Expression = And(())    # empty And — vacuous truth (ref :111-125)
FALSE: Expression = Or(())    # empty Or (ref :136-154)


def All(*expressions: Expression) -> Expression:
    return And(tuple(expressions))


def Any_(*expressions: Expression) -> Expression:
    return Or(tuple(expressions))
