"""Local end-to-end harness: boots a fake IdP + the real CLI server with
generated manifests, then asserts an expected-HTTP-status table — the
standalone analog of the reference's kind-cluster e2e
(ref: tests/e2e-test.sh:203-274 expected-status tables over the talker-api).

Run:  python tests/e2e/harness.py            (CPU platform forced)
Exit code 0 = all assertions passed.
"""

from __future__ import annotations

import asyncio
import base64
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".."))

IDP_PORT = 9143
HTTP_PORT = 5091
OIDC_PORT = 8183
GRPC_PORT = 50191


def make_idp_app(key):
    from aiohttp import web

    from authorino_tpu.utils import jose

    issuer = f"http://127.0.0.1:{IDP_PORT}"
    app = web.Application()

    async def wk(request):
        return web.json_response(
            {"issuer": issuer, "jwks_uri": f"{issuer}/jwks", "userinfo_endpoint": f"{issuer}/userinfo"}
        )

    async def jwks(request):
        return web.json_response({"keys": [jose.jwk_from_public_key(key.public_key(), kid="k1")]})

    async def userinfo(request):
        return web.json_response({"sub": "john", "email": "john@acme.com"})

    app.router.add_get("/.well-known/openid-configuration", wk)
    app.router.add_get("/jwks", jwks)
    app.router.add_get("/userinfo", userinfo)
    return app


def write_manifests(tmpdir: str, wb_pem: bytes, api_key: bytes = b"friend-secret-1",
                    evil_org: str = "evil"):
    import yaml

    api_secret = {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {
            "name": "friend-key",
            "namespace": "e2e",
            "labels": {"audience": "talker-api", "authorino.kuadrant.io/managed-by": "authorino"},
        },
        "data": {"api_key": base64.b64encode(api_key).decode()},
    }
    wb_secret = {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": {"name": "wristband-signing-key", "namespace": "e2e"},
        "data": {"key.pem": base64.b64encode(wb_pem).decode()},
    }
    authconfig = {
        "apiVersion": "authorino.kuadrant.io/v1beta2",
        "kind": "AuthConfig",
        "metadata": {"name": "talker-api-protection", "namespace": "e2e"},
        "spec": {
            "hosts": ["talker-api.example.com"],
            "patterns": {
                "api-path": [{"selector": "request.url_path", "operator": "matches", "value": "^/api"}]
            },
            "when": [{"selector": "request.method", "operator": "neq", "value": "OPTIONS"}],
            "authentication": {
                "friends": {
                    "apiKey": {"selector": {"matchLabels": {"audience": "talker-api"}}},
                    "credentials": {"authorizationHeader": {"prefix": "APIKEY"}},
                },
                "keycloak": {"jwt": {"issuerUrl": f"http://127.0.0.1:{IDP_PORT}"}},
            },
            "metadata": {"userinfo": {"userInfo": {"identitySource": "keycloak"}}},
            "authorization": {
                "deny-evil-org": {
                    "patternMatching": {
                        "patterns": [{"selector": "request.headers.x-org", "operator": "neq", "value": evil_org}]
                    }
                },
                "admins-can-delete": {
                    "opa": {
                        "rego": 'allow { input.request.method != "DELETE" }\n'
                                'allow { input.auth.identity.realm_access.roles[_] == "admin" }'
                    }
                },
                "api-paths-only-for-jwt": {
                    "patternMatching": {
                        "patterns": [{"selector": "auth.identity.iss", "operator": "neq", "value": ""}]
                    },
                    "when": [{"patternRef": "api-path"}],
                },
            },
            "response": {
                "unauthorized": {
                    "code": 302,
                    "message": {"value": "redirecting"},
                    "headers": {"Location": {"selector": "https://login.example.com?from={request.path}"}},
                },
                "success": {
                    "headers": {
                        "wristband": {
                            "wristband": {
                                "issuer": f"http://127.0.0.1:{OIDC_PORT}/e2e/talker-api-protection/wristband",
                                "tokenDuration": 300,
                                "signingKeyRefs": [{"name": "wristband-signing-key", "algorithm": "ES256"}],
                            }
                        },
                        "x-auth-data": {
                            "json": {"properties": {"method": {"selector": "request.method"}}}
                        },
                    }
                },
            },
        },
    }
    path = os.path.join(tmpdir, "manifests.yaml")
    # atomic replace: the dir watcher polls (mtime, size) every 2s and must
    # never observe a truncated mid-write file
    with open(path + ".tmp", "w") as f:
        yaml.dump_all([api_secret, wb_secret, authconfig], f)
    os.replace(path + ".tmp", path)
    return os.path.dirname(path)


async def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    import tempfile

    import aiohttp
    from aiohttp import web
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ec, rsa

    from authorino_tpu.utils import jose

    idp_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    wb_key = ec.generate_private_key(ec.SECP256R1())
    wb_pem = wb_key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )

    # fake IdP
    idp_runner = web.AppRunner(make_idp_app(idp_key))
    await idp_runner.setup()
    await web.TCPSite(idp_runner, "127.0.0.1", IDP_PORT).start()

    tmpdir = tempfile.mkdtemp(prefix="authorino-tpu-e2e-")
    manifest_dir = write_manifests(tmpdir, wb_pem)

    # the real server, in-process (same code path as `authorino-tpu server`)
    from authorino_tpu.cli import build_parser, run_server

    args = build_parser().parse_args(
        [
            "server",
            "--watch-dir", manifest_dir,
            "--ext-auth-http-port", str(HTTP_PORT),
            "--ext-auth-grpc-port", str(GRPC_PORT),
            "--oidc-http-port", str(OIDC_PORT),
        ]
    )
    server_task = asyncio.ensure_future(run_server(args))
    base = f"http://127.0.0.1:{HTTP_PORT}"

    def jwt(claims=None):
        iat = int(time.time())
        payload = {"iss": f"http://127.0.0.1:{IDP_PORT}", "sub": "john", "iat": iat, "exp": iat + 300}
        payload.update(claims or {})
        return jose.sign_jwt(payload, idp_key, "RS256", kid="k1")

    H = "talker-api.example.com"
    admin_jwt = jwt({"realm_access": {"roles": ["admin"]}})
    user_jwt = jwt()
    expired_jwt = jwt({"exp": 10})

    # expected-status table (ref: tests/e2e-test.sh:203-274 style)
    TABLE = [
        # (desc, method, path, headers, expected_status)
        ("anonymous denied (401)", "GET", "/hello", {}, 401),
        ("valid api key", "GET", "/hello", {"Authorization": "APIKEY friend-secret-1"}, 200),
        ("invalid api key", "GET", "/hello", {"Authorization": "APIKEY nope"}, 401),
        ("valid jwt", "GET", "/hello", {"Authorization": f"Bearer {user_jwt}"}, 200),
        ("expired jwt", "GET", "/hello", {"Authorization": f"Bearer {expired_jwt}"}, 401),
        ("OPTIONS skipped by top-level when", "OPTIONS", "/hello", {}, 200),
        ("evil org denied with redirect", "GET", "/hello",
         {"Authorization": "APIKEY friend-secret-1", "X-Org": "evil"}, 302),
        ("api key cannot DELETE", "DELETE", "/hello", {"Authorization": "APIKEY friend-secret-1"}, 302),
        ("admin jwt can DELETE", "DELETE", "/hello", {"Authorization": f"Bearer {admin_jwt}"}, 200),
        ("api path requires jwt identity", "GET", "/api/x",
         {"Authorization": "APIKEY friend-secret-1"}, 302),
        ("api path with jwt ok", "GET", "/api/x", {"Authorization": f"Bearer {user_jwt}"}, 200),
        ("unknown host 404", "GET", "/hello", {"__host": "nope.example.com"}, 404),
    ]

    # wait for readiness
    async with aiohttp.ClientSession() as sess:
        for _ in range(60):
            try:
                async with sess.get(f"{base}/readyz") as r:
                    if r.status == 200:
                        break
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.5)
        else:
            print("FAIL: server never became ready")
            return 1

        failures = 0
        for desc, method, path, headers, expected in TABLE:
            host = headers.pop("__host", H)
            req_headers = {"Host": host, **headers}
            async with sess.request(
                method, f"{base}{path}", headers=req_headers, allow_redirects=False
            ) as r:
                status = r.status
                mark = "PASS" if status == expected else "FAIL"
                if status != expected:
                    failures += 1
                print(f"[{mark}] {desc}: {method} {path} → {status} (want {expected})")

        # wristband token verifies against the served JWKS
        async with sess.get(
            f"{base}/check", headers={"Host": H, "Authorization": "APIKEY friend-secret-1"}
        ) as r:
            wb_token = r.headers.get("wristband", "")
        async with sess.get(
            f"http://127.0.0.1:{OIDC_PORT}/e2e/talker-api-protection/wristband/.well-known/openid-connect/certs"
        ) as r:
            jwks = (await r.json())["keys"]
        try:
            claims = jose.verify_jws(wb_token, jwks)
            assert claims["exp"] - claims["iat"] == 300
            print("[PASS] wristband verifies against served JWKS")
        except Exception as e:
            failures += 1
            print(f"[FAIL] wristband verification: {e}")

        # ---- live rotation (ref tests/e2e-test.sh: API-key revocation +
        # AuthConfig update): rewrite the manifests — the dir watcher must
        # revoke the old key, trust the new one, and recompile the rule
        # corpus (atomic device swap) with the flipped org rule
        write_manifests(tmpdir, wb_pem, api_key=b"friend-secret-2", evil_org="rogue")

        async def status_of(headers):
            req_headers = {"Host": H, **headers}
            async with sess.get(f"{base}/hello", headers=req_headers,
                                allow_redirects=False) as r:
                return r.status

        rotated = False
        for _ in range(20):  # poll interval is 2s; allow for reconcile lag
            await asyncio.sleep(1.0)
            old_k = await status_of({"Authorization": "APIKEY friend-secret-1"})
            new_k = await status_of({"Authorization": "APIKEY friend-secret-2"})
            if old_k == 401 and new_k == 200:
                rotated = True
                break
        if rotated:
            print("[PASS] live API-key rotation: old key revoked, new key trusted")
        else:
            failures += 1
            print(f"[FAIL] live API-key rotation (old={old_k}, new={new_k})")

        # secret rotation lands before the async corpus recompile (the
        # snapshot swap runs in a thread after the secret events) — poll.
        # Gated on the rotation having landed: polling with a never-trusted
        # key would cascade the same watcher failure under a second name.
        recompiled = False
        for _ in range(20 if rotated else 0):
            evil_now = await status_of({"Authorization": "APIKEY friend-secret-2", "X-Org": "evil"})
            rogue_now = await status_of({"Authorization": "APIKEY friend-secret-2", "X-Org": "rogue"})
            if (evil_now, rogue_now) == (200, 302):
                recompiled = True
                break
            await asyncio.sleep(1.0)
        if recompiled:
            print("[PASS] live corpus recompile: org rule flipped (evil allowed, rogue denied)")
        elif not rotated:
            failures += 1
            print("[FAIL] live corpus recompile: skipped (rotation never landed)")
        else:
            failures += 1
            print(f"[FAIL] live corpus recompile (evil={evil_now}, rogue={rogue_now})")

    # ---- gRPC ext_authz listener (the native C++ frontend when available,
    # grpc.aio otherwise — same assertions either way)
    def grpc_checks():
        import grpc

        from authorino_tpu import protos

        pb = protos.external_auth_pb2
        key = b"friend-secret-2" if rotated else b"friend-secret-1"

        def req(host, auth=None):
            r = pb.CheckRequest()
            http = r.attributes.request.http
            http.method = "GET"
            http.path = "/hello"
            http.host = host
            if auth:
                http.headers["authorization"] = auth
            return r

        out = []
        with grpc.insecure_channel(f"127.0.0.1:{GRPC_PORT}") as ch:
            call = ch.unary_unary(
                "/envoy.service.auth.v3.Authorization/Check",
                request_serializer=pb.CheckRequest.SerializeToString,
                response_deserializer=pb.CheckResponse.FromString,
            )
            ok = call(req(H, f"APIKEY {key.decode()}"), timeout=10)
            out.append(("grpc Check allow", ok.status.code, 0))
            deny = call(req(H, "APIKEY wrong"), timeout=10)
            out.append(("grpc Check deny", deny.status.code, 16))
            # OIDC through the wire: first sight verifies in the slow lane
            # (and registers the token in the verified-token cache when the
            # native frontend serves), the repeat must answer identically
            j1 = call(req(H, f"Bearer {admin_jwt}"), timeout=10)
            out.append(("grpc Check jwt allow (verify)", j1.status.code, 0))
            j2 = call(req(H, f"Bearer {admin_jwt}"), timeout=10)
            out.append(("grpc Check jwt allow (repeat)", j2.status.code, 0))
            nf = call(req("nope.example.com"), timeout=10)
            out.append(("grpc Check unknown host", nf.denied_response.status.code, 404))
            health = ch.unary_unary(
                "/grpc.health.v1.Health/Check",
                request_serializer=protos.health_pb2.HealthCheckRequest.SerializeToString,
                response_deserializer=protos.health_pb2.HealthCheckResponse.FromString,
            )(protos.health_pb2.HealthCheckRequest(), timeout=10)
            out.append(("grpc health SERVING", health.status, 1))
        return out

    try:
        for desc, got, want in await asyncio.to_thread(grpc_checks):
            mark = "PASS" if got == want else "FAIL"
            if got != want:
                failures += 1
            print(f"[{mark}] {desc}: {got} (want {want})")
    except Exception as e:
        failures += 6
        print(f"[FAIL] grpc listener checks: {e}")

    server_task.cancel()
    try:
        await server_task
    except (asyncio.CancelledError, Exception):
        pass
    await idp_runner.cleanup()
    from authorino_tpu.utils.http import close_sessions

    await close_sessions()
    n_assertions = len(TABLE) + 3 + 6  # + wristband + rotation + recompile + grpc
    print(f"\n{'OK' if failures == 0 else 'FAILED'}: {n_assertions - failures}/{n_assertions} assertions passed")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
