"""Selector / value / template engine tests — semantics pinned to the
reference's gjson usage (ref: pkg/json/json_test.go, pkg/jsonexp)."""

import base64

import pytest

from authorino_tpu.authjson import (
    JSONValue,
    Result,
    build_authorization_json,
    CheckRequestModel,
    HttpRequestAttributes,
    get,
    is_template,
    replace_placeholders,
    stringify_json,
)

DOC = {
    "auth": {
        "identity": {
            "username": "john",
            "sub": "abc-123",
            "roles": ["admin", "dev"],
            "age": 42,
            "ratio": 0.5,
            "active": True,
            "nothing": None,
            "nested": {"deep.key": "v"},
        },
        "metadata": {
            "resources": [
                {"uri": "/a", "owner": "john", "n": 1},
                {"uri": "/b", "owner": "jane", "n": 2},
                {"uri": "/c", "owner": "john", "n": 3},
            ]
        },
    },
    "request": {
        "http": {
            "headers": {"authorization": "Bearer tok-xyz", "x-tag": "One Two Three"},
            "path": "/hello",
        }
    },
}


class TestSelector:
    def test_simple_paths(self):
        assert get(DOC, "auth.identity.username").string() == "john"
        assert get(DOC, "request.http.path").py() == "/hello"

    def test_string_rendering(self):
        # gjson Result.String(): numbers minimal, bools lowercase, null -> ""
        assert get(DOC, "auth.identity.age").string() == "42"
        assert get(DOC, "auth.identity.ratio").string() == "0.5"
        assert get(DOC, "auth.identity.active").string() == "true"
        assert get(DOC, "auth.identity.nothing").string() == ""
        assert get(DOC, "auth.identity.missing").string() == ""
        assert get(DOC, "auth.identity.roles").string() == '["admin","dev"]'

    def test_array_index_and_length(self):
        assert get(DOC, "auth.identity.roles.0").string() == "admin"
        assert get(DOC, "auth.identity.roles.1").string() == "dev"
        assert get(DOC, "auth.identity.roles.#").py() == 2
        assert not get(DOC, "auth.identity.roles.5").exists

    def test_pipe_after_hash_applies_to_collected_array(self):
        # gjson array-vs-pipe: a.#.b|0 indexes the mapped ARRAY; a.#.b.0
        # keeps mapping per element (strings aren't indexable → omitted)
        doc = {"friends": [{"first": "Dale"}, {"first": "Roger"}, {"first": "Jane"}]}
        assert get(doc, "friends.#.first|0").py() == "Dale"
        assert get(doc, "friends.#.first|#").py() == 3
        assert get(doc, "friends.#.first.0").py() == []
        # plain paths: | and . identical
        assert get(doc, "friends|0|first").py() == "Dale"
        assert get(doc, "friends.0.first").py() == "Dale"

    def test_hash_mapping(self):
        assert get(DOC, "auth.metadata.resources.#.uri").py() == ["/a", "/b", "/c"]

    def test_escaped_dot(self):
        assert get(DOC, "auth.identity.nested.deep\\.key").string() == "v"

    def test_query_first_and_all(self):
        assert get(DOC, 'auth.metadata.resources.#(owner=="john").uri').py() == "/a"
        assert get(DOC, 'auth.metadata.resources.#(owner=="john")#.uri').py() == ["/a", "/c"]
        assert get(DOC, "auth.metadata.resources.#(n>1)#.uri").py() == ["/b", "/c"]
        assert not get(DOC, 'auth.metadata.resources.#(owner=="nobody")').exists

    def test_array_semantics_of_scalars(self):
        # gjson Result.Array(): scalar -> [itself], null/missing -> []
        assert [r.string() for r in get(DOC, "auth.identity.username").array()] == ["john"]
        assert get(DOC, "auth.identity.nothing").array() == []
        assert get(DOC, "auth.identity.missing").array() == []


class TestMultipaths:
    """gjson multipath composition: {obj} and [arr] construction
    (gjson path syntax; closes the documented selector-engine gap)."""

    def test_object_multipath_default_keys(self):
        r = get(DOC, "{auth.identity.username,request.http.path}")
        assert r.py() == {"username": "john", "path": "/hello"}

    def test_object_multipath_named_keys(self):
        r = get(DOC, '{"user":auth.identity.username,"p":request.http.path}')
        assert r.py() == {"user": "john", "p": "/hello"}

    def test_array_multipath(self):
        r = get(DOC, "[auth.identity.username,request.http.path]")
        assert r.py() == ["john", "/hello"]

    def test_missing_members_omitted(self):
        assert get(DOC, "{auth.identity.username,auth.nope}").py() == {"username": "john"}
        assert get(DOC, "[auth.nope,request.http.path]").py() == ["/hello"]

    def test_nested_multipath(self):
        r = get(DOC, '{"who":{auth.identity.username},"hdr":[request.http.headers.x-tag]}')
        assert r.py() == {"who": {"username": "john"}, "hdr": ["One Two Three"]}

    def test_multipath_member_with_modifier_arg(self):
        # a ':' inside a modifier argument must NOT read as a member key
        r = get(DOC, "{auth.identity.username|@case:upper}")
        assert r.py() == {"username": "JOHN"}

    def test_multipath_piped_into_modifier(self):
        r = get(DOC, "{auth.identity.username,request.http.path}|@values")
        assert sorted(r.py()) == ["/hello", "john"]
        assert get(DOC, "[auth.identity.username,request.http.path].1").py() == "/hello"

    def test_object_multipath_shadowed_by_templates_in_jsonvalue(self):
        # parity nuance shared with the reference: JSONValue treats any
        # {...} as a template placeholder (ref pkg/json/json.go:59
        # IsTemplate), so OBJECT multipaths only apply at the raw selector
        # level (pattern expressions); ARRAY multipaths work everywhere
        from authorino_tpu.authjson import JSONValue

        assert JSONValue(pattern="[auth.identity.username]").resolve_for(DOC) == ["john"]
        v = JSONValue(pattern="{auth.identity.username}")
        assert v.resolve_for(DOC) != {"username": "john"}  # template path wins

    def test_multipath_with_query_member(self):
        doc = {"items": [{"n": "a", "v": 1}, {"n": "b", "v": 2}]}
        # both quoted and unquoted keys, like gjson
        r = get(doc, '{"first_b":items.#(n==b).v,count:items.#}')
        assert r.py() == {"first_b": 2, "count": 2}


class TestModifiers:
    def test_extract(self):
        assert (
            get(DOC, 'request.http.headers.authorization.@extract:{"pos":1}').string()
            == "tok-xyz"
        )
        assert (
            get(DOC, 'request.http.headers.x-tag.@extract:{"sep":" ","pos":2}').string()
            == "Three"
        )
        # out-of-range pos → the reference returns raw "n" (pkg/json/json.go:181)
        assert get(DOC, 'request.http.headers.x-tag.@extract:{"pos":9}').string() == "n"

    def test_case(self):
        assert get(DOC, "auth.identity.username.@case:upper").string() == "JOHN"
        assert get(DOC, "request.http.headers.x-tag|@case:lower").string() == "one two three"

    def test_replace(self):
        assert (
            get(DOC, 'request.http.headers.x-tag.@replace:{"old":"Two","new":"2"}').string()
            == "One 2 Three"
        )

    def test_base64(self):
        encoded = base64.b64encode(b"john").decode()
        doc = {"v": encoded}
        assert get(doc, "v.@base64:decode").string() == "john"
        assert get({"v": "john"}, "v.@base64:encode").string() == encoded

    def test_strip(self):
        doc = {"v": "a\x00b\tc"}
        assert get(doc, "v.@strip").string() == "abc"

    def test_builtin_mods(self):
        assert get(DOC, "auth.identity.@keys").py() == [
            "username", "sub", "roles", "age", "ratio", "active", "nothing", "nested",
        ]
        assert get(DOC, "auth.identity.roles.@reverse").py() == ["dev", "admin"]


class TestTemplates:
    def test_is_template(self):
        assert is_template("Hello, {auth.identity.username}!")
        assert not is_template("auth.identity.username")
        # modifier braces alone do not make a template (ref pkg/json/json.go:59)
        assert not is_template('request.http.headers.authorization.@extract:{"pos":1}')

    def test_replace_placeholders(self):
        assert (
            replace_placeholders("Hello, {auth.identity.username}!", DOC) == "Hello, john!"
        )
        assert (
            replace_placeholders(
                'tok={request.http.headers.authorization.@extract:{"pos":1}}', DOC
            )
            == "tok=tok-xyz"
        )
        # \{ escapes a literal brace
        assert replace_placeholders(r"lit\{brace", DOC) == "lit{brace"

    def test_jsonvalue(self):
        assert JSONValue(static=42).resolve_for(DOC) == 42
        assert JSONValue(pattern="auth.identity.username").resolve_for(DOC) == "john"
        assert (
            JSONValue(pattern="u={auth.identity.username}").resolve_for(DOC) == "u=john"
        )

    def test_stringify(self):
        assert stringify_json("plain") == "plain"
        assert stringify_json(42) == "42"
        assert stringify_json({"a": 1}) == '{"a":1}'
        assert stringify_json(None) == ""


class TestWellKnown:
    def test_build(self):
        req = CheckRequestModel(
            http=HttpRequestAttributes(
                method="POST",
                path="/foo?bar=baz",
                host="svc.example.com",
                headers={"user-agent": "curl", "referer": "r"},
            ),
            context_extensions={"host": "override.example.com"},
        )
        doc = build_authorization_json(req, {"identity": {"u": 1}})
        assert doc["request"]["url_path"] == "/foo"
        assert doc["request"]["query"] == "bar=baz"
        assert doc["request"]["user_agent"] == "curl"
        assert doc["context"]["request"]["http"]["path"] == "/foo?bar=baz"
        assert doc["auth"]["identity"] == {"u": 1}
        assert req.host() == "override.example.com"
