"""Batch-aware observability suite (ISSUE 1): device/engine telemetry on
/metrics, span-linked batch tracing through the built-in OTLP/JSON exporter,
the /debug/* introspection surface, and the satellite fixes (stranded OTLP
enqueue, duplicate metric registration, observe_bucketed fallback, C++/Python
stage-bucket parity).

Deliberately import-light: this file must collect on images without
`cryptography` (the evaluators.identity tree), so identity/authorization
evaluators are minimal fakes over evaluators.base."""

from __future__ import annotations

import asyncio
import math
import re
import threading
import time
from pathlib import Path

import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.utils import metrics as metrics_mod
from authorino_tpu.utils import tracing as tracing_mod


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def sample(name, labels=None):
    from prometheus_client import REGISTRY

    v = REGISTRY.get_sample_value(name, labels or {})
    return 0.0 if v is None else v


RULE = All(
    Pattern("request.method", Operator.EQ, "GET"),
    Pattern("auth.identity.org", Operator.EQ, "acme"),
)


def build_engine(**kw) -> PolicyEngine:
    engine = PolicyEngine(max_batch=32, members_k=4,
                          mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id="c", hosts=["c"], runtime=None,
                    rules=ConfigRules(name="c", evaluators=[(None, RULE)]))
    ])
    return engine


def doc(allow=True):
    return {"request": {"method": "GET"},
            "auth": {"identity": {"org": "acme" if allow else "evil"}}}


# ---------------------------------------------------------------------------
# collector: OTLP/JSON sink on a background thread's own loop, so tests can
# exercise both loop-context and loop-less exporter paths against it
# ---------------------------------------------------------------------------

def start_collector():
    from aiohttp import web

    got: list = []
    holder: dict = {}
    started = threading.Event()

    def runner():
        async def main():
            app = web.Application()

            async def v1_traces(request):
                got.append(await request.json())
                return web.json_response({})

            app.router.add_post("/v1/traces", v1_traces)
            r = web.AppRunner(app)
            await r.setup()
            site = web.TCPSite(r, "127.0.0.1", 0)
            await site.start()
            holder["port"] = site._server.sockets[0].getsockname()[1]
            holder["loop"] = asyncio.get_running_loop()
            holder["stop"] = asyncio.Event()
            started.set()
            await holder["stop"].wait()
            await r.cleanup()

        asyncio.run(main())

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    assert started.wait(10)
    holder["thread"] = t
    holder["endpoint"] = f"http://127.0.0.1:{holder['port']}"
    return got, holder


def stop_collector(holder):
    holder["loop"].call_soon_threadsafe(holder["stop"].set)
    holder["thread"].join(timeout=10)


def collected_spans(got):
    out = []
    for payload in got:
        for rs in payload.get("resourceSpans", []):
            for ss in rs.get("scopeSpans", []):
                out.extend(ss.get("spans", []))
    return out


# ---------------------------------------------------------------------------
# tentpole: engine batch telemetry lands on /metrics; /debug/vars answers
# ---------------------------------------------------------------------------

class TestEngineTelemetry:
    def test_batch_histograms_and_debug_vars_via_http(self):
        """Acceptance: requests through the engine surface batch-occupancy /
        device-dispatch histograms on /metrics, drained native-frontend
        counters appear, and /debug/vars returns queue depth + config
        generation — all through the real HTTP endpoints."""
        from aiohttp.test_utils import TestClient, TestServer

        from authorino_tpu.service.http_server import build_app

        engine = build_engine()
        before = {
            "size": sample("auth_server_batch_size_count", {"lane": "engine"}),
            "occ": sample("auth_server_batch_pad_occupancy_count", {"lane": "engine"}),
            "wait": sample("auth_server_batch_queue_wait_seconds_count", {"lane": "engine"}),
            "disp": sample("auth_server_device_dispatch_seconds_count", {"lane": "engine"}),
            "fb": sample("auth_server_batch_host_fallback_count", {"lane": "engine"}),
            "fb_sum": sample("auth_server_batch_host_fallback_sum", {"lane": "engine"}),
        }

        async def body():
            outs = await asyncio.gather(*(engine.submit(doc(), "c")
                                          for _ in range(24)))
            for rule, skipped in outs:
                assert bool(rule[0]) and not bool(skipped[0])

            # drained native-frontend counters: the same drain class the
            # frontend's periodic thread runs, fed a stub fe_stats() here
            # (the C++ library is not buildable on every test image)
            drain = metrics_mod.NativeStatsDrain()
            drain.fold({"fast": 3, "slow": 1, "slow_pending": 2, "slow_queued": 1})
            drain.fold({"fast": 7, "slow": 1, "slow_pending": 5, "slow_queued": 0})

            client = TestClient(TestServer(build_app(engine)))
            await client.start_server()
            try:
                resp = await client.get("/metrics")
                assert resp.status == 200
                text = await resp.text()

                resp = await client.get("/debug/vars")
                assert resp.status == 200
                dv = await resp.json()
            finally:
                await client.close()
            return text, dv

        text, dv = run(body())

        # at least one micro-batch ran: every per-batch series moved
        assert sample("auth_server_batch_size_count", {"lane": "engine"}) > before["size"]
        assert sample("auth_server_batch_pad_occupancy_count", {"lane": "engine"}) > before["occ"]
        assert sample("auth_server_batch_queue_wait_seconds_count", {"lane": "engine"}) > before["wait"]
        assert sample("auth_server_device_dispatch_seconds_count", {"lane": "engine"}) > before["disp"]
        assert sample("auth_server_batch_host_fallback_count", {"lane": "engine"}) > before["fb"]
        # no fallback rows in this corpus: the per-batch counts are all 0
        assert sample("auth_server_batch_host_fallback_sum", {"lane": "engine"}) == before["fb_sum"]
        # occupancy is a ratio ≤ 1.0
        occ_sum = sample("auth_server_batch_pad_occupancy_sum", {"lane": "engine"})
        occ_n = sample("auth_server_batch_pad_occupancy_count", {"lane": "engine"})
        assert 0.0 < occ_sum / occ_n <= 1.0

        # the scrape text carries the new families + the drained native events
        assert 'auth_server_batch_size_bucket{' in text
        assert 'auth_server_device_dispatch_seconds_bucket{' in text
        assert 'auth_server_native_frontend_events_total{event="fast"}' in text
        assert sample("auth_server_native_frontend_events_total", {"event": "fast"}) >= 7.0
        # queue gauges show the LAST folded backlog
        assert sample("auth_server_native_frontend_queue_depth", {"queue": "slow_pending"}) == 5.0
        assert sample("auth_server_native_frontend_queue_depth", {"queue": "slow_queued"}) == 0.0

        # /debug/vars: config generation + queue depth + snapshot shape
        assert dv["engine"]["generation"] >= 1
        assert dv["engine"]["queue_depth"] == 0  # all futures resolved
        assert dv["engine"]["snapshot"]["configs"] == 1
        assert dv["engine"]["snapshot"]["compiled_configs"] == 1
        assert "pid" in dv["process"]

        # snapshot generation gauge followed apply_snapshot
        assert sample("auth_server_snapshot_generation",
                      {"component": "engine"}) >= 1.0

    def test_debug_profile_disabled_by_default(self):
        from aiohttp.test_utils import TestClient, TestServer

        from authorino_tpu.service.http_server import build_app

        engine = build_engine()

        async def body():
            client = TestClient(TestServer(build_app(engine)))
            await client.start_server()
            try:
                resp = await client.get("/debug/profile?seconds=0.1")
                return resp.status
            finally:
                await client.close()

        assert run(body()) == 403


# ---------------------------------------------------------------------------
# tentpole: span-linked batch tracing via the built-in OTLP/JSON exporter
# ---------------------------------------------------------------------------

class TestDeviceBatchSpans:
    def test_device_batch_span_links_request_spans(self):
        got, holder = start_collector()
        try:
            assert tracing_mod.setup_tracing(holder["endpoint"]) is True
            assert tracing_mod._native_exporter is not None
            engine = build_engine()

            async def body():
                spans = [tracing_mod.RequestSpan.from_headers({}, f"rid-{i}")
                         for i in range(6)]
                outs = await asyncio.gather(*(
                    engine.submit(doc(), "c", span=s) for s in spans))
                assert all(bool(r[0]) for r, _ in outs)
                await tracing_mod.shutdown_tracing()  # cancel task + flush
                from authorino_tpu.utils.http import close_sessions

                await close_sessions()
                return spans

            spans = run(body())
            exported = collected_spans(got)
            batches = [s for s in exported if s["name"] == "DeviceBatch"]
            assert batches, f"no DeviceBatch span exported: {exported}"
            links = [l for b in batches for l in b.get("links", [])]
            linked_ids = {l["spanId"] for l in links}
            assert {s.span_id for s in spans} <= linked_ids
            assert {l["traceId"] for l in links} >= {s.trace_id for s in spans}
            attrs = {a["key"]: a["value"] for b in batches
                     for a in b["attributes"]}
            assert "batch.size" in attrs and "batch.pad" in attrs
            assert "batch.eff" in attrs
            total = sum(int(a["value"]["intValue"])
                        for b in batches for a in b["attributes"]
                        if a["key"] == "batch.size")
            assert total == 6
            # pad is the pow2 bucket ≥ size
            for b in batches:
                ba = {a["key"]: int(a["value"]["intValue"])
                      for a in b["attributes"]}
                assert ba["batch.pad"] >= ba["batch.size"]
                assert int(b["endTimeUnixNano"]) >= int(b["startTimeUnixNano"])
        finally:
            tracing_mod._native_exporter = None
            stop_collector(holder)

    def test_phase_child_spans_under_request_span(self):
        from authorino_tpu.authjson import CheckRequestModel, HttpRequestAttributes
        from authorino_tpu.evaluators import (
            AuthorizationConfig, IdentityConfig, RuntimeAuthConfig)
        from authorino_tpu.pipeline import AuthPipeline

        class FakeIdentity:
            async def call(self, pipeline):
                return {"anonymous": True}

        class FakeAuthz:
            async def call(self, pipeline):
                return True

        got, holder = start_collector()
        try:
            assert tracing_mod.setup_tracing(holder["endpoint"]) is True

            async def body():
                cfg = RuntimeAuthConfig(
                    identity=[IdentityConfig("anon", FakeIdentity())],
                    authorization=[AuthorizationConfig("ok", FakeAuthz())],
                )
                req = CheckRequestModel(http=HttpRequestAttributes(
                    method="GET", path="/", host="svc.test"))
                span = tracing_mod.RequestSpan.from_headers({}, "rid-phase")
                pipeline = AuthPipeline(req, cfg, span=span)
                result = await pipeline.evaluate()
                assert result.success()
                span.end()
                await tracing_mod.shutdown_tracing()  # cancel task + flush
                from authorino_tpu.utils.http import close_sessions

                await close_sessions()
                return span

            span = run(body())
            exported = collected_spans(got)
            by_name = {s["name"]: s for s in exported}
            assert "Check" in by_name
            for phase in ("identity", "authorization"):
                assert phase in by_name, f"missing {phase} span: {by_name.keys()}"
                ps = by_name[phase]
                assert ps["traceId"] == span.trace_id
                assert ps["parentSpanId"] == span.span_id
                assert int(ps["endTimeUnixNano"]) >= int(ps["startTimeUnixNano"])
            # empty phases produce no spans
            assert "metadata" not in by_name and "response" not in by_name
        finally:
            tracing_mod._native_exporter = None
            stop_collector(holder)


# ---------------------------------------------------------------------------
# satellite: stranded loop-less enqueue must still export
# ---------------------------------------------------------------------------

class TestLooplessEnqueue:
    def test_spans_enqueued_without_loop_export_via_timer(self):
        got, holder = start_collector()
        try:
            exporter = tracing_mod.NativeOtlpExporter(
                holder["endpoint"], {}, flush_interval_s=0.05)
            # no running loop in this thread: the old code stranded these
            exporter.enqueue({
                "traceId": "ab" * 16, "spanId": "cd" * 8,
                "name": "Stranded", "kind": 1,
                "startTimeUnixNano": "1", "endTimeUnixNano": "2",
                "status": {},
            })
            deadline = time.monotonic() + 10
            while not got and time.monotonic() < deadline:
                time.sleep(0.02)
            spans = collected_spans(got)
            assert [s["name"] for s in spans] == ["Stranded"]
            assert not exporter._queue
        finally:
            stop_collector(holder)


# ---------------------------------------------------------------------------
# satellite: duplicate registration returns the ORIGINAL collector
# ---------------------------------------------------------------------------

class TestDuplicateRegistration:
    def test_counter_reused_on_duplicate(self):
        c1 = metrics_mod._counter("test_obs_dup_counter", "dup test", ())
        assert not isinstance(c1, metrics_mod._NoopMetric)
        c1.inc(2)
        c2 = metrics_mod._counter("test_obs_dup_counter", "dup test", ())
        assert c2 is c1  # NOT a fresh noop: recording must keep working
        c2.inc(3)
        assert sample("test_obs_dup_counter_total") == 5.0

    def test_histogram_and_gauge_reused_on_duplicate(self):
        h1 = metrics_mod._histogram("test_obs_dup_hist", "dup test", (),
                                    buckets=(1.0, 2.0))
        h2 = metrics_mod._histogram("test_obs_dup_hist", "dup test", (),
                                    buckets=(1.0, 2.0))
        assert h2 is h1
        h2.observe(1.5)
        assert sample("test_obs_dup_hist_count") == 1.0
        g1 = metrics_mod._gauge("test_obs_dup_gauge", "dup test", ())
        g2 = metrics_mod._gauge("test_obs_dup_gauge", "dup test", ())
        assert g2 is g1
        g2.set(7)
        assert sample("test_obs_dup_gauge") == 7.0

    def test_module_reload_keeps_series_recording(self):
        import importlib

        before = sample("auth_server_authconfig_total",
                        {"namespace": "obs-ns", "authconfig": "obs-cfg"})
        importlib.reload(metrics_mod)
        # the reloaded module's collectors are the REGISTRY originals
        metrics_mod.authconfig_total.labels("obs-ns", "obs-cfg").inc()
        assert sample("auth_server_authconfig_total",
                      {"namespace": "obs-ns", "authconfig": "obs-cfg"}) == before + 1


# ---------------------------------------------------------------------------
# satellite: observe_bucketed fallback (prometheus internals missing)
# ---------------------------------------------------------------------------

class _FallbackChild:
    """Quacks like a Histogram child WITHOUT `_buckets`/`_sum` — forces the
    per-observe fallback path."""

    def __init__(self, bounds):
        self._upper_bounds = bounds
        self.obs = []

    def observe(self, v):
        self.obs.append(v)


class TestObserveBucketedFallback:
    def test_residual_shift_matches_drained_sum(self):
        bounds = [1.0, 2.0, 4.0, math.inf]
        child = _FallbackChild(bounds)
        counts = [5, 3, 0, 2]
        target_sum = 5 * 0.8 + 3 * 1.7 + 2 * 5.0  # consistent with the shape
        metrics_mod.observe_bucketed(child, counts, target_sum)
        assert len(child.obs) == 10
        assert sum(child.obs) == pytest.approx(target_sum, abs=1e-9)
        # every observe lands in its source bucket
        in_b0 = [v for v in child.obs if v <= 1.0]
        in_b1 = [v for v in child.obs if 1.0 < v <= 2.0]
        in_b3 = [v for v in child.obs if v > 4.0]
        assert (len(in_b0), len(in_b1), len(in_b3)) == (5, 3, 2)

    def test_thinning_above_cap_preserves_shape(self):
        bounds = [1.0, math.inf]
        child = _FallbackChild(bounds)
        counts = [250_000, 50_000]  # 300k total > the 200k fallback cap
        target_sum = 250_000 * 0.5 + 50_000 * 1.5
        metrics_mod.observe_bucketed(child, counts, target_sum)
        total = len(child.obs)
        assert total == pytest.approx(200_000, abs=2)
        lo = sum(1 for v in child.obs if v <= 1.0)
        hi = total - lo
        # proportional thinning: the 5:1 bucket ratio survives
        assert lo / hi == pytest.approx(5.0, rel=0.01)
        # the scaled sum survives the thinning (residual shift is exact
        # whenever the target is consistent with the bucket shape)
        scale = total / 300_000
        assert sum(child.obs) == pytest.approx(target_sum * scale, rel=1e-6)

    def test_zero_total_is_a_noop(self):
        child = _FallbackChild([1.0, math.inf])
        metrics_mod.observe_bucketed(child, [0, 0], 0.0)
        assert child.obs == []


# ---------------------------------------------------------------------------
# satellite: STAGE_BUCKETS must mirror native/frontend.cpp STAGE_BOUNDS_NS
# ---------------------------------------------------------------------------

class TestStageBucketParity:
    def test_stage_buckets_match_cpp_bounds(self):
        cpp = (Path(__file__).resolve().parent.parent
               / "native" / "frontend.cpp").read_text()
        m = re.search(r"STAGE_BOUNDS_NS\[\]\s*=\s*\{([^}]*)\}", cpp)
        assert m, "STAGE_BOUNDS_NS not found in native/frontend.cpp"
        bounds_ns = [int(tok.strip().rstrip("L"))
                     for tok in m.group(1).replace("\n", " ").split(",")
                     if tok.strip()]
        py_ns = [round(b * 1e9) for b in metrics_mod.STAGE_BUCKETS]
        assert py_ns == bounds_ns, (
            "utils/metrics.py STAGE_BUCKETS and native/frontend.cpp "
            "STAGE_BOUNDS_NS diverged — drained stage histograms would land "
            "in the wrong Prometheus buckets")
        # and the C++ bucket count (bounds + overflow) matches the drain's
        m2 = re.search(r"N_STAGE_BUCKETS\s*=\s*(\d+)", cpp)
        assert m2 and int(m2.group(1)) == len(bounds_ns) + 1


# ---------------------------------------------------------------------------
# satellite (ISSUE 3): dedup/cache stat keys must not drift between the
# Python stats() merge, the C++ fe_stats exporter, and the drain's
# series-materialization list
# ---------------------------------------------------------------------------

class TestDedupCacheStatKeyParity:
    # the C++ credential-cache counters the verdict cache folds into
    CPP_KEYS = ("dyn_hit", "dyn_miss", "dyn_add")
    # Python-side verdict-cache keys merged into stats() next to them
    PY_KEYS = ("vdict_hit", "vdict_miss", "vdict_add", "vdict_evict")

    def test_cpp_exports_every_dyn_key(self):
        pymod = (Path(__file__).resolve().parent.parent
                 / "native" / "pymod.cpp").read_text()
        for key in self.CPP_KEYS:
            assert re.search(r'put\("%s"' % key, pymod), (
                f"native/pymod.cpp fe_stats no longer exports {key!r} — "
                "the verdict cache folds into these keys (native_frontend."
                "stats()) and the drain labels series by them")

    def test_python_stats_merge_uses_the_same_keys(self):
        # source-scan (not import: runtime/native_frontend.py needs
        # cryptography via the evaluator tree)
        src = (Path(__file__).resolve().parent.parent / "authorino_tpu"
               / "runtime" / "native_frontend.py").read_text()
        for key in self.CPP_KEYS + self.PY_KEYS:
            assert re.search(r'"%s"' % key, src), (
                f"native_frontend.stats() no longer references {key!r}")

    def test_drain_materializes_every_key(self):
        for key in self.CPP_KEYS + self.PY_KEYS:
            assert key in metrics_mod.NATIVE_ENSURE_KEYS, (
                f"{key!r} missing from NATIVE_ENSURE_KEYS — its "
                "auth_server_native_frontend_events_total series would "
                "not exist on /metrics until the first delta")

    def test_drain_creates_zero_valued_series(self):
        from prometheus_client import REGISTRY

        drain = metrics_mod.NativeStatsDrain()
        drain.fold({"fast": 1})  # any fold materializes the ensure list
        for key in metrics_mod.NATIVE_ENSURE_KEYS:
            # raw registry read: the series must EXIST (0.0), not be absent
            assert REGISTRY.get_sample_value(
                "auth_server_native_frontend_events_total",
                {"event": key}) is not None

    def test_verdict_cache_series_exist(self):
        metrics_mod.observe_dedup("testlane", 10, 4, 3, 3, 1)
        assert sample("auth_server_verdict_cache_hits_total",
                      {"lane": "testlane"}) == 3.0
        assert sample("auth_server_verdict_cache_misses_total",
                      {"lane": "testlane"}) == 3.0
        assert sample("auth_server_verdict_cache_evictions_total",
                      {"lane": "testlane"}) == 1.0
        # dedup ratio histogram: 10 rows → 4 device rows = 0.6 collapsed
        assert sample("auth_server_batch_dedup_ratio_sum",
                      {"lane": "testlane"}) == pytest.approx(0.6)
        assert sample("auth_server_batch_dedup_ratio_count",
                      {"lane": "testlane"}) == 1.0


# ---------------------------------------------------------------------------
# drain plumbing details
# ---------------------------------------------------------------------------

class TestNativeStatsDrain:
    def test_deltas_not_absolutes(self):
        drain = metrics_mod.NativeStatsDrain()
        base = sample("auth_server_native_frontend_events_total",
                      {"event": "denied"})
        drain.fold({"denied": 10})
        drain.fold({"denied": 10})  # no movement: no double count
        drain.fold({"denied": 25})
        assert sample("auth_server_native_frontend_events_total",
                      {"event": "denied"}) == base + 25

    def test_counter_reset_never_goes_negative(self):
        drain = metrics_mod.NativeStatsDrain()
        base = sample("auth_server_native_frontend_events_total",
                      {"event": "allowed"})
        drain.fold({"allowed": 100})
        drain.fold({"allowed": 3})  # fe restarted: counters reset
        drain.fold({"allowed": 5})
        assert sample("auth_server_native_frontend_events_total",
                      {"event": "allowed"}) == base + 102

    def test_empty_fold_is_noop(self):
        metrics_mod.NativeStatsDrain().fold({})
