"""Incremental compile & delta snapshot distribution (ISSUE 8).

Churn properties end to end: re-reconciling an unchanged corpus compiles
ZERO configs and uploads ZERO bytes; mutating one config recompiles exactly
that one, ships a rows-level delta, keeps ≥95% of verdict-cache entries
alive across the swap, and serves verdicts bit-identical to a cold full
compile.  Plus the serialization container (round-trip, corruption), the
leader/replica distribution protocol (vetted load, admission rejection with
the old snapshot still serving), the snapshot-diff engine, and the
mid-dispatch swap pinning regression.

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports); JAX_PLATFORMS=cpu."""

from __future__ import annotations

import asyncio
import json
import os
import random
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.engine import SnapshotRejected
from authorino_tpu.snapshots import (
    CompileCache,
    cache_tokens,
    encoding_epoch,
    rules_fingerprint,
    serialize_policy,
    snapshot_diff,
)
from authorino_tpu.snapshots.delta import apply_delta
from authorino_tpu.snapshots.diff import plan_delta
from authorino_tpu.snapshots.distribution import (
    SnapshotLoadError,
    SnapshotPublisher,
    SnapshotReplica,
    load_latest,
    load_snapshot_blob,
)
from authorino_tpu.snapshots.serialize import deserialize_policy


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def make_corpus(n=20, mutated=(), tag="MUT", seed=5):
    """Deterministic corpus; rebuilding with the same args yields fresh
    tree OBJECTS with identical structure (the fingerprint must see
    through object identity)."""
    rng = random.Random(seed)
    cfgs = []
    for i in range(n):
        const = f"org-{i}" + (f"-{tag}" if i in mutated else "")
        rule = All(
            Pattern("request.method", Operator.EQ,
                    ["GET", "POST"][i % 2]),
            Any_(
                Pattern("auth.identity.org", Operator.EQ, const),
                Pattern("auth.identity.roles", Operator.INCL, f"role-{i}"),
                Pattern("request.url_path", Operator.MATCHES,
                        rf"^/svc-{i % 3}/"),
            ),
        )
        cfgs.append(ConfigRules(name=f"cfg-{i}", evaluators=[(None, rule)]))
    rng.random()  # keep the signature honest about determinism
    return cfgs


def entries_of(cfgs):
    return [EngineEntry(id=c.name, hosts=[c.name], runtime=None, rules=c)
            for c in cfgs]


def build_engine(cfgs=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("verdict_cache_size", 4096)
    # cache-token survival contracts live on the DEVICE encode path —
    # host-lane routing (which skips encode and the verdict cache by
    # design) is pinned in tests/test_lane_select.py
    kw.setdefault("lane_select", False)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    if cfgs is not None:
        engine.apply_snapshot(entries_of(cfgs))
    return engine


def doc(i, method="GET"):
    return {"request": {"method": ["GET", "POST"][i % 2],
                        "url_path": f"/svc-{i % 3}/x"},
            "auth": {"identity": {"org": f"org-{i}", "roles": []}}}


async def submit_all(engine, n):
    return await asyncio.gather(*[engine.submit(doc(i), f"cfg-{i}")
                                  for i in range(n)])


# ---------------------------------------------------------------------------
# fingerprints + epoch
# ---------------------------------------------------------------------------


def test_fingerprint_stable_across_rebuilds_and_sensitive_to_change():
    a = make_corpus()[3]
    b = make_corpus()[3]          # fresh objects, same structure
    c = make_corpus(mutated={3})[3]
    assert a.evaluators[0][1] is not b.evaluators[0][1]
    assert rules_fingerprint(a) == rules_fingerprint(b)
    assert rules_fingerprint(a) != rules_fingerprint(c)
    # name-free: identical rules under different names share a fingerprint
    renamed = ConfigRules(name="other", evaluators=list(a.evaluators))
    assert rules_fingerprint(renamed) == rules_fingerprint(a)


def test_encoding_epoch_folds_in_interner_identity():
    cfgs = make_corpus(4)
    p1 = compile_corpus(cfgs, members_k=4)
    p2 = compile_corpus(make_corpus(4), members_k=4)  # fresh interner
    assert encoding_epoch(p1) != encoding_epoch(p2)
    # same interner, same layout → same epoch
    p3 = compile_corpus(make_corpus(4), members_k=4, interner=p1.interner)
    assert encoding_epoch(p3) == encoding_epoch(p1)


def test_cache_tokens_cover_padded_rows():
    cfgs = make_corpus(3)
    p = compile_corpus(cfgs, members_k=4)
    fps = {c.name: rules_fingerprint(c) for c in cfgs}
    toks = cache_tokens(p, fps)
    assert len(toks) == p.eval_rule.shape[0]
    for name, row in p.config_ids.items():
        assert toks[row] == (encoding_epoch(p), fps[name])


# ---------------------------------------------------------------------------
# compile cache: zero-recompile / exactly-one properties
# ---------------------------------------------------------------------------


def test_unchanged_corpus_compiles_zero_and_uploads_zero():
    engine = build_engine(make_corpus())
    snap1 = engine._snapshot
    engine.apply_snapshot(entries_of(make_corpus()))  # fresh trees
    snap2 = engine._snapshot
    rep = snap2.compile_report
    assert rep.compiled == 0 and rep.cached == 20 and rep.reused_policy
    assert snap2.policy is snap1.policy
    assert snap2.params is snap1.params
    assert snap2.upload["mode"] == "reuse"
    assert snap2.upload["upload_bytes"] == 0
    # the swap itself still happened (generation advances, index rebuilt)
    assert snap2.generation == snap1.generation + 1


def test_mutating_one_config_recompiles_exactly_one():
    engine = build_engine(make_corpus())
    engine.apply_snapshot(entries_of(make_corpus(mutated={7})))
    rep = engine._snapshot.compile_report
    assert rep.compiled == 1
    assert rep.compiled_names == ["cfg-7"]
    assert rep.cached == 19
    up = engine._snapshot.upload
    assert up["mode"] == "delta"
    assert 0 < up["upload_bytes"] < up["full_bytes"] / 2


def test_compile_cache_shares_artifacts_across_identical_configs():
    cache = CompileCache()
    rule = All(Pattern("auth.identity.org", Operator.EQ, "acme"))
    a1, hit1 = cache.artifact_for(ConfigRules(name="a", evaluators=[(None, rule)]))
    a2, hit2 = cache.artifact_for(ConfigRules(name="b", evaluators=[(None, rule)]))
    assert not hit1 and hit2 and a1 is a2
    assert cache.stats()["entries"] == 1


def test_compile_cache_lru_bound():
    cache = CompileCache(max_entries=2)
    for i in range(4):
        cache.artifact_for(ConfigRules(name=f"c{i}", evaluators=[
            (None, Pattern("auth.identity.org", Operator.EQ, f"o{i}"))]))
    assert len(cache) == 2


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_churn_verdicts_bit_identical_to_cold_compile(seed):
    """Property: after mutating one config, EVERY served verdict equals a
    cold full compile of the same corpus — the incremental path changes
    how tensors reach the device, never what they decide."""
    n = 12
    mut = seed % n
    engine = build_engine(make_corpus(n, seed=seed))
    run(submit_all(engine, n))  # warm (and pollute the caches)
    engine.apply_snapshot(entries_of(make_corpus(n, mutated={mut}, seed=seed)))
    got = run(submit_all(engine, n))

    cold = build_engine(make_corpus(n, mutated={mut}, seed=seed),
                        verdict_cache_size=0, batch_dedup=False)
    want = run(submit_all(cold, n))
    for (r1, s1), (r2, s2) in zip(got, want):
        np.testing.assert_array_equal(r1, r2)
        np.testing.assert_array_equal(s1, s2)


def test_verdict_cache_survival_at_least_95pct():
    """ISSUE 8 acceptance: mutate 1 of 40 configs → ≥95% of warmed
    verdict-cache entries still serve after the swap (per-config tokens;
    the global-generation keying this PR replaces survived 0%)."""
    n = 40
    engine = build_engine(make_corpus(n))
    run(submit_all(engine, n))
    vc = engine._verdict_cache
    assert vc.adds >= n
    engine.apply_snapshot(entries_of(make_corpus(n, mutated={11})))
    hits0 = vc.hits
    run(submit_all(engine, n))
    survived = vc.hits - hits0
    assert survived >= int(n * 0.95)
    # and the mutated config did NOT serve stale: its verdict flipped
    out = run(engine.submit(doc(11), "cfg-11"))
    cold = build_engine(make_corpus(n, mutated={11}),
                        verdict_cache_size=0, batch_dedup=False)
    want = run(cold.submit(doc(11), "cfg-11"))
    np.testing.assert_array_equal(out[0], want[0])


def test_changed_config_never_serves_stale_verdict():
    """The per-config keying is structural: a changed fingerprint makes
    every old entry unreachable, no flush, no TTL."""
    rule_acme = Pattern("auth.identity.org", Operator.EQ, "acme")
    rule_evil = Pattern("auth.identity.org", Operator.EQ, "evil")
    engine = build_engine([ConfigRules(name="c", evaluators=[(None, rule_acme)])])
    d = {"auth": {"identity": {"org": "acme"}}}
    out = run(engine.submit(d, "c"))
    assert bool(out[0][0])
    engine.apply_snapshot(entries_of(
        [ConfigRules(name="c", evaluators=[(None, rule_evil)])]))
    out = run(engine.submit(d, "c"))
    assert not bool(out[0][0])


def test_inflight_swap_inserts_under_pinned_tokens():
    """Mid-dispatch swap pinning (ISSUE 8 bugfix satellite): a batch in
    flight across a swap resolves AND inserts under its pinned snapshot's
    tokens — for an UNCHANGED config those tokens equal the new
    snapshot's, so the late insert is servable (not stale: identical
    semantics); for a CHANGED config they differ and the insert is
    unreachable from the new snapshot."""
    n = 4
    engine = build_engine(make_corpus(n))
    run(submit_all(engine, n))  # warm jit

    gate = threading.Event()
    real = PolicyEngine._encode_and_launch
    gated_launches = []

    class GatedHandle:
        def __init__(self, inner):
            self.inner = inner

        def is_ready(self):
            return gate.is_set() and (
                not hasattr(self.inner, "is_ready") or self.inner.is_ready())

        def __array__(self, dtype=None):
            return np.asarray(self.inner)

    def gated(snap, batch):
        item = real(engine, snap, batch)
        item.handle = GatedHandle(item.handle)
        gated_launches.append(item)
        return item

    engine._encode_and_launch = gated
    pinned_snap = engine._snapshot

    async def body():
        # cfg-1 (stays unchanged) and cfg-2 (will mutate) ride one gated
        # in-flight batch; different docs so nothing is cached yet
        d1 = {"request": {"method": "POST", "url_path": "/svc-1/z"},
              "auth": {"identity": {"org": "zzz", "roles": ["role-1"]}}}
        # url deliberately OUTSIDE cfg-2's ^/svc-2/ regex alternative, so
        # the verdict hinges on the org constant the mutation changes
        d2 = {"request": {"method": "GET", "url_path": "/nope/z"},
              "auth": {"identity": {"org": "org-2", "roles": []}}}
        pre = [asyncio.ensure_future(engine.submit(d1, "cfg-1")),
               asyncio.ensure_future(engine.submit(d2, "cfg-2"))]
        deadline = time.monotonic() + 5
        while not gated_launches and time.monotonic() < deadline:
            await asyncio.sleep(0.005)
        assert gated_launches
        engine._encode_and_launch = real.__get__(engine, PolicyEngine)
        engine.apply_snapshot(entries_of(make_corpus(n, mutated={2})))
        new_snap = engine._snapshot
        # unchanged config: tokens identical across the swap; changed: not
        assert new_snap.cache_tokens[pinned_snap.policy.config_ids["cfg-1"]] \
            == pinned_snap.cache_tokens[pinned_snap.policy.config_ids["cfg-1"]]
        assert new_snap.cache_tokens[pinned_snap.policy.config_ids["cfg-2"]] \
            != pinned_snap.cache_tokens[pinned_snap.policy.config_ids["cfg-2"]]
        adds0 = engine._verdict_cache.adds
        gate.set()
        outs = await asyncio.wait_for(asyncio.gather(*pre), timeout=10)
        assert engine._verdict_cache.adds > adds0  # late inserts landed
        # the in-flight batch resolved with its PINNED snapshot's
        # semantics (cfg-2 pre-mutation: org-2 allowed)
        assert bool(outs[1][0][0])
        # a fresh post-swap submit of the same cfg-2 row must NOT see the
        # pinned-token insert: mutated fingerprint → fresh evaluation
        # under the new rules (org-2 no longer matches org-2-MUT)
        hits0 = engine._verdict_cache.hits
        out2 = await engine.submit(d2, "cfg-2")
        assert not bool(out2[0][0])
        # ...while the unchanged config's late insert IS reachable
        hits1 = engine._verdict_cache.hits
        out1 = await engine.submit(d1, "cfg-1")
        assert engine._verdict_cache.hits > hits1
        np.testing.assert_array_equal(out1[0], outs[0][0])

    run(body())


def test_strict_verify_unchanged_corpus_skips_revalidation():
    from authorino_tpu.analysis.translation_validate import (
        clear_certificate_cache,
    )

    clear_certificate_cache()
    engine = build_engine(make_corpus(6), strict_verify=True)
    assert engine._snapshot.translation["validated"] == 6
    engine.apply_snapshot(entries_of(make_corpus(6)))
    tv = engine._snapshot.translation
    assert tv["validated"] == 0 and tv["cache_hits"] == 6
    # mutate one: exactly one re-validation (PR 6 certificate cache keyed
    # by the same fingerprints)
    engine.apply_snapshot(entries_of(make_corpus(6, mutated={2})))
    tv = engine._snapshot.translation
    assert tv["validated"] == 1 and tv["cache_hits"] == 5
    clear_certificate_cache()


# ---------------------------------------------------------------------------
# delta plan units
# ---------------------------------------------------------------------------


def test_plan_delta_modes():
    old = {"a": np.arange(64, dtype=np.int32).reshape(8, 8),
           "b": np.ones((4, 4), dtype=np.int32),
           "c": np.zeros((3,), dtype=np.int32),
           "levels": ((np.zeros((2, 2), dtype=np.int32),
                       np.ones((2,), dtype=bool)),),
           "matmul": None, "dfa": None}
    new = {k: (v if not isinstance(v, np.ndarray) else v.copy())
           for k, v in old.items()}
    new["levels"] = ((old["levels"][0][0].copy(), old["levels"][0][1].copy()),)
    new["a"][3] += 100                      # one row differs → rows mode
    new["c"] = np.zeros((5,), dtype=np.int32)  # shape change → full
    plan = plan_delta(old, new)
    modes = {e.name: e.mode for e in plan.entries}
    assert modes["a"] == "rows" and modes["b"] == "reuse"
    assert modes["c"] == "full"
    assert modes["levels.0.0"] == "reuse"  # generic tuple flattening
    a_entry = next(e for e in plan.entries if e.name == "a")
    assert list(a_entry.rows) == [3]
    assert plan.upload_bytes < plan.full_bytes
    # structure break: a lane appearing forces a full restage
    new2 = dict(new, dfa=np.ones((2, 2)))
    assert plan_delta(old, new2) is None


def test_apply_delta_reconstructs_exact_arrays():
    import jax

    old = {"a": np.arange(64, dtype=np.int32).reshape(8, 8),
           "b": np.ones((4, 4), dtype=np.int32), "matmul": None}
    new = {"a": old["a"].copy(), "b": old["b"].copy(), "matmul": None}
    new["a"][5] = -7
    prev_params = jax.tree.map(jax.device_put, old)
    plan = plan_delta(old, new)
    params, uploaded = apply_delta(prev_params, new, plan)
    np.testing.assert_array_equal(np.asarray(params["a"]), new["a"])
    assert params["b"] is prev_params["b"]          # reused buffer
    # the previous device buffer is untouched (double-buffer safety)
    np.testing.assert_array_equal(np.asarray(prev_params["a"]), old["a"])
    assert 0 < uploaded < new["a"].nbytes


# ---------------------------------------------------------------------------
# serialization + distribution
# ---------------------------------------------------------------------------


def _serialize_corpus(cfgs, certified=True, generation=1):
    policy = compile_corpus(cfgs, members_k=4)
    fps = {c.name: rules_fingerprint(c) for c in cfgs}
    meta = {"generation": generation, "certified": certified,
            "fingerprints": fps,
            "entries": [{"id": c.name, "hosts": [c.name]} for c in cfgs]}
    return serialize_policy(policy, meta=meta), policy


def test_serialize_roundtrip_bit_identical():
    cfgs = make_corpus(8)
    blob, policy = _serialize_corpus(cfgs)
    rt, meta = deserialize_policy(blob)
    for name in ("leaf_op", "leaf_attr", "leaf_const", "eval_cond",
                 "eval_rule", "eval_has_cond", "dfa_tables", "dfa_accept",
                 "dfa_table_of_row", "leaf_dfa_row", "attr_byte_slot",
                 "leaf_is_membership", "member_attr_slot", "member_attrs",
                 "cpu_leaf_list", "config_cacheable"):
        np.testing.assert_array_equal(getattr(policy, name),
                                      getattr(rt, name), err_msg=name)
    for (c1, i1), (c2, i2) in zip(policy.levels, rt.levels):
        np.testing.assert_array_equal(c1, c2)
        np.testing.assert_array_equal(i1, i2)
    assert rt.config_ids == policy.config_ids
    assert rt.attr_selectors == policy.attr_selectors
    assert rt.interner._table == policy.interner._table
    assert meta["certified"] is True
    # host oracle works on the reconstructed expression trees
    from authorino_tpu.models.policy_model import host_results

    for i in range(8):
        _, r1, s1 = host_results(policy, doc(i), i)
        _, r2, s2 = host_results(rt, doc(i), i)
        np.testing.assert_array_equal(r1, r2)
        np.testing.assert_array_equal(s1, s2)


def test_serialize_rejects_corruption_and_truncation():
    blob, _ = _serialize_corpus(make_corpus(4))
    flipped = bytearray(blob)
    flipped[len(flipped) // 2] ^= 0xFF
    with pytest.raises(SnapshotLoadError):
        load_snapshot_blob(bytes(flipped))
    with pytest.raises(SnapshotLoadError):
        load_snapshot_blob(blob[:200])
    with pytest.raises(SnapshotLoadError):
        load_snapshot_blob(b"not a snapshot at all")


def test_leader_replica_end_to_end(tmp_path):
    """Acceptance: a replica loads a leader-serialized vetted snapshot and
    serves bit-identical verdicts to an in-process compile of the same
    corpus; corrupt and uncertified snapshots are rejected at admission
    with the old snapshot still serving."""
    d = str(tmp_path / "pub")
    cfgs = make_corpus(10)

    leader = build_engine(strict_verify=True)
    pub = SnapshotPublisher(d)
    pub.attach(leader)
    leader.apply_snapshot(entries_of(cfgs))  # vetted + published (async)
    assert pub.flush()

    replica = build_engine()
    loaded = load_latest(d)
    assert loaded.certified and loaded.generation == leader.generation
    replica.apply_published(loaded)
    assert replica._snapshot.policy.config_ids == \
        leader._snapshot.policy.config_ids
    # host index routes (replica serves the compiled verdict lane)
    assert replica.lookup("cfg-3") is not None

    got = run(submit_all(replica, 10))
    want = run(submit_all(leader, 10))
    for (r1, s1), (r2, s2) in zip(got, want):
        np.testing.assert_array_equal(r1, r2)
        np.testing.assert_array_equal(s1, s2)

    good_snap = replica._snapshot
    good_gen = replica.generation

    # corrupt blob: flip a payload byte AND keep the manifest digest in
    # sync — the container's own sha256 trailer must still catch it
    man = json.loads(open(os.path.join(d, "MANIFEST.json")).read())
    p = os.path.join(d, man["current"])
    raw = bytearray(open(p, "rb").read())
    raw[len(raw) // 3] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    import hashlib

    man["sha256"] = hashlib.sha256(bytes(raw)).hexdigest()
    open(os.path.join(d, "MANIFEST.json"), "w").write(json.dumps(man))
    with pytest.raises(SnapshotLoadError):
        load_latest(d)
    assert replica._snapshot is good_snap  # old snapshot still serving

    # uncertified snapshot: loads fine, rejected at ADMISSION
    blob, _ = _serialize_corpus(make_corpus(10, mutated={1}),
                                certified=False, generation=99)
    pub.publish_blob(blob, 99)
    with pytest.raises(SnapshotRejected):
        replica.apply_published(load_latest(d))
    assert replica._snapshot is good_snap
    assert replica.generation == good_gen
    out = run(replica.submit(doc(3), "cfg-3"))
    assert bool(out[0][0])  # still serving the last vetted snapshot


def test_replica_poll_loop_applies_and_survives_rejection(tmp_path):
    d = str(tmp_path / "pub")
    leader = build_engine(strict_verify=True)
    pub = SnapshotPublisher(d)
    pub.attach(leader)
    leader.apply_snapshot(entries_of(make_corpus(6)))
    assert pub.flush()

    replica = build_engine()
    rep = SnapshotReplica(replica, d, poll_s=0.1)
    assert rep.poll_once() is True
    assert rep.applied == 1
    assert rep.poll_once() is False  # unchanged digest: no re-apply
    # a new vetted publish is picked up
    leader.apply_snapshot(entries_of(make_corpus(6, mutated={0})))
    assert pub.flush()
    assert rep.poll_once() is True and rep.applied == 2
    # an uncertified publish is rejected exactly once (digest remembered)
    blob, _ = _serialize_corpus(make_corpus(6, mutated={0, 1}),
                                certified=False, generation=50)
    pub.publish_blob(blob, 50)
    assert rep.poll_once() is False and rep.rejected == 1
    assert rep.poll_once() is False and rep.rejected == 1
    rep.stop()


def test_replica_delta_uploads_and_cache_survival_across_generations(tmp_path):
    """Churn reaches replicas too: the second published generation lands
    as a rows-level delta against the replica's previous device params,
    and — via interner adoption (every deserialize builds a fresh interner
    whose serial would otherwise change the epoch) — the replica's
    verdict-cache entries for untouched configs SURVIVE the swap."""
    n = 12
    d = str(tmp_path / "pub")
    leader = build_engine(strict_verify=True)
    pub = SnapshotPublisher(d)
    pub.attach(leader)
    leader.apply_snapshot(entries_of(make_corpus(n)))
    assert pub.flush()
    replica = build_engine()
    replica.apply_published(load_latest(d))
    run(submit_all(replica, n))  # warm the replica's verdict cache
    vc = replica._verdict_cache
    assert vc.adds >= n
    leader.apply_snapshot(entries_of(make_corpus(n, mutated={4})))
    assert pub.flush()
    replica.apply_published(load_latest(d))
    up = replica._snapshot.upload
    assert up["mode"] == "delta"
    assert up["upload_bytes"] < up["full_bytes"] / 2
    hits0 = vc.hits
    run(submit_all(replica, n))
    assert vc.hits - hits0 >= n - 1  # only the mutated config misses
    # and the mutated config's new rules actually serve
    out = run(replica.submit(doc(4), "cfg-4"))
    cold = build_engine(make_corpus(n, mutated={4}),
                        verdict_cache_size=0, batch_dedup=False)
    want = run(cold.submit(doc(4), "cfg-4"))
    np.testing.assert_array_equal(out[0], want[0])


def test_replica_never_republishes_loaded_snapshots(tmp_path):
    """Loop breaker: a node that both loads and publishes (a relay, or a
    misconfigured replica) must not republish what it consumed — that
    would re-apply/republish forever through any shared path."""
    d1, d2 = str(tmp_path / "up"), str(tmp_path / "down")
    leader = build_engine(strict_verify=True)
    pub = SnapshotPublisher(d1)
    pub.attach(leader)
    leader.apply_snapshot(entries_of(make_corpus(4)))
    assert pub.flush()

    relay = build_engine()
    relay_pub = SnapshotPublisher(d2)
    relay_pub.attach(relay)
    relay.apply_published(load_latest(d1))
    assert relay_pub.flush()
    assert relay._snapshot.published_origin
    assert not [f for f in os.listdir(d2) if f.endswith(".atpusnap")]


def test_join_during_quarantine_adopts_manifest_not_newest_blob(tmp_path):
    """ISSUE 18: a replica joining MID-CANARY — after the fleet guard
    rolled the candidate back — must serve the manifest's ``current``/
    ``active_generation`` (the leader's serving DECISION) and adopt its
    rollback/quarantine record, never the newest blob file in the
    directory: the quarantined candidate is still on disk (gc keeps
    recent blobs) and its filename sorts NEWEST."""
    d = str(tmp_path / "pub")
    baseline = make_corpus(8)
    leader = build_engine(baseline, strict_verify=True)
    base_gen = leader.generation
    pub = SnapshotPublisher(d)
    pub.publish_from_engine(leader)

    # the candidate reconcile publishes (generation base+1)...
    cand_blob, _ = _serialize_corpus(make_corpus(8, mutated={2}),
                                     certified=True,
                                     generation=base_gen + 1)
    pub.publish_blob(cand_blob, base_gen + 1)
    # ...then breaches the fleet guard: the leader republishes BASELINE
    # with the rollback/quarantine record — the manifest moves backwards
    # semantically while the candidate blob file stays on disk
    leader._snapshot.change_safety = {
        "rollback": {"reason": "fleet-guard-breach",
                     "guards": ["config-deny-rate"]},
        "quarantine": {"reason": "fleet-guard-breach",
                       "configs": ["cfg-2"]},
    }
    pub.publish_from_engine(leader)

    blobs = sorted(f for f in os.listdir(d) if f.endswith(".atpusnap"))
    man = json.loads(open(os.path.join(d, "MANIFEST.json")).read())
    assert blobs[-1] == f"snapshot-{base_gen + 1:012d}.atpusnap"
    assert man["current"] == f"snapshot-{base_gen:012d}.atpusnap"
    assert man["active_generation"] == base_gen
    assert man["rollback"]["reason"] == "fleet-guard-breach"

    # the joiner: manifest-directed adoption, never newest-blob
    joiner = build_engine()
    rep = SnapshotReplica(joiner, d, poll_s=0.2)
    assert rep.poll_once() is True
    assert (joiner._snapshot.change_safety or {})["rollback"][
        "reason"] == "fleet-guard-breach"
    assert joiner._snapshot.change_safety["quarantine"][
        "configs"] == ["cfg-2"]
    # the candidate flipped cfg-2's org constant; this doc allows ONLY
    # under baseline (no url_path rescue) — the joiner must allow
    probe = {"request": {"method": "GET", "url_path": "/other/x"},
             "auth": {"identity": {"org": "org-2", "roles": []}}}
    out = run(joiner.submit(dict(probe), "cfg-2"))
    assert bool(out[0][0])
    want = run(leader.submit(dict(probe), "cfg-2"))
    np.testing.assert_array_equal(out[0], want[0])
    # re-polling the unchanged manifest is a no-op (digest dedup), and
    # the quarantined blob never gets another look
    assert rep.poll_once() is False
    assert rep.rejected == 0 and rep.errors == 0


# ---------------------------------------------------------------------------
# diff engine + CLI
# ---------------------------------------------------------------------------


def test_snapshot_diff_names_exactly_the_changes():
    old = {c.name: rules_fingerprint(c) for c in make_corpus(6)}
    new_cfgs = make_corpus(6, mutated={2})[:5]  # drop cfg-5, mutate cfg-2
    new = {c.name: rules_fingerprint(c) for c in new_cfgs}
    new["cfg-9"] = "f" * 64                      # and add one
    d = snapshot_diff(old, new)
    assert d["changed"] == ["cfg-2"]
    assert d["removed"] == ["cfg-5"]
    assert d["added"] == ["cfg-9"]
    assert d["unchanged"] == 4
    assert d["recompile"] == ["cfg-2", "cfg-9"]


def test_snapshot_diff_cli(tmp_path):
    blob1, _ = _serialize_corpus(make_corpus(6), generation=1)
    # same interner continuity is NOT required for the CLI diff — it
    # compares fingerprints and host views structurally
    blob2, _ = _serialize_corpus(make_corpus(6, mutated={3}), generation=2)
    p1, p2 = str(tmp_path / "old.snap"), str(tmp_path / "new.snap")
    open(p1, "wb").write(blob1)
    open(p2, "wb").write(blob2)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "authorino_tpu.analysis",
         "--snapshot-diff", p1, p2, "--json"],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout)
    assert out["configs"]["changed"] == ["cfg-3"]
    assert out["new_generation"] == 2


def test_debug_vars_control_plane_block():
    engine = build_engine(make_corpus(5))
    engine.apply_snapshot(entries_of(make_corpus(5)))
    cp = engine.debug_vars()["control_plane"]
    assert cp["compile"]["compiled"] == 0
    assert cp["upload"]["mode"] == "reuse"
    assert cp["per_config_cache_keying"] is True
    assert "compile" in cp["phases_ms"]
    assert cp["compile_cache"]["hit_ratio"] is not None
