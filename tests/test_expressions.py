"""Pattern expression oracle tests (semantics: pkg/jsonexp/expressions.go)."""

import pytest

from authorino_tpu.expressions import (
    All,
    And,
    Any_,
    FALSE,
    Operator,
    Or,
    Pattern,
    PatternError,
    TRUE,
)

DOC = {
    "auth": {
        "identity": {"username": "john", "roles": ["admin", "dev"], "age": 42},
    },
    "request": {"http": {"path": "/pets/1", "method": "GET"}},
}


def P(sel, op, val):
    return Pattern(sel, Operator.from_string(op), val)


class TestPattern:
    def test_eq(self):
        assert P("auth.identity.username", "eq", "john").matches(DOC)
        assert not P("auth.identity.username", "eq", "jane").matches(DOC)
        # numbers compare through String() rendering
        assert P("auth.identity.age", "eq", "42").matches(DOC)
        # missing resolves to "" (gjson String of missing)
        assert P("auth.identity.nope", "eq", "").matches(DOC)

    def test_neq(self):
        assert P("auth.identity.username", "neq", "jane").matches(DOC)
        assert not P("auth.identity.username", "neq", "john").matches(DOC)

    def test_incl_excl(self):
        assert P("auth.identity.roles", "incl", "admin").matches(DOC)
        assert not P("auth.identity.roles", "incl", "root").matches(DOC)
        assert P("auth.identity.roles", "excl", "root").matches(DOC)
        assert not P("auth.identity.roles", "excl", "dev").matches(DOC)
        # scalar behaves as single-element array (gjson Result.Array())
        assert P("auth.identity.username", "incl", "john").matches(DOC)
        # missing → empty array → incl false, excl true
        assert not P("auth.identity.nope", "incl", "x").matches(DOC)
        assert P("auth.identity.nope", "excl", "x").matches(DOC)

    def test_matches(self):
        assert P("request.http.path", "matches", r"^/pets/\d+$").matches(DOC)
        assert not P("request.http.path", "matches", r"^/cats").matches(DOC)
        with pytest.raises(PatternError):
            P("request.http.path", "matches", r"([").matches(DOC)

    def test_unknown_operator(self):
        with pytest.raises(PatternError):
            Operator.from_string("contains")


class TestCombinators:
    def test_all_any(self):
        ok = P("auth.identity.username", "eq", "john")
        bad = P("auth.identity.username", "eq", "jane")
        assert All(ok, ok).matches(DOC)
        assert not All(ok, bad).matches(DOC)
        assert Any_(bad, ok).matches(DOC)
        assert not Any_(bad, bad).matches(DOC)

    def test_empty(self):
        # empty And vacuously true; empty Or false (ref :111-125, :136-154)
        assert TRUE.matches(DOC)
        assert not FALSE.matches(DOC)

    def test_nesting(self):
        expr = All(
            P("request.http.method", "eq", "GET"),
            Any_(
                P("auth.identity.roles", "incl", "root"),
                All(
                    P("auth.identity.roles", "incl", "admin"),
                    P("request.http.path", "matches", r"^/pets"),
                ),
            ),
        )
        assert expr.matches(DOC)
