"""Overload resilience (ISSUE 7): CoDel-style admission control, the
adaptive window/batch-cut controller, host-lane brownout, and
drain-under-overload — traffic failure must degrade throughput with typed
rejections, never correctness and never a raw exception.

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports)."""

from __future__ import annotations

import asyncio
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime import engine as engine_mod
from authorino_tpu.runtime import faults
from authorino_tpu.runtime.admission import (
    ADMIT,
    OVERLOADED,
    AdaptiveWindow,
    AdmissionController,
    R_DOOMED,
    R_OVERLOAD,
    R_QUEUE_FULL,
)
from authorino_tpu.utils.rpc import (
    DEADLINE_EXCEEDED,
    RESOURCE_EXHAUSTED,
    CheckAbort,
    http_status_for,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.FAULTS.disarm()


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def sample(name, labels=None):
    from prometheus_client import REGISTRY

    v = REGISTRY.get_sample_value(name, labels or {})
    return 0.0 if v is None else v


RULE = All(
    Pattern("auth.identity.roles", Operator.INCL, "admin"),
    Pattern("auth.identity.groups", Operator.EXCL, "banned"),
)


def build_engine(**kw) -> PolicyEngine:
    kw.setdefault("verdict_cache_size", 0)
    kw.setdefault("max_batch", 8)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id="c", hosts=["c"], runtime=None,
                    rules=ConfigRules(name="c", evaluators=[(None, RULE)]))
    ])
    return engine


def doc(i: int, allow: bool) -> dict:
    return {"auth": {"identity": {
        "roles": ["admin", f"r{i}"] if allow else [f"r{i}"],
        "groups": []}}}


async def submit_all(engine, docs, **kw):
    outs = await asyncio.gather(
        *(engine.submit(d, "c", **kw) for d in docs))
    return [bool(rule[0]) for rule, _ in outs]


class FakeHandle:
    def __init__(self, ready_at):
        self.ready_at = ready_at

    def is_ready(self):
        return time.monotonic() >= self.ready_at

    def __array__(self, dtype=None):
        return np.zeros((1, 1))


class SlowStubDevice:
    """Replaces _encode_and_launch: batches 'complete' after a fixed
    latency, so the window can be held saturated deterministically."""

    def __init__(self, engine, latency_s):
        self.engine = engine
        self.latency_s = latency_s
        self.launched_batches = 0
        self.launched_rows = 0
        engine._encode_and_launch = self._launch

    def _launch(self, snap, batch):
        n = len(batch)
        self.launched_batches += 1
        self.launched_rows += n
        binfo = {"batch_size": n, "pad": n, "eff": 0,
                 "start_ns": time.time_ns(), "duration_s": 0.0}

        def finalize(packed):
            rule = np.ones((n, 1), dtype=bool)
            return rule, np.zeros((n, 1), dtype=bool), None

        return engine_mod._Inflight(
            self.engine, batch,
            FakeHandle(time.monotonic() + self.latency_s),
            finalize, binfo, np.zeros(n))


# ---------------------------------------------------------------------------
# admission controller units
# ---------------------------------------------------------------------------


class TestAdmissionController:
    def test_cold_start_floor_admits_bursts(self):
        a = AdmissionController("t-cold", target_s=0.05, min_cap=128)
        assert a.admit(0) is None
        assert a.admit(127) is None
        code, reason = a.admit(128)
        assert code == RESOURCE_EXHAUSTED and reason == R_OVERLOAD
        assert http_status_for(code) == 429

    def test_wait_targeted_cap_follows_service_rate(self):
        a = AdmissionController("t-rate", target_s=0.1, min_cap=10)
        # 10k rows over 1s → rate ≈ 10k/s → cap ≈ 1000 (0.1s of work)
        a.observe_service(0, now=100.0)
        a.observe_service(10_000, now=101.0)
        cap = a.effective_cap()
        assert 500 <= cap <= 2000
        assert a.admit(cap - 1) is None
        code, reason = a.admit(cap)
        assert code == RESOURCE_EXHAUSTED and reason == R_OVERLOAD

    def test_hard_queue_cap_reason(self):
        a = AdmissionController("t-hard", target_s=10.0, queue_cap=16,
                                min_cap=4)
        code, reason = a.admit(16)
        assert code == RESOURCE_EXHAUSTED and reason == R_QUEUE_FULL

    def test_doomed_deadline_rejected_at_admission(self):
        a = AdmissionController("t-doom", target_s=0.05, min_cap=1000)
        a.observe_service(0, now=10.0)
        a.observe_service(1000, now=11.0)  # rate ≈ 1000/s
        now = 50.0
        # 500 queued at 1000/s → ~0.5s predicted wait; a 0.1s deadline
        # budget is doomed, a 5s one is fine
        code, reason = a.admit(500, now=now, deadline=now + 0.1)
        assert code == DEADLINE_EXCEEDED and reason == R_DOOMED
        assert a.admit(500, now=now, deadline=now + 5.0) is None

    def test_codel_state_flips_on_standing_min_wait(self):
        a = AdmissionController("t-codel", target_s=0.05, interval_s=0.5)
        assert a.state == ADMIT
        # min wait above target, sustained past one interval → OVERLOADED
        a.observe_waits([0.2, 0.3], now=1.0)
        assert a.state == ADMIT  # not sustained yet
        a.observe_waits([0.2], now=1.3)
        a.observe_waits([0.2], now=1.6)
        assert a.state == OVERLOADED
        # one batch whose MIN dips under target = the standing queue broke
        a.observe_waits([0.01, 0.4], now=1.7)
        assert a.state == ADMIT

    def test_transient_spike_never_flips_state(self):
        a = AdmissionController("t-spike", target_s=0.05, interval_s=0.5)
        # a single high-wait batch inside the interval, then clean batches
        a.observe_waits([0.3], now=1.0)
        a.observe_waits([0.001], now=1.2)
        a.observe_waits([0.001], now=1.9)
        assert a.state == ADMIT

    def test_drop_pacing_and_idle_decay(self):
        a = AdmissionController("t-drop", target_s=0.05, interval_s=0.5)
        for t in (1.0, 1.3, 1.6):
            a.observe_waits([0.2], now=t)
        assert a.state == OVERLOADED
        assert a.drop_now(now=1.61) is True        # first paced drop
        assert a.drop_now(now=1.62) is False       # inside the pacing gap
        assert a.drop_now(now=1.61 + 0.51) is True  # next interval
        # no wait observations for 2 intervals → the load vanished: the
        # stale OVERLOADED flag must not drop the next quiet-period burst
        assert a.drop_now(now=5.0) is False
        assert a.state == ADMIT


# ---------------------------------------------------------------------------
# adaptive window controller units
# ---------------------------------------------------------------------------


class TestAdaptiveWindow:
    def drive(self, c, rtt, rate, cut, rounds=40, depth=0):
        t = 100.0
        per = max(1, int(rate * 0.1))
        for _ in range(rounds):
            c.observe_arrivals(per)
            t += 0.1
            c.observe_batch(rtt, cut, depth, now=t)

    def test_starts_at_cap_and_shrinks_when_idle(self):
        c = AdaptiveWindow("t-start", cap=48, batch_cap=256)
        assert c.window == 48  # cold burst is never window-starved
        self.drive(c, rtt=0.001, rate=100, cut=8, rounds=60, depth=0)
        assert 1 <= c.window < 48  # light load returned device memory

    def test_converges_on_rtt_step_change(self):
        c = AdaptiveWindow("t-step", cap=48, batch_cap=256)
        # settle at a fast device first: queue clear, Little target ≈ 2
        self.drive(c, rtt=0.005, rate=2000, cut=50, rounds=80, depth=0)
        low = c.window
        assert low <= 5
        # device RTT steps 0.005 → 0.5 and a backlog forms: the controller
        # must open the window back up (work-conserving), never sit at the
        # light-load operating point while the queue stands
        self.drive(c, rtt=0.5, rate=2000, cut=50, rounds=40, depth=4)
        assert c.window > low
        assert c.window == 48  # backlog standing → the full cap
        assert c.batch_cut == 256  # full cuts amortize the deeper RTT
        # and step back down once the RTT recovers and the queue clears:
        # Little target = ceil(2000 × 0.005 / 50) + 1 = 2
        self.drive(c, rtt=0.005, rate=2000, cut=50, rounds=120, depth=0)
        assert c.window <= 6

    def test_backlog_never_tracks_the_achieved_rate_fixed_point(self):
        """The failure mode the law exists to avoid: under saturation the
        measured arrival rate equals the achieved rate, so a Little's-law
        tracker would pin a tiny window forever.  With a backlog standing
        the window must GROW regardless of the (self-limited) rate."""
        c = AdaptiveWindow("t-fixedpoint", cap=48, batch_cap=512)
        # a self-consistent low point: rate 350, rtt 0.1, cut 20 → Little
        # target 3 — but the queue never clears
        self.drive(c, rtt=0.1, rate=350, cut=20, rounds=5, depth=1000)
        assert c.window == 48
        assert c.batch_cut == 512

    def test_disabled_controller_pins_the_cap(self):
        c = AdaptiveWindow("t-off", cap=7, batch_cap=32, enabled=False)
        self.drive(c, rtt=0.001, rate=10, cut=4, rounds=30, depth=0)
        assert c.window == 7 and c.batch_cut == 32

    @pytest.mark.perf_guard
    def test_window_and_cut_bounds_hold_under_adversarial_feeds(self):
        """perf_guard invariant (ISSUE 7 satellite): whatever the
        observations — junk RTTs, absurd rates, zero everything — the
        controller NEVER leaves [1, cap] / [1, batch_cap]."""
        import random as _random

        rng = _random.Random(7)
        c = AdaptiveWindow("t-bounds", cap=48, batch_cap=256)
        t = 0.0
        feeds = [0.0, -1.0, float("inf"), float("nan"), 1e9, 1e-9]
        for i in range(500):
            c.observe_arrivals(rng.randrange(0, 100_000))
            t += rng.choice([0.0, 0.01, 0.5, 10.0])
            c.observe_batch(rng.choice(feeds), rng.randrange(-5, 10_000),
                            rng.randrange(0, 100), now=t)
            assert 1 <= c.window <= 48
            assert 1 <= c.batch_cut <= 256
        assert 1 <= c.window <= 48


# ---------------------------------------------------------------------------
# engine lane: admission before encode
# ---------------------------------------------------------------------------


class TestEngineAdmission:
    def test_saturated_window_rejects_typed_before_encode(self):
        """Acceptance: with the window saturated and the queue at the
        admission cap, further submits fail typed RESOURCE_EXHAUSTED at
        admission — the stub device proves the rejected requests never
        reached an encode."""
        engine = build_engine(max_batch=4, max_inflight_batches=1,
                              admission_queue_cap=8, brownout=False)
        stub = SlowStubDevice(engine, latency_s=30.0)
        rej0 = sample("auth_server_admission_rejected_total",
                      {"lane": "engine", "reason": "queue-full"})

        async def scenario():
            tasks = [asyncio.ensure_future(engine.submit(doc(i, True), "c"))
                     for i in range(4)]
            await asyncio.sleep(0.05)  # one batch launches, window = 1/1
            # fill the queue to the hard cap, then overflow it
            extra = [asyncio.ensure_future(engine.submit(doc(10 + i, True), "c"))
                     for i in range(8)]
            await asyncio.sleep(0.02)
            rejected = []
            for i in range(5):
                try:
                    await engine.submit(doc(50 + i, True), "c")
                except CheckAbort as e:
                    rejected.append(e)
            for t in tasks + extra:
                t.cancel()
            return rejected

        rejected = run(scenario())
        assert len(rejected) == 5
        assert all(e.code == RESOURCE_EXHAUSTED for e in rejected)
        assert all(http_status_for(e.code) == 429 for e in rejected)
        assert sample("auth_server_admission_rejected_total",
                      {"lane": "engine", "reason": "queue-full"}) == rej0 + 5
        # only the one window batch ever encoded: rejected work cost nothing
        assert stub.launched_batches == 1

    def test_doomed_deadline_rejected_at_admission_before_encode(self):
        # lane selection OFF: with it on, the lane-aware admission floor
        # ADMITS this deadline and the host lane rescues it (pinned in
        # tests/test_lane_select.py) — this test pins the legacy contract
        engine = build_engine(max_batch=4, brownout=False,
                              lane_select=False)
        stub = SlowStubDevice(engine, latency_s=30.0)
        engine._device_ewma = 5.0  # one expected device round trip = 5s
        shed0 = sample("auth_server_deadline_shed_total", {"lane": "engine"})
        doom0 = sample("auth_server_admission_rejected_total",
                       {"lane": "engine", "reason": "doomed-deadline"})

        async def one():
            with pytest.raises(CheckAbort) as ei:
                await engine.submit(doc(0, True), "c",
                                    deadline=time.monotonic() + 1.0)
            return ei.value

        e = run(one())
        assert e.code == DEADLINE_EXCEEDED
        assert http_status_for(e.code) == 504
        # counted as BOTH an admission rejection and a deadline shed (it is
        # one — just before the queue instead of at the batch cut)
        assert sample("auth_server_admission_rejected_total",
                      {"lane": "engine", "reason": "doomed-deadline"}) \
            == doom0 + 1
        assert sample("auth_server_deadline_shed_total",
                      {"lane": "engine"}) == shed0 + 1
        assert stub.launched_batches == 0  # never encoded, never launched

    def test_admission_precheck_front_door(self):
        engine = build_engine(brownout=False)
        # force OVERLOADED with recent observations + a device RTT that
        # dooms a tight deadline at the front door
        now = time.monotonic()
        for dt in (0.0, 0.4, 0.8):
            engine.admission.observe_waits([0.5], now=now + dt)
        assert engine.admission.overloaded
        engine._device_ewma = 5.0
        res = engine.admission_precheck(deadline=time.monotonic() + 0.01)
        assert res is not None and res.code == DEADLINE_EXCEEDED
        # a request with no deadline is never front-door rejected
        assert engine.admission_precheck(deadline=None) is None

    def test_precheck_hard_cap_and_consistency_with_admit(self):
        a = AdmissionController("t-pre", target_s=0.05, queue_cap=8,
                                min_cap=4)
        code, reason = a.precheck(8)
        assert code == RESOURCE_EXHAUSTED and reason == R_QUEUE_FULL
        # below the hard cap and not overloaded: precheck never rejects
        # (even where admit's dynamic cap would) — the submit gate stays
        # the one true admission point
        assert a.precheck(6) is None

    def test_idle_engine_unlatches_overloaded_on_next_decision(self):
        a = AdmissionController("t-idle", target_s=0.05, interval_s=0.5)
        for t in (1.0, 1.4, 1.8):
            a.observe_waits([0.5], now=t)
        assert a.state == OVERLOADED
        # the load vanished: the next admission decision (2x interval
        # later) clears the stale flag instead of dooming the burst
        # (deadline comfortably past the stale wait EWMA, which only
        # decays with fresh observations)
        assert a.admit(0, now=10.0, deadline=11.0, rtt_s=0.0) is None
        assert a.state == ADMIT

    def test_max_delay_s_is_a_deprecated_shim(self):
        with pytest.warns(DeprecationWarning):
            engine = build_engine(max_delay_s=0.123)
        assert engine.max_delay_s == 0.123  # echoed for /debug/vars only
        assert run(submit_all(engine, [doc(0, True)])) == [True]


# ---------------------------------------------------------------------------
# brownout: exact host-lane spill under saturation
# ---------------------------------------------------------------------------


class TestBrownout:
    def test_brownout_verdicts_bit_identical_to_oracle(self):
        """Acceptance: with the device window saturated, queued requests
        spill to the host lane and their verdicts are EXACT — including the
        membership-overflow rows the compact device payload is lossy for."""
        engine = build_engine(max_batch=4, max_inflight_batches=1,
                              admission_target_s=0.001,
                              brownout_max_batch=16)
        stub = SlowStubDevice(engine, latency_s=0.8)
        b0 = sample("auth_server_brownout_decisions_total",
                    {"lane": "engine"})
        over = {"auth": {"identity": {
            "roles": [f"r{k}" for k in range(10)] + ["admin"],
            "groups": []}}}
        docs = [doc(i, i % 3 != 0) for i in range(9)] + [over]
        expected = [RULE.matches(d) for d in docs]

        async def scenario():
            first = asyncio.ensure_future(engine.submit(doc(100, True), "c"))
            await asyncio.sleep(0.02)  # window (1) saturated by the stub
            queued = [asyncio.ensure_future(engine.submit(d, "c"))
                      for d in docs]
            await asyncio.sleep(0.05)  # head-of-queue age passes target/2
            trigger = asyncio.ensure_future(engine.submit(doc(101, True), "c"))
            out = await asyncio.wait_for(asyncio.gather(*queued), timeout=5)
            await asyncio.gather(first, trigger)
            return [bool(r[0]) for r, _ in out]

        assert run(scenario()) == expected
        assert sample("auth_server_brownout_decisions_total",
                      {"lane": "engine"}) >= b0 + len(docs)
        assert engine._brownout_total >= len(docs)
        # brownout is not a device failure: breaker untouched, nothing
        # counted as degraded
        assert engine.breaker.state == "closed"
        # the saturating batch still rode the (stub) device
        assert stub.launched_batches >= 1

    def test_brownout_rescues_deadlines_the_device_could_not_meet(self):
        """The brownout shed horizon is 0, not the device RTT: a deadline
        the DEVICE's inflated round trip could not meet is exactly what the
        microsecond host lane exists to rescue — it must be SERVED, not
        shed DEADLINE_EXCEEDED."""
        engine = build_engine(max_batch=4, max_inflight_batches=1,
                              admission_target_s=0.001,
                              brownout_max_batch=16)
        SlowStubDevice(engine, latency_s=0.8)

        async def scenario():
            first = asyncio.ensure_future(engine.submit(doc(100, True), "c"))
            await asyncio.sleep(0.02)  # window (1) saturated
            queued = [asyncio.ensure_future(
                engine.submit(doc(i, True), "c",
                              deadline=time.monotonic() + 1.0))
                for i in range(4)]
            await asyncio.sleep(0.05)
            # the device RTT estimate inflates AFTER they queued: their 1s
            # deadlines are now inside one device round trip
            engine._device_ewma = 5.0
            trigger = asyncio.ensure_future(engine.submit(doc(101, True), "c"))
            out = await asyncio.wait_for(asyncio.gather(*queued), timeout=5)
            await asyncio.gather(first, trigger)
            return [bool(r[0]) for r, _ in out]

        assert run(scenario()) == [True] * 4
        assert engine._brownout_total >= 4

    def test_brownout_off_keeps_requests_queued(self):
        engine = build_engine(max_batch=4, max_inflight_batches=1,
                              admission_target_s=0.001, brownout=False)
        SlowStubDevice(engine, latency_s=0.3)

        async def scenario():
            tasks = [asyncio.ensure_future(engine.submit(doc(i, True), "c"))
                     for i in range(8)]
            await asyncio.sleep(0.1)
            # nothing spilled: exactly one batch in flight, rest queued
            assert engine._brownout_total == 0
            out = await asyncio.wait_for(asyncio.gather(*tasks), timeout=5)
            return out

        out = run(scenario())
        assert len(out) == 8

    def test_brownout_concurrency_is_bounded(self):
        engine = build_engine(max_batch=2, max_inflight_batches=1,
                              admission_target_s=0.001,
                              brownout_max_batch=2)
        SlowStubDevice(engine, latency_s=0.5)
        assert engine._brownout_limit >= 1

        async def scenario():
            tasks = [asyncio.ensure_future(engine.submit(doc(i, True), "c"))
                     for i in range(30)]
            peak = 0
            for _ in range(50):
                await asyncio.sleep(0.01)
                peak = max(peak, engine._brownout_inflight)
            await asyncio.wait_for(
                asyncio.gather(*tasks), timeout=10)
            return peak

        peak = run(scenario())
        assert peak <= engine._brownout_limit
        assert engine._brownout_inflight == 0


# ---------------------------------------------------------------------------
# adaptive controller end to end: slow-device step change
# ---------------------------------------------------------------------------


class TestAdaptiveIntegration:
    def test_controller_rides_a_slow_device_rtt_step(self):
        """faults.py slow-device inflates the measured round trip (the
        delay rides the readback handle, not the encode worker) and the
        controller grows the window to keep offered load in flight."""
        engine = build_engine(max_batch=8, max_inflight_batches=16)
        faults.FAULTS.arm("kernel:delay:delay=0.08")

        async def sustained(seconds):
            stop_at = time.monotonic() + seconds
            sem = asyncio.Semaphore(64)
            tasks = set()

            async def one(i):
                try:
                    await engine.submit(doc(i % 50, True), "c")
                finally:
                    sem.release()

            i = 0
            while time.monotonic() < stop_at:
                await sem.acquire()
                t = asyncio.ensure_future(one(i))
                tasks.add(t)
                t.add_done_callback(tasks.discard)
                i += 1
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)

        run(sustained(1.5))
        c = engine.controller
        # the injected readback delay is VISIBLE as device RTT…
        assert c.rtt_ewma >= 0.05
        assert engine._device_ewma >= 0.05
        # …and the window grew off its light-load floor to cover it, while
        # never leaving the clamp (64 in flight / 8 per batch → target ~8)
        assert 3 <= c.window <= 16

    def test_window_gauge_matches_controller(self):
        engine = build_engine(max_batch=8, max_inflight_batches=12)
        run(submit_all(engine, [doc(i, True) for i in range(8)]))
        assert sample("auth_server_adaptive_window",
                      {"lane": "engine"}) == engine.controller.window


# ---------------------------------------------------------------------------
# drain under overload
# ---------------------------------------------------------------------------


class TestDrainUnderOverload:
    def test_drain_resolves_backlog_including_brownout_jobs(self):
        engine = build_engine(max_batch=4, max_inflight_batches=1,
                              admission_target_s=0.001)
        SlowStubDevice(engine, latency_s=0.15)

        async def scenario():
            tasks = [asyncio.ensure_future(engine.submit(doc(i, True), "c"))
                     for i in range(24)]
            await asyncio.sleep(0.05)
            loop = asyncio.get_running_loop()
            drained = await loop.run_in_executor(None, engine.drain, 5.0)
            done = await asyncio.gather(*tasks, return_exceptions=True)
            return drained, done

        drained, done = run(scenario())
        assert drained is True
        assert engine._brownout_inflight == 0 and engine._inflight == 0
        assert all(not isinstance(r, Exception) for r in done)

    def test_drain_under_overload_stays_bounded_by_timeout(self):
        """A wedged device under a standing backlog: drain() must give up
        within its timeout — the recovery path never becomes the hang."""
        engine = build_engine(max_batch=4, max_inflight_batches=1,
                              brownout=False)
        SlowStubDevice(engine, latency_s=60.0)

        async def scenario():
            tasks = [asyncio.ensure_future(engine.submit(doc(i, True), "c"))
                     for i in range(12)]
            await asyncio.sleep(0.03)
            loop = asyncio.get_running_loop()
            t0 = time.monotonic()
            drained = await loop.run_in_executor(None, engine.drain, 0.3)
            elapsed = time.monotonic() - t0
            for t in tasks:
                t.cancel()
            return drained, elapsed

        drained, elapsed = run(scenario())
        assert drained is False
        assert elapsed < 2.0


# ---------------------------------------------------------------------------
# surfacing: /readyz + /debug/vars
# ---------------------------------------------------------------------------


class TestOverloadSurfacing:
    def test_readyz_surfaces_overload_but_stays_ready(self):
        from aiohttp.test_utils import TestClient, TestServer

        from authorino_tpu.service.http_server import build_app

        engine = build_engine()
        for t in (1.0, 1.4, 1.8):
            engine.admission.observe_waits([0.5], now=t)
        assert engine.admission.overloaded

        async def scenario():
            app = build_app(engine, readiness=lambda: True)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/readyz")
                body, status = await r.text(), r.status
                dv = await (await client.get("/debug/vars")).json()
            finally:
                await client.close()
            return status, body, dv

        status, body, dv = run(scenario())
        # overload stays READY: admission is shedding typed rejections so
        # accepted work meets its SLO — a 503 would just move the queue
        assert status == 200 and "overloaded" in body
        adm = dv["engine"]["admission"]
        assert adm["state"] == "overloaded"
        assert "queue_wait_ewma_s" in adm and "effective_cap" in adm
        assert dv["engine"]["adaptive"]["window"] >= 1
        assert dv["engine"]["brownout"]["enabled"] is True

    def test_admission_state_gauge(self):
        engine = build_engine()
        for t in (1.0, 1.4, 1.8):
            engine.admission.observe_waits([0.5], now=t)
        assert sample("auth_server_admission_state",
                      {"lane": "engine"}) == 1.0
        engine.admission.observe_waits([0.0], now=2.0)
        assert sample("auth_server_admission_state",
                      {"lane": "engine"}) == 0.0


# ---------------------------------------------------------------------------
# code lint: the overload layer rides the unbounded-wait gate
# ---------------------------------------------------------------------------


class TestOverloadLintGate:
    def lint(self, src):
        from authorino_tpu.analysis.code_lint import lint_source

        return lint_source(src, "planted.py")

    def test_admission_and_brownout_paths_are_drain_paths(self):
        src = (
            "def admit(self):\n"
            "    self._evt.wait()\n"
            "def brownout_spill(self):\n"
            "    self._t.join()\n"
            "def overload_probe(self):\n"
            "    self._evt.wait()\n"
            "def adaptive_step(self):\n"
            "    self._evt.wait()\n"
        )
        found = self.lint(src)
        assert [f.kind for f in found] == ["unbounded-wait"] * 4

    def test_repo_overload_code_stays_clean(self):
        import os

        from authorino_tpu.analysis.code_lint import lint_paths

        root = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "authorino_tpu")
        assert [str(f) for f in lint_paths([root])] == []
