"""Compile-time verification subsystem (ISSUE 4, analysis/).

Three layers under test: the tensor-IR lint (clean compiles pass; each
hand-corrupted snapshot trips EXACTLY its intended finding kind), the
Cedar-style policy semantic analysis (plants are found, sound rules are
not flagged), and the async-hazard code lint — including the tier-1 gate
that the repo itself stays finding-free.  Plus the --strict-verify swap
rejection (old generation keeps serving) and the packer's typed PackError.

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports)."""

from __future__ import annotations

import json
import random
from copy import deepcopy

import numpy as np
import pytest

from authorino_tpu.analysis.code_lint import lint_paths, lint_source
from authorino_tpu.analysis.fixtures import (
    finding_fixture_configs,
    fixture_configs,
    fixture_policy,
)
from authorino_tpu.analysis.policy_analysis import (
    MAX_ATOMS,
    analyze_hosts,
    analyze_policy,
    analyze_snapshot,
)
from authorino_tpu.analysis.tensor_lint import (
    lint_device_batch,
    lint_scatter_plan,
    lint_snapshot,
    tensor_lint,
)
from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.compiler.encode import encode_batch_py
from authorino_tpu.compiler.pack import (
    PackError,
    batch_row_keys,
    dedup_rows,
    pack_batch,
)
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.engine import SnapshotRejected


def _random_corpus(seed: int, n_configs: int = 7):
    """bench.py-shaped generated corpus: every operator, ~regex mix,
    nested And/Or, shared + unique constants."""
    rng = random.Random(seed)
    configs = []
    for i in range(n_configs):
        pats = [
            Pattern("request.method", Operator.EQ,
                    rng.choice(["GET", "POST"])),
            Pattern("auth.identity.org", Operator.EQ, f"org-{i}"),
        ]
        for j in range(rng.randrange(1, 6)):
            kind = rng.random()
            if kind < 0.15:
                pats.append(Pattern("request.url_path", Operator.MATCHES,
                                    rf"^/api/v\d+/r{j}"))
            elif kind < 0.45:
                pats.append(Pattern("auth.identity.roles", Operator.INCL,
                                    f"role-{rng.randrange(6)}"))
            elif kind < 0.65:
                pats.append(Pattern("auth.identity.groups", Operator.EXCL,
                                    f"banned-{rng.randrange(4)}"))
            else:
                pats.append(Pattern(f"request.headers.x-{rng.randrange(3)}",
                                    Operator.NEQ, f"v-{rng.randrange(5)}"))
        rule = All(pats[0], Any_(*pats[1:]))
        cond = (Pattern("request.host", Operator.EQ, f"h{i}")
                if rng.random() < 0.4 else None)
        configs.append(ConfigRules(name=f"cfg-{i}",
                                   evaluators=[(cond, rule)]))
    return configs


def _docs(seed: int, n: int):
    rng = random.Random(seed)
    return [
        {
            "request": {"method": rng.choice(["GET", "POST"]),
                        "url_path": rng.choice(["/api/v1/r0", "/x"]),
                        "host": f"h{rng.randrange(4)}",
                        "headers": {f"x-{k}": f"v-{rng.randrange(5)}"
                                    for k in range(3)}},
            "auth": {"identity": {
                "org": f"org-{rng.randrange(8)}",
                "roles": [f"role-{rng.randrange(6)}"
                          for _ in range(rng.randrange(3))],
                "groups": [f"banned-{rng.randrange(4)}"
                           for _ in range(rng.randrange(2))],
            }},
        }
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# tensor lint: property (generated corpora pass) + targeted corruptions
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_generated_corpora_pass_tensor_lint(seed):
    policy = compile_corpus(_random_corpus(seed), members_k=8)
    assert tensor_lint(policy) == []
    docs = _docs(seed, 12)
    rows = [random.Random(seed).randrange(policy.n_configs and 7)
            for _ in docs]
    enc = encode_batch_py(policy, docs, rows, batch_pad=16)
    db = pack_batch(policy, enc)
    assert lint_device_batch(policy, db) == []
    keys = batch_row_keys(db, len(docs))
    all_rows = list(range(len(docs)))
    unique_rows, inverse = dedup_rows(keys, all_rows)
    assert lint_scatter_plan(keys, all_rows, unique_rows, inverse) == []


def test_fixture_policy_clean():
    assert tensor_lint(fixture_policy()) == []


def test_corrupt_dfa_table_index():
    p = deepcopy(fixture_policy())
    p.dfa_table_of_row = p.dfa_table_of_row.copy()
    p.dfa_table_of_row[0] = p.dfa_tables.shape[0] + 3
    kinds = {f.kind for f in tensor_lint(p)}
    assert kinds == {"dfa-table-index"}


def test_corrupt_cyclic_circuit():
    p = deepcopy(fixture_policy())
    ch0 = p.levels[0][0].copy()
    ch0[0, 0] = p.buffer_size - 1  # forward reference = cycle
    p.levels = ((ch0, p.levels[0][1]),) + p.levels[1:]
    kinds = {f.kind for f in tensor_lint(p)}
    assert kinds == {"circuit-order"}


def test_corrupt_scatter_map():
    keys = [b"a", b"b", b"a", b"c"]
    rows = [0, 1, 2, 3]
    # row 2 (key a) wrongly fans out from unique slot 1 (key b)
    bad = np.array([0, 1, 1, 2])
    kinds = {f.kind for f in lint_scatter_plan(keys, rows, [0, 1, 3], bad)}
    assert kinds == {"scatter-cover"}
    # and the real dedup plan passes
    unique_rows, inverse = dedup_rows(keys, rows)
    assert lint_scatter_plan(keys, rows, unique_rows, inverse) == []


def test_corrupt_dfa_next_state():
    p = deepcopy(fixture_policy())
    p.dfa_tables = p.dfa_tables.copy()
    p.dfa_tables[0, 0, 0] = 255  # way past S
    kinds = {f.kind for f in tensor_lint(p)}
    assert kinds == {"dfa-next-state"}


def test_corrupt_eval_table_range():
    p = deepcopy(fixture_policy())
    p.eval_rule = p.eval_rule.copy()
    p.eval_rule[0, 0] = p.buffer_size + 10
    kinds = {f.kind for f in tensor_lint(p)}
    assert kinds == {"operand-range"}


# ---------------------------------------------------------------------------
# packer: typed PackError instead of silent clamp/wrap
# ---------------------------------------------------------------------------


def test_pack_error_member_grid_overflow():
    policy = fixture_policy()
    enc = encode_batch_py(policy, _docs(1, 2), [0, 1], batch_pad=2)
    bad = deepcopy(policy)
    bad.n_member_attrs = max(bad.member_attrs.shape[0] - 1, 0)
    with pytest.raises(PackError, match="padded grid"):
        pack_batch(bad, enc)
    # tensor lint agrees the same policy is invalid
    assert any(f.kind == "operand-range"
               for f in tensor_lint(bad, check_lanes=False))


def test_pack_error_int16_wraparound():
    policy = fixture_policy()
    assert len(policy.interner) < 32767  # int16 wire dtype in effect
    enc = encode_batch_py(policy, _docs(2, 2), [0, 1], batch_pad=2)
    # an int32-encoded batch (the sharded encode contract) carrying an id
    # past the int16 wire range: .astype(int16) would silently WRAP it to a
    # negative id — a wrong operand, not an error — before this check
    enc.attrs_val = enc.attrs_val.astype(np.int32)
    enc.attrs_val[0, 0] = 40_000
    with pytest.raises(PackError, match="int16"):
        pack_batch(policy, enc)


# ---------------------------------------------------------------------------
# policy semantic analysis
# ---------------------------------------------------------------------------


def test_policy_analysis_finds_planted_kinds():
    findings, summary = analyze_policy(
        compile_corpus(finding_fixture_configs()))
    kinds = {f.kind for f in findings}
    assert {"constant-allow", "constant-deny", "shadowed-rule",
            "duplicate-rule"} <= kinds
    assert summary["configs"] == 3
    # the shadowed finding names its shadower
    sh = next(f for f in findings if f.kind == "shadowed-rule")
    assert sh.detail["shadowed_by"] == 0 and sh.detail["config"] == "blocked"


def test_policy_analysis_sound_rules_not_flagged():
    findings, _ = analyze_policy(compile_corpus(_random_corpus(7)))
    # generated rules mix eq/incl over distinct constants: satisfiable and
    # falsifiable, so the analyzer must stay quiet
    assert findings == []


def test_policy_analysis_complementary_atoms():
    eq = Pattern("a.b", Operator.EQ, "x")
    neq = Pattern("a.b", Operator.NEQ, "x")
    incl = Pattern("a.c", Operator.INCL, "y")
    excl = Pattern("a.c", Operator.EXCL, "y")
    taut = compile_corpus([ConfigRules(name="t", evaluators=[
        (None, Any_(eq, neq)), (None, Any_(incl, excl))])])
    findings, _ = analyze_policy(taut)
    assert [f.kind for f in findings] == ["constant-allow", "constant-allow"]
    # a condition gating an unsat rule: contribution ¬cond ∨ rule is NOT
    # constant (requests failing the condition pass) — must not be flagged
    # as constant-deny
    gated = compile_corpus([ConfigRules(name="g", evaluators=[
        (incl, All(eq, neq))])])
    findings, _ = analyze_policy(gated)
    assert "constant-deny" not in {f.kind for f in findings}


def test_policy_analysis_skips_wide_support():
    pats = [Pattern(f"a.k{i}", Operator.EQ, f"v{i}")
            for i in range(MAX_ATOMS + 2)]
    findings, summary = analyze_policy(
        compile_corpus([ConfigRules(name="wide",
                                    evaluators=[(None, Any_(*pats))])]))
    assert findings == []
    assert summary["skipped_wide"] == 1


def test_duplicate_host_detection():
    class E:
        def __init__(self, id_, hosts):
            self.id, self.hosts = id_, hosts

    findings = analyze_hosts([E("ns/a", ["x.com", "y.com"]),
                              E("ns/b", ["y.com"]),
                              E("ns/c", [])])
    assert [f.kind for f in findings] == ["duplicate-host"]
    assert findings[0].detail["host"] == "y.com"
    assert findings[0].detail["configs"] == ["ns/a", "ns/b"]


# ---------------------------------------------------------------------------
# async-hazard code lint
# ---------------------------------------------------------------------------


_PLANTED = '''
import time, jax, threading
from functools import partial

async def a1():
    time.sleep(1)

async def a2(lock):
    lock.acquire()

async def ok_awaited(sem):
    await sem.acquire()

async def a3(self):
    with self._queue_lock:
        await later()

async def ok_lock_no_await(self):
    with self._queue_lock:
        x = 1

@jax.jit
def a4(x):
    if x > 0:
        return x
    return -x

@partial(jax.jit, static_argnames=())
def ok_static(params, x):
    if params["t"] is not None:
        return x
    if x.shape[0] > 2:
        return x
    return x

def a5():
    try:
        pass
    except:
        pass

async def ok_suppressed():
    time.sleep(1)  # lint-ok: blocking-in-async -- startup-only

async def ok_nested_sync():
    def helper():
        time.sleep(1)
    return helper
'''


def test_code_lint_planted_hazards():
    kinds = [f.kind for f in lint_source(_PLANTED, "planted.py")]
    assert sorted(kinds) == ["bare-except", "blocking-in-async",
                             "blocking-in-async", "lock-across-await",
                             "tracer-branch"]
    lines = {f.kind: f.location for f in lint_source(_PLANTED, "p.py")}
    assert lines["lock-across-await"].endswith(":15")


def test_code_lint_await_after_nested_def():
    # a nested def must prune only ITS subtree: an await elsewhere in the
    # same compound statement still counts (review-found false negative)
    src = (
        "async def f(self, fast):\n"
        "    with self._lock:\n"
        "        if fast:\n"
        "            def helper():\n"
        "                pass\n"
        "        else:\n"
        "            await later()\n"
    )
    assert [f.kind for f in lint_source(src)] == ["lock-across-await"]


def test_code_lint_static_accessor_prunes_only_its_subtree():
    # `.shape` makes y.shape[0] static, but x is still a traced param in
    # the same compare side (review-found false negative)
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x, y):\n"
        "    if x + y.shape[0] > 3:\n"
        "        return x\n"
        "    return y\n"
    )
    assert [f.kind for f in lint_source(src)] == ["tracer-branch"]


def test_code_lint_suppression_scopes():
    src = "async def f():\n    import time\n    time.sleep(1)  # lint-ok\n"
    assert lint_source(src) == []
    src = ("async def f():\n    import time\n"
           "    time.sleep(1)  # lint-ok: tracer-branch\n")
    # wrong kind in the suppression: the finding survives
    assert [f.kind for f in lint_source(src)] == ["blocking-in-async"]
    assert lint_source("# lint: skip-file\nasync def f():\n"
                       "    import time\n    time.sleep(1)\n") == []


def test_code_lint_pickle_import_kind():
    """ISSUE 19 satellite: every on-disk artifact (snapshots, capture
    segments, the decision corpus) is a pickle-free checksummed
    container by design — a module-level pickle import outside tests/
    is a lint error, not a style choice."""
    src = "import pickle\nfrom cloudpickle import dumps\nimport dill\n"
    kinds = [f.kind for f in lint_source(src, "authorino_tpu/x.py")]
    assert kinds == ["pickle-import"] * 3
    # tests/ may unpickle fixtures; paths under tests/ are exempt
    assert lint_source(src, "tests/test_x.py") == []
    assert lint_source(src, "pkg/tests/helper.py") == []
    # suppressible only explicitly, with the usual reasoned syntax
    ok = "import pickle  # lint-ok: pickle-import -- trusted local cache\n"
    assert lint_source(ok, "authorino_tpu/x.py") == []
    # a RELATIVE `from .pickle import x` is someone's own module, not
    # stdlib pickle — no finding
    assert lint_source("from .pickle import x\n",
                       "authorino_tpu/x.py") == []


def test_code_lint_non_atomic_write_kind():
    """ISSUE 20 satellite: a durable artifact written with a bare
    ``open(path, "w")`` is a torn-write waiting for a SIGKILL — every
    durable writer must ride utils/atomicio.py (or hand-roll the same
    tmp + fsync + os.replace discipline)."""
    src = ("def dump(snapshot_path, blob):\n"
           "    with open(snapshot_path, 'wb') as f:\n"
           "        f.write(blob)\n")
    kinds = [f.kind for f in lint_source(src, "authorino_tpu/x.py")]
    assert kinds == ["non-atomic-write"]
    # the full discipline in the same scope passes: fsync + os.replace
    ok = ("import os\n"
          "def dump(snapshot_path, blob):\n"
          "    with open(snapshot_path + '.tmp', 'wb') as f:\n"
          "        f.write(blob)\n"
          "        f.flush()\n"
          "        os.fsync(f.fileno())\n"
          "    os.replace(snapshot_path + '.tmp', snapshot_path)\n")
    assert lint_source(ok, "authorino_tpu/x.py") == []
    # str.replace is NOT os.replace: the finding survives
    bad = ("import os\n"
           "def dump(snapshot_path, blob):\n"
           "    with open(snapshot_path, 'wb') as f:\n"
           "        f.write(blob)\n"
           "        os.fsync(f.fileno())\n"
           "    snapshot_path.replace('.tmp', '')\n")
    assert [f.kind for f in lint_source(bad, "authorino_tpu/x.py")] \
        == ["non-atomic-write"]
    # non-durable paths (no durable-artifact word in scope) are exempt —
    # this lint hunts restart-critical state, not every scratch file
    scratch = ("def dump(p, blob):\n"
               "    with open(p, 'wb') as f:\n"
               "        f.write(blob)\n")
    assert lint_source(scratch, "authorino_tpu/x.py") == []
    # reads never fire, tests/ are exempt, suppression is reasoned
    assert lint_source("def load(manifest_path):\n"
                       "    return open(manifest_path).read()\n",
                       "authorino_tpu/x.py") == []
    assert lint_source(src, "tests/test_x.py") == []
    ok2 = ("def dump(snapshot_path, blob):\n"
           "    with open(snapshot_path, 'wb') as f:"
           "  # lint-ok: non-atomic-write -- sentinel file\n"
           "        f.write(blob)\n")
    assert lint_source(ok2, "authorino_tpu/x.py") == []


def test_repo_stays_lint_clean():
    """The tier-1 gate: the new code lint over authorino_tpu/ must report
    no findings — a new blocking call in an async path, a lock held across
    await, a tracer branch in a jitted fn, or a bare except FAILS CI until
    fixed or suppressed with a reasoned `# lint-ok: <kind>` comment."""
    import authorino_tpu

    root = authorino_tpu.__path__[0]
    findings = lint_paths([root])
    assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# --strict-verify: swap rejection keeps the old snapshot serving
# ---------------------------------------------------------------------------


def _entries(configs):
    return [EngineEntry(id=c.name, hosts=[f"{c.name}.example.com"],
                        runtime=None, rules=c) for c in configs]


def test_strict_verify_rejects_corrupt_swap(monkeypatch):
    from authorino_tpu.runtime import engine as engine_mod
    from authorino_tpu.utils import metrics as metrics_mod

    eng = PolicyEngine(mesh=None, strict_verify=True, analyze_policies=False)
    # this test simulates a COMPILER bug by monkeypatching compile_corpus:
    # the incremental compile cache (ISSUE 8) would honestly skip the
    # recompile of an identical corpus, so force the monolithic path
    eng.compile_cache = None
    eng.apply_snapshot(_entries(fixture_configs()))
    g1 = eng.generation
    snap1 = eng._snapshot
    assert g1 == 1 and snap1 is not None

    real = engine_mod.compile_corpus

    def corrupt(*a, **k):
        p = real(*a, **k)
        p.dfa_table_of_row = p.dfa_table_of_row.copy()
        p.dfa_table_of_row[0] = p.dfa_tables.shape[0] + 7
        return p

    monkeypatch.setattr(engine_mod, "compile_corpus", corrupt)
    with pytest.raises(SnapshotRejected) as ei:
        eng.apply_snapshot(_entries(fixture_configs()))
    assert {f.kind for f in ei.value.findings} == {"dfa-table-index"}
    # the OLD snapshot is still live: generation unbumped, index serving
    assert eng.generation == g1
    assert eng._snapshot is snap1
    assert eng.lookup("api.example.com") is not None
    # and the rejection is counted (noop-metrics images skip the read)
    try:
        from prometheus_client import REGISTRY

        v = REGISTRY.get_sample_value(
            "auth_server_snapshot_rejected_total", {"component": "engine"})
        assert v is not None and v >= 1
    except ImportError:
        pass

    # a clean corpus swaps again afterwards
    monkeypatch.setattr(engine_mod, "compile_corpus", real)
    eng.apply_snapshot(_entries(fixture_configs()))
    assert eng.generation == g1 + 1


def test_strict_verify_off_by_default():
    eng = PolicyEngine(mesh=None)
    assert eng.strict_verify is False
    eng.apply_snapshot(_entries(fixture_configs()))
    assert eng.generation == 1
    # unvetted snapshots are NOT marked lint_ok: a strict native frontend
    # must lint them itself at refresh time
    assert eng._snapshot.lint_ok is False


def test_strict_verify_marks_snapshot_vetted():
    # the native frontend's refresh skips re-linting snapshots the engine
    # already vetted (runtime/native_frontend.py _refresh_locked)
    eng = PolicyEngine(mesh=None, strict_verify=True, analyze_policies=False)
    eng.apply_snapshot(_entries(fixture_configs()))
    assert eng._snapshot.lint_ok is True


# ---------------------------------------------------------------------------
# reconcile-path analysis: once per swap, on /debug/vars, metrics counted
# ---------------------------------------------------------------------------


def test_engine_analysis_on_debug_vars(caplog):
    import logging

    eng = PolicyEngine(mesh=None)
    entries = _entries(fixture_configs() + finding_fixture_configs())
    entries[1].hosts.append("api.example.com")  # planted duplicate host
    with caplog.at_level(logging.WARNING, logger="authorino_tpu.engine"):
        eng.apply_snapshot(entries)
    pa = eng.debug_vars()["policy_analysis"]
    assert pa is not None and pa["generation"] == 1
    kinds = {f["kind"] for f in pa["findings"]}
    assert {"duplicate-host", "constant-allow", "constant-deny",
            "shadowed-rule", "duplicate-rule"} <= kinds
    # logged exactly once per reconcile, not per finding/request
    msgs = [r for r in caplog.records if "policy analysis" in r.message]
    assert len(msgs) == 1


def test_engine_analysis_never_breaks_reconcile(monkeypatch):
    from authorino_tpu.runtime import engine as engine_mod

    eng = PolicyEngine(mesh=None)

    def boom(*a, **k):
        raise RuntimeError("analyzer bug")

    monkeypatch.setattr(
        "authorino_tpu.analysis.policy_analysis.analyze_snapshot", boom)
    eng.apply_snapshot(_entries(fixture_configs()))  # must not raise
    assert eng.generation == 1
    assert eng.debug_vars()["policy_analysis"] is None


# ---------------------------------------------------------------------------
# CLI: python -m authorino_tpu.analysis
# ---------------------------------------------------------------------------


def test_cli_self_lint_json(capsys):
    from authorino_tpu.analysis.__main__ import main

    assert main(["--self-lint", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert report["ok"] is True and report["findings"] == []


def test_cli_verify_fixtures(capsys):
    from authorino_tpu.analysis.__main__ import main

    assert main(["--verify-fixtures"]) == 0
    assert "OK" in capsys.readouterr().out


def test_cli_reports_findings(tmp_path, capsys):
    from authorino_tpu.analysis.__main__ import main

    bad = tmp_path / "bad.py"
    bad.write_text("import time\nasync def f():\n    time.sleep(1)\n")
    assert main(["--self-lint", str(bad)]) == 1
    assert "blocking-in-async" in capsys.readouterr().out
