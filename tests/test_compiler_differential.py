"""Differential tests: CPU pattern oracle vs compiled TPU kernel.

The analog of the reference's OPA-vs-JSON benchmark comparison table
(SURVEY.md §4): same rule corpus + request batch must produce identical
allow/deny bitmasks on both paths.
"""

import random
import string

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus, encode_batch
from authorino_tpu.expressions import FALSE as FALSE_RULE
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.models import PolicyModel


def kernel_decide(policy, docs, rows):
    """Production decision path: compact encode -> kernel -> host-fallback
    merge for membership-overflow rows (models/policy_model.py)."""
    return PolicyModel(policy).decide_rows(docs, rows)

SELECTORS = [
    "request.method",
    "request.url_path",
    "request.headers.x-org",
    "request.headers.x-tier",
    "auth.identity.username",
    "auth.identity.roles",
    "auth.identity.groups",
    "auth.identity.age",
]

VALUES = ["GET", "POST", "DELETE", "/a", "/b/c", "acme", "umbrella", "gold",
          "john", "jane", "admin", "dev", "ops", "42", ""]


def random_pattern(rng):
    op = rng.choice([Operator.EQ, Operator.NEQ, Operator.INCL, Operator.EXCL, Operator.MATCHES])
    sel = rng.choice(SELECTORS)
    if op is Operator.MATCHES:
        # includes an invalid regex: error-propagation must match the oracle
        val = rng.choice([r"^/a", r"\d+", r"^(GET|POST)$", r"adm.n", r"^$", r"(["])
    else:
        val = rng.choice(VALUES)
    return Pattern(sel, op, val)


def random_expr(rng, depth=0):
    if depth >= 3 or rng.random() < 0.5:
        return random_pattern(rng)
    comb = All if rng.random() < 0.5 else Any_
    n = rng.randint(1, 4)
    return comb(*[random_expr(rng, depth + 1) for _ in range(n)])


def random_doc(rng):
    roles = rng.sample(["admin", "dev", "ops", "root", "qa"], k=rng.randint(0, 4))
    groups = [rng.choice(VALUES) for _ in range(rng.randint(0, 20))]  # may overflow K
    doc = {
        "request": {
            "method": rng.choice(["GET", "POST", "DELETE", "PUT"]),
            "url_path": rng.choice(["/a", "/b/c", "/x/9", ""]),
            "headers": {},
        },
        "auth": {"identity": {}},
    }
    if rng.random() < 0.8:
        doc["request"]["headers"]["x-org"] = rng.choice(VALUES + ["unseen-org-xyz"])
    if rng.random() < 0.5:
        doc["request"]["headers"]["x-tier"] = rng.choice(["gold", "silver"])
    ident = doc["auth"]["identity"]
    if rng.random() < 0.9:
        ident["username"] = rng.choice(["john", "jane", "nobody-seen"])
    if rng.random() < 0.9:
        ident["roles"] = roles
    if rng.random() < 0.6:
        ident["groups"] = groups
    if rng.random() < 0.5:
        ident["age"] = rng.choice([42, 17, 0.5, None])
    return doc


def oracle_verdict(cfg: ConfigRules, doc) -> bool:
    """Reference semantics: all-must-pass; conditions gate each evaluator
    (skip counts as pass); evaluation errors deny
    (ref: pkg/service/auth_pipeline.go:287-322, 120-125)."""
    for cond, rule in cfg.evaluators:
        if cond is not None:
            try:
                if not cond.matches(doc):
                    continue
            except Exception:
                continue  # condition error → evaluator skipped (ignored)
        try:
            if not rule.matches(doc):
                return False
        except Exception:
            return False
    return True


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_differential_random_corpora(seed):
    rng = random.Random(seed)
    n_configs = rng.randint(2, 12)
    configs = []
    for i in range(n_configs):
        n_evals = rng.randint(1, 4)
        evaluators = []
        for _ in range(n_evals):
            cond = random_expr(rng) if rng.random() < 0.4 else None
            evaluators.append((cond, random_expr(rng)))
        configs.append(ConfigRules(name=f"cfg-{i}", evaluators=evaluators))

    # small K forces membership overflow → host-fallback routing for those
    # rows (kernel rows stay differential; fallback rows test the routing)
    policy = compile_corpus(configs, members_k=8)

    docs = [random_doc(rng) for _ in range(64)]
    rows = [rng.randrange(n_configs) for _ in docs]
    own = kernel_decide(policy, docs, rows)

    for r, (doc, row) in enumerate(zip(docs, rows)):
        expected = oracle_verdict(configs[row], doc)
        assert bool(own[r]) == expected, (
            f"seed={seed} req={r} cfg={row}: kernel={bool(own[r])} oracle={expected}\n"
            f"evaluators={[(str(c) if c else None, str(ru)) for c, ru in configs[row].evaluators]}\n"
            f"doc={doc}"
        )


def test_empty_and_edge_expressions():
    from authorino_tpu.expressions import TRUE, FALSE

    configs = [
        ConfigRules("allow-all", evaluators=[(None, TRUE)]),
        ConfigRules("deny-all", evaluators=[(None, FALSE)]),
        ConfigRules("no-evaluators", evaluators=[]),
        ConfigRules("gated", evaluators=[(Pattern("request.method", Operator.EQ, "GET"), FALSE)]),
    ]
    policy = compile_corpus(configs)
    docs = [{"request": {"method": m}} for m in ("GET", "POST")]
    # NOTE: the encoder resolves only each request's own config's attributes —
    # other configs' verdict columns are garbage by design. Route per config.
    own = kernel_decide(policy, docs + docs + docs + docs, [0, 0, 1, 1, 2, 2, 3, 3])
    # allow-all allows everything; deny-all denies; no evaluators → allow
    assert own[0] and own[1]
    assert not own[2] and not own[3]
    assert own[4] and own[5]
    # gated: cond GET → rule FALSE denies; cond POST unmatched → skip → allow
    assert not own[6]
    assert own[7]


def test_interning_exactness_no_collisions():
    # unseen request values must not equal any constant
    configs = [ConfigRules("c", evaluators=[(None, Pattern("a.b", Operator.EQ, "secret-value"))])]
    policy = compile_corpus(configs)
    docs = [{"a": {"b": "secret-value"}}, {"a": {"b": "other"}}, {"a": {}}, {}]
    own = kernel_decide(policy, docs, [0, 0, 0, 0])
    assert own == [True, False, False, False]

    # eq "" matches a missing value (gjson String() of missing is "")
    configs = [ConfigRules("c", evaluators=[(None, Pattern("a.b", Operator.EQ, ""))])]
    policy = compile_corpus(configs)
    own = kernel_decide(policy, [{}, {"a": {"b": "x"}}], [0, 0])
    assert own == [True, False]


def test_membership_overflow_exact():
    # array longer than K must still evaluate incl/excl exactly via CPU lane
    K = 4
    configs = [
        ConfigRules("c", evaluators=[
            (None, Pattern("roles", Operator.INCL, "needle")),
            (None, Pattern("roles", Operator.EXCL, "banned")),
        ])
    ]
    policy = compile_corpus(configs, members_k=K)
    long_with_needle = {"roles": [f"r{i}" for i in range(10)] + ["needle"]}
    long_without = {"roles": [f"r{i}" for i in range(10)]}
    long_banned = {"roles": [f"r{i}" for i in range(10)] + ["needle", "banned"]}
    short_hit = {"roles": ["needle"]}
    docs = [long_with_needle, long_without, long_banned, short_hit]
    own = kernel_decide(policy, docs, [0] * 4)
    assert own == [True, False, False, True]


def test_regex_lane():
    configs = [
        ConfigRules("c", evaluators=[(None, Pattern("path", Operator.MATCHES, r"^/pets/\d+$"))]),
        ConfigRules("bad", evaluators=[(None, Pattern("path", Operator.MATCHES, "(["))]),
    ]
    policy = compile_corpus(configs)
    docs = [{"path": "/pets/1"}, {"path": "/pets/x"}, {"path": "/pets/2"}]
    own = kernel_decide(policy, docs, [0, 0, 1])
    # invalid regex → evaluation error → deny (ref: error return denies)
    assert own == [True, False, False]


def test_invalid_regex_error_propagation_matches_oracle():
    """Error propagation follows the reference's left-to-right short-circuit:
    Or(bad, true) errors (deny) but Or(true, bad) short-circuits (allow).
    Such trees ride a whole-tree CPU-fallback leaf — kernel must agree with
    the oracle in both directions (a naive constant-False leaf fails open)."""
    bad = Pattern("path", Operator.MATCHES, "([")
    true_leaf = Pattern("m", Operator.EQ, "GET")
    configs = [
        ConfigRules("or-bad-first", evaluators=[(None, Any_(bad, true_leaf))]),
        ConfigRules("or-bad-second", evaluators=[(None, Any_(true_leaf, bad))]),
        ConfigRules("and-bad", evaluators=[(None, All(true_leaf, bad))]),
        ConfigRules("cond-bad", evaluators=[(Any_(bad, true_leaf), FALSE_RULE)]),
    ]
    policy = compile_corpus(configs)
    doc = {"path": "/x", "m": "GET"}
    own = kernel_decide(policy, [doc] * 4, [0, 1, 2, 3])
    expected = [oracle_verdict(c, doc) for c in configs]
    assert own == expected
    # pin the concrete semantics too
    assert expected == [False, True, False, True]  # cond errors → skip → allow


def test_fast_resolver_negative_index_matches_selector():
    """items.-1 must resolve MISSING like selector.get, not Python-negative."""
    configs = [ConfigRules("c", evaluators=[(None, Pattern("items.-1", Operator.EQ, "b"))])]
    policy = compile_corpus(configs)
    own = kernel_decide(policy, [{"items": ["a", "b"]}], [0])
    assert not own[0]


# ---------------------------------------------------------------------------
# translation validation (ISSUE 6): the per-doc differential above samples
# the input space; certification proves circuit ≡ oracle over ALL atom
# assignments (and DFA tables against their regexes via witnesses), per
# config, for the same generated corpora.
# ---------------------------------------------------------------------------


def _random_corpus(seed):
    rng = random.Random(seed)
    configs = []
    for i in range(rng.randint(2, 12)):
        evaluators = []
        for _ in range(rng.randint(1, 4)):
            cond = random_expr(rng) if rng.random() < 0.4 else None
            evaluators.append((cond, random_expr(rng)))
        configs.append(ConfigRules(name=f"cfg-{i}", evaluators=evaluators))
    return configs


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_certify_generated_corpora(seed):
    """Property pass: every generated config (invalid regexes, DFA + CPU
    regex lanes, shared subtrees, conditions) earns a clean certificate."""
    from authorino_tpu.analysis.translation_validate import certify_snapshot

    policy = compile_corpus(_random_corpus(seed), members_k=8)
    certs, failures, stats = certify_snapshot(policy, use_cache=False,
                                              seed=seed)
    assert failures == [], "\n".join(str(f) for f in failures)
    assert stats["failed"] == 0
    assert len(certs) == len(policy.config_ids)
    assert all(c.ok and len(c.fingerprint) == 64 for c in certs)


def test_certify_rejects_every_planted_mutant():
    """...and the SAME validator rejects every planted miscompile class —
    a certifier that passes everything would pass the property above too."""
    from authorino_tpu.analysis.translation_validate import mutation_self_test

    findings = mutation_self_test()
    assert findings == [], "\n".join(str(f) for f in findings)
