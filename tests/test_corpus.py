"""Policy CI decision corpus (ISSUE 19, docs/policy_ci.md).

Covers the acceptance list: the 100k-record dedup proof (frequency
weights preserved exactly through distillation), corpus container
round-trip + typed rejection of corruption/magic/version/schema skew,
coverage-guided row synthesis (unexercised columns get oracle-verified
synthetic witnesses; uncoverable columns get typed reason codes, incl.
the relation-closure-implied case), the 3-seed cross-lane differential
(synthesized rows encode + decide bit-identically on fused, gather and
matmul, matching the host oracle AND the row's own recorded verdict /
attribution), the engine ``--corpus-pregate`` rejecting a planted
constant-deny edit on a ZERO-captured-traffic config on synthetic-origin
evidence alone (with /debug/vars and flight-recorder trails), and
``corpus_diff`` naming the exact generation that introduced a flip
across a 4-generation published snapshot chain.

Deliberately import-light; JAX_PLATFORMS=cpu."""

from __future__ import annotations

import hashlib
import json
import os
import random
import struct

import jax.numpy as jnp
import numpy as np
import pytest

from authorino_tpu.analysis.fixtures import (
    fixture_configs,
    fixture_policy,
    relations_fixture_policy,
)
from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.compiler.encode import encode_batch_py
from authorino_tpu.compiler.pack import pack_batch
from authorino_tpu.corpus import (
    CORPUS_SCHEMA,
    CorpusFormatError,
    distill_records,
    read_corpus,
    read_corpus_file,
    synthesize_rows,
    write_corpus,
)
from authorino_tpu.corpus.bisect import corpus_diff, load_generation_chain
from authorino_tpu.corpus.pregate import corpus_preflight, replay_corpus
from authorino_tpu.corpus.synthesize import augment_corpus, coverage_report
from authorino_tpu.corpus.store import MAGIC
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.models.policy_model import host_results
from authorino_tpu.ops import fused_kernel as fk
from authorino_tpu.ops import pattern_eval as pe
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.change_safety import GuardThresholds
from authorino_tpu.runtime.engine import SnapshotRejected
from authorino_tpu.snapshots.distribution import (
    SnapshotPublisher,
    serialize_policy,
)

# small-fixture thresholds: one flipped row must be judgeable
TH = GuardThresholds(min_requests=8, min_config_requests=1,
                     min_config_allows=1)


def api_doc(i=0):
    return {"request": {"method": "GET", "url_path": f"/api/v1/x{i}",
                        "host": "h", "headers": {"x-tag": "aa"}},
            "auth": {"identity": {"org": "acme", "roles": ["admin"],
                                  "groups": []}}}


def api_records(n, shapes=1):
    return [{"authconfig": "api", "doc": api_doc(i % shapes),
             "t": 1.0 + i * 1e-3} for i in range(n)]


def constant_deny_admin():
    """fixture_configs() with 'admin' evaluator 0 rewritten to the
    unsatisfiable All(org EQ acme, org NEQ acme) — the planted edit on a
    config no captured traffic ever hits."""
    org = Pattern("auth.identity.org", Operator.EQ, "acme")
    norg = Pattern("auth.identity.org", Operator.NEQ, "acme")
    cfgs = fixture_configs()
    for i, c in enumerate(cfgs):
        if c.name == "admin":
            cfgs[i] = ConfigRules(name="admin", evaluators=[
                (None, All(org, norg)), c.evaluators[1]])
    return cfgs


def entries_of(cfgs):
    return [EngineEntry(id=c.name, hosts=[c.name], runtime=None, rules=c)
            for c in cfgs]


# ---------------------------------------------------------------------------
# 1. distillation: the 100k dedup proof
# ---------------------------------------------------------------------------


def test_100k_records_distill_with_weights_preserved():
    policy = fixture_policy()
    n, shapes = 100_000, 8
    d = distill_records(api_records(n, shapes=shapes), policy)
    rows = d["rows"]
    assert len(rows) == shapes
    assert sum(r["weight"] for r in rows) == n
    assert d["dedup_ratio"] == n / shapes
    c = d["counters"]
    assert c["records_in"] == n
    assert c["distilled"] == shapes
    assert c["deduped"] == n - shapes
    assert c["dropped_unparseable"] == 0
    # canonical row keys, not content-hash fallbacks, and stable metadata
    assert c["fallback_keys"] == 0
    for r in rows:
        assert r["origin"] == "captured"
        assert r["row_key"] and not r["row_key"].startswith("doc:")
        assert r["first_seen"] <= r["last_seen"]
        # re-decided through the exact host oracle
        assert r["verdict"] == "allow" and r["rule_index"] == -1


def test_distill_accounts_unparseable_never_drops_silently():
    policy = fixture_policy()
    recs = api_records(4) + [{"authconfig": "api", "doc": None, "t": 9.0},
                             {"doc": api_doc(), "t": 9.0}]
    d = distill_records(recs, policy)
    assert d["counters"]["dropped_unparseable"] == 2
    assert sum(r["weight"] for r in d["rows"]) == 4


def test_distill_keeps_missing_config_rows_bisectable():
    """A row whose config the distilling snapshot no longer carries keeps
    its captured verdict (content-hash key) instead of vanishing — it
    must stay replayable against OLDER generations by --corpus-diff."""
    policy = fixture_policy()
    recs = [{"authconfig": "retired", "doc": api_doc(), "t": 1.0,
             "verdict": "deny", "rule_index": 0}]
    d = distill_records(recs, policy)
    (row,) = d["rows"]
    assert row["verdict"] == "deny"
    assert row["row_key"].startswith("doc:")
    assert d["counters"]["fallback_keys"] == 1


# ---------------------------------------------------------------------------
# 2. container: round-trip + typed rejection
# ---------------------------------------------------------------------------


def test_container_round_trip_bit_identical(tmp_path):
    policy = fixture_policy()
    rows = distill_records(api_records(16, shapes=4), policy)["rows"]
    p = str(tmp_path / "c.atpucorp")
    write_corpus(p, rows, meta={"note": "t"})
    header, back = read_corpus_file(p)
    assert back == rows
    assert header["count"] == 4 and header["meta"] == {"note": "t"}
    # directory read concatenates containers oldest-name-first
    write_corpus(str(tmp_path / "a.atpucorp"), rows[:1])
    assert read_corpus(str(tmp_path)) == rows[:1] + rows


@pytest.mark.parametrize("mutate", ["truncate", "magic", "flip", "version",
                                    "schema"])
def test_container_rejects_skew_typed(tmp_path, mutate):
    policy = fixture_policy()
    rows = distill_records(api_records(4), policy)["rows"]
    p = str(tmp_path / "c.atpucorp")
    write_corpus(p, rows)
    blob = open(p, "rb").read()
    if mutate == "truncate":
        blob = blob[:10]
    elif mutate == "magic":
        blob = b"NOTACORP1\x00" + blob[len(MAGIC):]
    elif mutate == "flip":
        b = bytearray(blob)
        b[len(b) // 2] ^= 0xFF
        blob = bytes(b)
    else:
        # rebuild with a skewed header and a VALID checksum: the typed
        # version/schema gate must fire, not the checksum one
        (hlen,) = struct.unpack_from("<Q", blob, len(MAGIC))
        start = len(MAGIC) + 8
        header = json.loads(blob[start:start + hlen])
        header["version" if mutate == "version" else "schema"] += 1
        hb = json.dumps(header, sort_keys=True,
                        separators=(",", ":")).encode()
        body = MAGIC + struct.pack("<Q", len(hb)) + hb \
            + blob[start + hlen:-32]
        blob = body + hashlib.sha256(body).digest()
    with open(p, "wb") as f:
        f.write(blob)
    with pytest.raises(CorpusFormatError):
        read_corpus_file(p)


# ---------------------------------------------------------------------------
# 3. coverage + synthesis
# ---------------------------------------------------------------------------


def test_synthesis_covers_unexercised_columns_verified_by_oracle():
    policy = fixture_policy()
    captured = distill_records(api_records(32), policy)["rows"]
    aug = augment_corpus(policy, captured)
    assert aug["coverage_after"]["fraction"] \
        > aug["coverage_before"]["fraction"]
    for row in aug["rows"]:
        assert row["schema"] == CORPUS_SCHEMA
        assert row["origin"] == "synthetic" and row["weight"] == 1
        # every synthetic row re-verifies through the exact host oracle:
        # the recorded verdict AND first-false attribution hold
        own, rule_res, skipped = host_results(
            policy, row["doc"], policy.config_ids[row["authconfig"]])
        assert (row["verdict"] == "allow") == bool(own)
        fire = int(pe.firing_columns(rule_res[None, :], skipped[None, :])[0])
        assert fire == row["rule_index"]
    # each config gets an allow witness (the row a constant-deny flips)
    allows = {r["authconfig"] for r in aug["rows"]
              if r["verdict"] == "allow"}
    assert {"admin", "public"} <= allows
    # deny witnesses for the never-fired admin columns
    fired = {(r["authconfig"], r["rule_index"]) for r in aug["rows"]
             if r["verdict"] == "deny"}
    assert ("admin", 0) in fired and ("admin", 1) in fired


def test_uncoverable_columns_get_typed_reasons_never_skipped():
    # 'public' is All() — a tautology can never be the first-false column
    policy = fixture_policy()
    _, report = synthesize_rows(policy)
    assert report["targets"] == report["synthesized"] \
        + len(report["uncoverable"])
    assert {"config": "public", "evaluator": 0,
            "reason": "unsatisfiable"} in report["uncoverable"]
    # the relation-closure-implied case: hier evaluator 1 wants
    # InGroup(staff) true with InGroup(all) false, but the closure makes
    # staff a subset of all — infeasible in a way the boolean atom model
    # cannot see, caught at oracle-verification time with its own reason
    rpolicy = relations_fixture_policy()
    _, rreport = synthesize_rows(rpolicy)
    reasons = {(u["config"], u["evaluator"]): u["reason"]
               for u in rreport["uncoverable"]}
    assert reasons.get(("hier", 1)) == "materialization-failed"


def test_coverage_report_marks_exercised_columns():
    policy = fixture_policy()
    rows, _ = synthesize_rows(policy, targets=[("api", 0)])
    cov = coverage_report(policy, rows)
    api = cov["configs"]["api"]
    assert api["columns"][0]["exercised"]
    assert api["unexercised"] == [1]
    assert cov["columns_exercised"] == 1


# ---------------------------------------------------------------------------
# 4. cross-lane validity: synthesized rows ride every lane bit-identically
# ---------------------------------------------------------------------------


def _rand_corpus(rng: random.Random, n_configs=5):
    """Seeded random corpus over the synthesizable atom classes: interned
    equality, membership, DFA-decidable regex, int-lane numerics."""
    orgs = ("acme", "beta", "gamma")
    roles = ("admin", "dev", "ops")
    rxs = (r"^/api/v[0-9]+/", r"^/public/", r"^/v2/[a-z]+$")
    cfgs = []
    for i in range(n_configs):
        evs = [
            (None, All(Pattern("auth.identity.org", Operator.EQ,
                               rng.choice(orgs)),
                       Pattern("auth.identity.roles", Operator.INCL,
                               rng.choice(roles)))),
            (None, Any_(Pattern("request.size", Operator.GE,
                                str(rng.choice((10, 1024)))),
                        Pattern("request.url_path", Operator.MATCHES,
                                rng.choice(rxs)))),
        ]
        if rng.random() < 0.5:
            evs.reverse()
        cfgs.append(ConfigRules(name=f"c{i}", evaluators=evs))
    return cfgs


@pytest.mark.parametrize("seed", [7, 19, 31])
def test_synthesized_rows_bit_identical_across_lanes_and_oracle(seed):
    rng = random.Random(seed)
    policy = compile_corpus(_rand_corpus(rng), members_k=4, ovf_assist=True)
    rows, report = synthesize_rows(policy)
    assert report["synthesized"] >= len(policy.config_ids)  # not vacuous
    docs = [r["doc"] for r in rows]
    gids = [policy.config_ids[r["authconfig"]] for r in rows]
    db = pack_batch(policy, encode_batch_py(policy, docs, gids))
    assert not db.host_fallback.any()
    has_dfa = policy.n_byte_attrs > 0
    args = (jnp.asarray(db.attrs_val), jnp.asarray(db.members_c),
            jnp.asarray(db.cpu_dense), jnp.asarray(db.config_id),
            jnp.asarray(db.attr_bytes) if has_dfa else None,
            jnp.asarray(db.byte_ovf) if has_dfa else None,
            *pe._extra_operands(db))
    packed_f = np.asarray(fk.eval_fused_kernel(
        pe.to_device(policy, lane="fused"), db))
    for lane in ("gather", "matmul"):
        packed_l = np.asarray(pe.eval_bitpacked_jit(
            pe.to_device(policy, lane=lane), *args))
        np.testing.assert_array_equal(packed_f, packed_l, err_msg=lane)
    E = int(policy.eval_rule.shape[1])
    verdict, firing = pe.unpack_attribution(packed_f, E)
    for i, row in enumerate(rows):
        # the kernel agrees with the row's RECORDED verdict/attribution
        # (which synthesis already verified against the host oracle) —
        # so corpus rows mean the same thing on every lane
        assert bool(verdict[i]) == (row["verdict"] == "allow"), (seed, i)
        assert int(firing[i]) == row["rule_index"], (seed, i)


# ---------------------------------------------------------------------------
# 5. the pregate: weighted replay + the zero-traffic catch
# ---------------------------------------------------------------------------


def test_replay_corpus_weights_flips_by_frequency():
    old = fixture_policy()
    new = compile_corpus(constant_deny_admin())
    rows = distill_records(api_records(16), old)["rows"]
    admin_doc = api_doc()
    admin_doc["request"]["host"] = "/api/v1/h"  # baseline-allow on admin
    rows += [{"schema": CORPUS_SCHEMA, "authconfig": "admin",
              "doc": admin_doc, "verdict": "allow", "rule_index": -1,
              "rule": "", "weight": 40_000, "first_seen": 1.0,
              "last_seen": 2.0, "origin": "captured", "row_key": "k",
              "generation": 1}]
    rep = replay_corpus(old, new, rows)
    # one flipped ROW counts with its full collapsed frequency
    assert rep["flips"]["newly_denied"] == 40_000
    assert rep["replayed"] == 40_016 and rep["replayed_rows"] == 2
    assert rep["per_config"]["admin"]["newly_denied"] == 40_000
    assert rep["origins"]["captured"]["flips"] == 40_000
    assert rep["load_model"] == "corpus"


def test_corpus_preflight_catches_zero_traffic_edit_on_synth_rows_only():
    baseline = fixture_policy()
    candidate = compile_corpus(constant_deny_admin())
    captured = distill_records(api_records(32), baseline)["rows"]
    # captured evidence alone is BLIND: no admin traffic ever happened
    blind = corpus_preflight(baseline, candidate, captured, TH,
                             changed={"admin"})
    assert blind["breach"] is None
    # + synthesized witnesses: caught, attributed, provably synthetic
    synth = augment_corpus(baseline, captured)["rows"]
    pf = corpus_preflight(baseline, candidate, captured + synth, TH,
                         changed={"admin"})
    breach = pf["breach"]
    assert breach is not None and "admin" in breach["suspects"]
    origins = pf["report"]["origins"]
    assert origins["captured"]["flips"] == 0
    assert origins["synthetic"]["flips"] >= 1
    # clean churn (fresh tree objects, same semantics) stays silent
    clean = corpus_preflight(baseline, compile_corpus(fixture_configs()),
                             captured + synth, TH, changed={"admin"})
    assert clean["breach"] is None


def test_engine_corpus_pregate_rejects_with_zero_live_exposure(tmp_path):
    corpus_path = str(tmp_path / "c.atpucorp")
    baseline = fixture_policy()
    write_corpus(corpus_path,
                 distill_records(api_records(32), baseline)["rows"])
    engine = PolicyEngine(mesh=None, max_batch=8, lane_select=False,
                          analyze_policies=False, metadata_prefetch=False,
                          canary_thresholds=TH,
                          corpus_pregate=corpus_path)
    engine.apply_snapshot(entries_of(fixture_configs()))
    gen_before = engine.generation
    with pytest.raises(SnapshotRejected) as ei:
        engine.apply_snapshot(entries_of(constant_deny_admin()))
    # the typed rejection carries the weighted corpus diff
    assert "admin" in ei.value.corpus_diff["suspects"]
    assert engine.generation == gen_before
    dv = engine.debug_vars()["corpus"]
    assert dv["enabled"] and dv["rows_captured"] >= 1
    assert dv["rows_synthetic"] >= 1
    assert dv["last"]["result"] == "breach"
    # the catch came from synthetic-origin evidence (zero live traffic)
    assert dv["last"]["origins"]["synthetic"]["flips"] >= 1
    assert dv["last"]["origins"]["captured"]["flips"] == 0
    # a clean re-apply of the original semantics still lands
    engine.apply_snapshot(entries_of(fixture_configs()))
    assert engine.generation > gen_before


def test_engine_corpus_pregate_missing_file_skips_never_blocks(tmp_path):
    engine = PolicyEngine(mesh=None, max_batch=8, lane_select=False,
                          analyze_policies=False, metadata_prefetch=False,
                          canary_thresholds=TH,
                          corpus_pregate=str(tmp_path / "absent.atpucorp"))
    engine.apply_snapshot(entries_of(fixture_configs()))
    engine.apply_snapshot(entries_of(constant_deny_admin()))  # must land
    dv = engine.debug_vars()["corpus"]
    assert dv["last"]["result"] == "skipped"
    assert dv["load_error"]


# ---------------------------------------------------------------------------
# 6. history bisect: --corpus-diff names the exact generation
# ---------------------------------------------------------------------------


def _publish_chain(directory, bad_from=3, n=4):
    pub = SnapshotPublisher(directory, keep=n + 2)
    for gen in range(1, n + 1):
        cfgs = constant_deny_admin() if gen >= bad_from \
            else fixture_configs()
        pub.publish_blob(
            serialize_policy(compile_corpus(cfgs),
                             meta={"generation": gen}), gen, {})


def test_corpus_diff_attributes_flip_to_exact_generation(tmp_path):
    _publish_chain(str(tmp_path), bad_from=3, n=4)
    chain = load_generation_chain(str(tmp_path))
    assert [s.generation for s in chain] == [1, 2, 3, 4]
    baseline = fixture_policy()
    captured = distill_records(api_records(32), baseline)["rows"]
    rows = captured + augment_corpus(baseline, captured)["rows"]
    report = corpus_diff(chain, rows)
    assert report["flipped_rows"] >= 1
    assert set(report["by_generation"]) == {"3"}
    flip = report["flips"][0]
    assert (flip["generation"], flip["from_generation"]) == (3, 2)
    assert flip["authconfig"] == "admin"
    assert flip["direction"] == "newly-denied"
    assert flip["origins"] == ["synthetic"]


def test_corpus_diff_clean_chain_reports_no_flips(tmp_path):
    _publish_chain(str(tmp_path), bad_from=99, n=4)
    baseline = fixture_policy()
    captured = distill_records(api_records(8), baseline)["rows"]
    rows = captured + augment_corpus(baseline, captured)["rows"]
    report = corpus_diff(load_generation_chain(str(tmp_path)), rows)
    assert report["flips"] == [] and report["flipped_rows"] == 0


# ---------------------------------------------------------------------------
# 7. the verify-fixtures wiring stays armed
# ---------------------------------------------------------------------------


def test_verify_fixtures_corpus_selftest_is_clean_and_not_blind():
    from authorino_tpu.analysis.__main__ import (
        _corpus_selftest,
        _pickle_lint_selftest,
    )
    from authorino_tpu.corpus import synthesize as syn

    policy = fixture_policy()
    assert _corpus_selftest(policy) == []
    assert _pickle_lint_selftest() == []
    # a BLIND synthesizer must fail the self-test (and with it tier-1)
    real = syn.augment_corpus

    def blind(policy, rows, **kw):
        out = real(policy, rows, **kw)
        out["rows"] = []
        out["coverage_after"] = out["coverage_before"]
        return out

    syn.augment_corpus = blind
    try:
        assert _corpus_selftest(policy)
    finally:
        syn.augment_corpus = real
