"""Control-plane tests: translation, v1beta1↔v1beta2 conversion, reconciler
status/bootstrap/collision, secret reconciler live rotation, YAML source."""

import asyncio
import base64
import json
import os

import pytest

from authorino_tpu.apis import to_v1beta1, to_v1beta2
from authorino_tpu.controllers import (
    AuthConfigReconciler,
    SecretReconciler,
    TranslationError,
    translate_auth_config,
)
from authorino_tpu.controllers.reconciler import (
    STATUS_CACHING_ERROR,
    STATUS_HOSTS_NOT_LINKED,
    STATUS_RECONCILED,
)
from authorino_tpu.k8s import InMemoryCluster, LabelSelector, Secret
from authorino_tpu.runtime import PolicyEngine
from authorino_tpu.authjson import CheckRequestModel, HttpRequestAttributes


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


V2_SPEC = {
    "hosts": ["talker-api.example.com"],
    "patterns": {
        "admin-path": [{"selector": "request.url_path", "operator": "matches", "value": "^/admin"}]
    },
    "when": [{"selector": "request.method", "operator": "neq", "value": "OPTIONS"}],
    "authentication": {
        "api-clients": {
            "apiKey": {"selector": {"matchLabels": {"audience": "talker-api"}}},
            "credentials": {"authorizationHeader": {"prefix": "APIKEY"}},
        },
        "anon": {"anonymous": {}, "priority": 1},
    },
    "authorization": {
        "admin-only": {
            "patternMatching": {
                "patterns": [
                    {
                        "any": [
                            {"selector": "auth.identity.metadata.labels.role", "operator": "eq", "value": "admin"},
                            {"selector": "auth.identity.anonymous", "operator": "neq", "value": "true"},
                        ]
                    }
                ]
            },
            "when": [{"patternRef": "admin-path"}],
        }
    },
    "response": {
        "unauthorized": {"code": 302, "message": {"value": "redirect"}},
        "success": {
            "headers": {"x-auth": {"json": {"properties": {"user": {"selector": "auth.identity.anonymous"}}}}}
        },
    },
}


def make_cluster():
    cluster = InMemoryCluster()
    cluster.put_secret(
        Secret(
            name="client-1",
            namespace="tenant",
            labels={"audience": "talker-api", "role": "admin",
                    "authorino.kuadrant.io/managed-by": "authorino"},
            data={"api_key": b"secret-key-1"},
        )
    )
    return cluster


class TestTranslate:
    def test_full_translate(self):
        engine = PolicyEngine()
        entry = run(
            translate_auth_config("ac", "tenant", V2_SPEC, cluster=make_cluster(), engine=engine)
        )
        assert entry.id == "tenant/ac"
        assert entry.hosts == ["talker-api.example.com"]
        assert [c.name for c in entry.runtime.identity] == ["api-clients", "anon"]
        assert entry.runtime.identity[0].credentials.key_selector == "APIKEY"
        assert entry.runtime.conditions is not None
        assert entry.rules is not None and len(entry.rules.evaluators) == 1
        cond, rules = entry.rules.evaluators[0]
        assert cond is not None  # when: [patternRef admin-path]

    def test_translate_errors(self):
        with pytest.raises(TranslationError, match="missing hosts"):
            run(translate_auth_config("x", "ns", {"authentication": {"a": {"anonymous": {}}}}))
        with pytest.raises(TranslationError, match="pattern not found"):
            run(
                translate_auth_config(
                    "x",
                    "ns",
                    {
                        "hosts": ["h"],
                        "authorization": {
                            "z": {"patternMatching": {"patterns": [{"patternRef": "nope"}]}}
                        },
                    },
                )
            )
        with pytest.raises(TranslationError, match="invalid rego"):
            run(
                translate_auth_config(
                    "x",
                    "ns",
                    {"hosts": ["h"], "authorization": {"z": {"opa": {"rego": "default z = input.y"}}}},
                )
            )


class TestConversion:
    def test_v1beta1_roundtrip(self):
        v2 = {
            "apiVersion": "authorino.kuadrant.io/v1beta2",
            "kind": "AuthConfig",
            "metadata": {"name": "ac", "namespace": "ns"},
            "spec": V2_SPEC,
        }
        v1 = to_v1beta1(v2)
        assert v1["apiVersion"].endswith("v1beta1")
        spec1 = v1["spec"]
        assert {i["name"] for i in spec1["identity"]} == {"api-clients", "anon"}
        assert spec1["authorization"][0]["json"]["rules"]
        assert spec1["denyWith"]["unauthorized"]["code"] == 302
        back = to_v1beta2(v1)
        spec2 = back["spec"]
        assert set(spec2["authentication"]) == {"api-clients", "anon"}
        assert spec2["authentication"]["api-clients"]["credentials"] == {
            "authorizationHeader": {"prefix": "APIKEY"}
        }
        assert spec2["authorization"]["admin-only"]["patternMatching"]["patterns"]
        assert spec2["response"]["unauthorized"]["code"] == 302
        assert spec2["response"]["success"]["headers"]["x-auth"]["json"]["properties"]["user"] == {
            "selector": "auth.identity.anonymous"
        }


def resource(name="ac", namespace="tenant", spec=None, labels=None):
    return {
        "apiVersion": "authorino.kuadrant.io/v1beta2",
        "kind": "AuthConfig",
        "metadata": {"name": name, "namespace": namespace, "labels": labels or {}},
        "spec": spec or dict(V2_SPEC),
    }


class TestReconciler:
    def test_reconcile_status_and_serving(self):
        async def body():
            engine = PolicyEngine(max_batch=4)
            cluster = make_cluster()
            rec = AuthConfigReconciler(engine, cluster=cluster)
            await rec.reconcile_all([resource()])
            assert rec.status.get("tenant/ac").reason == STATUS_RECONCILED
            assert rec.ready()
            status = rec.status.status_object("tenant/ac")
            assert status["summary"]["hostsReady"] == ["talker-api.example.com"]

            # serving end-to-end through the engine: API key + admin role
            req = CheckRequestModel(
                http=HttpRequestAttributes(
                    method="GET",
                    path="/admin/x",
                    host="talker-api.example.com",
                    headers={"authorization": "APIKEY secret-key-1"},
                )
            )
            result = await engine.check(req)
            assert result.success(), result.message

            # wrong api key → anonymous matches instead (priority 1) and the
            # admin-only pattern denies under /admin
            req2 = CheckRequestModel(
                http=HttpRequestAttributes(
                    method="GET", path="/admin/x", host="talker-api.example.com",
                    headers={"authorization": "APIKEY wrong"},
                )
            )
            result2 = await engine.check(req2)
            assert not result2.success()
            assert result2.status == 302  # denyWith

            # outside /admin → authz condition unmatched → allow
            req3 = CheckRequestModel(
                http=HttpRequestAttributes(
                    method="GET", path="/public", host="talker-api.example.com",
                    headers={"authorization": "APIKEY wrong"},
                )
            )
            result3 = await engine.check(req3)
            assert result3.success()

        run(body())

    def test_translate_error_status(self):
        async def body():
            engine = PolicyEngine()
            rec = AuthConfigReconciler(engine)
            bad = resource(spec={"hosts": ["h.example.com"], "authorization": {"z": {"opa": {"rego": "default z = input.y"}}}})
            await rec.reconcile_all([bad])
            assert rec.status.get("tenant/ac").reason == STATUS_CACHING_ERROR
            assert not rec.ready()

        run(body())

    def test_host_collision(self):
        async def body():
            engine = PolicyEngine()
            spec = {"hosts": ["shared.example.com"], "authentication": {"anon": {"anonymous": {}}}}
            r1 = resource(name="first", spec=dict(spec))
            r2 = resource(name="second", spec=dict(spec))
            rec = AuthConfigReconciler(engine)
            await rec.reconcile_all([r1, r2])
            reasons = {id_: rep.reason for id_, rep in rec.status.all().items()}
            assert reasons["tenant/first"] == STATUS_RECONCILED
            assert reasons["tenant/second"] == STATUS_HOSTS_NOT_LINKED

        run(body())

    def test_label_selector_sharding(self):
        async def body():
            engine = PolicyEngine()
            rec = AuthConfigReconciler(engine, label_selector=LabelSelector.parse("group=a"))
            spec = {"hosts": ["a.example.com"], "authentication": {"anon": {"anonymous": {}}}}
            watched = resource(name="mine", spec=dict(spec), labels={"group": "a"})
            unwatched = resource(
                name="other",
                spec={"hosts": ["b.example.com"], "authentication": {"anon": {"anonymous": {}}}},
                labels={"group": "b"},
            )
            await rec.reconcile_all([watched, unwatched])
            assert engine.lookup("a.example.com") is not None
            assert engine.lookup("b.example.com") is None

        run(body())


class TestSecretReconciler:
    def test_live_rotation_through_cluster_events(self):
        async def body():
            engine = PolicyEngine(max_batch=4)
            cluster = make_cluster()
            rec = AuthConfigReconciler(engine, cluster=cluster)
            sec_rec = SecretReconciler(
                engine,
                secret_label_selector=LabelSelector.parse("authorino.kuadrant.io/managed-by=authorino"),
            )
            cluster.on_secret_event(sec_rec.on_event)
            await rec.reconcile_all([resource()])

            def check(key):
                # /admin path: valid API key → allow; anonymous fallback → deny
                req = CheckRequestModel(
                    http=HttpRequestAttributes(
                        method="GET", path="/admin/x", host="talker-api.example.com",
                        headers={"authorization": f"APIKEY {key}"},
                    )
                )
                return engine.check(req)

            assert (await check("secret-key-1")).success()
            # rotate the key → old revoked, new works (ref secret_controller.go)
            cluster.put_secret(
                Secret(
                    name="client-1",
                    namespace="tenant",
                    labels={"audience": "talker-api", "authorino.kuadrant.io/managed-by": "authorino"},
                    data={"api_key": b"rotated-key"},
                )
            )
            r = await check("secret-key-1")
            assert not r.success()
            assert (await check("rotated-key")).success()
            # delete the secret → revoked (falls back to deny since the
            # admin-only rule's 'anonymous neq true' fails for anonymous)
            cluster.remove_secret("tenant", "client-1")
            r = await check("rotated-key")
            assert not r.success()

        run(body())


class TestYamlSource:
    def test_load_and_serve_from_dir(self, tmp_path):
        async def body():
            import yaml as yaml_mod

            from authorino_tpu.controllers.sources import YamlDirSource

            secret = {
                "apiVersion": "v1",
                "kind": "Secret",
                "metadata": {
                    "name": "client-1",
                    "namespace": "tenant",
                    "labels": {"audience": "talker-api", "authorino.kuadrant.io/managed-by": "authorino"},
                },
                "data": {"api_key": base64.b64encode(b"from-yaml").decode()},
            }
            (tmp_path / "manifests.yaml").write_text(
                yaml_mod.dump_all([resource(), secret], default_flow_style=False)
            )
            engine = PolicyEngine(max_batch=4)
            cluster = InMemoryCluster()
            rec = AuthConfigReconciler(engine, cluster=cluster)
            sec_rec = SecretReconciler(
                engine,
                secret_label_selector=LabelSelector.parse("authorino.kuadrant.io/managed-by=authorino"),
            )
            source = YamlDirSource(str(tmp_path), rec, cluster, sec_rec)
            await source.sync()
            req = CheckRequestModel(
                http=HttpRequestAttributes(
                    method="GET", path="/x", host="talker-api.example.com",
                    headers={"authorization": "APIKEY from-yaml"},
                )
            )
            assert (await engine.check(req)).success()

        run(body())


class AdversarialCluster:
    """Scripted fake API server for K8sWatchSource: serves pre-planned
    lists and watch streams that inject 410 Gone mid-watch, raw connection
    drops, and re-lists replaying unchanged state — the failure modes a
    real apiserver exhibits (envtest-style adversarial soak)."""

    def __init__(self, lists, watches, swap_counter):
        self.lists = list(lists)          # [(items, rv)]
        self.watches = list(watches)      # [[("yield", type, obj)|("raise",)]]
        self.swap_counter = swap_counter
        self.list_params = []
        self.watch_params = []
        self.swaps_at_last_list = None
        self.done = asyncio.Event()       # set when the last watch parks

    def _ac_path(self, namespace=None, name=None):
        return "/apis/authorino.kuadrant.io/v1beta1/authconfigs"

    async def list_auth_configs_rv(self, selector):
        self.list_params.append(selector)
        entry = self.lists.pop(0) if self.lists else self.lists_last
        if entry == "raise":  # scripted apiserver outage during re-list
            raise RuntimeError("apiserver unavailable")
        items, rv = entry
        self.lists_last = (items, rv)
        if not self.lists:
            # capture the swap count as the FINAL list is served: the
            # unchanged re-list must not trigger another corpus swap
            self.swaps_at_last_list = self.swap_counter[0]
        return list(items), rv

    async def watch(self, path, params=None):
        self.watch_params.append(dict(params or {}))
        if not self.watches:
            self.done.set()
            await asyncio.Event().wait()  # park forever
        script = self.watches.pop(0)
        for action in script:
            if action[0] == "yield":
                yield action[1], action[2]
            elif action[0] == "raise":
                raise RuntimeError("connection reset by peer")


def v1_ac(name, rv, hosts):
    return {
        "apiVersion": "authorino.kuadrant.io/v1beta1",
        "kind": "AuthConfig",
        "metadata": {"namespace": "t", "name": name, "resourceVersion": rv},
        "spec": {"hosts": hosts},
    }


class TestAdversarialWatch:
    def test_gone_drops_and_stale_relists(self):
        from authorino_tpu.controllers.sources import K8sWatchSource

        async def body():
            engine = PolicyEngine()
            swaps = [0]
            engine.add_swap_listener(lambda: swaps.__setitem__(0, swaps[0] + 1))
            rec = AuthConfigReconciler(engine)

            a1 = v1_ac("a", "1", ["a.test"])
            a2 = v1_ac("a", "13", ["a2.test"])   # modified during outage 2
            b = v1_ac("b", "2", ["b.test"])
            c = v1_ac("c", "11", ["c.test"])
            lists = [
                ([a1, b], "10"),                  # L1: initial
                ([a1, c], "12"),                  # L2: B deleted while down
                ([a2, c], "14"),                  # L3: identical to live state
            ]
            watches = [
                # W1: new object arrives, then the server ends the resume
                # point with a 410 Gone ERROR status
                [("yield", "ADDED", c),
                 ("yield", "ERROR", {"kind": "Status", "code": 410})],
                # W2: a modification lands, then the stream drops raw
                [("yield", "MODIFIED", a2), ("raise",)],
                # W3+: park (scripted by the cluster itself)
            ]
            cluster = AdversarialCluster(lists, watches, swaps)
            src = K8sWatchSource(cluster, rec, resync_interval_s=0.01)
            src.start()
            await asyncio.wait_for(cluster.done.wait(), timeout=10)
            await asyncio.sleep(0.1)  # let the final (no-op) re-list settle

            # no missed delete: B disappeared during the first outage
            assert engine.lookup("b.test") is None
            assert rec.status.get("t/b") is None
            # modification during the second outage is live
            assert engine.lookup("a2.test") is not None
            assert engine.lookup("a.test") is None
            assert engine.lookup("c.test") is not None
            # no duplicate reconcile: the unchanged re-list (L3) caused no
            # further corpus swap
            assert cluster.swaps_at_last_list is not None
            assert swaps[0] == cluster.swaps_at_last_list
            # resume-point continuity across failures: watch #1 resumes from
            # the initial list, #2 from the post-410 re-list, #3 from the
            # last delivered event / final list
            rvs = [p.get("resourceVersion") for p in cluster.watch_params[:3]]
            assert rvs == ["10", "12", "14"], rvs
            # readiness: every surviving config reconciled
            assert rec.ready()
            assert rec.status.get("t/a").reason == STATUS_RECONCILED
            assert rec.status.get("t/c").reason == STATUS_RECONCILED
            await src.stop()

        run(body())


class TestResyncDedupRetry:
    def test_caching_error_retried_on_identical_relist(self, monkeypatch):
        """The resourceVersion dedup must NOT swallow retries of configs in
        CachingError: resyncs are their self-heal path (a transient Secret/
        discovery failure would otherwise wedge /readyz at 503 forever)."""
        from authorino_tpu.controllers import reconciler as rec_mod

        async def body():
            engine = PolicyEngine()
            rec = AuthConfigReconciler(engine)
            calls = {"n": 0}
            real = rec_mod.translate_auth_config

            async def flaky(*a, **k):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise TranslationError("transient backend failure")
                return await real(*a, **k)

            monkeypatch.setattr(rec_mod, "translate_auth_config", flaky)
            cr = {
                "apiVersion": "authorino.kuadrant.io/v1beta2",
                "kind": "AuthConfig",
                "metadata": {"namespace": "t", "name": "x", "resourceVersion": "5"},
                "spec": {"hosts": ["x.test"]},
            }
            await rec.reconcile_all([cr])
            assert rec.status.get("t/x").reason == STATUS_CACHING_ERROR
            # identical re-list (same resourceVersion): must retry, not skip
            await rec.reconcile_all([dict(cr)])
            assert rec.status.get("t/x").reason == STATUS_RECONCILED
            assert rec.ready()
            # now healthy + unchanged: the next identical re-list skips
            swaps = [0]
            engine.add_swap_listener(lambda: swaps.__setitem__(0, swaps[0] + 1))
            await rec.reconcile_all([dict(cr)])
            assert swaps[0] == 0

        run(body())


class TestWatchBookmarksAndStorms:
    def test_bookmarks_advance_resume_point(self):
        """BOOKMARK events must advance the watch resume point without
        reconciling anything: after a drop, the next watch resumes from the
        bookmark's resourceVersion, so the re-watch window shrinks to
        nothing even when no real events flowed (informer bookmark
        semantics)."""
        from authorino_tpu.controllers.sources import K8sWatchSource

        async def body():
            engine = PolicyEngine()
            swaps = [0]
            engine.add_swap_listener(lambda: swaps.__setitem__(0, swaps[0] + 1))
            rec = AuthConfigReconciler(engine)
            a = v1_ac("a", "1", ["a.test"])
            bookmark = {"kind": "AuthConfig",
                        "metadata": {"resourceVersion": "42"}}
            # W1 ends gracefully after the bookmark; the follow-up re-list
            # FAILS (apiserver outage) — the bookmark rv is then the only
            # valid resume point, and the index must keep serving meanwhile
            lists = [([a], "10"), "raise", ([a], "43")]
            watches = [
                [("yield", "BOOKMARK", bookmark)],
                # W2: resumes from the bookmark rv, then parks via cluster
            ]
            cluster = AdversarialCluster(lists, watches, swaps)
            src = K8sWatchSource(cluster, rec, resync_interval_s=0.01)
            src.start()
            await asyncio.wait_for(cluster.done.wait(), timeout=10)
            swaps_after_first = swaps[0]
            assert engine.lookup("a.test") is not None
            # the second watch resumed from the BOOKMARK rv (42) — the
            # failed re-list could not refresh it, the bookmark carried it
            rvs = [p.get("resourceVersion") for p in cluster.watch_params[:2]]
            assert rvs == ["10", "42"], rvs
            # the bookmark itself reconciled nothing new
            assert swaps[0] == swaps_after_first
            await src.stop()

        run(body())

    def test_reconnect_storm_soak_zero_missed_deletes(self):
        """A storm of watch drops / 410s / stale re-lists while requests
        are being served: the index must end EXACTLY at the final apiserver
        state (no missed deletes, no zombies), readiness must hold, and
        every concurrent Check() must answer (VERDICT r3 next #9)."""
        from authorino_tpu.controllers.sources import K8sWatchSource

        async def body():
            engine = PolicyEngine(max_batch=4)
            swaps = [0]
            engine.add_swap_listener(lambda: swaps.__setitem__(0, swaps[0] + 1))
            rec = AuthConfigReconciler(engine)

            # scripted evolution: 12 reconnect cycles; each cycle adds
            # cfg-i, deletes cfg-(i-3) — half the deletes happen DURING the
            # outage (only visible via the re-list), half on the stream
            rv = [100]

            def bump():
                rv[0] += 1
                return str(rv[0])

            live: dict = {}
            lists = []
            watches = []
            live["cfg-0"] = v1_ac("cfg-0", bump(), ["cfg-0.test"])
            lists.append((list(live.values()), bump()))  # initial list
            for i in range(1, 13):
                script = []
                added = v1_ac(f"cfg-{i}", bump(), [f"cfg-{i}.test"])
                live[f"cfg-{i}"] = added
                script.append(("yield", "ADDED", added))
                gone = f"cfg-{i - 3}"
                if gone in live:
                    doomed = live.pop(gone)
                    if i % 2 == 0:
                        # on-stream delete
                        script.append(("yield", "DELETED", doomed))
                    # odd i: delete silently during the outage — only the
                    # re-list can reveal it (the missed-delete trap)
                if i % 5 == 0:
                    script.append(
                        ("yield", "ERROR", {"kind": "Status", "code": 410}))
                else:
                    script.append(("raise",))
                watches.append(script)
                lists.append((list(live.values()), bump()))
            cluster = AdversarialCluster(lists, watches, swaps)
            src = K8sWatchSource(cluster, rec, resync_interval_s=0.005)
            src.start()

            # concurrent serving during the storm
            served = [0]
            stop_serving = asyncio.Event()

            async def serve():
                while not stop_serving.is_set():
                    req = CheckRequestModel(http=HttpRequestAttributes(
                        method="GET", path="/x",
                        host=f"cfg-{served[0] % 13}.test"))
                    result = await engine.check(req)
                    assert result is not None
                    served[0] += 1
                    await asyncio.sleep(0)

            server_task = asyncio.ensure_future(serve())
            try:
                await asyncio.wait_for(cluster.done.wait(), timeout=20)
                await asyncio.sleep(0.1)  # let the final re-list settle
            finally:
                stop_serving.set()
                await server_task

            # zero missed deletes, zero zombies: the index is EXACTLY the
            # final live set
            for i in range(13):
                name = f"cfg-{i}"
                if name in live:
                    assert engine.lookup(f"{name}.test") is not None, name
                    assert rec.status.get(f"t/{name}").reason == STATUS_RECONCILED
                else:
                    assert engine.lookup(f"{name}.test") is None, f"zombie {name}"
                    assert rec.status.get(f"t/{name}") is None
            assert rec.ready()
            assert served[0] > 0
            await src.stop()

        run(body())


class TestTopLevelWhenFolding:
    def test_anonymous_gate_folds_into_kernel(self):
        """An AuthConfig-level `when` gate on an anonymous pattern config
        compiles into every evaluator's condition (unmatched gate ⇒ whole
        pipeline skipped ⇒ OK, ref auth_pipeline.go:454-457) so the config
        keeps the kernel fast lane (round 4)."""
        from authorino_tpu.runtime.native_frontend import fast_lane_eligible

        engine = PolicyEngine(max_batch=8, mesh=None)
        spec = {
            "hosts": ["gated.test"],
            "when": [{"selector": "request.method",
                      "operator": "neq", "value": "OPTIONS"}],
            "authentication": {"anon": {"anonymous": {}}},
            "authorization": {"rules": {"patternMatching": {"patterns": [
                {"selector": "request.headers.x-org",
                 "operator": "eq", "value": "acme"}]}}},
        }
        entry = run(translate_auth_config("gated", "t", spec, engine=engine))
        # the gate moved into the compiled rules
        assert entry.runtime.conditions is None
        cond, _rule = entry.rules.evaluators[0]
        assert cond is not None
        engine.apply_snapshot([entry])
        assert fast_lane_eligible(entry, engine._snapshot.policy) is not None

        async def check(method, headers=None):
            req = CheckRequestModel(http=HttpRequestAttributes(
                method=method, path="/x", host="gated.test",
                headers=headers or {}))
            return (await engine.check(req)).code

        # gate unmatched (OPTIONS) → whole pipeline skipped → OK
        assert run(check("OPTIONS")) == 0
        # gate matched: the rule decides
        assert run(check("GET", {"x-org": "acme"})) == 0
        assert run(check("GET", {"x-org": "evil"})) == 7

    def test_credential_identity_gate_does_not_fold(self):
        """Folding is only sound for anonymous identities: a skipped
        pipeline must allow credential-less requests, which the credential
        fast lane could not honor — the gate stays on the pipeline."""
        engine = PolicyEngine(max_batch=8)
        cluster = InMemoryCluster()
        cluster.put_secret(Secret(name="k", namespace="t",
                                  labels={"g": "w"}, data={"api_key": b"s3"}))
        spec = {
            "hosts": ["gated-key.test"],
            "when": [{"selector": "context.request.http.method",
                      "operator": "neq", "value": "OPTIONS"}],
            "authentication": {"keys": {"apiKey": {
                "selector": {"matchLabels": {"g": "w"}}}}},
            "authorization": {"rules": {"patternMatching": {"patterns": [
                {"selector": "context.request.http.headers.x-org",
                 "operator": "eq", "value": "acme"}]}}},
        }
        entry = run(translate_auth_config("gk", "t", spec,
                                          cluster=cluster, engine=engine))
        assert entry.runtime.conditions is not None
        engine.apply_snapshot([entry])

        async def check(method, headers=None):
            req = CheckRequestModel(http=HttpRequestAttributes(
                method=method, path="/x", host="gated-key.test",
                headers=headers or {}))
            return (await engine.check(req)).code

        # skipped pipeline allows even without credentials
        assert run(check("OPTIONS")) == 0
        # gate matched: credentials enforced
        assert run(check("GET")) == 16

    def test_auth_rooted_gate_does_not_fold(self):
        """The reference evaluates the AuthConfig gate at pipeline start,
        where auth.identity is still None (ref auth_pipeline.go:454-457);
        a folded gate would see the resolved anonymous identity instead.
        `auth.identity.anonymous neq "true"` matches pre-resolution
        (missing selector → "") and runs the deny rules — after folding it
        would be unmatched and ALLOW, a fail-open divergence.  Any
        auth.*-rooted selector keeps the gate on the pipeline."""
        engine = PolicyEngine(max_batch=8, mesh=None)
        spec = {
            "hosts": ["gated-auth.test"],
            "when": [{"selector": "auth.identity.anonymous",
                      "operator": "neq", "value": "true"}],
            "authentication": {"anon": {"anonymous": {}}},
            "authorization": {"rules": {"patternMatching": {"patterns": [
                {"selector": "request.headers.x-org",
                 "operator": "eq", "value": "acme"}]}}},
        }
        entry = run(translate_auth_config("ga", "t", spec, engine=engine))
        assert entry.runtime.conditions is not None
        engine.apply_snapshot([entry])

        async def check(headers=None):
            req = CheckRequestModel(http=HttpRequestAttributes(
                method="GET", path="/x", host="gated-auth.test",
                headers=headers or {}))
            return (await engine.check(req)).code

        # gate matches pre-resolution ("" neq "true") → rules enforced
        assert run(check({"x-org": "evil"})) == 7
        assert run(check({"x-org": "acme"})) == 0

    def test_nested_auth_rooted_gate_does_not_fold(self):
        """auth.* detection must walk nested And/Or gate trees."""
        engine = PolicyEngine(max_batch=8, mesh=None)
        spec = {
            "hosts": ["gated-nest.test"],
            "patterns": {"who": [
                {"selector": "auth.identity.sub", "operator": "eq", "value": "x"}]},
            "when": [{"any": [
                {"selector": "request.method", "operator": "eq", "value": "GET"},
                {"patternRef": "who"},
            ]}],
            "authentication": {"anon": {"anonymous": {}}},
            "authorization": {"rules": {"patternMatching": {"patterns": [
                {"selector": "request.headers.x-org",
                 "operator": "eq", "value": "acme"}]}}},
        }
        entry = run(translate_auth_config("gn", "t", spec, engine=engine))
        assert entry.runtime.conditions is not None

    def test_conditioned_anonymous_identity_does_not_fold(self):
        """A conditional anonymous identity could turn gate-unmatched
        requests from skip-OK into 401 under the fold — the gate must stay
        on the pipeline."""
        engine = PolicyEngine(max_batch=8, mesh=None)
        spec = {
            "hosts": ["gated-cond.test"],
            "when": [{"selector": "request.method",
                      "operator": "neq", "value": "OPTIONS"}],
            "authentication": {"anon": {"anonymous": {}, "when": [
                {"selector": "request.headers.x-flag",
                 "operator": "eq", "value": "on"}]}},
            "authorization": {"rules": {"patternMatching": {"patterns": [
                {"selector": "request.headers.x-org",
                 "operator": "eq", "value": "acme"}]}}},
        }
        entry = run(translate_auth_config("gc", "t", spec, engine=engine))
        assert entry.runtime.conditions is not None
        engine.apply_snapshot([entry])

        async def check(method, headers=None):
            req = CheckRequestModel(http=HttpRequestAttributes(
                method=method, path="/x", host="gated-cond.test",
                headers=headers or {}))
            return (await engine.check(req)).code

        # gate unmatched → skip whole pipeline → OK despite the identity's
        # own (unmatched) conditions
        assert run(check("OPTIONS")) == 0
        # gate matched, identity conditions unmatched → UNAUTHENTICATED
        assert run(check("GET", {"x-org": "acme"})) == 16
