"""Golden round-trip conversion suite: v1beta1 ↔ v1beta2.

Scenario breadth modeled on the reference's 1,023-LoC conversion test
(ref: api/v1beta2/auth_config_conversion_test.go): every evaluator kind,
all credentials variants, denyWith, named patterns, top-level and
per-evaluator conditions, priorities/metrics/caching, extended properties,
response wrappers, and callbacks.  Fixtures are written in canonical form
(the shape the converter itself emits) so round-trips must be *exactly*
equal — any dropped or renamed field fails loudly instead of silently.
"""

import copy

import pytest

from authorino_tpu.apis.convert import (
    API_VERSION_V1BETA1,
    API_VERSION_V1BETA2,
    to_v1beta1,
    to_v1beta2,
)


def v1(spec):
    return {
        "apiVersion": API_VERSION_V1BETA1,
        "kind": "AuthConfig",
        "metadata": {"name": "golden", "namespace": "ns"},
        "spec": spec,
    }


def v2(spec):
    return {
        "apiVersion": API_VERSION_V1BETA2,
        "kind": "AuthConfig",
        "metadata": {"name": "golden", "namespace": "ns"},
        "spec": spec,
    }


def roundtrip_v1(resource):
    """v1beta1 → v1beta2 → v1beta1 must be exactly equal."""
    src = copy.deepcopy(resource)
    out = to_v1beta1(to_v1beta2(resource))
    assert out == src, _diff(src, out)


def roundtrip_v2(resource):
    """v1beta2 → v1beta1 → v1beta2 must be exactly equal."""
    src = copy.deepcopy(resource)
    out = to_v1beta2(to_v1beta1(resource))
    assert out == src, _diff(src, out)


def _diff(a, b, path=""):
    lines = []

    def walk(x, y, p):
        if isinstance(x, dict) and isinstance(y, dict):
            for k in sorted(set(x) | set(y)):
                if k not in x:
                    lines.append(f"+ {p}.{k} = {y[k]!r}")
                elif k not in y:
                    lines.append(f"- {p}.{k} = {x[k]!r}")
                else:
                    walk(x[k], y[k], f"{p}.{k}")
        elif isinstance(x, list) and isinstance(y, list):
            if len(x) != len(y):
                lines.append(f"~ {p}: len {len(x)} != {len(y)}")
            for i, (xi, yi) in enumerate(zip(x, y)):
                walk(xi, yi, f"{p}[{i}]")
        elif x != y:
            lines.append(f"~ {p}: {x!r} != {y!r}")

    walk(a, b, path or "$")
    return "\n".join(lines) or "(structures equal)"


# ---------------------------------------------------------------------------
# identity / authentication
# ---------------------------------------------------------------------------

CREDENTIALS_V1 = [
    {"in": "authorization_header", "keySelector": "Bearer"},
    {"in": "authorization_header", "keySelector": "APIKEY"},
    {"in": "custom_header", "keySelector": "X-API-Key"},
    {"in": "query", "keySelector": "api_key"},
    {"in": "cookie", "keySelector": "APIKEY"},
]


@pytest.mark.parametrize("credentials", CREDENTIALS_V1)
def test_api_key_identity_all_credentials_variants(credentials):
    roundtrip_v1(v1({
        "hosts": ["app.example.com"],
        "identity": [{
            "name": "api-key",
            "credentials": credentials,
            "apiKey": {
                "selector": {"matchLabels": {"audience": "app"}},
                "allNamespaces": True,
            },
        }],
    }))


def test_oidc_identity_with_extended_properties_and_cache():
    roundtrip_v1(v1({
        "hosts": ["app.example.com"],
        "identity": [{
            "name": "keycloak",
            "priority": 1,
            "metrics": True,
            "when": [{"selector": "request.path", "operator": "neq", "value": "/public"}],
            "cache": {
                "key": {"valueFrom": {"authJSON": "auth.identity.sub"}},
                "ttl": 300,
            },
            "credentials": {"in": "authorization_header", "keySelector": "Bearer"},
            "extendedProperties": [
                {"name": "tenant", "overwrite": False, "value": "acme"},
                {"name": "roles", "overwrite": True,
                 "valueFrom": {"authJSON": "auth.identity.realm_access.roles"}},
            ],
            "oidc": {"endpoint": "https://kc.example.com/realms/demo", "ttl": 600},
        }],
    }))


def test_oauth2_introspection_identity():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "identity": [{
            "name": "opaque",
            "oauth2": {
                "tokenIntrospectionUrl": "https://idp/introspect",
                "tokenTypeHint": "access_token",
                "credentialsRef": {"name": "idp-credentials"},
            },
        }],
    }))


def test_mtls_kubernetes_plain_anonymous_identities():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "identity": [
            {"name": "mtls", "mtls": {
                "selector": {"matchLabels": {"pki": "internal"}},
                "allNamespaces": False,
            }},
            {"name": "sa-token", "kubernetes": {"audiences": ["talker-api", "other"]}},
            {"name": "plain", "plain": {"authJSON": "context.metadata_context.filter_metadata.envoy\\.filters\\.http\\.jwt_authn|verified_jwt"}},
            {"name": "anon", "anonymous": {}},
        ],
    }))


# ---------------------------------------------------------------------------
# metadata
# ---------------------------------------------------------------------------

def test_metadata_http_userinfo_uma():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "metadata": [
            {
                "name": "geo",
                "priority": 2,
                "http": {
                    "endpoint": "https://geo.example.com/{context.request.http.headers.x-forwarded-for.@extract:{\"sep\":\",\"}}",
                    "method": "POST",
                    "contentType": "application/x-www-form-urlencoded",
                    "body": {"valueFrom": {"authJSON": "auth.identity.user"}},
                    "bodyParameters": [
                        {"name": "city", "valueFrom": {"authJSON": "request.headers.x-city"}},
                        {"name": "static", "value": "fixed"},
                    ],
                    "headers": [
                        {"name": "X-Secret", "valueFrom": {"authJSON": "auth.metadata.secret"}},
                    ],
                    "sharedSecretRef": {"name": "geo-secret", "key": "shared"},
                    "credentials": {"in": "custom_header", "keySelector": "X-Auth"},
                },
            },
            {"name": "userinfo", "userInfo": {"identitySource": "keycloak"}},
            {"name": "resources", "uma": {
                "endpoint": "https://kc.example.com/realms/demo",
                "credentialsRef": {"name": "uma-credentials"},
            }},
        ],
    }))


def test_metadata_http_oauth2_credentials():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "metadata": [{
            "name": "ext",
            "http": {
                "endpoint": "https://ext/metadata",
                "method": "GET",
                "oauth2": {
                    "tokenUrl": "https://idp/token",
                    "clientId": "authorino",
                    "clientSecretRef": {"name": "oauth", "key": "secret"},
                    "scopes": ["read"],
                },
            },
        }],
    }))


# ---------------------------------------------------------------------------
# authorization
# ---------------------------------------------------------------------------

def test_pattern_matching_authorization_with_named_patterns():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "patterns": {
            "admin-path": [{"selector": "request.path", "operator": "matches", "value": "^/admin(/.*)?$"}],
            "safe-verbs": [{"selector": "request.method", "operator": "incl", "value": "GET"}],
        },
        "when": [{"patternRef": "safe-verbs"}],
        "authorization": [{
            "name": "rbac",
            "json": {"rules": [
                {"patternRef": "admin-path"},
                {"any": [
                    {"selector": "auth.identity.roles", "operator": "incl", "value": "admin"},
                    {"all": [
                        {"selector": "auth.identity.roles", "operator": "incl", "value": "operator"},
                        {"selector": "request.method", "operator": "eq", "value": "GET"},
                    ]},
                ]},
            ]},
        }],
    }))


def test_opa_authorization_inline_and_external():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "authorization": [{
            "name": "opa",
            "opa": {
                "inlineRego": "allow { input.auth.identity.admin }",
                "allValues": True,
                "externalRegistry": {
                    "endpoint": "https://registry/policy.rego",
                    "sharedSecretRef": {"name": "opa-registry", "key": "token"},
                    "ttl": 120,
                    "credentials": {"in": "authorization_header", "keySelector": "Bearer"},
                },
            },
        }],
    }))


def test_kubernetes_sar_authorization():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "authorization": [{
            "name": "sar",
            "kubernetes": {
                "user": {"valueFrom": {"authJSON": "auth.identity.username"}},
                "groups": ["system:authenticated"],
                "resourceAttributes": {
                    "namespace": {"value": "default"},
                    "resource": {"valueFrom": {"authJSON": "context.request.http.path.@extract:{\"sep\":\"/\",\"pos\":1}"}},
                    "verb": {"value": "get"},
                },
            },
        }],
    }))


def test_authzed_spicedb_authorization():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "authorization": [{
            "name": "spicedb",
            "authzed": {
                "endpoint": "spicedb.example.com:50051",
                "insecure": True,
                "sharedSecretRef": {"name": "spicedb-token", "key": "grpc-preshared-key"},
                "subject": {
                    "name": {"valueFrom": {"authJSON": "auth.identity.sub"}},
                    "kind": {"value": "user"},
                },
                "resource": {
                    "name": {"valueFrom": {"authJSON": "context.request.http.path.@extract:{\"sep\":\"/\",\"pos\":2}"}},
                    "kind": {"value": "document"},
                },
                "permission": {"value": "read"},
            },
        }],
    }))


# ---------------------------------------------------------------------------
# response / denyWith / callbacks
# ---------------------------------------------------------------------------

def test_deny_with_full_customization():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "denyWith": {
            "unauthenticated": {
                "code": 302,
                "message": {"value": "redirecting to login"},
                "headers": [
                    {"name": "Location", "valueFrom": {"authJSON": "http://login.example.com?redirect_to={context.request.http.path}"}},
                ],
                "body": {"value": "unauthenticated"},
            },
            "unauthorized": {
                "code": 403,
                "message": {"valueFrom": {"authJSON": "auth.metadata.denial-reason"}},
            },
        },
    }))


def test_response_wristband_json_plain_with_wrappers():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "response": [
            {
                "name": "wristband",
                "wrapper": "httpHeader",
                "wrapperKey": "x-wristband",
                "wristband": {
                    "issuer": "https://authorino-oidc:8083/ns/golden/wristband",
                    "customClaims": [
                        {"name": "scope", "valueFrom": {"authJSON": "auth.identity.scope"}},
                    ],
                    "tokenDuration": 300,
                    "signingKeyRefs": [{"name": "signing-key", "algorithm": "ES256"}],
                },
            },
            {
                "name": "headers",
                "wrapper": "httpHeader",
                "wrapperKey": "x-auth-data",
                "json": {"properties": [
                    {"name": "username", "valueFrom": {"authJSON": "auth.identity.username"}},
                    {"name": "app", "value": "talker-api"},
                ]},
            },
            {
                "name": "plain-token",
                "wrapper": "httpHeader",
                "plain": {"valueFrom": {"authJSON": "auth.credential"}},
            },
            # envoyDynamicMetadata entries LAST: v1beta2 groups success
            # responses by wrapper (headers vs dynamicMetadata), so the
            # canonical v1beta1 order lists all httpHeader wrappers first —
            # regrouping is semantic-preserving (same as the reference,
            # where Go map iteration already drops list order)
            {
                "name": "rate-limit-data",
                "wrapper": "envoyDynamicMetadata",
                "wrapperKey": "ext_auth_data",
                "json": {"properties": [
                    {"name": "username", "valueFrom": {"authJSON": "auth.identity.preferred_username"}},
                ]},
            },
        ],
    }))


def test_callbacks_http():
    roundtrip_v1(v1({
        "hosts": ["h"],
        "callbacks": [{
            "name": "audit",
            "priority": 3,
            "when": [{"selector": "auth.authorization.rbac", "operator": "eq", "value": "true"}],
            "http": {
                "endpoint": "https://audit.example.com/log",
                "method": "POST",
                "contentType": "application/json",
                "body": {"valueFrom": {"authJSON": "context.request"}},
            },
        }],
    }))


# ---------------------------------------------------------------------------
# the big one: every section at once, both directions
# ---------------------------------------------------------------------------

FULL_V1_SPEC = {
    "hosts": ["talker-api.example.com", "*.wild.example.com"],
    "patterns": {
        "api-route": [{"selector": "request.path", "operator": "matches", "value": "^/api/"}],
    },
    "when": [{"patternRef": "api-route"}],
    "identity": [
        {"name": "k", "credentials": {"in": "authorization_header", "keySelector": "APIKEY"},
         "apiKey": {"selector": {"matchLabels": {"app": "talker"}}, "allNamespaces": False}},
        {"name": "o", "oidc": {"endpoint": "https://kc/realms/demo", "ttl": 0}},
    ],
    "metadata": [
        {"name": "u", "userInfo": {"identitySource": "o"}},
    ],
    "authorization": [
        {"name": "rules", "priority": 1,
         "json": {"rules": [{"selector": "auth.identity.email_verified", "operator": "eq", "value": "true"}]}},
    ],
    "denyWith": {
        "unauthorized": {"code": 403, "message": {"value": "nope"}},
    },
    "response": [
        {"name": "hdr", "wrapper": "httpHeader", "wrapperKey": "x-data",
         "json": {"properties": [{"name": "user", "valueFrom": {"authJSON": "auth.identity.sub"}}]}},
    ],
    "callbacks": [
        {"name": "cb", "http": {"endpoint": "https://cb/log", "method": "POST"}},
    ],
}


def test_omitted_optionals_stay_omitted():
    """Optional refs (sharedSecretRef, credentialsRef, audiences, groups)
    left out of the source must NOT come back as explicit nulls — a null
    injected by the conversion webhook rewrites the stored resource."""
    roundtrip_v1(v1({
        "hosts": ["h"],
        "identity": [
            {"name": "opaque", "oauth2": {
                "tokenIntrospectionUrl": "https://idp/introspect",
                "tokenTypeHint": "access_token",
            }},
            {"name": "sa", "kubernetes": {}},
        ],
        "authorization": [
            {"name": "opa-ext", "opa": {
                "inlineRego": "allow { true }",
                "allValues": False,
                "externalRegistry": {"endpoint": "https://r/p.rego", "ttl": 30},
            }},
            {"name": "sar", "kubernetes": {
                "user": {"valueFrom": {"authJSON": "auth.identity.user"}},
            }},
            {"name": "spicedb", "authzed": {
                "endpoint": "db:50051",
                "insecure": False,
                "subject": {"kind": {"value": "user"}},
                "resource": {"kind": {"value": "doc"}},
                "permission": {"value": "read"},
            }},
        ],
    }))


def test_full_spec_roundtrip_v1():
    roundtrip_v1(v1(copy.deepcopy(FULL_V1_SPEC)))


def test_full_spec_roundtrip_v2():
    # the v2 shape of the same resource, canonical per the converter
    resource2 = to_v1beta2(v1(copy.deepcopy(FULL_V1_SPEC)))
    roundtrip_v2(resource2)


def test_conversion_is_idempotent_on_target_version():
    r1 = v1(copy.deepcopy(FULL_V1_SPEC))
    assert to_v1beta1(r1) is r1            # already v1beta1: unchanged
    r2 = to_v1beta2(r1)
    assert to_v1beta2(r2) is r2            # already v1beta2: unchanged
