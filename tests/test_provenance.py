"""Decision provenance suite (ISSUE 9): which-rule-fired attribution
exactness across lanes (kernel / engine / verdict-cache hit / dedup
fan-out / host-oracle degrade, property-tested against the host
expression trees), the decision-record schema pin, the flight-recorder
dump under a chaos profile, the SLO burn-rate tracker, the metrics-
catalogue drift gate, and the zero-per-request-Python perf guard.

Deliberately import-light: collects and runs without `cryptography`
(JAX_PLATFORMS=cpu), like tests/test_observability.py."""

from __future__ import annotations

import asyncio
import json
import random
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.compiler.compile import compile_corpus
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.models.policy_model import PolicyModel, host_results
from authorino_tpu.ops.pattern_eval import (
    firing_columns,
    unpack_attribution,
)
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime import provenance as prov_mod
from authorino_tpu.runtime.flight_recorder import FlightRecorder, RECORDER
from authorino_tpu.utils.slo import SloTracker


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


SELECTORS = [
    "request.method", "request.url_path", "request.headers.x-org",
    "request.headers.x-tier", "auth.identity.username",
    "auth.identity.roles", "auth.identity.groups",
]
VALUES = ["acme", "evil", "GET", "POST", "/a", "/b/c", "gold", "admin",
          "dev", "john", "jane"]


def random_pattern(rng):
    op = rng.choice([Operator.EQ, Operator.NEQ, Operator.INCL,
                     Operator.EXCL, Operator.MATCHES])
    sel = rng.choice(SELECTORS)
    if op is Operator.MATCHES:
        val = rng.choice([r"^/a", r"\d+", r"^(GET|POST)$", r"adm.n", r"^$"])
    else:
        val = rng.choice(VALUES)
    return Pattern(sel, op, val)


def random_expr(rng, depth=0):
    if depth >= 2 or rng.random() < 0.5:
        return random_pattern(rng)
    comb = All if rng.random() < 0.5 else Any_
    return comb(*[random_expr(rng, depth + 1)
                  for _ in range(rng.randint(1, 3))])


def random_doc(rng):
    doc = {
        "request": {
            "method": rng.choice(["GET", "POST", "DELETE"]),
            "url_path": rng.choice(["/a", "/b/c", "/x", ""]),
            "headers": {},
            "host": rng.choice(["a.test", "b.test"]),
        },
        "auth": {"identity": {}},
    }
    if rng.random() < 0.8:
        doc["request"]["headers"]["x-org"] = rng.choice(VALUES)
    if rng.random() < 0.5:
        doc["request"]["headers"]["x-tier"] = rng.choice(["gold", "silver"])
    ident = doc["auth"]["identity"]
    if rng.random() < 0.9:
        ident["username"] = rng.choice(["john", "jane", "nobody"])
    if rng.random() < 0.8:
        ident["roles"] = rng.sample(["admin", "dev", "ops"],
                                    k=rng.randint(0, 3))
    if rng.random() < 0.6:
        ident["groups"] = [rng.choice(VALUES)
                           for _ in range(rng.randint(0, 20))]
    return doc


def oracle_firing(policy, doc, row) -> int:
    """Host-expression-tree attribution: the first not-skipped false rule
    column — the property every lane must reproduce."""
    _, rule, skipped = host_results(policy, doc, row)
    return int(firing_columns(rule[None, :], skipped[None, :])[0])


def build_engine(configs, **kw) -> PolicyEngine:
    # attribution parity across cache/dedup/degrade needs the DEVICE
    # path deterministically; host-lane attribution parity is pinned in
    # tests/test_lane_select.py
    kw.setdefault("lane_select", False)
    engine = PolicyEngine(max_batch=32, members_k=4, mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id=c.name, hosts=[c.name], runtime=None, rules=c)
        for c in configs
    ])
    return engine


RULE = All(
    Pattern("request.method", Operator.EQ, "GET"),
    Pattern("auth.identity.org", Operator.EQ, "acme"),
)
DENY_RULE2 = Pattern("request.headers.x-tier", Operator.EQ, "gold")


def doc(method="GET", org="acme", tier="gold"):
    return {"request": {"method": method, "host": "c", "headers":
                        {"x-tier": tier}},
            "auth": {"identity": {"org": org}}}


# ---------------------------------------------------------------------------
# attribution exactness: property test across lanes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_attribution_matches_host_oracle_property(seed):
    """Kernel-lane attribution (bitpacked readback → unpack_attribution)
    equals host-expression-tree attribution for random corpora/docs —
    membership-overflow (host_fallback) rows excluded (the engine path
    re-decides those through the oracle itself, covered below)."""
    from authorino_tpu.ops.pattern_eval import eval_bitpacked_jit, to_device

    rng = random.Random(seed)
    configs = []
    for i in range(rng.randint(2, 6)):
        evaluators = [(random_expr(rng) if rng.random() < 0.4 else None,
                       random_expr(rng))
                      for _ in range(rng.randint(1, 3))]
        configs.append(ConfigRules(name=f"cfg-{i}", evaluators=evaluators))
    policy = compile_corpus(configs, members_k=4)
    model = PolicyModel(policy)
    docs = [random_doc(rng) for _ in range(48)]
    rows = [rng.randrange(len(configs)) for _ in docs]
    db = model.encode(docs, rows)
    params = to_device(policy)
    import jax.numpy as jnp

    has_dfa = params["dfa_tables"] is not None
    packed = np.asarray(eval_bitpacked_jit(
        params, jnp.asarray(db.attrs_val), jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense), jnp.asarray(db.config_id),
        jnp.asarray(db.attr_bytes) if has_dfa else None,
        jnp.asarray(db.byte_ovf) if has_dfa else None))
    E = int(policy.eval_rule.shape[1])
    verdict, firing = unpack_attribution(packed, E)
    for r in range(len(docs)):
        if db.host_fallback[r]:
            continue  # lossy compact row: the serving paths re-decide it
        want = oracle_firing(policy, docs[r], rows[r])
        assert int(firing[r]) == want, (
            f"seed={seed} row={r}: kernel attributed {int(firing[r])}, "
            f"oracle {want}")
        assert bool(verdict[r]) == (want < 0)


def test_attribution_parity_engine_cache_dedup_and_degrade():
    """The same request attributes to the same rule through: a fresh
    engine dispatch, a duplicate row in one batch (dedup fan-out), a
    verdict-cache hit on a later batch, and the breaker-open host-oracle
    degrade path."""
    configs = [ConfigRules(name="c", evaluators=[(None, RULE),
                                                 (None, DENY_RULE2)])]
    engine = build_engine(configs)
    policy = engine._snapshot.policy
    row = policy.config_ids["c"]
    deny_doc = doc(org="evil")          # rule 0 fires
    deny_doc2 = doc(tier="silver")      # rule 1 fires
    want0 = oracle_firing(policy, deny_doc, row)
    want1 = oracle_firing(policy, deny_doc2, row)
    assert want0 == 0 and want1 == 1

    def firing_of(res):
        rule, skipped = res
        return int(firing_columns(np.asarray(rule)[None, :],
                                  np.asarray(skipped)[None, :])[0])

    async def pass1():
        # duplicates of both docs in one gather: dedup fan-out must give
        # every duplicate the same attribution
        outs = await asyncio.gather(*(
            [engine.submit(dict(deny_doc), "c") for _ in range(6)]
            + [engine.submit(dict(deny_doc2), "c") for _ in range(6)]))
        return [firing_of(o) for o in outs]

    got = run(pass1())
    assert got[:6] == [want0] * 6 and got[6:] == [want1] * 6

    # verdict-cache hit: a later batch serves the same rows from cache
    cache = engine._verdict_cache
    hits_before = cache.hits
    got2 = run(asyncio.wait_for(_submit_one(engine, deny_doc), 30))
    assert firing_of(got2) == want0
    assert cache.hits > hits_before

    # breaker-open degrade: whole batches re-decide through the oracle
    engine.breaker.record_failure()
    for _ in range(10):
        engine.breaker.record_failure()
    assert engine.breaker.state == "open"
    got3 = run(asyncio.wait_for(_submit_one(engine, deny_doc2), 30))
    assert firing_of(got3) == want1


async def _submit_one(engine, d):
    return await engine.submit(dict(d), "c")


def test_membership_overflow_fallback_attributes_exactly():
    """host_fallback rows (membership overflow past K) re-decide through
    the oracle inside finalize — attribution must match the oracle's."""
    rule = Pattern("auth.identity.groups", Operator.INCL, "magic")
    configs = [ConfigRules(name="c", evaluators=[(None, rule)])]
    engine = build_engine(configs)
    policy = engine._snapshot.policy
    row = policy.config_ids["c"]
    overflow_doc = {"request": {"method": "GET", "host": "c",
                                "headers": {}},
                    "auth": {"identity": {
                        "groups": [f"g{i}" for i in range(40)]}}}
    want = oracle_firing(policy, overflow_doc, row)
    assert want == 0  # denied: 'magic' not among the groups
    rule_res, skipped = run(_submit_one(engine, overflow_doc))
    got = int(firing_columns(np.asarray(rule_res)[None, :],
                             np.asarray(skipped)[None, :])[0])
    assert got == want


# ---------------------------------------------------------------------------
# heat map + dead-rule report
# ---------------------------------------------------------------------------


def test_heat_map_folds_and_dead_rule_report():
    prov_mod._reset_fired_for_tests()
    configs = [ConfigRules(name="c", evaluators=[(None, RULE),
                                                 (None, DENY_RULE2)])]
    engine = build_engine(configs)
    heat = engine._snapshot.heat
    assert heat is not None
    folds_before = heat.fold_calls
    run(_submit_one(engine, doc(org="evil")))       # rule 0 fires
    assert heat.fold_calls > folds_before
    heat.flush()  # counters flush on cadence/scrape; force it for the reads
    fired = prov_mod.fired_pairs()
    assert ("c", 0) in fired and ("c", 1) not in fired
    report = prov_mod.dead_rule_report(heat, engine._analysis)
    assert report["rules_total"] == 2
    assert report["rules_fired"] == 1
    never = {d["rule"] for d in report["never_fired"]}
    assert len(never) == 1 and next(iter(never)).startswith("1:")
    # /metrics carries the attributed series
    from prometheus_client import REGISTRY

    label = prov_mod.rule_label(0, str(RULE))
    v = REGISTRY.get_sample_value("auth_server_rule_fired_total",
                                  {"authconfig": "c", "rule": label})
    assert v and v >= 1.0


def test_constant_allow_rule_is_statically_explained_dead():
    """A constant-allow rule can never fire; the dead-rule report must
    cross-reference the static finding (PR 4) for it."""
    prov_mod._reset_fired_for_tests()
    const_rule = Pattern("request.method", Operator.NEQ,
                         "\x00never-a-method")  # constant-true in practice
    configs = [
        ConfigRules(name="live", evaluators=[(None, RULE)]),
        ConfigRules(name="const", evaluators=[(None, All())]),
    ]
    engine = build_engine(configs)
    del const_rule
    report = prov_mod.dead_rule_report(engine._snapshot.heat,
                                       engine._analysis)
    by_cfg = {d["authconfig"]: d for d in report["never_fired"]}
    assert "const" in by_cfg
    assert "constant-allow" in by_cfg["const"]["static_findings"]


# ---------------------------------------------------------------------------
# decision log: schema pin + head sampling
# ---------------------------------------------------------------------------


def test_decision_record_schema_pinned():
    log = prov_mod.DecisionLog(capacity=8, sample_n=1)
    log.record(lane="engine", host="a.test", authconfig="c", verdict=False,
               rule="0:x eq y", rule_index=0, latency_ms=1.25,
               generation=3)
    rec = log.to_json()["records"][-1]
    assert tuple(sorted(rec)) == tuple(sorted(prov_mod.DECISION_FIELDS))
    assert rec["verdict"] == "deny" and rec["rule_index"] == 0
    assert log.to_json()["schema"] == prov_mod.DECISION_SCHEMA


def test_decision_log_head_sampling_bounds():
    log = prov_mod.DecisionLog(capacity=16, sample_n=100)
    fires = sum(1 for _ in range(50) if log.should_sample(10))
    # 500 decisions at 1-in-100: ~5 fires, never one per batch
    assert 1 <= fires <= 10


def test_engine_samples_decision_records():
    prov_mod.DECISIONS.configure(sample_n=1)
    try:
        configs = [ConfigRules(name="c", evaluators=[(None, RULE)])]
        engine = build_engine(configs)
        before = prov_mod.DECISIONS.records_total
        run(_submit_one(engine, doc(org="evil")))
        assert prov_mod.DECISIONS.records_total > before
        rec = prov_mod.DECISIONS.to_json(n=1)["records"][-1]
        assert rec["authconfig"] == "c"
        assert rec["verdict"] == "deny"
        assert rec["rule"] and rec["rule"].startswith("0:")
        assert rec["host"] == "c"
        assert rec["generation"] == engine.generation
    finally:
        prov_mod.DECISIONS.configure(sample_n=64)


def test_debug_decisions_endpoint():
    from aiohttp.test_utils import TestClient, TestServer

    from authorino_tpu.service.http_server import build_app

    prov_mod.DECISIONS.configure(sample_n=1)
    try:
        configs = [ConfigRules(name="c", evaluators=[(None, RULE)])]
        engine = build_engine(configs)

        async def body():
            await engine.submit(doc(org="evil"), "c")
            client = TestClient(TestServer(build_app(engine)))
            await client.start_server()
            try:
                resp = await client.get("/debug/decisions?n=5")
                assert resp.status == 200
                payload = await resp.json()
            finally:
                await client.close()
            return payload

        payload = run(body())
        assert payload["schema"] == prov_mod.DECISION_SCHEMA
        assert payload["records"]
        assert len(payload["records"]) <= 5
    finally:
        prov_mod.DECISIONS.configure(sample_n=64)


# ---------------------------------------------------------------------------
# deny-reason knob + dynamic_metadata provenance
# ---------------------------------------------------------------------------


def test_deny_reason_knob_and_pipeline_metadata():
    from authorino_tpu.evaluators.authorization.pattern_matching import (
        PatternMatching,
    )
    from authorino_tpu.evaluators.base import EvaluationError

    configs = [ConfigRules(name="c", evaluators=[(None, RULE)])]
    engine = build_engine(configs)
    pm = PatternMatching(RULE, batched_provider=engine.provider_for("c"),
                         evaluator_slot=0,
                         attributor=engine.attribution_for("c"))

    async def call_once():
        # drive via the engine loop: provider awaits engine.submit
        try:
            await pm.call(_PipelineStub(engine))
        except EvaluationError as e:
            return e
        raise AssertionError("deny expected")

    prov_mod.EXPOSE_DENY_REASON = False
    try:
        e = run(call_once())
        assert str(e) == "Unauthorized"
        assert e.provenance["authconfig"] == "c"
        assert e.provenance["rule_index"] == 0
        assert "acme" in e.provenance["rule"]
        prov_mod.EXPOSE_DENY_REASON = True
        e2 = run(call_once())
        assert "denied by c rule[0]" in str(e2)
        assert "acme" in str(e2)
    finally:
        prov_mod.EXPOSE_DENY_REASON = False


class _PipelineStub:
    def __init__(self, engine):
        self.engine = engine
        self.span = None
        self.deadline = None

    def authorization_json(self):
        return doc(org="evil")


def test_denied_check_response_carries_dynamic_metadata():
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from authorino_tpu.pipeline.pipeline import AuthResult
    from authorino_tpu.service.grpc_server import check_response_from_result
    from authorino_tpu.utils.rpc import PERMISSION_DENIED

    result = AuthResult(code=PERMISSION_DENIED, message="Unauthorized",
                        metadata={"ext_authz_provenance": {
                            "authconfig": "c", "rule_index": 0,
                            "rule": "x eq y", "lane": "engine"}})
    resp = check_response_from_result(result)
    md = resp.dynamic_metadata
    prov = md.fields["ext_authz_provenance"].struct_value
    assert prov.fields["authconfig"].string_value == "c"
    assert prov.fields["rule"].string_value == "x eq y"
    # the deny response itself still carries the generic reason header
    headers = {h.header.key: h.header.value
               for h in resp.denied_response.headers}
    assert headers.get("X-Ext-Auth-Reason") == "Unauthorized"


# ---------------------------------------------------------------------------
# SLO burn rate
# ---------------------------------------------------------------------------


def test_slo_tracker_burn_rates():
    t0 = 1_000_000.0
    slo = SloTracker("testlane-a", slo_ms=50.0, objective=0.999)
    # 1000 requests, 10 bad → bad fraction 1% → burn 10x on every window
    for i in range(10):
        slo.observe(100, 1, now=t0 + i)
    assert abs(slo.burn_rate(60, now=t0 + 10) - 10.0) < 0.2
    assert abs(slo.burn_rate(3600, now=t0 + 10) - 10.0) < 0.2
    js = slo.to_json(now=t0 + 10)
    assert js["windows"]["1m"]["total"] == 1000
    assert js["windows"]["1m"]["bad"] == 10
    # outside the 1m window the short burn decays to 0
    assert slo.burn_rate(60, now=t0 + 3000) == 0.0
    assert slo.burn_rate(3600, now=t0 + 3000) > 0.0


def test_engine_feeds_slo_tracker():
    configs = [ConfigRules(name="c", evaluators=[(None, RULE)])]
    engine = build_engine(configs, slo_ms=10_000.0)
    run(_submit_one(engine, doc()))
    js = engine.slo.to_json()
    assert js["observed_total"] >= 1
    assert js["bad_total"] == 0  # 10s target: nothing is bad
    dv = engine.debug_vars()
    assert dv["slo"]["slo_ms"] == 10_000.0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_and_bundle(tmp_path):
    rec = FlightRecorder(capacity=32, dump_dir=str(tmp_path),
                         min_dump_interval_s=0.0)
    rec.record("breaker", lane="x", detail={"state": "half-open"})
    rec.record("reconcile", detail={"generation": 1})
    path = rec.dump("manual")
    bundle = json.loads(open(path).read())
    assert bundle["kind"] == "authorino-tpu-flight-bundle"
    assert bundle["schema"] == 1
    kinds = [e["kind"] for e in bundle["events"]]
    assert kinds == ["breaker", "reconcile"]
    assert "metrics" in bundle and "vars" in bundle


def test_flight_recorder_dump_under_chaos_profile(tmp_path):
    """Acceptance: a live chaos drive (device-down profile) produces a
    flight-recorder bundle containing the breaker trail and the
    triggering anomaly, readable by the analysis CLI."""
    from authorino_tpu.analysis.__main__ import main as analysis_main
    from authorino_tpu.runtime import faults

    old = (RECORDER.dump_dir, RECORDER.min_dump_interval_s,
           RECORDER.enabled)
    RECORDER.configure(dump_dir=str(tmp_path), min_dump_interval_s=0.0,
                       enabled=True)
    dumps_before = list(RECORDER.dumps)
    configs = [ConfigRules(name="c", evaluators=[(None, RULE)])]
    engine = build_engine(configs, breaker_threshold=2, breaker_reset_s=60.0)
    faults.FAULTS.arm("device-down")
    try:
        # every dispatch fails → retry → degrade; two failures trip the
        # breaker OPEN → anomaly → auto-dump.  Verdicts stay exact.
        for _ in range(3):
            rule_res, skipped = run(_submit_one(engine, doc(org="evil")))
            assert not bool(rule_res[0])
        assert engine.breaker.state == "open"
    finally:
        faults.FAULTS.disarm()
    # the dump runs on its own thread: wait it out
    deadline = time.monotonic() + 10.0
    new_dumps = []
    while time.monotonic() < deadline:
        new_dumps = [d for d in RECORDER.dumps if d not in dumps_before]
        if new_dumps:
            break
        time.sleep(0.05)
    RECORDER.configure(dump_dir=old[0], min_dump_interval_s=old[1],
                       enabled=old[2])
    assert new_dumps, "breaker OPEN did not produce a flight bundle"
    bundle = json.loads(open(new_dumps[0]).read())
    assert bundle["trigger"] == "breaker-open"
    kinds = [e["kind"] for e in bundle["events"]]
    assert "breaker-open" in kinds
    # the breaker trail rides the registered engine's debug-vars snapshot
    eng_vars = bundle["vars"].get("engine")
    assert eng_vars is not None
    assert eng_vars["breaker"]["state"] == "open"
    assert eng_vars["breaker"]["transitions"]
    # ...and the analysis CLI reads it
    assert analysis_main(["--flight-dump", new_dumps[0]]) == 0


def test_breaker_and_admission_flips_recorded():
    from authorino_tpu.runtime.admission import AdmissionController
    from authorino_tpu.runtime.breaker import CircuitBreaker

    events_before = RECORDER.events_total
    br = CircuitBreaker("testlane-b", threshold=1, reset_s=60.0)
    br.record_failure()
    assert RECORDER.events_total > events_before
    tail = [e for e in RECORDER.to_json()["tail"]
            if e["lane"] == "testlane-b"]
    assert tail and tail[-1]["kind"] == "breaker-open"

    adm = AdmissionController("testlane-c", target_s=0.001, interval_s=0.01)
    t = time.monotonic()
    for i in range(40):
        adm.observe_waits((0.5,), now=t + i * 0.01)
    assert adm.overloaded
    tail = [e for e in RECORDER.to_json()["tail"]
            if e["lane"] == "testlane-c"]
    assert tail and tail[-1]["kind"] == "admission-overloaded"


# ---------------------------------------------------------------------------
# metrics-catalogue drift gate (satellite, wired as tier-1)
# ---------------------------------------------------------------------------


def test_metrics_catalog_gate():
    from authorino_tpu.analysis.metrics_catalog import catalog_drift

    missing, stale = catalog_drift()
    assert not missing, (
        f"families registered in utils/metrics.py but missing from "
        f"docs/observability.md: {missing}")
    assert not stale, (
        f"families documented in docs/observability.md but not registered "
        f"in utils/metrics.py: {stale}")


def test_metrics_catalog_detects_planted_drift(tmp_path):
    """A blind gate is worse than none: a doc missing one registered
    family, or naming a ghost one, must trip it."""
    from authorino_tpu.analysis.metrics_catalog import (
        DOC_PATH,
        catalog_drift,
    )

    text = open(DOC_PATH).read()
    pruned = text.replace("auth_server_rule_fired_total", "auth_server_rule_")
    p1 = tmp_path / "pruned.md"
    p1.write_text(pruned)
    missing, _ = catalog_drift(str(p1))
    assert "auth_server_rule_fired_total" in missing
    p2 = tmp_path / "ghost.md"
    p2.write_text(text + "\n| `auth_server_ghost_series_total` | counter |")
    _, stale = catalog_drift(str(p2))
    assert "auth_server_ghost_series_total" in stale


# ---------------------------------------------------------------------------
# perf guard: zero per-request Python on the fold path
# ---------------------------------------------------------------------------


@pytest.mark.perf_guard
def test_fold_is_per_batch_not_per_request():
    """Structural pin: pushing N concurrent requests through the engine
    folds attribution once per BATCH (fold_calls ≪ N) and samples at most
    one decision record per batch."""
    prov_mod.DECISIONS.configure(sample_n=1)
    try:
        configs = [ConfigRules(name="c", evaluators=[(None, RULE)])]
        engine = build_engine(configs)
        heat = engine._snapshot.heat
        records_before = prov_mod.DECISIONS.records_total

        async def burst():
            await asyncio.gather(*(engine.submit(doc(), "c")
                                   for _ in range(64)))

        run(burst())
        assert heat.fold_calls <= 16, (
            f"{heat.fold_calls} folds for 64 requests: fold is not "
            f"per-batch")
        assert (prov_mod.DECISIONS.records_total - records_before
                <= heat.fold_calls)
    finally:
        prov_mod.DECISIONS.configure(sample_n=64)


@pytest.mark.perf_guard
def test_attribution_decode_is_vectorized():
    """The per-batch decode + fold must be numpy-vectorized: decoding a
    16k-row batch has to beat an equivalent per-row Python loop by >5x
    (the native lane's zero-per-request-Python contract)."""
    rng = np.random.default_rng(5)
    B, E = 16384, 8
    own_rule = rng.random((B, E)) > 0.3
    own_skipped = rng.random((B, E)) > 0.7
    rows = rng.integers(0, 32, size=B)
    heat = prov_mod.HeatMap([f"cfg-{i}" for i in range(32)],
                            [[f"r{j}" for j in range(E)]
                             for _ in range(32)], E)
    firing_columns(own_rule[:8], own_skipped[:8])  # warm
    t0 = time.perf_counter()
    firing = firing_columns(own_rule, own_skipped)
    heat.fold(rows, firing)
    vectorized = time.perf_counter() - t0

    t0 = time.perf_counter()
    slow = np.empty(B, dtype=np.int64)
    counts = {}
    for r in range(B):
        first = -1
        for e in range(E):
            if not own_skipped[r, e] and not own_rule[r, e]:
                first = e
                break
        slow[r] = first
        if first >= 0:
            counts[(int(rows[r]), first)] = counts.get(
                (int(rows[r]), first), 0) + 1
    per_row = time.perf_counter() - t0
    assert np.array_equal(firing, slow)
    assert vectorized * 5 < per_row, (
        f"vectorized fold {vectorized * 1e3:.2f}ms vs per-row "
        f"{per_row * 1e3:.2f}ms: not vectorized enough")


# ---------------------------------------------------------------------------
# compiler provenance map + rule labels
# ---------------------------------------------------------------------------


def test_compiler_emits_provenance_map():
    configs = [ConfigRules(name="a", evaluators=[(None, RULE),
                                                 (None, DENY_RULE2)]),
               ConfigRules(name="b", evaluators=[(All(), RULE)])]
    policy = compile_corpus(configs, members_k=4)
    pm = policy.provenance_map()
    assert set(pm) == {"a", "b"}
    assert pm["a"]["rules"] == [str(RULE), str(DENY_RULE2)]
    assert pm["a"]["row"] == policy.config_ids["a"]
    # memoized: one walk per corpus
    assert policy.rule_sources() is policy.rule_sources()


def test_rule_label_truncates_but_never_merges():
    long_a = "x eq " + "a" * 300
    long_b = "x eq " + "b" * 300
    la, lb = prov_mod.rule_label(0, long_a), prov_mod.rule_label(0, long_b)
    assert len(la) <= prov_mod.RULE_LABEL_MAX + 4
    assert la != lb or long_a == long_b
    assert prov_mod.rule_label(1, "short") == "1:short"
