"""Cost-model lane selection + speculative dual-dispatch (ISSUE 12,
runtime/lane_select.py + docs/performance.md "Lane selection").

Covers: the cost-model decision law (units), the host lane serving light
load first-class (stub device proves ZERO device launches), the
latency-critical-head deadline rescue, lane-aware admission, speculative
first-wins resolution (never double-resolves a future, never double-burns
the SLO, losing lane cancelled/ignored cleanly — including a wedged
losing lane held past the watchdog), and 3-seed verdict+attribution
parity across both lanes against the host expression oracle.

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports)."""

from __future__ import annotations

import asyncio
import threading
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.ops.pattern_eval import firing_columns
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime import engine as engine_mod
from authorino_tpu.runtime import faults
from authorino_tpu.runtime.admission import AdmissionController
from authorino_tpu.runtime.lane_select import (
    DEVICE,
    HOST,
    LaneCostModel,
    LaneSelector,
    R_BATCH,
    R_COST,
    R_DISABLED,
    R_EXPLORE,
    R_HOST_BUSY,
    Speculation,
)
from authorino_tpu.utils.rpc import DEADLINE_EXCEEDED


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.FAULTS.disarm()


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


async def wait_until(pred, timeout=5.0, step=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        await asyncio.sleep(step)
    return pred()


RULE = All(
    Pattern("auth.identity.roles", Operator.INCL, "admin"),
    Pattern("auth.identity.groups", Operator.EXCL, "banned"),
)


def build_engine(**kw) -> PolicyEngine:
    kw.setdefault("verdict_cache_size", 0)
    kw.setdefault("max_batch", 8)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id="c", hosts=["c"], runtime=None,
                    rules=ConfigRules(name="c", evaluators=[(None, RULE)]))
    ])
    return engine


def doc(i: int, allow: bool) -> dict:
    return {"auth": {"identity": {
        "roles": ["admin", f"r{i}"] if allow else [f"r{i}"],
        "groups": []}}}


async def submit_all(engine, docs, **kw):
    outs = await asyncio.gather(
        *(engine.submit(d, "c", **kw) for d in docs))
    return [bool(rule[0]) for rule, _ in outs]


def seed_model(engine, host_row_s=1e-4, device_rtt_s=0.1):
    """Teach the cost model a fast host lane and a slow device, so the
    next small cut decides HOST deterministically."""
    engine.lanes.cost.observe_host(host_row_s * 10, 10)
    engine.lanes.cost.observe_device(device_rtt_s, 8)
    engine._device_ewma = device_rtt_s


class FakeHandle:
    def __init__(self, ready_at):
        self.ready_at = ready_at

    def is_ready(self):
        return time.monotonic() >= self.ready_at

    def __array__(self, dtype=None):
        return np.zeros((1, 1))


class SlowStubDevice:
    """Replaces _encode_and_launch: batches 'complete' after a fixed
    latency (allow-all verdicts), so lane routing is observable."""

    def __init__(self, engine, latency_s):
        self.engine = engine
        self.latency_s = latency_s
        self.launched_batches = 0
        self.launched_rows = 0
        engine._encode_and_launch = self._launch

    def _launch(self, snap, batch):
        n = len(batch)
        self.launched_batches += 1
        self.launched_rows += n
        binfo = {"batch_size": n, "pad": n, "eff": 0,
                 "start_ns": time.time_ns(), "duration_s": 0.0}

        def finalize(packed):
            rule = np.ones((n, 1), dtype=bool)
            return rule, np.zeros((n, 1), dtype=bool), None

        return engine_mod._Inflight(
            self.engine, batch,
            FakeHandle(time.monotonic() + self.latency_s),
            finalize, binfo, np.zeros(n))


# ---------------------------------------------------------------------------
# cost model units
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_host_cost_scales_with_rows(self):
        c = LaneCostModel("t-hc")
        c.observe_host(0.001, 10)  # 100us/row
        assert c.host_cost(1) == pytest.approx(1e-4, rel=0.01)
        assert c.host_cost(50) == pytest.approx(5e-3, rel=0.01)

    def test_device_cost_inflates_with_occupancy_and_mesh(self):
        c = LaneCostModel("t-dc")
        c.observe_device(0.1, 256)
        base = c.device_cost(0, 8)
        assert base == pytest.approx(0.1, rel=0.01)
        assert c.device_cost(8, 8) == pytest.approx(2 * base, rel=0.01)
        c.mesh_penalty = 4.0  # 3 of 4 devices down
        assert c.device_cost(0, 8) == pytest.approx(4 * base, rel=0.01)

    def test_cold_start_prefers_device(self):
        # no observations at all: there is no evidence to flip the old
        # device-always behavior, so the selector must keep it
        s = LaneSelector("t-cold")
        assert s.decide(4, 0, 8)[0] == DEVICE

    def test_burn_bias_bounded_and_directional(self):
        c = LaneCostModel("t-burn")
        assert c.burn_bias() == 1.0
        c.observe_slo(DEVICE, 100, 100)
        assert 1.0 < c.burn_bias() <= 2.0  # device burning -> host favored
        c2 = LaneCostModel("t-burn2")
        c2.observe_slo(HOST, 100, 100)
        assert 0.5 <= c2.burn_bias() < 1.0

    def test_burn_decays(self):
        c = LaneCostModel("t-decay")
        t0 = 100.0
        c.observe_slo(DEVICE, 100, 100, now=t0)
        assert c.burn_frac(DEVICE) == 1.0
        # a clean minute later, the bad history has decayed away
        c.observe_slo(DEVICE, 1000, 0, now=t0 + 120.0)
        assert c.burn_frac(DEVICE) < 0.05

    def test_min_service_is_the_admission_floor(self):
        c = LaneCostModel("t-floor")
        c.observe_host(0.001, 10)
        c.observe_device(0.5, 8)
        assert c.min_service_s() == pytest.approx(1e-4, rel=0.01)


class TestSelector:
    def seeded(self, **kw):
        c = LaneCostModel(kw.pop("lane", "t-sel"))
        c.observe_host(0.001, 10)   # 100us/row
        c.observe_device(0.1, 256)  # 100ms RTT
        return LaneSelector("t-sel", cost=c, **kw)

    def test_small_cut_goes_host_large_goes_device(self):
        s = self.seeded(host_max_rows=64)
        assert s.decide(4, 0, 8) == (HOST, R_COST)
        assert s.decide(65, 0, 8) == (DEVICE, R_BATCH)
        # crossover: 100us x n vs 100ms -> device wins past ~1000 rows,
        # but the host_max_rows cap binds first by design
        assert s.decide(64, 0, 8)[0] == HOST

    def test_host_busy_and_disabled(self):
        s = self.seeded(host_concurrency=1)
        s.host_inflight = 1
        assert s.decide(4, 0, 8) == (DEVICE, R_HOST_BUSY)
        s2 = self.seeded()
        s2.enabled = False
        assert s2.decide(4, 0, 8) == (DEVICE, R_DISABLED)

    def test_burn_bias_flips_a_close_call(self):
        c = LaneCostModel("t-flip")
        c.observe_host(0.08, 1)    # host 80ms/row — close to the RTT
        c.observe_device(0.1, 8)   # device 100ms
        s = LaneSelector("t-flip", cost=c, explore_every=0)
        assert s.decide(1, 0, 8)[0] == HOST  # raw cost: 80 < 100
        c.observe_slo(HOST, 100, 100)        # host burning budget
        which, why = s.decide(1, 0, 8)
        assert which == DEVICE and why == "slo-burn"

    def test_explore_probes_the_device_periodically(self):
        s = self.seeded(explore_every=8)
        picks = [s.decide(2, 0, 8) for _ in range(8)]
        assert picks[-1] == (DEVICE, R_EXPLORE)
        assert all(w == HOST for w, _ in picks[:-1])


class TestSpeculation:
    def test_first_claim_wins_exactly_once(self):
        sp = Speculation("t")
        assert sp.claim(HOST) is True
        assert sp.claim(DEVICE) is False
        assert sp.winner == HOST

    def test_acquire_is_idempotent_for_the_owner(self):
        sp = Speculation("t")
        assert sp.acquire(DEVICE) is True
        assert sp.acquire(DEVICE) is True   # the owner keeps ownership
        assert sp.acquire(HOST) is False

    def test_concurrent_claims_single_winner(self):
        for _ in range(50):
            sp = Speculation("t")
            wins = []
            barrier = threading.Barrier(2)

            def claim(which):
                barrier.wait()
                if sp.claim(which):
                    wins.append(which)

            ts = [threading.Thread(target=claim, args=(w,))
                  for w in (HOST, DEVICE)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert len(wins) == 1 and wins[0] == sp.winner


# ---------------------------------------------------------------------------
# lane-aware admission
# ---------------------------------------------------------------------------


class TestLaneAwareAdmission:
    def test_lane_floor_rescues_tight_deadlines_at_admission(self):
        a = AdmissionController("t-lane-adm", target_s=0.05, min_cap=1000)
        now = 50.0
        # device RTT 5s, deadline budget 1s: doomed without the floor...
        assert a.admit(0, now=now, deadline=now + 1.0, rtt_s=5.0) is not None
        # ...admitted with a microsecond host-lane floor
        a.lane_floor = lambda: 1e-4
        assert a.admit(0, now=now, deadline=now + 1.0, rtt_s=5.0) is None
        # an already-expired deadline is still doomed, floor or not
        code, _ = a.admit(0, now=now, deadline=now - 0.01, rtt_s=5.0)
        assert code == DEADLINE_EXCEEDED

    def test_broken_floor_never_breaks_admission(self):
        a = AdmissionController("t-lane-adm2", target_s=0.05, min_cap=10)

        def boom():
            raise RuntimeError("floor broke")

        a.lane_floor = boom
        assert a.admit(0, now=1.0, deadline=2.0, rtt_s=0.0) is None

    def test_engine_wires_the_floor_only_when_enabled(self):
        e1 = build_engine(lane_select=True)
        assert e1.admission.lane_floor is not None
        e2 = build_engine(lane_select=False)
        assert e2.admission.lane_floor is None

    def test_floor_collapses_when_host_lane_saturated(self):
        """Backpressure stays honest: with the host concurrency cap taken,
        the admission floor falls back to the device RTT — admission must
        not admit tight-deadline work the host lane cannot rescue."""
        engine = build_engine()
        seed_model(engine, device_rtt_s=5.0)
        assert engine.admission.lane_floor() < 1.0
        engine.lanes.host_inflight = engine.lanes.host_limit
        assert engine.admission.lane_floor() == float("inf")
        now = time.monotonic()
        assert engine.admission.admit(0, now=now, deadline=now + 1.0,
                                      rtt_s=5.0) is not None
        engine.lanes.host_inflight = 0
        assert engine.admission.admit(0, now=now, deadline=now + 1.0,
                                      rtt_s=5.0) is None


# ---------------------------------------------------------------------------
# engine integration: the host lane as a first-class serving lane
# ---------------------------------------------------------------------------


class TestHostLaneServing:
    def test_light_load_served_host_side_zero_device_launches(self):
        engine = build_engine()
        stub = SlowStubDevice(engine, latency_s=0.2)
        seed_model(engine)
        engine.lanes.explore_every = 0  # pin: no periodic device probe
        outs = run(submit_all(engine, [doc(i, i % 2 == 0)
                                       for i in range(4)]))
        assert outs == [True, False, True, False]
        assert stub.launched_batches == 0  # the cut never touched a device
        ls = engine.lanes.to_json()
        assert ls["rows"][HOST] == 4
        assert any(k.startswith("host:") for k in ls["decisions"])

    def test_large_cut_rides_the_device(self):
        engine = build_engine(max_batch=64, lane_host_max_rows=4)
        stub = SlowStubDevice(engine, latency_s=0.01)
        seed_model(engine)

        async def burst():
            return await submit_all(engine, [doc(i, True)
                                             for i in range(32)])

        assert all(run(burst()))
        assert stub.launched_batches >= 1  # > host_max_rows: batch work

    def test_host_lane_observes_cost_and_service(self):
        engine = build_engine()
        SlowStubDevice(engine, latency_s=0.2)
        seed_model(engine)
        engine.lanes.explore_every = 0
        before = engine.lanes.cost.host_batches
        run(submit_all(engine, [doc(0, True)]))
        assert engine.lanes.cost.host_batches > before
        assert engine.lanes.cost.host_row_s > 0

    def test_cache_only_batches_never_feed_the_device_rtt(self):
        """A fully verdict-cache-resolved batch (zero device rows) must
        not drag the device RTT EWMA down to cache-turnaround time —
        that would read as a fast device and pin small cuts device-side
        under cache-hit-heavy traffic."""
        engine = build_engine()
        stub = SlowStubDevice(engine, latency_s=0.0)
        real = stub._launch

        def cache_only(snap, batch):
            item = real(snap, batch)
            item.binfo["device_rows"] = 0
            return item

        engine._encode_and_launch = cache_only
        before = engine.lanes.cost.device_batches
        run(submit_all(engine, [doc(0, True)]))
        assert engine.lanes.cost.device_batches == before
        assert engine.lanes.cost.device_rtt_s == 0.0

    def test_explore_decision_reaches_the_device(self):
        engine = build_engine()
        stub = SlowStubDevice(engine, latency_s=0.01)
        seed_model(engine)
        engine.lanes.explore_every = 2  # every 2nd host win explores

        async def series():
            for i in range(4):
                await submit_all(engine, [doc(i, True)])

        run(series())
        assert stub.launched_batches >= 1
        assert "device:explore" in engine.lanes.to_json()["decisions"]

    def test_deadline_head_rescued_not_shed(self):
        """A device-bound cut whose head cannot make the device RTT is
        answered host-side instead of shed typed DEADLINE_EXCEEDED."""
        engine = build_engine(max_batch=16, lane_host_max_rows=2)
        SlowStubDevice(engine, latency_s=0.5)
        seed_model(engine, device_rtt_s=0.5)
        engine.lanes.explore_every = 0

        async def mixed():
            # 8 > lane_host_max_rows: the CUT rides the device; two of its
            # members carry deadlines inside the 0.5s device horizon
            tight = time.monotonic() + 0.1
            futs = [engine.submit(doc(i, True), "c",
                                  deadline=tight if i < 2 else None)
                    for i in range(8)]
            return await asyncio.gather(*futs, return_exceptions=True)

        outs = run(mixed())
        assert not any(isinstance(o, Exception) for o in outs)
        assert all(bool(r[0][0]) for r in outs)
        dec = engine.lanes.to_json()["decisions"]
        assert dec.get("host:deadline", 0) >= 1

    def test_degrade_teaches_the_cost_model(self):
        """Every host-oracle batch feeds the per-row EWMA — degrade
        included: an engine whose device is down routes subsequent cuts
        host-side AT THE CUT (first-class) instead of bouncing every
        batch off the open breaker's degrade path."""
        engine = build_engine(breaker_threshold=2)
        faults.FAULTS.arm("kernel:raise:p=1.0")
        try:
            assert run(submit_all(engine, [doc(0, True)])) == [True]
            assert engine.lanes.cost.host_row_s > 0  # degrade taught it
            assert run(submit_all(engine, [doc(1, False)])) == [False]
        finally:
            faults.FAULTS.disarm()
        assert engine.lanes.to_json()["rows"][HOST] >= 1

    def test_drain_waits_out_host_lane_batches(self):
        engine = build_engine()
        SlowStubDevice(engine, latency_s=0.05)
        seed_model(engine)
        run(submit_all(engine, [doc(0, True)]))
        assert engine.drain(timeout_s=5.0) is True
        assert engine.lanes.host_inflight == 0

    def test_debug_vars_lane_block(self):
        engine = build_engine()
        ls = engine.debug_vars()["lane_select"]
        for key in ("enabled", "host_max_rows", "speculative", "decisions",
                    "rows", "speculative_outcomes", "cost"):
            assert key in ls
        for key in ("host_row_ewma_s", "device_rtt_ewma_s", "mesh_penalty",
                    "burn_bias"):
            assert key in ls["cost"]


# ---------------------------------------------------------------------------
# speculative dual-dispatch: first-wins, no double-resolve, no double-burn
# ---------------------------------------------------------------------------


def trip_to_half_open(engine, reset_s=0.02):
    """Drive the lane breaker OPEN and past its cooldown, so the next
    dispatch claims the half-open probe slot."""
    for _ in range(engine.breaker.threshold):
        engine.breaker.record_failure()
    assert engine.breaker.state == "open"
    time.sleep(reset_s + 0.01)


class TestSpeculativeDualDispatch:
    def test_probe_rides_both_lanes_host_wins_device_confirms(self):
        engine = build_engine(breaker_threshold=2, breaker_reset_s=0.02,
                              slo_ms=1000.0)
        stub = SlowStubDevice(engine, latency_s=0.3)
        seed_model(engine, device_rtt_s=0.3)
        # force the CUT onto the device so the probe is a device dispatch
        engine.lanes.host_max_rows = 0
        trip_to_half_open(engine)
        slo_before = engine.slo.total

        async def probe():
            t0 = time.monotonic()
            outs = await submit_all(engine, [doc(i, True) for i in range(3)])
            return outs, time.monotonic() - t0

        outs, took = run(probe())
        assert outs == [True, True, True]
        # the host twin answered: clients never waited out the 0.3s probe
        assert took < 0.25, f"clients waited out the probe: {took:.3f}s"
        assert stub.launched_batches == 1  # the device half DID launch
        spec = engine.lanes.to_json()["speculative_outcomes"]
        assert spec.get("launched") == 1
        assert spec.get("host-win") == 1
        # the device half closes the breaker when its readback lands
        run(wait_until(lambda: engine.breaker.state == "closed"))
        assert engine.breaker.state == "closed"
        run(wait_until(
            lambda: engine.lanes.to_json()["speculative_outcomes"].get(
                "device-win", 0) == 0 and engine._inflight == 0))
        # SLO burned exactly once for the batch (host side), never twice
        assert engine.slo.total == slo_before + 3
        assert engine._inflight == 0  # the window slot was freed

    def test_wedged_losing_device_cancelled_past_watchdog(self):
        """The losing device half wedges forever: the watchdog abandons it
        WITHOUT re-failing the already-resolved batch — no double-resolve,
        no retry storm, slot freed, outcome counted device-fail."""
        engine = build_engine(breaker_threshold=2, breaker_reset_s=0.02,
                              device_timeout_s=0.1, slo_ms=1000.0)
        stub = SlowStubDevice(engine, latency_s=10_000.0)  # never ready
        seed_model(engine, device_rtt_s=0.05)
        engine.lanes.host_max_rows = 0
        trip_to_half_open(engine)
        slo_before = engine.slo.total

        async def probe():
            outs = await submit_all(engine, [doc(0, True), doc(1, False)])
            assert outs == [True, False]
            # the watchdog fires twice (launch + the one retry), then the
            # spec-aware failure path frees the slot without degrading
            assert await wait_until(lambda: engine._inflight == 0,
                                    timeout=8.0)

        run(probe())
        spec = engine.lanes.to_json()["speculative_outcomes"]
        assert spec.get("host-win") == 1
        assert spec.get("device-fail", 0) >= 1
        # SLO burned once on the host side; the wedged loser added nothing
        assert engine.slo.total == slo_before + 2
        # the device halves kept feeding the breaker: it re-opened
        assert engine.breaker.state == "open"
        assert stub.launched_batches >= 1

    def test_device_wins_when_host_is_slow(self):
        """Host twin loses the race: the device resolves, the late host
        result is confirmation only (no double-resolve, host-win absent)."""
        engine = build_engine(breaker_threshold=2, breaker_reset_s=0.02,
                              slo_ms=1000.0)
        SlowStubDevice(engine, latency_s=0.02)
        seed_model(engine, device_rtt_s=0.02)
        engine.lanes.host_max_rows = 0
        # make the host twin slow: wrap the host decide with a sleep
        real = engine._host_decide_batch

        def slow_host(snap, batch, fold=True, lane="engine"):
            time.sleep(0.3)
            return real(snap, batch, fold=fold, lane=lane)

        engine._host_decide_batch = slow_host
        trip_to_half_open(engine)
        slo_before = engine.slo.total
        outs = run(submit_all(engine, [doc(0, True)]))
        assert outs == [True]
        run(wait_until(
            lambda: engine.lanes.host_inflight == 0, timeout=5.0))
        spec = engine.lanes.to_json()["speculative_outcomes"]
        assert spec.get("device-win") == 1
        assert spec.get("host-win", 0) == 0
        assert engine.slo.total == slo_before + 1  # burned once (device)
        assert engine.breaker.state == "closed"

    def test_no_speculation_when_disabled_or_breaker_closed(self):
        engine = build_engine(speculative_dispatch=False,
                              breaker_threshold=2, breaker_reset_s=0.02)
        SlowStubDevice(engine, latency_s=0.02)
        seed_model(engine)
        engine.lanes.host_max_rows = 0
        trip_to_half_open(engine)
        assert run(submit_all(engine, [doc(0, True)])) == [True]
        assert engine.lanes.to_json()["speculative_outcomes"] == {}
        # closed breaker: plain dispatch never speculates either
        engine2 = build_engine()
        SlowStubDevice(engine2, latency_s=0.02)
        seed_model(engine2)
        engine2.lanes.host_max_rows = 0
        assert run(submit_all(engine2, [doc(0, True)])) == [True]
        assert engine2.lanes.to_json()["speculative_outcomes"] == {}

    def test_futures_resolve_exactly_once(self):
        """Direct first-wins check at the resolution layer: after the host
        twin resolved, a device completion for the same batch must not
        overwrite results (and vice versa)."""
        engine = build_engine(breaker_threshold=2, breaker_reset_s=0.02)
        SlowStubDevice(engine, latency_s=0.15)
        seed_model(engine, device_rtt_s=0.15)
        engine.lanes.host_max_rows = 0
        trip_to_half_open(engine)

        async def probe():
            rule, skipped = await engine.submit(doc(0, False), "c")
            first = bool(rule[0])
            # wait out the device completion; the resolved value must not
            # flip (the stub answers allow-all — a second resolution would
            # surface as True)
            await asyncio.sleep(0.3)
            return first

        assert run(probe()) is False  # the host oracle's (exact) verdict


# ---------------------------------------------------------------------------
# parity: verdict + attribution identical across lanes (3 seeds)
# ---------------------------------------------------------------------------


def rand_corpus(rng, n_cfg=6):
    entries = []
    rules = []
    for i in range(n_cfg):
        rule = All(
            Pattern("request.method", Operator.NEQ, "DELETE"),
            Any_(
                Pattern("auth.identity.org", Operator.EQ, f"org-{i}"),
                Pattern("auth.identity.roles", Operator.INCL,
                        f"role-{rng.randrange(4)}"),
            ),
        )
        rules.append(rule)
        entries.append(EngineEntry(
            id=f"cfg-{i}", hosts=[f"h{i}"], runtime=None,
            rules=ConfigRules(name=f"cfg-{i}", evaluators=[(None, rule)])))
    return entries, rules


def rand_doc(rng, i):
    return {
        "request": {"method": rng.choice(["GET", "POST", "DELETE"])},
        "auth": {"identity": {
            "org": f"org-{rng.randrange(8)}",
            "roles": [f"role-{rng.randrange(4)}" for _ in range(2)],
        }},
    }


@pytest.mark.parametrize("seed", [7, 23, 91])
def test_lane_parity_verdict_and_attribution(seed):
    """Random traffic through the engine with the host lane FORCED on vs
    the device lane forced on: verdicts AND firing columns must both equal
    the host expression oracle — the bit-identical-verdicts property the
    speculative race relies on."""
    import random

    rng = random.Random(seed)
    entries, rules = rand_corpus(rng)
    docs = [rand_doc(rng, i) for i in range(48)]
    which_cfg = [rng.randrange(len(entries)) for _ in docs]

    def serve(force_host: bool):
        engine = PolicyEngine(members_k=4, mesh=None, verdict_cache_size=0,
                              max_batch=8, lane_select=force_host,
                              speculative_dispatch=False)
        engine.apply_snapshot(entries)
        if force_host:
            seed_model(engine, device_rtt_s=10.0)  # host always wins
            engine.lanes.explore_every = 0

        async def go():
            outs = []
            for d, ci in zip(docs, which_cfg):
                rule, skipped = await engine.submit(d, f"cfg-{ci}")
                outs.append((np.asarray(rule, dtype=bool),
                             np.asarray(skipped, dtype=bool)))
            return outs

        out = run(go())
        if force_host:
            assert engine.lanes.to_json()["rows"][HOST] == len(docs)
        return out

    host_outs = serve(True)
    dev_outs = serve(False)
    for (hr, hs), (dr, ds), d, ci in zip(host_outs, dev_outs, docs,
                                         which_cfg):
        want = bool(rules[ci].matches(d))
        assert bool(hr[0]) == bool(dr[0]) == want
        hf = int(firing_columns(hr[None, :], hs[None, :])[0])
        df = int(firing_columns(dr[None, :], ds[None, :])[0])
        assert hf == df, f"attribution diverged: host {hf} device {df}"


def test_mesh_cost_feed_units():
    """cost_feed() is total/healthy: 1.0 with a healthy mesh, rising as
    per-device breakers trip (unit-level — the mesh lane itself runs in
    tests/test_mesh.py on forced host devices)."""

    class _B:
        def __init__(self, state):
            self.state = state

    class _Set:
        def __init__(self, states):
            self.breakers = {i: _B(s) for i, s in enumerate(states)}

    class _State:
        pass

    from authorino_tpu.parallel.sharded_eval import ShardedPolicyModel

    m = ShardedPolicyModel.__new__(ShardedPolicyModel)
    m.state = _State()
    m.state.breakers = _Set(["closed"] * 4)
    assert m.cost_feed() == 1.0
    m.state.breakers = _Set(["closed", "closed", "open", "open"])
    assert m.cost_feed() == 2.0
    m.state.breakers = _Set(["open"] * 4)
    assert m.cost_feed() == 4.0
