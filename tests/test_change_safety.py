"""Change safety (ISSUE 10): canary snapshot swaps, guard-breach
auto-rollback, and poison-config quarantine.

End-to-end over the real engine dispatch path: a planted constant-deny
poison config breaches the canary guard, auto-rolls-back, and is
quarantined with the REST of the reconcile still landing; a clean canary
promotes at the window end; in-flight batches across a rollback resolve
and insert verdicts under their own pinned generation (the PR 8 pinning
regression, extended); a canary-cohort request never observes a torn
generation across promotion; the leader's rollback record propagates
through the publisher manifest so replicas converge; and the satellite
bounds (flight-recorder on-disk retention, replica rejected-digest
memory) are regression-pinned.

Deliberately import-light: collects on images without `cryptography`;
JAX_PLATFORMS=cpu."""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.expressions import Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.change_safety import (
    CanaryGuard,
    GuardThresholds,
    _StubHeat,
    _feed,
    cohort_bucket,
    guard_self_test,
    in_canary_cohort,
)
from authorino_tpu.utils import metrics as metrics_mod
from authorino_tpu.runtime.flight_recorder import RECORDER, FlightRecorder
from authorino_tpu.snapshots import rules_fingerprint, serialize_policy
from authorino_tpu.snapshots.distribution import (
    SnapshotPublisher,
    SnapshotReplica,
    load_latest,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def org_corpus(orgs):
    """name -> org constant; each config allows exactly that org."""
    return [ConfigRules(name=n,
                        evaluators=[(None, Pattern("auth.identity.org",
                                                   Operator.EQ, org))])
            for n, org in orgs.items()]


def entries_of(cfgs):
    return [EngineEntry(id=c.name, hosts=[c.name], runtime=None, rules=c)
            for c in cfgs]


def build_engine(cfgs=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("verdict_cache_size", 4096)
    # device-path contracts (canary cohorts riding gated DEVICE batches,
    # generation-token cache keying): routing must stay deterministic —
    # lane-selection semantics are pinned in tests/test_lane_select.py
    kw.setdefault("lane_select", False)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    if cfgs is not None:
        engine.apply_snapshot(entries_of(cfgs))
    return engine


def cdoc(j, org):
    """Request identity varies with j — the cohort hash input."""
    return {"request": {"host": f"h{j}", "path": f"/p{j}", "method": "GET"},
            "auth": {"identity": {"org": org}}}


def docs_in_cohort(org, want, fraction, canary):
    """Deterministically pick `want` docs landing in the requested cohort."""
    out, j = [], 0
    while len(out) < want:
        d = cdoc(j, org)
        if in_canary_cohort(d, fraction) is canary:
            out.append(d)
        j += 1
        assert j < 10000
    return out


# guard thresholds small enough for unit-scale traffic, with the same
# structure as production defaults
TH = GuardThresholds(min_requests=8, min_config_requests=4,
                     min_config_allows=2)


async def _wait(pred, timeout_s=20.0, interval=0.02):
    deadline = time.monotonic() + timeout_s
    while not pred() and time.monotonic() < deadline:
        await asyncio.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# guard self-test (the analysis --verify-fixtures gate rides this)
# ---------------------------------------------------------------------------


def test_guard_self_test_is_clean():
    """A blind or trigger-happy guard fails tier-1, not just the analysis
    CLI: the planted poison must breach, the clean churn must not."""
    assert guard_self_test() == []


def test_cohort_hash_is_stable_identity():
    d = cdoc(3, "org-x")
    assert cohort_bucket(d) == cohort_bucket(json.loads(json.dumps(d)))
    # fraction monotonicity: a doc in the f cohort stays in every f' > f
    f = (cohort_bucket(d) + 1) / 10000
    assert in_canary_cohort(d, f)
    assert in_canary_cohort(d, min(1.0, f + 0.2))


# ---------------------------------------------------------------------------
# the tentpole end to end: poison -> breach -> rollback -> quarantine
# ---------------------------------------------------------------------------


def test_poison_breach_rolls_back_quarantines_and_releases():
    """A semantically valid constant-deny on a hot config passes every
    compile gate, breaches the canary guard under live traffic, is
    auto-rolled-back and quarantined — while a benign change in the SAME
    reconcile still lands.  The poison spec resyncing back stays
    substituted; a fixed spec releases the quarantine."""
    fraction = 0.5
    v1 = {"c-poison": "org-p", "c-clean": "org-c", "c-benign": "org-b"}
    engine = build_engine(org_corpus(v1), canary_fraction=fraction,
                          canary_window_s=30.0, canary_thresholds=TH,
                          verdict_cache_size=0, batch_dedup=False)
    # warm both cohorts' baselines
    pc = docs_in_cohort("org-p", 6, fraction, canary=True)
    pb = docs_in_cohort("org-p", 6, fraction, canary=False)
    cc = docs_in_cohort("org-c", 4, fraction, canary=True)
    cb = docs_in_cohort("org-c", 4, fraction, canary=False)

    async def pump():
        outs = await asyncio.gather(
            *[engine.submit(dict(d), "c-poison") for d in pc + pb],
            *[engine.submit(dict(d), "c-clean") for d in cc + cb])
        return [bool(o[0][0]) for o in outs]

    assert all(run(pump()))  # baseline: everything allows

    # the reconcile: c-poison constant-denies (typo'd constant), c-benign
    # legitimately moves org-b -> org-b2
    v2 = {"c-poison": "org-NEVER", "c-clean": "org-c", "c-benign": "org-b2"}
    engine.apply_snapshot(entries_of(org_corpus(v2)))
    assert engine._canary is not None  # corpus changed -> canary, not swap

    async def drive_until_rollback():
        async def step():
            await pump()
            return engine._canary is None and engine.quarantine_active
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if await step():
                return True
        return False

    assert run(drive_until_rollback()), \
        f"guard never breached: {engine.change_safety_vars()}"

    lr = engine._last_rollback
    assert lr is not None and lr["reason"] == "guard-breach"
    assert lr["detect_ms"] is not None
    assert lr["quarantined"] == ["c-poison"]
    assert "c-poison" in (lr["detail"] or {}).get("suspects", [])
    q = engine._quarantine
    assert sorted(q["configs"]) == ["c-poison"]
    # flight recorder saw the anomaly + the quarantine
    with RECORDER._ring_lock:
        kinds = [e["kind"] for e in RECORDER._ring]
    assert "snapshot-rollback" in kinds and "quarantine" in kinds

    async def verdicts():
        o1 = await engine.submit(cdoc(1, "org-p"), "c-poison")
        o2 = await engine.submit(cdoc(2, "org-b2"), "c-benign")
        o3 = await engine.submit(cdoc(3, "org-b"), "c-benign")
        o4 = await engine.submit(cdoc(4, "org-c"), "c-clean")
        return [bool(o[0][0]) for o in (o1, o2, o3, o4)]

    allowed_p, allowed_b2, allowed_b, allowed_c = run(verdicts())
    assert allowed_p      # poison quarantined: prior vetted artifact serves
    assert allowed_b2     # the benign change in the same reconcile LANDED
    assert not allowed_b  # ...really landed (old constant gone)
    assert allowed_c      # untouched config unaffected throughout

    # the control plane resyncing the SAME poison spec must not re-serve it
    gen = engine.generation
    engine.apply_snapshot(entries_of(org_corpus(v2)))
    assert engine._canary is None  # substituted corpus is identical: no-op
    assert engine.quarantine_active
    assert run(_submit1(engine, cdoc(5, "org-p"), "c-poison"))

    # a FIXED spec releases the quarantine back to the normal canaried path
    v3 = {"c-poison": "org-p2", "c-clean": "org-c", "c-benign": "org-b2"}
    engine.apply_snapshot(entries_of(org_corpus(v3)))
    assert not engine.quarantine_active
    if engine._canary is not None:  # the fix itself canaries; promote it
        assert engine.canary_promote()
    assert run(_submit1(engine, cdoc(6, "org-p2"), "c-poison"))
    assert engine.generation > gen


async def _submit1(engine, doc, host):
    out = await engine.submit(doc, host)
    return bool(out[0][0])


def test_new_poison_config_quarantines_out_and_persists():
    """A poison config NEW this reconcile has no prior artifact: it
    quarantines out entirely — and the quarantine record must survive the
    re-apply even though nothing substitutes for it (regression: the
    re-apply used to omit the no-prior entry, the substitution pass read
    that as 'config changed' and cleared the quarantine it was arming, so
    every resync of the same bad spec re-canaried forever)."""
    fraction = 0.5
    engine = build_engine(org_corpus({"c-base": "org-a"}),
                          canary_fraction=fraction, canary_window_s=30.0,
                          canary_thresholds=TH, verdict_cache_size=0,
                          batch_dedup=False)
    v2 = {"c-base": "org-a", "c-new": "org-NEVER"}
    engine.apply_snapshot(entries_of(org_corpus(v2)))
    assert engine._canary is not None
    # baseline cohort warms on the unchanged config; the NEW config only
    # exists in the candidate corpus, so its traffic rides the canary
    # cohort (the baseline index has no such host)
    base_docs = docs_in_cohort("org-a", 10, fraction, canary=False)
    new_docs = docs_in_cohort("org-a", 6, fraction, canary=True)

    async def drive():
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            await asyncio.gather(
                *[engine.submit(dict(d), "c-base") for d in base_docs],
                *[engine.submit(dict(d), "c-new") for d in new_docs])
            if engine._canary is None and engine.quarantine_active:
                return True
        return False

    assert run(drive()), \
        f"guard never breached: {engine.change_safety_vars()}"
    lr = engine._last_rollback
    assert lr["reason"] == "guard-breach"
    assert lr["quarantined"] == ["c-new"]
    q = engine._quarantine
    assert sorted(q["configs"]) == ["c-new"]
    assert q["configs"]["c-new"]["prior"] is None
    # the same bad spec resyncing stays quarantined out
    engine.apply_snapshot(entries_of(org_corpus(v2)))
    assert engine._canary is None
    assert engine.quarantine_active
    assert run(_submit1(engine, cdoc(0, "org-a"), "c-base"))
    # a FIXED spec releases it back to the normal (canaried) path
    engine.apply_snapshot(entries_of(org_corpus(
        {"c-base": "org-a", "c-new": "org-ok"})))
    assert not engine.quarantine_active
    if engine._canary is not None:
        assert engine.canary_promote()
    assert run(_submit1(engine, cdoc(1, "org-ok"), "c-new"))


def test_all_error_canary_breaches_error_guard():
    """A canary whose batches ALL fail accumulates zero decided samples —
    the error guard gates on ATTEMPTED (decided + errored) counts, so the
    broken generation cannot ride the min-sample gate to a blind promote
    (regression: the gate used to require min decided requests)."""
    heat = _StubHeat(["cfg"])
    g = CanaryGuard(thresholds=TH, check_interval_s=0.0)
    _feed(g, False, heat, 0, 64, 0.0)  # healthy baseline cohort
    g.observe_errors(True, 64)         # canary cohort: every request errors
    b = g.breach()
    assert b is not None and "error-rate" in b["guards"]


def test_quarantine_record_reaches_manifest(tmp_path):
    """The quarantine re-apply's snapshot carries the quarantine record
    BEFORE the swap listeners fire, so the publisher manifest (what
    replicas and fleet operators read) names the held-out configs
    (regression: the record used to be stamped after notify, losing the
    race against the publish thread's read)."""
    d = str(tmp_path / "pub")
    leader = build_engine(org_corpus({"c": "org-a"}), strict_verify=True)
    pub = SnapshotPublisher(d)
    pub.attach(leader)
    poisoned = org_corpus({"c": "org-a", "p": "org-NEVER"})
    fp = rules_fingerprint(poisoned[1])
    leader._quarantine = {"since": time.time(), "reason": "guard-breach",
                          "from_generation": 1,
                          "configs": {"p": {"poison": fp, "prior": None}}}
    leader._quarantine_prior = {}
    leader.apply_snapshot(entries_of(poisoned))
    assert leader.quarantine_active  # no-prior poison stays quarantined
    assert pub.flush()
    man = json.loads(open(os.path.join(d, "MANIFEST.json")).read())
    assert man["quarantine"]["configs"] == ["p"]
    assert man["quarantine"]["from_generation"] == 1


def test_added_config_serves_both_cohorts_mid_canary():
    """A config ADDED by the canaried reconcile has no baseline artifact:
    its traffic rides the candidate regardless of cohort (regression: the
    baseline cohort's batches encoded against the baseline snapshot,
    KeyError'd, and walked the breaker open on healthy hardware)."""
    fraction = 0.5
    engine = build_engine(org_corpus({"c": "org-a"}),
                          canary_fraction=fraction, canary_window_s=30.0,
                          canary_thresholds=TH)
    engine.apply_snapshot(entries_of(org_corpus({"c": "org-a",
                                                 "n": "org-n"})))
    assert engine._canary is not None
    docs = docs_in_cohort("org-n", 3, fraction, canary=False) + \
        docs_in_cohort("org-n", 3, fraction, canary=True)

    async def body():
        outs = await asyncio.gather(
            *[engine.submit(dict(d), "n") for d in docs])
        return [bool(o[0][0]) for o in outs]

    assert all(run(body()))  # both cohorts decide via the candidate
    assert not run(_submit1(engine, cdoc(0, "org-x"), "n"))  # denies exact
    assert engine._canary is not None  # healthy traffic: no breach
    assert engine._last_rollback is None


def test_drain_cancels_canary_window_timer():
    """SIGTERM mid-canary: the window timer must not fire a promote into
    a tearing-down process (swap listeners would rebuild stopped
    frontends); the canary stays undecided through drain."""
    engine = build_engine(org_corpus({"c": "org-a"}), canary_fraction=0.5,
                          canary_window_s=0.25, canary_thresholds=TH)
    engine.apply_snapshot(entries_of(org_corpus({"c": "org-b"})))
    assert engine._canary is not None
    fired = []
    engine.add_swap_listener(lambda: fired.append(1))
    engine.begin_drain()
    time.sleep(0.6)  # well past the window expiry
    assert engine._canary is not None  # undecided, never promoted
    assert not fired


def test_conclude_breach_evaluation_bypasses_rate_limit():
    """The window-expiry conclusion forces a final guard evaluation: a
    per-batch check moments earlier must not rate-limit the decision into
    promoting a breaching canary."""
    heat = _StubHeat(["cfg"])
    g = CanaryGuard(thresholds=TH, check_interval_s=3600.0)
    assert g.breach() is None  # consumes the interval budget
    _feed(g, False, heat, 0, 64, 0.0)
    _feed(g, True, heat, 0, 64, 1.0)
    assert g.breach() is None  # rate-limited: evidence unseen
    b = g.breach(force=True)   # what _canary_conclude runs
    assert b is not None and "cfg" in b["suspects"]


def test_guard_close_zeros_delta_gauges():
    """Promote/rollback zeroes the live guard-delta gauges — a
    breach-level delta must not keep dashboards alerting after the
    rollback already handled it."""
    heat = _StubHeat(["cfg"])
    g = CanaryGuard(thresholds=TH, check_interval_s=0.0)
    _feed(g, False, heat, 0, 64, 0.0)
    _feed(g, True, heat, 0, 64, 1.0)
    assert g.breach() is not None
    gauge = metrics_mod.canary_guard_delta.labels("deny-rate")
    assert gauge._value.get() > 0
    g.close()
    assert gauge._value.get() == 0.0


def test_clean_canary_promotes_at_window_end():
    """No breach evidence -> the window timer promotes, even with zero
    canary traffic (an idle canary must not hang the reconcile)."""
    engine = build_engine(org_corpus({"c": "org-a"}), canary_fraction=0.25,
                          canary_window_s=0.3, canary_thresholds=TH)
    engine.apply_snapshot(entries_of(org_corpus({"c": "org-a2"})))
    assert engine._canary is not None
    gen_candidate = engine._canary.snap.generation
    assert run(_wait(lambda: engine._canary is None, timeout_s=10))
    assert engine._last_rollback is None
    assert engine._snapshot.generation == gen_candidate
    assert run(_submit1(engine, cdoc(0, "org-a2"), "c"))
    assert not run(_submit1(engine, cdoc(1, "org-a"), "c"))


def test_identical_resync_swaps_straight_through():
    """An unchanged-fingerprint resync has nothing to prove: no canary."""
    v1 = org_corpus({"c": "org-a"})
    engine = build_engine(v1, canary_fraction=0.5, canary_window_s=30.0)
    engine.apply_snapshot(entries_of(org_corpus({"c": "org-a"})))
    assert engine._canary is None


def test_reconcile_mid_canary_supersedes():
    """A newer reconcile landing mid-canary rolls the undecided candidate
    back first (never two candidate generations), then canaries itself."""
    engine = build_engine(org_corpus({"c": "org-a"}), canary_fraction=0.5,
                          canary_window_s=30.0, canary_thresholds=TH)
    engine.apply_snapshot(entries_of(org_corpus({"c": "org-b"})))
    first = engine._canary
    assert first is not None
    engine.apply_snapshot(entries_of(org_corpus({"c": "org-c"})))
    assert engine._last_rollback["reason"] == "superseded"
    assert not engine.quarantine_active  # supersede never quarantines
    second = engine._canary
    assert second is not None and second is not first
    assert engine.canary_promote()
    assert run(_submit1(engine, cdoc(0, "org-c"), "c"))
    assert not run(_submit1(engine, cdoc(1, "org-b"), "c"))


# ---------------------------------------------------------------------------
# in-flight batches across rollback / promotion (the PR 8 pinning
# regression, extended to the change-safety transitions)
# ---------------------------------------------------------------------------


def test_inflight_canary_batch_resolves_across_rollback():
    """A batch dispatched under the canary generation, still in flight
    when the rollback lands, resolves with the CANARY snapshot's semantics
    and inserts its verdicts under that generation's tokens — unreachable
    from the rolled-back baseline, which serves its own (different)
    verdict for the same request."""
    engine = build_engine(org_corpus({"c": "org-a"}), canary_fraction=1.0,
                          canary_window_s=30.0, canary_thresholds=TH)
    run(_submit1(engine, cdoc(9, "org-a"), "c"))  # warm jit
    engine.apply_snapshot(entries_of(org_corpus({"c": "org-b"})))
    phase = engine._canary
    assert phase is not None

    gate = threading.Event()
    real = PolicyEngine._encode_and_launch
    gated_launches = []

    class GatedHandle:
        def __init__(self, inner):
            self.inner = inner

        def is_ready(self):
            return gate.is_set() and (
                not hasattr(self.inner, "is_ready")
                or self.inner.is_ready())

        def __array__(self, dtype=None):
            return np.asarray(self.inner)

    def gated(snap, batch):
        item = real(engine, snap, batch)
        item.handle = GatedHandle(item.handle)
        gated_launches.append((snap, item))
        return item

    engine._encode_and_launch = gated

    async def body():
        d = cdoc(42, "org-a")  # denied by the canary, allowed by baseline
        fut = asyncio.ensure_future(engine.submit(dict(d), "c"))
        assert await _wait(lambda: bool(gated_launches), timeout_s=5)
        engine._encode_and_launch = real.__get__(engine, PolicyEngine)
        snap_used, _ = gated_launches[0]
        assert snap_used is phase.snap  # fraction 1.0: rides the canary
        assert engine.canary_rollback()  # manual, mid-flight
        assert engine._canary is None
        adds0 = engine._verdict_cache.adds
        gate.set()
        out = await asyncio.wait_for(fut, timeout=10)
        # pinned semantics: the in-flight batch decided under the canary
        # corpus (org-a denied), no exception, verdict delivered
        assert not bool(out[0][0])
        assert engine._verdict_cache.adds > adds0  # late insert landed
        # the rolled-back generation serves ITS semantics for the same
        # request — the canary-token insert is structurally unreachable
        out2 = await engine.submit(dict(d), "c")
        assert bool(out2[0][0])

    run(body())
    assert engine._last_rollback["manual"] is True


def test_canary_cohort_never_observes_torn_generation():
    """Every canary-cohort request decides under the candidate corpus —
    before, during, and after the promotion race — and every batch rides
    exactly one generation (cohort-partitioned cuts)."""
    fraction = 0.5
    engine = build_engine(org_corpus({"c": "org-a"}), canary_fraction=fraction,
                          canary_window_s=30.0, canary_thresholds=TH,
                          verdict_cache_size=0, batch_dedup=False)
    engine.apply_snapshot(entries_of(org_corpus({"c": "org-b"})))
    assert engine._canary is not None
    # org-b docs: candidate allows, baseline denies — a torn read shows up
    # as a denied canary-cohort verdict
    docs = docs_in_cohort("org-b", 12, fraction, canary=True)

    async def body():
        stop = asyncio.Event()
        results = []

        async def storm():
            while not stop.is_set():
                outs = await asyncio.gather(
                    *[engine.submit(dict(d), "c") for d in docs])
                results.extend(bool(o[0][0]) for o in outs)

        task = asyncio.ensure_future(storm())
        await asyncio.sleep(0.15)
        loop = asyncio.get_running_loop()
        # promote on a worker thread mid-storm (as /debug/canary does)
        assert await loop.run_in_executor(None, engine.canary_promote)
        await asyncio.sleep(0.15)
        stop.set()
        await task
        return results

    results = run(body())
    assert len(results) >= 12
    assert all(results), "a canary-cohort request fell back to the " \
        "baseline generation mid-promotion"
    assert engine._canary is None


# ---------------------------------------------------------------------------
# manual rollback + bounded generation history
# ---------------------------------------------------------------------------


def test_manual_rollback_walks_bounded_history():
    engine = build_engine(org_corpus({"c": "org-v1"}), snapshot_history=2)
    for v in ("org-v2", "org-v3", "org-v4"):
        engine.apply_snapshot(entries_of(org_corpus({"c": v})))
    assert [s.generation for s, _ in engine._history] == [2, 3]  # bounded
    assert engine.canary_rollback()  # no canary active -> history pop
    assert run(_submit1(engine, cdoc(0, "org-v3"), "c"))
    assert engine.rollback_last()
    assert run(_submit1(engine, cdoc(1, "org-v2"), "c"))
    assert not run(_submit1(engine, cdoc(2, "org-v4"), "c"))
    assert not engine.rollback_last()  # history exhausted
    # each rollback was a FRESH generation (monotonic, never reused)
    assert engine.generation == 6
    assert engine.change_safety_vars()["last_rollback"]["manual"] is True


# ---------------------------------------------------------------------------
# leader/replica convergence: the manifest carries the decision
# ---------------------------------------------------------------------------


def test_rollback_record_reaches_replica_via_manifest(tmp_path):
    d = str(tmp_path / "pub")
    leader = build_engine(org_corpus({"c": "org-v1"}), strict_verify=True,
                          snapshot_history=4)
    pub = SnapshotPublisher(d)
    pub.attach(leader)
    leader.apply_snapshot(entries_of(org_corpus({"c": "org-v2"})))
    assert pub.flush()

    replica = build_engine()
    rep = SnapshotReplica(replica, d)
    assert rep.poll_once() is True
    assert run(_submit1(replica, cdoc(0, "org-v2"), "c"))

    assert leader.rollback_last(reason="manual")
    assert pub.flush()
    man = json.loads(open(os.path.join(d, "MANIFEST.json")).read())
    # the manifest names the leader's serving decision + its provenance
    assert man["active_generation"] == man["generation"]
    assert man["rollback"]["reason"] == "manual"
    assert man["rollback"]["from_generation"] == 2

    assert rep.poll_once() is True  # replica converges on the rollback
    assert run(_submit1(replica, cdoc(1, "org-v1"), "c"))
    assert not run(_submit1(replica, cdoc(2, "org-v2"), "c"))
    assert (replica._snapshot.change_safety or {}).get("rollback", {}) \
        .get("reason") == "manual"


# ---------------------------------------------------------------------------
# satellite bounds: flight-recorder disk retention, replica digest memory
# ---------------------------------------------------------------------------


def test_flight_recorder_disk_retention_bounded(tmp_path):
    fr = FlightRecorder(capacity=64, dump_dir=str(tmp_path),
                        min_dump_interval_s=0.0, keep=3)
    for i in range(7):
        fr.dump(f"trigger-{i}")
        time.sleep(0.01)  # distinct mtimes for the prune ordering
    names = [n for n in os.listdir(str(tmp_path))
             if n.startswith("flight-") and n.endswith(".json")]
    assert len(names) == 3
    # the NEWEST bundles survive — the incident just dumped is never the
    # one pruned
    assert any("trigger-6" in n for n in names)
    assert not any("trigger-0" in n for n in names)


def test_replica_rejected_digest_memory_is_one_digest(tmp_path):
    """The rejected-digest memo is the LAST digest only — O(1) across any
    number of distinct rejected publishes (a leader stuck publishing bad
    blobs must not grow replica memory), while still short-circuiting
    re-polls of the same blob."""
    d = str(tmp_path / "pub")
    pub = SnapshotPublisher(d)
    replica = build_engine(org_corpus({"c": "org-a"}))
    rep = SnapshotReplica(replica, d)
    good_snap = replica._snapshot

    def bad_blob(i):
        cfgs = org_corpus({"c": f"org-bad-{i}"})
        policy = compile_corpus(cfgs, members_k=4)
        meta = {"generation": 100 + i, "certified": False,
                "fingerprints": {c.name: rules_fingerprint(c)
                                 for c in cfgs},
                "entries": [{"id": c.name, "hosts": [c.name]}
                            for c in cfgs]}
        return serialize_policy(policy, meta=meta)

    for i in range(12):
        pub.publish_blob(bad_blob(i), 100 + i)
        assert rep.poll_once() is False
        assert rep.poll_once() is False  # memoized: no second admission run
    assert rep.rejected == 12
    assert isinstance(rep._seen_digest, str)  # one digest, not a set
    assert replica._snapshot is good_snap  # old snapshot never stopped


def test_change_safety_vars_json_safe():
    engine = build_engine(org_corpus({"c": "org-a"}), canary_fraction=0.5,
                          canary_window_s=30.0)
    engine.apply_snapshot(entries_of(org_corpus({"c": "org-b"})))
    vars1 = engine.change_safety_vars()
    json.dumps(vars1)  # /debug/vars + /debug/canary must serialize
    assert vars1["canary"]["fraction"] == 0.5
    assert engine.canary_promote()
    json.dumps(engine.change_safety_vars())
