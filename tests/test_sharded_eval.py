"""Sharded (dp × mp) evaluation must agree with the single-corpus model and
the CPU oracle, on an 8-device virtual CPU mesh (conftest sets XLA flags)."""

import random

import jax
import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.models import PolicyModel
from authorino_tpu.parallel import ShardedPolicyModel, build_mesh

from test_compiler_differential import oracle_verdict, random_doc, random_expr


def make_corpus(rng, n_configs):
    configs = []
    for i in range(n_configs):
        evaluators = []
        for _ in range(rng.randint(1, 3)):
            cond = random_expr(rng) if rng.random() < 0.3 else None
            evaluators.append((cond, random_expr(rng)))
        configs.append(ConfigRules(name=f"cfg-{i}", evaluators=evaluators))
    return configs


def test_eight_virtual_devices_present():
    assert len(jax.devices()) >= 8


@pytest.mark.parametrize("seed,dp", [(11, 2), (12, 4), (13, 1)])
def test_sharded_matches_oracle(seed, dp):
    rng = random.Random(seed)
    configs = make_corpus(rng, n_configs=13)  # uneven split across shards
    mesh = build_mesh(n_devices=8, dp=dp)
    sharded = ShardedPolicyModel(configs, mesh, members_k=8)
    single = PolicyModel.from_configs(configs, members_k=8)

    docs = [random_doc(rng) for _ in range(32)]
    names = [f"cfg-{rng.randrange(len(configs))}" for _ in docs]

    got = sharded.decide(docs, names)
    got_single = single.decide(docs, names)
    expected = [oracle_verdict(configs[int(n.split('-')[1])], d) for d, n in zip(docs, names)]
    assert got == expected
    assert got_single == expected


def test_sharded_params_actually_sharded():
    rng = random.Random(7)
    configs = make_corpus(rng, 8)
    mesh = build_mesh(n_devices=8, dp=2)  # mp = 4
    m = ShardedPolicyModel(configs, mesh)
    # leaf tables carry a leading [S=4] axis sharded over mp
    assert m.params["leaf_op"].shape[0] == 4
    shard_devs = {d for d in m.params["leaf_op"].sharding.device_set}
    assert len(shard_devs) == 8  # placed across the whole mesh
