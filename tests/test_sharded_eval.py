"""Sharded (dp × mp) evaluation must agree with the single-corpus model and
the CPU oracle, on an 8-device virtual CPU mesh (conftest sets XLA flags)."""

import random

import jax
import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.models import PolicyModel
from authorino_tpu.parallel import ShardedPolicyModel, build_mesh

from test_compiler_differential import oracle_verdict, random_doc, random_expr


def make_corpus(rng, n_configs):
    configs = []
    for i in range(n_configs):
        evaluators = []
        for _ in range(rng.randint(1, 3)):
            cond = random_expr(rng) if rng.random() < 0.3 else None
            evaluators.append((cond, random_expr(rng)))
        configs.append(ConfigRules(name=f"cfg-{i}", evaluators=evaluators))
    return configs


def test_eight_virtual_devices_present():
    assert len(jax.devices()) >= 8


@pytest.mark.parametrize("seed,dp", [(11, 2), (12, 4), (13, 1)])
def test_sharded_matches_oracle(seed, dp):
    rng = random.Random(seed)
    configs = make_corpus(rng, n_configs=13)  # uneven split across shards
    mesh = build_mesh(n_devices=8, dp=dp)
    sharded = ShardedPolicyModel(configs, mesh, members_k=8)
    single = PolicyModel.from_configs(configs, members_k=8)

    docs = [random_doc(rng) for _ in range(32)]
    names = [f"cfg-{rng.randrange(len(configs))}" for _ in docs]

    got = sharded.decide(docs, names)
    got_single = single.decide(docs, names)
    expected = [oracle_verdict(configs[int(n.split('-')[1])], d) for d, n in zip(docs, names)]
    assert got == expected
    assert got_single == expected


def test_sharded_params_actually_sharded():
    rng = random.Random(7)
    configs = make_corpus(rng, 8)
    mesh = build_mesh(n_devices=8, dp=2)  # mp = 4
    m = ShardedPolicyModel(configs, mesh)
    # leaf tables carry a leading [S=4] axis sharded over mp
    assert m.params["leaf_op"].shape[0] == 4
    shard_devs = {d for d in m.params["leaf_op"].sharding.device_set}
    assert len(shard_devs) == 8  # placed across the whole mesh


def test_sharded_matmul_lane_active():
    """The stacked params carry the MXU matmul operands (one per shard,
    leading [S] axis) — the sharded path must not silently fall back to the
    gather formulation."""
    rng = random.Random(31)
    configs = make_corpus(rng, 9)
    mesh = build_mesh(n_devices=8, dp=2)  # mp = 4
    m = ShardedPolicyModel(configs, mesh, members_k=8)
    assert m.has_matmul and m.params["matmul"] is not None
    assert m.params["matmul"]["attr_onehot"].shape[0] == 4  # [S, A, L]
    # and it still matches the oracle end-to-end
    docs = [random_doc(rng) for _ in range(16)]
    names = [f"cfg-{rng.randrange(9)}" for _ in docs]
    expected = [oracle_verdict(configs[int(n.split('-')[1])], d) for d, n in zip(docs, names)]
    assert m.decide(docs, names) == expected


def test_sharded_dfa_lane_rides_the_mesh():
    """Regexes concentrated in a few configs: only some shards naturally
    have DFA rows, the ShapeTargets union forces a uniform lane, and the
    device verdicts still match the oracle."""
    from authorino_tpu.expressions import All, Operator, Pattern

    configs = []
    for i in range(9):  # 9 configs over mp=4 shards → uneven
        pats = [Pattern("request.method", Operator.EQ, "GET")]
        if i % 3 == 0:  # regexes only in configs 0,3,6 → shards 0,3,2
            pats.append(Pattern("request.url_path", Operator.MATCHES, rf"^/svc-{i}/\d+$"))
        configs.append(ConfigRules(name=f"cfg-{i}", evaluators=[(None, All(*pats))]))
    mesh = build_mesh(n_devices=8, dp=2)
    m = ShardedPolicyModel(configs, mesh, members_k=4)
    assert m.has_dfa and m.params["dfa_tables"] is not None

    docs, names, expected = [], [], []
    for i in range(9):
        for path, ok in [(f"/svc-{i}/42", True), (f"/svc-{i}/x", False)]:
            docs.append({"request": {"method": "GET", "url_path": path}})
            names.append(f"cfg-{i}")
            expected.append(ok if i % 3 == 0 else True)
    assert m.decide(docs, names) == expected


def test_sharded_full_outputs_match_single_corpus():
    """apply_full returns the same own (verdict, rule, skipped) tensors as
    the single-corpus eval_full_jit — the contract PolicyEngine serves."""
    import jax.numpy as jnp

    from authorino_tpu.ops.pattern_eval import eval_full_jit

    rng = random.Random(21)
    configs = make_corpus(rng, 11)
    mesh = build_mesh(n_devices=8, dp=2)
    sharded = ShardedPolicyModel(configs, mesh, members_k=8)
    single = PolicyModel.from_configs(configs, members_k=8)

    docs = [random_doc(rng) for _ in range(24)]
    names = [f"cfg-{rng.randrange(len(configs))}" for _ in docs]
    rows = [single.policy.config_ids[n] for n in names]

    enc_s = sharded.encode(docs, names)
    own_s, rule_s, skip_s = sharded.apply_full(enc_s)

    db = single.encode(docs, rows)
    has_dfa = single.params["dfa_tables"] is not None
    own_1, rule_1, skip_1 = (
        np.asarray(a)
        for a in eval_full_jit(
            single.params,
            jnp.asarray(db.attrs_val),
            jnp.asarray(db.members_c),
            jnp.asarray(db.cpu_dense),
            jnp.asarray(db.config_id),
            jnp.asarray(db.attr_bytes) if has_dfa else None,
            jnp.asarray(db.byte_ovf) if has_dfa else None,
        )
    )
    B = len(docs)
    ok = ~enc_s.host_fallback[:B]  # compact-lossy rows go to the host oracle
    E = min(rule_s.shape[1], rule_1.shape[1])  # padding columns may differ
    assert (own_s[:B][ok] == own_1[:B][ok]).all()
    assert (rule_s[:B, :E][ok] == rule_1[:B, :E][ok]).all()
    assert (skip_s[:B, :E][ok] == skip_1[:B, :E][ok]).all()


def test_engine_serves_from_sharded_snapshot():
    """PolicyEngine auto-detects the multi-device mesh, compiles the corpus
    as a ShardedPolicyModel (non-default members_k plumbed through), and the
    batched submit path returns oracle-exact rule/skipped."""
    import asyncio

    from authorino_tpu.expressions import All, Any_, Operator, Pattern
    from authorino_tpu.runtime import EngineEntry, PolicyEngine

    engine = PolicyEngine(max_batch=4, members_k=4)
    entries = []
    exprs = {}
    for i in range(6):
        rule = All(
            Pattern("request.method", Operator.EQ, "GET"),
            Any_(
                Pattern("auth.identity.roles", Operator.INCL, f"r{i}"),
                Pattern("request.url_path", Operator.MATCHES, rf"^/pub-{i}/"),
            ),
        )
        exprs[f"ns/cfg-{i}"] = rule
        entries.append(
            EngineEntry(
                id=f"ns/cfg-{i}",
                hosts=[f"svc-{i}.example.com"],
                runtime=None,
                rules=ConfigRules(name=f"ns/cfg-{i}", evaluators=[(None, rule)]),
            )
        )
    engine.apply_snapshot(entries)
    assert engine._snapshot.sharded is not None  # 8 virtual devices → sharded
    # the base K is plumbed through; shards compile at the grid-relief K
    # (mp shards → ~mp× larger compact membership grid, capped)
    sharded = engine._snapshot.sharded
    assert sharded.members_k == 4
    assert sharded.shards[0].members_k == sharded.members_k_eff
    assert sharded.members_k_eff == 4 * sharded.n_shards

    docs = [
        {"request": {"method": "GET", "url_path": "/pub-2/x"},
         "auth": {"identity": {"roles": ["nope"]}}},
        {"request": {"method": "GET", "url_path": "/priv"},
         "auth": {"identity": {"roles": ["r3", "other"]}}},
        {"request": {"method": "POST", "url_path": "/pub-4/x"},
         "auth": {"identity": {"roles": ["r4"]}}},
        # membership overflow vs members_k=4 → host-fallback lane
        {"request": {"method": "GET", "url_path": "/priv"},
         "auth": {"identity": {"roles": [f"x{k}" for k in range(9)] + ["r5"]}}},
    ]
    names = ["ns/cfg-2", "ns/cfg-3", "ns/cfg-4", "ns/cfg-5"]

    async def run():
        return await asyncio.gather(*[engine.submit(d, n) for d, n in zip(docs, names)])

    results = asyncio.new_event_loop().run_until_complete(run())
    got = [bool(rule[0]) for rule, _ in results]
    expected = [bool(exprs[n].matches(d)) for d, n in zip(docs, names)]
    assert got == expected == [True, True, False, True]


class TestServingPathBitParity:
    """VERDICT sweep: the mesh serving path and the single-corpus serving
    path must produce IDENTICAL per-evaluator (rule, skipped) bits on a
    corpus that exercises all three lanes — device-DFA regex rows (incl.
    byte-tensor overflow), membership overflow (host-fallback lane), and
    compiled evaluator conditions — across dp=1,2,4 mesh shapes."""

    K = 4  # small members_k so overflow is easy to trigger

    def corpus(self):
        from authorino_tpu.expressions import All, Any_, Operator, Pattern

        rx = Pattern("request.url_path", Operator.MATCHES, r"^/api/v[0-9]+/ok")
        cond = Pattern("request.method", Operator.EQ, "GET")
        gated = Pattern("request.path", Operator.EQ, "/gated")
        mem = All(Pattern("auth.identity.roles", Operator.INCL, "admin"),
                  Pattern("auth.identity.groups", Operator.EXCL, "banned"))
        mix = Any_(rx, Pattern("auth.identity.roles", Operator.INCL, "root"))
        return {
            "cfg-rx": ConfigRules(name="cfg-rx", evaluators=[(None, rx), (cond, gated)]),
            "cfg-mem": ConfigRules(name="cfg-mem", evaluators=[(None, mem)]),
            "cfg-mix": ConfigRules(name="cfg-mix", evaluators=[(cond, mix)]),
        }

    def docs(self):
        long_ok = "/api/v3/ok" + "x" * 120     # > DFA_VALUE_BYTES → byte overflow
        long_no = "/nope/" + "y" * 120
        many = [f"r{k}" for k in range(9)]     # > members_k → host fallback
        return [
            ({"request": {"url_path": "/api/v1/ok", "method": "GET", "path": "/gated"},
              "auth": {"identity": {}}}, "cfg-rx"),
            ({"request": {"url_path": "/api/x", "method": "POST", "path": "/other"},
              "auth": {"identity": {}}}, "cfg-rx"),
            ({"request": {"url_path": long_ok, "method": "GET", "path": "/other"},
              "auth": {"identity": {}}}, "cfg-rx"),
            ({"request": {"url_path": long_no, "method": "POST", "path": "/gated"},
              "auth": {"identity": {}}}, "cfg-rx"),
            ({"request": {}, "auth": {"identity": {"roles": many + ["admin"], "groups": []}}},
             "cfg-mem"),
            ({"request": {}, "auth": {"identity": {"roles": many, "groups": ["banned"]}}},
             "cfg-mem"),
            ({"request": {}, "auth": {"identity": {"roles": ["admin"], "groups": []}}},
             "cfg-mem"),
            ({"request": {"url_path": "/api/v9/ok", "method": "GET"},
              "auth": {"identity": {"roles": many}}}, "cfg-mix"),
            ({"request": {"url_path": "/zzz", "method": "POST"},
              "auth": {"identity": {"roles": many + ["root"]}}}, "cfg-mix"),
        ]

    @pytest.mark.parametrize("dp", [1, 2, 4])
    def test_bit_parity(self, dp):
        import asyncio

        from authorino_tpu.runtime import EngineEntry, PolicyEngine

        corpus = self.corpus()

        def engine_for(mesh):
            e = PolicyEngine(max_batch=16, members_k=self.K,
                             mesh=mesh)
            e.apply_snapshot([EngineEntry(id=n, hosts=[n], runtime=None, rules=c)
                              for n, c in corpus.items()])
            return e

        single = engine_for(None)
        sharded = engine_for(build_mesh(n_devices=8, dp=dp))
        assert sharded._snapshot.sharded is not None  # really on the mesh
        assert single._snapshot.policy is not None

        async def collect(engine):
            outs = await asyncio.gather(
                *(engine.submit(doc, name) for doc, name in self.docs()))
            return [(tuple(map(bool, r)), tuple(map(bool, s))) for r, s in outs]

        got_sharded = asyncio.run(collect(sharded))
        got_single = asyncio.run(collect(single))
        assert got_sharded == got_single

        # both agree with the expression oracle per evaluator slot
        for (doc, name), (rule_bits, skip_bits) in zip(self.docs(), got_single):
            evs = corpus[name].evaluators
            for e, (cond, rule) in enumerate(evs):
                want_skip = cond is not None and not cond.matches(doc)
                assert skip_bits[e] == want_skip, (name, e)
                if not want_skip:
                    assert rule_bits[e] == rule.matches(doc), (name, e)
