"""Differential test: matmul (MXU) lane vs gather lane of the evaluation
kernel — same compiled corpus, same encoded batches, bit-identical outputs.

The gather lane is the semantic reference (ops/pattern_eval.py module doc);
the matmul lane is the default serving lane.  A bf16 variant runs only where
the backend has MXU-style bf16 dot support (skipped on CPU CI, exercised on
real TPU runs)."""

import os
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.compiler.encode import encode_batch_py
from authorino_tpu.compiler.pack import pack_batch
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.ops import pattern_eval as pe


def _mixed_corpus(n_configs=23, seed=5):
    rng = random.Random(seed)
    configs = []
    for i in range(n_configs):
        pats = [
            Pattern("request.method", Operator.EQ, rng.choice(["GET", "POST"])),
            Pattern("auth.identity.org", Operator.NEQ, f"org-{i % 7}"),
            Pattern("auth.identity.roles", Operator.INCL, f"role-{i % 5}"),
            Pattern("auth.identity.groups", Operator.EXCL, f"banned-{i % 3}"),
            Pattern("request.url_path", Operator.MATCHES, rf"^/svc-{i % 4}/"),
        ]
        rule = All(pats[0], Any_(*pats[1:]))
        cond = Pattern("request.headers.x-env", Operator.NEQ, "dev") if i % 2 else None
        configs.append(ConfigRules(name=f"cfg-{i}", evaluators=[(cond, rule)]))
    return configs


def _docs(n, seed=11):
    rng = random.Random(seed)
    docs = []
    for _ in range(n):
        docs.append(
            {
                "request": {
                    "method": rng.choice(["GET", "POST", "PUT"]),
                    "url_path": rng.choice(["/svc-0/a", "/svc-1/b", "/other", "/svc-3/"]),
                    "headers": {"x-env": rng.choice(["dev", "prod"])},
                },
                "auth": {
                    "identity": {
                        "org": f"org-{rng.randrange(9)}",
                        "roles": [f"role-{rng.randrange(7)}" for _ in range(rng.randrange(0, 20))],
                        "groups": [f"banned-{rng.randrange(5)}" for _ in range(rng.randrange(0, 3))],
                    }
                },
            }
        )
    return docs


def _both_lane_params(policy, monkeypatch):
    monkeypatch.setenv("AUTHORINO_TPU_EVAL_LANE", "matmul")
    params_mm = pe.to_device(policy)
    monkeypatch.setenv("AUTHORINO_TPU_EVAL_LANE", "gather")
    params_g = pe.to_device(policy)
    assert params_mm["matmul"] is not None
    assert params_g["matmul"] is None
    return params_mm, params_g


def test_matmul_lane_matches_gather_lane(monkeypatch):
    policy = compile_corpus(_mixed_corpus(), members_k=4)
    params_mm, params_g = _both_lane_params(policy, monkeypatch)
    docs = _docs(64)
    rows = [i % policy.n_configs for i in range(len(docs))]
    db = pack_batch(policy, encode_batch_py(policy, docs, rows, batch_pad=64))
    args = (
        jnp.asarray(db.attrs_val),
        jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense),
        jnp.asarray(db.attr_bytes),
        jnp.asarray(db.byte_ovf),
    )
    v_mm, (r_mm, s_mm) = pe.eval_verdicts(params_mm, *args)
    v_g, (r_g, s_g) = pe.eval_verdicts(params_g, *args)
    np.testing.assert_array_equal(np.asarray(v_mm), np.asarray(v_g))
    np.testing.assert_array_equal(np.asarray(r_mm), np.asarray(r_g))
    np.testing.assert_array_equal(np.asarray(s_mm), np.asarray(s_g))


def test_matmul_lane_bf16_matches_gather_lane(monkeypatch):
    """bf16 operand numerics (the real TPU configuration)."""
    if jax.default_backend() == "cpu":
        pytest.skip("CPU dot kernels lack BF16xBF16->F32")
    policy = compile_corpus(_mixed_corpus(31), members_k=4)
    params_mm, params_g = _both_lane_params(policy, monkeypatch)
    assert params_mm["matmul"]["rule_m"].dtype == jnp.bfloat16
    docs = _docs(128, seed=17)
    rows = [i % policy.n_configs for i in range(len(docs))]
    db = pack_batch(policy, encode_batch_py(policy, docs, rows, batch_pad=128))
    args = (
        jnp.asarray(db.attrs_val),
        jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense),
        jnp.asarray(db.attr_bytes),
        jnp.asarray(db.byte_ovf),
    )
    v_mm, _ = pe.eval_verdicts(params_mm, *args)
    v_g, _ = pe.eval_verdicts(params_g, *args)
    np.testing.assert_array_equal(np.asarray(v_mm), np.asarray(v_g))


def test_bitpacked_readback_roundtrips_both_lanes(monkeypatch):
    """The packed u8 bitmask readback (8 verdicts/byte, little bit order)
    must round-trip exactly against the unpacked [B, 1+2E] verdict arrays
    on BOTH the matmul and gather lanes — the D2H compression can never
    change an answer."""
    policy = compile_corpus(_mixed_corpus(), members_k=4)
    params_mm, params_g = _both_lane_params(policy, monkeypatch)
    docs = _docs(64)
    rows = [i % policy.n_configs for i in range(len(docs))]
    db = pack_batch(policy, encode_batch_py(policy, docs, rows, batch_pad=64))
    args = (
        jnp.asarray(db.attrs_val),
        jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense),
        jnp.asarray(db.config_id),
        jnp.asarray(db.attr_bytes),
        jnp.asarray(db.byte_ovf),
    )
    E = int(policy.eval_rule.shape[1])
    cols = 1 + 2 * E
    for params in (params_mm, params_g):
        reference = np.asarray(pe.eval_packed_jit(params, *args))
        packed = np.asarray(pe.eval_bitpacked_jit(params, *args))
        assert packed.dtype == np.uint8
        assert packed.shape == (reference.shape[0], pe.packed_width(cols))
        np.testing.assert_array_equal(
            pe.unpack_verdicts(packed, cols), reference)
    # bits past the verdict columns are zero padding (byte-stable wire)
    tail_bits = pe.packed_width(cols) * 8 - cols
    if tail_bits:
        full = np.unpackbits(packed, axis=1, bitorder="little")
        assert not full[:, cols:].any()


def test_interner_overflow_falls_back_to_gather(monkeypatch):
    policy = compile_corpus(_mixed_corpus(5), members_k=4)
    monkeypatch.setenv("AUTHORINO_TPU_EVAL_LANE", "matmul")
    monkeypatch.setattr(pe, "_F32_EXACT", len(policy.interner))
    params = pe.to_device(policy)
    assert params["matmul"] is None  # ids no longer exact in f32
