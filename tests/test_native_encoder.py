"""Differential tests: native (C++) encoder vs the Python reference encoder.

Every field of EncodedBatch must be bit-identical across randomized corpora
and documents — strings, unicode/escapes, numbers (int/float/edge renderings),
arrays with membership overflow, nested raw-JSON values, device-regex byte
lanes and overflows, CPU-lane regexes, whole-tree fallbacks, and
gjson-extended (complex) selectors finished in Python."""

import json
import random
import string

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.compiler.encode import encode_batch_py as encode_batch
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.native import get_native_encoder, load_library

pytestmark = pytest.mark.skipif(load_library() is None, reason="native encoder unavailable")


def assert_same(policy, docs, rows, batch_pad=0):
    nat = get_native_encoder(policy)
    assert nat is not None
    a = encode_batch(policy, docs, rows, batch_pad=batch_pad)
    b = nat.encode_batch(docs, rows, batch_pad=batch_pad)
    assert b is not None, "native encoder bailed"
    for f in ("attrs_val", "attrs_members", "overflow", "cpu_lane", "config_id",
              "attr_bytes", "byte_ovf"):
        av, bv = getattr(a, f), getattr(b, f)
        assert np.array_equal(av, bv), (
            f"{f} mismatch:\npy={av}\nnative={bv}\ndocs={json.dumps(docs)[:500]}"
        )


def one_config(*patterns, name="cfg-0", cond=None):
    rule = All(*patterns) if len(patterns) > 1 else patterns[0]
    return ConfigRules(name=name, evaluators=[(cond, rule)])


class TestScalars:
    def test_string_values(self):
        policy = compile_corpus([one_config(Pattern("a.b", Operator.EQ, "x"))])
        docs = [{"a": {"b": "x"}}, {"a": {"b": "y"}}, {"a": {}}, {}, {"a": {"b": ""}}]
        assert_same(policy, docs, [0] * len(docs))

    def test_numbers_and_bools(self):
        pats = [
            Pattern("v.i", Operator.EQ, "42"),
            Pattern("v.f", Operator.EQ, "1.5"),
            Pattern("v.b", Operator.EQ, "true"),
            Pattern("v.n", Operator.NEQ, ""),
        ]
        policy = compile_corpus([one_config(*pats)])
        docs = [
            {"v": {"i": 42, "f": 1.5, "b": True, "n": None}},
            {"v": {"i": 42.0, "f": 3, "b": False, "n": 0}},
            {"v": {"i": -0.0, "f": 0.1, "b": True, "n": 10**30}},
            {"v": {"i": 1e16, "f": 1.5e-7, "b": True, "n": -12345678901234567890}},
            {"v": {"i": 0.30000000000000004, "f": 123456789.123456789, "b": True, "n": 2**63}},
            {"v": {"i": 1e15 + 0.5, "f": -1.2345e22, "b": False, "n": 5e-324}},
        ]
        assert_same(policy, docs, [0] * len(docs))

    def test_unicode_and_escapes(self):
        policy = compile_corpus([one_config(Pattern("s", Operator.EQ, "héllo\nworld"))])
        docs = [
            {"s": "héllo\nworld"},
            {"s": "naïve £ → 🎉"},
            {"s": 'quote " backslash \\ tab\t'},
            {"s": "nul\x00byte"},
            {"s": "\x01\x02control"},
        ]
        assert_same(policy, docs, [0] * len(docs))


class TestMembership:
    def test_arrays_and_overflow(self):
        pats = [
            Pattern("roles", Operator.INCL, "admin"),
            Pattern("groups", Operator.EXCL, "banned"),
        ]
        policy = compile_corpus([one_config(*pats)], members_k=4)
        docs = [
            {"roles": ["admin"], "groups": []},
            {"roles": ["a", "b", "c", "d", "e", "admin"], "groups": list("abcdefg") + ["banned"]},
            {"roles": "admin", "groups": None},           # scalar / null
            {"roles": [1, 2.5, True, None], "groups": [["nested"], {"k": "v"}]},
            {"roles": [f"r{i}" for i in range(20)], "groups": [f"g{i}" for i in range(20)]},
        ]
        assert_same(policy, docs, [0] * len(docs))

    def test_nested_raw_json_rendering(self):
        policy = compile_corpus([one_config(
            Pattern("obj", Operator.EQ, '{"a":1,"b":[true,null]}'))])
        docs = [
            {"obj": {"a": 1, "b": [True, None]}},
            {"obj": {"a": 1.0, "b": [True, None]}},   # float renders 1.0 in dumps
            {"obj": [{"x": "é"}, 2.5, -0.0]},
            {"obj": {"k": 'str with " and \\'}},
        ]
        assert_same(policy, docs, [0] * len(docs))


class TestRegexLanes:
    def test_dfa_lane_and_byte_overflow(self):
        policy = compile_corpus([one_config(
            Pattern("path", Operator.MATCHES, r"^/api/v\d+/"))])
        docs = [
            {"path": "/api/v1/x"},
            {"path": "/other"},
            {"path": "/api/v" + "9" * 300 + "/long-overflow"},   # > DFA_VALUE_BYTES
            {"path": "nul\x00inside"},
            {"path": 123},
        ]
        assert_same(policy, docs, [0] * len(docs))

    def test_cpu_regex_lane(self):
        # backreference → not DFA-compilable → OP_CPU
        policy = compile_corpus([one_config(
            Pattern("s", Operator.MATCHES, r"(ab)\1"))])
        docs = [{"s": "abab"}, {"s": "ab"}, {"s": ""}, {}]
        assert_same(policy, docs, [0] * len(docs))

    def test_tree_cpu_fallback(self):
        # invalid regex → whole-tree CPU oracle leaf
        policy = compile_corpus([one_config(
            Pattern("a", Operator.EQ, "1"),
            Any_(Pattern("s", Operator.MATCHES, "([bad"), Pattern("b", Operator.EQ, "2")),
        )])
        docs = [{"a": "1", "s": "x", "b": "2"}, {"a": "1", "b": "3"}]
        assert_same(policy, docs, [0] * len(docs))


class TestComplexSelectors:
    def test_modifiers_finished_in_python(self):
        pats = [
            Pattern("user.name|@case:upper", Operator.EQ, "ALICE"),
            Pattern("plain.key", Operator.EQ, "v"),
        ]
        policy = compile_corpus([one_config(*pats)])
        docs = [
            {"user": {"name": "alice"}, "plain": {"key": "v"}},
            {"user": {"name": "Bob"}, "plain": {"key": "w"}},
        ]
        assert_same(policy, docs, [0] * len(docs))

    def test_array_index_path(self):
        policy = compile_corpus([one_config(Pattern("items.1.id", Operator.EQ, "second"))])
        docs = [
            {"items": [{"id": "first"}, {"id": "second"}]},
            {"items": [{"id": "only"}]},
            {"items": "not-a-list"},
        ]
        assert_same(policy, docs, [0] * len(docs))

    def test_array_index_int_divergent_segments(self):
        # segments Python int() accepts but the C digit parser rejects
        # (underscores, non-ASCII decimal digits) must be Python-finished,
        # not silently resolved to missing by the native walk
        policy = compile_corpus([
            one_config(Pattern("items.1_0.id", Operator.EQ, "eleventh"), name="cfg-0"),
            ConfigRules(name="cfg-1", evaluators=[
                (None, Pattern("items.١.id", Operator.EQ, "second"))]),
        ])
        docs = [
            {"items": [{"id": f"item-{i}"} for i in range(12)]},
            {"items": [{"id": "first"}, {"id": "second"}]},
            {"items": []},
        ]
        assert_same(policy, docs, [0, 1, 0])

    def test_escaped_dot_key(self):
        policy = compile_corpus([one_config(
            Pattern(r"headers.x\.request\.id", Operator.EQ, "r1"))])
        docs = [{"headers": {"x.request.id": "r1"}}, {"headers": {"x": {"request": {"id": "r1"}}}}]
        assert_same(policy, docs, [0] * len(docs))


class TestMultiConfigRandomized:
    def _random_corpus(self, rng, n_configs=8):
        configs = []
        for i in range(n_configs):
            pats = [Pattern("request.method", Operator.EQ, rng.choice(["GET", "POST"]))]
            for j in range(rng.randrange(1, 5)):
                kind = rng.random()
                if kind < 0.2:
                    pats.append(Pattern("request.url_path", Operator.MATCHES, rf"^/svc-{i}/r{j}"))
                elif kind < 0.5:
                    pats.append(Pattern("auth.identity.roles", Operator.INCL, f"role-{i}-{j}"))
                elif kind < 0.7:
                    pats.append(Pattern("auth.identity.groups", Operator.EXCL, f"ban-{i}"))
                else:
                    pats.append(Pattern(f"request.headers.h{j}", Operator.NEQ, f"v{i}"))
            configs.append(one_config(*pats, name=f"cfg-{i}",
                                      cond=Pattern("env", Operator.NEQ, "dev") if rng.random() < 0.3 else None))
        return configs

    def _random_doc(self, rng):
        return {
            "request": {
                "method": rng.choice(["GET", "POST", "PUT"]),
                "url_path": rng.choice(["/svc-1/r0", "/svc-0/r1", "/x", "/" + "y" * rng.choice([3, 200])]),
                "headers": {f"h{j}": rng.choice(["v0", "v3", "", 7, None]) for j in range(rng.randrange(4))},
            },
            "auth": {"identity": {
                "roles": [f"role-{rng.randrange(8)}-{rng.randrange(5)}" for _ in range(rng.randrange(12))],
                "groups": rng.choice([[], ["ban-1"], [f"g{k}" for k in range(15)], "scalar", None]),
            }},
            "env": rng.choice(["dev", "prod", 1, None]),
        }

    def test_randomized_differential(self):
        rng = random.Random(1234)
        for trial in range(5):
            configs = self._random_corpus(rng)
            policy = compile_corpus(configs, members_k=4)
            n = rng.randrange(1, 40)
            docs = [self._random_doc(rng) for _ in range(n)]
            rows = [rng.randrange(len(configs)) for _ in range(n)]
            assert_same(policy, docs, rows, batch_pad=rng.choice([0, 64]))

    def test_empty_batch(self):
        policy = compile_corpus([one_config(Pattern("a", Operator.EQ, "1"))])
        assert_same(policy, [], [], batch_pad=8)


class TestVerdictParity:
    """End-to-end: native-encoded batches produce identical kernel verdicts."""

    def test_verdicts_match(self):
        from authorino_tpu.compiler.pack import pack_batch
        from authorino_tpu.ops.pattern_eval import eval_batch_jit, to_device

        rng = random.Random(7)
        tc = TestMultiConfigRandomized()
        configs = tc._random_corpus(rng)
        policy = compile_corpus(configs, members_k=4)
        params = to_device(policy)
        docs = [tc._random_doc(rng) for _ in range(32)]
        rows = [rng.randrange(len(configs)) for _ in range(32)]
        nat = get_native_encoder(policy)
        own_py, _ = eval_batch_jit(params, pack_batch(policy, encode_batch(policy, docs, rows)))
        own_nat, _ = eval_batch_jit(params, pack_batch(policy, nat.encode_batch(docs, rows)))
        assert np.array_equal(own_py, own_nat)
