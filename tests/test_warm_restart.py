"""Crash-safe warm restart (ISSUE 20): the durable state plane, fs-stage
fault injection through the one atomic-write discipline, torn-write fuzzing
of every container reader, leader-dominance over local warm state, and the
SIGKILL kill harness (serve → kill -9 → restart from disk alone → bit-exact
verdicts, every artifact old-valid or new-valid).

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports); JAX_PLATFORMS=cpu."""

from __future__ import annotations

import asyncio
import errno
import json
import os
import random
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.corpus.store import CorpusFormatError, read_corpus_file, \
    write_corpus
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.replay.capture import CaptureFormatError, read_segment, \
    write_segment
from authorino_tpu.runtime import EngineEntry, PolicyEngine, faults
from authorino_tpu.runtime.flight_recorder import RECORDER
from authorino_tpu.runtime.state_plane import StatePlane
from authorino_tpu.snapshots import rules_fingerprint, serialize_policy
from authorino_tpu.snapshots.distribution import (
    SnapshotLoadError,
    SnapshotPublisher,
    SnapshotReplica,
    load_hotset,
    load_latest,
    load_snapshot_blob,
)
from authorino_tpu.utils.atomicio import atomic_write_bytes, atomic_write_json


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test leaves the process-wide fault plane OFF."""
    yield
    faults.FAULTS.disarm()


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def sample(name, labels=None):
    from prometheus_client import REGISTRY

    v = REGISTRY.get_sample_value(name, labels or {})
    return 0.0 if v is None else v


def make_corpus(n=6, tag=""):
    cfgs = []
    for i in range(n):
        rule = All(
            Pattern("request.method", Operator.EQ, ["GET", "POST"][i % 2]),
            Any_(
                Pattern("auth.identity.org", Operator.EQ, f"org-{i}{tag}"),
                Pattern("auth.identity.roles", Operator.INCL, f"role-{i}"),
            ),
        )
        cfgs.append(ConfigRules(name=f"cfg-{i}", evaluators=[(None, rule)]))
    return cfgs


def entries_of(cfgs):
    return [EngineEntry(id=c.name, hosts=[c.name], runtime=None, rules=c)
            for c in cfgs]


def build_engine(cfgs=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("verdict_cache_size", 4096)
    kw.setdefault("lane_select", False)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    if cfgs is not None:
        engine.apply_snapshot(entries_of(cfgs))
    return engine


def doc(i, method="GET"):
    return {"request": {"method": method, "url_path": "/x"},
            "auth": {"identity": {"org": f"org-{i}", "roles": []}}}


def seed_state_dir(d, cfgs=None, traffic=0):
    """A leader publishes its vetted snapshot (and optionally a warmed hot
    set) into ``d`` — the exact write path the state plane uses."""
    leader = build_engine(strict_verify=True)
    plane = StatePlane(leader, d)
    plane.start()
    leader.apply_snapshot(entries_of(cfgs or make_corpus()))
    assert plane.publisher.flush()
    if traffic:
        async def pump():
            await asyncio.gather(*[leader.submit(doc(i % 6), f"cfg-{i % 6}")
                                   for i in range(traffic)])

        run(pump())
        assert plane.export_hotset_once()
    return leader


# ---------------------------------------------------------------------------
# atomic writes under injected fs faults
# ---------------------------------------------------------------------------


class TestAtomicWriteFaults:
    @pytest.mark.parametrize("mode", ["eio", "enospc", "short",
                                      "rename-fail"])
    def test_destination_intact_and_tmp_unlinked(self, tmp_path, mode):
        path = str(tmp_path / "MANIFEST.json")
        atomic_write_bytes(path, b"OLD-VALID", artifact="manifest")
        faults.FAULTS.arm(f"fs:{mode}:artifact=manifest:n=1")
        before = sample("auth_server_state_write_failures_total",
                        {"artifact": "manifest"})
        with pytest.raises(OSError):
            atomic_write_bytes(path, b"NEW" * 100, artifact="manifest")
        assert open(path, "rb").read() == b"OLD-VALID"
        assert not os.path.exists(path + ".tmp")
        assert sample("auth_server_state_write_failures_total",
                      {"artifact": "manifest"}) == before + 1
        # n=1 exhausted: the next write goes through
        atomic_write_bytes(path, b"NEW-VALID", artifact="manifest")
        assert open(path, "rb").read() == b"NEW-VALID"

    def test_torn_write_scribbles_destination_prefix(self, tmp_path):
        """torn is the one deliberate exception: the DESTINATION holds a
        prefix afterwards — the aftermath readers must reject typed."""
        path = str(tmp_path / "seg.atpucap")
        write_segment(path, [{"i": 1}])
        faults.FAULTS.arm("fs:torn:artifact=capture:n=1")
        with pytest.raises(OSError):
            write_segment(path, [{"i": k} for k in range(50)])
        with pytest.raises(CaptureFormatError):
            read_segment(path)

    def test_artifact_scoping(self, tmp_path):
        """A rule scoped artifact=hotset must not touch manifest writes."""
        faults.FAULTS.arm("fs:eio:artifact=hotset")
        m = str(tmp_path / "MANIFEST.json")
        atomic_write_json(m, {"ok": 1}, artifact="manifest")
        assert json.load(open(m)) == {"ok": 1}
        with pytest.raises(OSError) as e:
            atomic_write_json(str(tmp_path / "HOTSET.json"), {},
                              artifact="hotset")
        assert e.value.errno == errno.EIO

    def test_deterministic_prefix_from_seed(self, tmp_path):
        """Same seed ⇒ same torn prefix bytes (reproducible crashes)."""
        torn = []
        for trial in range(2):
            path = str(tmp_path / f"t{trial}")
            faults.FAULTS.arm("fs:torn:n=1", seed=99)
            with pytest.raises(OSError):
                atomic_write_bytes(path, bytes(range(256)) * 8)
            torn.append(open(path, "rb").read())
            faults.FAULTS.disarm()
        assert torn[0] == torn[1]


# ---------------------------------------------------------------------------
# reader fuzz: every container rejects corruption TYPED, never unhandled
# ---------------------------------------------------------------------------


def _mutations(blob, rng):
    """Crash/corruption shapes: truncation (torn tail), bit flip, garbage
    prepend/append, empty file, and a bare prefix (torn overwrite)."""
    out = [b"", blob[:rng.randrange(1, len(blob))]]
    flip = bytearray(blob)
    i = rng.randrange(len(flip))
    flip[i] ^= 1 << rng.randrange(8)
    out.append(bytes(flip))
    out.append(b"\x00garbage\x00" + blob)
    out.append(blob + b"trailing-junk")
    out.append(blob[: len(blob) // 2])
    return out


class TestReaderFuzz:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_all_five_readers_reject_typed_or_serve_old(self, tmp_path,
                                                        seed):
        rng = random.Random(seed)
        d = str(tmp_path)
        seed_state_dir(d, traffic=24)
        snap_name = json.load(open(os.path.join(d, "MANIFEST.json")))[
            "current"]
        cap = os.path.join(d, "seg.atpucap")
        corp = os.path.join(d, "c.atpucorp")
        rows = [{"authconfig": "cfg-0", "doc": {"i": i}, "rule_index": 0,
                 "lane": "device", "verdict": True} for i in range(8)]
        write_segment(cap, rows)
        write_corpus(corp, rows)

        # (reader, path, typed-failure contract)
        cases = [
            ("snapshot-blob",
             os.path.join(d, snap_name),
             lambda p: load_snapshot_blob(open(p, "rb").read()),
             (SnapshotLoadError,)),
            ("manifest",
             os.path.join(d, "MANIFEST.json"),
             lambda p: load_latest(d),
             (SnapshotLoadError,)),
            ("hotset",
             os.path.join(d, "HOTSET.json"),
             lambda p: load_hotset(d),          # total: dict or None
             ()),
            ("capture", cap, read_segment, (CaptureFormatError,)),
            ("corpus", corp, read_corpus_file, (CorpusFormatError,)),
        ]
        for name, path, reader, typed in cases:
            pristine = open(path, "rb").read()
            reader(path)  # the pristine artifact must load
            for mut in _mutations(pristine, rng):
                with open(path, "wb") as f:
                    f.write(mut)
                try:
                    reader(path)
                except typed:
                    pass  # typed rejection IS the contract
                except Exception as e:  # pragma: no cover - the assertion
                    pytest.fail(f"{name}: unhandled {type(e).__name__} "
                                f"on {len(mut)}-byte mutation: {e}")
            with open(path, "wb") as f:
                f.write(pristine)
            reader(path)  # old-valid restored ⇒ loads again

    def test_corrupt_state_dir_is_typed_cold_start_not_a_boot_failure(
            self, tmp_path):
        d = str(tmp_path)
        seed_state_dir(d)
        snap_name = json.load(open(os.path.join(d, "MANIFEST.json")))[
            "current"]
        blob_path = os.path.join(d, snap_name)
        with open(blob_path, "wb") as f:
            f.write(open(blob_path, "rb").read()[:100])  # torn blob
        engine = build_engine(strict_verify=True)
        plane = StatePlane(engine, d)
        summary = plane.warm_start()  # must NOT raise
        assert summary["snapshot"] == "error"
        snap = engine._snapshot
        assert snap is None or snap.policy is None  # cold, still boots


# ---------------------------------------------------------------------------
# the state plane: warm start, staleness, supersession
# ---------------------------------------------------------------------------


class TestStatePlane:
    def test_empty_dir_is_a_miss(self, tmp_path):
        engine = build_engine(strict_verify=True)
        plane = StatePlane(engine, str(tmp_path))
        summary = plane.warm_start()
        assert summary == {"snapshot": "miss", "hotset": "miss"}
        assert plane.serving_warm() is False

    def test_warm_start_serves_before_any_control_plane(self, tmp_path):
        d = str(tmp_path)
        leader = seed_state_dir(d, traffic=24)
        engine = build_engine(strict_verify=True)
        plane = StatePlane(engine, d)
        engine.state_plane = plane
        summary = plane.warm_start()
        assert summary["snapshot"] == "ok"
        assert summary["hotset"] == "ok" and summary["hotset_imported"] > 0
        assert plane.serving_warm() and plane.stale_reason() is None
        # bit-exact against the engine that wrote the state
        for i in range(6):
            want = run(leader.submit(doc(i), f"cfg-{i}"))
            got = run(engine.submit(doc(i), f"cfg-{i}"))
            assert np.array_equal(want[0], got[0])
            assert np.array_equal(want[1], got[1])
        assert engine.debug_vars()["state_plane"]["serving_warm"] is True

    def test_stale_snapshot_degrades_not_fails(self, tmp_path):
        d = str(tmp_path)
        seed_state_dir(d)
        # age the manifest's publish time (MANIFEST carries it, not the blob)
        mp = os.path.join(d, "MANIFEST.json")
        man = json.load(open(mp))
        man["published_unix"] = time.time() - 3600.0
        atomic_write_json(mp, man, artifact="manifest")
        old_dir = RECORDER.dump_dir
        RECORDER.configure(dump_dir=str(tmp_path / "flight"))
        try:
            engine = build_engine(strict_verify=True)
            plane = StatePlane(engine, d, max_snapshot_age_s=60.0)
            summary = plane.warm_start()
            assert summary["snapshot"] == "stale"
            assert summary["snapshot_age_s"] > 60.0
            # STILL serving (old verdicts beat no verdicts)...
            out = run(engine.submit(doc(0), "cfg-0"))
            assert bool(out[0][0])
            # ...but degraded: /readyz reason + anomaly + age gauge
            assert "stale snapshot" in plane.stale_reason()
            with RECORDER._ring_lock:
                kinds = [e["kind"] for e in RECORDER._ring]
            assert "stale-snapshot" in kinds
            assert sample("auth_server_snapshot_age_seconds") > 60.0
        finally:
            RECORDER.configure(dump_dir=old_dir)

    def test_fresh_blob_goes_stale_live_then_swap_clears(self, tmp_path):
        d = str(tmp_path)
        seed_state_dir(d)
        engine = build_engine(strict_verify=True)
        plane = StatePlane(engine, d, max_snapshot_age_s=0.2)
        assert plane.warm_start()["snapshot"] == "ok"  # fresh at boot
        time.sleep(0.25)
        assert "stale snapshot" in plane.stale_reason()  # degraded live
        # first live reconcile supersedes the warm blob: all clear
        engine.apply_snapshot(entries_of(make_corpus(tag="-new")))
        assert plane.serving_warm() is False
        assert plane.stale_reason() is None
        assert sample("auth_server_snapshot_age_seconds") == 0.0

    def test_write_behind_round_trips_the_next_restart(self, tmp_path):
        """Serve → reconcile → drain; a second process warm-starts into
        the LAST vetted state, hot set included."""
        d = str(tmp_path)
        first = build_engine(strict_verify=True)
        plane = StatePlane(first, d, hotset_k=64)
        plane.start()
        first.apply_snapshot(entries_of(make_corpus()))
        first.apply_snapshot(entries_of(make_corpus(tag="-v2")))
        async def pump():
            await asyncio.gather(*[first.submit(doc(i % 6), f"cfg-{i % 6}")
                                   for i in range(24)])

        run(pump())
        plane.shutdown(timeout_s=5.0)

        second = build_engine(strict_verify=True)
        summary = StatePlane(second, d).warm_start()
        assert summary["snapshot"] == "ok"
        assert summary["hotset_imported"] > 0
        for i in range(6):
            want = run(first.submit(doc(i), f"cfg-{i}"))
            got = run(second.submit(doc(i), f"cfg-{i}"))
            assert np.array_equal(want[0], got[0])


# ---------------------------------------------------------------------------
# dominance: a reachable leader ALWAYS beats local warm state
# ---------------------------------------------------------------------------


class TestLeaderDominance:
    def test_newer_local_state_never_outranks_the_leader(self, tmp_path):
        """The local blob is NEWER than the leader's (the leader rolled
        back, or this replica outlived a retracted publish).  The warm
        start may serve it fail-statically, but the first successful poll
        must swap to the leader's corpus — leader dominance is what keeps
        a fleet convergent."""
        local = str(tmp_path / "state")
        leader_dir = str(tmp_path / "pub")
        seed_state_dir(local, cfgs=make_corpus(tag="-local-newer"))
        leader = seed_state_dir(leader_dir, cfgs=make_corpus())

        engine = build_engine(strict_verify=True)
        plane = StatePlane(engine, local)
        assert plane.warm_start()["snapshot"] == "ok"
        probe = {"request": {"method": "GET", "url_path": "/x"},
                 "auth": {"identity": {"org": "org-0", "roles": []}}}
        # warm (local) state DENIES org-0: its constant is org-0-local-newer
        assert not bool(run(engine.submit(dict(probe), "cfg-0"))[0][0])

        rep = SnapshotReplica(engine, leader_dir, poll_s=0.1)
        assert rep.poll_once() is True  # digest differs ⇒ leader wins
        assert plane.serving_warm() is False
        out = run(engine.submit(dict(probe), "cfg-0"))
        want = run(leader.submit(dict(probe), "cfg-0"))
        assert bool(out[0][0]) and np.array_equal(out[0], want[0])

    def test_unchanged_leader_digest_is_not_reapplied(self, tmp_path):
        """Warm start from a state dir seeded by THE SAME leader: the
        first poll applies once (the replica has no digest memory across
        restarts), the second is a no-op."""
        d = str(tmp_path)
        seed_state_dir(d)
        engine = build_engine(strict_verify=True)
        StatePlane(engine, d).warm_start()
        rep = SnapshotReplica(engine, d, poll_s=0.1)
        assert rep.poll_once() is True
        assert rep.poll_once() is False  # digest remembered from here on

    def test_rollback_manifest_dominates_newer_local_blob(self, tmp_path):
        """The leader rolled back (manifest points at the OLD generation,
        with the rollback record).  A replica warm-started from its own
        newer local state must adopt the manifest-directed generation —
        never the newest blob anywhere."""
        local = str(tmp_path / "state")
        leader_dir = str(tmp_path / "pub")
        seed_state_dir(local, cfgs=make_corpus(tag="-local-newer"))
        leader = build_engine(make_corpus(), strict_verify=True)
        base_gen = leader.generation
        pub = SnapshotPublisher(leader_dir)
        pub.publish_from_engine(leader)
        # the retracted candidate blob (generation base+1) stays on disk...
        cand = make_corpus(tag="-retracted")
        cand_blob = serialize_policy(
            compile_corpus(cand, members_k=4),
            meta={"generation": base_gen + 1, "certified": True,
                  "fingerprints": {c.name: rules_fingerprint(c)
                                   for c in cand},
                  "entries": [{"id": c.name, "hosts": [c.name]}
                              for c in cand]})
        pub.publish_blob(cand_blob, base_gen + 1)
        # ...then the fleet guard rolls back: manifest moves backwards with
        # the rollback record
        leader._snapshot.change_safety = {
            "rollback": {"reason": "fleet-guard-breach",
                         "guards": ["config-deny-rate"]}}
        pub.publish_from_engine(leader)

        engine = build_engine(strict_verify=True)
        plane = StatePlane(engine, local)
        assert plane.warm_start()["snapshot"] == "ok"
        rep = SnapshotReplica(engine, leader_dir, poll_s=0.1)
        assert rep.poll_once() is True
        man = json.load(open(os.path.join(leader_dir, "MANIFEST.json")))
        assert man["active_generation"] == base_gen
        assert (engine._snapshot.change_safety or {})["rollback"][
            "reason"] == "fleet-guard-breach"
        # serving the ROLLED-BACK corpus (org-0 allows), not the retracted
        probe = {"request": {"method": "GET", "url_path": "/x"},
                 "auth": {"identity": {"org": "org-0", "roles": []}}}
        assert bool(run(engine.submit(dict(probe), "cfg-0"))[0][0])


# ---------------------------------------------------------------------------
# the kill harness: SIGKILL a live process, restart from disk alone
# ---------------------------------------------------------------------------


HARNESS = "authorino_tpu.runtime.restart_harness"


def _harness_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("AUTHORINO_TPU_FAULTS", None)
    return env


def _kill_and_verify(tmp_path, stress, kill_after_s):
    d = str(tmp_path / "sd")
    table = os.path.join(d, "TABLE.json")
    ready = os.path.join(d, "READY")
    report_path = str(tmp_path / "report.json")
    os.makedirs(d, exist_ok=True)
    proc = subprocess.Popen(
        [sys.executable, "-m", HARNESS, "serve", "--state-dir", d,
         "--table", table, "--ready", ready, "--stress", stress,
         "--configs", "6", "--variants", "3"],
        env=_harness_env(), stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + 120.0
        while not os.path.exists(ready):
            assert proc.poll() is None, "harness serve died before READY"
            assert time.monotonic() < deadline, "harness serve never READY"
            time.sleep(0.1)
        time.sleep(kill_after_s)  # land the kill mid-churn
        assert proc.poll() is None, "harness serve exited on its own"
    finally:
        try:
            proc.send_signal(signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait(timeout=30)
    out = subprocess.run(
        [sys.executable, "-m", HARNESS, "restart", "--state-dir", d,
         "--table", table, "--report", report_path],
        env=_harness_env(), capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, \
        f"restart verification failed:\n{out.stdout}\n{out.stderr}"
    report = json.load(open(report_path))
    assert report["recovered"] and report["table_hit"]
    assert report["verdicts_match"], report.get("mismatch")
    assert report["artifacts"]["unhandled"] == []
    return report


class TestKillHarness:
    @pytest.mark.parametrize("stress", ["reconcile", "capture"])
    def test_sigkill_mid_churn_recovers_bit_exact(self, tmp_path, stress):
        report = _kill_and_verify(tmp_path, stress, kill_after_s=1.0)
        assert report["warm_start"]["snapshot"] in ("ok", "stale")

    @pytest.mark.slow
    @pytest.mark.parametrize("stress", ["reconcile", "capture"])
    @pytest.mark.parametrize("kill_after_s", [0.2, 0.7, 1.6, 2.9])
    def test_sigkill_sweep(self, tmp_path, stress, kill_after_s):
        _kill_and_verify(tmp_path, stress, kill_after_s=kill_after_s)
