"""Host-fallback lane: bounded, observed, storm-tested.

Requests whose membership arrays exceed members_k cannot ride the compact
device payload — they are re-decided by the host expression oracle
(runtime/engine.py / parallel/sharded_eval.py).  This suite asserts the
lane is exact, metered (auth_server_host_fallback_total), capped
(max_fallback_per_batch → fail-closed deny + shed counter), and that a
100%-overflow storm degrades gracefully instead of blowing up latency."""

from __future__ import annotations

import asyncio
import time

import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine


def counter_value(name: str) -> float:
    try:
        from prometheus_client import REGISTRY

        v = REGISTRY.get_sample_value(name + "_total")
        return v if v is not None else 0.0
    except ImportError:
        pytest.skip("prometheus_client unavailable")


RULE = All(
    Pattern("auth.identity.roles", Operator.INCL, "admin"),
    Pattern("auth.identity.groups", Operator.EXCL, "banned"),
)


def build_engine(mesh, **kw) -> PolicyEngine:
    engine = PolicyEngine(max_batch=64, members_k=4,
                          mesh=mesh, **kw)
    engine.apply_snapshot([
        EngineEntry(id="c", hosts=["c"], runtime=None,
                    rules=ConfigRules(name="c", evaluators=[(None, RULE)]))
    ])
    return engine


def overflow_doc(allow: bool) -> dict:
    # 70 members overflow members_k=4 AND the mesh lane's grid-relief K
    # (≤ MEMBERS_K_RELIEF_CAP = 64), with the deciding one LAST — the
    # compact payload truncates it away, so only the host oracle answers
    # correctly on either lane
    roles = [f"r{k}" for k in range(70)] + (["admin"] if allow else [])
    return {"auth": {"identity": {"roles": roles, "groups": []}}}


def plain_doc(allow: bool) -> dict:
    return {"auth": {"identity": {"roles": ["admin"] if allow else ["dev"],
                                  "groups": []}}}


async def submit_all(engine, docs):
    outs = await asyncio.gather(*(engine.submit(d, "c") for d in docs))
    return [bool(rule[0]) for rule, _ in outs]


@pytest.mark.parametrize("mesh", [None, "auto"])
def test_fallback_exact_and_metered(mesh):
    engine = build_engine(mesh)
    before = counter_value("auth_server_host_fallback")
    docs = [overflow_doc(i % 3 != 0) for i in range(32)]
    results = asyncio.run(submit_all(engine, docs))
    expected = [RULE.matches(d) for d in docs]
    assert results == expected
    assert counter_value("auth_server_host_fallback") >= before + 32


@pytest.mark.parametrize("mesh", [None, "auto"])
def test_fallback_cap_sheds_fail_closed(mesh):
    # generous window: all 16 submits must land in ONE micro-batch, or the
    # per-batch cap legitimately decides more than 4 across batches
    engine = build_engine(mesh, max_fallback_per_batch=4)
    engine.max_delay_s = 0.05
    before_shed = counter_value("auth_server_host_fallback_shed")
    docs = [overflow_doc(True) for _ in range(16)]
    results = asyncio.run(submit_all(engine, docs))
    # exactly cap-many decided exactly (allow); the rest denied fail-closed
    assert sum(results) == 4
    assert counter_value("auth_server_host_fallback_shed") >= before_shed + 12


def test_storm_degrades_gracefully():
    """A 100%-overflow batch must not blow request latency past ~10× the
    no-overflow batch (the oracle runs compiled closures, ~2µs/request)."""
    engine = build_engine(None)

    async def timed(docs):
        # warm the XLA cache for this bucket first
        await submit_all(engine, [plain_doc(True)] * len(docs))
        t0 = time.perf_counter()
        await submit_all(engine, docs)
        return time.perf_counter() - t0

    normal = asyncio.run(timed([plain_doc(i % 2 == 0) for i in range(64)]))
    storm = asyncio.run(timed([overflow_doc(i % 2 == 0) for i in range(64)]))
    # generous absolute floor keeps the bound meaningful yet unflaky on a
    # noisy 1-core host
    assert storm < 10 * normal + 0.5, (storm, normal)
