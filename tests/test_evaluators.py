"""Leaf evaluator tests with fake in-process backends (the reference's
pkg/httptest style: local HTTP servers faking Keycloak/UMA/registries;
SURVEY.md §4)."""

import asyncio
import base64
import json

import pytest
from aiohttp import web
from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric import ec, rsa

from authorino_tpu.authjson import (
    CheckRequestModel,
    HttpRequestAttributes,
    JSONProperty,
    JSONValue,
    PeerAttributes,
)
from authorino_tpu.evaluators import EvaluationError, IdentityConfig, RuntimeAuthConfig
from authorino_tpu.evaluators.authorization import OPA
from authorino_tpu.evaluators.authorization.rego import RegoError, compile_module
from authorino_tpu.evaluators.identity import APIKey, KubernetesAuth, MTLS, Noop, OAuth2, OIDC
from authorino_tpu.evaluators.metadata import GenericHttp, UserInfo
from authorino_tpu.evaluators.response import SigningKey, Wristband
from authorino_tpu.evaluators.credentials import AuthCredentials
from authorino_tpu.k8s import InMemoryCluster, LabelSelector, Secret
from authorino_tpu.pipeline import AuthPipeline
from authorino_tpu.utils import jose


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def make_pipeline(headers=None, source_cert="", identity=None):
    req = CheckRequestModel(
        http=HttpRequestAttributes(
            method="GET", path="/x", host="svc.example.com", headers=headers or {}
        ),
        source=PeerAttributes(certificate=source_cert),
    )
    p = AuthPipeline(req, RuntimeAuthConfig())
    if identity is not None:
        conf = IdentityConfig("test", Noop())
        p.identity_results[conf] = identity
        p._sync_auth()
    return p


class TestAPIKey:
    def _cluster(self):
        cluster = InMemoryCluster()
        cluster.put_secret(
            Secret(
                name="app-1-key",
                namespace="ns",
                labels={"audience": "app"},
                data={"api_key": b"ndyBzreUzF4zqDQsqSPMHkRhriEOtcRx"},
            )
        )
        return cluster

    def test_valid_and_invalid_key(self):
        ak = APIKey("api-key", LabelSelector.parse("audience=app"), cluster=self._cluster())
        run(ak.load_secrets())
        p = make_pipeline(headers={"authorization": "APIKEY ndyBzreUzF4zqDQsqSPMHkRhriEOtcRx"})
        ak.credentials = AuthCredentials(key_selector="APIKEY")
        obj = run(ak.call(p))
        assert obj["metadata"]["name"] == "app-1-key"
        p2 = make_pipeline(headers={"authorization": "APIKEY wrong"})
        with pytest.raises(EvaluationError, match="invalid"):
            run(ak.call(p2))

    def test_live_rotation(self):
        cluster = self._cluster()
        ak = APIKey("api-key", LabelSelector.parse("audience=app"), cluster=cluster)
        run(ak.load_secrets())
        ak.credentials = AuthCredentials(key_selector="APIKEY")
        # revoke (ref secret_controller.go:100-106)
        ak.revoke_k8s_secret_based_identity("ns", "app-1-key")
        with pytest.raises(EvaluationError):
            run(ak.call(make_pipeline(headers={"authorization": "APIKEY ndyBzreUzF4zqDQsqSPMHkRhriEOtcRx"})))
        # add a rotated key
        ak.add_k8s_secret_based_identity(
            Secret(name="app-1-key", namespace="ns", labels={"audience": "app"}, data={"api_key": b"new-key"})
        )
        obj = run(ak.call(make_pipeline(headers={"authorization": "APIKEY new-key"})))
        assert obj["metadata"]["name"] == "app-1-key"


class TestMTLS:
    def _make_ca_and_cert(self, valid=True):
        from datetime import datetime, timedelta, timezone

        from cryptography import x509
        from cryptography.hazmat.primitives import hashes
        from cryptography.x509.oid import NameOID

        ca_key = ec.generate_private_key(ec.SECP256R1())
        ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "test-ca")])
        now = datetime.now(timezone.utc)
        ca_cert = (
            x509.CertificateBuilder()
            .subject_name(ca_name)
            .issuer_name(ca_name)
            .public_key(ca_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - timedelta(days=1))
            .not_valid_after(now + timedelta(days=30))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
            .sign(ca_key, hashes.SHA256())
        )
        signer = ca_key if valid else ec.generate_private_key(ec.SECP256R1())
        leaf_key = ec.generate_private_key(ec.SECP256R1())
        leaf = (
            x509.CertificateBuilder()
            .subject_name(
                x509.Name(
                    [
                        x509.NameAttribute(NameOID.COMMON_NAME, "john"),
                        x509.NameAttribute(NameOID.ORGANIZATION_NAME, "acme"),
                    ]
                )
            )
            .issuer_name(ca_name)
            .public_key(leaf_key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - timedelta(hours=1))
            .not_valid_after(now + timedelta(days=1))
            .sign(signer, hashes.SHA256())
        )
        ca_pem = ca_cert.public_bytes(serialization.Encoding.PEM)
        leaf_pem = leaf.public_bytes(serialization.Encoding.PEM).decode()
        return ca_pem, leaf_pem

    def test_verify_subject(self):
        ca_pem, leaf_pem = self._make_ca_and_cert(valid=True)
        cluster = InMemoryCluster()
        cluster.put_secret(Secret(name="ca", namespace="ns", labels={"app": "mtls"}, data={"ca.crt": ca_pem}))
        m = MTLS("mtls", LabelSelector.parse("app=mtls"), cluster=cluster)
        run(m.load_secrets())
        obj = run(m.call(make_pipeline(source_cert=leaf_pem)))
        assert obj["CommonName"] == "john"
        assert obj["Organization"] == "acme"

    def test_unknown_authority(self):
        ca_pem, _ = self._make_ca_and_cert(valid=True)
        _, rogue_pem = self._make_ca_and_cert(valid=False)
        cluster = InMemoryCluster()
        cluster.put_secret(Secret(name="ca", namespace="ns", labels={"app": "mtls"}, data={"ca.crt": ca_pem}))
        m = MTLS("mtls", LabelSelector.parse("app=mtls"), cluster=cluster)
        run(m.load_secrets())
        with pytest.raises(EvaluationError, match="unknown authority"):
            run(m.call(make_pipeline(source_cert=rogue_pem)))
        with pytest.raises(EvaluationError, match="missing"):
            run(m.call(make_pipeline()))


class FakeIdP:
    """Fake Keycloak-ish IdP: discovery, JWKS, userinfo, introspection."""

    def __init__(self):
        self.key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        self.issuer = None
        self.userinfo = {"sub": "john", "email": "john@acme.com"}
        self.active_tokens = {"opaque-token-1": {"active": True, "username": "john"}}

    def token(self, claims=None):
        iat = int(__import__("time").time())
        payload = {"iss": self.issuer, "sub": "john", "iat": iat, "exp": iat + 300,
                   "realm_access": {"roles": ["admin"]}}
        payload.update(claims or {})
        return jose.sign_jwt(payload, self.key, "RS256", kid="k1")

    def app(self):
        app = web.Application()

        async def well_known(request):
            return web.json_response(
                {
                    "issuer": self.issuer,
                    "jwks_uri": f"{self.issuer}/jwks",
                    "userinfo_endpoint": f"{self.issuer}/userinfo",
                    "token_endpoint": f"{self.issuer}/token",
                }
            )

        async def jwks(request):
            return web.json_response({"keys": [jose.jwk_from_public_key(self.key.public_key(), kid="k1")]})

        async def userinfo(request):
            return web.json_response(self.userinfo)

        async def introspect(request):
            form = await request.post()
            return web.json_response(self.active_tokens.get(form.get("token"), {"active": False}))

        app.router.add_get("/.well-known/openid-configuration", well_known)
        app.router.add_get("/jwks", jwks)
        app.router.add_get("/userinfo", userinfo)
        app.router.add_post("/introspect", introspect)
        return app


def with_fake_idp(test_body):
    async def scenario():
        from aiohttp.test_utils import TestServer

        idp = FakeIdP()
        server = TestServer(idp.app())
        await server.start_server()
        idp.issuer = str(server.make_url("")).rstrip("/")
        try:
            await test_body(idp)
        finally:
            await server.close()
            from authorino_tpu.utils.http import close_sessions

            await close_sessions()

    run(scenario())


class TestOIDC:
    def test_jwt_verify_and_claims(self):
        async def body(idp):
            oidc = OIDC("keycloak", idp.issuer)
            token = idp.token()
            p = make_pipeline(headers={"authorization": f"Bearer {token}"})
            claims = await oidc.call(p)
            assert claims["sub"] == "john"
            assert claims["realm_access"]["roles"] == ["admin"]
            # tampered token denied
            bad = token[:-4] + "AAAA"
            with pytest.raises(EvaluationError):
                await oidc.call(make_pipeline(headers={"authorization": f"Bearer {bad}"}))
            # expired token denied
            expired = idp.token({"exp": 1})
            with pytest.raises(EvaluationError, match="expired"):
                await oidc.call(make_pipeline(headers={"authorization": f"Bearer {expired}"}))
            await oidc.clean()

        with_fake_idp(body)

    def test_userinfo_bound_to_same_issuer(self):
        async def body(idp):
            oidc = OIDC("keycloak", idp.issuer)
            ui = UserInfo(oidc)
            token = idp.token()
            p = make_pipeline(headers={"authorization": f"Bearer {token}"})
            conf = IdentityConfig("keycloak", oidc)
            p.identity_results[conf] = await oidc.call(p)
            p._sync_auth()
            data = await ui.call(p)
            assert data["email"] == "john@acme.com"
            # identity resolved by a different evaluator → skip error (ref user_info.go:22-44)
            p2 = make_pipeline(identity={"anonymous": True})
            with pytest.raises(EvaluationError, match="Missing identity"):
                await ui.call(p2)
            await oidc.clean()

        with_fake_idp(body)


class TestOAuth2Introspection:
    def test_active_and_inactive(self):
        async def body(idp):
            ev = OAuth2("oauth2", f"{idp.issuer}/introspect", "client", "secret")
            p = make_pipeline(headers={"authorization": "Bearer opaque-token-1"})
            obj = await ev.call(p)
            assert obj["username"] == "john"
            with pytest.raises(EvaluationError, match="not active"):
                await ev.call(make_pipeline(headers={"authorization": "Bearer nope"}))

        with_fake_idp(body)


class TestKubernetesTokenReview:
    def test_token_review(self):
        cluster = InMemoryCluster()
        cluster.token_reviews["good-token"] = {
            "status": {"authenticated": True, "user": {"username": "system:serviceaccount:ns:app"}}
        }
        ev = KubernetesAuth("k8s", cluster=cluster)
        obj = run(ev.call(make_pipeline(headers={"authorization": "Bearer good-token"})))
        assert obj["username"].startswith("system:serviceaccount")
        with pytest.raises(EvaluationError, match="Not authenticated"):
            run(ev.call(make_pipeline(headers={"authorization": "Bearer bad"})))


class TestGenericHttp:
    def test_get_and_post(self):
        async def body(idp):
            seen = {}

            async def echo(request):
                seen["headers"] = dict(request.headers)
                seen["query"] = dict(request.query)
                seen["body"] = await request.text()
                return web.json_response({"ok": True})

            from aiohttp.test_utils import TestServer

            app = web.Application()
            app.router.add_route("*", "/meta", echo)
            server = TestServer(app)
            await server.start_server()
            base = str(server.make_url("")).rstrip("/")
            try:
                ev = GenericHttp(
                    endpoint=JSONValue(pattern=base + "/meta?user={auth.identity.user}"),
                    method="GET",
                    shared_secret="s3cr3t",
                    credentials=AuthCredentials(key_selector="Bearer"),
                    headers=[JSONProperty("X-Tag", JSONValue(static="v1"))],
                )
                p = make_pipeline(identity={"user": "john"})
                out = await ev.call(p)
                assert out == {"ok": True}
                assert seen["headers"]["Authorization"] == "Bearer s3cr3t"
                assert seen["headers"]["X-Tag"] == "v1"
                assert seen["query"] == {"user": "john"}

                ev2 = GenericHttp(
                    endpoint=JSONValue(static=base + "/meta"),
                    method="POST",
                    parameters=[JSONProperty("u", JSONValue(pattern="auth.identity.user"))],
                )
                out = await ev2.call(p)
                assert json.loads(seen["body"]) == {"u": "john"}
            finally:
                await server.close()

        with_fake_idp(body)


class TestRego:
    def test_basic_allow(self):
        m = compile_module(
            """
            default allow = false
            allow { input.auth.identity.role == "admin" }
            allow { input.request.method == "GET"; input.request.path == "/public" }
            """
        )
        assert m.evaluate({"auth": {"identity": {"role": "admin"}}, "request": {}})["allow"]
        assert m.evaluate({"auth": {"identity": {}}, "request": {"method": "GET", "path": "/public"}})["allow"]
        assert not m.evaluate({"auth": {"identity": {"role": "dev"}}, "request": {"method": "POST"}})["allow"]

    def test_iteration_and_builtins(self):
        m = compile_module(
            """
            default allow = false
            allow { input.roles[_] == "admin" }
            allow { startswith(input.path, "/public/") }
            """
        )
        assert m.evaluate({"roles": ["dev", "admin"], "path": "/x"})["allow"]
        assert m.evaluate({"roles": [], "path": "/public/a"})["allow"]
        assert not m.evaluate({"roles": ["dev"], "path": "/private"})["allow"]

    def test_bindings_and_value_rules(self):
        m = compile_module(
            """
            default allow = false
            user := input.identity.username
            allow { user == "john" }
            greeting = msg { msg := sprintf("hello %s", [user]) }
            """
        )
        out = m.evaluate({"identity": {"username": "john"}})
        assert out["allow"] and out["user"] == "john" and out["greeting"] == "hello john"

    def test_not_and_in(self):
        m = compile_module(
            """
            default allow = false
            allow { not denied; "gold" in input.tiers }
            denied { input.banned == true }
            """
        )
        assert m.evaluate({"tiers": ["gold"], "banned": False})["allow"]
        assert not m.evaluate({"tiers": ["gold"], "banned": True})["allow"]
        assert not m.evaluate({"tiers": ["silver"], "banned": False})["allow"]

    def test_unsupported_syntax_rejected(self):
        # constructs outside the subset fail CLOSED at compile, never get
        # silently misparsed into a policy that means something else
        with pytest.raises(RegoError):
            compile_module("default x = input.y")  # non-constant default
        # a `with` target that names neither a document path nor a known
        # function/builtin still fails CLOSED — at eval (round 4: real
        # function/builtin mocking is supported, unknown targets are not)
        m = compile_module("allow { count([1]) == 1 with nosuch as 3 }")
        with pytest.raises(RegoError):
            m.evaluate({})
        with pytest.raises(RegoError):
            compile_module("else = true { input.y }")  # dangling else

    def test_else_chain_ordered(self):
        # OPA: else blocks evaluate strictly in order; the first definition
        # whose body is satisfied supplies the value
        m = compile_module(
            """
            default access = "none"
            access = "admin" { input.user == "root" }
            else = "write" { input.tier == "gold" }
            else = "read" { input.known }
            """
        )
        assert m.evaluate({"user": "root"})["access"] == "admin"
        assert m.evaluate({"user": "u", "tier": "gold", "known": True})["access"] == "write"
        assert m.evaluate({"user": "u", "known": True})["access"] == "read"
        assert m.evaluate({"user": "u"})["access"] == "none"

    def test_else_bare_value_and_v1_if(self):
        # bare `else { body }` values true; `else := v if cond` (v1 sugar)
        # and a trailing unconditional `else := v` fallback
        m = compile_module(
            """
            allow { input.x == 1 }
            else { input.y == 2 }
            level := 3 if input.n > 10
            else := 2 if input.n > 5
            else := 1
            """
        )
        assert m.evaluate({"x": 1})["allow"] is True
        assert m.evaluate({"y": 2})["allow"] is True
        assert m.evaluate({}).get("allow") is None  # undefined, no default
        assert m.evaluate({"n": 20})["level"] == 3
        assert m.evaluate({"n": 7})["level"] == 2
        assert m.evaluate({"n": 1})["level"] == 1

    def test_else_rejected_on_partial_set(self):
        with pytest.raises(RegoError):
            compile_module('s contains "a" { input.x }\nelse = true { input.y }')

    def test_user_functions(self):
        # OPA functions: computed head values, multiple definitions tried in
        # order, Const params unify, undefined when no definition matches
        m = compile_module(
            """
            default allow = false
            double(x) = 2 * x
            ext(name) = out { out := trim_suffix(name, ".json") }
            classify(1) = "one"
            classify(x) = "many" { x > 1 }
            bool_fn(x) { x > 10 }
            allow { double(input.n) == 6 }
            kind := classify(input.n)
            big { bool_fn(input.n) }
            stripped := ext("a.json")
            """
        )
        out = m.evaluate({"n": 3})
        assert out["allow"] and out["kind"] == "many" and out["stripped"] == "a"
        assert "big" not in out
        assert m.evaluate({"n": 1})["kind"] == "one"
        assert m.evaluate({"n": 11})["big"] is True
        # no classify() definition matches 0 ("many" needs x > 1) → the
        # call is undefined and the rule that uses it drops out
        assert "kind" not in m.evaluate({"n": 0})

    def test_user_function_else_and_recursion_guard(self):
        m = compile_module(
            """
            f(x) = "big" { x > 10 } else = "small" { x > 0 } else = "neg"
            v := f(input.n)
            """
        )
        assert m.evaluate({"n": 11})["v"] == "big"
        assert m.evaluate({"n": 3})["v"] == "small"
        assert m.evaluate({"n": -1})["v"] == "neg"
        rec = compile_module("f(x) = f(x) { true }\nv := f(1)")
        with pytest.raises(RegoError):
            rec.evaluate({})

    def test_data_documents(self):
        # external data tree under data.*, and the module's own package
        # mounted at data.<package> as a virtual document
        m = compile_module(
            """
            package acl
            default allow = false
            helper { input.x == 1 }
            allow { input.user == data.admins[_] }
            allow { data.acl.helper }
            via_pkg := data.acl.limits.max
            """,
            package="acl",
        )
        assert m.evaluate({"user": "alice"}, data={"admins": ["alice", "bob"]})["allow"]
        assert not m.evaluate({"user": "eve"}, data={"admins": ["alice"]})["allow"]
        assert m.evaluate({"x": 1})["allow"]          # virtual self-reference
        # data falls back to the external tree under non-rule names
        out = m.evaluate({}, data={"acl": {"limits": {"max": 9}}})
        assert out["via_pkg"] == 9
        # a rule reading its own whole package document is recursive —
        # OPA raises rego_recursion_error, we match (fail closed)
        m2 = compile_module("package p\na := 1\nwhole := data.p", package="p")
        with pytest.raises(RegoError):
            m2.evaluate({})

    def test_with_recursion_fails_closed(self):
        # a cycle routed through `with` is still a cycle: the guard spans
        # the whole with-chain (OPA rejects recursion statically)
        m = compile_module('p { q with input.x as 1 }\nq { p }')
        with pytest.raises(RegoError, match="recursive"):
            m.evaluate({})

    def test_repeated_function_params_unify(self):
        # OPA: f(x, x) matches only when both arguments are equal
        m = compile_module("f(x, x) = x { true }\nr := f(input.a, input.b)")
        assert m.evaluate({"a": 2, "b": 2})["r"] == 2
        assert "r" not in m.evaluate({"a": 1, "b": 2})

    def test_with_on_some_in_and_every(self):
        m = compile_module(
            """
            default a = false
            default b = false
            a { some x in input.xs; x == 9 with input.y as 1 }
            b { every x in input.xs { x > input.min } with input.min as 0 }
            """
        )
        assert m.evaluate({"xs": [9]})["a"] is True
        assert m.evaluate({"xs": [1, 2], "min": 5})["b"] is True  # mocked min
        assert not m.evaluate({"xs": [0], "min": 5})["b"]

    def test_data_ancestor_prefix(self):
        # referencing an ancestor of your own package pulls in the whole
        # package document — including the referencing rule, which is a
        # dependency cycle: OPA raises rego_recursion_error, we fail closed
        m = compile_module("package a.b\nallow = true\nr := data.a", package="a.b")
        with pytest.raises(RegoError, match="recursive"):
            m.evaluate({}, data={"a": {"ext": 7}})
        # non-package data paths keep walking the external tree
        m2 = compile_module("package a.b\nr := data.other.k", package="a.b")
        assert m2.evaluate({}, data={"other": {"k": 5}})["r"] == 5

    def test_with_mocking(self):
        # `with` overlays input/data for the wrapped expression AND the
        # rules it references (OPA with modifier scoping)
        m = compile_module(
            """
            default allow = false
            inner { input.role == "admin" }
            allow { inner with input.role as "admin" }
            both { inner with input.role as input.alt }
            listed { input.user in data.users }
            mocked_data { listed with data.users as ["bob"] with input.user as "bob" }
            """
        )
        out = m.evaluate({"role": "user"})
        assert out["allow"] is True          # inner sees the mocked role
        assert "both" not in out             # alt missing → mock value undefined
        assert m.evaluate({"role": "u", "alt": "admin"})["both"] is True
        assert m.evaluate({"user": "eve"}, data={"users": []})["mocked_data"] is True


class TestRegoBuiltinsExtra:
    def _eval(self, rego_src, input_doc):
        from authorino_tpu.evaluators.authorization import rego

        module = rego.compile_module("default allow = false\n" + rego_src, package="t")
        return module.evaluate(input_doc)["allow"]

    def test_regex_match(self):
        src = 'allow { regex.match("^/api/v[0-9]+/", input.path) }'
        assert self._eval(src, {"path": "/api/v2/pets"}) is True
        assert self._eval(src, {"path": "/admin"}) is False

    def test_substring_indexof(self):
        src = 'allow { indexof(input.s, "-") == 3 ; substring(input.s, 0, 3) == "abc" }'
        assert self._eval(src, {"s": "abc-def"}) is True
        assert self._eval(src, {"s": "ab-cdef"}) is False

    def test_type_checks_and_sort(self):
        src = ('allow { is_string(input.s) ; is_number(input.n) ; '
               'is_array(input.a) ; sort(input.a)[0] == 1 }')
        assert self._eval(src, {"s": "x", "n": 2, "a": [3, 1, 2]}) is True
        assert self._eval(src, {"s": 1, "n": 2, "a": [3, 1, 2]}) is False

    def test_substring_negative_offset_fails_closed(self):
        # OPA errors on negative offsets; slicing from the end would fail
        # OPEN on the common substring(s, indexof(s, x), n) miss
        from authorino_tpu.evaluators.authorization import rego

        src = 'allow { substring(input.s, indexof(input.s, "#"), 2) == "ef" }'
        with pytest.raises(rego.RegoError, match="negative offset"):
            self._eval(src, {"s": "abcdef"})

    def test_every(self):
        src = 'allow { every r in input.roles { startswith(r, "team-") } }'
        assert self._eval(src, {"roles": ["team-a", "team-b"]}) is True
        assert self._eval(src, {"roles": ["team-a", "other"]}) is False
        assert self._eval(src, {"roles": []}) is True  # vacuous

    def test_every_key_value(self):
        src = 'allow { every k, v in input.limits { v <= 10 ; k != "forbidden" } }'
        assert self._eval(src, {"limits": {"a": 5, "b": 10}}) is True
        assert self._eval(src, {"limits": {"a": 11}}) is False
        assert self._eval(src, {"limits": {"forbidden": 1}}) is False

    def test_array_comprehension(self):
        src = ('names := [u.name | some u in input.users ; u.admin]\n'
               'allow { count(names) == 2 ; names[0] == "a" }')
        assert self._eval(src, {"users": [
            {"name": "a", "admin": True}, {"name": "b", "admin": False},
            {"name": "c", "admin": True}]}) is True

    def test_set_and_object_comprehensions(self):
        src = ('tiers := {u.tier | some u in input.users}\n'
               'by_name := {u.name: u.tier | some u in input.users}\n'
               'allow { count(tiers) == 2 ; by_name.a == "gold" }')
        assert self._eval(src, {"users": [
            {"name": "a", "tier": "gold"}, {"name": "b", "tier": "free"},
            {"name": "c", "tier": "gold"}]}) is True

    def test_partial_set_rules(self):
        # v1 `contains` form — the modern deny-set idiom
        src = ('violations contains msg { input.x > 5 ; msg := "too big" }\n'
               'violations contains msg { input.y == "bad" ; msg := "bad y" }\n'
               'allow { count(violations) == 0 }')
        assert self._eval(src, {"x": 1, "y": "ok"}) is True
        assert self._eval(src, {"x": 9, "y": "ok"}) is False
        assert self._eval(src, {"x": 9, "y": "bad"}) is False
        # v0 bracket form, multiple bindings dedupe as a set
        src0 = ('roles[r] { some r in input.rs }\n'
                'allow { count(roles) == 2 }')
        assert self._eval(src0, {"rs": ["a", "b", "a"]}) is True

    def test_arithmetic(self):
        src = ('allow { count(input.roles) + 1 > 2 ; input.n * 2 <= 10 ; '
               'input.n % 2 == 1 ; (input.n + 1) / 2 == 3 ; -input.n == 0 - 5 }')
        assert self._eval(src, {"roles": ["a", "b"], "n": 5}) is True
        assert self._eval(src, {"roles": [], "n": 5}) is False

    def test_arithmetic_iterates_refs(self):
        # existential ref[_] semantics flow THROUGH arithmetic: any element
        # satisfying the expression satisfies the rule (OPA behavior)
        src = "deny { input.scores[_] - input.threshold > 0 }\nallow { not deny }"
        assert self._eval(src, {"scores": [1, 100], "threshold": 50}) is False
        assert self._eval(src, {"scores": [1, 2], "threshold": 50}) is True

    def test_default_constant_folding_and_rejection(self):
        from authorino_tpu.evaluators.authorization import rego

        m = rego.compile_module("default limit = 60 * 60\nallow { input.x }", package="t")
        assert m.evaluate({"x": True}) == {"limit": 3600, "allow": True}
        with pytest.raises(rego.RegoError, match="must be a constant"):
            rego.compile_module("default limit = input.x + 1")

    def test_exact_integer_division(self):
        src = "x := input.a / input.b\nallow { x == 2 }"
        from authorino_tpu.evaluators.authorization import rego

        m = rego.compile_module("default allow = false\n" + src, package="t")
        out = m.evaluate({"a": 4, "b": 2})
        assert out["x"] == 2 and not isinstance(out["x"], float)  # JSON "2", not "2.0"
        assert rego.compile_module("y := 3 / 2", package="t").evaluate({})["y"] == 1.5

    def test_modulo_truncated_like_go(self):
        # Go big.Int.Rem: sign of the dividend (-7 rem 2 == -1, not 1)
        src = "allow { input.n % 2 == 1 }"
        assert self._eval(src, {"n": 7}) is True
        assert self._eval(src, {"n": -7}) is False
        assert self._eval("allow { input.n % 2 == 0 - 1 }", {"n": -7}) is True

    def test_arithmetic_errors_deny(self):
        from authorino_tpu.evaluators.authorization import rego

        with pytest.raises(rego.RegoError, match="divide by zero"):
            self._eval("allow { input.a / input.b == 1 }", {"a": 1, "b": 0})
        with pytest.raises(rego.RegoError, match="non-number"):
            self._eval('allow { input.s + 1 == 2 }', {"s": "x"})

    def test_braceless_if_bodies(self):
        # v1 brace-less form: the condition must BIND, not silently drop
        src = ('deny contains "x" if input.flagged\n'
               'allow if count(deny) == 0')
        assert self._eval(src, {"flagged": True}) is False
        assert self._eval(src, {"flagged": False}) is True

    def test_set_rule_iterating_head(self):
        # every value of an iterating head joins the set, not just the first
        src = ('banned contains input.blocked[_] { true }\n'
               'allow { not input.user in banned }')
        assert self._eval(src, {"blocked": ["a", "b", "c"], "user": "c"}) is False
        assert self._eval(src, {"blocked": ["a", "b", "c"], "user": "z"}) is True

    def test_partial_set_conflicting_types_rejected(self):
        from authorino_tpu.evaluators.authorization import rego

        with pytest.raises(rego.RegoError, match="conflicting rule types"):
            rego.compile_module(
                'x contains v { v := input.a }\nx { input.b }'
            )

    def test_with_parses_on_every_expression_form(self):
        # `with` is a postfix modifier on comparisons, assignments, and
        # bare terms alike — all three shapes must overlay, not misparse
        from authorino_tpu.evaluators.authorization import rego

        for src, want in [
            ("allow { input.x == 1 with input as {\"x\": 1} }", True),
            ("allow { x := input.y with input.y as 3; x == 3 }", True),
            ("allow { input.x with input as {\"x\": true} }", True),
        ]:
            m = rego.compile_module("default allow = false\n" + src)
            assert m.evaluate({})["allow"] is want, src

    def test_object_comprehension_key_conflict_denies(self):
        from authorino_tpu.evaluators.authorization import rego

        src = ('by := {u.name: u.role | some u in input.users}\n'
               'allow { by.alice == "admin" }')
        with pytest.raises(rego.RegoError, match="conflicting"):
            self._eval(src, {"users": [
                {"name": "alice", "role": "viewer"},
                {"name": "alice", "role": "admin"}]})
        # duplicate key with the SAME value is fine (like OPA)
        assert self._eval(src, {"users": [
            {"name": "alice", "role": "admin"},
            {"name": "alice", "role": "admin"}]}) is True

    def test_set_comprehension_bool_number_distinct(self):
        src = 's := {x | some x in input.xs}\nallow { count(s) == 2 }'
        assert self._eval(src, {"xs": [1, True]}) is True  # OPA: 2 elements
        assert self._eval(src, {"xs": [1, 1.0]}) is False  # numbers equal

    def test_regex_match_linear_time_on_catastrophic_pattern(self):
        # ^(a+)+$ explodes under backtracking engines; the DFA lane must
        # answer in linear time like OPA's RE2
        import time

        src = 'allow { regex.match("^(a+)+$", input.v) }'
        t0 = time.perf_counter()
        assert self._eval(src, {"v": "a" * 28 + "!"}) is False
        assert self._eval(src, {"v": "a" * 28}) is True
        assert time.perf_counter() - t0 < 1.0


class TestOPAEvaluator:
    def test_opa_call(self):
        opa = OPA("policy", inline_rego='allow { input.auth.identity.anonymous == true }')
        p = make_pipeline(identity={"anonymous": True})
        assert run(opa.call(p)) is True
        p2 = make_pipeline(identity={"anonymous": False})
        with pytest.raises(EvaluationError, match="Unauthorized"):
            run(opa.call(p2))

    def test_opa_all_values(self):
        opa = OPA(
            "policy",
            inline_rego='allow { input.auth.identity.user == "john" }\nuser := input.auth.identity.user',
            all_values=True,
        )
        out = run(opa.call(make_pipeline(identity={"user": "john"})))
        assert out["allow"] is True and out["user"] == "john"

    def test_invalid_rego_rejected_at_compile(self):
        with pytest.raises(ValueError, match="invalid rego"):
            OPA("policy", inline_rego="default x = input.y")

    def test_opa_data_documents(self):
        opa = OPA("policy",
                  inline_rego='allow { input.auth.identity.sub == data.admins[_] }',
                  data={"admins": ["u1"]})
        p = make_pipeline(identity={"sub": "u1"})
        assert run(opa.call(p)) is True
        p2 = make_pipeline(identity={"sub": "u2"})
        with pytest.raises(EvaluationError, match="Unauthorized"):
            run(opa.call(p2))


class TestWristband:
    def _signing_key(self):
        key = ec.generate_private_key(ec.SECP256R1())
        pem = key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
        return SigningKey.from_pem("wristband-key", "ES256", pem)

    def test_issue_and_verify(self):
        sk = self._signing_key()
        wb = Wristband(
            issuer="https://authorino.example.com/ns/ac/wristband",
            custom_claims=[JSONProperty("username", JSONValue(pattern="auth.identity.user"))],
            token_duration=300,
            signing_keys=[sk],
        )
        p = make_pipeline(identity={"user": "john"})
        token = run(wb.call(p))
        jwks = json.loads(wb.jwks())["keys"]
        claims = jose.verify_jws(token, jwks)
        assert claims["iss"] == wb.issuer
        assert claims["username"] == "john"
        assert claims["exp"] - claims["iat"] == 300
        assert len(claims["sub"]) == 64  # sha256 hex
        cfg = json.loads(wb.openid_config())
        assert cfg["jwks_uri"].endswith("/openid-connect/certs")


class TestRegoDataLayering:
    def test_exact_package_ref_merges_external_tree(self):
        # data.<package> exactly: virtual doc layered over the external
        # subtree at the same path, consistent with leaf/ancestor refs
        # (only reachable without recursion from outside a rule body, so
        # exercised at the resolver level)
        from authorino_tpu.evaluators.authorization import rego

        m = compile_module("package p\na := 1", package="p")
        ev = rego._Evaluator(m, {}, data={"p": {"ext": 7, "a": 99}})
        vals = list(ev._data_values(["p"], {}))
        assert vals == [{"ext": 7, "a": 1}]  # virtual wins on conflict


class TestRegoBuiltinsRound3:
    def _val(self, expr, input_doc=None, data=None):
        from authorino_tpu.evaluators.authorization import rego

        m = rego.compile_module(f"package t\nv := {expr}", package="t")
        return m.evaluate(input_doc or {}, data=data).get("v")

    def test_object_builtins(self):
        assert sorted(self._val('object.keys({"a": 1, "b": 2})')) == ["a", "b"]
        assert self._val('object.union({"a": {"x": 1}}, {"a": {"y": 2}})') == \
            {"a": {"x": 1, "y": 2}}
        assert self._val('object.remove({"a": 1, "b": 2}, ["a"])') == {"b": 2}
        assert self._val('object.filter({"a": 1, "b": 2}, ["a"])') == {"a": 1}

    def test_array_and_number_builtins(self):
        assert self._val("numbers.range(1, 4)") == [1, 2, 3, 4]   # inclusive
        assert self._val("numbers.range(3, 1)") == [3, 2, 1]      # descending
        assert self._val("array.slice([1, 2, 3, 4], 1, 3)") == [2, 3]
        assert self._val("array.slice([1, 2], -5, 99)") == [1, 2]  # clamped
        assert self._val("array.reverse([1, 2, 3])") == [3, 2, 1]
        assert self._val('strings.reverse("abc")') == "cba"
        assert self._val("format_int(255, 16)") == "ff"

    def test_set_builtins(self):
        # sets are represented as deduped arrays throughout this interpreter
        assert self._val("union([[1, 2], [2, 3]])") == [1, 2, 3]
        assert self._val("intersection([[1, 2, 3], [2, 3, 4]])") == [2, 3]

    def test_glob_match(self):
        # OPA >= 0.43: null delimiters = NO delimiters (* spans everything);
        # an EMPTY array defaults to ["."] (* stays within one label)
        assert self._val('glob.match("*.github.com", null, "a.b.github.com")') is True
        assert self._val('glob.match("*.github.com", [], "api.github.com")') is True
        assert self._val('glob.match("*.github.com", [], "a.b.github.com")') is False
        assert self._val('glob.match("*.github.com", ["."], "a.b.github.com")') is False
        # ** spans delimiters even with them set
        assert self._val('glob.match("**.github.com", ["."], "a.b.github.com")') is True
        assert self._val('glob.match("api-?.acme.com", ["."], "api-1.acme.com")') is True
        assert self._val('glob.match("api-?.acme.com", ["."], "api-12.acme.com")') is False
        # gobwas matches newlines where delimiters allow (DOTALL)
        assert self._val('glob.match("a**b", null, input.s)', {"s": "a\nb"}) is True

    def test_numbers_range_type_errors(self):
        from authorino_tpu.evaluators.authorization import rego

        m = rego.compile_module("package t\nv := numbers.range(x, 3)\nx := input.n",
                                package="t")
        assert m.evaluate({"n": 1})["v"] == [1, 2, 3]
        assert m.evaluate({"n": 1.0})["v"] == [1, 2, 3]   # integral float ok
        with pytest.raises(rego.RegoError):
            m.evaluate({"n": 1.5})


class TestRegoRound4:
    """walk(), `with` function/builtin mocking, multi-module composition
    (the round-3 fail-closed rejections, now implemented — VERDICT r3
    missing #3; the reference evaluates these via embedded OPA,
    ref pkg/evaluators/authorization/opa.go:86-141)."""

    def test_walk_relation(self):
        m = compile_module(
            'paths contains p { walk(input, [p, v]); v == "x" }\n'
            'has_admin { walk(input, [_, v]); v == "admin" }\n'
        )
        out = m.evaluate({"a": {"b": "x"}, "roles": ["admin", "user"]})
        assert out["has_admin"] is True
        assert out["paths"] == [["a", "b"]]

    def test_walk_ground_and_nested(self):
        m = compile_module(
            'allow { walk(input, [["a", "b"], v]); v == 1 }\n'
            # collect every string leaf under any "labels" object
            'labels contains v { walk(input, [p, lv]); p[count(p) - 1] == "labels"; '
            'v := lv[_] }\n'
        )
        out = m.evaluate({"a": {"b": 1},
                          "x": {"labels": {"t": "blue"}},
                          "y": {"labels": {"u": "green"}}})
        assert out["allow"] is True
        assert sorted(out["labels"]) == ["blue", "green"]

    def test_function_mocking(self):
        m = compile_module(
            "f(x) = x * 2\n"
            "g(x) = x + 100\n"
            "doubled = f(3)\n"
            "mocked { f(3) == 103 with f as g }\n"
            "consted { f(3) == 42 with f as 42 }\n"
            "builtin_const { count(\"abc\") == 99 with count as 99 }\n"
            "builtin_fn { count(\"abc\") == 6 with count as double_len }\n"
            "double_len(s) = 2 * 3\n"
        )
        out = m.evaluate({})
        assert out["doubled"] == 6
        assert out["mocked"] is True
        assert out["consted"] is True
        assert out["builtin_const"] is True
        assert out["builtin_fn"] is True

    def test_mock_scopes_referenced_rules(self):
        # the mock applies through rules the wrapped expression references
        # (OPA `with` scoping: a fresh evaluation under the override)
        m = compile_module(
            "inner = count(input.xs)\n"
            "outer { inner == 7 with count as 7 }\n"
            "normal = inner\n"
        )
        out = m.evaluate({"xs": []})
        assert out["outer"] is True
        assert out["normal"] == 0

    def test_mock_combined_with_input(self):
        m = compile_module(
            "f(x) = count(x)\n"
            "ok { f(input.xs) == 9 with input.xs as [1] with f as 9 }\n"
        )
        assert m.evaluate({"xs": []})["ok"] is True

    def test_multi_module_composition(self):
        src = (
            "package main\n"
            "allow { data.lib.helpers.is_admin }\n"
            "doubled = data.lib.mathx.double(4)\n"
            "libdoc = data.lib.helpers\n"
            "package lib.helpers\n"
            'is_admin { input.user.role == "admin" }\n'
            "level = 3\n"
            "package lib.mathx\n"
            "double(x) = x * 2\n"
        )
        m = compile_module(src)
        out = m.evaluate({"user": {"role": "admin"}})
        assert out["allow"] is True
        assert out["doubled"] == 8
        assert out["libdoc"] == {"is_admin": True, "level": 3}
        deny = compile_module(src).evaluate({"user": {"role": "peon"}})
        assert "allow" not in deny
        assert deny["libdoc"] == {"level": 3}

    def test_multi_module_subtree_and_external_data(self):
        src = (
            "package main\n"
            "tree = data.lib\n"
            "ext = data.settings.mode\n"
            "package lib.a\n"
            "x = 1\n"
            "package lib.b\n"
            "y { false }\n"
        )
        m = compile_module(src)
        out = m.evaluate({}, data={"settings": {"mode": "strict"},
                                   "lib": {"a": {"ext": True}, "c": 9}})
        # virtual docs merge over external data, packages nest
        assert out["tree"] == {"a": {"x": 1, "ext": True}, "b": {}, "c": 9}
        assert out["ext"] == "strict"

    def test_multi_module_cross_module_mock(self):
        src = (
            "package main\n"
            "ok { data.lib.f(1) == 10 with data.lib.f as ten }\n"
            "ten(x) = 10\n"
            "package lib\n"
            "f(x) = x\n"
        )
        assert compile_module(src).evaluate({})["ok"] is True

    def test_recursion_across_modules_fails_closed(self):
        src = (
            "package main\n"
            "a { data.lib.b }\n"
            "package lib\n"
            "b { data.main.a }\n"
        )
        m = compile_module(src, package="main")
        with pytest.raises(RegoError):
            m.evaluate({})

    def test_opa_evaluator_uses_round4_features(self):
        # through the real OPA evaluator seam (inline rego, main package
        # injected): helper package + walk + mocking all compose
        rego_src = (
            "roles contains v { walk(input.auth, [_, v]); is_string(v) }\n"
            'allow { "admin" in roles }\n'
        )
        opa = OPA("t/az", inline_rego=rego_src)
        out = opa._module.evaluate(
            {"auth": {"identity": {"realm_access": {"roles": ["admin"]}}}})
        assert out["allow"] is True

    def test_some_key_value_in(self):
        m = compile_module(
            "admins contains u { some u, r in input.users; r == \"admin\" }\n"
            "second = v { some i, v in input.xs; i == 1 }\n"
            "anyval { some _, v in input.users; v == \"admin\" }\n"
        )
        out = m.evaluate({"users": {"ann": "admin", "bob": "user"},
                          "xs": ["a", "b", "c"]})
        assert out["admins"] == ["ann"]
        assert out["second"] == "b"
        assert out["anyval"] is True

    def test_mock_cycle_fails_closed(self):
        # a mock chain that cycles (directly or mutually) must be a
        # RegoError (→ deny), never unbounded recursion
        direct = compile_module(
            'allow { count([1]) == 1 with count as count }')
        with pytest.raises(RegoError, match="cycle"):
            direct.evaluate({})
        mutual = compile_module(
            'allow { count([1]) == 1 with count as sum with sum as count }')
        with pytest.raises(RegoError, match="cycle"):
            mutual.evaluate({})

    def test_encoding_and_time_builtins(self):
        m = compile_module(
            'j = json.marshal({"a": [1, 2]})\n'
            'b = base64.encode("hi")\n'
            'bd = base64.decode("aGk=")\n'
            'bu = base64url.encode_no_pad("hi?")\n'
            'bud = base64url.decode("aGk_")\n'
            'h = hex.encode("hi")\n'
            'hd = hex.decode("6869")\n'
            't = time.parse_rfc3339_ns("2026-07-30T00:00:00Z")\n'
            'tns = time.parse_rfc3339_ns("2026-07-30T00:00:00.123456789Z")\n'
            'tus = time.parse_rfc3339_ns("2026-07-30T12:34:56.654321+00:00")\n'
            'js = json.marshal({"b": 1, "a": 2})\n'
        )
        out = m.evaluate({})
        assert out["j"] == '{"a":[1,2]}'
        assert out["b"] == "aGk=" and out["bd"] == "hi"
        assert out["bu"] == "aGk_" and out["bud"] == "hi?"
        assert out["h"] == "6869" and out["hd"] == "hi"
        assert out["t"] == 1785369600000000000
        # exact integer ns — no float rounding, no sub-µs truncation
        assert out["tns"] == 1785369600123456789
        assert out["tus"] == 1785414896654321000
        # Go encoding/json marshals object keys sorted
        assert out["js"] == '{"a":2,"b":1}'

    def test_crypto_units_regex_builtins(self):
        m = compile_module(
            'h = crypto.sha256("hello")\n'
            'h1 = crypto.sha1("hello")\n'
            'h5 = crypto.md5("hello")\n'
            'b = units.parse_bytes("10MiB")\n'
            'b2 = units.parse_bytes("2K")\n'
            'parts = regex.split("[,;] ?", "a,b; c")\n'
            'parts2 = regex.split("(,)|;", "a,b;c")\n'
            'rep = regex.replace("xabbcy", "a(b+)c", "<$1>")\n'
            'rep0 = regex.replace("xabbcy", "ab+c", "<$0>")\n'
            'repd = regex.replace("cost", "co", "$$")\n'
            # Go Regexp.Expand: `$1x` parses as group name "1x" → no such
            # group → "" (Python \g<1x> would raise re.error)
            'repgo = regex.replace("xabbcy", "a(b+)c", "<$1x>")\n'
            # reference to a nonexistent numeric group → "" (not an error)
            'repmiss = regex.replace("xabbcy", "a(b+)c", "<$9>")\n'
            # unmatched optional group expands to ""
            'repopt = regex.replace("ac", "a(b)?c", "<$1>")\n'
            # backslashes in the template are literal in Go
            'repbs = regex.replace("ab", "a", "\\\\d$0")\n'
        )
        out = m.evaluate({})
        assert out["h"] == ("2cf24dba5fb0a30e26e83b2ac5b9e29e"
                            "1b161e5c1fa7425e73043362938b9824")
        assert out["h1"] == "aaf4c61ddcc5e8a2dabede0f3b482cd9aea9434d"
        assert out["h5"] == "5d41402abc4b2a76b9719d911017c592"
        assert out["b"] == 10 * 1024 * 1024
        assert out["b2"] == 2000
        assert out["parts"] == ["a", "b", "c"]
        assert out["parts2"] == ["a", "b", "c"]  # no capture-group leakage
        assert out["rep"] == "x<bb>y"
        assert out["rep0"] == "x<abbc>y"
        assert out["repd"] == "$st"
        assert out["repgo"] == "x<>y"
        assert out["repmiss"] == "x<>y"
        assert out["repopt"] == "<>"
        assert out["repbs"] == "\\dab"
        with pytest.raises(RegoError):
            compile_module("h = crypto.sha256(3)").evaluate({})
