"""Fault-injected graceful degradation (ISSUE 5): the injectable fault
plane, the device circuit breaker with half-open probing, batch
retry-then-host-oracle degradation (exactness preserved), the completer
watchdog, deadline-aware shedding, typed fail-closed errors, graceful
drain, and the unbounded-wait code-lint extension.

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports)."""

from __future__ import annotations

import asyncio
import threading
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime import engine as engine_mod
from authorino_tpu.runtime import faults
from authorino_tpu.runtime.breaker import CircuitBreaker
from authorino_tpu.utils.rpc import (
    DEADLINE_EXCEEDED,
    UNAVAILABLE,
    CheckAbort,
    http_status_for,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """Every test leaves the process-wide fault plane OFF."""
    yield
    faults.FAULTS.disarm()


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def sample(name, labels=None):
    from prometheus_client import REGISTRY

    v = REGISTRY.get_sample_value(name, labels or {})
    return 0.0 if v is None else v


RULE = All(
    Pattern("auth.identity.roles", Operator.INCL, "admin"),
    Pattern("auth.identity.groups", Operator.EXCL, "banned"),
)


def build_engine(**kw) -> PolicyEngine:
    # verdict cache off by default here: cached verdicts legitimately skip
    # the device, which would mask whether a fault path actually ran
    kw.setdefault("verdict_cache_size", 0)
    kw.setdefault("max_batch", 8)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id="c", hosts=["c"], runtime=None,
                    rules=ConfigRules(name="c", evaluators=[(None, RULE)]))
    ])
    return engine


def doc(i: int, allow: bool) -> dict:
    # per-index distinct docs so no two rows dedup-collapse
    return {"auth": {"identity": {
        "roles": ["admin", f"r{i}"] if allow else [f"r{i}"],
        "groups": []}}}


async def submit_all(engine, docs, **kw):
    outs = await asyncio.gather(
        *(engine.submit(d, "c", **kw) for d in docs))
    return [bool(rule[0]) for rule, _ in outs]


# ---------------------------------------------------------------------------
# fault plane
# ---------------------------------------------------------------------------


class TestFaultPlane:
    def test_off_by_default_and_zero_cost_gate(self):
        faults.FAULTS.disarm()
        assert faults.ACTIVE is False
        assert faults.FAULTS.describe()["armed"] is False

    def test_profile_expansion_and_spec_keys(self):
        faults.FAULTS.arm("device-down")
        d = faults.FAULTS.describe()
        assert d["armed"] and d["rules"] == ["kernel:raise"]
        faults.FAULTS.arm("kernel:delay:delay_ms=20:p=0.5:n=3:lane=native")
        r = faults.FAULTS._rules[0]
        assert (r.stage, r.mode, r.lane) == ("kernel", "delay", "native")
        assert r.delay_s == pytest.approx(0.02)
        assert r.p == 0.5 and r.n == 3
        # "dispatch" is an alias for the kernel stage
        faults.FAULTS.arm("dispatch:raise")
        assert faults.FAULTS._rules[0].stage == "kernel"

    def test_bad_specs_raise(self):
        for bad in ("kernel", "kernel:explode", "nostage:raise",
                    "kernel:raise:zzz=1"):
            with pytest.raises(ValueError):
                faults.FAULTS.arm(bad)

    def test_firing_limit_and_lane_filter(self):
        faults.FAULTS.arm("kernel:raise:n=2:lane=engine")
        with pytest.raises(faults.InjectedFault):
            faults.FAULTS.check("kernel", "engine")
        # other lane and other stages never match
        faults.FAULTS.check("kernel", "native")
        faults.FAULTS.check("readback", "engine")
        with pytest.raises(faults.InjectedFault):
            faults.FAULTS.check("kernel", "engine")
        # n=2 exhausted: the rule goes quiet
        faults.FAULTS.check("kernel", "engine")
        assert faults.FAULTS.fired == {"kernel:raise:engine": 2}

    def test_time_window(self):
        faults.FAULTS.arm("kernel:raise:for=0.05")
        with pytest.raises(faults.InjectedFault):
            faults.FAULTS.check("kernel", "engine")
        time.sleep(0.08)
        faults.FAULTS.check("kernel", "engine")  # window closed: no fault

    def test_hung_handle_wrap_and_release(self):
        class H:
            def is_ready(self):
                return True

            def __array__(self, dtype=None):
                return np.zeros((1, 1))

        faults.FAULTS.arm("kernel:hang")
        h = faults.FAULTS.wrap_handle(H(), "engine")
        assert isinstance(h, faults.HungHandle)
        assert h.is_ready() is False
        with pytest.raises(faults.InjectedFault):
            np.asarray(h)  # permanent wedge must not deadlock the caller
        # bounded wedge: the real handle shows through after the window
        h2 = faults.HungHandle(H(), release_at=time.monotonic() - 1)
        assert h2.is_ready() is True
        assert np.asarray(h2).shape == (1, 1)


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_trips_after_threshold_and_recovers_via_probe(self):
        br = CircuitBreaker("t1", threshold=3, reset_s=0.05)
        assert br.state == "closed"
        for _ in range(2):
            br.record_failure()
        assert br.state == "closed" and br.allow_device()
        br.record_failure()
        assert br.state == "open"
        assert br.allow_device() is False  # cooldown not elapsed
        time.sleep(0.06)
        assert br.allow_device() is True   # the half-open probe slot
        assert br.state == "half-open"
        assert br.allow_device() is False  # ONE probe at a time
        br.record_success()
        assert br.state == "closed"
        assert br.allow_device() is True

    def test_probe_failure_reopens(self):
        br = CircuitBreaker("t2", threshold=1, reset_s=0.05)
        br.record_failure()
        assert br.state == "open"
        time.sleep(0.06)
        assert br.allow_device() is True
        br.record_failure()
        assert br.state == "open"
        assert br.allow_device() is False  # cooldown restarted
        states = [t["state"] for t in br.to_json()["transitions"]]
        assert states == ["open", "half-open", "open"]

    def test_release_probe_frees_the_slot_without_a_verdict(self):
        # a batch admitted as the half-open probe may turn out fully
        # verdict-cache-resolved — it proved nothing about the device and
        # must release the slot (NOT close the circuit, NOT wedge it)
        br = CircuitBreaker("t4", threshold=1, reset_s=0.01)
        br.record_failure()
        time.sleep(0.02)
        assert br.allow_device() is True       # probe claimed
        br.release_probe()
        assert br.state == "half-open"         # no verdict recorded
        assert br.allow_device() is True       # next batch can probe again
        br.record_success()
        assert br.state == "closed"

    def test_cache_resolved_batch_never_closes_the_circuit(self):
        # lane selection OFF: the breaker probe semantics this test pins
        # are synchronous (the probe batch's device verdict lands before
        # submit returns); with speculative dual-dispatch the host twin
        # answers first and the breaker verdict arrives when the device
        # half completes (pinned in tests/test_lane_select.py)
        engine = build_engine(verdict_cache_size=1024, breaker_threshold=1,
                              breaker_reset_s=0.05, lane_select=False,
                              speculative_dispatch=False)
        d = doc(0, True)
        assert run(submit_all(engine, [d])) == [True]  # seeds the cache
        engine.breaker.record_failure()
        assert engine.breaker.state == "open"
        time.sleep(0.06)
        # the cached doc resolves without the device: still correct, and
        # the breaker must NOT flip closed off it
        assert run(submit_all(engine, [d])) == [True]
        assert engine.breaker.state == "half-open"
        # a fresh (uncached) doc is the real probe
        assert run(submit_all(engine, [doc(1, False)])) == [False]
        assert engine.breaker.state == "closed"

    def test_success_resets_consecutive_count(self):
        br = CircuitBreaker("t3", threshold=2, reset_s=10)
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == "closed"  # never two CONSECUTIVE failures


# ---------------------------------------------------------------------------
# engine lane: retry, degrade, breaker, watchdog
# ---------------------------------------------------------------------------


class TestEngineDegradation:
    def test_transient_fault_retried_once_device_answers(self):
        engine = build_engine()
        retries0 = sample("auth_server_batch_retries_total",
                          {"lane": "engine"})
        degraded0 = sample("auth_server_degraded_decisions_total",
                           {"lane": "engine"})
        faults.FAULTS.arm("kernel:raise:n=1")
        assert run(submit_all(engine, [doc(0, True)])) == [True]
        assert sample("auth_server_batch_retries_total",
                      {"lane": "engine"}) == retries0 + 1
        # the RETRY succeeded on the device: nothing degraded, breaker closed
        assert sample("auth_server_degraded_decisions_total",
                      {"lane": "engine"}) == degraded0
        assert engine.breaker.state == "closed"

    def test_persistent_failure_serves_exact_verdicts_and_recovers(self):
        """The acceptance scenario: under a persistent device fault every
        request keeps being answered with ORACLE-EXACT verdicts (no request
        ever observes a raw exception), the breaker trips, and once the
        fault clears the half-open probe restores device serving.

        Lane selection OFF: this pins the BREAKER-GATED degrade machinery
        — with the cost model live, the first degrade teaches the host-row
        EWMA and subsequent cuts route host-side at the cut (first-class,
        not counted as degraded), which is the ISSUE 12 behavior pinned in
        tests/test_lane_select.py."""
        engine = build_engine(breaker_threshold=2, breaker_reset_s=0.2,
                              lane_select=False)
        degraded0 = sample("auth_server_degraded_decisions_total",
                           {"lane": "engine"})
        faults.FAULTS.arm("device-down")

        docs = [doc(i, i % 3 != 0) for i in range(24)]
        expected = [RULE.matches(d) for d in docs]

        async def staggered():
            out = []
            for d in docs:  # sequential: multiple batches → breaker trips
                rule, _ = await engine.submit(d, "c")
                out.append(bool(rule[0]))
            return out

        assert run(staggered()) == expected
        assert engine.breaker.state == "open"
        assert sample("auth_server_degraded_decisions_total",
                      {"lane": "engine"}) >= degraded0 + 24
        # breaker OPEN: batches skip the device entirely — the fault plane
        # stops seeing kernel attempts while the oracle keeps answering
        fired_open = dict(faults.FAULTS.fired)
        assert run(submit_all(engine, [doc(100, True), doc(101, False)])) \
            == [True, False]
        assert faults.FAULTS.fired == fired_open

        # fault clears → cooldown elapses → half-open probe → CLOSED
        faults.FAULTS.disarm()
        time.sleep(0.25)
        assert run(submit_all(engine, [doc(200, True)])) == [True]
        assert engine.breaker.state == "closed"
        states = [t["state"] for t in engine.breaker.transitions]
        assert states[-2:] == ["half-open", "closed"]

    def test_flap_profile_recovers_without_operator_action(self):
        # the flap fault class: device down for a window, then healthy —
        # the breaker must ride it out and re-close on its own.  Lane
        # selection off: the recovery this test pins comes from breaker
        # half-open probes on DEVICE-routed batches (see the note on
        # test_persistent_failure above)
        engine = build_engine(breaker_threshold=2, breaker_reset_s=0.15,
                              lane_select=False)
        faults.FAULTS.arm("kernel:raise:for=0.2")

        async def staggered(docs_):
            out = []
            for d in docs_:
                rule, _ = await engine.submit(d, "c")
                out.append(bool(rule[0]))
            return out

        # every request answered correctly THROUGH the flap
        assert run(staggered([doc(i, True) for i in range(6)])) == [True] * 6
        time.sleep(0.4)  # fault window closed AND cooldown elapsed
        assert run(submit_all(engine, [doc(10, True)])) == [True]
        assert engine.breaker.state == "closed"

    def test_degrade_is_oracle_exact_on_membership_overflow(self):
        # overflow rows (roles > members_k) are the kernel's lossy case —
        # the degraded lane must stay exact there too (the oracle ignores
        # the compact payload entirely)
        engine = build_engine(breaker_threshold=100)
        faults.FAULTS.arm("device-down")
        over = {"auth": {"identity": {
            "roles": [f"r{k}" for k in range(10)] + ["admin"],
            "groups": []}}}
        assert run(submit_all(engine, [over])) == [RULE.matches(over)]

    def test_watchdog_times_out_wedged_batches(self):
        engine = build_engine(device_timeout_s=0.15, breaker_threshold=100)
        wd0 = sample("auth_server_device_watchdog_timeouts_total",
                     {"lane": "engine"})
        faults.FAULTS.arm("wedge")  # readbacks never arrive
        t0 = time.monotonic()
        assert run(submit_all(engine, [doc(0, True)])) == [True]
        elapsed = time.monotonic() - t0
        # attempt 0 wedges (0.15s) → retry wedges (0.15s) → oracle degrade
        assert sample("auth_server_device_watchdog_timeouts_total",
                      {"lane": "engine"}) == wd0 + 2
        assert 0.25 < elapsed < 5.0

    def test_no_snapshot_is_typed_unavailable(self):
        engine = PolicyEngine(members_k=4, mesh=None, verdict_cache_size=0)

        async def one():
            with pytest.raises(CheckAbort) as ei:
                await engine.submit(doc(0, True), "c")
            return ei.value

        e = run(one())
        assert e.code == UNAVAILABLE
        assert "unavailable" in str(e).lower() or "snapshot" in str(e)


# ---------------------------------------------------------------------------
# deadline-aware shedding
# ---------------------------------------------------------------------------


class TestDeadlineShedding:
    def test_expired_deadline_is_shed_typed_before_dispatch(self):
        engine = build_engine()
        shed0 = sample("auth_server_deadline_shed_total", {"lane": "engine"})

        async def one():
            with pytest.raises(CheckAbort) as ei:
                await engine.submit(doc(0, True), "c",
                                    deadline=time.monotonic() - 0.01)
            return ei.value

        e = run(one())
        assert e.code == DEADLINE_EXCEEDED
        assert http_status_for(e.code) == 504
        assert sample("auth_server_deadline_shed_total",
                      {"lane": "engine"}) == shed0 + 1

    def test_headroom_uses_device_rtt_estimate(self):
        # lane selection OFF: with it on, a deadline the device cannot
        # make but the host lane can is RESCUED host-side instead of shed
        # (pinned in tests/test_lane_select.py) — this pins the legacy
        # shed contract
        engine = build_engine(lane_select=False)
        # a warm request seeds the EWMA; then force a huge estimate — a
        # deadline inside one expected RTT cannot be met and must shed
        assert run(submit_all(engine, [doc(0, True)])) == [True]
        engine._device_ewma = 5.0

        async def one():
            with pytest.raises(CheckAbort) as ei:
                await engine.submit(doc(1, True), "c",
                                    deadline=time.monotonic() + 1.0)
            return ei.value

        assert run(one()).code == DEADLINE_EXCEEDED
        # a comfortable deadline still rides the device
        engine._device_ewma = 0.0
        assert run(submit_all(engine, [doc(2, True)],
                              deadline=time.monotonic() + 30)) == [True]

    def test_mixed_batch_sheds_only_the_expired(self):
        engine = build_engine()

        async def mixed():
            past = time.monotonic() - 0.01
            live = engine.submit(doc(0, True), "c",
                                 deadline=time.monotonic() + 30)
            dead = engine.submit(doc(1, True), "c", deadline=past)
            r = await asyncio.gather(live, dead, return_exceptions=True)
            return r

        live, dead = run(mixed())
        assert bool(live[0][0]) is True
        assert isinstance(dead, CheckAbort) and dead.code == DEADLINE_EXCEEDED


# ---------------------------------------------------------------------------
# pipeline: typed codes end to end
# ---------------------------------------------------------------------------


def make_runtime(provider):
    from authorino_tpu.evaluators.authorization import PatternMatching
    from authorino_tpu.evaluators.base import (
        AuthorizationConfig,
        RuntimeAuthConfig,
    )

    ev = PatternMatching(RULE, batched_provider=provider, evaluator_slot=0)
    return RuntimeAuthConfig(
        labels={"namespace": "ns", "name": "cfg"},
        authorization=[AuthorizationConfig(name="authz", evaluator=ev)])


def make_request():
    from authorino_tpu.authjson.wellknown import (
        CheckRequestModel,
        HttpRequestAttributes,
    )

    return CheckRequestModel(
        http=HttpRequestAttributes(id="r1", method="GET", path="/",
                                   host="c", headers={}))


class TestPipelineTypedCodes:
    def test_timeout_maps_to_deadline_exceeded_504(self):
        from authorino_tpu.pipeline.pipeline import AuthPipeline

        async def never(pipeline, slot):
            await asyncio.sleep(30)

        pipeline = AuthPipeline(make_request(), make_runtime(never),
                                timeout=0.02)
        result = run(pipeline.evaluate())
        assert result.code == DEADLINE_EXCEEDED
        assert result.message == "context deadline exceeded"
        assert http_status_for(result.code) == 504

    def test_expired_deadline_fails_fast(self):
        from authorino_tpu.pipeline.pipeline import AuthPipeline

        async def never(pipeline, slot):  # must never be reached
            raise AssertionError("phase ran past an expired deadline")

        pipeline = AuthPipeline(make_request(), make_runtime(never),
                                deadline=time.monotonic() - 1)
        result = run(pipeline.evaluate())
        assert result.code == DEADLINE_EXCEEDED

    def test_checkabort_resolves_typed_not_raw(self):
        from authorino_tpu.pipeline.pipeline import AuthPipeline

        async def aborting(pipeline, slot):
            raise CheckAbort(UNAVAILABLE, "policy evaluation unavailable")

        pipeline = AuthPipeline(make_request(), make_runtime(aborting))
        result = run(pipeline.evaluate())
        assert result.code == UNAVAILABLE
        assert result.message == "policy evaluation unavailable"
        assert http_status_for(result.code) == 503

    def test_engine_check_end_to_end_degraded_never_raw(self):
        """Full service path under a persistent device fault: engine.check
        answers OK/denied per the oracle — never an exception, never a raw
        exception repr in the deny reason."""
        engine = build_engine(breaker_threshold=100)
        rt = make_runtime(engine.provider_for("c"))
        engine.index.set("c", "c", EngineEntry(
            id="c", hosts=["c"], runtime=rt, rules=None), override=True)
        faults.FAULTS.arm("device-down")

        async def checks():
            allowed = await engine.check(make_request())
            req2 = make_request()
            req2.http.headers["x"] = "y"
            return allowed

        result = run(checks())
        assert result.code in (0, 7)  # OK or a clean deny — oracle-decided
        assert "InjectedFault" not in (result.message or "")


# ---------------------------------------------------------------------------
# graceful drain
# ---------------------------------------------------------------------------


class FakeHandle:
    def __init__(self, ready_at):
        self.ready_at = ready_at

    def is_ready(self):
        return time.monotonic() >= self.ready_at

    def __array__(self, dtype=None):
        return np.zeros((1, 1))


class SlowStubDevice:
    """Replaces _encode_and_launch with a stub whose batches complete after
    a fixed latency — in-flight work a drain must wait out."""

    def __init__(self, engine, latency_s):
        self.engine = engine
        self.latency_s = latency_s
        self.launched = 0
        engine._encode_and_launch = self._launch

    def _launch(self, snap, batch):
        n = len(batch)
        self.launched += n
        binfo = {"batch_size": n, "pad": n, "eff": 0,
                 "start_ns": time.time_ns(), "duration_s": 0.0}

        def finalize(packed):
            rule = np.ones((n, 1), dtype=bool)
            return rule, np.zeros((n, 1), dtype=bool), None

        return engine_mod._Inflight(
            self.engine, batch,
            FakeHandle(time.monotonic() + self.latency_s),
            finalize, binfo, np.zeros(n))


class TestGracefulDrain:
    def test_drain_resolves_all_inflight_then_blocks_admission(self):
        engine = build_engine(max_batch=4, max_inflight_batches=4)
        stub = SlowStubDevice(engine, latency_s=0.15)

        async def scenario():
            inflight = [asyncio.ensure_future(engine.submit(doc(i, True), "c"))
                        for i in range(16)]
            await asyncio.sleep(0.03)  # let batches cut and launch
            engine.begin_drain()
            # drain stops ADMISSION...
            with pytest.raises(CheckAbort) as ei:
                await engine.submit(doc(99, True), "c")
            assert ei.value.code == UNAVAILABLE
            # ...while every already-admitted request still resolves
            done = await asyncio.gather(*inflight)
            loop = asyncio.get_running_loop()
            drained = await loop.run_in_executor(None, engine.drain, 5.0)
            return done, drained

        done, drained = run(scenario())
        assert drained is True
        assert len(done) == 16 and all(bool(r[0]) for r, _ in done)
        assert engine._inflight == 0 and not engine._queue
        assert stub.launched == 16

    def test_drain_times_out_on_wedged_device(self):
        engine = build_engine(max_batch=4)
        SlowStubDevice(engine, latency_s=60)

        async def scenario():
            fut = asyncio.ensure_future(engine.submit(doc(0, True), "c"))
            await asyncio.sleep(0.03)
            loop = asyncio.get_running_loop()
            drained = await loop.run_in_executor(None, engine.drain, 0.1)
            fut.cancel()
            return drained

        assert run(scenario()) is False

    def test_readyz_surfaces_drain_and_degraded_circuit(self):
        from aiohttp.test_utils import TestClient, TestServer

        from authorino_tpu.service.http_server import build_app

        engine = build_engine(breaker_threshold=1)

        async def scenario():
            app = build_app(engine, readiness=lambda: True)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/readyz")
                ok_body = await r.text()
                ok_status = r.status
                # tripped breaker: surfaced, but STILL ready (host-degraded
                # verdicts are exact; shifting load helps nobody)
                engine.breaker.record_failure()
                r = await client.get("/readyz")
                degraded_body, degraded_status = await r.text(), r.status
                engine.breaker.record_success()
                engine.begin_drain()
                r = await client.get("/readyz")
                drain_body, drain_status = await r.text(), r.status
                dv = await (await client.get("/debug/vars")).json()
                return (ok_status, ok_body, degraded_status, degraded_body,
                        drain_status, drain_body, dv)
            finally:
                await client.close()

        (ok_status, ok_body, degraded_status, degraded_body, drain_status,
         drain_body, dv) = run(scenario())
        assert (ok_status, ok_body) == (200, "ok")
        assert degraded_status == 200 and "degraded" in degraded_body
        assert drain_status == 503 and "draining" in drain_body
        assert dv["engine"]["draining"] is True
        assert dv["engine"]["breaker"]["state"] == "closed"


# ---------------------------------------------------------------------------
# code lint: unbounded-wait on breaker/drain paths
# ---------------------------------------------------------------------------


class TestUnboundedWaitLint:
    def lint(self, src):
        from authorino_tpu.analysis.code_lint import lint_source

        return lint_source(src, "planted.py")

    def test_flags_timeoutless_wait_and_join_on_drain_paths(self):
        src = (
            "def drain(self):\n"
            "    self._evt.wait()\n"
            "def stop(self):\n"
            "    self._thread.join()\n"
            "async def shutdown(self):\n"
            "    await self._done.wait()\n"
        )
        found = self.lint(src)
        assert [f.kind for f in found] == ["unbounded-wait"] * 3
        assert [f.location for f in found] == [
            "planted.py:2", "planted.py:4", "planted.py:6"]

    def test_bounded_or_off_path_waits_are_clean(self):
        src = (
            "def drain(self):\n"
            "    self._evt.wait(0.2)\n"
            "def stop(self):\n"
            "    self._thread.join(timeout=5)\n"
            "def completer_poll(self):\n"
            "    self._evt.wait()\n"          # not a drain-path name
            "def stop_all(self):\n"
            "    p = os.path.join('a', 'b')\n"  # args present: not waitish
        )
        assert self.lint(src) == []

    def test_nested_def_takes_its_own_name(self):
        src = (
            "def drain(self):\n"
            "    def poll():\n"
            "        evt.wait()\n"   # nested non-drain name: clean
            "    self._evt.wait()\n"  # the drain body itself: flagged
        )
        found = self.lint(src)
        assert [f.location for f in found] == ["planted.py:4"]

    def test_suppression(self):
        src = (
            "def drain(self):\n"
            "    self._evt.wait()  # lint-ok: unbounded-wait -- bounded by "
            "caller\n"
        )
        assert self.lint(src) == []

    def test_repo_drain_paths_stay_clean(self):
        import os

        from authorino_tpu.analysis.code_lint import lint_paths

        root = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "authorino_tpu")
        assert [str(f) for f in lint_paths([root])
                if f.kind == "unbounded-wait"] == []
