"""Kernel cost observatory (ISSUE 16, runtime/kernel_cost.py +
docs/performance.md "Kernel cost model").

The perf-guard plane: structural device-cost counts (launches, H2D/D2H
bytes, pad waste) pinned as EXACT values, not wall-clock thresholds —
they do not swing with the host, so a regression here is a real shape
change in the dispatch plane, never flake.

Covers: one-launch-per-batch parity with exact H2D/D2H byte math on the
engine lane; the planted-extra-launch self-test (the gate demonstrably
trips when a stray launch appears); zero-launch parity for fully
cache/dedup-resolved batches; host-lane serving folding rows with ZERO
device launches; mesh lane counting ONE collective launch per
shard-step (not one per shard); the native-frontend per-row H2D
arithmetic (pure shape math, unit-tested without the C++ module); the
warm-jit-grid entry-point audit (PR 1's grid predates the bitpacked /
fused readback and the PR 14 relations operands — pinned here so the
surface cannot drift again); the modeled-cost regression anomaly
(>=2x per-row jump -> cost-regression flight-recorder record, advisory);
the /debug/profile smoke; and the new metric families.

Deliberately import-light: collects on images without `cryptography`."""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.compiler.compile import compile_corpus
from authorino_tpu.compiler.encode import encode_batch
from authorino_tpu.compiler.pack import pack_batch
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.ops.pattern_eval import packed_width, staged_h2d_bytes
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.flight_recorder import FlightRecorder
from authorino_tpu.runtime.kernel_cost import (
    LEDGER,
    CostModel,
    entry_points,
    params_fingerprint,
)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def sample(name, labels=None):
    from prometheus_client import REGISTRY

    v = REGISTRY.get_sample_value(name, labels or {})
    return 0.0 if v is None else v


# the raw (underived) ledger fields — deltas over these are exact
RAW = ("batches", "launches", "zero_launch_batches", "rows",
       "device_rows", "h2d_bytes", "d2h_bytes", "pad_rows",
       "pad_waste_rows", "dedup_avoided_rows", "cache_avoided_rows")


def delta(before, after):
    return {k: after[k] - before[k] for k in RAW}


def assert_launch_parity(d):
    """The structural perf-regression gate: every batch that reached the
    device performed exactly ONE launch (ROADMAP item 2's one-dispatch
    target), and cache/dedup-resolved batches performed exactly zero.  A
    failover re-dispatch, a stray warm-up launch, or an un-fused operand
    upload all break this equality."""
    assert d["launches"] == d["batches"] - d["zero_launch_batches"], (
        f"launch parity broken: {d['launches']} launches for "
        f"{d['batches']} batches ({d['zero_launch_batches']} zero-launch)")


RULE = All(
    Pattern("request.method", Operator.EQ, "GET"),
    Pattern("auth.identity.org", Operator.EQ, "acme"),
)


def build_engine(**kw) -> PolicyEngine:
    kw.setdefault("max_batch", 32)
    kw.setdefault("lane_select", False)
    kw.setdefault("batch_dedup", False)
    kw.setdefault("verdict_cache_size", 0)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id="c", hosts=["c"], runtime=None,
                    rules=ConfigRules(name="c", evaluators=[(None, RULE)]))
    ])
    return engine


def doc(i: int, allow=True):
    return {"request": {"method": "GET"},
            "auth": {"identity": {"org": "acme" if allow else "evil",
                                  "tag": f"t{i}"}}}


async def submit_all(engine, docs):
    outs = await asyncio.gather(*(engine.submit(d, "c") for d in docs))
    return [bool(rule[0]) for rule, _ in outs]


def per_row_h2d(policy) -> int:
    """Exact fused-staging bytes for ONE padded row of this policy —
    the same encode/pack path the engine ships, at batch_pad=1."""
    db1 = pack_batch(policy, encode_batch(policy, [doc(0)], [0],
                                          batch_pad=1))
    return staged_h2d_bytes(db1)


# ---------------------------------------------------------------------------
# engine lane: exact structural pins + the planted-launch self-test
# ---------------------------------------------------------------------------

class TestEngineLane:
    def test_one_launch_per_batch_exact_bytes(self):
        m0 = {k: sample(f"auth_server_kernel_{k}_total", {"lane": "engine"})
              for k in ("launches", "h2d_bytes", "d2h_bytes",
                        "pad_waste_rows")}

        async def go():
            engine = build_engine()
            b0 = LEDGER.snapshot("engine")
            assert await submit_all(engine, [doc(i) for i in range(5)]) \
                == [True] * 5
            return engine, delta(b0, LEDGER.snapshot("engine"))

        engine, d = run(go())
        policy = engine._snapshot.policy
        E = int(policy.eval_rule.shape[1])
        W = packed_width(1 + 2 * E)

        assert d["rows"] == 5
        assert d["device_rows"] == 5          # no dedup/cache configured
        assert d["batches"] >= 1
        assert d["zero_launch_batches"] == 0
        assert_launch_parity(d)
        # pad bucketing holds whatever the cut count: bytes are LINEAR in
        # the padded rows, so the per-row pins are exact even if the loop
        # split the 5 submissions across cuts
        assert d["pad_rows"] >= 5
        assert d["pad_waste_rows"] == d["pad_rows"] - 5
        assert d["h2d_bytes"] == d["pad_rows"] * per_row_h2d(policy)
        assert d["d2h_bytes"] == d["pad_rows"] * W
        assert d["dedup_avoided_rows"] == 0
        assert d["cache_avoided_rows"] == 0

        # the counter families moved by exactly the ledger deltas
        assert sample("auth_server_kernel_launches_total",
                      {"lane": "engine"}) - m0["launches"] == d["launches"]
        assert sample("auth_server_kernel_h2d_bytes_total",
                      {"lane": "engine"}) - m0["h2d_bytes"] == d["h2d_bytes"]
        assert sample("auth_server_kernel_d2h_bytes_total",
                      {"lane": "engine"}) - m0["d2h_bytes"] == d["d2h_bytes"]
        assert sample("auth_server_kernel_pad_waste_rows_total",
                      {"lane": "engine"}) - m0["pad_waste_rows"] \
            == d["pad_waste_rows"]

        # derived ratios on the /debug/vars block
        lane = LEDGER.to_json()["engine"]
        assert lane["launches_per_batch"] <= 1.0
        assert lane["d2h_bytes_per_pad_row"] >= 1.0

    def test_planted_extra_launch_trips_gate(self):
        async def go():
            engine = build_engine()
            b0 = LEDGER.snapshot("engine")
            await submit_all(engine, [doc(i) for i in range(3)])
            # plant a stray launch, exactly what a failover re-dispatch
            # or an accidental double-dispatch would record
            LEDGER.observe_launch("engine")
            return delta(b0, LEDGER.snapshot("engine"))

        d = run(go())
        assert d["launches"] == d["batches"] + 1
        with pytest.raises(AssertionError, match="launch parity"):
            assert_launch_parity(d)

    def test_dedup_collapses_device_rows(self):
        async def go():
            engine = build_engine(batch_dedup=True, verdict_cache_size=256)
            b0 = LEDGER.snapshot("engine")
            assert await submit_all(engine, [doc(7)] * 4) == [True] * 4
            d1 = delta(b0, LEDGER.snapshot("engine"))
            b1 = LEDGER.snapshot("engine")
            assert await submit_all(engine, [doc(7)] * 4) == [True] * 4
            return d1, delta(b1, LEDGER.snapshot("engine"))

        d1, d2 = run(go())
        # first round: identical rows collapse before the launch
        assert d1["rows"] == 4
        assert d1["device_rows"] >= 1
        assert (d1["dedup_avoided_rows"] + d1["cache_avoided_rows"]
                == 4 - d1["device_rows"])
        assert_launch_parity(d1)
        # second round: every row verdict-cache-resolved -> ZERO launches,
        # ZERO device rows, ZERO bytes — and the batch still counts
        assert d2["rows"] == 4
        assert d2["cache_avoided_rows"] == 4
        assert d2["device_rows"] == 0
        assert d2["launches"] == 0
        assert d2["zero_launch_batches"] == d2["batches"] >= 1
        assert d2["h2d_bytes"] == 0 and d2["d2h_bytes"] == 0
        assert_launch_parity(d2)

    def test_debug_vars_block_and_entry_points(self):
        engine = build_engine()
        kc = engine.debug_vars()["kernel_cost"]
        assert set(kc) == {"ledger", "modeled", "entry_points"}
        assert kc["ledger"].keys() <= {"engine", "host", "mesh", "native"}
        names = [e["entry"] for e in kc["entry_points"]]
        assert names == ["eval_bitpacked", "eval_fused", "fused_kernel"]
        for e in kc["entry_points"]:
            assert e["operands"][:4] == ["attrs_val", "members_c",
                                         "cpu_dense", "config_id"]

    def test_modeled_cost_populated_at_reconcile(self):
        engine = build_engine()
        modeled = engine.debug_vars()["kernel_cost"]["modeled"]
        assert modeled["component"] == "engine"
        assert modeled["generations_analyzed"] >= 1
        cur = modeled["current"]
        assert cur["regressions"] == []
        e = cur["entries"]["eval_bitpacked"]
        assert e["flops_per_row"] > 0
        assert e["bytes_per_row"] > 0
        assert sample("auth_server_kernel_modeled_flops_per_row",
                      {"entry": "eval_bitpacked"}) > 0


# ---------------------------------------------------------------------------
# host lane: light load served host-side = rows folded, ZERO launches
# ---------------------------------------------------------------------------

class TestHostLane:
    def test_host_lane_zero_device_launches(self):
        async def go():
            engine = build_engine(lane_select=True, max_batch=8)
            # teach the cost model a fast host and a slow device, and pin
            # exploration off: the next small cuts decide HOST
            engine.lanes.cost.observe_host(1e-3, 10)
            engine.lanes.cost.observe_device(0.1, 8)
            engine._device_ewma = 0.1
            engine.lanes.explore_every = 0
            h0 = LEDGER.snapshot("host")
            e0 = LEDGER.snapshot("engine")
            assert await submit_all(engine, [doc(i) for i in range(4)]) \
                == [True] * 4
            return (delta(h0, LEDGER.snapshot("host")),
                    delta(e0, LEDGER.snapshot("engine")))

        dh, de = run(go())
        assert dh["rows"] == 4
        assert dh["batches"] >= 1
        # a host-lane batch is structurally free of the device: no
        # launches, no bytes on the link, no padded rows burned
        assert dh["launches"] == 0
        assert dh["device_rows"] == 0
        assert dh["h2d_bytes"] == 0 and dh["d2h_bytes"] == 0
        assert dh["pad_rows"] == 0
        assert de["launches"] == 0 and de["batches"] == 0


# ---------------------------------------------------------------------------
# mesh lane: ONE collective launch per shard-step, not one per shard
# ---------------------------------------------------------------------------

@pytest.mark.mesh
class TestMeshLane:
    def test_one_collective_launch_per_shard_step(self, mesh_devices):
        from authorino_tpu.parallel import build_mesh

        async def go():
            mesh = build_mesh(n_devices=8, dp=2)
            engine = PolicyEngine(max_batch=32, members_k=4, mesh=mesh,
                                  lane_select=False, batch_dedup=False,
                                  verdict_cache_size=0)
            engine.apply_snapshot([
                EngineEntry(id=f"c{i}", hosts=[f"c{i}"], runtime=None,
                            rules=ConfigRules(
                                name=f"c{i}", evaluators=[(None, RULE)]))
                for i in range(4)
            ])
            m0 = LEDGER.snapshot("mesh")
            e0 = LEDGER.snapshot("engine")
            outs = await asyncio.gather(
                *(engine.submit(doc(i), f"c{i % 4}") for i in range(6)))
            assert [bool(rule[0]) for rule, _ in outs] == [True] * 6
            dv = engine.debug_vars()
            return (delta(m0, LEDGER.snapshot("mesh")),
                    delta(e0, LEDGER.snapshot("engine")), dv)

        dm, de, dv = run(go())
        assert dm["rows"] == 6
        assert dm["device_rows"] == 6
        assert dm["batches"] >= 1
        # the 2x4 mesh runs ONE psum-merged program per shard-step: the
        # parity gate would trip at 8x if launches were counted per shard
        assert_launch_parity(dm)
        assert dm["h2d_bytes"] > 0 and dm["d2h_bytes"] > 0
        # sharded batches fold into the mesh lane, never the engine lane
        assert de["batches"] == 0 and de["launches"] == 0

        ep = dv["kernel_cost"]["entry_points"]
        assert [e["entry"] for e in ep] == ["sharded_step"]
        assert ep[0]["n_shards"] >= 2
        assert "one launch per shard-step" in ep[0]["kind"]


# ---------------------------------------------------------------------------
# native frontend: per-row H2D arithmetic is pure shape math — unit-tested
# here without the C++ module; the full-lane pins ride the native suite
# ---------------------------------------------------------------------------

class TestNativeRowBytes:
    def _arrays(self):
        return {
            "attrs_val": np.zeros((4, 3), np.int32),      # 12 B/row
            "members": np.zeros((4, 2, 4), np.int32),     # 32 B/row
            "cpu_dense": np.zeros((4, 5), np.bool_),      # 5 B/row
            "config_id": np.zeros((4,), np.int32),        # 4 B/row
            "attr_bytes": np.zeros((4, 2, 8), np.uint8),  # eff-trimmed
            "byte_ovf": np.zeros((4, 2), np.bool_),       # 2 B/row
            "shard_of": np.zeros((4,), np.int32),         # 4 B/row
        }

    def test_row_h2d_bytes_exact(self):
        nf = pytest.importorskip(
            "authorino_tpu.runtime.native_frontend",
            reason="native frontend module import needs cryptography")
        NativeFrontend = nf.NativeFrontend

        a = self._arrays()
        base = 12 + 32 + 5 + 4
        assert NativeFrontend._row_h2d_bytes(None, a, 0, False, False) \
            == base
        # DFA lane ships the eff-trimmed byte columns + overflow flags
        assert NativeFrontend._row_h2d_bytes(None, a, 6, True, False) \
            == base + 2 * 6 + 2
        # mesh routing adds one shard_of element per row
        assert NativeFrontend._row_h2d_bytes(None, a, 6, True, True) \
            == base + 2 * 6 + 2 + 4


# ---------------------------------------------------------------------------
# warm-jit-grid audit: the entry points a snapshot can dispatch through,
# with the operand lanes each stages (PR 1's grid surface, re-pinned)
# ---------------------------------------------------------------------------

class TestEntryPointAudit:
    def _cfg(self, *leaves):
        return ConfigRules(name="a", evaluators=[(None, All(*leaves))])

    def test_plain_corpus_base_operands(self):
        pol = compile_corpus([self._cfg(
            Pattern("m", Operator.EQ, "GET"))],
            members_k=4, ovf_assist=False)
        ep = entry_points(policy=pol)
        assert [e["entry"] for e in ep] == ["eval_bitpacked", "eval_fused",
                                            "fused_kernel"]
        for e in ep:
            assert e["operands"] == ["attrs_val", "members_c",
                                     "cpu_dense", "config_id"]

    def test_regex_corpus_adds_dfa_operands(self):
        pol = compile_corpus([self._cfg(
            Pattern("p", Operator.MATCHES, r"^/api/v1"))],
            members_k=4, ovf_assist=False)
        ops = entry_points(policy=pol)[0]["operands"]
        assert "attr_bytes" in ops and "byte_ovf" in ops
        assert "attrs_num" not in ops and "rel_rows" not in ops

    def test_numeric_corpus_adds_numeric_operands(self):
        pol = compile_corpus([self._cfg(
            Pattern("v.x", Operator.GT, "10"))],
            members_k=4, ovf_assist=False)
        ops = entry_points(policy=pol)[0]["operands"]
        assert "attrs_num" in ops and "num_valid" in ops

    def test_relations_corpus_adds_relation_operands(self):
        from authorino_tpu.expressions import InGroup
        from authorino_tpu.relations.closure import RelationClosure

        rel = RelationClosure([("alice", "staff"), ("staff", "org")])
        pol = compile_corpus([self._cfg(
            InGroup("auth.identity.sub", "org", rel))],
            members_k=4, ovf_assist=True)
        ops = entry_points(policy=pol)[0]["operands"]
        assert "rel_rows" in ops
        assert "member_ovf" in ops  # ovf_assist lane

    def test_no_snapshot_is_empty(self):
        assert entry_points() == []

    def test_auto_lane_decision_rides_the_audit_surface(self):
        """ISSUE 18 satellite: the `--kernel-lane auto` resolution is
        recorded on the kernel-dispatch entries of /debug/vars
        kernel_cost.entry_points — as a FIELD, never a phantom entry
        (the entry list and operand lanes above are a pinned surface)."""
        from authorino_tpu.ops import pattern_eval as pe

        pol = compile_corpus([self._cfg(
            Pattern("m", Operator.EQ, "GET"))],
            members_k=4, ovf_assist=False)
        pe.auto_lane()  # resolve against this process's visible devices
        ep = entry_points(policy=pol)
        assert [e["entry"] for e in ep] == ["eval_bitpacked", "eval_fused",
                                            "fused_kernel"]
        dec = [e for e in ep if e["entry"] == "fused_kernel"][0][
            "kernel_lane_auto"]
        assert dec["requested"] == "auto"
        assert dec["lane"] == pe.last_auto_decision()["lane"]
        assert dec["devices"] >= 1 and dec["platforms"]
        # eval-stage entries never carry it: auto arms the DISPATCH lane
        assert "kernel_lane_auto" not in ep[0]


# ---------------------------------------------------------------------------
# modeled-cost regression gate: >=2x per-row jump between generations ->
# cost-regression anomaly on the flight recorder (advisory, never blocks)
# ---------------------------------------------------------------------------

class TestCostRegression:
    @staticmethod
    def _model(flops_per_row):
        def fake(*, policy=None, params=None, sharded=None, pad=16):
            return {"eval_bitpacked": {
                "entry": "eval_bitpacked", "pad": pad, "eff": 0,
                "flops": flops_per_row[0] * pad,
                "bytes_accessed": 100.0 * pad,
                "flops_per_row": flops_per_row[0],
                "bytes_per_row": 100.0,
            }}
        return fake

    def test_regression_records_anomaly(self, tmp_path):
        frec = FlightRecorder(capacity=32, dump_dir=str(tmp_path),
                              min_dump_interval_s=0.0)
        cm = CostModel("engine")
        f = [1000.0]
        cm._model_entries = self._model(f)
        rec1 = cm.analyze(1, recorder=frec)
        assert rec1["regressions"] == []

        f[0] = 2000.0  # exactly the 2x gate
        rec2 = cm.analyze(2, recorder=frec)
        assert len(rec2["regressions"]) == 1
        r = rec2["regressions"][0]
        assert r["entry"] == "eval_bitpacked"
        assert r["axis"] == "flops_per_row"
        assert r["ratio"] == 2.0
        assert r["previous_generation"] == 1

        tail = frec.to_json()["tail"]
        hits = [e for e in tail if e["kind"] == "cost-regression"]
        assert len(hits) == 1
        assert hits[0]["lane"] == "engine"
        assert hits[0]["detail"]["generation"] == 2

        js = cm.to_json()
        assert js["regressions_seen"] == 1
        assert js["last_regression"]["entry"] == "eval_bitpacked"

    def test_below_threshold_is_silent(self, tmp_path):
        frec = FlightRecorder(capacity=32, dump_dir=str(tmp_path),
                              min_dump_interval_s=0.0)
        cm = CostModel("engine")
        f = [1000.0]
        cm._model_entries = self._model(f)
        cm.analyze(1, recorder=frec)
        f[0] = 1999.0  # 1.999x: under the gate
        rec2 = cm.analyze(2, recorder=frec)
        assert rec2["regressions"] == []
        assert not [e for e in frec.to_json()["tail"]
                    if e["kind"] == "cost-regression"]

    def test_same_generation_analyzed_once(self):
        cm = CostModel("engine")
        f = [1000.0]
        cm._model_entries = self._model(f)
        rec1 = cm.analyze(5)
        f[0] = 9000.0  # canary promote re-installs generation 5
        rec2 = cm.analyze(5)
        assert rec2 is rec1
        assert cm.to_json()["generations_analyzed"] == 1

    def test_fingerprint_shapes(self):
        fp = params_fingerprint({"a": np.zeros((2, 3), np.int16),
                                 "b": None})
        assert isinstance(fp, tuple) and fp
        assert fp == params_fingerprint({"a": np.ones((2, 3), np.int16),
                                         "b": None})
        assert fp != params_fingerprint({"a": np.zeros((2, 4), np.int16),
                                         "b": None})


# ---------------------------------------------------------------------------
# /debug/profile smoke (armed): 200 + trace dir on disk; bad seconds 400
# ---------------------------------------------------------------------------

class TestDebugProfile:
    def test_profile_smoke_and_validation(self):
        from aiohttp.test_utils import TestClient, TestServer

        from authorino_tpu.service.http_server import build_app

        engine = build_engine()

        async def body():
            client = TestClient(TestServer(
                build_app(engine, enable_profile=True)))
            await client.start_server()
            try:
                resp = await client.get("/debug/profile?seconds=0.1")
                ok = resp.status, await resp.json()
                bad = (await client.get(
                    "/debug/profile?seconds=abc")).status
                nan = (await client.get(
                    "/debug/profile?seconds=nan")).status
                return ok, bad, nan
            finally:
                await client.close()

        (status, js), bad, nan = run(body())
        assert status == 200
        assert js["seconds"] == 0.1
        assert os.path.isdir(js["trace_dir"])
        assert bad == 400 and nan == 400
