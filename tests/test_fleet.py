"""Fleet serving plane (ISSUE 18): N replicas as one engine.

The properties that make a replica set one system: rendezvous routing
moves only the departed replica's keys on membership change; router
outcomes (unhealthy / spillover / load-shift) are health- and deadline-
driven, never random; fold deltas replay through the GLOBAL canary guard
so a poison canaried on one replica breaches on fleet evidence and rolls
the whole fleet back via the manifest; the global containment inequality
fires on fleet-wide tenant share when every per-replica share is
individually clean; and a cold replica joining mid-flood inherits the
leader's verdict-cache hot set bit-exactly — or refuses it when the
interner content doesn't match.

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports); JAX_PLATFORMS=cpu."""

from __future__ import annotations

import asyncio
import json
import os
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.expressions import Operator, Pattern
from authorino_tpu.fleet import (
    FleetAggregator,
    FleetHarness,
    FleetRouter,
    GlobalContainment,
    in_fleet_cohort,
    routing_key,
)
from authorino_tpu.fleet import warmjoin
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.change_safety import GuardThresholds
from authorino_tpu.snapshots.distribution import (
    SnapshotPublisher,
    load_hotset,
    load_latest,
)
from authorino_tpu.utils.rpc import UNAVAILABLE, CheckAbort


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def org_corpus(orgs):
    """name -> org constant; each config allows exactly that org (so a
    constant typo is a CONSTANT-DENY poison — the verdict actually
    flips, unlike structural mutations rescued by a sibling branch)."""
    return [ConfigRules(name=n,
                        evaluators=[(None, Pattern("auth.identity.org",
                                                   Operator.EQ, org))])
            for n, org in orgs.items()]


def entries_of(cfgs):
    return [EngineEntry(id=c.name, hosts=[c.name], runtime=None, rules=c)
            for c in cfgs]


def build_engine(cfgs=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("verdict_cache_size", 4096)
    kw.setdefault("lane_select", False)
    # leaders must certify what they publish: replicas reject
    # uncertified snapshots at admission (from_published)
    kw.setdefault("strict_verify", True)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    if cfgs is not None:
        engine.apply_snapshot(entries_of(cfgs))
    return engine


def cdoc(j, org):
    return {"request": {"host": f"h{j}", "path": f"/p{j}", "method": "GET"},
            "auth": {"identity": {"org": org}}}


V1 = {f"c{i}": f"org-{i}" for i in range(6)}

# low-volume thresholds for deterministic tier-1 canary tests (the
# defaults need hundreds of requests per cohort)
TH = GuardThresholds(min_requests=8, min_config_requests=4,
                     min_config_allows=2, min_tenant_attempts=8)


def static_health(**over):
    h = {"ready": True, "draining": False, "breaker_open": False,
         "overloaded": False, "queue_depth": 0, "predicted_wait_s": 0.0}
    h.update(over)
    return h


# ---------------------------------------------------------------------------
# router: rendezvous placement + hybrid outcomes
# ---------------------------------------------------------------------------


def test_routing_key_stable_and_distinct():
    a = routing_key("c1", cdoc(1, "org-1"))
    assert a == routing_key("c1", cdoc(1, "org-1"))
    assert a != routing_key("c2", cdoc(1, "org-1"))
    assert a != routing_key("c1", cdoc(2, "org-1"))


def test_rendezvous_moves_only_departed_replicas_keys():
    """The consistent-hash property: removing one replica reassigns
    exactly the keys that lived on it — every other key keeps its
    placement (cache locality survives membership churn)."""
    router = FleetRouter()
    for name in ("ra", "rb", "rc", "rd"):
        router.add_replica(name, static_health)
    keys = [routing_key(f"c{i % 7}", cdoc(i, f"org-{i % 7}"))
            for i in range(300)]
    before = {k: router.route(k)[0] for k in keys}
    router.remove_replica("rb")
    after = {k: router.route(k)[0] for k in keys}
    for k in keys:
        if before[k] != "rb":
            assert after[k] == before[k]
        else:
            assert after[k] != "rb"
    moved = sum(1 for k in keys if before[k] == "rb")
    assert 0 < moved < len(keys) / 2  # ~1/4 of the keyspace, never more


def test_router_unhealthy_routes_to_best_routable():
    router = FleetRouter()
    key = routing_key("c1", cdoc(1, "org-1"))
    router.add_replica("ra", static_health)
    router.add_replica("rb", static_health)
    primary = router.route(key)[0]
    other = "rb" if primary == "ra" else "ra"
    router.remove_replica(primary)
    router.add_replica(primary, lambda: static_health(ready=False))
    first, second = router.route(key)
    assert first == other and second is None
    assert router.outcomes.get("unhealthy", 0) >= 1
    # draining counts as unroutable too (the SIGTERM choreography)
    router.remove_replica(primary)
    router.add_replica(primary, lambda: static_health(draining=True))
    assert router.route(key)[0] == other


def test_router_deadline_spillover_and_load_shift():
    router = FleetRouter(load_factor=2.0, min_shift_depth=8)
    key = routing_key("c1", cdoc(1, "org-1"))
    router.add_replica("ra", static_health)
    router.add_replica("rb", static_health)
    primary = router.route(key)[0]
    backup = "rb" if primary == "ra" else "ra"
    # spillover: the first choice's predicted wait eats the budget
    router.remove_replica(primary)
    router.add_replica(primary, lambda: static_health(predicted_wait_s=0.5))
    first, second = router.route(key, deadline_budget_s=0.1)
    assert (first, second) == (backup, primary)
    assert router.outcomes.get("spillover", 0) == 1
    # without a deadline the same health routes primary (affinity wins)
    assert router.route(key)[0] == primary
    # load-shift: backlog ratio past load_factor beyond min_shift_depth
    router.remove_replica(primary)
    router.add_replica(primary, lambda: static_health(queue_depth=64))
    first, second = router.route(key)
    assert (first, second) == (backup, primary)
    assert router.outcomes.get("load-shift", 0) == 1


def test_router_exclude_is_policy_not_unhealthy():
    router = FleetRouter()
    key = routing_key("c1", cdoc(1, "org-1"))
    router.add_replica("ra", static_health)
    router.add_replica("rb", static_health)
    primary = router.route(key)[0]
    backup = "rb" if primary == "ra" else "ra"
    first, second = router.route(key, exclude=primary)
    assert (first, second) == (backup, None)
    # exclusion is caller policy: never counted as an unhealthy outcome
    assert router.outcomes.get("unhealthy", 0) == 0


def test_router_no_replica_and_health_probe_exception():
    router = FleetRouter()
    key = routing_key("c1", cdoc(1, "org-1"))
    assert router.route(key) == (None, None)
    assert router.outcomes.get("no-replica") == 1

    def bad_probe():
        raise RuntimeError("probe died")

    router.add_replica("ra", bad_probe)  # a raising probe is a down replica
    assert router.route(key) == (None, None)


def test_in_fleet_cohort_fraction_and_determinism():
    keys = [routing_key(f"c{i % 5}", cdoc(i, f"org-{i % 5}"))
            for i in range(1000)]
    assert not any(in_fleet_cohort(k, 0.0) for k in keys)
    assert all(in_fleet_cohort(k, 1.0) for k in keys)
    half = [in_fleet_cohort(k, 0.5) for k in keys]
    assert half == [in_fleet_cohort(k, 0.5) for k in keys]
    assert 0.35 < sum(half) / len(half) < 0.65


# ---------------------------------------------------------------------------
# aggregator: fold deltas -> global guard; global containment
# ---------------------------------------------------------------------------


def fold(requests=0, denies=0, errors=0, slo_total=0, slo_bad=0,
         tenants=None, wait_hot=False):
    return {"errors": errors, "slo_total": slo_total, "slo_bad": slo_bad,
            "tenants": tenants or {}, "tenant_rejects": {},
            "wait_hot": wait_hot,
            "admission_state": "OVERLOADED" if wait_hot else "HEALTHY"}


def tfold(**tenants):
    """tenant -> (requests, denies, rate)."""
    return {n: {"requests": r, "denies": d, "slo_bad": 0, "rate": rate}
            for n, (r, d, rate) in tenants.items()}


def test_aggregator_deltas_feed_global_guard_cohorts():
    """The canary replica's fold deltas land on the canary side, the
    rest of the fleet's on the baseline side; a poison deny spike local
    to the canary breaches on GLOBAL evidence."""
    agg = FleetAggregator()
    agg.ingest("rc", fold(tenants=tfold(c3=(10, 0, 1.0))))
    agg.ingest("rb", fold(tenants=tfold(c3=(10, 0, 1.0))))
    agg.arm_guard("rc", changed={"c3"}, thresholds=TH)
    # canary replica: 16 more c3 requests, ALL denied; fleet: clean
    agg.ingest("rc", fold(tenants=tfold(c3=(26, 16, 1.0))))
    agg.ingest("rb", fold(tenants=tfold(c3=(40, 0, 1.0))))
    b = agg.guard_breach()
    assert b is not None and "config-deny-rate" in b["guards"]
    assert "c3" in b["suspects"]
    assert agg.breaches and agg.breaches[0] is b


def test_aggregator_arm_rebaselines_and_clamps_counter_resets():
    agg = FleetAggregator()
    agg.ingest("ra", fold(tenants=tfold(c0=(500, 500, 1.0))))
    agg.arm_guard("ra", thresholds=TH)
    # identical fold again: zero delta, nothing leaks into the cohort
    agg.ingest("ra", fold(tenants=tfold(c0=(500, 500, 1.0))))
    assert agg.guard._canary.total == 0
    # a restarted replica reports SMALLER cumulatives: clamp, not negative
    agg.ingest("ra", fold(tenants=tfold(c0=(5, 2, 1.0))))
    assert agg.guard._canary.total == 0
    assert agg.guard._canary.denies == 0
    assert agg.guard_breach() is None


def test_global_containment_fires_when_every_local_share_is_clean():
    """The acceptance property: consistent-hash concentration makes a
    fleet-hot tenant look locally entitled on EVERY replica (few tenants
    share its replicas, so local entitlement is large); only the global
    fold sees the outsized fleet share."""
    local = {
        "ra": {"hot": 10.0, "t1": 1.0, "t2": 1.0},
        "rb": {"t3": 1.0, "t4": 1.0, "t5": 1.0},
        "rc": {"t6": 1.0, "t7": 1.0, "t8": 1.0},
    }
    t0 = time.monotonic()
    # per-replica containment (the pre-fleet check) clears every replica
    for rates in local.values():
        checker = GlobalContainment()
        assert checker.check(rates, pressure=True, now=t0) == {}
        assert checker.check(rates, pressure=True, now=t0 + 0.6) == {}
    # the global fold: 9 active tenants, hot's share 10/18 > 3x entitled
    agg = FleetAggregator()
    for name, rates in local.items():
        agg.ingest(name, fold(
            tenants=tfold(**{t: (100, 0, r) for t, r in rates.items()}),
            wait_hot=(name == "ra")))
    assert agg.containment_check(now=t0) == {}        # sustain arming
    suspects = agg.containment_check(now=t0 + 0.6)
    assert "hot" in suspects and suspects["hot"]["ratio"] > 3.0
    # forgetting the hot replica's fold drops the suspicion with it
    agg.forget("ra")
    assert agg.containment_check(now=t0 + 1.2) == {}


def test_global_containment_needs_fleet_pressure():
    agg = FleetAggregator()
    agg.ingest("ra", fold(tenants=tfold(hot=(100, 0, 10.0),
                                        t1=(10, 0, 0.1))))
    t0 = time.monotonic()
    assert agg.containment_check(now=t0) == {}
    assert agg.containment_check(now=t0 + 0.6) == {}  # idle fleet: traffic


# ---------------------------------------------------------------------------
# warm-join: hot-set export/import
# ---------------------------------------------------------------------------


def serve(engine, docs_cfgs):
    async def _go():
        return await asyncio.gather(
            *[engine.submit(dict(d), c) for d, c in docs_cfgs])
    return run(_go())


def leader_with_published(tmp_path):
    leader = build_engine(org_corpus(V1))
    pub = SnapshotPublisher(str(tmp_path))
    pub.publish_from_engine(leader)
    return leader, pub


def test_hotset_roundtrip_imports_and_hits(tmp_path):
    leader, pub = leader_with_published(tmp_path)
    traffic = [(cdoc(j, f"org-{j % 6}"), f"c{j % 6}") for j in range(24)]
    serve(leader, traffic)
    digest = warmjoin.export_hotset(leader, k=64)
    assert digest is not None and len(digest["entries"]) > 0
    pub.publish_hotset(digest)

    joiner = build_engine()
    joiner.apply_published(load_latest(str(tmp_path)))
    imported, skipped = warmjoin.import_hotset(
        joiner, load_hotset(str(tmp_path)))
    assert imported == len(digest["entries"]) and skipped == 0
    # a warm-imported entry serves as a HIT: zero new misses on replay
    cache = joiner._verdict_cache
    h0, m0 = cache.hits, cache.misses
    (rule, skipped_col), = serve(joiner, traffic[:1])
    assert cache.hits > h0 and cache.misses == m0
    # ...and the verdict is bit-exact vs the leader serving the same doc
    (lrule, lskip), = serve(leader, traffic[:1])
    np.testing.assert_array_equal(rule, lrule)
    np.testing.assert_array_equal(skipped_col, lskip)


def test_hotset_refuses_interner_and_version_mismatch(tmp_path):
    leader, pub = leader_with_published(tmp_path)
    serve(leader, [(cdoc(j, f"org-{j % 6}"), f"c{j % 6}")
                   for j in range(12)])
    digest = warmjoin.export_hotset(leader, k=64)
    joiner = build_engine()
    joiner.apply_published(load_latest(str(tmp_path)))
    # wrong interner content: every entry refused (the row-key byte
    # layout is interner-relative — importing would poison verdicts)
    assert warmjoin.import_hotset(
        joiner, dict(digest, interner="0" * 16)) == (0, 0)
    assert warmjoin.import_hotset(joiner, dict(digest, version=99)) == (0, 0)
    assert warmjoin.import_hotset(joiner, None) == (0, 0)


# ---------------------------------------------------------------------------
# harness: join/leave/crash choreography, canary, bit-exactness
# ---------------------------------------------------------------------------


def make_fleet(tmp_path, n_replicas=2, warm=False):
    h = FleetHarness(str(tmp_path), build_engine, poll_s=0.05)
    h.add_leader(entries=entries_of(org_corpus(V1)))
    for i in range(1, n_replicas + 1):
        h.add_replica(f"r{i}", warm_join=warm)
    return h


def fleet_traffic(h, n, start=0, collect=False):
    """Open-loop round-robin over the corpus; returns (ok, typed, outs)."""
    ok = typed = 0
    outs = []
    for j in range(start, start + n):
        cfg = f"c{j % 6}"
        try:
            r, s = h.check(cfg, cdoc(j, V1[cfg]))
        except CheckAbort:
            typed += 1
        else:
            ok += 1
            if collect:
                outs.append((cfg, j, bool(r[0])))
    return ok, typed, outs


def test_fleet_serves_and_crash_degrades_typed_only(tmp_path):
    h = make_fleet(tmp_path, n_replicas=2)
    try:
        ok, typed, outs = fleet_traffic(h, 60, collect=True)
        assert (ok, typed) == (60, 0)
        assert all(allowed for _, _, allowed in outs)
        h.crash_replica("r2")
        # only TYPED rejections may surface; anything raw fails the test
        ok, typed, _ = fleet_traffic(h, 60, start=60)
        assert ok + typed == 60 and ok > 0
        # the crashed replica's health collapses out of the routable set
        assert "r2" not in {h.router.route(
            routing_key(f"c{i}", cdoc(i, "x")))[0] for i in range(20)}
        # graceful leave: drain completes bounded, fold forgotten
        assert h.remove_replica("r1") is True
        ok2, typed2, _ = fleet_traffic(h, 30, start=120)
        assert ok2 == 30  # leader alone still serves everything
    finally:
        h.shutdown()


def test_fleet_no_routable_replica_is_typed(tmp_path):
    h = FleetHarness(str(tmp_path), build_engine)
    with pytest.raises(CheckAbort) as ei:
        h.check("c0", cdoc(1, "org-0"))
    assert ei.value.code == UNAVAILABLE


def test_fleet_warm_join_beats_cold(tmp_path):
    h = make_fleet(tmp_path, n_replicas=0)
    try:
        trace = [(cdoc(j, f"org-{j % 6}"), f"c{j % 6}") for j in range(30)]
        serve(h.leader.engine, trace)
        assert h.publish_hotset(k=256) is True
        cold = h.add_replica("cold", warm_join=False)
        warm = h.add_replica("warm", warm_join=True)
        assert warm.warm_imported > 0 and cold.warm_imported == 0
        for rep in (cold, warm):
            for d, c in trace:
                rep.check(c, dict(d)).result(timeout=10)
        cold_hits = cold.engine._verdict_cache.hits
        warm_hits = warm.engine._verdict_cache.hits
        assert warm_hits > cold_hits  # the whole point of the hot set
    finally:
        h.shutdown()


def test_fleet_verdicts_bit_exact_across_replicas(tmp_path):
    """Every replica — and a cold independent compile of the same corpus
    (the host-side oracle) — serves bit-identical verdict columns."""
    h = make_fleet(tmp_path, n_replicas=2, warm=True)
    oracle = build_engine(org_corpus(V1))
    try:
        trace = [(cdoc(j, f"org-{j % 9}" if j % 3 else "org-elsewhere"),
                  f"c{j % 6}") for j in range(40)]
        want = serve(oracle, trace)
        for rep in h.replicas.values():
            got = [rep.check(c, dict(d)).result(timeout=10)
                   for d, c in trace]
            for (wr, ws), (gr, gs) in zip(want, got):
                np.testing.assert_array_equal(wr, gr)
                np.testing.assert_array_equal(ws, gs)
    finally:
        h.shutdown()


def test_fleet_canary_breach_rolls_back_fleet_wide(tmp_path):
    """The tentpole end to end: poison canaried on ONE replica, judged on
    fleet folds, rolled back everywhere via the manifest — late joiners
    included."""
    h = make_fleet(tmp_path, n_replicas=2)
    try:
        fleet_traffic(h, 60)
        h.publish_folds()
        poison = dict(V1, c3="org-NEVER")
        h.start_canary("r1", entries_of(org_corpus(poison)),
                       changed={"c3"}, thresholds=TH, fraction=0.5)
        gen_canary = h.replicas["r1"].engine.generation
        breach = None
        for round_ in range(8):
            fleet_traffic(h, 60, start=1000 * (round_ + 1))
            h.publish_folds()
            breach = h.canary_tick()
            if breach:
                break
        assert breach is not None, h.aggregator.to_json()
        assert "config-deny-rate" in breach["breach"]["guards"]
        assert "c3" in breach["breach"]["suspects"]
        assert breach["detection_s"] > 0 and breach["mttr_s"] >= 0
        # the canary re-adopted baseline: the poison verdict is gone
        r, _ = h.replicas["r1"].check(
            "c3", cdoc(7777, "org-3")).result(timeout=10)
        assert bool(r[0])
        assert h.replicas["r1"].engine.generation > gen_canary
        # the manifest carries the rollback record fleet-wide
        man = json.load(open(os.path.join(str(tmp_path), "MANIFEST.json")))
        assert man["rollback"]["reason"] == "fleet-guard-breach"
        assert man["rollback"]["canary_replica"] == "r1"
        assert man["quarantine"]["configs"] == ["c3"]
        # a replica joining AFTER the breach converges on baseline
        late = h.add_replica("late", warm_join=False)
        r, _ = late.check("c3", cdoc(7778, "org-3")).result(timeout=10)
        assert bool(r[0])
        # guard disarmed: cohort pinning is over, ticks return nothing
        assert h.canary_tick() is None
    finally:
        h.shutdown()


def test_fleet_canary_cohort_pins_traffic(tmp_path):
    """While armed, the cohort slice lands on the canary replica and
    NOTHING else does — the split that makes the fold cohorts
    comparable."""
    h = make_fleet(tmp_path, n_replicas=2)
    try:
        h.start_canary("r1", entries_of(org_corpus(V1)), changed=set(),
                       thresholds=TH, fraction=0.5)
        canary_engine = h.replicas["r1"].engine
        before = canary_engine.tenancy.stats.total_requests
        in_cohort = out_cohort = 0
        for j in range(80):
            cfg = f"c{j % 6}"
            d = cdoc(j, V1[cfg])
            if in_fleet_cohort(routing_key(cfg, d), 0.5):
                in_cohort += 1
            else:
                out_cohort += 1
            h.check(cfg, d)
        assert in_cohort > 0 and out_cohort > 0
        served = canary_engine.tenancy.stats.total_requests - before
        assert served == in_cohort  # the cohort, the whole cohort, and
    finally:                        # nothing but the cohort
        h.shutdown()


def test_engine_fleet_fold_shape():
    """The fold contract the aggregator and process replicas share."""
    engine = build_engine(org_corpus({"ca": "org-a"}))
    serve(engine, [(cdoc(j, "org-a" if j % 2 else "org-x"), "ca")
                   for j in range(8)])
    h = engine.fleet_health()
    assert h["ready"] is True and h["draining"] is False
    assert h["breaker_open"] is False and "predicted_wait_s" in h
    f = engine.fleet_fold()
    assert f["tenants"]["ca"]["requests"] == 8
    assert f["tenants"]["ca"]["denies"] == 4  # org-x rows deny
    assert f["admission_state"] in ("HEALTHY", "OVERLOADED")
    engine.drain(5.0)
    assert engine.fleet_health()["ready"] is False


# ---------------------------------------------------------------------------
# code lint: the fleet plane rides the unbounded-wait gate (ISSUE 18)
# ---------------------------------------------------------------------------


def test_code_lint_flags_unbounded_waits_on_fleet_paths():
    """router/fleet/replica/join functions run exactly when a peer
    replica may be dead or wedged — a timeoutless wait there stalls the
    whole fleet's routing, not one process."""
    from authorino_tpu.analysis.code_lint import lint_source

    src = (
        "def router_pick(self):\n"
        "    self._evt.wait()\n"
        "def fleet_tick(self):\n"
        "    self._thread.join()\n"
        "def replica_sync(self):\n"
        "    self._evt.wait()\n"
        "async def warm_join(self):\n"
        "    await self._done.wait()\n"
        "def replica_sync_bounded(self):\n"
        "    self._evt.wait(0.5)\n"   # bounded: clean
        "def rejoin_paths(self):\n"
        "    os.path.join('a', 'b')\n"  # args present: not waitish
    )
    found = lint_source(src, "planted.py")
    assert [f.kind for f in found] == ["unbounded-wait"] * 4
    assert [f.location for f in found] == [
        "planted.py:2", "planted.py:4", "planted.py:6", "planted.py:8"]
