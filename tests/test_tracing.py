"""W3C trace-context propagation tests (ref: pkg/trace — spans propagate
traceparent + x-request-id into outbound evaluator calls)."""

import asyncio

from aiohttp import web
from aiohttp.test_utils import TestServer

from authorino_tpu.authjson import CheckRequestModel, HttpRequestAttributes, JSONValue
from authorino_tpu.evaluators import IdentityConfig, MetadataConfig, RuntimeAuthConfig
from authorino_tpu.evaluators.identity import Noop
from authorino_tpu.evaluators.metadata import GenericHttp
from authorino_tpu.pipeline import AuthPipeline
from authorino_tpu.utils.tracing import RequestSpan


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_traceparent_parse_and_mint():
    parent = "00-0123456789abcdef0123456789abcdef-00f067aa0ba902b7-01"
    span = RequestSpan.from_headers({"traceparent": parent}, request_id="req-1")
    assert span.trace_id == "0123456789abcdef0123456789abcdef"  # trace id propagates
    assert span.span_id != "00f067aa0ba902b7"  # new span id per hop
    out = span.inject({})
    assert out["traceparent"].startswith("00-0123456789abcdef0123456789abcdef-")
    assert out["x-request-id"] == "req-1"

    minted = RequestSpan.from_headers({}, request_id="req-2")
    assert len(minted.trace_id) == 32 and len(minted.span_id) == 16


def test_outbound_propagation_through_generic_http():
    async def body():
        seen = {}

        async def meta(request):
            seen["traceparent"] = request.headers.get("traceparent")
            seen["x-request-id"] = request.headers.get("x-request-id")
            return web.json_response({"ok": True})

        app = web.Application()
        app.router.add_get("/meta", meta)
        server = TestServer(app)
        await server.start_server()
        try:
            base = str(server.make_url("")).rstrip("/")
            cfg = RuntimeAuthConfig(
                identity=[IdentityConfig("anon", Noop())],
                metadata=[MetadataConfig("m", GenericHttp(endpoint=JSONValue(static=base + "/meta")))],
            )
            req = CheckRequestModel(
                http=HttpRequestAttributes(
                    method="GET", path="/", host="svc.example.com",
                    headers={"traceparent": "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"},
                )
            )
            span = RequestSpan.from_headers(req.http.headers, "rid-9")
            pipeline = AuthPipeline(req, cfg, span=span)
            result = await pipeline.evaluate()
            assert result.success()
            assert seen["traceparent"].startswith("00-" + "ab" * 16 + "-")
            assert seen["x-request-id"] == "rid-9"
        finally:
            await server.close()
            from authorino_tpu.utils.http import close_sessions

            await close_sessions()

    run(body())
