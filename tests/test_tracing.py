"""W3C trace-context propagation tests (ref: pkg/trace — spans propagate
traceparent + x-request-id into outbound evaluator calls)."""

import asyncio

from aiohttp import web
from aiohttp.test_utils import TestServer

from authorino_tpu.authjson import CheckRequestModel, HttpRequestAttributes, JSONValue
from authorino_tpu.evaluators import IdentityConfig, MetadataConfig, RuntimeAuthConfig
from authorino_tpu.evaluators.identity import Noop
from authorino_tpu.evaluators.metadata import GenericHttp
from authorino_tpu.pipeline import AuthPipeline
from authorino_tpu.utils.tracing import RequestSpan


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_traceparent_parse_and_mint():
    parent = "00-0123456789abcdef0123456789abcdef-00f067aa0ba902b7-01"
    span = RequestSpan.from_headers({"traceparent": parent}, request_id="req-1")
    assert span.trace_id == "0123456789abcdef0123456789abcdef"  # trace id propagates
    assert span.span_id != "00f067aa0ba902b7"  # new span id per hop
    out = span.inject({})
    assert out["traceparent"].startswith("00-0123456789abcdef0123456789abcdef-")
    assert out["x-request-id"] == "req-1"

    minted = RequestSpan.from_headers({}, request_id="req-2")
    assert len(minted.trace_id) == 32 and len(minted.span_id) == 16


def test_outbound_propagation_through_generic_http():
    async def body():
        seen = {}

        async def meta(request):
            seen["traceparent"] = request.headers.get("traceparent")
            seen["x-request-id"] = request.headers.get("x-request-id")
            return web.json_response({"ok": True})

        app = web.Application()
        app.router.add_get("/meta", meta)
        server = TestServer(app)
        await server.start_server()
        try:
            base = str(server.make_url("")).rstrip("/")
            cfg = RuntimeAuthConfig(
                identity=[IdentityConfig("anon", Noop())],
                metadata=[MetadataConfig("m", GenericHttp(endpoint=JSONValue(static=base + "/meta")))],
            )
            req = CheckRequestModel(
                http=HttpRequestAttributes(
                    method="GET", path="/", host="svc.example.com",
                    headers={"traceparent": "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"},
                )
            )
            span = RequestSpan.from_headers(req.http.headers, "rid-9")
            pipeline = AuthPipeline(req, cfg, span=span)
            result = await pipeline.evaluate()
            assert result.success()
            assert seen["traceparent"].startswith("00-" + "ab" * 16 + "-")
            assert seen["x-request-id"] == "rid-9"
        finally:
            await server.close()
            from authorino_tpu.utils.http import close_sessions

            await close_sessions()

    run(body())


class TestNativeOtlpExport:
    def test_spans_export_to_fake_collector(self):
        """The built-in OTLP/HTTP JSON exporter (no OTel SDK needed) must
        deliver finished request spans to a collector: hex ids, request-id
        attribute, error status, service.name resource, and the basic-auth
        header derived from the endpoint URL userinfo
        (ref pkg/trace/exporter.go:26-117)."""
        from aiohttp import web
        from aiohttp.test_utils import TestServer

        from authorino_tpu.utils import tracing

        async def body():
            got = {}

            async def v1_traces(request):
                got["auth"] = request.headers.get("authorization", "")
                got["payload"] = await request.json()
                return web.json_response({})

            app = web.Application()
            app.router.add_post("/v1/traces", v1_traces)
            server = TestServer(app)
            await server.start_server()
            try:
                base = str(server.make_url("")).rstrip("/")
                url = base.replace("http://", "http://u:pw@", 1)
                assert tracing.setup_tracing(url) is True
                assert tracing._native_exporter is not None
                tracing._native_exporter.flush_interval_s = 0.01

                tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
                span = tracing.RequestSpan.from_headers({"traceparent": tp}, "rid-1")
                span.end()
                span2 = tracing.RequestSpan.from_headers({}, "rid-2")
                span2.end(error="Unauthorized")
                await tracing._native_exporter.flush()

                import base64

                assert got["auth"] == "Basic " + base64.b64encode(b"u:pw").decode()
                rs = got["payload"]["resourceSpans"][0]
                res_attrs = {a["key"]: a["value"]["stringValue"]
                             for a in rs["resource"]["attributes"]}
                assert res_attrs["service.name"] == "authorino-tpu"
                spans = rs["scopeSpans"][0]["spans"]
                assert len(spans) == 2
                by_rid = {s["attributes"][0]["value"]["stringValue"]: s for s in spans}
                assert by_rid["rid-1"]["traceId"] == "ab" * 16  # propagated
                assert len(by_rid["rid-2"]["traceId"]) == 32    # minted hex
                assert by_rid["rid-2"]["status"] == {"code": 2, "message": "Unauthorized"}
                assert by_rid["rid-1"]["status"] == {}
                assert int(spans[0]["endTimeUnixNano"]) >= int(spans[0]["startTimeUnixNano"])
            finally:
                tracing._native_exporter = None
                await server.close()
                from authorino_tpu.utils.http import close_sessions

                await close_sessions()

        run(body())

    def test_grpc_endpoint_without_sdk_stays_propagation_only(self):
        from authorino_tpu.utils import tracing

        assert tracing.setup_tracing("rpc://collector:4317") is False
        assert tracing._native_exporter is None


class TestNativeFrontendTracing:
    def test_active_tracing_samples_spans_and_keeps_fast_lane(self):
        """With span export active, the native frontend head-samples:
        1-in-N requests take the Python pipeline and produce exported spans
        with the propagated trace id, the rest keep serving natively —
        observability must not cost the fast lane wholesale (VERDICT r3
        weak #2)."""
        import grpc

        from aiohttp import web
        from aiohttp.test_utils import TestServer

        from authorino_tpu import protos
        from authorino_tpu.compiler import ConfigRules
        from authorino_tpu.expressions import Operator, Pattern
        from authorino_tpu.evaluators import (
            AuthorizationConfig, IdentityConfig, RuntimeAuthConfig)
        from authorino_tpu.evaluators.authorization import PatternMatching
        from authorino_tpu.evaluators.identity import Noop
        from authorino_tpu.runtime import EngineEntry, PolicyEngine
        from authorino_tpu.runtime.native_frontend import NativeFrontend
        from authorino_tpu.utils import tracing

        pb = protos.external_auth_pb2

        async def setup_collector():
            got = []

            async def v1_traces(request):
                got.append(await request.json())
                return web.json_response({})

            app = web.Application()
            app.router.add_post("/v1/traces", v1_traces)
            server = TestServer(app)
            await server.start_server()
            return server, got

        async def body():
            server, got = await setup_collector()
            try:
                assert tracing.setup_tracing(str(server.make_url("")).rstrip("/"))
                tracing._native_exporter.flush_interval_s = 0.01

                rule = Pattern("request.method", Operator.EQ, "GET")
                engine = PolicyEngine(max_batch=16, mesh=None)
                cfg_id = "ns/traced"
                pm = PatternMatching(rule, batched_provider=engine.provider_for(cfg_id),
                                     evaluator_slot=0)
                runtime = RuntimeAuthConfig(
                    identity=[IdentityConfig("anon", Noop())],
                    authorization=[AuthorizationConfig("rules", pm)])
                engine.apply_snapshot([EngineEntry(
                    id=cfg_id, hosts=["traced.test"], runtime=runtime,
                    rules=ConfigRules(name=cfg_id, evaluators=[(None, rule)]))])
                fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500,
                                    trace_sample_n=4)
                port = fe.start()
                try:
                    req = pb.CheckRequest()
                    http = req.attributes.request.http
                    http.method = "GET"
                    http.path = "/x"
                    http.host = "traced.test"
                    http.headers["traceparent"] = "00-" + "77" * 16 + "-" + "88" * 8 + "-01"

                    def call():
                        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
                            return ch.unary_unary(
                                "/envoy.service.auth.v3.Authorization/Check",
                                request_serializer=pb.CheckRequest.SerializeToString,
                                response_deserializer=pb.CheckResponse.FromString,
                            )(req, timeout=10)

                    import asyncio as aio

                    # 1st request is the sample (counter starts at 0):
                    # slow lane + exported span with the propagated id
                    resp = await aio.to_thread(call)
                    assert resp.status.code == 0
                    stats = fe.stats()
                    assert stats["fast"] == 0 and stats["slow"] == 1, stats
                    assert stats["trace_sampled"] == 1
                    await tracing._native_exporter.flush()
                    assert got, "no span exported"
                    sp = got[0]["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
                    assert sp["traceId"] == "77" * 16
                    # next 3 of every 4 stay native; the 5th samples again
                    for _ in range(7):
                        resp = await aio.to_thread(call)
                        assert resp.status.code == 0
                    stats = fe.stats()
                    assert stats["trace_sampled"] == 2, stats
                    assert stats["fast"] == 6 and stats["slow"] == 2, stats
                finally:
                    fe.stop()
            finally:
                tracing._native_exporter = None
                await server.close()
                from authorino_tpu.utils.http import close_sessions

                await close_sessions()

        run(body())
