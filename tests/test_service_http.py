"""End-to-end slice: HTTP /check → engine → pipeline → batched TPU verdict
(the minimum end-to-end slice of SURVEY.md §7 step 4, matching baseline
config #1: anonymous identity + one patternMatching rule)."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from authorino_tpu.authjson import JSONProperty, JSONValue
from authorino_tpu.compiler import ConfigRules
from authorino_tpu.evaluators import (
    AuthorizationConfig,
    IdentityConfig,
    ResponseConfig,
    RuntimeAuthConfig,
)
from authorino_tpu.evaluators.authorization import PatternMatching
from authorino_tpu.evaluators.identity import Noop
from authorino_tpu.evaluators.response import DynamicJSON
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.service.http_server import build_app


def build_engine(batched: bool) -> PolicyEngine:
    engine = PolicyEngine(max_batch=8)
    rules = All(
        Pattern("request.headers.x-api-tier", Operator.EQ, "gold"),
        Pattern("request.method", Operator.NEQ, "DELETE"),
    )
    cond = Pattern("request.url_path", Operator.MATCHES, r"^/protected")
    pm = PatternMatching(
        rules,
        batched_provider=engine.provider_for("tenant/talker-api") if batched else None,
        evaluator_slot=0,
    )
    runtime = RuntimeAuthConfig(
        labels={"namespace": "tenant", "name": "talker-api"},
        identity=[IdentityConfig("anon", Noop())],
        authorization=[AuthorizationConfig("tier-check", pm, conditions=None if batched else cond)],
        response=[
            ResponseConfig(
                "x-auth-data",
                DynamicJSON([JSONProperty("tier", JSONValue(pattern="request.headers.x-api-tier"))]),
            )
        ],
    )
    entry = EngineEntry(
        id="tenant/talker-api",
        hosts=["talker-api.example.com", "*.wild.example.com"],
        runtime=runtime,
        rules=ConfigRules(
            name="tenant/talker-api",
            evaluators=[(cond, rules)],
        ),
    )
    engine.apply_snapshot([entry])
    return engine


@pytest.mark.parametrize("batched", [False, True])
def test_check_endpoint_allow_deny(batched):
    # the aiohttp test client always hits the /check route; the simulated
    # original request travels via headers — build explicit scenarios:
    async def call(client, host, tier=None, method="GET"):
        headers = {"Host": host}
        if tier:
            headers["X-Api-Tier"] = tier
        r = await client.request(method, "/check", headers=headers)
        return r

    async def run_all():
        engine = build_engine(batched)
        app = build_app(engine)
        async with TestClient(TestServer(app)) as client:
            # NOTE: the raw-HTTP adapter takes path from the incoming
            # request (/check), so the condition (^/protected) won't match →
            # evaluator skipped → allow. Exercise both gate outcomes via the
            # wildcard host config below and header-only rules.
            r = await call(client, "talker-api.example.com", tier="gold")
            assert r.status == 200
            # skipped condition → no authorization result recorded, allow
            r = await call(client, "talker-api.example.com", tier="bronze")
            assert r.status == 200

            # unknown host → 404 "Service not found" (ref auth.go:270-289)
            r = await call(client, "unknown.example.com", tier="gold")
            assert r.status == 404
            assert r.headers.get("X-Ext-Auth-Reason") == "Service not found"

            # wildcard host match
            r = await call(client, "deep.wild.example.com", tier="gold")
            assert r.status == 200

    asyncio.new_event_loop().run_until_complete(run_all())


@pytest.mark.parametrize("batched", [False, True])
def test_check_condition_matched_rules_enforced(batched):
    """Host-based config where conditions always match: rules are enforced."""

    async def run_all():
        engine = PolicyEngine(max_batch=4)
        rules = All(Pattern("request.headers.x-api-tier", Operator.EQ, "gold"))
        pm = PatternMatching(
            rules,
            batched_provider=engine.provider_for("ns/cfg") if batched else None,
        )
        runtime = RuntimeAuthConfig(
            identity=[IdentityConfig("anon", Noop())],
            authorization=[AuthorizationConfig("tier", pm)],
        )
        engine.apply_snapshot(
            [
                EngineEntry(
                    id="ns/cfg",
                    hosts=["svc.example.com"],
                    runtime=runtime,
                    rules=ConfigRules(name="ns/cfg", evaluators=[(None, rules)]),
                )
            ]
        )
        app = build_app(engine)
        async with TestClient(TestServer(app)) as client:
            r = await client.get(
                "/check", headers={"Host": "svc.example.com", "X-Api-Tier": "gold"}
            )
            assert r.status == 200
            # response-phase header injection is exercised in the other test;
            # here check deny + reason header
            r = await client.get(
                "/check", headers={"Host": "svc.example.com", "X-Api-Tier": "bronze"}
            )
            assert r.status == 403
            assert r.headers.get("X-Ext-Auth-Reason") == "Unauthorized"

            # micro-batching: concurrent requests coalesce into one kernel call
            results = await asyncio.gather(
                *[
                    client.get(
                        "/check",
                        headers={
                            "Host": "svc.example.com",
                            "X-Api-Tier": "gold" if i % 2 == 0 else "bronze",
                        },
                    )
                    for i in range(16)
                ]
            )
            statuses = [r.status for r in results]
            assert statuses == [200 if i % 2 == 0 else 403 for i in range(16)]

    asyncio.new_event_loop().run_until_complete(run_all())


def test_admission_review_mode():
    async def run_all():
        engine = PolicyEngine()
        rules = All(Pattern("request.body.@fromstr.request.operation", Operator.NEQ, "DELETE"))
        runtime = RuntimeAuthConfig(
            identity=[IdentityConfig("anon", Noop())],
            authorization=[AuthorizationConfig("no-delete", PatternMatching(rules))],
        )
        engine.apply_snapshot(
            [EngineEntry(id="ns/w", hosts=["webhook.example.com"], runtime=runtime)]
        )
        app = build_app(engine)
        async with TestClient(TestServer(app)) as client:
            review = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {"uid": "abc-123", "operation": "CREATE"},
            }
            r = await client.post(
                "/check", headers={"Host": "webhook.example.com"}, json=review
            )
            assert r.status == 200
            payload = await r.json()
            assert payload["kind"] == "AdmissionReview"
            assert payload["response"] == {"uid": "abc-123", "allowed": True}

            review["request"]["operation"] = "DELETE"
            r = await client.post(
                "/check", headers={"Host": "webhook.example.com"}, json=review
            )
            payload = await r.json()
            assert payload["response"]["allowed"] is False
            assert "status" in payload["response"]

    asyncio.new_event_loop().run_until_complete(run_all())


def test_engine_snapshot_swap_under_load():
    """Reconcile-time swap must not break in-flight serving."""

    async def run_all():
        engine = PolicyEngine(max_batch=4)

        def snapshot(tier):
            rules = All(Pattern("request.headers.x-api-tier", Operator.EQ, tier))
            runtime = RuntimeAuthConfig(
                identity=[IdentityConfig("anon", Noop())],
                authorization=[
                    AuthorizationConfig(
                        "tier", PatternMatching(rules, batched_provider=engine.provider_for("ns/cfg"))
                    )
                ],
            )
            return [
                EngineEntry(
                    id="ns/cfg",
                    hosts=["svc.example.com"],
                    runtime=runtime,
                    rules=ConfigRules(name="ns/cfg", evaluators=[(None, rules)]),
                )
            ]

        engine.apply_snapshot(snapshot("gold"))
        app = build_app(engine)
        async with TestClient(TestServer(app)) as client:

            async def hammer(n):
                out = []
                for _ in range(n):
                    r = await client.get(
                        "/check", headers={"Host": "svc.example.com", "X-Api-Tier": "silver"}
                    )
                    out.append(r.status)
                return out

            first = await hammer(3)
            assert first == [403, 403, 403]
            engine.apply_snapshot(snapshot("silver"))  # rule flip mid-serving
            second = await hammer(3)
            assert second == [200, 200, 200]

    asyncio.new_event_loop().run_until_complete(run_all())
