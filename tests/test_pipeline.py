"""5-phase pipeline semantics tests — the contract defined by the
reference's pkg/service/auth_pipeline_test.go (short-circuits, priorities,
conditions, denyWith, challenge headers)."""

import asyncio
import json

import pytest

from authorino_tpu.authjson import CheckRequestModel, HttpRequestAttributes, JSONValue, JSONProperty
from authorino_tpu.evaluators import (
    AuthorizationConfig,
    AuthCredentials,
    DenyWith,
    DenyWithValues,
    EvaluationError,
    IdentityConfig,
    IdentityExtension,
    MetadataConfig,
    ResponseConfig,
    RuntimeAuthConfig,
)
from authorino_tpu.evaluators.authorization import PatternMatching
from authorino_tpu.evaluators.identity import Noop, Plain
from authorino_tpu.evaluators.response import DynamicJSON
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.pipeline import AuthPipeline
from authorino_tpu.utils.rpc import OK, PERMISSION_DENIED, UNAUTHENTICATED


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


def request(headers=None, method="GET", path="/"):
    return CheckRequestModel(
        http=HttpRequestAttributes(
            method=method, path=path, host="svc.example.com", headers=headers or {}
        )
    )


class _StubEval:
    """Configurable leaf evaluator for pipeline contract tests."""

    def __init__(self, result=None, error=None, delay=0.0):
        self.result = result
        self.error = error
        self.delay = delay
        self.called = 0
        self.cancelled = 0

    async def call(self, pipeline):
        self.called += 1
        try:
            if self.delay:
                await asyncio.sleep(self.delay)
        except asyncio.CancelledError:
            self.cancelled += 1
            raise
        if self.error:
            raise EvaluationError(self.error)
        return self.result


class TestIdentityPhase:
    def test_anonymous_success(self):
        cfg = RuntimeAuthConfig(identity=[IdentityConfig("anon", Noop())])
        result = run(AuthPipeline(request(), cfg).evaluate())
        assert result.success()

    def test_single_failure_returns_raw_message(self):
        cfg = RuntimeAuthConfig(identity=[IdentityConfig("x", _StubEval(error="bad token"))])
        result = run(AuthPipeline(request(), cfg).evaluate())
        assert result.code == UNAUTHENTICATED
        assert result.message == "bad token"
        # challenge headers (ref config.go:29-40)
        assert result.headers == [{"WWW-Authenticate": 'Bearer realm="x"'}]

    def test_multi_failure_aggregates_errors_json(self):
        cfg = RuntimeAuthConfig(
            identity=[
                IdentityConfig("a", _StubEval(error="err-a")),
                IdentityConfig("b", _StubEval(error="err-b")),
            ]
        )
        result = run(AuthPipeline(request(), cfg).evaluate())
        assert result.code == UNAUTHENTICATED
        assert json.loads(result.message) == {"a": "err-a", "b": "err-b"}

    def test_first_success_cancels_slower_peers(self):
        slow = _StubEval(result={"u": "slow"}, delay=5.0)
        fast = _StubEval(result={"u": "fast"}, delay=0.0)
        cfg = RuntimeAuthConfig(
            identity=[IdentityConfig("slow", slow), IdentityConfig("fast", fast)]
        )
        pipeline = AuthPipeline(request(), cfg)
        result = run(pipeline.evaluate())
        assert result.success()
        assert pipeline.authorization_json()["auth"]["identity"] == {"u": "fast"}

    def test_priority_buckets_sequential(self):
        order = []

        class Tracker(_StubEval):
            def __init__(self, tag, **kw):
                super().__init__(**kw)
                self.tag = tag

            async def call(self, pipeline):
                order.append(self.tag)
                return await super().call(pipeline)

        # priority 0 fails, priority 1 succeeds → evaluated in order
        cfg = RuntimeAuthConfig(
            identity=[
                IdentityConfig("p1", Tracker("p1", result={"u": 1}), priority=1),
                IdentityConfig("p0", Tracker("p0", error="nope"), priority=0),
            ]
        )
        result = run(AuthPipeline(request(), cfg).evaluate())
        assert result.success()
        assert order == ["p0", "p1"]

    def test_extended_properties(self):
        cfg = RuntimeAuthConfig(
            identity=[
                IdentityConfig(
                    "plain",
                    Plain("request.headers.x-user|@fromstr"),
                    extended_properties=[
                        IdentityExtension("tier", JSONValue(static="gold")),
                        IdentityExtension("name", JSONValue(static="overwritten"), overwrite=False),
                    ],
                )
            ]
        )
        pipeline = AuthPipeline(request(headers={"x-user": '{"name":"john"}'}), cfg)
        result = run(pipeline.evaluate())
        assert result.success()
        ident = pipeline.authorization_json()["auth"]["identity"]
        assert ident == {"name": "john", "tier": "gold"}  # no overwrite of name

    def test_conditions_skip_identity(self):
        gated = IdentityConfig(
            "gated",
            _StubEval(result={"u": 1}),
            conditions=Pattern("request.method", Operator.EQ, "POST"),
        )
        anon = IdentityConfig("anon", Noop())
        cfg = RuntimeAuthConfig(identity=[gated, anon])
        pipeline = AuthPipeline(request(method="GET"), cfg)
        result = run(pipeline.evaluate())
        assert result.success()
        assert pipeline.authorization_json()["auth"]["identity"] == {"anonymous": True}


class TestAuthorizationPhase:
    def _cfg(self, *authz):
        return RuntimeAuthConfig(
            identity=[IdentityConfig("anon", Noop())],
            authorization=list(authz),
        )

    def test_pattern_matching_allow_deny(self):
        allow = AuthorizationConfig(
            "rbac",
            PatternMatching(All(Pattern("request.headers.x-org", Operator.EQ, "acme"))),
        )
        result = run(AuthPipeline(request(headers={"x-org": "acme"}), self._cfg(allow)).evaluate())
        assert result.success()

        result = run(AuthPipeline(request(headers={"x-org": "evil"}), self._cfg(allow)).evaluate())
        assert result.code == PERMISSION_DENIED
        assert result.message == "Unauthorized"

    def test_all_must_pass(self):
        ok = AuthorizationConfig("ok", _StubEval(result=True))
        bad = AuthorizationConfig("bad", _StubEval(error="denied by policy"))
        result = run(AuthPipeline(request(), self._cfg(ok, bad)).evaluate())
        assert result.code == PERMISSION_DENIED
        assert result.message == "denied by policy"

    def test_conditions_skip_authorization(self):
        gated = AuthorizationConfig(
            "gated",
            _StubEval(error="would deny"),
            conditions=Pattern("request.method", Operator.EQ, "DELETE"),
        )
        result = run(AuthPipeline(request(method="GET"), self._cfg(gated)).evaluate())
        assert result.success()

    def test_authz_results_in_auth_json(self):
        ok = AuthorizationConfig("policy-x", _StubEval(result={"score": 9}))
        pipeline = AuthPipeline(request(), self._cfg(ok))
        result = run(pipeline.evaluate())
        assert result.success()
        assert pipeline.authorization_json()["auth"]["authorization"]["policy-x"] == {"score": 9}


class TestMetadataResponsePhases:
    def test_metadata_failures_tolerated(self):
        cfg = RuntimeAuthConfig(
            identity=[IdentityConfig("anon", Noop())],
            metadata=[
                MetadataConfig("good", _StubEval(result={"m": 1})),
                MetadataConfig("bad", _StubEval(error="boom")),
            ],
        )
        pipeline = AuthPipeline(request(), cfg)
        result = run(pipeline.evaluate())
        assert result.success()
        assert pipeline.authorization_json()["auth"]["metadata"] == {"good": {"m": 1}}

    def test_response_headers_and_dynamic_metadata(self):
        cfg = RuntimeAuthConfig(
            identity=[IdentityConfig("anon", Noop())],
            response=[
                ResponseConfig(
                    "x-ext-auth-data",
                    DynamicJSON([JSONProperty("user", JSONValue(pattern="auth.identity.anonymous"))]),
                ),
                ResponseConfig(
                    "rate-limit-data",
                    DynamicJSON([JSONProperty("level", JSONValue(static=3))]),
                    wrapper="envoyDynamicMetadata",
                    wrapper_key="ext_auth_data",
                ),
            ],
        )
        result = run(AuthPipeline(request(), cfg).evaluate())
        assert result.success()
        assert result.headers == [{"x-ext-auth-data": '{"user":true}'}]
        assert result.metadata == {"ext_auth_data": {"level": 3}}


class TestTopLevel:
    def test_top_level_conditions_skip_pipeline(self):
        cfg = RuntimeAuthConfig(
            conditions=Pattern("request.path", Operator.EQ, "/admin"),
            identity=[IdentityConfig("x", _StubEval(error="should not run"))],
        )
        result = run(AuthPipeline(request(path="/public"), cfg).evaluate())
        assert result.success()

    def test_deny_with_unauthorized(self):
        cfg = RuntimeAuthConfig(
            identity=[IdentityConfig("anon", Noop())],
            authorization=[AuthorizationConfig("deny", _StubEval(error="nope"))],
            deny_with=DenyWith(
                unauthorized=DenyWithValues(
                    code=302,
                    message=JSONValue(static="redirecting"),
                    headers=[JSONProperty("Location", JSONValue(pattern="http://login{request.path}"))],
                )
            ),
        )
        result = run(AuthPipeline(request(path="/x"), cfg).evaluate())
        assert result.code == PERMISSION_DENIED
        assert result.status == 302
        assert result.message == "redirecting"
        assert result.headers == [{"Location": "http://login/x"}]

    def test_timeout(self):
        cfg = RuntimeAuthConfig(
            identity=[IdentityConfig("slow", _StubEval(result={"u": 1}, delay=2.0))]
        )
        result = run(AuthPipeline(request(), cfg, timeout=0.05).evaluate())
        assert not result.success()


class TestHostIndex:
    def test_radix_wildcards(self):
        from authorino_tpu.index import HostIndex, IndexError_

        idx = HostIndex()
        idx.set("cfg-1", "talker-api.example.com", "A")
        idx.set("cfg-2", "*.example.org", "B")
        idx.set("cfg-3", "example.org", "C")
        assert idx.get("talker-api.example.com") == "A"
        assert idx.get("anything.example.org") == "B"
        assert idx.get("deep.nested.example.org") == "B"
        assert idx.get("example.org") == "C"
        assert idx.get("unknown.example.com") is None
        # collision policy (ref :176-186)
        with pytest.raises(IndexError_):
            idx.set("cfg-9", "talker-api.example.com", "Z")
        idx.set("cfg-9", "talker-api.example.com", "Z", override=True)
        assert idx.get("talker-api.example.com") == "Z"
        # delete by id
        idx.delete("cfg-2")
        assert idx.get("anything.example.org") is None
        assert idx.find_keys("cfg-3") == ["example.org"]
