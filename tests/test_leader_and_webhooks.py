"""Leader election, leader-gated status updates, conversion/validation
webhooks, and the K8s watch-source event plumbing."""

import asyncio
import json

import pytest

from authorino_tpu.controllers import AuthConfigReconciler
from authorino_tpu.controllers.reconciler import STATUS_RECONCILED
from authorino_tpu.controllers.status_updater import AuthConfigStatusUpdater
from authorino_tpu.k8s import InMemoryCluster, InMemoryLeases, LeaderElector
from authorino_tpu.runtime import PolicyEngine
from authorino_tpu.service.webhooks import convert_review, validate_review


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


SPEC = {
    "hosts": ["api.example.com"],
    "authorization": {
        "allow-all": {"patternMatching": {"patterns": [
            {"selector": "request.method", "operator": "neq", "value": ""}
        ]}}
    },
}


def make_resource(name="cfg", ns="ns1", api="authorino.kuadrant.io/v1beta2", spec=None):
    return {
        "apiVersion": api,
        "kind": "AuthConfig",
        "metadata": {"name": name, "namespace": ns},
        "spec": spec or SPEC,
    }


class TestLeaderElection:
    def test_single_winner_and_failover(self):
        async def body():
            leases = InMemoryLeases()
            a = LeaderElector(leases, "replica-a", duration_s=0.2, renew_interval=0.02)
            b = LeaderElector(leases, "replica-b", duration_s=0.2, renew_interval=0.02)
            assert await a.try_acquire_or_renew() is True
            assert await b.try_acquire_or_renew() is False
            assert a.is_leader() and not b.is_leader()
            # renew keeps leadership
            assert await a.try_acquire_or_renew() is True
            # expiry → failover
            await asyncio.sleep(0.25)
            assert await b.try_acquire_or_renew() is True
            assert b.is_leader()
            assert await a.try_acquire_or_renew() is False
            assert not a.is_leader()

        run(body())

    def test_voluntary_release(self):
        async def body():
            leases = InMemoryLeases()
            a = LeaderElector(leases, "a", duration_s=30.0)
            b = LeaderElector(leases, "b", duration_s=30.0)
            assert await a.try_acquire_or_renew()
            await a.release()
            assert await b.try_acquire_or_renew() is True

        run(body())

    def test_transient_renew_error_keeps_leading_until_deadline(self):
        # client-go renew-deadline semantics: an apiserver blip must not
        # demote the leader while the lease is still unexpired — nobody
        # else can take it, so demoting leaves zero status writers
        class FlakyLeases(InMemoryLeases):
            fail = False

            async def put_lease(self, namespace, name, lease):
                if self.fail:
                    raise RuntimeError("apiserver unavailable")
                return await super().put_lease(namespace, name, lease)

        async def body():
            leases = FlakyLeases()
            a = LeaderElector(leases, "a", duration_s=0.3, renew_interval=0.02)  # renew deadline 0.2
            assert await a.try_acquire_or_renew() is True
            leases.fail = True
            assert await a.try_acquire_or_renew() is True  # blip: still leading
            assert a.is_leader()
            # past the renew deadline but before lease expiry: demote now,
            # strictly before any follower could acquire (no split-brain)
            await asyncio.sleep(0.25)
            assert await a.try_acquire_or_renew() is False
            assert not a.is_leader()
            leases.fail = False
            assert await a.try_acquire_or_renew() is True

        run(body())

    def test_invalid_timing_config_rejected(self):
        # client-go hard-errors on leaseDuration <= renewDeadline and
        # renewDeadline <= retryPeriod — silently accepting them re-opens
        # the two-leaders-during-partition window
        import pytest

        leases = InMemoryLeases()
        with pytest.raises(ValueError):
            LeaderElector(leases, "a", duration_s=15.0, renew_deadline_s=20.0)
        with pytest.raises(ValueError):
            LeaderElector(leases, "a", duration_s=15.0, renew_deadline_s=10.0,
                          renew_interval=10.0)

    def test_hanging_renew_counts_against_deadline(self):
        # a renew call that BLOCKS past the deadline must demote on the
        # failure path immediately — the clock is re-read after the await,
        # not captured before it
        class HangingLeases(InMemoryLeases):
            hang_s = 0.0

            async def put_lease(self, namespace, name, lease):
                if self.hang_s:
                    await asyncio.sleep(self.hang_s)
                    raise RuntimeError("apiserver partitioned")
                return await super().put_lease(namespace, name, lease)

        async def body():
            leases = HangingLeases()
            a = LeaderElector(leases, "a", duration_s=0.3, renew_interval=0.02)
            assert await a.try_acquire_or_renew() is True
            leases.hang_s = 0.25  # blocks past the 0.2 renew deadline
            assert await a.try_acquire_or_renew() is False
            assert not a.is_leader()

        run(body())

    def test_lease_name_derived_from_label_selector(self):
        from authorino_tpu.k8s.leader import leader_election_id

        a = leader_election_id("shard=a")
        b = leader_election_id("shard=b")
        assert a != b
        assert a.endswith(".authorino.kuadrant.io")
        assert leader_election_id("shard=a") == a  # deterministic
        # two label-sharded instances elect independent leaders
        async def body():
            leases = InMemoryLeases()
            ea = LeaderElector(leases, "replica-1", name=a)
            eb = LeaderElector(leases, "replica-2", name=b)
            assert await ea.try_acquire_or_renew() is True
            assert await eb.try_acquire_or_renew() is True

        run(body())

    def test_transition_callbacks(self):
        events = []

        async def body():
            leases = InMemoryLeases()
            a = LeaderElector(
                leases, "a", duration_s=0.2, renew_interval=0.02,
                on_started_leading=lambda: events.append("start"),
                on_stopped_leading=lambda: events.append("stop"),
            )
            await a.try_acquire_or_renew()
            await a.release()
            assert events == ["start", "stop"]

        run(body())


class TestStatusUpdater:
    def test_leader_writes_status_non_leader_does_not(self):
        async def body():
            engine = PolicyEngine()
            cluster = InMemoryCluster()
            rec = AuthConfigReconciler(engine, cluster=cluster)
            await rec.reconcile_all([make_resource()])
            assert rec.status.get("ns1/cfg").reason == STATUS_RECONCILED

            upd = AuthConfigStatusUpdater(rec, cluster, leases=cluster, namespace="ns1")
            # not leader yet → no writes
            assert await upd.sync_once() == 0
            assert ("ns1", "cfg") not in cluster.statuses
            # acquire leadership → writes
            assert await upd.elector.try_acquire_or_renew()
            assert await upd.sync_once() == 1
            status = cluster.statuses[("ns1", "cfg")]
            assert status["summary"]["ready"] is True
            assert status["summary"]["hostsReady"] == ["api.example.com"]
            conds = {c["type"]: c["status"] for c in status["conditions"]}
            assert conds == {"Available": "True", "Ready": "True"}
            # unchanged → no rewrite
            assert await upd.sync_once() == 0

        run(body())

    def test_no_leader_election_mode_always_writes(self):
        async def body():
            engine = PolicyEngine()
            cluster = InMemoryCluster()
            rec = AuthConfigReconciler(engine, cluster=cluster)
            await rec.reconcile_all([make_resource()])
            upd = AuthConfigStatusUpdater(rec, cluster, leader_election=False)
            assert await upd.sync_once() == 1

        run(body())


class TestConversionWebhook:
    def test_convert_v1beta2_to_v1beta1_and_back(self):
        obj = make_resource()
        review = {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "ConversionReview",
            "request": {
                "uid": "u1",
                "desiredAPIVersion": "authorino.kuadrant.io/v1beta1",
                "objects": [obj],
            },
        }
        out = convert_review(review)
        assert out["kind"] == "ConversionReview"
        assert out["response"]["uid"] == "u1"
        assert out["response"]["result"]["status"] == "Success"
        (conv,) = out["response"]["convertedObjects"]
        assert conv["apiVersion"] == "authorino.kuadrant.io/v1beta1"
        assert conv["metadata"]["name"] == "cfg"
        # v1beta1 uses a list-shaped authorization
        assert isinstance(conv["spec"]["authorization"], list)

        back = convert_review(
            {
                "request": {
                    "uid": "u2",
                    "desiredAPIVersion": "authorino.kuadrant.io/v1beta2",
                    "objects": [conv],
                }
            }
        )
        (round_tripped,) = back["response"]["convertedObjects"]
        assert round_tripped["spec"]["authorization"] == SPEC["authorization"]

    def test_convert_unsupported_version(self):
        out = convert_review(
            {"request": {"uid": "u", "desiredAPIVersion": "authorino.kuadrant.io/v9", "objects": []}}
        )
        assert out["response"]["result"]["status"] == "Failure"

    def test_status_preserved(self):
        obj = make_resource()
        obj["status"] = {"summary": {"ready": True}}
        out = convert_review(
            {
                "request": {
                    "uid": "u",
                    "desiredAPIVersion": "authorino.kuadrant.io/v1beta1",
                    "objects": [obj],
                }
            }
        )
        assert out["response"]["convertedObjects"][0]["status"] == obj["status"]


class TestValidationWebhook:
    def _review(self, obj, op="CREATE"):
        return {"request": {"uid": "u", "operation": op, "object": obj}}

    def test_valid_spec_allowed(self):
        out = validate_review(self._review(make_resource()))
        assert out["response"]["allowed"] is True

    def test_missing_hosts_rejected(self):
        bad = make_resource(spec={"authorization": {}})
        out = validate_review(self._review(bad))
        assert out["response"]["allowed"] is False
        assert "hosts" in out["response"]["status"]["message"]

    def test_unknown_field_rejected(self):
        bad = make_resource(spec={**SPEC, "identity": []})  # v1beta1 field in a v1beta2 CR
        out = validate_review(self._review(bad))
        assert out["response"]["allowed"] is False

    def test_delete_always_allowed(self):
        out = validate_review(self._review(make_resource(spec={}), op="DELETE"))
        assert out["response"]["allowed"] is True

    def test_bad_regex_rejected(self):
        bad = make_resource(spec={
            "hosts": ["h"],
            "authorization": {"a": {"patternMatching": {"patterns": [
                {"selector": "request.path", "operator": "matches", "value": "([unclosed"}
            ]}}},
        })
        out = validate_review(self._review(bad))
        assert out["response"]["allowed"] is False
        assert "pattern" in out["response"]["status"]["message"].lower() or "regex" in out["response"]["status"]["message"].lower() or "invalid" in out["response"]["status"]["message"].lower()

    def test_bad_operator_rejected(self):
        bad = make_resource(spec={
            "hosts": ["h"],
            "when": [{"selector": "request.path", "operator": "gte", "value": "1"}],
        })
        out = validate_review(self._review(bad))
        assert out["response"]["allowed"] is False


class TestInMemoryAuthConfigStore:
    def test_events_and_status_patch(self):
        async def body():
            cluster = InMemoryCluster()
            seen = []
            cluster.on_auth_config_event(lambda kind, obj: seen.append((kind, obj["metadata"]["name"])))
            cluster.put_auth_config(make_resource("a"))
            cluster.put_auth_config(make_resource("b"))
            cluster.remove_auth_config("ns1", "a")
            assert seen == [("upsert", "a"), ("upsert", "b"), ("delete", "a")]
            assert [o["metadata"]["name"] for o in await cluster.list_auth_configs()] == ["b"]
            await cluster.patch_auth_config_status("ns1", "b", {"summary": {"ready": True}})
            assert (await cluster.list_auth_configs())[0]["status"]["summary"]["ready"] is True

        run(body())
