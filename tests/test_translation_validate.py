"""Translation validation (ISSUE 6, analysis/translation_validate.py).

Under test: circuit equivalence against the host expression oracle
(exhaustive + sampled tiers), regex↔DFA witness equivalence, the canonical
per-config fingerprint (stable across compile orders, sensitive to every
certified artifact), the process-wide certificate cache (re-reconciling an
unchanged corpus re-validates NOTHING; changing one config re-validates
exactly that config), the lowerability report's reason-code catalogue, the
mutation self-test (every planted miscompile class rejected — the tier-1
gate that the validator can never silently go blind), and the
--strict-verify wiring (a miscompiled snapshot is rejected at swap time
with the old snapshot still serving).

Deliberately import-light: collects on images without ``cryptography``."""

from __future__ import annotations

import json
from copy import deepcopy

import numpy as np
import pytest

from authorino_tpu.analysis.fixtures import (
    fixture_configs,
    fixture_policy,
    lowerability_fixture_entries,
)
from authorino_tpu.analysis.translation_validate import (
    _MUTANTS,
    SAMPLES_DEFAULT,
    certify_config,
    certify_snapshot,
    clear_certificate_cache,
    config_fingerprint,
    lowerability_report,
    mutation_self_test,
)
from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.compiler.compile import FALSE_SLOT, TRUE_SLOT
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.engine import SnapshotRejected


def _entries(configs):
    return [EngineEntry(id=c.name, hosts=[f"{c.name}.example.com"],
                        runtime=None, rules=c) for c in configs]


# ---------------------------------------------------------------------------
# clean corpora certify; certificates carry the right evidence
# ---------------------------------------------------------------------------


def test_fixture_corpus_certifies_clean():
    certs, failures, stats = certify_snapshot(fixture_policy(),
                                              use_cache=False)
    assert failures == []
    assert stats["validated"] == 3 and stats["failed"] == 0
    by_name = {c.config: c for c in certs}
    # every config got an exhaustive certificate with a fingerprint
    for c in certs:
        assert c.ok and c.mode == "exhaustive" and len(c.fingerprint) == 64
        assert c.n_assignments == 1 << c.n_atoms
    # the DFA-bearing configs cross-checked witnesses
    assert by_name["api"].dfa_rows >= 1 and by_name["api"].dfa_witnesses > 0
    # JSON-safe for /debug/vars and the CLI
    json.dumps([c.to_json() for c in certs])


def test_invalid_regex_tree_certifies():
    # whole-tree CPU-fallback leaves (invalid regex) are opaque atoms on
    # BOTH sides — including the error-ordering corner the oracle pins
    bad = Pattern("p", Operator.MATCHES, "([")
    ok = Pattern("m", Operator.EQ, "GET")
    shared = Any_(bad, ok)
    policy = compile_corpus([
        ConfigRules("t", evaluators=[(shared, Any_(ok)),
                                     (None, All(ok, bad))]),
        ConfigRules("s", evaluators=[(shared, shared)]),
    ])
    _, failures, stats = certify_snapshot(policy, use_cache=False)
    assert failures == [] and stats["validated"] == 2


def test_wide_config_uses_sampled_tier():
    pats = [Pattern(f"a.k{i}", Operator.EQ, f"v{i}") for i in range(18)]
    policy = compile_corpus([ConfigRules(name="w", evaluators=[
        (None, Any_(*pats))])])
    certs, failures, stats = certify_snapshot(policy, use_cache=False,
                                              seed=7)
    assert failures == [] and stats["sampled"] == 1
    (c,) = certs
    assert c.mode == "sampled" and c.seed == 7
    assert c.n_assignments == SAMPLES_DEFAULT + 2  # + all-true/all-false


def test_sampled_tier_catches_redirected_rule():
    pats = [Pattern(f"a.k{i}", Operator.EQ, f"v{i}") for i in range(18)]
    policy = compile_corpus([ConfigRules(name="w", evaluators=[
        (None, All(*pats))])])
    policy.eval_rule = policy.eval_rule.copy()
    policy.eval_rule[0, 0] = TRUE_SLOT
    _, failures, _ = certify_snapshot(policy, use_cache=False)
    assert any(f.kind == "translation-mismatch" for f in failures)


# ---------------------------------------------------------------------------
# each miscompile class is rejected with its intended kind
# ---------------------------------------------------------------------------


def _mutate(name):
    p = deepcopy(fixture_policy())
    dict(_MUTANTS)[name](p)
    return p


@pytest.mark.parametrize("mutant,kind", [
    ("circuit-child-flip", "translation-mismatch"),
    ("eval-rule-redirect", "translation-mismatch"),
    ("leaf-attr-swap", "translation-mismatch"),
    ("leaf-const-swap", "translation-mismatch"),
    ("dfa-transition-corrupt", "dfa-mismatch"),
    ("dfa-accept-flip", "dfa-mismatch"),
    ("dfa-pad-corrupt", "dfa-mismatch"),
])
def test_planted_miscompile_rejected(mutant, kind):
    _, failures, stats = certify_snapshot(_mutate(mutant), use_cache=False)
    assert failures, f"mutant {mutant} certified clean"
    assert kind in {f.kind for f in failures}
    assert stats["failed"] >= 1


def test_mutation_self_test_green():
    """The tier-1 gate (mirrors PR 4's test_repo_stays_lint_clean): every
    planted mutant class must be rejected and the clean fixture corpus
    must certify — a blind validator FAILS CI."""
    findings = mutation_self_test()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_verify_fixtures_runs_translation_validation(capsys):
    # --verify-fixtures now carries the certification + self-test, so the
    # CI entry point can never silently skip them
    from authorino_tpu.analysis.__main__ import main

    assert main(["--verify-fixtures"]) == 0
    assert "OK" in capsys.readouterr().out


def test_dfa_witnesses_cover_reject_side():
    """A transition redirected into a dead state makes the table REJECT
    strings the regex accepts — only witnesses derived from a fresh
    reference determinization can see that direction."""
    policy = compile_corpus([ConfigRules("c", evaluators=[
        (None, Pattern("p", Operator.MATCHES, r"^/api/v[0-9]+/"))])])
    policy.dfa_tables = policy.dfa_tables.copy()
    t = policy.dfa_tables[0]
    # kill the '/' transition out of the start state: everything the
    # pattern accepts is now unreachable in the audited table
    dead = int(t.max()) if int(t.max()) != int(t[0, ord("/")]) else 0
    t[0, ord("/")] = dead
    _, failures, _ = certify_snapshot(policy, use_cache=False)
    assert any(f.kind == "dfa-mismatch" for f in failures)


# ---------------------------------------------------------------------------
# fingerprints: canonical, order-independent, artifact-sensitive
# ---------------------------------------------------------------------------


def test_fingerprint_stable_across_compile_order():
    pa = compile_corpus(fixture_configs())
    pb = compile_corpus(list(reversed(fixture_configs())))
    fa = {n: config_fingerprint(pa, g) for n, g in pa.config_ids.items()}
    fb = {n: config_fingerprint(pb, g) for n, g in pb.config_ids.items()}
    assert fa == fb  # interner ids / buffer slots never leak into the fp


def test_fingerprint_changes_with_semantics_only():
    base = compile_corpus(fixture_configs())
    fp = {n: config_fingerprint(base, g)
          for n, g in base.config_ids.items()}
    changed = fixture_configs()
    changed[1] = ConfigRules(name="admin", evaluators=[
        (None, Pattern("auth.identity.org", Operator.EQ, "other-org"))])
    p2 = compile_corpus(changed)
    fp2 = {n: config_fingerprint(p2, g) for n, g in p2.config_ids.items()}
    assert fp2["admin"] != fp["admin"]
    assert fp2["api"] == fp["api"] and fp2["public"] == fp["public"]


def test_fingerprint_covers_dfa_artifacts():
    # a corrupted table must change the fingerprint, or the certificate
    # cache would mask the corruption on the next reconcile
    base = fixture_policy()
    row = base.config_ids["api"]
    fp = config_fingerprint(base, row)
    mut = deepcopy(base)
    mut.dfa_tables = mut.dfa_tables.copy()
    mut.dfa_tables[0, 0, ord("x")] ^= 1
    assert config_fingerprint(mut, row) != fp


# ---------------------------------------------------------------------------
# the certificate cache is provably incremental
# ---------------------------------------------------------------------------


def test_cache_skips_unchanged_and_revalidates_changed():
    clear_certificate_cache()
    _, _, s1 = certify_snapshot(compile_corpus(fixture_configs()))
    assert s1["validated"] == 3 and s1["cache_hits"] == 0
    # identical corpus, fresh compile: ZERO re-validations
    _, _, s2 = certify_snapshot(compile_corpus(fixture_configs()))
    assert s2["validated"] == 0 and s2["cache_hits"] == 3
    # change ONE config: exactly that config re-validates
    changed = fixture_configs()
    changed[2] = ConfigRules(name="public", evaluators=[
        (None, Pattern("request.method", Operator.EQ, "GET"))])
    certs, _, s3 = certify_snapshot(compile_corpus(changed))
    assert s3["validated"] == 1 and s3["cache_hits"] == 2
    assert next(c for c in certs if c.config == "public").cached is False
    clear_certificate_cache()


def test_cache_never_shields_a_mutant():
    # the mutant's fingerprint differs from the clean one (artifact bytes
    # are fingerprinted), so a warm cache cannot serve it a certificate
    clear_certificate_cache()
    certify_snapshot(fixture_policy())  # warm the cache with clean certs
    _, failures, stats = certify_snapshot(_mutate("dfa-transition-corrupt"))
    assert stats["failed"] >= 1 and failures
    clear_certificate_cache()


def test_cache_never_shields_padded_column_corruption():
    """Padded columns are corpus layout, not fingerprinted semantics — so
    their structural check must run UNCACHED: a corrupted padded column
    on an otherwise-unchanged config bypasses the certificate cache
    (review-found cache-masking hole, regression-pinned)."""
    clear_certificate_cache()
    certify_snapshot(fixture_policy())  # warm the cache with clean certs
    p = fixture_policy()
    row = p.config_ids["public"]
    p.eval_rule = p.eval_rule.copy()
    p.eval_rule[row, p.eval_rule.shape[1] - 1] = FALSE_SLOT
    _, failures, stats = certify_snapshot(p)  # cache ON — must still fail
    assert stats["failed"] >= 1
    assert any("padded evaluator" in f.message for f in failures)
    clear_certificate_cache()


def test_cache_never_serves_another_configs_certificate():
    """The fingerprint hashes the (source, compiled) PAIR: a miscompile
    whose wrong circuit is structurally identical to another validated
    config's circuit must NOT be served that config's cached certificate
    (review-found cache-aliasing hole, regression-pinned)."""
    clear_certificate_cache()
    cfgs = [ConfigRules("a", evaluators=[
                (None, Pattern("m", Operator.EQ, "GET"))]),
            ConfigRules("b", evaluators=[
                (None, Pattern("m", Operator.EQ, "POST"))])]
    p = compile_corpus(cfgs)
    ga, gb = p.config_ids["a"], p.config_ids["b"]
    # simulate a const-swap miscompile: b's rule slot now points at a's
    # (perfectly valid, already-certified) circuit
    p.eval_rule = p.eval_rule.copy()
    p.eval_rule[gb, 0] = p.eval_rule[ga, 0]
    assert config_fingerprint(p, ga) != config_fingerprint(p, gb)
    _, failures, stats = certify_snapshot(p)  # cache ON — must still fail
    assert stats["failed"] >= 1
    assert any(f.detail.get("config") == "b" for f in failures)
    clear_certificate_cache()


def test_shared_corrupt_table_attributed_to_each_config():
    """Two configs sharing one deduped (corrupt) DFA table must EACH report
    the failure under their own name — the memoized findings are copied,
    not mutated (review-found mis-attribution, regression-pinned)."""
    rx = Pattern("request.url_path", Operator.MATCHES, r"^/api/v[0-9]+/")
    policy = compile_corpus([
        ConfigRules("alpha", evaluators=[(None, rx)]),
        ConfigRules("beta", evaluators=[(None, rx)]),
    ])
    assert policy.dfa_tables.shape[0] >= 1
    policy.dfa_accept = policy.dfa_accept.copy()
    policy.dfa_accept[0, 0] = not bool(policy.dfa_accept[0, 0])
    _, failures, _ = certify_snapshot(policy, use_cache=False)
    named = {f.detail.get("config") for f in failures
             if f.kind == "dfa-mismatch"}
    assert {"alpha", "beta"} <= named


# ---------------------------------------------------------------------------
# --strict-verify: a miscompiled snapshot cannot swap in
# ---------------------------------------------------------------------------


def test_strict_verify_rejects_miscompiled_swap(monkeypatch):
    from authorino_tpu.runtime import engine as engine_mod

    clear_certificate_cache()
    eng = PolicyEngine(mesh=None, strict_verify=True, analyze_policies=False)
    # this test simulates a COMPILER bug by monkeypatching compile_corpus:
    # the incremental compile cache (ISSUE 8) would honestly skip the
    # recompile of an identical corpus, so force the monolithic path
    eng.compile_cache = None
    eng.apply_snapshot(_entries(fixture_configs()))
    g1, snap1 = eng.generation, eng._snapshot
    assert snap1.translation["validated"] == 3

    real = engine_mod.compile_corpus

    def miscompile(*a, **k):
        p = real(*a, **k)
        # structurally VALID (passes tensor lint) but semantically wrong:
        # only translation validation can catch it
        dict(_MUTANTS)["circuit-child-flip"](p)
        return p

    monkeypatch.setattr(engine_mod, "compile_corpus", miscompile)
    with pytest.raises(SnapshotRejected) as ei:
        eng.apply_snapshot(_entries(fixture_configs()))
    assert "translation-mismatch" in {f.kind for f in ei.value.findings}
    # old snapshot still serving, generation unbumped
    assert eng.generation == g1 and eng._snapshot is snap1
    assert eng.lookup("api.example.com") is not None

    # clean corpus swaps again — entirely from the certificate cache
    monkeypatch.setattr(engine_mod, "compile_corpus", real)
    eng.apply_snapshot(_entries(fixture_configs()))
    assert eng.generation == g1 + 1
    assert eng._snapshot.translation == {
        "validated": 0, "cache_hits": 3, "failed": 0, "sampled": 0,
        "dfa_witnesses": 0}
    clear_certificate_cache()


def test_engine_reconcile_is_incremental(monkeypatch):
    clear_certificate_cache()
    eng = PolicyEngine(mesh=None, strict_verify=True, analyze_policies=False)
    eng.apply_snapshot(_entries(fixture_configs()))
    assert eng.debug_vars()["translation_validation"]["validated"] == 3
    # re-reconcile the same corpus: zero re-validations (all cache hits)
    eng.apply_snapshot(_entries(fixture_configs()))
    tv = eng.debug_vars()["translation_validation"]
    assert tv["validated"] == 0 and tv["cache_hits"] == 3
    # change one config: exactly one re-validation
    changed = fixture_configs()
    changed[0] = ConfigRules(name="api", evaluators=[
        (None, Pattern("request.method", Operator.NEQ, "TRACE"))])
    eng.apply_snapshot(_entries(changed))
    tv = eng.debug_vars()["translation_validation"]
    assert tv["validated"] == 1 and tv["cache_hits"] == 2
    # and the metric counted the hits (noop-metrics images skip the read)
    try:
        from prometheus_client import REGISTRY

        v = REGISTRY.get_sample_value(
            "auth_server_translation_validate_total",
            {"result": "cache_hit"})
        assert v is not None and v >= 5
    except ImportError:
        pass
    clear_certificate_cache()


# ---------------------------------------------------------------------------
# lowerability report
# ---------------------------------------------------------------------------


def test_lowerability_reason_catalogue():
    entries = lowerability_fixture_entries()
    rules = [e.rules for e in entries if e.rules is not None]
    rep = lowerability_report(entries, compile_corpus(rules))
    assert rep["fast"] == 4 and rep["slow"] == 4
    cfg = rep["configs"]
    assert cfg["api"]["reasons"] == ["cpu-grid-overflow", "cpu-regex"]
    assert cfg["public"] == {"lane": "fast", "reasons": []}
    assert cfg["bad-regex"]["reasons"] == ["invalid-regex-fallback"]
    assert cfg["interpreter-only"] == {
        "lane": "slow", "reasons": ["no-authorization-rules"]}
    assert cfg["opa-unsupported"]["reasons"] == ["unsupported-comparator"]
    assert cfg["metadata-bound"]["reasons"] == ["metadata-dependency"]
    assert cfg["external-az"]["reasons"] == ["external-authorization"]
    # full aggregate counts survive even when the listing is bounded
    rep2 = lowerability_report(entries, compile_corpus(rules), max_listed=2)
    assert rep2["fast"] == 4 and rep2["slow"] == 4
    assert rep2["truncated"] is True and len(rep2["configs"]) == 2
    assert rep2["by_reason"] == rep["by_reason"]
    json.dumps(rep)  # /debug/vars + artifact contract


def test_lowerability_on_engine_debug_vars():
    eng = PolicyEngine(mesh=None)
    eng.apply_snapshot(_entries(fixture_configs()))
    low = eng.debug_vars()["lowerability"]
    assert low is not None and low["generation"] == 1
    assert low["fast"] == 3 and low["slow"] == 0
    assert ["fast", "", 1] in low["series"]


def test_lowerability_accepts_mesh_shard_list():
    """Mesh snapshots have no single corpus policy — the classifier reads
    each config's CPU-assist leaves from its owning shard (review-found
    sharded blind spot, regression-pinned)."""
    entries = lowerability_fixture_entries()
    rules = [e.rules for e in entries if e.rules is not None]
    # split the corpus in two like the sharded model's per-shard compiles
    shards = [compile_corpus(rules[:2]), compile_corpus(rules[2:])]
    rep = lowerability_report(entries, shards)
    assert rep["configs"]["api"]["reasons"] == ["cpu-grid-overflow",
                                                "cpu-regex"]
    assert rep["configs"]["bad-regex"]["reasons"] == [
        "invalid-regex-fallback"]
    # parity with the single-corpus classification
    assert rep["by_reason"] == lowerability_report(
        entries, compile_corpus(rules))["by_reason"]


def test_cli_coverage_report(capsys):
    from authorino_tpu.analysis.__main__ import main

    assert main(["--coverage-report", "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    cov = report["coverage"]
    # 4 fast + the 3 ISSUE 14 relations fixtures (hier/quota/roles — all
    # fast: the coverage corpus compiles with ovf_assist) vs 4 slow
    assert cov["fast"] == 7 and cov["slow"] == 4
    assert "unsupported-comparator" in cov["by_reason"]
    # the would-be-fast-if-fixed rollup rides the report (ISSUE 14)
    assert cov["blocking_reasons"]["unsupported-comparator"] == {
        "configs": 1, "sole_blocker": 1}
    assert {"hier", "quota", "roles"} <= set(cov["configs"])


# ---------------------------------------------------------------------------
# satellite: the wide-support analysis skip is no longer silent
# ---------------------------------------------------------------------------


def test_policy_analysis_skip_is_surfaced():
    from authorino_tpu.analysis.policy_analysis import MAX_ATOMS, analyze_policy

    pats = [Pattern(f"a.k{i}", Operator.EQ, f"v{i}")
            for i in range(MAX_ATOMS + 2)]
    policy = compile_corpus([
        ConfigRules(name="wide", evaluators=[(None, Any_(*pats))]),
        ConfigRules(name="narrow", evaluators=[(None, pats[0])]),
    ])
    _, summary = analyze_policy(policy)
    assert summary["skipped_wide"] == 1
    assert summary["skipped"] == [
        {"config": "wide", "evaluator": 0, "atoms": MAX_ATOMS + 2}]


def test_engine_surfaces_skipped_configs(monkeypatch):
    from authorino_tpu.analysis.policy_analysis import MAX_ATOMS

    pats = [Pattern(f"a.k{i}", Operator.EQ, f"v{i}")
            for i in range(MAX_ATOMS + 2)]
    wide = ConfigRules(name="ns/wide", evaluators=[(None, Any_(*pats))])
    eng = PolicyEngine(mesh=None)
    eng.apply_snapshot(_entries([wide]))
    summary = eng.debug_vars()["policy_analysis"]["summary"]
    assert summary["skipped_wide"] == 1
    assert summary["skipped"][0]["config"] == "ns/wide"
    try:
        from prometheus_client import REGISTRY

        v = REGISTRY.get_sample_value(
            "auth_server_policy_analysis_skipped_total",
            {"authconfig": "ns/wide"})
        assert v is not None and v >= 1
    except ImportError:
        pass


# ---------------------------------------------------------------------------
# certify_config unit corners
# ---------------------------------------------------------------------------


def test_padded_evaluator_columns_must_be_vacuous():
    policy = fixture_policy()
    row = policy.config_ids["public"]  # one real evaluator, padded to E
    policy.eval_rule = policy.eval_rule.copy()
    policy.eval_rule[row, policy.eval_rule.shape[1] - 1] = FALSE_SLOT
    _, failures = certify_config(policy, row)
    assert any("padded evaluator" in f.message for f in failures)


def test_empty_config_certifies():
    policy = compile_corpus([ConfigRules("empty", evaluators=[])])
    cert, failures = certify_config(policy, 0)
    assert failures == [] and cert.ok and cert.n_atoms == 0


def test_certify_unlinted_table_index_corruption_degrades_to_finding():
    """certify's public API must not assume the tensor lint ran first: an
    out-of-range dfa_table_of_row entry yields a dfa-mismatch finding,
    never an IndexError (review-found edge, regression-pinned)."""
    p = deepcopy(fixture_policy())
    p.dfa_table_of_row = p.dfa_table_of_row.copy()
    p.dfa_table_of_row[0] = p.dfa_tables.shape[0] + 7
    _, failures, stats = certify_snapshot(p, use_cache=False)
    assert stats["failed"] >= 1
    assert any(f.kind == "dfa-mismatch" and "table axis" in f.message
               for f in failures)


def test_mutation_self_test_on_structureless_corpus_reports_not_crashes():
    """A corpus without And/Or nodes or DFA tables cannot host several
    planters — the self-test must report them as unplantable findings,
    not crash (review-found edge, regression-pinned)."""
    policy = compile_corpus([ConfigRules("leafy", evaluators=[
        (None, Pattern("m", Operator.EQ, "GET"))])])
    findings = mutation_self_test(policy)
    assert findings  # planters for circuits/DFA tables cannot plant here
    assert all(f.kind == "validator-blind" and "could not be planted"
               in f.message for f in findings)
