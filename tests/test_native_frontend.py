"""Differential suite for the native C++ gRPC frontend (native/frontend.cpp +
runtime/native_frontend.py): every response must match the Python grpc.aio
server (service/grpc_server.py) field for field — same corpus, same
requests, fast lane and slow lane both.

The engine here is built with mesh=None so the single-corpus fast lane
engages (the suite-wide conftest forces an 8-device virtual mesh, which
routes everything to the slow lane — covered by its own test below)."""

from __future__ import annotations

import asyncio
import threading
import time

import grpc
import pytest

from authorino_tpu import protos
from authorino_tpu.compiler import ConfigRules
from authorino_tpu.evaluators import (
    AuthorizationConfig,
    DenyWith,
    DenyWithValues,
    IdentityConfig,
    RuntimeAuthConfig,
)
from authorino_tpu.authjson.value import JSONProperty, JSONValue
from authorino_tpu.evaluators.authorization import OPA, PatternMatching
from authorino_tpu.evaluators.credentials import AuthCredentials
from authorino_tpu.evaluators.identity import APIKey, Noop
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.k8s.client import LabelSelector, Secret
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.native_frontend import NativeFrontend, fast_lane_eligible

pb = protos.external_auth_pb2


def _native_available() -> bool:
    from authorino_tpu.native import load_library

    mod = load_library()
    return mod is not None and hasattr(mod, "fe_start")


pytestmark = pytest.mark.skipif(
    not _native_available(), reason="native frontend unavailable (no libnghttp2?)")


# ---------------------------------------------------------------------------
# corpus: a mix that exercises fast lane, slow lane, DFA, denyWith
# ---------------------------------------------------------------------------

def make_pattern_entry(engine, cfg_id, hosts, rule, cond=None, deny_with=None):
    pm = PatternMatching(rule, batched_provider=engine.provider_for(cfg_id),
                         evaluator_slot=0)
    ns, _, nm = cfg_id.partition("/")
    runtime = RuntimeAuthConfig(
        labels={"namespace": ns, "name": nm},  # like translate injects
        identity=[IdentityConfig("anon", Noop())],
        authorization=[AuthorizationConfig("rules", pm)],
        deny_with=deny_with or DenyWith(),
    )
    return EngineEntry(id=cfg_id, hosts=hosts, runtime=runtime,
                       rules=ConfigRules(name=cfg_id, evaluators=[(cond, rule)]))


def build_engine() -> PolicyEngine:
    engine = PolicyEngine(max_batch=64, mesh=None)

    def pattern_entry(i, cfg_id, hosts, rule, cond=None, deny_with=None):
        return make_pattern_entry(engine, cfg_id, hosts, rule, cond, deny_with)

    entries = []
    # fast: plain eq/neq/incl over request attrs
    entries.append(pattern_entry(
        0, "ns/fast-eq", ["fast-eq.test"],
        All(Pattern("request.method", Operator.EQ, "GET"),
            Pattern("request.headers.x-org", Operator.EQ, "acme"))))
    # fast: compiled evaluator conditions (skipped ⇒ allow)
    entries.append(pattern_entry(
        1, "ns/fast-cond", ["fast-cond.test"],
        Pattern("request.headers.x-role", Operator.EQ, "admin"),
        cond=Pattern("request.method", Operator.EQ, "POST")))
    # fast: device-DFA regex over url_path
    entries.append(pattern_entry(
        2, "ns/fast-rx", ["fast-rx.test"],
        Pattern("request.url_path", Operator.MATCHES, r"^/api/v[0-9]+/ok")))
    # fast: static denyWith customization
    entries.append(pattern_entry(
        3, "ns/fast-deny", ["fast-deny.test"],
        Pattern("request.headers.x-pass", Operator.EQ, "yes"),
        deny_with=DenyWith(unauthorized=DenyWithValues(
            code=302,
            message=JSONValue(static="moved"),
            headers=[JSONProperty("Location", JSONValue(static="http://login.test"))],
        ))))
    # fast (round 4): API-key identity-only — credential map lookup, pure
    # C++ decision, no kernel involvement
    api_key = APIKey("friends", LabelSelector.from_spec({"matchLabels": {"g": "t"}}),
                     credentials=AuthCredentials(key_selector="APIKEY"))
    api_key.add_k8s_secret_based_identity(
        Secret(namespace="ns", name="k1", labels={"g": "t"}, data={"api_key": b"sekret"}))
    entries.append(EngineEntry(
        id="ns/fast-keyonly", hosts=["slow-key.test"],
        runtime=RuntimeAuthConfig(
            labels={"namespace": "ns", "name": "fast-keyonly"},
            identity=[IdentityConfig("friends", api_key,
                                     credentials=AuthCredentials(key_selector="APIKEY"))]),
        rules=None))
    # fast (round 4): API-key identity + patterns over auth.identity.* —
    # per-key plan variants resolved at refresh time
    api_key2 = APIKey(
        "team", LabelSelector.from_spec({"matchLabels": {"g": "t2"}}),
        credentials=AuthCredentials(key_selector="X-API-KEY", location="custom_header"))
    api_key2.add_k8s_secret_based_identity(Secret(
        namespace="ns", name="adm", labels={"g": "t2"},
        annotations={"role": "admin"}, data={"api_key": b"adminkey"}))
    api_key2.add_k8s_secret_based_identity(Secret(
        namespace="ns", name="usr", labels={"g": "t2"},
        annotations={"role": "user"}, data={"api_key": b"userkey"}))
    rule_role = Pattern("auth.identity.metadata.annotations.role", Operator.EQ, "admin")
    pm_role = PatternMatching(rule_role, batched_provider=engine.provider_for("ns/fast-key"),
                              evaluator_slot=0)
    entries.append(EngineEntry(
        id="ns/fast-key", hosts=["fast-key.test"],
        runtime=RuntimeAuthConfig(
            labels={"namespace": "ns", "name": "fast-key"},
            identity=[IdentityConfig(
                "team", api_key2,
                credentials=AuthCredentials(key_selector="X-API-KEY",
                                            location="custom_header"))],
            authorization=[AuthorizationConfig("rules", pm_role)]),
        rules=ConfigRules(name="ns/fast-key", evaluators=[(None, rule_role)])))
    # fast (round 4): remaining credential locations (cookie / query)
    for host, loc, sel in (("cookie-key.test", "cookie", "ses"),
                           ("query-key.test", "query", "tok")):
        ak = APIKey(f"k-{loc}", LabelSelector.from_spec({"matchLabels": {"g": loc}}),
                    credentials=AuthCredentials(key_selector=sel, location=loc))
        ak.add_k8s_secret_based_identity(Secret(
            namespace="ns", name=f"s-{loc}", labels={"g": loc},
            data={"api_key": b"c0ffee"}))
        entries.append(EngineEntry(
            id=f"ns/fast-{loc}", hosts=[host],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": f"fast-{loc}"},
                identity=[IdentityConfig(
                    f"k-{loc}", ak,
                    credentials=AuthCredentials(key_selector=sel, location=loc))]),
            rules=None))
    # slow: templated denyWith needs per-request resolution
    entries.append(pattern_entry(
        7, "ns/slow-tmpl", ["slow-tmpl.test"],
        Pattern("request.method", Operator.EQ, "GET"),
        deny_with=DenyWith(unauthorized=DenyWithValues(
            message=JSONValue(pattern="request.path")))))
    # wildcard host: pattern-only, so it rides the FAST lane — the C++
    # side replicates the index's wildcard walk-up
    entries.append(pattern_entry(
        5, "ns/fast-wild", ["*.wild.test"],
        Pattern("request.method", Operator.NEQ, "DELETE")))
    # fast (round 5): patternMatching + decidable inline Rego in ONE config —
    # the Rego verdict lowers into a kernel slot (rego_lower) so the mixed
    # config keeps the fast lane (VERDICT r4 item 1; the reference runs OPA
    # inline at full server speed, ref pkg/evaluators/authorization/opa.go:86-117)
    opa = OPA("ns/fast-rego/rego", inline_rego=(
        'allow { input.request.method == "GET" }\n'
        'allow { input.request.headers["x-root"] == "true" }'))
    rule_tier = Pattern("request.headers.x-tier", Operator.EQ, "gold")
    pm_tier = PatternMatching(rule_tier,
                              batched_provider=engine.provider_for("ns/fast-rego"),
                              evaluator_slot=0)
    lowered = opa.lowered_verdict()
    assert lowered is not None
    opa.kernel_slot = 1
    entries.append(EngineEntry(
        id="ns/fast-rego", hosts=["fast-rego.test"],
        runtime=RuntimeAuthConfig(
            labels={"namespace": "ns", "name": "fast-rego"},
            identity=[IdentityConfig("anon", Noop())],
            authorization=[AuthorizationConfig("rules", pm_tier),
                           AuthorizationConfig("rego", opa)]),
        rules=ConfigRules(name="ns/fast-rego",
                          evaluators=[(None, rule_tier), (None, lowered)])))
    engine.apply_snapshot(entries)
    return engine


def make_req(host, method="GET", path="/", headers=None, ctx=None):
    req = pb.CheckRequest()
    http = req.attributes.request.http
    http.method = method
    http.path = path
    http.host = host
    for k, v in (headers or {}).items():
        http.headers[k] = v
    for k, v in (ctx or {}).items():
        req.attributes.context_extensions[k] = v
    return req


REQUESTS = [
    make_req("fast-eq.test", headers={"x-org": "acme"}),
    make_req("fast-eq.test", headers={"x-org": "evil"}),
    make_req("fast-eq.test", method="POST", headers={"x-org": "acme"}),
    make_req("fast-eq.test"),                                    # header missing
    make_req("fast-cond.test"),                                  # cond unmatched → allow
    make_req("fast-cond.test", method="POST"),                   # cond matched → deny
    make_req("fast-cond.test", method="POST", headers={"x-role": "admin"}),
    make_req("fast-rx.test", path="/api/v2/ok?x=1"),
    make_req("fast-rx.test", path="/api/nope"),
    make_req("fast-rx.test", path="/api/v9/ok" + "a" * 100),     # > DFA_VALUE_BYTES
    make_req("fast-deny.test", headers={"x-pass": "yes"}),
    make_req("fast-deny.test", headers={"x-pass": "no"}),        # custom 302 deny
    make_req("slow-key.test", headers={"authorization": "APIKEY sekret"}),
    make_req("slow-key.test", headers={"authorization": "APIKEY wrong"}),
    make_req("slow-key.test"),                                   # credential missing
    make_req("slow-key.test", headers={"authorization": "Bearer sekret"}),  # wrong scheme
    make_req("fast-key.test", headers={"x-api-key": "adminkey"}),  # identity const allows
    make_req("fast-key.test", headers={"x-api-key": "userkey"}),   # identity const denies
    make_req("fast-key.test", headers={"x-api-key": "nope"}),      # unknown key
    make_req("fast-key.test"),                                     # header missing
    make_req("slow-tmpl.test", method="POST", path="/here"),       # templated deny → slow
    make_req("cookie-key.test", headers={"cookie": "a=1; ses=c0ffee; b=2"}),
    make_req("cookie-key.test", headers={"cookie": "ses=wrong"}),
    make_req("cookie-key.test", headers={"cookie": "other=1"}),    # cred missing
    make_req("query-key.test", path="/hello?x=1&tok=c0ffee&y=2"),
    make_req("query-key.test", path="/hello?tok=bad"),
    make_req("query-key.test", path="/hello"),                     # cred missing
    make_req("a.wild.test"),
    make_req("a.wild.test", method="DELETE"),
    make_req("deep.a.wild.test"),            # wildcard matches any depth
    make_req("wild.test"),                   # walk-up matches the base itself
    make_req("a.wild.test:8443"),            # port strip before wildcard
    make_req("unknown.test"),                # exact+wildcard miss → 404
    make_req("fast-eq.test:8080", headers={"x-org": "acme"}),    # port strip
    make_req("other.test", headers={"x-org": "acme"}, ctx={"host": "fast-eq.test"}),
    # mixed pattern + lowered-Rego config: both evaluators kernel-decided
    make_req("fast-rego.test", headers={"x-tier": "gold"}),              # GET → allow
    make_req("fast-rego.test", method="DELETE", headers={"x-tier": "gold"}),  # rego deny
    make_req("fast-rego.test", method="DELETE",
             headers={"x-tier": "gold", "x-root": "true"}),              # 2nd rego body
    make_req("fast-rego.test", headers={"x-tier": "wood"}),              # pattern deny
    make_req("fast-rego.test", method="DELETE", headers={"x-root": "TRUE"}),  # both deny
]


def wait_for_snap_retire(fe, timeout_s: float = 30.0) -> None:
    """Poll until every superseded snapshot drained and retired."""
    deadline = time.monotonic() + timeout_s
    while len(fe._snaps) > 1 and time.monotonic() < deadline:
        time.sleep(0.1)
    assert len(fe._snaps) == 1


def response_key(resp: pb.CheckResponse):
    kind = resp.WhichOneof("http_response")
    headers = []
    body = ""
    status = 0
    if kind == "denied_response":
        d = resp.denied_response
        status = d.status.code
        headers = sorted((h.header.key, h.header.value) for h in d.headers)
        body = d.body
    elif kind == "ok_response":
        headers = sorted((h.header.key, h.header.value) for h in resp.ok_response.headers)
    return (resp.status.code, kind, status, headers, body)


def grpc_call(port, req, path="/envoy.service.auth.v3.Authorization/Check"):
    with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        call = ch.unary_unary(path,
                              request_serializer=pb.CheckRequest.SerializeToString,
                              response_deserializer=pb.CheckResponse.FromString)
        return call(req, timeout=10)


def run_python_server(engine):
    """The grpc.aio reference server on a background loop thread."""
    from authorino_tpu.service.grpc_server import build_server

    started = threading.Event()
    holder = {}

    def runner():
        async def main():
            server = build_server(engine, address="127.0.0.1:0")
            await server.start()
            holder["port"] = server.bound_port
            holder["loop"] = asyncio.get_running_loop()
            started.set()
            await holder["stop"].wait()
            await server.stop(0.2)

        holder["stop"] = None

        async def boot():
            holder["stop"] = asyncio.Event()
            await main()

        asyncio.run(boot())

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    started.wait(30)
    return holder, t


def test_sharded_engine_serves_fast_lane():
    """A mesh-sharded corpus must ride the fast lane too (round 4): the C++
    encoder lays each request into its owning shard's [B, S, ...] slice and
    one shard_map dispatch serves the batch — multi-device scaling composes
    with the native frontend instead of disabling it.  Differential against
    the Python server on the same sharded engine.  (Runs FIRST: the C++
    server is one-per-process, so this test must finish before the
    module-scoped stack fixture starts its own.)"""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    engine = PolicyEngine(max_batch=16, mesh="auto")
    entries = []
    # enough configs to land on several mp shards, incl. a device-DFA regex
    for i in range(10):
        entries.append(make_pattern_entry(
            engine, f"ns/shard-{i}", [f"shard-{i}.test"],
            All(Pattern("request.headers.x-org", Operator.EQ, f"org-{i}"),
                Pattern("request.method", Operator.NEQ, "DELETE"))))
    entries.append(make_pattern_entry(
        engine, "ns/shard-rx", ["shard-rx.test"],
        Pattern("request.url_path", Operator.MATCHES, r"^/v[0-9]+/ok")))
    # credential variants × sharding: per-key auth.* constants must resolve
    # against the OWNING SHARD's compile
    aks = APIKey("sh-keys", LabelSelector.from_spec({"matchLabels": {"g": "sh"}}),
                 credentials=AuthCredentials(key_selector="APIKEY"))
    aks.add_k8s_secret_based_identity(Secret(
        namespace="ns", name="sh-adm", labels={"g": "sh"},
        annotations={"role": "admin"}, data={"api_key": b"sh-admin"}))
    aks.add_k8s_secret_based_identity(Secret(
        namespace="ns", name="sh-usr", labels={"g": "sh"},
        annotations={"role": "user"}, data={"api_key": b"sh-user"}))
    rule_sh = Pattern("auth.identity.metadata.annotations.role", Operator.EQ,
                      "admin")
    pm_sh = PatternMatching(rule_sh,
                            batched_provider=engine.provider_for("ns/shard-key"),
                            evaluator_slot=0)
    entries.append(EngineEntry(
        id="ns/shard-key", hosts=["shard-key.test"],
        runtime=RuntimeAuthConfig(
            labels={"namespace": "ns", "name": "shard-key"},
            identity=[IdentityConfig("sh-keys", aks,
                                     credentials=AuthCredentials(
                                         key_selector="APIKEY"))],
            authorization=[AuthorizationConfig("rules", pm_sh)]),
        rules=ConfigRules(name="ns/shard-key", evaluators=[(None, rule_sh)])))
    engine.apply_snapshot(entries)
    assert engine._snapshot.sharded is not None, "mesh path not engaged"
    fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
    port = fe.start()
    holder, t = run_python_server(engine)
    try:
        reqs = []
        for i in range(10):
            reqs.append(make_req(f"shard-{i}.test", headers={"x-org": f"org-{i}"}))
            reqs.append(make_req(f"shard-{i}.test", headers={"x-org": "evil"}))
            reqs.append(make_req(f"shard-{i}.test", method="DELETE",
                                 headers={"x-org": f"org-{i}"}))
        reqs.append(make_req("shard-rx.test", path="/v2/ok"))
        reqs.append(make_req("shard-rx.test", path="/nope"))
        reqs.append(make_req("shard-rx.test", path="/v2/ok" + "x" * 200))  # ovf
        reqs.append(make_req("shard-key.test",
                             headers={"authorization": "APIKEY sh-admin"}))
        reqs.append(make_req("shard-key.test",
                             headers={"authorization": "APIKEY sh-user"}))
        reqs.append(make_req("shard-key.test",
                             headers={"authorization": "APIKEY nope"}))
        reqs.append(make_req("shard-key.test"))
        reqs.append(make_req("unknown.test"))
        for i, req in enumerate(reqs):
            native = response_key(grpc_call(port, req))
            python = response_key(grpc_call(holder["port"], req))
            assert native == python, f"sharded req #{i}: {native} vs {python}"
        stats = fe.stats()
        assert stats["fast"] > 0, f"sharded fast lane never engaged: {stats}"
        assert stats["fast"] >= len(reqs) - 1  # all but the 404 ride fast
        # seeded random sweep across shards, credentials, regex/overflow
        rng = __import__("random").Random(8)
        mism = []
        for i in range(120):
            host = rng.choice([f"shard-{rng.randrange(10)}.test",
                               "shard-rx.test", "shard-key.test",
                               "nope.test"])
            headers = {}
            if rng.random() < 0.6:
                headers["x-org"] = rng.choice(
                    [f"org-{rng.randrange(10)}", "evil", ""])
            if rng.random() < 0.5:
                headers["authorization"] = rng.choice(
                    ["APIKEY sh-admin", "APIKEY sh-user", "APIKEY zz", ""])
            req = make_req(host, method=rng.choice(["GET", "DELETE"]),
                           path=rng.choice(["/v2/ok", "/no",
                                            "/v1/ok" + "y" * 180]),
                           headers=headers)
            nk = response_key(grpc_call(port, req))
            pk = response_key(grpc_call(holder["port"], req))
            if nk != pk:
                mism.append((i, nk, pk))
        assert not mism, f"{len(mism)} diverged on the mesh, first: {mism[0]}"
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)
        fe.stop()


# ---------------------------------------------------------------------------
# OIDC/JWT fast lane: the C++ variant map as a verified-token cache
# (round 4; ref pkg/evaluators/identity/oidc.go:41-103 verifies per request —
# here verification runs once in the slow lane and repeats serve natively)
# ---------------------------------------------------------------------------

def run_fake_idp():
    """FakeIdP (test_evaluators) on its own loop thread, alive while the
    frontend's slow lane and the Python server both fetch discovery/JWKS."""
    from test_evaluators import FakeIdP

    started = threading.Event()
    holder = {}

    def runner():
        async def main():
            from aiohttp.test_utils import TestServer

            idp = FakeIdP()
            server = TestServer(idp.app())
            await server.start_server()
            idp.issuer = str(server.make_url("")).rstrip("/")
            holder["idp"] = idp
            holder["loop"] = asyncio.get_running_loop()
            holder["stop"] = asyncio.Event()
            started.set()
            await holder["stop"].wait()
            await server.close()

        asyncio.run(main())

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    started.wait(30)
    return holder, t


def _oidc_engine(idp):
    from authorino_tpu.evaluators.identity import OIDC

    engine = PolicyEngine(max_batch=32, mesh=None)
    oidc = OIDC("kc", idp.issuer)
    rule = Pattern("auth.identity.realm_access.roles", Operator.INCL, "admin")
    pm = PatternMatching(rule, batched_provider=engine.provider_for("ns/oidc"),
                         evaluator_slot=0)
    entries = [
        EngineEntry(
            id="ns/oidc", hosts=["oidc.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "oidc"},
                identity=[IdentityConfig("kc", oidc)],
                authorization=[AuthorizationConfig("rules", pm)]),
            rules=ConfigRules(name="ns/oidc", evaluators=[(None, rule)])),
        # identity-only: token validity IS the decision (pure C++ on hits)
        EngineEntry(
            id="ns/oidc-only", hosts=["oidc-only.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "oidc-only"},
                identity=[IdentityConfig("kc", oidc)]),
            rules=None),
    ]
    engine.apply_snapshot(entries)
    return engine, oidc


def test_oidc_fast_lane_token_cache():
    holder, t = run_fake_idp()
    idp = holder["idp"]
    try:
        engine, oidc = _oidc_engine(idp)
        # eligibility: dyn spec with the claim attr rows for registration
        snap = engine._snapshot
        spec = fast_lane_eligible(snap.by_id["ns/oidc"], snap.policy)
        assert spec is not None and len(spec.sources) == 1
        assert spec.sources[0].dyn and spec.sources[0].cred_kind == 1
        assert spec.sources[0].cred_key == "Bearer" and spec.auth_attrs

        fe = NativeFrontend(engine, port=0, max_batch=32, window_us=500)
        port = fe.start()
        pyholder, pyt = run_python_server(engine)
        try:
            bearer = lambda tok: {"authorization": f"Bearer {tok}"}
            admin = idp.token()  # realm_access.roles = [admin]
            user = idp.token({"realm_access": {"roles": ["user"]}})

            # first sight of a token: slow lane verifies AND registers
            r1 = grpc_call(port, make_req("oidc.test", headers=bearer(admin)))
            assert r1.status.code == 0
            assert fe.stats()["dyn_add"] >= 1
            # repeats ride the fast lane (claims resolved from the cache)
            r2 = grpc_call(port, make_req("oidc.test", headers=bearer(admin)))
            assert r2.status.code == 0
            assert fe.stats()["dyn_hit"] >= 1
            # a cached token with the wrong role denies through the kernel
            d1 = grpc_call(port, make_req("oidc.test", headers=bearer(user)))
            d2 = grpc_call(port, make_req("oidc.test", headers=bearer(user)))
            assert d1.status.code == 7 and d2.status.code == 7
            assert fe.stats()["dyn_hit"] >= 2
            # identity-only config: cached token → direct C++ OK
            before_ok = fe.stats()["direct_ok"]
            grpc_call(port, make_req("oidc-only.test", headers=bearer(admin)))
            o2 = grpc_call(port, make_req("oidc-only.test", headers=bearer(admin)))
            assert o2.status.code == 0
            assert fe.stats()["direct_ok"] > before_ok

            # differential vs the Python server, hits and misses both
            matrix = [
                make_req("oidc.test", headers=bearer(admin)),
                make_req("oidc.test", headers=bearer(user)),
                make_req("oidc.test", headers=bearer("not-a-token")),
                make_req("oidc.test", headers={"authorization": "Basic zzz"}),
                make_req("oidc.test"),
                make_req("oidc-only.test", headers=bearer(admin)),
                make_req("oidc-only.test"),
            ]
            for i, rq in enumerate(matrix):
                native = response_key(grpc_call(port, rq))
                python = response_key(grpc_call(pyholder["port"], rq))
                assert native == python, f"oidc req #{i}: {native} vs {python}"

            # expiry is enforced in C++: past its exp the token stops being
            # served from the cache.  jose honors a 30s clock-skew leeway,
            # so the slow lane still answers OK here — the point is the
            # route: post-exp requests must MISS the cache (and a dead
            # deadline must not re-register)
            short = idp.token({"exp": int(time.time()) + 1})
            a = grpc_call(port, make_req("oidc.test", headers=bearer(short)))
            assert a.status.code == 0
            time.sleep(1.3)
            miss_before = fe.stats()["dyn_miss"]
            b = grpc_call(port, make_req("oidc.test", headers=bearer(short)))
            assert b.status.code == 0  # within leeway: pipeline parity
            assert fe.stats()["dyn_miss"] > miss_before
            c = grpc_call(port, make_req("oidc.test", headers=bearer(short)))
            assert fe.stats()["dyn_miss"] > miss_before + 1  # stayed slow
        finally:
            pyholder["loop"].call_soon_threadsafe(pyholder["stop"].set)
            pyt.join(timeout=10)
            fe.stop()
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)


def test_oidc_jwks_rotation_drops_token_cache():
    """Key rotation at the provider must invalidate every cached token:
    the OIDC change listener swaps in a fresh C++ snapshot (empty variant
    map), so old-key tokens fall back to the slow lane and fail
    verification against the new JWKS."""
    from cryptography.hazmat.primitives.asymmetric import rsa

    holder, t = run_fake_idp()
    idp = holder["idp"]
    try:
        engine, oidc = _oidc_engine(idp)
        fe = NativeFrontend(engine, port=0, max_batch=32, window_us=500)
        port = fe.start()
        try:
            bearer = lambda tok: {"authorization": f"Bearer {tok}"}
            old_tok = idp.token()
            r1 = grpc_call(port, make_req("oidc.test", headers=bearer(old_tok)))
            r2 = grpc_call(port, make_req("oidc.test", headers=bearer(old_tok)))
            assert r1.status.code == 0 and r2.status.code == 0
            assert fe.stats()["dyn_hit"] >= 1

            idp.key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
            # refresh discovery+JWKS (any loop works; the change listener
            # fires from here and rebuilds the frontend snapshot)
            fut = asyncio.run_coroutine_threadsafe(oidc.refresh(), holder["loop"])
            fut.result(30)

            deadline = time.time() + 60
            code = 0
            while time.time() < deadline:
                code = grpc_call(port, make_req(
                    "oidc.test", headers=bearer(old_tok))).status.code
                if code == 16:
                    break
                time.sleep(0.2)
            assert code == 16, "old-key token still served after rotation"
            new_tok = idp.token()
            rn = grpc_call(port, make_req("oidc.test", headers=bearer(new_tok)))
            assert rn.status.code == 0
        finally:
            fe.stop()
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)


def test_multi_identity_or_fast_lane():
    """API key OR JWT in one AuthConfig (the canonical Authorino pairing):
    both identity sources ride the fast lane — static per-key variants for
    the API key, the verified-token cache for OIDC — and the all-sources-
    failed answers come from per-bitmask static templates, byte-exact with
    the pipeline's aggregated JSON error (round 4)."""
    holder, t = run_fake_idp()
    idp = holder["idp"]
    try:
        from authorino_tpu.evaluators.identity import OIDC

        engine = PolicyEngine(max_batch=32, mesh=None)
        ak = APIKey("api-users", LabelSelector.from_spec(
            {"matchLabels": {"g": "multi"}}),
            credentials=AuthCredentials(key_selector="APIKEY"))
        ak.add_k8s_secret_based_identity(Secret(
            namespace="ns", name="svc-key", labels={"g": "multi"},
            annotations={"role": "admin"}, data={"api_key": b"svc-secret"}))
        oidc = OIDC("kc", idp.issuer)
        rule = Any_(
            Pattern("auth.identity.metadata.annotations.role", Operator.EQ,
                    "admin"),
            Pattern("auth.identity.realm_access.roles", Operator.INCL,
                    "admin"))
        cfg_id = "ns/multi"
        pm = PatternMatching(rule, batched_provider=engine.provider_for(cfg_id),
                             evaluator_slot=0)
        engine.apply_snapshot([EngineEntry(
            id=cfg_id, hosts=["multi.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "multi"},
                # distinct priorities: deterministic order in BOTH servers
                identity=[
                    IdentityConfig("api-users", ak, priority=0,
                                   credentials=AuthCredentials(
                                       key_selector="APIKEY")),
                    IdentityConfig("kc", oidc, priority=1),
                ],
                authorization=[AuthorizationConfig("rules", pm)]),
            rules=ConfigRules(name=cfg_id, evaluators=[(None, rule)]))])
        spec = fast_lane_eligible(engine._snapshot.by_id[cfg_id],
                                  engine._snapshot.policy)
        assert spec is not None and len(spec.sources) == 2
        assert not spec.sources[0].dyn and spec.sources[1].dyn

        fe = NativeFrontend(engine, port=0, max_batch=32, window_us=500)
        port = fe.start()
        pyholder, pyt = run_python_server(engine)
        try:
            admin = idp.token()  # realm_access.roles = [admin]
            viewer = idp.token({"realm_access": {"roles": ["viewer"]}})

            # API-key path: pure static variant, no slow lane at all
            r = grpc_call(port, make_req("multi.test",
                                         headers={"authorization": "APIKEY svc-secret"}))
            assert r.status.code == 0
            assert fe.stats()["slow"] == 0
            # JWT path: first sight slow, repeat fast
            r1 = grpc_call(port, make_req("multi.test",
                                          headers={"authorization": f"Bearer {admin}"}))
            r2 = grpc_call(port, make_req("multi.test",
                                          headers={"authorization": f"Bearer {admin}"}))
            assert r1.status.code == 0 and r2.status.code == 0
            assert fe.stats()["dyn_hit"] >= 1

            matrix = [
                make_req("multi.test",
                         headers={"authorization": "APIKEY svc-secret"}),
                make_req("multi.test",
                         headers={"authorization": f"Bearer {admin}"}),
                make_req("multi.test",
                         headers={"authorization": f"Bearer {viewer}"}),  # deny
                make_req("multi.test"),                       # both missing
                make_req("multi.test",
                         headers={"authorization": "APIKEY nope"}),  # invalid+missing
                make_req("multi.test",
                         headers={"authorization": "Bearer junk"}),  # slow verify
            ]
            for i, rq in enumerate(matrix):
                native = response_key(grpc_call(port, rq))
                python = response_key(grpc_call(pyholder["port"], rq))
                assert native == python, f"multi req #{i}: {native} vs {python}"
            # the all-fail answers above were native template decisions
            assert fe.stats()["unauth"] >= 2
        finally:
            pyholder["loop"].call_soon_threadsafe(pyholder["stop"].set)
            pyt.join(timeout=10)
            fe.stop()
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)


def test_response_templates_ride_fast_lane():
    """Response evaluators whose outputs are constant per identity outcome
    (DynamicJSON/Plain over auth.*) keep the fast lane: OK bytes are
    precomputed per credential variant — the 'inject an identity header'
    pattern (round 4).  Differential against the Python server, headers
    AND dynamic metadata."""
    from google.protobuf.json_format import MessageToDict

    from authorino_tpu.evaluators import ResponseConfig
    from authorino_tpu.evaluators.response import DynamicJSON, Plain

    holder, t = run_fake_idp()
    idp = holder["idp"]
    try:
        from authorino_tpu.evaluators.identity import OIDC

        engine = PolicyEngine(max_batch=32, mesh=None)
        ak = APIKey("keys", LabelSelector.from_spec({"matchLabels": {"g": "rt"}}),
                    credentials=AuthCredentials(key_selector="APIKEY"))
        ak.add_k8s_secret_based_identity(Secret(
            namespace="ns", name="alice-key", labels={"g": "rt"},
            annotations={"role": "admin"}, data={"api_key": b"alice-secret"}))
        oidc = OIDC("kc", idp.issuer)
        entries = []
        # anonymous + static/template response headers
        rule = Pattern("request.method", Operator.NEQ, "DELETE")
        pm = PatternMatching(rule, batched_provider=engine.provider_for("ns/r-anon"),
                             evaluator_slot=0)
        entries.append(EngineEntry(
            id="ns/r-anon", hosts=["r-anon.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "r-anon"},
                identity=[IdentityConfig("anon", Noop())],
                authorization=[AuthorizationConfig("rules", pm)],
                response=[
                    ResponseConfig("x-static", Plain(JSONValue(static="on"))),
                    ResponseConfig("x-anon", DynamicJSON([JSONProperty(
                        "anon", JSONValue(pattern="auth.identity.anonymous"))])),
                ]),
            rules=ConfigRules(name="ns/r-anon", evaluators=[(None, rule)])))
        # API key + per-key identity header (template) + dynamic metadata
        entries.append(EngineEntry(
            id="ns/r-key", hosts=["r-key.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "r-key"},
                identity=[IdentityConfig("keys", ak,
                                         credentials=AuthCredentials(
                                             key_selector="APIKEY"))],
                response=[
                    ResponseConfig("x-user", Plain(JSONValue(
                        pattern="secret {auth.identity.metadata.name} "
                                "is {auth.identity.metadata.annotations.role}"))),
                    ResponseConfig("ident", DynamicJSON([JSONProperty(
                        "name",
                        JSONValue(pattern="auth.identity.metadata.name"))]),
                        wrapper="envoyDynamicMetadata"),
                ]),
            rules=None))
        # OIDC + claim-derived header (registered with the token variant)
        entries.append(EngineEntry(
            id="ns/r-jwt", hosts=["r-jwt.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "r-jwt"},
                identity=[IdentityConfig("kc", oidc)],
                response=[ResponseConfig("x-sub", Plain(JSONValue(
                    pattern="auth.identity.sub")))]),
            rules=None))
        engine.apply_snapshot(entries)
        for cfg in ("ns/r-anon", "ns/r-key", "ns/r-jwt"):
            assert fast_lane_eligible(engine._snapshot.by_id[cfg],
                                      engine._snapshot.policy) is not None, cfg

        fe = NativeFrontend(engine, port=0, max_batch=32, window_us=500)
        port = fe.start()
        pyholder, pyt = run_python_server(engine)
        try:
            tok = idp.token({"sub": "john"})
            reqs = [
                make_req("r-anon.test"),
                make_req("r-key.test",
                         headers={"authorization": "APIKEY alice-secret"}),
                make_req("r-jwt.test",
                         headers={"authorization": f"Bearer {tok}"}),
                make_req("r-jwt.test",
                         headers={"authorization": f"Bearer {tok}"}),  # cached
            ]
            for i, rq in enumerate(reqs):
                native = grpc_call(port, rq)
                python = grpc_call(pyholder["port"], rq)
                assert MessageToDict(native) == MessageToDict(python), (
                    f"response req #{i}: {MessageToDict(native)} "
                    f"vs {MessageToDict(python)}")
            # spot-check the injected values themselves
            r = grpc_call(port, reqs[1])
            hdrs = {h.header.key: h.header.value for h in r.ok_response.headers}
            assert hdrs["x-user"] == "secret alice-key is admin"
            assert r.dynamic_metadata.fields["ident"].struct_value.fields[
                "name"].string_value == "alice-key"
            # the repeats were native, not pipeline
            stats = fe.stats()
            assert stats["fast"] >= 4
        finally:
            pyholder["loop"].call_soon_threadsafe(pyholder["stop"].set)
            pyt.join(timeout=10)
            fe.stop()
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)


def test_identity_extensions_ride_fast_lane():
    """auth.*-only identity extensions resolve constantly per credential —
    applied at variant-build time, visible to both the kernel's auth.*
    patterns and the response templates (round 4)."""
    from google.protobuf.json_format import MessageToDict

    from authorino_tpu.evaluators import ResponseConfig
    from authorino_tpu.evaluators.base import IdentityExtension
    from authorino_tpu.evaluators.response import Plain

    engine = PolicyEngine(max_batch=16, mesh=None)
    ak = APIKey("keys", LabelSelector.from_spec({"matchLabels": {"g": "ext"}}),
                credentials=AuthCredentials(key_selector="APIKEY"))
    ak.add_k8s_secret_based_identity(Secret(
        namespace="ns", name="bob-key", labels={"g": "ext"},
        annotations={"level": "9"}, data={"api_key": b"bob-secret"}))
    exts = [
        IdentityExtension("tier", JSONValue(
            pattern="auth.identity.metadata.annotations.level")),
        IdentityExtension("source", JSONValue(static="api-key")),
    ]
    rule = Pattern("auth.identity.tier", Operator.EQ, "9")
    pm = PatternMatching(rule, batched_provider=engine.provider_for("ns/ext"),
                         evaluator_slot=0)
    engine.apply_snapshot([EngineEntry(
        id="ns/ext", hosts=["ext.test"],
        runtime=RuntimeAuthConfig(
            labels={"namespace": "ns", "name": "ext"},
            identity=[IdentityConfig(
                "keys", ak, extended_properties=exts,
                credentials=AuthCredentials(key_selector="APIKEY"))],
            authorization=[AuthorizationConfig("rules", pm)],
            response=[ResponseConfig("x-src", Plain(JSONValue(
                pattern="auth.identity.source")))]),
        rules=ConfigRules(name="ns/ext", evaluators=[(None, rule)]))])
    assert fast_lane_eligible(engine._snapshot.by_id["ns/ext"],
                              engine._snapshot.policy) is not None

    fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
    port = fe.start()
    holder, t = run_python_server(engine)
    try:
        req = make_req("ext.test", headers={"authorization": "APIKEY bob-secret"})
        native = grpc_call(port, req)
        python = grpc_call(holder["port"], req)
        assert MessageToDict(native) == MessageToDict(python)
        assert native.status.code == 0  # pattern over the EXTENDED tier
        hdrs = {h.header.key: h.header.value for h in native.ok_response.headers}
        assert hdrs["x-src"] == "api-key"
        assert fe.stats()["fast"] >= 1 and fe.stats()["slow"] == 0
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)
        fe.stop()


def test_oidc_cache_survives_reconcile_storm():
    """Reconcile swaps drop the verified-token cache (by design: fresh
    snapshot, empty variant maps).  Under a storm of swaps with live OIDC
    traffic, every response must stay correct — misses re-verify and
    re-register, hits serve natively, nothing errors (round 4)."""
    import concurrent.futures

    holder, t = run_fake_idp()
    idp = holder["idp"]
    try:
        engine, oidc = _oidc_engine(idp)
        base_entries = list(engine._snapshot.by_id.values())
        fe = NativeFrontend(engine, port=0, max_batch=32, window_us=500)
        port = fe.start()
        try:
            bearer = {"authorization": f"Bearer {idp.token()}"}
            grpc_call(port, make_req("oidc.test", headers=bearer))  # prime

            stop = threading.Event()
            codes = []

            def loader():
                with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
                    call = ch.unary_unary(
                        "/envoy.service.auth.v3.Authorization/Check",
                        request_serializer=pb.CheckRequest.SerializeToString,
                        response_deserializer=pb.CheckResponse.FromString)
                    req = make_req("oidc.test", headers=bearer)
                    while not stop.is_set():
                        codes.append(call(req, timeout=30).status.code)

            with concurrent.futures.ThreadPoolExecutor(2) as pool:
                futs = [pool.submit(loader) for _ in range(2)]
                adds_seen = [fe.stats()["dyn_add"]]
                for i in range(5):
                    # a real reconcile: new snapshot, cache dropped
                    extra = make_pattern_entry(
                        engine, f"ns/storm-{i}", [f"storm-{i}.test"],
                        Pattern("request.method", Operator.NEQ, "DELETE"))
                    engine.apply_snapshot(base_entries + [extra])
                    time.sleep(0.4)
                    adds_seen.append(fe.stats()["dyn_add"])
                stop.set()
                for f in futs:
                    f.result(timeout=30)
            assert codes and all(c == 0 for c in codes), (
                f"{sum(1 for c in codes if c)} non-OK of {len(codes)}")
            # each swap forced at least one re-registration
            assert adds_seen[-1] >= adds_seen[0] + 3, adds_seen
            assert fe.stats()["dyn_hit"] > 0
        finally:
            fe.stop()
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)


def test_per_request_features_stay_slow():
    """Negative eligibility: anything genuinely per-request must keep the
    slow lane — response templates over request.*, identity extensions
    over request.*, wristbands (per-request signatures)."""
    from authorino_tpu.evaluators import ResponseConfig
    from authorino_tpu.evaluators.base import IdentityExtension
    from authorino_tpu.evaluators.response import Plain

    engine = PolicyEngine(max_batch=8, mesh=None)

    def entry_with(response=None, exts=None):
        rule = Pattern("request.method", Operator.NEQ, "DELETE")
        cfg_id = f"ns/neg-{len(response or [])}-{len(exts or [])}"
        pm = PatternMatching(rule, batched_provider=engine.provider_for(cfg_id),
                             evaluator_slot=0)
        return EngineEntry(
            id=cfg_id, hosts=[f"{cfg_id.split('/')[1]}.test"],
            runtime=RuntimeAuthConfig(
                identity=[IdentityConfig("anon", Noop(),
                                         extended_properties=exts or [])],
                authorization=[AuthorizationConfig("rules", pm)],
                response=response or []),
            rules=ConfigRules(name=cfg_id, evaluators=[(None, rule)]))

    # request.*-templated response → slow
    e1 = entry_with(response=[ResponseConfig(
        "x-path", Plain(JSONValue(pattern="request.path")))])
    # request.*-templated identity extension → slow
    e2 = entry_with(exts=[IdentityExtension(
        "path", JSONValue(pattern="request.path"))])
    # auth.*-only versions of both → fast
    e3 = entry_with(
        response=[ResponseConfig("x-anon", Plain(JSONValue(
            pattern="auth.identity.anonymous")))],
        exts=[IdentityExtension("src", JSONValue(static="anon"))])
    engine.apply_snapshot([e1, e2, e3])
    policy = engine._snapshot.policy
    assert fast_lane_eligible(e1, policy) is None
    assert fast_lane_eligible(e2, policy) is None
    assert fast_lane_eligible(e3, policy) is not None


def test_oauth2_cache_opt_in_rides_fast_lane():
    """OAuth2 introspection identities stay slow by default (introspection
    IS the revocation check) — but an explicit `cache` opt-in keyed by the
    credential header makes the dyn lane honor the user's own TTL
    semantics (round 4): hits serve natively, entries expire at cache.ttl,
    and post-TTL revocation is enforced."""
    from authorino_tpu.evaluators.cache import EvaluatorCache
    from authorino_tpu.evaluators.identity import OAuth2

    holder, t = run_fake_idp()
    idp = holder["idp"]
    try:
        engine = PolicyEngine(max_batch=16, mesh=None)
        url = f"{idp.issuer}/introspect"
        no_cache = OAuth2("oa", url, "cid", "csec")
        cached = OAuth2("oa", url, "cid", "csec")
        entries = [
            EngineEntry(
                id="ns/oauth-nocache", hosts=["oauth-nocache.test"],
                runtime=RuntimeAuthConfig(
                    labels={"namespace": "ns", "name": "oauth-nocache"},
                    identity=[IdentityConfig("oa", no_cache)]),
                rules=None),
            EngineEntry(
                id="ns/oauth", hosts=["oauth.test"],
                runtime=RuntimeAuthConfig(
                    labels={"namespace": "ns", "name": "oauth"},
                    identity=[IdentityConfig(
                        "oa", cached,
                        cache=EvaluatorCache(JSONValue(
                            pattern="request.headers.authorization"), 1))]),
                rules=None),
        ]
        engine.apply_snapshot(entries)
        snap = engine._snapshot
        assert fast_lane_eligible(snap.by_id["ns/oauth-nocache"],
                                  snap.policy) is None
        spec = fast_lane_eligible(snap.by_id["ns/oauth"], snap.policy)
        assert spec is not None and spec.sources[0].dyn
        assert spec.sources[0].ttl_cap == 1.0

        fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
        port = fe.start()
        try:
            hdr = {"authorization": "Bearer opaque-token-1"}
            r1 = grpc_call(port, make_req("oauth.test", headers=hdr))
            t_reg = time.monotonic()
            assert r1.status.code == 0  # slow: introspected + registered
            r2 = grpc_call(port, make_req("oauth.test", headers=hdr))
            assert r2.status.code == 0
            assert fe.stats()["dyn_hit"] >= 1
            # the no-cache config always introspects (slow lane)
            slow_before = fe.stats()["slow"]
            n1 = grpc_call(port, make_req("oauth-nocache.test", headers=hdr))
            n2 = grpc_call(port, make_req("oauth-nocache.test", headers=hdr))
            assert n1.status.code == 0 and n2.status.code == 0
            assert fe.stats()["slow"] >= slow_before + 2

            # revocation takes effect once the user's TTL lapses: the dyn
            # entry AND the pipeline cache both expire at cache.ttl = 1s
            idp.active_tokens["opaque-token-1"] = {"active": False}
            t_revoked = time.monotonic()
            r3 = grpc_call(port, make_req("oauth.test", headers=hdr))
            if time.monotonic() - t_reg < 0.8:
                # still inside the opted-in window (guard: a slow CI stall
                # past the 1s TTL would legitimately re-introspect)
                assert r3.status.code == 0
            time.sleep(max(0.0, 1.3 - (time.monotonic() - t_revoked)))
            r4 = grpc_call(port, make_req("oauth.test", headers=hdr))
            assert r4.status.code == 16  # re-introspected: revoked
        finally:
            fe.stop()
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)


def test_k8s_tokenreview_cache_opt_in_rides_fast_lane():
    """K8s TokenReview under an explicit cache opt-in (and explicit
    audiences — the default audience is the request host, which would vary
    per request): first review slow, repeats native, patterns over the
    reviewed user resolve from the cached identity."""
    from authorino_tpu.evaluators.cache import EvaluatorCache
    from authorino_tpu.evaluators.identity import KubernetesAuth
    from authorino_tpu.k8s import InMemoryCluster

    cluster = InMemoryCluster()
    cluster.token_reviews["sa-token"] = {"status": {
        "authenticated": True,
        "user": {"username": "system:serviceaccount:ns:app",
                 "groups": ["system:authenticated"]}}}
    engine = PolicyEngine(max_batch=16, mesh=None)
    ka = KubernetesAuth("k8s", audiences=["talker-api"], cluster=cluster)
    rule = Pattern("auth.identity.username", Operator.EQ,
                   "system:serviceaccount:ns:app")
    pm = PatternMatching(rule, batched_provider=engine.provider_for("ns/k8s"),
                         evaluator_slot=0)
    entries = [
        EngineEntry(
            id="ns/k8s", hosts=["k8s.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "k8s"},
                identity=[IdentityConfig(
                    "k8s", ka,
                    cache=EvaluatorCache(JSONValue(
                        pattern="request.headers.authorization"), 60))],
                authorization=[AuthorizationConfig("rules", pm)]),
            rules=ConfigRules(name="ns/k8s", evaluators=[(None, rule)])),
        # no explicit audiences → host-dependent review → ineligible
        EngineEntry(
            id="ns/k8s-hostaud", hosts=["k8s-hostaud.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "k8s-hostaud"},
                identity=[IdentityConfig(
                    "k8s", KubernetesAuth("k8s", cluster=cluster),
                    cache=EvaluatorCache(JSONValue(
                        pattern="request.headers.authorization"), 60))]),
            rules=None),
    ]
    engine.apply_snapshot(entries)
    snap = engine._snapshot
    assert fast_lane_eligible(snap.by_id["ns/k8s"], snap.policy) is not None
    assert fast_lane_eligible(snap.by_id["ns/k8s-hostaud"], snap.policy) is None

    fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
    port = fe.start()
    holder, t = run_python_server(engine)
    try:
        hdr = {"authorization": "Bearer sa-token"}
        r1 = grpc_call(port, make_req("k8s.test", headers=hdr))
        r2 = grpc_call(port, make_req("k8s.test", headers=hdr))
        assert r1.status.code == 0 and r2.status.code == 0
        assert fe.stats()["dyn_hit"] >= 1
        for rq in (make_req("k8s.test", headers=hdr),
                   make_req("k8s.test", headers={"authorization": "Bearer bad"}),
                   make_req("k8s.test")):
            native = response_key(grpc_call(port, rq))
            python = response_key(grpc_call(holder["port"], rq))
            assert native == python, (native, python)
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)
        fe.stop()


def test_identity_templated_deny_rides_fast_lane():
    """denyWith.unauthorized templated over the identity precomputes per
    credential variant (round 4): denial messages naming the caller serve
    natively, byte-exact with the pipeline; request.*-templated denials
    still route slow."""
    from google.protobuf.json_format import MessageToDict

    engine = PolicyEngine(max_batch=16, mesh=None)
    ak = APIKey("keys", LabelSelector.from_spec({"matchLabels": {"g": "dt"}}),
                credentials=AuthCredentials(key_selector="APIKEY"))
    ak.add_k8s_secret_based_identity(Secret(
        namespace="ns", name="eve-key", labels={"g": "dt"},
        annotations={"role": "viewer"}, data={"api_key": b"eve-secret"}))
    rule = Pattern("auth.identity.metadata.annotations.role", Operator.EQ,
                   "admin")

    def entry(cfg_id, host, deny_pattern):
        pm = PatternMatching(rule, batched_provider=engine.provider_for(cfg_id),
                             evaluator_slot=0)
        return EngineEntry(
            id=cfg_id, hosts=[host],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": cfg_id.split("/")[1]},
                identity=[IdentityConfig("keys", ak,
                                         credentials=AuthCredentials(
                                             key_selector="APIKEY"))],
                authorization=[AuthorizationConfig("rules", pm)],
                deny_with=DenyWith(unauthorized=DenyWithValues(
                    code=403,
                    message=JSONValue(pattern=deny_pattern),
                    headers=[JSONProperty("x-denied-user", JSONValue(
                        pattern="auth.identity.metadata.name"))]))),
            rules=ConfigRules(name=cfg_id, evaluators=[(None, rule)]))

    e_auth = entry("ns/deny-tmpl", "deny-tmpl.test",
                   "role {auth.identity.metadata.annotations.role} "
                   "may not pass")
    e_req = entry("ns/deny-req", "deny-req.test", "request.path")
    engine.apply_snapshot([e_auth, e_req])
    policy = engine._snapshot.policy
    assert fast_lane_eligible(e_auth, policy) is not None
    assert fast_lane_eligible(e_req, policy) is None  # request-templated

    fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
    port = fe.start()
    holder, t = run_python_server(engine)
    try:
        hdr = {"authorization": "APIKEY eve-secret"}
        native = grpc_call(port, make_req("deny-tmpl.test", headers=hdr))
        python = grpc_call(holder["port"], make_req("deny-tmpl.test", headers=hdr))
        assert MessageToDict(native) == MessageToDict(python)
        assert native.status.code == 7
        assert native.denied_response.status.code == 403
        assert native.denied_response.body == ""
        hdrs = {h.header.key: h.header.value
                for h in native.denied_response.headers}
        assert hdrs["x-denied-user"] == "eve-key"
        # the denial itself was a native fast-lane decision
        assert fe.stats()["fast"] >= 1 and fe.stats()["slow"] == 0
        # missing credential: all-fail template still byte-exact
        n2 = grpc_call(port, make_req("deny-tmpl.test"))
        p2 = grpc_call(holder["port"], make_req("deny-tmpl.test"))
        assert MessageToDict(n2) == MessageToDict(p2)
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)
        fe.stop()


def test_hybrid_lane_procedural_rego():
    """A config mixing kernel patterns with PROCEDURAL (non-lowerable) Rego
    rides the hybrid lane (round 5): kernel denials answer natively, kernel
    passes hand the raw request to the slow pipeline — which re-runs the
    full phase (∧-verdict, so re-deciding covered patterns is correct).
    The reference evaluates OPA inline in the same server
    (ref pkg/evaluators/authorization/opa.go:86-117)."""
    engine = PolicyEngine(max_batch=16, mesh=None)
    rule = Pattern("request.headers.x-tier", Operator.EQ, "gold")
    pm = PatternMatching(rule, batched_provider=engine.provider_for("ns/hyb"),
                         evaluator_slot=0)
    opa = OPA("ns/hyb/rego",
              inline_rego='allow { count(input.request.path) > 5 }')
    assert opa.lowered_verdict() is None  # genuinely procedural
    engine.apply_snapshot([EngineEntry(
        id="ns/hyb", hosts=["hyb.test"],
        runtime=RuntimeAuthConfig(
            labels={"namespace": "ns", "name": "hyb"},
            identity=[IdentityConfig("anon", Noop())],
            authorization=[AuthorizationConfig("rules", pm),
                           AuthorizationConfig("rego", opa)]),
        rules=ConfigRules(name="ns/hyb", evaluators=[(None, rule)]))])
    snap = engine._snapshot
    spec = fast_lane_eligible(snap.by_id["ns/hyb"], snap.policy)
    assert spec is not None and spec.hybrid and spec.has_batch

    def hyb_total():
        from prometheus_client import REGISTRY

        return sum(
            s.value for m in REGISTRY.collect()
            if m.name == "auth_server_authconfig"
            for s in m.samples
            if s.name == "auth_server_authconfig_total"
            and s.labels.get("namespace") == "ns"
            and s.labels.get("authconfig") == "hyb")

    fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
    port = fe.start()
    holder, t = run_python_server(engine)
    try:
        base_total = hyb_total()
        # kernel deny: answered natively, zero slow-lane work
        d = grpc_call(port, make_req("hyb.test", path="/abcdefg",
                                     headers={"x-tier": "wood"}))
        assert d.status.code == 7
        s0 = fe.stats()
        assert s0["fast"] >= 1 and s0["slow"] == 0 and s0["hybrid"] == 0
        # kernel pass + rego deny: handed off, denied by the pipeline
        d2 = grpc_call(port, make_req("hyb.test", path="/ab",
                                      headers={"x-tier": "gold"}))
        assert d2.status.code == 7
        s1 = fe.stats()
        assert s1["hybrid"] == 1 and s1["slow"] == 1
        # kernel pass + rego pass: handed off, allowed by the pipeline
        ok = grpc_call(port, make_req("hyb.test", path="/abcdefg",
                                      headers={"x-tier": "gold"}))
        assert ok.status.code == 0
        assert fe.stats()["hybrid"] == 2
        # one authconfig_total per REQUEST: kernel-allowed handoffs are
        # counted by the pipeline only (no dispatch+pipeline double count)
        assert hyb_total() - base_total == 3
        # differential vs the Python server across the whole matrix
        matrix = [
            make_req("hyb.test", path=p, headers=h)
            for p in ("/ab", "/abcdefg")
            for h in ({"x-tier": "gold"}, {"x-tier": "wood"}, {})
        ]
        for i, rq in enumerate(matrix):
            native = response_key(grpc_call(port, rq))
            python = response_key(grpc_call(holder["port"], rq))
            assert native == python, f"hybrid req #{i}: {native} vs {python}"
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)
        fe.stop()


def test_hybrid_priority_order_guard():
    """Kernel pre-deny must not preempt an uncovered evaluator the pipeline
    would have failed in an EARLIER priority bucket (its denial could
    differ) — such configs stay fully slow."""
    engine = PolicyEngine(max_batch=16, mesh=None)
    rule = Pattern("request.headers.x-tier", Operator.EQ, "gold")
    pm = PatternMatching(rule, batched_provider=engine.provider_for("ns/hp"),
                         evaluator_slot=0)
    opa = OPA("ns/hp/rego",
              inline_rego='allow { count(input.request.path) > 5 }')
    engine.apply_snapshot([EngineEntry(
        id="ns/hp", hosts=["hp.test"],
        runtime=RuntimeAuthConfig(
            identity=[IdentityConfig("anon", Noop())],
            authorization=[
                AuthorizationConfig("rules", pm, priority=1),
                AuthorizationConfig("rego", opa, priority=0)]),
        rules=ConfigRules(name="ns/hp", evaluators=[(None, rule)]))])
    snap = engine._snapshot
    assert fast_lane_eligible(snap.by_id["ns/hp"], snap.policy) is None


def test_hybrid_allows_arbitrary_responses():
    """Hybrid OKs run the full pipeline, so per-request response templates
    (which disqualify the FULL fast lane) are fine on hybrid configs."""
    from authorino_tpu.evaluators import ResponseConfig
    from authorino_tpu.evaluators.response import Plain

    engine = PolicyEngine(max_batch=16, mesh=None)
    rule = Pattern("request.headers.x-tier", Operator.EQ, "gold")
    pm = PatternMatching(rule, batched_provider=engine.provider_for("ns/hr"),
                         evaluator_slot=0)
    opa = OPA("ns/hr/rego",
              inline_rego='allow { count(input.request.path) > 5 }')
    engine.apply_snapshot([EngineEntry(
        id="ns/hr", hosts=["hr.test"],
        runtime=RuntimeAuthConfig(
            labels={"namespace": "ns", "name": "hr"},
            identity=[IdentityConfig("anon", Noop())],
            authorization=[AuthorizationConfig("rules", pm),
                           AuthorizationConfig("rego", opa)],
            response=[ResponseConfig(
                "x-path", Plain(JSONValue(pattern="request.path")))]),
        rules=ConfigRules(name="ns/hr", evaluators=[(None, rule)]))])
    snap = engine._snapshot
    spec = fast_lane_eligible(snap.by_id["ns/hr"], snap.policy)
    assert spec is not None and spec.hybrid

    fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
    port = fe.start()
    holder, t = run_python_server(engine)
    try:
        ok = grpc_call(port, make_req("hr.test", path="/abcdefg",
                                      headers={"x-tier": "gold"}))
        assert ok.status.code == 0
        hdrs = {h.header.key: h.header.value
                for h in ok.ok_response.headers}
        assert hdrs.get("x-path") == "/abcdefg"
        python = grpc_call(holder["port"], make_req(
            "hr.test", path="/abcdefg", headers={"x-tier": "gold"}))
        assert response_key(ok) == response_key(python)
        # kernel deny still answers natively
        d = grpc_call(port, make_req("hr.test", path="/abcdefg",
                                     headers={"x-tier": "wood"}))
        pd = grpc_call(holder["port"], make_req(
            "hr.test", path="/abcdefg", headers={"x-tier": "wood"}))
        assert response_key(d) == response_key(pd)
        assert fe.stats()["slow"] == fe.stats()["hybrid"]
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)
        fe.stop()


def test_stop_drains_inflight_slow_requests():
    """fe.stop() while slow-lane requests are in flight must complete them
    before the loop closes — a cancelled handler would leave its client
    hanging until the gRPC deadline (round-4 review finding)."""
    import concurrent.futures

    from authorino_tpu.evaluators import MetadataConfig

    class SleepyMeta:
        async def call(self, pipeline):
            await asyncio.sleep(1.0)
            return {}

    engine = PolicyEngine(max_batch=16, mesh=None)
    engine.apply_snapshot([EngineEntry(
        id="ns/sleepy2", hosts=["sleepy2.test"],
        runtime=RuntimeAuthConfig(
            identity=[IdentityConfig("anon", Noop())],
            metadata=[MetadataConfig("m", SleepyMeta())]),
        rules=None)])
    fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
    port = fe.start()
    stopped = False
    try:
        with concurrent.futures.ThreadPoolExecutor(1) as pool:
            fut = pool.submit(grpc_call, port, make_req("sleepy2.test"))
            deadline = time.monotonic() + 5
            while fe.stats().get("slow", 0) < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            t0 = time.monotonic()
            fe.stop()
            stopped = True
            # the in-flight request still answers (drained, not cancelled)
            resp = fut.result(timeout=10)
            assert resp.status.code == 0
            assert time.monotonic() - t0 < 8
    finally:
        if not stopped:
            fe.stop()


def test_mtls_fast_lane_cert_cache():
    """mTLS identities ride the fast lane too (round 4): the forwarded
    client certificate is the credential key of the verified-credential
    cache — first sight verifies in the slow lane, repeats serve natively,
    subject-based patterns resolve from the cached identity."""
    import urllib.parse

    from test_evaluators import TestMTLS

    from authorino_tpu.k8s import InMemoryCluster

    ca_pem, leaf_pem = TestMTLS()._make_ca_and_cert(valid=True)
    _, rogue_pem = TestMTLS()._make_ca_and_cert(valid=False)
    cluster = InMemoryCluster()
    cluster.put_secret(Secret(name="ca", namespace="ns", labels={"app": "mtls"},
                              data={"ca.crt": ca_pem}))
    mtls = __import__("authorino_tpu.evaluators.identity",
                      fromlist=["MTLS"]).MTLS(
        "mtls", LabelSelector.parse("app=mtls"), cluster=cluster)
    asyncio.run(mtls.load_secrets())

    engine = PolicyEngine(max_batch=16, mesh=None)
    rule = Pattern("auth.identity.Organization", Operator.EQ, "acme")
    pm = PatternMatching(rule, batched_provider=engine.provider_for("ns/mtls"),
                         evaluator_slot=0)
    entries = [
        EngineEntry(
            id="ns/mtls", hosts=["mtls.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "mtls"},
                identity=[IdentityConfig("mtls", mtls)],
                authorization=[AuthorizationConfig("rules", pm)]),
            rules=ConfigRules(name="ns/mtls", evaluators=[(None, rule)])),
        EngineEntry(  # identity-only: cert validity IS the decision
            id="ns/mtls-only", hosts=["mtls-only.test"],
            runtime=RuntimeAuthConfig(
                labels={"namespace": "ns", "name": "mtls-only"},
                identity=[IdentityConfig("mtls", mtls)]),
            rules=None),
    ]
    engine.apply_snapshot(entries)
    spec = fast_lane_eligible(engine._snapshot.by_id["ns/mtls"],
                              engine._snapshot.policy)
    assert spec is not None and len(spec.sources) == 1
    assert spec.sources[0].dyn and spec.sources[0].cred_kind == 5

    fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
    port = fe.start()
    holder, t = run_python_server(engine)
    try:
        def cert_req(host, pem=None):
            req = make_req(host)
            if pem is not None:
                req.attributes.source.certificate = urllib.parse.quote(pem)
            return req

        r1 = grpc_call(port, cert_req("mtls.test", leaf_pem))
        assert r1.status.code == 0
        assert fe.stats()["dyn_add"] >= 1
        r2 = grpc_call(port, cert_req("mtls.test", leaf_pem))
        assert r2.status.code == 0
        assert fe.stats()["dyn_hit"] >= 1
        o1 = grpc_call(port, cert_req("mtls-only.test", leaf_pem))
        o2 = grpc_call(port, cert_req("mtls-only.test", leaf_pem))
        assert o1.status.code == 0 and o2.status.code == 0

        matrix = [
            cert_req("mtls.test", leaf_pem),
            cert_req("mtls.test", rogue_pem),   # unknown authority → slow
            cert_req("mtls.test"),              # missing cert → static unauth
            cert_req("mtls-only.test", leaf_pem),
            cert_req("mtls-only.test"),
        ]
        for i, rq in enumerate(matrix):
            native = response_key(grpc_call(port, rq))
            python = response_key(grpc_call(holder["port"], rq))
            assert native == python, f"mtls req #{i}: {native} vs {python}"

        # CA rotation: the secret reconciler's in-place mutation notifies
        # swap listeners → fresh snapshot, cache dropped, old cert rejected
        new_ca, _ = TestMTLS()._make_ca_and_cert(valid=True)
        mtls.revoke_k8s_secret_based_identity("ns", "ca")
        mtls.add_k8s_secret_based_identity(Secret(
            name="ca", namespace="ns", labels={"app": "mtls"},
            data={"ca.crt": new_ca}))
        engine.notify_swap_listeners()
        wait_for_snap_retire(fe)
        r3 = grpc_call(port, cert_req("mtls.test", leaf_pem))
        assert r3.status.code == 16  # UNAUTHENTICATED: unknown authority now
    finally:
        holder["loop"].call_soon_threadsafe(holder["stop"].set)
        t.join(timeout=10)
        fe.stop()


def test_slow_lane_no_head_of_line_blocking():
    """A straggling slow-lane request (slow metadata backend) must not
    delay unrelated slow-lane requests queued behind it: admission is
    continuous, not batch-gather convoys (VERDICT r3 weak #7)."""
    import concurrent.futures

    from authorino_tpu.evaluators import MetadataConfig

    class SleepyMeta:
        async def call(self, pipeline):
            await asyncio.sleep(2.5)
            return {}

    engine = PolicyEngine(max_batch=16, mesh=None)
    entries = [
        EngineEntry(
            id="ns/sleepy", hosts=["sleepy.test"],
            runtime=RuntimeAuthConfig(
                identity=[IdentityConfig("anon", Noop())],
                metadata=[MetadataConfig("m", SleepyMeta())]),
            rules=None),
        # quick but slow-lane (templated denyWith)
        make_pattern_entry(
            engine, "ns/quick", ["quick.test"],
            Pattern("request.method", Operator.EQ, "GET"),
            deny_with=DenyWith(unauthorized=DenyWithValues(
                message=JSONValue(pattern="request.path")))),
    ]
    engine.apply_snapshot(entries)
    fe = NativeFrontend(engine, port=0, max_batch=16, window_us=500)
    port = fe.start()
    try:
        with concurrent.futures.ThreadPoolExecutor(2) as pool:
            straggler = pool.submit(grpc_call, port, make_req("sleepy.test"))
            deadline = time.monotonic() + 5
            while fe.stats().get("slow", 0) < 1 and time.monotonic() < deadline:
                time.sleep(0.02)  # straggler admitted into the slow lane
            t0 = time.monotonic()
            quick = grpc_call(port, make_req("quick.test"))
            quick_s = time.monotonic() - t0
            assert quick.status.code == 0
            assert quick_s < 1.5, f"head-of-line blocked: {quick_s:.2f}s"
            assert straggler.result(timeout=10).status.code == 0
    finally:
        fe.stop()


@pytest.fixture(scope="module")
def stack():
    engine = build_engine()
    fe = NativeFrontend(engine, port=0, max_batch=64, window_us=500)
    native_port = fe.start()
    holder, t = run_python_server(engine)
    yield engine, fe, native_port, holder["port"]
    holder["loop"].call_soon_threadsafe(holder["stop"].set)
    t.join(timeout=10)
    fe.stop()


def test_differential_vs_python_server(stack):
    _, fe, native_port, py_port = stack
    for i, req in enumerate(REQUESTS):
        native = response_key(grpc_call(native_port, req))
        python = response_key(grpc_call(py_port, req))
        assert native == python, f"request #{i} diverged: {native} vs {python}"
    stats = fe.stats()
    assert stats["fast"] > 0, "fast lane never engaged"
    assert stats["slow"] > 0, "slow lane never engaged"


def test_fast_lane_classification(stack):
    engine, _, _, _ = stack
    snap = engine._snapshot
    by_id = snap.by_id
    policy = snap.policy
    assert fast_lane_eligible(by_id["ns/fast-eq"], policy) is not None
    assert fast_lane_eligible(by_id["ns/fast-cond"], policy) is not None
    assert fast_lane_eligible(by_id["ns/fast-rx"], policy) is not None
    assert fast_lane_eligible(by_id["ns/fast-deny"], policy) is not None
    # API-key identity-only: pure credential-map decision, no kernel
    spec = fast_lane_eligible(by_id["ns/fast-keyonly"], policy)
    assert spec is not None and not spec.has_batch
    assert len(spec.sources) == 1 and spec.sources[0].cred_kind == 1
    assert any(k == b"sekret" for k, _, _ in spec.sources[0].variants)
    # API-key + auth.identity.* patterns: per-key K_CONST plan variants
    spec2 = fast_lane_eligible(by_id["ns/fast-key"], policy)
    assert spec2 is not None and spec2.has_batch
    assert spec2.sources[0].cred_kind == 2
    assert spec2.sources[0].cred_key == "x-api-key"
    assert len(spec2.sources[0].variants) == 2
    assert all(vplans for _, vplans, _ in spec2.sources[0].variants)
    # templated denyWith: per-request resolution → slow lane
    assert fast_lane_eligible(by_id["ns/slow-tmpl"], policy) is None
    # mixed pattern + lowered Rego: BOTH evaluators kernel-decided (r5)
    spec3 = fast_lane_eligible(by_id["ns/fast-rego"], policy)
    assert spec3 is not None and spec3.has_batch


def test_lowered_rego_rides_fast_lane(stack):
    """Mixed pattern+Rego traffic must be served natively — zero slow-lane
    handoffs for the lowered config (BASELINE class 5, VERDICT r4 item 1)."""
    _, fe, native_port, _ = stack
    before = fe.stats()
    for hdrs, method in [({"x-tier": "gold"}, "GET"),
                         ({"x-tier": "gold"}, "DELETE"),
                         ({"x-tier": "gold", "x-root": "true"}, "DELETE"),
                         ({"x-tier": "wood"}, "GET")]:
        grpc_call(native_port, make_req("fast-rego.test", method=method,
                                        headers=hdrs))
    after = fe.stats()
    assert after["fast"] - before["fast"] == 4
    assert after["slow"] == before["slow"]


def test_prewarm_covers_bucket_grid(stack):
    """Every (batch_pad, byte_eff) jit variant compiles off the serving
    path at swap time (VERDICT r3 weak #1)."""
    _, fe, _, _ = stack
    assert fe.wait_warm(180)
    with fe._lock:
        rec = fe._snaps[fe._next_snap_id - 1]
    assert rec.params is not None
    assert set(fe._bucket_grid(rec)) <= rec.warm


def test_swap_under_load_never_compiles_on_live_requests(stack):
    """Reconcile swaps with NEW corpus shapes must keep serving from
    warmed jit variants only: the previous snapshot serves until the new
    one's largest bucket is compiled, then dispatch rounds up to warmed
    shapes.  A pick outside rec.warm would be an inline XLA compile on a
    live request — the exact source of BENCH_r03 trial 1's 3.3s p99."""
    engine, fe, native_port, _ = stack
    assert fe.wait_warm(180)
    base_entries = list(engine._snapshot.by_id.values())

    picked_unwarmed = []
    orig = fe._pick_warm_shape

    def spy(rec, count, eff):
        out = orig(rec, count, eff)
        if rec.warm and out not in rec.warm:
            picked_unwarmed.append(out)
        return out

    fe._pick_warm_shape = spy
    stop = threading.Event()
    errs, lat = [], []

    def loader():
        with grpc.insecure_channel(f"127.0.0.1:{native_port}") as ch:
            call = ch.unary_unary(
                "/envoy.service.auth.v3.Authorization/Check",
                request_serializer=pb.CheckRequest.SerializeToString,
                response_deserializer=pb.CheckResponse.FromString)
            req = make_req("fast-eq.test", headers={"x-org": "acme"})
            while not stop.is_set():
                t0 = time.monotonic()
                try:
                    call(req, timeout=60)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)
                    return
                lat.append(time.monotonic() - t0)

    t = threading.Thread(target=loader)
    t.start()
    try:
        time.sleep(0.3)
        for i in range(2):
            # a brand-new selector changes the operand shapes → the swap
            # gate must compile the new variants before going live
            extra = make_pattern_entry(
                engine, f"ns/extra-{i}", [f"extra-{i}.test"],
                Pattern(f"request.headers.x-fresh-{i}", Operator.EQ, "v"))
            engine.apply_snapshot(base_entries + [extra])
            time.sleep(0.3)
        assert fe.wait_warm(180)
        time.sleep(0.5)
    finally:
        stop.set()
        t.join(20)
        fe._pick_warm_shape = orig
        engine.apply_snapshot(base_entries)  # restore the module corpus
        wait_for_snap_retire(fe)
    assert not errs
    assert len(lat) > 20
    assert not picked_unwarmed, f"inline compiles on live requests: {picked_unwarmed}"
    lat.sort()
    assert lat[int(len(lat) * 0.99)] < 5.0


def test_api_key_rotation_rebuilds_fast_lane(stack):
    """Live add/revoke of an API key (the secret reconciler's in-place
    mutation, ref controllers/secret_controller.go:108-130) must rebuild the
    C++ credential variants via the swap-listener notification."""
    engine, fe, native_port, _ = stack
    ev = engine._snapshot.by_id["ns/fast-keyonly"].runtime.identity[0].evaluator
    ev.add_k8s_secret_based_identity(Secret(
        namespace="ns", name="k2", labels={"g": "t"}, data={"api_key": b"fresh"}))
    engine.notify_swap_listeners()
    wait_for_snap_retire(fe)
    ok = grpc_call(native_port,
                   make_req("slow-key.test", headers={"authorization": "APIKEY fresh"}))
    assert ok.status.code == 0
    ev.revoke_k8s_secret_based_identity("ns", "k2")
    engine.notify_swap_listeners()
    wait_for_snap_retire(fe)
    deny = grpc_call(native_port,
                     make_req("slow-key.test", headers={"authorization": "APIKEY fresh"}))
    assert deny.status.code == 16  # UNAUTHENTICATED
    stats = fe.stats()
    assert stats["direct_ok"] > 0 and stats["unauth"] > 0


def test_dfa_overflow_rides_fast_lane(stack):
    """Values longer than the device byte tensor run the same DFA on the
    C++ host — still the fast lane, still exact."""
    _, fe, native_port, py_port = stack
    before = fe.stats()["dfa_overflow"]
    req = make_req("fast-rx.test", path="/api/v1/ok" + "b" * 200)
    assert response_key(grpc_call(native_port, req)) == response_key(grpc_call(py_port, req))
    assert fe.stats()["dfa_overflow"] > before


def test_health_and_unimplemented(stack):
    _, _, native_port, _ = stack
    hreq = protos.health_pb2.HealthCheckRequest()
    with grpc.insecure_channel(f"127.0.0.1:{native_port}") as ch:
        health = ch.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=hreq.SerializeToString,
            response_deserializer=protos.health_pb2.HealthCheckResponse.FromString,
        )(hreq, timeout=10)
        assert health.status == protos.health_pb2.HealthCheckResponse.SERVING
        with pytest.raises(grpc.RpcError) as err:
            ch.unary_unary(
                "/envoy.service.auth.v3.Authorization/Nope",
                request_serializer=pb.CheckRequest.SerializeToString,
                response_deserializer=pb.CheckResponse.FromString,
            )(make_req("fast-eq.test"), timeout=10)
        assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_invalid_request(stack):
    """CheckRequest without http attributes → INVALID_ARGUMENT CheckResponse
    (ref pkg/service/auth.go:242-255)."""
    _, _, native_port, py_port = stack
    req = pb.CheckRequest()
    assert response_key(grpc_call(native_port, req)) == response_key(grpc_call(py_port, req))


def test_snapshot_swap_retires_old(stack):
    engine, fe, native_port, _ = stack
    rule = Pattern("request.headers.x-new", Operator.EQ, "v2")
    cfg_id = "ns/swapped"
    pm = PatternMatching(rule, batched_provider=engine.provider_for(cfg_id),
                         evaluator_slot=0)
    runtime = RuntimeAuthConfig(identity=[IdentityConfig("anon", Noop())],
                                authorization=[AuthorizationConfig("rules", pm)])
    old_entries = list(engine._snapshot.by_id.values())
    engine.apply_snapshot(old_entries + [
        EngineEntry(id=cfg_id, hosts=["swapped.test"], runtime=runtime,
                    rules=ConfigRules(name=cfg_id, evaluators=[(None, rule)]))])
    resp = grpc_call(native_port, make_req("swapped.test", headers={"x-new": "v2"}))
    assert resp.status.code == 0
    resp = grpc_call(native_port, make_req("swapped.test", headers={"x-new": "v1"}))
    assert resp.status.code == 7
    # old snapshots retire once their batches drain
    wait_for_snap_retire(fe)


def test_swap_storm_under_load(stack):
    """Reconcile-time snapshot swaps must never drop or corrupt in-flight
    wire traffic: fire concurrent Check()s at a config that is identical in
    every snapshot while the engine swaps corpora repeatedly; every
    response must stay deterministic and old snapshots must all retire."""
    engine, fe, native_port, _ = stack
    base_entries = list(engine._snapshot.by_id.values())

    errors = []
    done = threading.Event()
    counts = {"ok": 0, "deny": 0}

    def worker(allow: bool):
        req = make_req("fast-eq.test",
                       headers={"x-org": "acme" if allow else "evil"})
        with grpc.insecure_channel(f"127.0.0.1:{native_port}") as ch:
            call = ch.unary_unary(
                "/envoy.service.auth.v3.Authorization/Check",
                request_serializer=pb.CheckRequest.SerializeToString,
                response_deserializer=pb.CheckResponse.FromString)
            while not done.is_set():
                try:
                    resp = call(req, timeout=10)
                    want = 0 if allow else 7
                    if resp.status.code != want:
                        errors.append((allow, resp.status.code))
                    counts["ok" if allow else "deny"] += 1
                except Exception as e:  # noqa: BLE001
                    errors.append((allow, repr(e)))

    threads = [threading.Thread(target=worker, args=(i % 2 == 0,))
               for i in range(4)]
    for t in threads:
        t.start()
    # churn: each swap adds/removes a throwaway config; fast-eq is identical
    # in every snapshot so worker expectations never change
    for i in range(10):
        extra = []
        if i % 2 == 0:
            rule = Pattern("request.headers.x-tmp", Operator.EQ, f"v{i}")
            cfg_id = f"ns/tmp-{i}"
            pm = PatternMatching(rule, batched_provider=engine.provider_for(cfg_id),
                                 evaluator_slot=0)
            extra = [EngineEntry(
                id=cfg_id, hosts=[f"tmp-{i}.test"],
                runtime=RuntimeAuthConfig(
                    identity=[IdentityConfig("anon", Noop())],
                    authorization=[AuthorizationConfig("rules", pm)]),
                rules=ConfigRules(name=cfg_id, evaluators=[(None, rule)]))]
        engine.apply_snapshot(base_entries + extra)
        time.sleep(0.05)
    time.sleep(0.3)
    done.set()
    for t in threads:
        t.join(timeout=20)

    assert not errors, errors[:5]
    assert counts["ok"] > 5 and counts["deny"] > 5, counts
    # every superseded snapshot drains and retires
    wait_for_snap_retire(fe)


def test_fast_lane_metrics_labeled_per_config(stack):
    """Fast-lane decisions bump auth_server_authconfig_* with the SAME
    namespace/name labels the pipeline uses (ref auth_pipeline.go:26-36)."""
    prom = pytest.importorskip("prometheus_client")

    def sample(name, labels):
        v = prom.REGISTRY.get_sample_value(name, labels)
        return v or 0.0

    _, _, native_port, _ = stack
    base_total = sample("auth_server_authconfig_total",
                        {"namespace": "ns", "authconfig": "fast-eq"})
    base_ok = sample("auth_server_authconfig_response_status_total",
                     {"namespace": "ns", "authconfig": "fast-eq", "status": "OK"})
    base_deny = sample("auth_server_authconfig_response_status_total",
                       {"namespace": "ns", "authconfig": "fast-eq",
                        "status": "PERMISSION_DENIED"})
    for org in ("acme", "evil", "acme"):
        grpc_call(native_port, make_req("fast-eq.test", headers={"x-org": org}))
    # the dispatcher folds metrics after completing the batch — the last
    # response can reach the client a beat before its own increment lands
    deadline = time.monotonic() + 10
    while (sample("auth_server_authconfig_total",
                  {"namespace": "ns", "authconfig": "fast-eq"}) < base_total + 3
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert sample("auth_server_authconfig_total",
                  {"namespace": "ns", "authconfig": "fast-eq"}) == base_total + 3
    assert sample("auth_server_authconfig_response_status_total",
                  {"namespace": "ns", "authconfig": "fast-eq", "status": "OK"}) == base_ok + 2
    assert sample("auth_server_authconfig_response_status_total",
                  {"namespace": "ns", "authconfig": "fast-eq",
                   "status": "PERMISSION_DENIED"}) == base_deny + 1


def test_hostile_wire_input(stack):
    """A hand-rolled wire must survive hostile bytes: raw garbage, a valid
    preface followed by junk, truncated frames, an abortive RST close, and
    a well-formed stream carrying a corrupt protobuf — all without taking
    the server down or wedging later traffic."""
    import socket
    import struct

    _, fe, native_port, _ = stack

    def tcp(payload, linger=0.2, rst=False):
        s = socket.create_connection(("127.0.0.1", native_port), timeout=5)
        try:
            s.sendall(payload)
            time.sleep(linger)
            if rst:  # abortive close: RST instead of FIN
                s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
        finally:
            s.close()

    parse_errors_before = fe.stats()["parse_errors"]
    preface = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
    tcp(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")          # not HTTP/2 at all
    tcp(preface + b"\x00\x00\x00\x04\x00\x00\x00\x00\x00", rst=True)  # RST mid-session
    tcp(b"\x00" * 64)                                   # binary garbage
    tcp(preface + b"\xff" * 32)                         # preface then junk
    tcp(preface + b"\x00\x00\x04\x04\x00\x00\x00\x00")  # truncated SETTINGS
    # valid h2 session carrying a corrupt gRPC message: hand-rolled HEADERS
    # (literal :path to Check) + DATA with a non-protobuf body
    hp = (b"\x83\x86"                                    # :method POST, :scheme http
          + b"\x04" + bytes([len(b"/envoy.service.auth.v3.Authorization/Check")])
          + b"/envoy.service.auth.v3.Authorization/Check"
          + b"\x01\x01a")                                # :authority "a"
    frames = (preface
              + b"\x00\x00\x00\x04\x00\x00\x00\x00\x00"  # empty SETTINGS
              + len(hp).to_bytes(3, "big") + b"\x01\x04" + (1).to_bytes(4, "big") + hp
              + (10).to_bytes(3, "big") + b"\x00\x01" + (1).to_bytes(4, "big")
              + b"\x00" + (5).to_bytes(4, "big") + b"\xde\xad\xbe\xef\x99")
    tcp(frames, linger=0.5)

    # the corrupt protobuf actually reached the decoder (else this test
    # silently stops covering its key scenario)
    assert fe.stats()["parse_errors"] > parse_errors_before
    # the server still answers correctly afterwards
    resp = grpc_call(native_port, make_req("fast-eq.test", headers={"x-org": "acme"}))
    assert resp.status.code == 0


def test_duration_and_stage_histograms(stack):
    """The fast lane must feed the SAME duration series the pipeline
    observes (auth_server_authconfig_duration_seconds; VERDICT r3 weak #4)
    plus the on-box stage histograms (enqueue→flush→complete→respond;
    VERDICT r3 missing #4: a latency artifact, not an argument)."""
    _, fe, native_port, _ = stack
    for _ in range(40):
        grpc_call(native_port, make_req("fast-eq.test", headers={"x-org": "acme"}))
    grpc_call(native_port, make_req("slow-key.test",
                                    headers={"authorization": "APIKEY sekret"}))
    fe.drain_histograms()
    # on-box stages recorded for every batched fast request
    for stage in ("wait", "exec", "respond"):
        assert sum(fe.stage_totals[stage]) > 0, f"stage {stage} never recorded"
    # prometheus series carries the fast-lane durations per authconfig
    from prometheus_client import REGISTRY

    samples = {
        (s.labels.get("namespace"), s.labels.get("authconfig")): s.value
        for m in REGISTRY.collect()
        if m.name == "auth_server_authconfig_duration_seconds"
        for s in m.samples if s.name.endswith("_count")
    }
    assert samples.get(("ns", "fast-eq"), 0) >= 40
    # direct decisions (identity-only API key) are clocked too
    assert samples.get(("ns", "fast-keyonly"), 0) >= 1


def test_observe_bucketed_fallback_preserves_shape():
    """If prometheus_client internals (`_buckets`/`_sum`) ever vanish, the
    fallback must keep per-bucket counts (incl. +Inf overflow binned ABOVE
    the last finite bound) and land the exact drained sum — not collapse to
    one mean observation (ADVICE r4)."""
    from authorino_tpu.utils import metrics as metrics_mod

    class FakeChild:
        _upper_bounds = [0.001, 0.01, 0.1, float("inf")]

        def __init__(self):
            self.observed = []

        def observe(self, v):
            self.observed.append(v)

    child = FakeChild()
    # counts per bucket: 5 in (0,1ms], 3 in (1,10ms], 0, 2 overflow
    metrics_mod.observe_bucketed(child, [5, 3, 0, 2], sum_seconds=0.5)
    assert len(child.observed) == 10
    binned = [0, 0, 0, 0]
    for v in child.observed:
        for i, b in enumerate(FakeChild._upper_bounds):
            if v <= b:
                binned[i] += 1
                break
    assert binned == [5, 3, 0, 2]  # overflow NOT folded into le=0.1
    assert abs(sum(child.observed) - 0.5) < 1e-9


def test_randomized_differential_sweep(stack):
    """300 seeded-random requests across the module corpus — hosts (exact,
    wildcard, ports, overrides, unknown), methods, paths (regex lane,
    overflow lengths), credentials (valid/invalid/missing, all locations),
    random extra headers — every response byte-compared field-for-field
    against the Python server."""
    import random

    _, fe, native_port, py_port = stack
    rng = random.Random(20260730)
    hosts = ["fast-eq.test", "fast-cond.test", "fast-rx.test",
             "fast-deny.test", "slow-key.test", "fast-key.test",
             "cookie-key.test", "query-key.test", "slow-tmpl.test",
             "a.wild.test", "deep.a.wild.test", "wild.test", "unknown.test",
             "fast-eq.test:8080", "fast-rego.test"]
    methods = ["GET", "POST", "DELETE", "OPTIONS"]
    creds = [None, "APIKEY sekret", "APIKEY wrong", "Bearer sekret",
             "APIKEY", ""]
    cookies = [None, "ses=c0ffee", "a=1; ses=c0ffee", "ses=wrong", "x=1"]
    paths = ["/", "/api/v1/ok", "/api/v12/ok?q=1", "/api/nope",
             "/api/v2/ok" + "z" * 150, "/hello?tok=c0ffee",
             "/hello?tok=bad&x=1", "/x#frag", "/%20esc"]

    mismatches = []
    for i in range(300):
        headers = {}
        if rng.random() < 0.6:
            c = rng.choice(creds)
            if c is not None:
                headers["authorization"] = c
        if rng.random() < 0.4:
            ck = rng.choice(cookies)
            if ck is not None:
                headers["cookie"] = ck
        if rng.random() < 0.5:
            headers[f"x-attr-{rng.randrange(3)}"] = f"v{rng.randrange(5)}"
        if rng.random() < 0.3:
            headers["x-org"] = rng.choice(["acme", "evil", ""])
        if rng.random() < 0.3:
            headers["x-api-key"] = rng.choice(["adminkey", "userkey", "no"])
        if rng.random() < 0.2:
            headers["x-role"] = rng.choice(["admin", "user"])
        if rng.random() < 0.2:
            headers["x-pass"] = rng.choice(["yes", "no"])
        if rng.random() < 0.3:
            headers["x-tier"] = rng.choice(["gold", "wood", ""])
        if rng.random() < 0.3:
            headers["x-root"] = rng.choice(["true", "false", "TRUE", ""])
        ctx = ({"host": rng.choice(hosts[:4])}
               if rng.random() < 0.1 else None)
        req = make_req(rng.choice(hosts), method=rng.choice(methods),
                       path=rng.choice(paths), headers=headers, ctx=ctx)
        native = response_key(grpc_call(native_port, req))
        python = response_key(grpc_call(py_port, req))
        if native != python:
            mismatches.append((i, native, python))
    assert not mismatches, f"{len(mismatches)} diverged, first: {mismatches[0]}"
