"""Deploy-artifact lint: the CRD (install/), kustomize sets, and Dockerfile
must be structurally valid, and the CRD's OpenAPI schemas must accept the
golden AuthConfig fixtures in BOTH versions (parity target:
ref install/crd/authorino.kuadrant.io_authconfigs.yaml + deploy/)."""

import copy
import os

import pytest
import yaml

import jsonschema

from authorino_tpu.apis.convert import to_v1beta2

from test_conversion_golden import FULL_V1_SPEC, v1

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CRD_PATH = os.path.join(REPO, "install", "crd", "authorino.kuadrant.io_authconfigs.yaml")


def load_crd():
    with open(CRD_PATH) as f:
        return yaml.safe_load(f)


def openapi_to_jsonschema(node):
    """Minimal OpenAPI-v3-structural → JSON-schema translation: the K8s
    extension x-kubernetes-preserve-unknown-fields means 'any value here'."""
    if isinstance(node, dict):
        if node.get("x-kubernetes-preserve-unknown-fields") and "type" not in node:
            return True  # any value
        return {k: openapi_to_jsonschema(v) for k, v in node.items()
                if not k.startswith("x-kubernetes-")}
    if isinstance(node, list):
        return [openapi_to_jsonschema(x) for x in node]
    return node


class TestCRD:
    def test_crd_structure(self):
        crd = load_crd()
        assert crd["kind"] == "CustomResourceDefinition"
        assert crd["apiVersion"] == "apiextensions.k8s.io/v1"
        assert crd["metadata"]["name"] == "authconfigs.authorino.kuadrant.io"
        spec = crd["spec"]
        assert spec["group"] == "authorino.kuadrant.io"
        assert spec["names"]["kind"] == "AuthConfig"
        assert spec["scope"] == "Namespaced"
        versions = {v["name"]: v for v in spec["versions"]}
        assert set(versions) == {"v1beta1", "v1beta2"}
        # v1beta1 is the storage/hub version (ref: api/v1beta1
        # auth_config_types.go:787 +kubebuilder:storageversion)
        assert versions["v1beta1"]["storage"] is True
        assert versions["v1beta2"]["storage"] is False
        for v in versions.values():
            assert v["served"] is True
            assert "status" in v["subresources"]
            assert v["schema"]["openAPIV3Schema"]["type"] == "object"

    @pytest.mark.parametrize("version", ["v1beta1", "v1beta2"])
    def test_golden_fixture_validates(self, version):
        crd = load_crd()
        schemas = {
            v["name"]: v["schema"]["openAPIV3Schema"] for v in crd["spec"]["versions"]
        }
        resource = v1(copy.deepcopy(FULL_V1_SPEC))
        if version == "v1beta2":
            resource = to_v1beta2(resource)
        schema = openapi_to_jsonschema(schemas[version])
        jsonschema.validate(resource, schema)

    @pytest.mark.parametrize("version", ["v1beta1", "v1beta2"])
    def test_schema_rejects_bad_operator_and_missing_hosts(self, version):
        crd = load_crd()
        schemas = {
            v["name"]: v["schema"]["openAPIV3Schema"] for v in crd["spec"]["versions"]
        }
        schema = openapi_to_jsonschema(schemas[version])
        with pytest.raises(jsonschema.ValidationError):
            jsonschema.validate({"spec": {}}, schema)  # hosts required
        bad = {
            "spec": {
                "hosts": ["h"],
                "when": [{"selector": "x", "operator": "regex", "value": "y"}],
            }
        }
        with pytest.raises(jsonschema.ValidationError):
            jsonschema.validate(bad, schema)  # operator not in enum

    def test_webhook_patch(self):
        path = os.path.join(REPO, "install", "crd", "patches", "webhook_in_authconfigs.yaml")
        with open(path) as f:
            patch = yaml.safe_load(f)
        conv = patch["spec"]["conversion"]
        assert conv["strategy"] == "Webhook"
        svc = conv["webhook"]["clientConfig"]["service"]
        assert svc["path"] == "/convert"
        assert conv["webhook"]["conversionReviewVersions"] == ["v1"]


class TestDeploy:
    def _docs(self, *rel):
        with open(os.path.join(REPO, *rel)) as f:
            return [d for d in yaml.safe_load_all(f) if d]

    def test_kustomizations_reference_existing_files(self):
        for base in ("install", "deploy"):
            [k] = self._docs(base, "kustomization.yaml")
            for r in k.get("resources", []):
                assert os.path.exists(os.path.join(REPO, base, r)), r
            for p in k.get("patches", []):
                assert os.path.exists(os.path.join(REPO, base, p["path"])), p

    def test_deployment_matches_cli_surface(self):
        docs = self._docs("deploy", "deployment.yaml")
        by_kind = {}
        for d in docs:
            by_kind.setdefault(d["kind"], []).append(d)
        deployments = {d["metadata"]["name"]: d for d in by_kind["Deployment"]}
        server = deployments["authorino-tpu"]
        [container] = server["spec"]["template"]["spec"]["containers"]
        # args must be valid flags of the actual CLI
        from authorino_tpu.cli import build_parser

        parser = build_parser()
        parser.parse_args(container["args"])
        # declared ports match the CLI defaults
        ports = {p["name"]: p["containerPort"] for p in container["ports"]}
        assert ports == {"grpc": 50051, "http": 5001, "oidc": 8083, "metrics": 8080}

        webhooks = deployments["authorino-tpu-webhooks"]
        [wc] = webhooks["spec"]["template"]["spec"]["containers"]
        parser.parse_args(wc["args"])
        assert wc["ports"][0]["containerPort"] == 9443

    def test_rbac_covers_required_verbs(self):
        docs = self._docs("deploy", "rbac.yaml")
        cluster_rules = next(
            d for d in docs if d["kind"] == "ClusterRole"
        )["rules"]
        flat = {
            (g, res, verb)
            for r in cluster_rules
            for g in r["apiGroups"]
            for res in r["resources"]
            for verb in r["verbs"]
        }
        for needed in [
            ("authorino.kuadrant.io", "authconfigs", "watch"),
            ("authorino.kuadrant.io", "authconfigs/status", "patch"),
            ("", "secrets", "watch"),
            ("authentication.k8s.io", "tokenreviews", "create"),
            ("authorization.k8s.io", "subjectaccessreviews", "create"),
        ]:
            assert needed in flat, needed
        lease_rules = next(d for d in docs if d["kind"] == "Role")["rules"]
        assert any(
            "coordination.k8s.io" in r["apiGroups"] and "leases" in r["resources"]
            and {"create", "update"} <= set(r["verbs"])
            for r in lease_rules
        )

    def test_webhook_service_matches_crd_patch(self):
        docs = self._docs("deploy", "deployment.yaml")
        svc = next(
            d for d in docs
            if d["kind"] == "Service" and d["metadata"]["name"] == "authorino-tpu-webhooks"
        )
        with open(os.path.join(REPO, "install", "crd", "patches", "webhook_in_authconfigs.yaml")) as f:
            patch = yaml.safe_load(f)
        ref = patch["spec"]["conversion"]["webhook"]["clientConfig"]["service"]
        assert ref["name"] == svc["metadata"]["name"]
        assert ref["namespace"] == svc["metadata"]["namespace"]
        assert ref["port"] in [p["port"] for p in svc["spec"]["ports"]]

    def test_dockerfile_entrypoint(self):
        with open(os.path.join(REPO, "Dockerfile")) as f:
            content = f.read()
        assert 'ENTRYPOINT ["authorino-tpu"]' in content
        assert 'CMD ["server"]' in content
        assert "pymod.cpp" in content  # native encoder is built into the image
