"""Pipelined async device dispatch (ISSUE 2): the engine's three-stage
pipeline (encode → non-blocking dispatch window → completion) must overlap
micro-batches on the device link, resolve them FIFO-independently, dispatch
immediately at light load (no max_delay_s stacking), stay correct across
snapshot swaps with batches in flight, and leak no per-loop state.

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports)."""

from __future__ import annotations

import asyncio
import threading
import time

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.expressions import Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime import engine as engine_mod


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def sample(name, labels=None):
    from prometheus_client import REGISTRY

    v = REGISTRY.get_sample_value(name, labels or {})
    return 0.0 if v is None else v


RULE_ACME = Pattern("auth.identity.org", Operator.EQ, "acme")
RULE_EVIL = Pattern("auth.identity.org", Operator.EQ, "evil")


def build_engine(rule=RULE_ACME, **kw) -> PolicyEngine:
    kw.setdefault("max_batch", 8)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id="c", hosts=["c"], runtime=None,
                    rules=ConfigRules(name="c", evaluators=[(None, rule)]))
    ])
    return engine


def doc(org="acme"):
    return {"auth": {"identity": {"org": org}}}


class FakeHandle:
    """Stub device result: ready when its event is set (or after a fixed
    deadline), numpy-materializable like a jax.Array."""

    def __init__(self, ready_at: float = None):
        self.evt = threading.Event()
        self.ready_at = ready_at

    def is_ready(self) -> bool:
        if self.ready_at is not None:
            return time.monotonic() >= self.ready_at
        return self.evt.is_set()

    def __array__(self, dtype=None):
        return np.zeros((1, 1))


class StubDevice:
    """Replaces PolicyEngine._encode_and_launch with a stub whose batches
    complete only when released — models a device behind a long link and
    records launch/in-flight bookkeeping for assertions."""

    def __init__(self, engine, latency_s: float = None, allow=True):
        self.engine = engine
        self.latency_s = latency_s
        self.allow = allow
        self.launches = []          # [(FakeHandle, [config names])]
        self.lock = threading.Lock()
        self.concurrent = 0
        self.peak = 0
        engine._encode_and_launch = self._launch

    def _launch(self, snap, batch):
        n = len(batch)
        handle = FakeHandle(
            None if self.latency_s is None
            else time.monotonic() + self.latency_s)
        with self.lock:
            self.concurrent += 1
            self.peak = max(self.peak, self.concurrent)
            self.launches.append((handle, [p.config_name for p in batch]))
        binfo = {"batch_size": n, "pad": n, "eff": 0,
                 "start_ns": time.time_ns(), "duration_s": 0.0}

        def finalize(packed):
            with self.lock:
                self.concurrent -= 1
            rule = np.full((n, 1), self.allow, dtype=bool)
            return rule, np.zeros((n, 1), dtype=bool), None

        return engine_mod._Inflight(self.engine, batch, handle, finalize,
                                    binfo, np.zeros(n))


async def wait_until(cond, timeout=5.0, interval=0.005):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        await asyncio.sleep(interval)
    return False


def wait_until_sync(cond, timeout=5.0, interval=0.005):
    """Futures resolve before the completer's own bookkeeping (gauge set,
    stage observe, slot release) — poll briefly instead of racing it."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------------------------------------------------------------------------
# tentpole: overlap + FIFO-independent completion
# ---------------------------------------------------------------------------

def test_three_batches_in_flight_and_fifo_independent_resolution():
    """≥3 micro-batches concurrently in flight against a sleeping stub
    device, and a later batch's futures resolve while earlier launches are
    still on the wire (completion is arrival-ordered, not launch-ordered)."""
    engine = build_engine(max_batch=4, max_inflight_batches=8)
    dev = StubDevice(engine)

    async def body():
        tasks = [asyncio.ensure_future(engine.submit(doc(), "c"))
                 for _ in range(12)]
        assert await wait_until(lambda: len(dev.launches) == 3)
        # all three launched, none resolved: true concurrent in-flight
        assert dev.concurrent == 3
        assert engine._inflight == 3
        assert not any(t.done() for t in tasks)
        # release the LAST launch first: its 4 futures must resolve while
        # launches 0 and 1 are still in flight
        dev.launches[2][0].evt.set()
        late = await asyncio.wait_for(asyncio.gather(*tasks[8:]), timeout=5)
        assert all(bool(r[0]) for r, _ in late)
        assert not any(t.done() for t in tasks[:8])
        assert dev.concurrent == 2
        dev.launches[0][0].evt.set()
        dev.launches[1][0].evt.set()
        early = await asyncio.wait_for(asyncio.gather(*tasks[:8]), timeout=5)
        assert all(bool(r[0]) for r, _ in early)

    run(body())
    assert dev.peak >= 3
    assert engine.inflight_peak >= 3
    assert wait_until_sync(lambda: engine._inflight == 0)


def test_window_bounds_inflight_as_counter():
    """The dispatch window is a hard bound: with max_inflight_batches=2 and
    6 batches worth of queued requests, exactly 2 launch; each completion
    admits the next (completion-driven flushing)."""
    engine = build_engine(max_batch=2, max_inflight_batches=2)
    dev = StubDevice(engine)

    async def body():
        tasks = [asyncio.ensure_future(engine.submit(doc(), "c"))
                 for _ in range(12)]
        assert await wait_until(lambda: len(dev.launches) == 2)
        await asyncio.sleep(0.05)  # window full: no further launches
        assert len(dev.launches) == 2
        assert engine._inflight == 2
        assert len(engine._queue) == 8
        dev.launches[0][0].evt.set()  # one slot frees → one more batch cuts
        assert await wait_until(lambda: len(dev.launches) == 3)
        for h, _ in dev.launches:
            h.evt.set()
        while not all(t.done() for t in tasks):
            for h, _ in dev.launches:  # release every follow-on launch
                h.evt.set()
            await asyncio.sleep(0.005)
        return await asyncio.gather(*tasks)

    outs = run(body())
    assert len(outs) == 12
    assert dev.peak == 2
    assert engine.inflight_peak <= 2


def test_light_load_dispatches_without_waiting_max_delay():
    """A lone request with an open window dispatches immediately — its
    latency must not include max_delay_s (set absurdly high here)."""
    engine = build_engine()

    async def warm():
        return await engine.submit(doc(), "c")

    run(warm())  # XLA compile outside the timed window
    engine.max_delay_s = 30.0

    async def body():
        t0 = time.monotonic()
        rule, skipped = await asyncio.wait_for(engine.submit(doc(), "c"),
                                               timeout=5.0)
        return time.monotonic() - t0, rule

    elapsed, rule = run(body())
    assert bool(rule[0])
    assert elapsed < 2.0, f"light-load submit stacked a delay: {elapsed:.3f}s"


@pytest.mark.perf_guard
def test_dispatch_path_issues_no_blocking_readback():
    """Micro-benchmark guard against re-serialization: 4 batches with a
    stubbed 0.3s device latency must complete in ~one latency (pipelined),
    not four (a blocking readback anywhere on the dispatch path would
    serialize them)."""
    engine = build_engine(max_batch=4, max_inflight_batches=8)
    dev = StubDevice(engine, latency_s=0.3)

    async def body():
        t0 = time.monotonic()
        outs = await asyncio.gather(*(engine.submit(doc(), "c")
                                      for _ in range(16)))
        return time.monotonic() - t0, outs

    wall, outs = run(body())
    assert len(outs) == 16
    assert len(dev.launches) == 4
    # serial would be ≥ 1.2s; pipelined is one latency + slack for a noisy
    # 1-core host
    assert wall < 0.9, f"batches serialized: wall={wall:.3f}s for 4×0.3s"


# ---------------------------------------------------------------------------
# satellite: snapshot-swap safety with >1 batch in flight
# ---------------------------------------------------------------------------

def test_inflight_batches_survive_snapshot_swap():
    """Batches launched against generation G resolve with G's verdicts
    while apply_snapshot swaps to G+1 (double-buffer guarantee, now with
    the completion deferred past the swap)."""
    # lane selection OFF: this test gates DEVICE launches, and with the
    # cost model live the small warm-RTT cuts would ride the host lane
    # (host/device swap parity is pinned in tests/test_lane_select.py)
    engine = build_engine(rule=RULE_ACME, max_batch=4, lane_select=False)
    run(engine.submit(doc(), "c"))  # warm both jit caches
    gate = threading.Event()
    real = PolicyEngine._encode_and_launch

    class GatedHandle:
        def __init__(self, inner):
            self.inner = inner

        def is_ready(self):
            return gate.is_set() and (
                not hasattr(self.inner, "is_ready") or self.inner.is_ready())

        def __array__(self, dtype=None):
            return np.asarray(self.inner)

    gated_launches = []

    def gated(snap, batch):
        item = real(engine, snap, batch)
        item.handle = GatedHandle(item.handle)
        gated_launches.append(item)
        return item

    engine._encode_and_launch = gated

    async def body():
        # two gated batches launch against G (acme allowed).  Wait for the
        # LAUNCHES, not the window counter: the counter increments at batch
        # cut, before the encode worker runs the (gated) launch
        pre = [asyncio.ensure_future(engine.submit(doc("acme"), "c"))
               for _ in range(8)]
        assert await wait_until(lambda: len(gated_launches) >= 2)
        gen_before = engine.generation
        # swap to G+1 (evil allowed, acme denied) while G's batches fly
        engine._encode_and_launch = real.__get__(engine, PolicyEngine)
        engine.apply_snapshot([
            EngineEntry(id="c", hosts=["c"], runtime=None,
                        rules=ConfigRules(name="c",
                                          evaluators=[(None, RULE_EVIL)]))
        ])
        assert engine.generation == gen_before + 1
        post = await asyncio.gather(*(engine.submit(doc("acme"), "c")
                                      for _ in range(4)))
        assert not any(bool(r[0]) for r, _ in post)  # G+1: acme denied
        assert not any(t.done() for t in pre)        # G still in flight
        gate.set()
        outs = await asyncio.wait_for(asyncio.gather(*pre), timeout=10)
        # G's semantics: acme allowed, even though G+1 now serves
        assert all(bool(r[0]) for r, _ in outs)

    run(body())


# ---------------------------------------------------------------------------
# satellite: no per-loop dispatcher state; closed loops are harmless
# ---------------------------------------------------------------------------

def test_no_per_loop_state_accumulates():
    """The old per-loop _pending/_flush_handles dicts leaked an entry per
    event loop; the global dispatcher holds no loop-keyed state at all."""
    engine = build_engine()

    async def three():
        return await asyncio.gather(*(engine.submit(doc(), "c")
                                      for _ in range(3)))

    for _ in range(6):
        loop = asyncio.new_event_loop()
        try:
            outs = loop.run_until_complete(three())
        finally:
            loop.close()
        assert all(bool(r[0]) for r, _ in outs)
    assert not hasattr(engine, "_pending")
    assert not hasattr(engine, "_flush_handles")
    assert len(engine._queue) == 0
    assert wait_until_sync(lambda: engine._inflight == 0)
    assert engine.debug_vars()["queue_depth"] == 0


def test_loop_closed_before_completion_is_survivable():
    """A loop that dies with requests in flight must not wedge the shared
    completer: its futures are moot, the window slot frees, and fresh loops
    keep being served."""
    engine = build_engine(max_batch=2)
    dev = StubDevice(engine)

    async def launch_and_abandon():
        asyncio.ensure_future(engine.submit(doc(), "c"))
        assert await wait_until(lambda: engine._inflight >= 1)

    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(launch_and_abandon())
    finally:
        loop.close()  # the in-flight batch's owning loop is now gone
    for h, _ in dev.launches:
        h.evt.set()
    deadline = time.monotonic() + 5
    while engine._inflight and time.monotonic() < deadline:
        time.sleep(0.005)
    assert engine._inflight == 0  # slot freed despite the dead loop
    # the engine still serves new loops afterwards
    del engine._encode_and_launch  # restore the real bound method
    outs = run(engine.submit(doc(), "c"))
    assert bool(outs[0][0])


def test_batch_error_propagates_to_every_future():
    engine = build_engine()
    with pytest.raises(Exception):
        run(engine.submit(doc(), "no-such-config"))
    assert wait_until_sync(lambda: engine._inflight == 0)


# ---------------------------------------------------------------------------
# satellite: per-request queue waits + inflight gauge on /metrics
# ---------------------------------------------------------------------------

def test_queue_wait_histogram_counts_every_request():
    """The queue-wait histogram must record TRUE per-request waits (one
    count per request), not just batch[0]'s."""
    engine = build_engine(max_batch=8)
    before = sample("auth_server_batch_queue_wait_seconds_count",
                    {"lane": "engine"})

    async def many():
        return await asyncio.gather(*(engine.submit(doc(), "c")
                                      for _ in range(24)))

    run(many())
    after = sample("auth_server_batch_queue_wait_seconds_count",
                   {"lane": "engine"})
    assert after >= before + 24, (before, after)


def test_inflight_gauge_and_pipeline_stages_exported():
    engine = build_engine()

    async def many():
        return await asyncio.gather(*(engine.submit(doc(), "c")
                                      for _ in range(8)))

    run(many())
    # gauge exists (0 once drained) and every pipeline stage recorded
    assert wait_until_sync(lambda: engine._inflight == 0)
    assert sample("auth_server_inflight_batches", {"lane": "engine"}) == 0.0
    for stage in ("encode", "launch", "device", "resolve"):
        assert wait_until_sync(lambda: sample(
            "auth_server_pipeline_stage_seconds_count",
            {"lane": "engine", "stage": stage}) > 0), stage
    dv = engine.debug_vars()
    assert dv["inflight_batches"] == 0
    assert dv["inflight_peak"] >= 1
    assert dv["max_inflight_batches"] == engine.max_inflight_batches


# ---------------------------------------------------------------------------
# satellite: fused H2D staging is bit-exact vs per-operand transfers
# ---------------------------------------------------------------------------

def test_fused_h2d_staging_matches_per_operand_path():
    import jax.numpy as jnp

    from authorino_tpu.compiler.compile import compile_corpus
    from authorino_tpu.compiler.encode import encode_batch
    from authorino_tpu.compiler.pack import pack_batch
    from authorino_tpu.expressions import All, Any_
    from authorino_tpu.ops.pattern_eval import (
        dispatch_packed,
        eval_fused_jit,
        fuse_batch,
        fused_h2d_supported,
        to_device,
        unpack_verdicts,
    )

    assert fused_h2d_supported()  # little-endian bitcast probe
    rule = All(
        Pattern("request.method", Operator.EQ, "GET"),
        Any_(Pattern("auth.identity.roles", Operator.INCL, "admin"),
             Pattern("request.url_path", Operator.MATCHES, r"^/api/v\d+")),
    )
    policy = compile_corpus(
        [ConfigRules(name="c", evaluators=[(None, rule)])], members_k=4)
    params = to_device(policy)
    docs = [
        {"request": {"method": "GET", "url_path": "/api/v1"},
         "auth": {"identity": {"roles": ["admin"]}}},
        {"request": {"method": "POST", "url_path": "/nope"},
         "auth": {"identity": {"roles": ["dev"]}}},
    ] * 6
    enc = encode_batch(policy, docs, [0] * len(docs), batch_pad=16)
    db = pack_batch(policy, enc)
    reference = np.asarray(dispatch_packed(params, db))
    buf, layout = fuse_batch(db)
    assert buf.dtype == np.uint8 and buf.ndim == 1  # ONE staging buffer
    fused = np.asarray(eval_fused_jit(params, jnp.asarray(buf), layout))
    # the fused readback is the BIT-PACKED u8 bitmask (8 verdicts/byte);
    # decoding it must reproduce the per-operand bool result exactly
    assert fused.dtype == np.uint8
    E = int(policy.eval_rule.shape[1])
    assert fused.shape[1] == (1 + 2 * E + 7) // 8
    assert np.array_equal(reference, unpack_verdicts(fused, 1 + 2 * E))
