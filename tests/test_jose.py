"""JOSE regression tests (the broader verify paths are covered through the
OIDC/wristband evaluator tests)."""

import base64

import pytest

from authorino_tpu.utils import jose


def oct_jwk(secret: bytes, kid: str = "") -> dict:
    k = base64.urlsafe_b64encode(secret).rstrip(b"=").decode()
    out = {"kty": "oct", "k": k}
    if kid:
        out["kid"] = kid
    return out


class TestPublicKeyCache:
    def test_distinct_hmac_secrets_never_collide(self):
        # the key cache must key on the key MATERIAL: two oct JWKs with
        # different secrets are different keys — a collision verifies
        # tokens against the wrong secret (authentication bypass)
        token = jose.sign_jwt({"sub": "x"}, b"secret-one", "HS256")
        assert jose.verify_jws(token, [oct_jwk(b"secret-one")]) == {"sub": "x"}
        with pytest.raises(jose.JoseError):
            jose.verify_jws(token, [oct_jwk(b"secret-two")])

    def test_rotated_rsa_keys_never_collide(self):
        from cryptography.hazmat.primitives.asymmetric import rsa

        old = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        new = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        token = jose.sign_jwt({"sub": "x"}, old, "RS256", kid="k1")
        old_jwk = jose.jwk_from_public_key(old.public_key(), kid="k1")
        new_jwk = jose.jwk_from_public_key(new.public_key(), kid="k1")
        assert jose.verify_jws(token, [old_jwk]) == {"sub": "x"}
        with pytest.raises(jose.JoseError):
            jose.verify_jws(token, [new_jwk])  # same kid, rotated material
