"""Test bootstrap: force an 8-device virtual CPU mesh so multi-chip sharding
paths are exercised without TPU hardware.

NOTE: on this image a sitecustomize shim registers the TPU-tunnel ("axon")
PJRT plugin at interpreter startup and imports jax before conftest runs, so
env-var overrides alone are too late; backend *initialization* is still lazy,
so `jax.config.update("jax_platforms", "cpu")` after import wins."""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def mesh_devices():
    """The forced 8-device virtual CPU mesh (ISSUE 11 satellite): the
    XLA_FLAGS export above runs BEFORE jax import, so dp×mp shapes up to
    4×2 exercise the real shard_map partitioning on the CPU-only image.
    Fails loudly (not skips) if the forcing stopped working — tier-1 mesh
    coverage must never silently evaporate."""
    devices = jax.devices()
    assert len(devices) >= 8, (
        "expected >= 8 virtual CPU devices "
        "(XLA_FLAGS=--xla_force_host_platform_device_count=8 was exported "
        f"too late?), got {len(devices)}")
    return devices[:8]
