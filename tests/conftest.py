"""Test bootstrap: force an 8-device virtual CPU mesh *before* jax imports,
so multi-chip sharding paths are exercised without TPU hardware."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
