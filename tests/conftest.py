"""Test bootstrap: force an 8-device virtual CPU mesh so multi-chip sharding
paths are exercised without TPU hardware.

NOTE: on this image a sitecustomize shim registers the TPU-tunnel ("axon")
PJRT plugin at interpreter startup and imports jax before conftest runs, so
env-var overrides alone are too late; backend *initialization* is still lazy,
so `jax.config.update("jax_platforms", "cpu")` after import wins."""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
