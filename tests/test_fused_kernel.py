"""Fused mega-kernel (ISSUE 17, ops/fused_kernel.py): the whole hot path
in ONE launch.

Covers: the 3-seed cross-lane differential (fused vs gather vs matmul vs
the host oracle — verdict AND attribution — over corpora exercising the
DFA byte scan incl. byte overflow, relation gathers, numeric compares,
membership overflow with and without ovf-assist, and CPU-fallback regex
rows); the staged pre-fusion baseline staying bit-exact while costing >1
launch on the ledger; the perf-guard pin that the fused engine lane
performs EXACTLY one launch per batch with the exact bitpacked D2H byte
count (plus the planted-extra-launch self-test on the fused lane); the
snapshot-swap prewarm hook; the entry-point audit listing the fused
entry; the certifier rejecting the new fused-layout mutant classes with
the fused lane selected; strict-verify rejection of a fused-layout
corruption leaving the old snapshot serving; lane resolution via
--kernel-lane / AUTHORINO_TPU_KERNEL_LANE / auto; the occupancy-shaped
mesh pad; and the mesh 2x2 fused parity sweep."""

import asyncio
import copy
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.compiler.encode import encode_batch_py
from authorino_tpu.compiler.pack import pack_batch
from authorino_tpu.expressions import All, Any_, InGroup, Operator, Pattern
from authorino_tpu.models.policy_model import host_results
from authorino_tpu.ops import fused_kernel as fk
from authorino_tpu.ops import pattern_eval as pe
from authorino_tpu.relations.closure import RelationClosure
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.kernel_cost import LEDGER

from test_kernel_cost import assert_launch_parity, delta, sample


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


K = 4  # members_k small enough that role lists overflow on purpose


def _corpus(rng: random.Random, n_configs=6):
    """Every lane in one corpus: relations (deep chain), numeric compares,
    membership (overflow-capable at K=4), eq, device-DFA regex rows (two
    distinct tables -> the grouped gather layout is non-trivial), and one
    CPU-regex config (backreference: outside the DFA subset)."""
    deep = [(f"d{i}", f"d{i + 1}") for i in range(6)]
    rel = RelationClosure(deep + [("u", "left"), ("left", "mid"),
                                  ("mid", "top")])
    groups = ["mid", "top", "left", "d3", "d5"]
    cfgs = []
    for i in range(n_configs):
        leaves = [
            InGroup("auth.identity.sub", rng.choice(groups), rel),
            Pattern("req.n", rng.choice(
                [Operator.GT, Operator.GE, Operator.LT, Operator.LE]),
                str(rng.randrange(-5, 30))),
            Pattern("auth.identity.roles", Operator.INCL, f"r{i % 3}"),
            Pattern("req.m", Operator.EQ, rng.choice(["GET", "POST"])),
            Pattern("req.path", Operator.MATCHES, rf"^/svc-{i % 3}/"),
        ]
        rng.shuffle(leaves)
        rule = All(leaves[0], Any_(*leaves[1:4]))
        cond = leaves[4] if rng.random() < 0.5 else None
        cfgs.append(ConfigRules(name=f"cfg-{i}",
                                evaluators=[(cond, rule), (None, leaves[4])]))
    cfgs.append(ConfigRules(name="cfg-cpu", evaluators=[
        (None, Pattern("req.q", Operator.MATCHES, r"^(a+)\1$"))]))
    return cfgs


def _docs(rng: random.Random, n=48):
    ents = [f"d{i}" for i in range(7)] + ["u", "left", "mid", "top",
                                          "stranger"]
    docs = []
    for _ in range(n):
        docs.append({
            "req": {"n": rng.choice([-10, 0, 3, 29, 30, "x", None]),
                    "m": rng.choice(["GET", "POST", "PUT"]),
                    # the long path exceeds DFA_VALUE_BYTES -> byte overflow
                    "path": rng.choice(["/svc-0/a", "/svc-1/b", "/zzz",
                                        "/svc-2/" + "x" * 200]),
                    "q": rng.choice(["aaaa", "aaa", "ab"])},
            "auth": {"identity": {
                "sub": rng.choice(ents),
                "roles": [f"r{rng.randrange(4)}"
                          for _ in range(rng.choice([1, 2, K + 3]))],
            }},
        })
    return docs


def _batch(policy, docs, names):
    rows = [policy.config_ids[n] for n in names]
    db = pack_batch(policy, encode_batch_py(policy, docs, rows))
    has_dfa = policy.n_byte_attrs > 0
    args = (
        jnp.asarray(db.attrs_val), jnp.asarray(db.members_c),
        jnp.asarray(db.cpu_dense), jnp.asarray(db.config_id),
        jnp.asarray(db.attr_bytes) if has_dfa else None,
        jnp.asarray(db.byte_ovf) if has_dfa else None,
        *pe._extra_operands(db),
    )
    return db, rows, args


# ---------------------------------------------------------------------------
# 1. cross-lane differential: fused == gather == matmul == host oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [7, 19, 31])
def test_fused_bit_identical_across_lanes_and_oracle(seed):
    rng = random.Random(seed)
    cfgs = _corpus(rng)
    policy = compile_corpus(cfgs, members_k=K, ovf_assist=True)
    docs = _docs(rng)
    names = [rng.choice([c.name for c in cfgs]) for _ in docs]
    db, rows, args = _batch(policy, docs, names)
    assert not db.host_fallback.any()  # ovf_assist: no lossy rows

    params = {lane: pe.to_device(policy, lane=lane)
              for lane in ("fused", "gather", "matmul")}
    for lane, p in params.items():
        assert pe.kernel_lane_of(p) == lane
    assert params["fused"]["fused"] is not None
    assert params["gather"]["fused"] is None

    # the in-kernel bitpacked readback, all three lanes, bit for bit
    packed_f = np.asarray(fk.eval_fused_kernel(params["fused"], db))
    assert packed_f.dtype == np.uint8
    for lane in ("gather", "matmul"):
        packed_l = np.asarray(pe.eval_bitpacked_jit(params[lane], *args))
        np.testing.assert_array_equal(packed_f, packed_l, err_msg=lane)

    # verdict AND attribution against the host oracle, every row
    E = int(policy.eval_rule.shape[1])
    verdict, firing = pe.unpack_attribution(packed_f, E)
    want = [host_results(policy, d, r) for d, r in zip(docs, rows)]
    w_fire = pe.firing_columns(np.stack([w[1] for w in want]),
                               np.stack([w[2] for w in want]))
    for i in range(len(docs)):
        assert bool(verdict[i]) == bool(want[i][0]), (seed, i)
        assert int(firing[i]) == int(w_fire[i]), (seed, i)


def test_fused_matches_gather_on_host_fallback_corpus():
    """Without ovf-assist, membership-overflow rows route to the host
    oracle — the fused lane's device results for those rows (and the pad
    tail) must still be bit-identical to the gather lane's."""
    rng = random.Random(5)
    cfgs = _corpus(rng)
    policy = compile_corpus(cfgs, members_k=K, ovf_assist=False)
    docs = _docs(rng)
    names = [rng.choice([c.name for c in cfgs]) for _ in docs]
    db, _, args = _batch(policy, docs, names)
    assert db.host_fallback.any()  # K+3 role lists overflow K=4

    packed_f = np.asarray(
        fk.eval_fused_kernel(pe.to_device(policy, lane="fused"), db))
    packed_g = np.asarray(
        pe.eval_bitpacked_jit(pe.to_device(policy, lane="gather"), *args))
    np.testing.assert_array_equal(packed_f, packed_g)


# ---------------------------------------------------------------------------
# 2. staged pre-fusion baseline: same bits, MORE launches
# ---------------------------------------------------------------------------


def test_staged_baseline_bit_exact_but_multi_launch():
    rng = random.Random(3)
    cfgs = _corpus(rng)
    policy = compile_corpus(cfgs, members_k=K, ovf_assist=True)
    docs = _docs(rng, n=32)
    names = [rng.choice([c.name for c in cfgs]) for _ in docs]
    db, _, _ = _batch(policy, docs, names)
    params = pe.to_device(policy, lane="fused")

    fused = np.asarray(fk.eval_fused_kernel(params, db))
    staged = np.asarray(fk.dispatch_staged(params, db))
    np.testing.assert_array_equal(fused, staged)

    # a DFA+relations+numeric corpus costs 5 stage launches unfused:
    # leaves, DFA scan, value lanes, circuit, bitpack
    assert fk.staged_launches(params, db) == 5

    # the ledger records them as real launches — the structural proof the
    # mega-kernel actually fuses something
    b0 = LEDGER.snapshot("host")
    fk.dispatch_staged(params, db, ledger_lane="host")
    d = delta(b0, LEDGER.snapshot("host"))
    assert d["launches"] == fk.staged_launches(params, db) > 1


# ---------------------------------------------------------------------------
# 3. perf guard: the fused engine lane is ONE launch per batch, exact D2H
# ---------------------------------------------------------------------------


ENGINE_REL = RelationClosure([("alice", "staff"), ("staff", "org")])
ENGINE_RULE = All(
    Pattern("request.method", Operator.EQ, "GET"),
    Pattern("request.url_path", Operator.MATCHES, r"^/api/"),
    InGroup("auth.identity.sub", "org", ENGINE_REL),
    Pattern("auth.identity.age", Operator.GE, "18"),
)


def build_fused_engine(rule=ENGINE_RULE, **kw) -> PolicyEngine:
    kw.setdefault("max_batch", 32)
    kw.setdefault("lane_select", False)
    kw.setdefault("batch_dedup", False)
    kw.setdefault("verdict_cache_size", 0)
    kw.setdefault("kernel_lane", "fused")
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id="c", hosts=["c"], runtime=None,
                    rules=ConfigRules(name="c", evaluators=[(None, rule)]))
    ])
    return engine


def fused_doc(i: int, allow=True):
    return {"request": {"method": "GET",
                        "url_path": "/api/v1" if allow else "/other"},
            "auth": {"identity": {"sub": "alice", "age": 42,
                                  "tag": f"t{i}"}}}


async def submit_all(engine, docs):
    outs = await asyncio.gather(*(engine.submit(d, "c") for d in docs))
    return [bool(rule[0]) for rule, _ in outs]


class TestFusedEngineLane:
    def test_one_launch_per_batch_exact_d2h(self):
        lane0 = sample("auth_server_kernel_lane_total", {"lane": "fused"})

        async def go():
            engine = build_fused_engine()
            b0 = LEDGER.snapshot("engine")
            got = await submit_all(
                engine, [fused_doc(i, allow=i % 2 == 0) for i in range(6)])
            assert got == [i % 2 == 0 for i in range(6)]
            return engine, delta(b0, LEDGER.snapshot("engine"))

        engine, d = run(go())
        params = engine._snapshot.params
        assert params.get("fused") is not None
        assert pe.kernel_lane_of(params) == "fused"

        # launches_per_batch == 1.0 EXACTLY on the fused lane
        assert d["batches"] >= 1
        assert d["zero_launch_batches"] == 0
        assert d["launches"] == d["batches"]
        assert_launch_parity(d)

        # D2H is the in-kernel bitpacked readback and nothing else
        policy = engine._snapshot.policy
        E = int(policy.eval_rule.shape[1])
        W = pe.packed_width(1 + 2 * E)
        assert policy.fused_pack_w == W
        assert d["d2h_bytes"] == d["pad_rows"] * W

        # the lane counter moved by exactly the batches dispatched fused
        assert sample("auth_server_kernel_lane_total",
                      {"lane": "fused"}) - lane0 == d["batches"]

        # entry-point audit: the mega-kernel is a first-class audited entry
        names = [e["entry"] for e in
                 engine.debug_vars()["kernel_cost"]["entry_points"]]
        assert "fused_kernel" in names

    def test_planted_extra_launch_trips_gate_on_fused_lane(self):
        async def go():
            engine = build_fused_engine()
            b0 = LEDGER.snapshot("engine")
            await submit_all(engine, [fused_doc(i) for i in range(3)])
            LEDGER.observe_launch("engine")  # a stray unfused stage
            return delta(b0, LEDGER.snapshot("engine"))

        d = run(go())
        assert d["launches"] == d["batches"] + 1
        with pytest.raises(AssertionError, match="launch parity"):
            assert_launch_parity(d)


# ---------------------------------------------------------------------------
# 4. snapshot-swap prewarm (both frontends warm this module's entries)
# ---------------------------------------------------------------------------


def test_snapshot_swap_prewarms_fused_entry(monkeypatch):
    calls = []
    real = fk.prewarm_fused

    def probe(policy, params, **kw):
        calls.append(real(policy, params, **kw))
        return calls[-1]

    monkeypatch.setattr(fk, "prewarm_fused", probe)
    engine = build_fused_engine()
    assert calls == [True]  # warmed exactly once, at swap

    # no-op (False) on a snapshot without the fused subtree
    gp = pe.to_device(engine._snapshot.policy, lane="gather")
    assert fk.prewarm_fused(engine._snapshot.policy, gp) is False


# ---------------------------------------------------------------------------
# 5. certifier + strict-verify: fused-layout corruptions cannot serve
# ---------------------------------------------------------------------------


def _plant_perm(p):
    p.dfa_row_perm = p.dfa_row_perm.copy()
    p.dfa_row_perm[0] = p.dfa_row_perm[1]


def _plant_int8(p):
    p.leaf_op_i8 = p.leaf_op_i8.copy()
    p.leaf_op_i8[0] += 1


def _plant_packw(p):
    p.fused_pack_w = int(p.fused_pack_w) + 1


def test_certifier_rejects_fused_layout_with_fused_lane(monkeypatch):
    from authorino_tpu.analysis.translation_validate import certify_snapshot

    monkeypatch.setenv("AUTHORINO_TPU_KERNEL_LANE", "fused")
    rng = random.Random(11)
    policy = compile_corpus(_corpus(rng), members_k=K, ovf_assist=True)
    _, fails, _ = certify_snapshot(policy, use_cache=False)
    assert not fails, fails[:3]
    for plant in (_plant_perm, _plant_int8, _plant_packw):
        bad = copy.deepcopy(policy)
        plant(bad)
        _, fails, _ = certify_snapshot(bad, use_cache=False)
        assert any(f.kind == "fused-layout" for f in fails), plant.__name__


def test_strict_verify_fused_corruption_keeps_old_snapshot(monkeypatch):
    import authorino_tpu.snapshots.compile_cache as cc
    from authorino_tpu.runtime.engine import SnapshotRejected

    engine = build_fused_engine(strict_verify=True)
    assert run(submit_all(engine, [fused_doc(0)])) == [True]

    real = cc.compile_corpus

    def corrupting(*a, **kw):
        pol = real(*a, **kw)
        pol.fused_pack_w = int(pol.fused_pack_w) + 1  # fused-pack-width
        return pol

    monkeypatch.setattr(cc, "compile_corpus", corrupting)
    with pytest.raises(SnapshotRejected):
        engine.apply_snapshot([
            EngineEntry(id="c2", hosts=["c2"], runtime=None,
                        rules=ConfigRules(name="c2", evaluators=[
                            (None, Pattern("a.b", Operator.EQ, "x"))]))
        ])
    # the rejected corpus never swapped in: the old snapshot still serves
    assert run(submit_all(engine, [fused_doc(1)])) == [True]


# ---------------------------------------------------------------------------
# 6. lane resolution + occupancy pad units
# ---------------------------------------------------------------------------


def test_kernel_lane_env_and_auto_resolution(monkeypatch):
    policy = compile_corpus([ConfigRules(name="c", evaluators=[
        (None, Pattern("a.b", Operator.EQ, "x"))])], members_k=4)
    monkeypatch.setenv("AUTHORINO_TPU_KERNEL_LANE", "fused")
    p = pe.to_device(policy)
    assert p["fused"] is not None and pe.kernel_lane_of(p) == "fused"
    monkeypatch.delenv("AUTHORINO_TPU_KERNEL_LANE")
    if jax.default_backend() != "tpu":
        # auto keeps the classic per-stage lane off-TPU
        assert pe.to_device(policy)["fused"] is None
    # explicit argument wins regardless of env
    monkeypatch.setenv("AUTHORINO_TPU_KERNEL_LANE", "gather")
    assert pe.to_device(policy, lane="fused")["fused"] is not None


def test_kernel_lane_auto_consults_every_device(monkeypatch):
    """ISSUE 18 satellite: auto arms the fused lane iff EVERY device is a
    real TPU.  jax.default_backend() names only the highest-priority
    platform, so a single TPU in a mixed device set used to arm the
    Pallas kernel for devices that can only interpret it."""

    class _Dev:
        def __init__(self, platform):
            self.platform = platform

    assert pe.auto_lane(_Dev("tpu")) == "fused"
    assert pe.auto_lane(_Dev("cpu")) != "fused"
    # the regression: mixed visibility must NOT arm fused, whatever the
    # default backend claims
    monkeypatch.setattr(pe.jax, "devices",
                        lambda *a, **k: [_Dev("tpu"), _Dev("cpu")])
    assert pe.auto_lane() != "fused"
    dec = pe.last_auto_decision()
    assert dec == {"requested": "auto", "lane": dec["lane"],
                   "devices": 2, "platforms": ["cpu", "tpu"]}
    # all-TPU visibility is the one case that arms it
    monkeypatch.setattr(pe.jax, "devices",
                        lambda *a, **k: [_Dev("tpu"), _Dev("tpu")])
    assert pe.auto_lane() == "fused"
    assert pe.last_auto_decision()["platforms"] == ["tpu"]


def test_occupancy_pad_shapes():
    # pow2 floor, never below the real row count, busiest-shard * dp
    assert fk.occupancy_pad([1, 1], dp=2, n_rows=2) == 16
    assert fk.occupancy_pad([0, 0], dp=2, n_rows=0) == 16
    assert fk.occupancy_pad([8, 1], dp=2, n_rows=9) == 16
    assert fk.occupancy_pad([20, 1], dp=2, n_rows=21) == 64
    assert fk.occupancy_pad([1, 1], dp=2, n_rows=100) == 128
    assert fk.occupancy_pad([64, 0], dp=2, n_rows=64, cap=64) == 128


# ---------------------------------------------------------------------------
# 7. mesh 2x2: fused lane parity under shard_map
# ---------------------------------------------------------------------------


@pytest.mark.mesh
@pytest.mark.parametrize("seed", [13, 37])
def test_mesh_2x2_fused_parity(seed, mesh_devices):
    from authorino_tpu.parallel import ShardedPolicyModel, build_mesh

    rng = random.Random(seed)
    cfgs = _corpus(rng)
    docs = _docs(rng)
    names = [rng.choice([c.name for c in cfgs]) for _ in docs]
    mesh = build_mesh(n_devices=4, dp=2)  # 2x2
    sharded = ShardedPolicyModel(cfgs, mesh, members_k=K, ovf_assist=True,
                                 kernel_lane="fused")
    assert sharded.has_fused
    own_rule, own_skip = sharded.run_full(docs, names)
    n = len(docs)
    fire = pe.firing_columns(own_rule[:n], own_skip[:n])
    for i, (d, name) in enumerate(zip(docs, names)):
        shard, row = sharded.locator[name]
        w_own, w_rule, w_skip = host_results(sharded.shards[shard], d,
                                             int(row))
        w_fire = pe.firing_columns(w_rule[None, :], w_skip[None, :])[0]
        got_own = bool(np.all(own_skip[i] | own_rule[i]))
        assert got_own == w_own, (seed, i)
        assert int(fire[i]) == int(w_fire), (seed, i)
