"""gRPC ext_authz server tests: real grpc.aio client/server over localhost,
asserting wire-level CheckRequest/CheckResponse behavior
(contract: ref pkg/service/auth.go:239-357)."""

import asyncio

import grpc
import pytest

from authorino_tpu import protos
from authorino_tpu.compiler import ConfigRules
from authorino_tpu.evaluators import (
    AuthorizationConfig,
    IdentityConfig,
    RuntimeAuthConfig,
)
from authorino_tpu.evaluators.authorization import PatternMatching
from authorino_tpu.evaluators.identity import Noop
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.service.grpc_server import build_server

external_auth_pb2 = protos.external_auth_pb2


def make_engine():
    engine = PolicyEngine(max_batch=4)
    rules = All(Pattern("request.headers.x-org", Operator.EQ, "acme"))
    runtime = RuntimeAuthConfig(
        identity=[IdentityConfig("anon", Noop())],
        authorization=[
            AuthorizationConfig(
                "org", PatternMatching(rules, batched_provider=engine.provider_for("ns/cfg"))
            )
        ],
    )
    engine.apply_snapshot(
        [
            EngineEntry(
                id="ns/cfg",
                hosts=["svc.example.com"],
                runtime=runtime,
                rules=ConfigRules(name="ns/cfg", evaluators=[(None, rules)]),
            )
        ]
    )
    return engine


def check_request(host="svc.example.com", org="acme", ctx_host=None):
    req = external_auth_pb2.CheckRequest()
    http = req.attributes.request.http
    http.method = "GET"
    http.path = "/hello"
    http.host = host
    http.headers["x-org"] = org
    http.headers["host"] = host
    if ctx_host:
        req.attributes.context_extensions["host"] = ctx_host
    return req


def test_grpc_check_allow_deny_notfound():
    async def run_all():
        engine = make_engine()
        server = build_server(engine, address="127.0.0.1:0")
        port = server.add_insecure_port("127.0.0.1:0")
        await server.start()
        try:
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
                call = channel.unary_unary(
                    "/envoy.service.auth.v3.Authorization/Check",
                    request_serializer=external_auth_pb2.CheckRequest.SerializeToString,
                    response_deserializer=external_auth_pb2.CheckResponse.FromString,
                )
                # allow
                resp = await call(check_request(org="acme"))
                assert resp.status.code == 0
                assert resp.WhichOneof("http_response") == "ok_response"
                # deny → PERMISSION_DENIED(7), HTTP 403, reason header
                resp = await call(check_request(org="evil"))
                assert resp.status.code == 7
                assert resp.denied_response.status.code == 403
                reasons = {
                    h.header.key: h.header.value for h in resp.denied_response.headers
                }
                assert reasons.get("X-Ext-Auth-Reason") == "Unauthorized"
                # unknown host → NOT_FOUND(5), 404 (ref auth.go:287-289)
                resp = await call(check_request(host="nope.example.com"))
                assert resp.status.code == 5
                assert resp.denied_response.status.code == 404
                # context_extensions host override (ref auth.go:270-276)
                resp = await call(
                    check_request(host="nope.example.com", ctx_host="svc.example.com")
                )
                assert resp.status.code == 0
                # missing http attributes → INVALID_ARGUMENT(3) (ref :242-255)
                resp = await call(external_auth_pb2.CheckRequest())
                assert resp.status.code == 3

                # health service
                health = channel.unary_unary(
                    "/grpc.health.v1.Health/Check",
                    request_serializer=protos.health_pb2.HealthCheckRequest.SerializeToString,
                    response_deserializer=protos.health_pb2.HealthCheckResponse.FromString,
                )
                hr = await health(protos.health_pb2.HealthCheckRequest())
                assert hr.status == protos.health_pb2.HealthCheckResponse.SERVING
        finally:
            await server.stop(None)

    asyncio.new_event_loop().run_until_complete(run_all())
