"""Multi-chip mesh as the headline lane (ISSUE 11).

Everything here runs on the 8-device virtual CPU mesh that tests/conftest.py
forces via XLA_FLAGS=--xla_force_host_platform_device_count=8 *before* jax
imports (the ``mesh_devices`` fixture asserts the forcing took) — no TPU
needed for tier-1 mesh coverage.

Covers the ISSUE 11 acceptance criteria:
  - bit-exact verdict + attribution parity, mesh vs single-corpus vs host
    oracle, across dp×mp shapes {1×1, 2×1, 2×2, 4×2}, including
    membership-overflow and CPU-fallback rows;
  - verdict-cache keying parity with PR 8: (encoding_epoch,
    rules_fingerprint) tokens, ≥95% survival across a 1-of-N mutation swap;
  - strict-verify lints the packed shards BEFORE the device upload;
  - injected one-device-down resolves batches on healthy devices via
    per-device breaker failover — zero host-degrade decisions until ALL
    devices are down;
  - a one-config mutation ships delta bytes only to the owning shard;
  - grid relief: a corpus that trips cpu-grid-overflow on one device serves
    from the fast lane when rule-sharded, and the lowerability report's
    reason-code count drops.
"""

from __future__ import annotations

import asyncio
import random

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.models.policy_model import host_results
from authorino_tpu.ops.pattern_eval import firing_columns, unpack_attribution
from authorino_tpu.parallel import ShardedPolicyModel, build_mesh
from authorino_tpu.parallel.sharded_eval import (
    MeshUnavailable,
    _reset_mesh_state_for_tests,
)
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime.faults import FAULTS

from test_compiler_differential import oracle_verdict, random_doc, random_expr

pytestmark = pytest.mark.mesh

# dp × mp shapes the acceptance sweep pins (all fit the 8 virtual devices)
SHAPES = [(1, 1), (2, 1), (2, 2), (4, 2)]


def counter_value(name: str, labels=None) -> float:
    from prometheus_client import REGISTRY

    v = REGISTRY.get_sample_value(name, labels or {})
    return v if v is not None else 0.0


@pytest.fixture(autouse=True)
def _fresh_mesh_state():
    """Per-device breakers/occupancy are process-wide per mesh (device
    health outlives snapshots) — isolate tests from each other."""
    _reset_mesh_state_for_tests()
    yield
    FAULTS.disarm()
    _reset_mesh_state_for_tests()


def lane_corpus():
    """A corpus exercising every lane: device-DFA regex rows (incl. byte
    overflow), compiled conditions, membership rows (overflow-capable), and
    a CPU-regex leaf (non-DFA subset)."""
    rx = Pattern("request.url_path", Operator.MATCHES, r"^/api/v[0-9]+/ok")
    cond = Pattern("request.method", Operator.EQ, "GET")
    gated = Pattern("request.path", Operator.EQ, "/gated")
    mem = All(Pattern("auth.identity.roles", Operator.INCL, "admin"),
              Pattern("auth.identity.groups", Operator.EXCL, "banned"))
    # backreference keeps this regex out of the DFA subset → cpu-regex lane
    cpu_rx = Pattern("request.query", Operator.MATCHES, r"^(a+)\1$")
    mix = Any_(rx, Pattern("auth.identity.roles", Operator.INCL, "root"))
    return {
        "cfg-rx": ConfigRules(name="cfg-rx",
                              evaluators=[(None, rx), (cond, gated)]),
        "cfg-mem": ConfigRules(name="cfg-mem", evaluators=[(None, mem)]),
        "cfg-mix": ConfigRules(name="cfg-mix", evaluators=[(cond, mix)]),
        "cfg-cpu": ConfigRules(name="cfg-cpu", evaluators=[(None, cpu_rx)]),
    }


def lane_docs():
    long_ok = "/api/v3/ok" + "x" * 120      # > DFA_VALUE_BYTES → byte overflow
    many = [f"r{k}" for k in range(70)]     # > any relieved K → host fallback
    return [
        ({"request": {"url_path": "/api/v1/ok", "method": "GET",
                      "path": "/gated"}, "auth": {"identity": {}}}, "cfg-rx"),
        ({"request": {"url_path": "/api/x", "method": "POST",
                      "path": "/other"}, "auth": {"identity": {}}}, "cfg-rx"),
        ({"request": {"url_path": long_ok, "method": "GET",
                      "path": "/other"}, "auth": {"identity": {}}}, "cfg-rx"),
        ({"request": {}, "auth": {"identity": {
            "roles": many + ["admin"], "groups": []}}}, "cfg-mem"),
        ({"request": {}, "auth": {"identity": {
            "roles": many, "groups": ["banned"]}}}, "cfg-mem"),
        ({"request": {}, "auth": {"identity": {
            "roles": ["admin"], "groups": []}}}, "cfg-mem"),
        ({"request": {"url_path": "/api/v9/ok", "method": "GET"},
          "auth": {"identity": {"roles": many}}}, "cfg-mix"),
        ({"request": {"url_path": "/zzz", "method": "POST"},
          "auth": {"identity": {"roles": many + ["root"]}}}, "cfg-mix"),
        ({"request": {"query": "aaaa"}, "auth": {}}, "cfg-cpu"),
        ({"request": {"query": "aaa"}, "auth": {}}, "cfg-cpu"),
    ]


def oracle_bits(model: ShardedPolicyModel, doc, name):
    shard, row = model.locator[name]
    _, rule, skipped = host_results(model.shards[shard], doc, int(row))
    return rule, skipped


# ---------------------------------------------------------------------------
# 1. bit-exact parity across dp×mp shapes (acceptance sweep)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dp,mp", SHAPES)
def test_bit_exact_parity_across_shapes(dp, mp, mesh_devices):
    """Mesh lane vs host oracle, all lanes, every pinned shape — run_full's
    (rule, skipped) matrices (host fallback applied, exactly what the
    engine serves) must equal the oracle's bit for bit."""
    corpus = lane_corpus()
    mesh = build_mesh(n_devices=dp * mp, dp=dp)
    model = ShardedPolicyModel(list(corpus.values()), mesh, members_k=4)
    docs = [d for d, _ in lane_docs()]
    names = [n for _, n in lane_docs()]
    rule, skipped = model.run_full(docs, names)
    for r, (doc, name) in enumerate(zip(docs, names)):
        want_rule, want_skip = oracle_bits(model, doc, name)
        E = len(want_rule)
        assert (skipped[r, :E] == want_skip).all(), (dp, mp, r, name)
        # rule bits compare where not condition-skipped: the kernel
        # evaluates skipped columns for real while the oracle leaves them
        # at the vacuous TRUE — both are outside the verdict contract
        live = ~want_skip
        assert (rule[r, :E][live] == want_rule[live]).all(), (dp, mp, r, name)
        # the boolean verdict agrees with the expression oracle
        evs = corpus[name].evaluators
        want = all(
            (cond is not None and not cond.matches(doc)) or rule_e.matches(doc)
            for cond, rule_e in evs)
        got = all(skipped[r, e] or rule[r, e] for e in range(len(evs)))
        assert got == want, (dp, mp, r, name)


@pytest.mark.parametrize("seed", [101, 202, 303])
def test_attribution_parity_property(seed, mesh_devices):
    """Provenance parity (ISSUE 11 satellite): firing_columns /
    unpack_attribution over the shard-stacked bitpacked readback must match
    the host oracle — and the degrade lane (host_decide_many) must
    attribute identically to the device lane it replaces."""
    rng = random.Random(seed)
    configs = []
    for i in range(11):
        evaluators = []
        for _ in range(rng.randint(1, 3)):
            cond = random_expr(rng) if rng.random() < 0.3 else None
            evaluators.append((cond, random_expr(rng)))
        configs.append(ConfigRules(name=f"cfg-{i}", evaluators=evaluators))
    mesh = build_mesh(n_devices=8, dp=2)
    model = ShardedPolicyModel(configs, mesh, members_k=8)
    docs = [random_doc(rng) for _ in range(48)]
    names = [f"cfg-{rng.randrange(len(configs))}" for _ in docs]

    enc = model.encode(docs, names)
    packed = np.asarray(model.dispatch_full(enc))
    E = int(model.shards[0].eval_rule.shape[1])
    verdict, firing = unpack_attribution(packed, E)

    degraded = model.host_decide_many(names, docs)
    for r, (doc, name) in enumerate(zip(docs, names)):
        want_rule, want_skip = oracle_bits(model, doc, name)
        want_fire = int(firing_columns(want_rule[None, :],
                                       want_skip[None, :])[0])
        # degrade lane: always the oracle
        d_rule, d_skip = degraded[r]
        got_fire_d = int(firing_columns(d_rule[None, :], d_skip[None, :])[0])
        assert got_fire_d == want_fire, (r, name)
        if not enc.host_fallback[r]:
            # device lane: bit-identical attribution for non-lossy rows
            assert int(firing[r]) == want_fire, (r, name)
            assert bool(verdict[r]) == oracle_verdict(
                configs[int(name.split("-")[1])], doc), (r, name)


def test_attribution_parity_through_dedup_fanout(mesh_devices):
    """Duplicate rows collapse to unique device work; the inverse fan-out
    must hand every duplicate the same verdict AND the same attribution
    (engine serving path, mesh snapshot)."""
    corpus = lane_corpus()
    engine = PolicyEngine(max_batch=32, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2))
    engine.apply_snapshot([
        EngineEntry(id=n, hosts=[n], runtime=None, rules=c)
        for n, c in corpus.items()])
    deny_doc = {"request": {"url_path": "/api/x", "method": "POST",
                            "path": "/other"}, "auth": {"identity": {}}}

    async def run():
        return await asyncio.gather(
            *(engine.submit(dict(deny_doc), "cfg-rx") for _ in range(6)))

    outs = asyncio.new_event_loop().run_until_complete(run())
    bits = {(tuple(map(bool, r)), tuple(map(bool, s))) for r, s in outs}
    assert len(bits) == 1  # every duplicate decided identically
    rule, skipped = outs[0]
    want_rule, want_skip = oracle_bits(engine._snapshot.sharded,
                                       deny_doc, "cfg-rx")
    E = len(want_rule)
    assert (np.asarray(skipped)[:E] == want_skip).all()
    live = ~want_skip
    assert (np.asarray(rule)[:E][live] == want_rule[live]).all()
    heat = engine._snapshot.heat
    assert heat is not None and heat.fold_calls >= 1


# ---------------------------------------------------------------------------
# 2. verdict-cache keying parity + survival across a 1-of-N mutation swap
# ---------------------------------------------------------------------------


def config_i(i: int, suffix: str = "") -> ConfigRules:
    return ConfigRules(name=f"ns/c{i}", evaluators=[
        (None, Pattern("request.path", Operator.EQ, f"/p{i}{suffix}"))])


def entries_for(configs):
    return [EngineEntry(id=c.name, hosts=[c.name], runtime=None, rules=c)
            for c in configs]


def test_mesh_cache_tokens_survive_one_of_n_mutation(mesh_devices):
    N = 40
    # lane selection off: cache-token survival is a DEVICE encode-path
    # contract (host-lane routing skips encode and the verdict cache)
    engine = PolicyEngine(max_batch=64, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2),
                          verdict_cache_size=4096, lane_select=False)
    engine.apply_snapshot(entries_for([config_i(i) for i in range(N)]))
    snap_old = engine._snapshot
    assert snap_old.mesh_tokens is not None  # PR 8 keying, not generations

    docs = [{"request": {"path": f"/p{i}"}} for i in range(N)]
    names = [f"ns/c{i}" for i in range(N)]

    async def run_all():
        return await asyncio.gather(
            *(engine.submit(d, n) for d, n in zip(docs, names)))

    loop = asyncio.new_event_loop()
    loop.run_until_complete(run_all())
    vc = engine._verdict_cache
    assert vc.counts()["entries"] >= N  # warmed: one entry per config row

    # 1-of-N mutation swap
    engine.apply_snapshot(entries_for(
        [config_i(0, suffix="x")] + [config_i(i) for i in range(1, N)]))
    snap_new = engine._snapshot

    # token parity is the survival mechanism: untouched configs keep the
    # exact (encoding_epoch, rules_fingerprint) token across the swap,
    # the mutated one gets a fresh fingerprint
    sharded = snap_new.sharded
    for i in range(1, N):
        s, r = sharded.locator[f"ns/c{i}"]
        assert snap_new.mesh_tokens[s][r] == snap_old.mesh_tokens[s][r], i
    s0, r0 = sharded.locator["ns/c0"]
    assert snap_new.mesh_tokens[s0][r0] != snap_old.mesh_tokens[s0][r0]

    hits_before = vc.counts()["hits"]
    loop.run_until_complete(run_all())
    hits = vc.counts()["hits"] - hits_before
    assert hits >= int(0.95 * N), hits  # ≥95% survival after 1-of-N swap


def test_mesh_inflight_pinning_inserts_under_own_tokens(mesh_devices):
    """A batch encoded against the OLD snapshot inserts under the old
    snapshot's tokens even if a swap lands mid-flight — token equality for
    untouched configs then makes those entries hit on the new snapshot."""
    engine = PolicyEngine(max_batch=8, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2),
                          verdict_cache_size=256)
    engine.apply_snapshot(entries_for([config_i(i) for i in range(4)]))
    old = engine._snapshot
    # swap BEFORE any traffic: in-flight pinning means the pinned snapshot
    # object (not engine._snapshot at completion time) provides the tokens
    engine.apply_snapshot(entries_for(
        [config_i(0, "x")] + [config_i(i) for i in range(1, 4)]))
    new = engine._snapshot
    assert old is not new
    s, r = new.sharded.locator["ns/c2"]
    assert new.mesh_tokens[s][r] == old.mesh_tokens[s][r]


# ---------------------------------------------------------------------------
# 3. strict verify: lint the packed shards BEFORE the upload (PR 4 caveat)
# ---------------------------------------------------------------------------


def test_strict_verify_lints_before_mesh_upload(monkeypatch, mesh_devices):
    from authorino_tpu.analysis import tensor_lint as lint_mod

    staged_at_lint = []
    real = lint_mod.lint_snapshot

    def probe(snap, *a, **kw):
        if getattr(snap, "sharded", None) is not None:
            # params is the DEVICE pytree — None means nothing staged yet
            staged_at_lint.append(snap.sharded.params is not None)
        return real(snap, *a, **kw)

    monkeypatch.setattr(lint_mod, "lint_snapshot", probe)
    engine = PolicyEngine(max_batch=8, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2),
                          strict_verify=True)
    engine.apply_snapshot(entries_for([config_i(i) for i in range(6)]))
    assert staged_at_lint == [False]          # lint ran pre-upload
    assert engine._snapshot.sharded.params is not None  # then staged
    assert engine._snapshot.lint_ok


def test_strict_verify_rejection_never_stages(monkeypatch, mesh_devices):
    from authorino_tpu.analysis import Finding
    from authorino_tpu.analysis import tensor_lint as lint_mod
    from authorino_tpu.runtime.engine import SnapshotRejected

    uploads = []
    real_upload = ShardedPolicyModel.upload

    def counting_upload(self, prev=None):
        uploads.append(self)
        return real_upload(self, prev)

    monkeypatch.setattr(ShardedPolicyModel, "upload", counting_upload)
    monkeypatch.setattr(
        lint_mod, "lint_snapshot",
        lambda snap, *a, **kw: [Finding(
            kind="shard-stack", message="synthetic corruption",
            layer="tensor_lint", severity="error")])
    engine = PolicyEngine(max_batch=8, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2),
                          strict_verify=True)
    with pytest.raises(SnapshotRejected):
        engine.apply_snapshot(entries_for([config_i(0)]))
    assert uploads == []  # a rejected corpus never shipped a byte


# ---------------------------------------------------------------------------
# 4. per-device failover: one device down ≠ host degrade
# ---------------------------------------------------------------------------


def run_batches(engine, n_rounds=6, n=8, idxs=(0, 1, 2, 3)):
    """Submit ``n_rounds`` batches of matching-path requests over the
    configs named by ``idxs`` (each doc matches its own config's pattern,
    so every verdict is expected allow)."""
    docs = [{"request": {"path": f"/p{idxs[i % len(idxs)]}"}}
            for i in range(n)]
    names = [f"ns/c{idxs[i % len(idxs)]}" for i in range(n)]
    async def round_():
        return await asyncio.gather(
            *(engine.submit(d, nm) for d, nm in zip(docs, names)))

    loop = asyncio.new_event_loop()
    outs = []
    for _ in range(n_rounds):
        outs += loop.run_until_complete(round_())
    got = [bool(rule[0]) for rule, _ in outs]
    return got, [True] * (n * n_rounds)


def test_one_device_down_fails_over_without_degrade(mesh_devices):
    engine = PolicyEngine(max_batch=8, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2),
                          verdict_cache_size=0, batch_dedup=False)
    engine.apply_snapshot(entries_for([config_i(i) for i in range(4)]))
    degraded_before = counter_value("auth_server_degraded_decisions_total",
                                    {"lane": "engine"})
    failover_before = counter_value("auth_server_device_failover_total",
                                    {"device": "0"})
    FAULTS.arm("one-device-down")  # kernel:raise:device=0
    try:
        got, expected = run_batches(engine)
    finally:
        FAULTS.disarm()
    assert got == expected  # verdicts exact throughout the incident
    # zero host-oracle decisions: every batch resolved on a healthy device
    assert counter_value("auth_server_degraded_decisions_total",
                         {"lane": "engine"}) == degraded_before
    assert counter_value("auth_server_device_failover_total",
                         {"device": "0"}) > failover_before
    mesh_vars = engine.debug_vars()["mesh"]
    b0 = mesh_vars["breakers"]["0"]
    assert b0["consecutive_failures"] > 0 or b0["state"] != "closed"
    assert mesh_vars["failovers"]["0"] > 0
    # healthy devices actually absorbed the traffic
    assert sum(int(v) for d, v in mesh_vars["launches"].items()
               if d != "0") > 0


def test_open_device_reprobes_and_rejoins_the_mesh(mesh_devices):
    """Recovery: an OPEN device whose cooldown elapsed must actually get
    its half-open probe from live traffic (due probes sort FIRST in
    dispatch_routed — closed-first ordering would starve the probe and
    strand the mesh in single-device dispatch forever), and a successful
    probe returns the lane to full-mesh launches."""
    # breaker_threshold reaches the per-DEVICE mesh breakers too (the
    # engine plumbs it into MeshState at first touch of the mesh).
    # Lane selection off: the probe must come from live DEVICE traffic —
    # with the cost model live, these small cuts would ride the host lane
    # and the reprobe timing would depend on explore cadence instead
    engine = PolicyEngine(max_batch=8, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2),
                          verdict_cache_size=0, batch_dedup=False,
                          breaker_threshold=3, lane_select=False)
    engine.apply_snapshot(entries_for([config_i(i) for i in range(4)]))
    FAULTS.arm("one-device-down")  # kernel:raise:device=0
    try:
        run_batches(engine, n_rounds=4)  # walk device 0's breaker open
    finally:
        FAULTS.disarm()
    state = engine._snapshot.sharded.state
    b0 = state.breakers.get(0)
    assert b0.state == "open"
    full_launches_before = state.launches[0]
    b0._opened_at -= b0.reset_s + 1.0  # cooldown elapsed (no wall sleep)
    got, expected = run_batches(engine, n_rounds=3)
    assert got == expected
    # the probe fired on device 0, succeeded, and closed the breaker
    assert b0.state == "closed"
    assert [t["state"] for t in b0.to_json()["transitions"]][-2:] == \
        ["half-open", "closed"]
    # ...and full-mesh launches resumed (device 0 participates again)
    assert state.launches[0] > full_launches_before


def test_all_devices_down_degrades_exactly(mesh_devices):
    engine = PolicyEngine(max_batch=8, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2),
                          verdict_cache_size=0, batch_dedup=False,
                          breaker_threshold=1000)
    engine.apply_snapshot(entries_for([config_i(i) for i in range(4)]))
    degraded_before = counter_value("auth_server_degraded_decisions_total",
                                    {"lane": "engine"})
    # every device id scoped down → MeshUnavailable → retry → host degrade
    FAULTS.arm(";".join(f"kernel:raise:device={d}" for d in range(8)))
    try:
        got, expected = run_batches(engine, n_rounds=2)
    finally:
        FAULTS.disarm()
    assert got == expected  # host oracle keeps answers exact
    assert counter_value("auth_server_degraded_decisions_total",
                         {"lane": "engine"}) > degraded_before


def test_mesh_unavailable_when_all_breakers_exhausted(mesh_devices):
    corpus = [config_i(i) for i in range(4)]
    model = ShardedPolicyModel([c for c in corpus],
                               build_mesh(n_devices=8, dp=2), members_k=4)
    enc = model.encode([{"request": {"path": "/p0"}}], ["ns/c0"])
    FAULTS.arm(";".join(f"kernel:raise:device={d}" for d in range(8)))
    try:
        with pytest.raises(MeshUnavailable):
            model.dispatch_routed(enc)
    finally:
        FAULTS.disarm()
    # every device recorded its failure
    assert all(v >= 1 for v in model.state.failovers.values())


# ---------------------------------------------------------------------------
# 5. per-shard delta uploads: a one-config mutation feeds its owning shard
# ---------------------------------------------------------------------------


def test_one_config_mutation_ships_to_owning_shard_only(mesh_devices):
    N = 8
    engine = PolicyEngine(max_batch=8, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2))
    engine.apply_snapshot(entries_for([config_i(i) for i in range(N)]))
    first = engine._snapshot.upload
    assert first["mode"] == "full"

    engine.apply_snapshot(entries_for(
        [config_i(0, suffix="x")] + [config_i(i) for i in range(1, N)]))
    up = engine._snapshot.upload
    assert up["mode"] == "delta"
    assert up["upload_bytes"] * 2 <= up["full_bytes"]  # ≪ full mesh upload
    owner, _ = engine._snapshot.sharded.locator["ns/c0"]
    per_shard = up["per_shard_bytes"]
    assert per_shard[str(owner)] > 0
    for s, b in per_shard.items():
        if s != str(owner):
            assert b == 0, (s, per_shard)  # unchanged shards got zero bytes

    # and the delta-staged corpus still serves exact verdicts (c0's new
    # pattern no longer matches /p0; the untouched configs all allow)
    got, expected = run_batches(engine, n_rounds=1, n=7,
                                idxs=tuple(range(1, N)))
    assert got == expected


# ---------------------------------------------------------------------------
# 6. grid relief: cpu-grid-overflow exiles serve from the fast lane
# ---------------------------------------------------------------------------


def membership_corpus(n=6):
    return [ConfigRules(name=f"m/c{i}", evaluators=[
        (None, Pattern("auth.identity.roles", Operator.INCL, f"g{i}"))])
        for i in range(n)]


def relief_docs(n=6, roles=40):
    # 40 roles overflow the single-corpus K=16 but fit the mesh's relieved
    # K (≥ 32; 64 on mp=4) — the exact rows grid relief rescues
    return ([{"auth": {"identity": {
        "roles": [f"x{k}" for k in range(roles)] + [f"g{i}"]}}}
        for i in range(n)],
        [f"m/c{i}" for i in range(n)])


def test_grid_relief_serves_overflow_from_fast_lane(mesh_devices):
    docs, names = relief_docs()
    single = PolicyEngine(max_batch=8, members_k=16, mesh=None)
    single.apply_snapshot(entries_for(membership_corpus()))
    sharded = PolicyEngine(max_batch=8, members_k=16,
                           mesh=build_mesh(n_devices=8, dp=2))
    sharded.apply_snapshot(entries_for(membership_corpus()))

    # single corpus: every row is a host-fallback exile (lossy compact K)
    from authorino_tpu.compiler.encode import encode_batch
    from authorino_tpu.compiler.pack import pack_batch

    pol = single._snapshot.policy
    rows = [pol.config_ids[n] for n in names]
    db = pack_batch(pol, encode_batch(pol, docs, rows))
    assert db.host_fallback[: len(docs)].all()

    # mesh: the same rows ride the kernel (no fallback), bit-exact verdicts
    enc = sharded._snapshot.sharded.encode(docs, names)
    assert not enc.host_fallback[: len(docs)].any()
    assert sharded._snapshot.sharded.decide(docs, names) == [True] * len(docs)

    # lowerability: the caveat count drops to zero on the mesh report
    single_report = single._lowerability["by_reason"]
    mesh_report = sharded._lowerability["by_reason"]
    assert single_report.get("cpu-grid-overflow", 0) == len(names)
    assert mesh_report.get("cpu-grid-overflow", 0) == 0


# ---------------------------------------------------------------------------
# 7. mesh↔mesh canary (control-plane parity)
# ---------------------------------------------------------------------------


def test_mesh_canary_promotes_clean_window(mesh_devices):
    engine = PolicyEngine(max_batch=8, members_k=4,
                          mesh=build_mesh(n_devices=8, dp=2),
                          canary_fraction=0.5, canary_window_s=0.3)
    engine.apply_snapshot(entries_for([config_i(i) for i in range(4)]))
    gen_baseline = engine._snapshot.generation
    engine.apply_snapshot(entries_for(
        [config_i(0, suffix="x")] + [config_i(i) for i in range(1, 4)]))
    assert engine._canary is not None  # mesh↔mesh swaps canary now
    phase = engine._canary
    # traffic over the configs the reconcile did NOT touch: both cohorts
    # must allow identically, so the guard window stays clean
    got, expected = run_batches(engine, n_rounds=2, n=6, idxs=(1, 2, 3))
    assert got == expected
    engine._canary_conclude(phase)
    assert engine._canary is None
    assert engine._snapshot.generation > gen_baseline
    assert engine._snapshot.sharded is phase.snap.sharded
