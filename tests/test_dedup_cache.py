"""Dedup-and-cache the hot path (ISSUE 3): batch row dedup must be
bit-identical to full evaluation (dedup-evaluate-scatter property), the
snapshot-scoped verdict cache must never serve a stale verdict across a
snapshot swap (generation-keyed, structural invalidation), non-cacheable
configs must bypass the cache, and the compiler's rule-tensor compaction
(node dedup + shared DFA tables) must preserve semantics.

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports)."""

from __future__ import annotations

import asyncio
import random
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules, compile_corpus
from authorino_tpu.compiler.encode import encode_batch_py
from authorino_tpu.compiler.pack import (
    batch_row_keys,
    dedup_rows,
    pack_batch,
    row_key_bytes,
    select_rows,
)
from authorino_tpu.expressions import All, Any_, Operator, Pattern
from authorino_tpu.ops import pattern_eval as pe
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.utils.verdict_cache import VerdictCache


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def _corpus(n_configs=9):
    rng = random.Random(3)
    configs = []
    for i in range(n_configs):
        rule = All(
            Pattern("request.method", Operator.EQ, rng.choice(["GET", "POST"])),
            Any_(
                Pattern("auth.identity.roles", Operator.INCL, f"role-{i % 4}"),
                Pattern("auth.identity.org", Operator.NEQ, f"org-{i % 3}"),
                Pattern("request.url_path", Operator.MATCHES, rf"^/svc-{i % 2}/"),
            ),
        )
        configs.append(ConfigRules(name=f"cfg-{i}", evaluators=[(None, rule)]))
    return configs


def _doc(rng, n_roles=None):
    return {
        "request": {
            "method": rng.choice(["GET", "POST", "PUT"]),
            "url_path": rng.choice(["/svc-0/a", "/svc-1/b", "/other"]),
        },
        "auth": {"identity": {
            "org": f"org-{rng.randrange(5)}",
            # members_k=4 below: > 4 roles forces membership overflow →
            # a host-fallback row (the lossy-encoding case the row key
            # must fold in)
            "roles": [f"role-{rng.randrange(6)}" for _ in range(
                rng.randrange(0, 8) if n_roles is None else n_roles)],
        }},
    }


def _dup_docs(n, dup_fraction, seed=11):
    """n docs where ~dup_fraction of rows repeat an earlier doc exactly."""
    rng = random.Random(seed)
    docs = []
    for _ in range(n):
        if docs and rng.random() < dup_fraction:
            docs.append(rng.choice(docs))
        else:
            docs.append(_doc(rng))
    return docs


# ---------------------------------------------------------------------------
# tentpole property: dedup-evaluate-scatter ≡ full evaluation, bit for bit
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dup_fraction,seed", [
    (0.0, 1),     # all-unique extreme (dedup is the identity)
    (0.5, 2),
    (0.9, 3),
    (1.0, 4),     # all-duplicate extreme (one device row)
])
def test_dedup_evaluate_scatter_bit_identical(dup_fraction, seed):
    policy = compile_corpus(_corpus(), members_k=4)
    params = pe.to_device(policy)
    rng = random.Random(seed)
    n, pad = 48, 64
    docs = ([_doc(rng)] * n if dup_fraction == 1.0
            else _dup_docs(n, dup_fraction, seed=seed))
    rows = ([0] * n if dup_fraction == 1.0
            else [rng.randrange(policy.n_configs) for _ in range(n)])
    db = pack_batch(policy, encode_batch_py(policy, docs, rows, batch_pad=pad))

    reference = np.asarray(pe.dispatch_packed(params, db))[:n]  # [n, 1+2E]

    keys = batch_row_keys(db, n)
    unique_rows, inverse = dedup_rows(keys, list(range(n)))
    u = len(unique_rows)
    if dup_fraction == 1.0:
        assert u == 1
    if dup_fraction == 0.0:
        assert u == n
    db_u = select_rows(db, unique_rows, batch_pad=u + (-u % 16))
    packed_u = np.asarray(pe.dispatch_packed(params, db_u))
    scattered = packed_u[inverse]  # fan unique verdicts back out
    np.testing.assert_array_equal(scattered, reference)


def test_row_keys_fold_in_the_lossy_fallback_flag():
    """Two requests identical in the compact payload but differing in
    membership overflow (first K elements equal, one has extras) must get
    DIFFERENT row keys — aliasing them would let a cached/deduped verdict
    stand in for a row whose true answer only the host oracle knows."""
    policy = compile_corpus(_corpus(), members_k=4)
    rng = random.Random(7)
    base = _doc(rng, n_roles=4)
    over = {"request": dict(base["request"]),
            "auth": {"identity": dict(base["auth"]["identity"])}}
    # same first K=4 roles, then overflow
    over["auth"]["identity"]["roles"] = (
        base["auth"]["identity"]["roles"] + ["extra-1", "extra-2"])
    db = pack_batch(policy, encode_batch_py(policy, [base, over], [0, 0],
                                            batch_pad=16))
    assert bool(db.host_fallback[1]) and not bool(db.host_fallback[0])
    keys = batch_row_keys(db, 2)
    assert keys[0] != keys[1]


def test_row_key_bytes_empty_batch():
    assert row_key_bytes([np.zeros((4, 2), dtype=np.int32)], 0) == []


# ---------------------------------------------------------------------------
# engine integration: dedup + cache on the pipelined dispatch path
# ---------------------------------------------------------------------------

RULE_ACME = Pattern("auth.identity.org", Operator.EQ, "acme")
RULE_EVIL = Pattern("auth.identity.org", Operator.EQ, "evil")


def build_engine(rule=RULE_ACME, name="c", **kw) -> PolicyEngine:
    kw.setdefault("max_batch", 8)
    # dedup + verdict-cache contracts live on the DEVICE encode path; the
    # cost model would route these small warm-RTT cuts host-side (which
    # legitimately bypasses encode and the cache — lane-selection
    # semantics are pinned in tests/test_lane_select.py)
    kw.setdefault("lane_select", False)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id=name, hosts=[name], runtime=None,
                    rules=ConfigRules(name=name, evaluators=[(None, rule)]))
    ])
    return engine


def doc(org="acme"):
    return {"auth": {"identity": {"org": org}}}


def test_engine_results_identical_with_and_without_dedup_cache():
    """The same submissions (duplicates included) through a dedup+cache
    engine and a both-off engine resolve to identical verdicts."""
    on = build_engine(verdict_cache_size=1024, batch_dedup=True)
    off = build_engine(verdict_cache_size=0, batch_dedup=False)
    orgs = ["acme", "evil", "acme", "acme", "zed", "evil", "acme", "acme"]

    async def drive(engine):
        return await asyncio.gather(*(engine.submit(doc(o), "c")
                                      for o in orgs))

    got_on = run(drive(on))
    got_off = run(drive(off))
    for (r1, s1), (r2, s2) in zip(got_on, got_off):
        np.testing.assert_array_equal(r1, r2)
        np.testing.assert_array_equal(s1, s2)
    assert [bool(r[0]) for r, _ in got_on] == [o == "acme" for o in orgs]


def test_engine_verdict_cache_hits_repeat_rows():
    engine = build_engine(verdict_cache_size=1024)

    async def burst():
        return await asyncio.gather(*(engine.submit(doc("acme"), "c")
                                      for _ in range(6)))

    run(burst())          # first batch: misses + adds
    hits0 = engine._verdict_cache.hits
    outs = run(burst())   # same row digest: served from the cache
    assert engine._verdict_cache.hits > hits0
    assert all(bool(r[0]) for r, _ in outs)


def test_snapshot_swap_never_serves_stale_cached_verdict():
    """Generation-keyed invalidation with batches IN FLIGHT across the
    swap: entries inserted under generation G must not satisfy lookups
    under G+1, even while a gated G batch is still completing."""
    engine = build_engine(rule=RULE_ACME, verdict_cache_size=1024)
    run(engine.submit(doc("acme"), "c"))  # warm jit + seed the G cache
    assert engine._verdict_cache.adds >= 1

    gate = threading.Event()
    real = PolicyEngine._encode_and_launch
    gated_launches = []

    class GatedHandle:
        def __init__(self, inner):
            self.inner = inner

        def is_ready(self):
            return gate.is_set() and (
                not hasattr(self.inner, "is_ready") or self.inner.is_ready())

        def __array__(self, dtype=None):
            return np.asarray(self.inner)

    def gated(snap, batch):
        item = real(engine, snap, batch)
        item.handle = GatedHandle(item.handle)
        gated_launches.append(item)
        return item

    engine._encode_and_launch = gated

    async def body():
        # a G batch launches (cache-missing doc) and stays in flight
        pre = [asyncio.ensure_future(engine.submit(doc("evil"), "c"))
               for _ in range(4)]
        deadline = time.monotonic() + 5
        while not gated_launches and time.monotonic() < deadline:
            await asyncio.sleep(0.005)
        assert gated_launches
        # swap: acme (cached as ALLOWED under G) is now DENIED
        engine._encode_and_launch = real.__get__(engine, PolicyEngine)
        engine.apply_snapshot([
            EngineEntry(id="c", hosts=["c"], runtime=None,
                        rules=ConfigRules(name="c",
                                          evaluators=[(None, RULE_EVIL)]))
        ])
        post = await asyncio.gather(*(engine.submit(doc("acme"), "c")
                                      for _ in range(3)))
        # G's cached ALLOW for acme must NOT leak into G+1
        assert not any(bool(r[0]) for r, _ in post)
        # and evil is allowed under G+1 (fresh evaluation, then cached)
        post_evil = await engine.submit(doc("evil"), "c")
        assert bool(post_evil[0][0])
        gate.set()
        outs = await asyncio.wait_for(asyncio.gather(*pre), timeout=10)
        # the in-flight G batch resolves with G semantics: evil denied
        assert not any(bool(r[0]) for r, _ in outs)

    run(body())
    # the in-flight batch's late inserts landed under G, not G+1: a fresh
    # G+1 lookup of the same evil row still answers from G+1's own entry
    out = run(engine.submit(doc("evil"), "c"))
    assert bool(out[0][0])


def test_non_cacheable_configs_bypass_the_cache():
    """A config whose rules reference a request-unique selector compiles
    with cacheable=False and must neither insert nor serve from the
    verdict cache."""
    rule = All(Pattern("request.id", Operator.NEQ, ""),
               Pattern("auth.identity.org", Operator.EQ, "acme"))
    policy = compile_corpus([ConfigRules(name="c", evaluators=[(None, rule)])])
    assert not bool(policy.config_cacheable[0])

    engine = build_engine(rule=rule, verdict_cache_size=1024)
    d = {"request": {"id": "r-1"}, "auth": {"identity": {"org": "acme"}}}

    async def twice():
        a = await engine.submit(d, "c")
        b = await engine.submit(d, "c")
        return a, b

    (r1, _), (r2, _) = run(twice())
    assert bool(r1[0]) and bool(r2[0])
    vc = engine._verdict_cache
    assert vc.adds == 0 and vc.hits == 0
    # ...while a cacheable config on the same engine does use it
    cacheable_policy = compile_corpus(
        [ConfigRules(name="c2", evaluators=[(None, RULE_ACME)])])
    assert bool(cacheable_policy.config_cacheable[0])


def test_dedup_can_be_disabled():
    engine = build_engine(verdict_cache_size=0, batch_dedup=False)

    async def burst():
        return await asyncio.gather(*(engine.submit(doc("acme"), "c")
                                      for _ in range(6)))

    outs = run(burst())
    assert all(bool(r[0]) for r, _ in outs)
    assert engine._verdict_cache is None


# ---------------------------------------------------------------------------
# verdict cache unit behavior
# ---------------------------------------------------------------------------

def test_verdict_cache_lru_bound_and_counters():
    vc = VerdictCache(max_entries=2)
    vc.put(("g1", b"a"), 1)
    vc.put(("g1", b"b"), 2)
    assert vc.get(("g1", b"a")) == 1          # refreshes a
    vc.put(("g1", b"c"), 3)                   # evicts b (LRU)
    assert vc.get(("g1", b"b")) is None
    assert vc.get(("g1", b"a")) == 1
    assert vc.evictions == 1 and vc.adds == 3
    assert vc.hits == 2 and vc.misses == 1
    assert len(vc) == 2


def test_verdict_cache_generation_keys_are_disjoint():
    vc = VerdictCache()
    vc.put((1, b"row"), "old")
    assert vc.get((2, b"row")) is None  # structural invalidation by keying


# ---------------------------------------------------------------------------
# rule-tensor compaction: node dedup + shared DFA tables
# ---------------------------------------------------------------------------

def test_identical_rule_trees_share_circuit_nodes():
    rule = lambda: All(  # noqa: E731 - fresh tree per config
        Pattern("request.method", Operator.EQ, "GET"),
        Any_(Pattern("auth.identity.org", Operator.EQ, "a"),
             Pattern("auth.identity.org", Operator.EQ, "b")),
    )
    one = compile_corpus([ConfigRules(name="c0", evaluators=[(None, rule())])],
                         pad=False)
    many = compile_corpus(
        [ConfigRules(name=f"c{i}", evaluators=[(None, rule())])
         for i in range(5)], pad=False)
    # 5 configs with the identical tree lower to the SAME circuit size
    assert many.buffer_size == one.buffer_size
    # and every config's verdict still reads its own (shared) slots
    docs = [{"request": {"method": "GET"},
             "auth": {"identity": {"org": "a"}}},
            {"request": {"method": "POST"},
             "auth": {"identity": {"org": "a"}}}]
    params = pe.to_device(many)
    db = pack_batch(many, encode_batch_py(many, docs, [2, 3], batch_pad=8))
    own, _ = pe.eval_batch_jit(params, db)
    assert bool(own[0]) and not bool(own[1])


def test_shared_regex_dfa_tables_dedupe_across_attrs_and_configs():
    pattern = r"^/api/v\d+/"
    configs = [
        ConfigRules(name="c0", evaluators=[
            (None, Pattern("request.url_path", Operator.MATCHES, pattern))]),
        ConfigRules(name="c1", evaluators=[
            (None, Pattern("request.path", Operator.MATCHES, pattern))]),
        ConfigRules(name="c2", evaluators=[
            (None, Pattern("request.headers.x-route", Operator.MATCHES,
                           pattern))]),
    ]
    policy = compile_corpus(configs)
    # three DFA rows (three attrs), ONE shared transition table
    assert int(policy.dfa_table_of_row.shape[0]) >= 3
    assert int(policy.dfa_tables.shape[0]) == 1
    assert np.array_equal(policy.dfa_table_of_row[:3], [0, 0, 0])
    # expanded view hands per-row tables to row-indexed consumers
    assert policy.dfa_tables_by_row.shape[0] == policy.dfa_table_of_row.shape[0]
    # and the deduped gather-lane scan still answers exactly (each request
    # judged against its OWN config — the encoder only resolves own attrs)
    params = pe.to_device(policy, lane="gather")
    docs = [{"request": {"url_path": "/api/v3/x"}},
            {"request": {"path": "/api/v2/z"}},
            {"request": {"headers": {"x-route": "/zzz"}}}]
    db = pack_batch(policy, encode_batch_py(policy, docs, [0, 1, 2],
                                            batch_pad=8))
    own, _ = pe.eval_batch_jit(params, db)
    assert bool(own[0])        # c0: url_path matches the shared DFA
    assert bool(own[1])        # c1: path matches through the SAME table
    assert not bool(own[2])    # c2: x-route does not match


# ---------------------------------------------------------------------------
# perf guard: dedup must beat the no-dedup path on a 90%-duplicate batch
# ---------------------------------------------------------------------------

@pytest.mark.perf_guard
def test_dedup_beats_full_evaluation_on_90pct_duplicates():
    """Device-work micro-bench: a 512-row batch with ~90% duplicates
    evaluates faster through dedup-evaluate-scatter (≤ 64 unique rows on
    the kernel) than shipping all 512 rows.  Min-of-runs on both sides to
    shed scheduler noise."""
    policy = compile_corpus(_corpus(24), members_k=4)
    params = pe.to_device(policy)
    rng = random.Random(5)
    uniques = [_doc(rng, n_roles=2) for _ in range(48)]
    docs = [rng.choice(uniques) for _ in range(512)]
    rows = [hash(id(d)) % policy.n_configs for d in docs]
    rows = [r % policy.n_configs for r in rows]
    db = pack_batch(policy, encode_batch_py(policy, docs, rows, batch_pad=512))
    n = len(docs)
    keys = batch_row_keys(db, n)
    unique_rows, inverse = dedup_rows(keys, list(range(n)))
    u = len(unique_rows)
    assert u <= 64, f"workload not duplicate-heavy enough: {u} unique"
    from authorino_tpu.utils import bucket_pow2

    db_u = select_rows(db, unique_rows, batch_pad=bucket_pow2(u))

    # warm both jit variants off the clock
    np.asarray(pe.dispatch_packed(params, db, bitpack=True))
    np.asarray(pe.dispatch_packed(params, db_u, bitpack=True))

    def best_of(fn, runs=5):
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t_full = best_of(lambda: np.asarray(
        pe.dispatch_packed(params, db, bitpack=True)))
    t_dedup = best_of(lambda: (
        np.asarray(pe.dispatch_packed(params, db_u, bitpack=True))[inverse]))
    assert t_dedup < t_full, (
        f"dedup path ({t_dedup * 1e3:.2f}ms, {u} rows) not faster than "
        f"full evaluation ({t_full * 1e3:.2f}ms, {n} rows)")


# ---------------------------------------------------------------------------
# packed-bitmask helpers
# ---------------------------------------------------------------------------

def test_packed_width():
    assert pe.packed_width(1) == 1
    assert pe.packed_width(8) == 1
    assert pe.packed_width(9) == 2
    assert pe.packed_width(17) == 3


def test_unpack_verdicts_known_bytes():
    packed = np.array([[0b00000111, 0b00000001]], dtype=np.uint8)
    got = pe.unpack_verdicts(packed, 9)
    assert got.shape == (1, 9)
    assert got[0].tolist() == [True, True, True, False, False,
                               False, False, False, True]
