"""Tenant QoS plane (ISSUE 15): weighted-fair batch cuts, per-tenant
quotas/SLO, noisy-neighbor containment, stratified decision sampling, and
the tenant-label cardinality lint.

Deliberately import-light: collects on images without `cryptography`
(no evaluators.identity / native_frontend imports)."""

from __future__ import annotations

import asyncio
import time
from collections import deque

import numpy as np
import pytest

from authorino_tpu.compiler import ConfigRules
from authorino_tpu.expressions import All, Operator, Pattern
from authorino_tpu.runtime import EngineEntry, PolicyEngine
from authorino_tpu.runtime import provenance as prov_mod
from authorino_tpu.runtime.admission import ADMIT, AdmissionController
from authorino_tpu.runtime.flight_recorder import RECORDER
from authorino_tpu.tenancy import (
    R_TENANT_CONTAINED,
    R_TENANT_QUOTA,
    FairCutter,
    NoisyNeighborDetector,
    TenantAdmission,
    TenantPlane,
    TenantStats,
    WeightBook,
)
from authorino_tpu.utils.rpc import RESOURCE_EXHAUSTED, CheckAbort
from authorino_tpu.utils.slo import KeyedBurn


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


RULE = All(
    Pattern("auth.identity.roles", Operator.INCL, "admin"),
    Pattern("auth.identity.groups", Operator.EXCL, "banned"),
)


def build_engine(n_tenants=3, annotations=None, **kw) -> PolicyEngine:
    kw.setdefault("verdict_cache_size", 0)
    kw.setdefault("max_batch", 8)
    engine = PolicyEngine(members_k=4, mesh=None, **kw)
    engine.apply_snapshot([
        EngineEntry(id=f"t{i}", hosts=[f"t{i}"], runtime=None,
                    rules=ConfigRules(name=f"t{i}",
                                      evaluators=[(None, RULE)]),
                    annotations=(annotations or {}).get(f"t{i}"))
        for i in range(n_tenants)
    ])
    return engine


def doc(i: int, allow: bool = True) -> dict:
    return {"auth": {"identity": {
        "roles": ["admin", f"r{i}"] if allow else [f"r{i}"],
        "groups": []}}}


class P:
    """Minimal _Pending stand-in for the cutter/admission units."""

    def __init__(self, tenant, seq=0):
        self.config_name = tenant
        self.seq = seq
        self.t_enq = time.monotonic()


# ---------------------------------------------------------------------------
# weights from annotations
# ---------------------------------------------------------------------------


class TestWeights:
    def test_class_weight_quota_resolution(self):
        book = WeightBook()
        book.rebuild({
            "gold": {"authorino.tpu/qos-class": "Gold"},
            "explicit": {"authorino.tpu/qos-weight": "7.5"},
            "quota": {"authorino.tpu/qos-quota-rps": "25"},
            "junk": {"authorino.tpu/qos-weight": "not-a-number"},
            "plain": None,
        })
        assert book.weight("gold") == 4.0
        assert book.weight("explicit") == 7.5
        assert book.weight("junk") == 1.0       # typo never zeroes a share
        assert book.weight("plain") == 1.0
        assert book.weight("never-seen") == 1.0
        assert book.quota_rps("quota") == 25.0
        assert book.quota_rps("plain") == 0.0

    def test_override_beats_annotation(self):
        book = WeightBook(overrides={"t": 9.0})
        book.rebuild({"t": {"authorino.tpu/qos-weight": "2"}})
        assert book.weight("t") == 9.0

    def test_share_is_relative_to_backlogged_set(self):
        book = WeightBook()
        book.rebuild({"a": {"authorino.tpu/qos-weight": "3"}, "b": None})
        assert book.share("a", ["a", "b"]) == pytest.approx(0.75)
        assert book.share("a", ["a"]) == 1.0
        assert book.share("b", []) == 1.0

    def test_engine_binds_annotations_at_reconcile(self):
        engine = build_engine(annotations={
            "t0": {"authorino.tpu/qos-weight": "4"}})
        assert engine.tenancy.book.weight("t0") == 4.0
        assert engine.tenancy.book.weight("t1") == 1.0


# ---------------------------------------------------------------------------
# weighted-fair cut: work conservation, share accuracy, ordering
# ---------------------------------------------------------------------------


class TestFairCut:
    def test_sole_backlogged_tenant_gets_the_full_batch(self):
        """Work conservation: with one tenant backlogged, fairness must
        never leave batch slots empty."""
        book = WeightBook()
        book.rebuild({"a": None})
        cutter = FairCutter(book.weight)
        q = deque(P("a", i) for i in range(40))
        batch = cutter.cut(q, 16)
        assert len(batch) == 16
        assert [p.seq for p in batch] == list(range(16))

    def test_uncontended_cut_equals_unfair_pop(self):
        cutter = FairCutter(lambda t: 1.0)
        q = deque(P("a", i) for i in range(5))
        batch = cutter.cut(q, 8)
        assert [p.seq for p in batch] == list(range(5)) and not q

    @pytest.mark.parametrize("weights", [
        {"a": 1.0, "b": 1.0},
        {"a": 1.0, "b": 4.0},
        {"a": 1.0, "b": 2.0, "c": 4.0},
    ])
    def test_share_accuracy_within_one_batch_of_slack(self, weights):
        """Property (ISSUE 15 satellite): with every tenant persistently
        backlogged, cumulative selected counts track the weight mix within
        one batch of slack, under three weight mixes."""
        book = WeightBook()
        book.rebuild({t: {"authorino.tpu/qos-weight": str(w)}
                      for t, w in weights.items()})
        cutter = FairCutter(book.weight)
        n, cuts = 16, 24
        got = {t: 0 for t in weights}
        q = deque()
        seq = 0
        for _ in range(cuts):
            # replenish so every tenant stays deeply backlogged
            for t in weights:
                for _ in range(2 * n):
                    q.append(P(t, seq))
                    seq += 1
            for p in cutter.cut(q, n):
                got[p.config_name] += 1
        total_w = sum(weights.values())
        for t, w in weights.items():
            expected = cuts * n * w / total_w
            assert abs(got[t] - expected) <= n, (
                f"tenant {t}: got {got[t]}, expected ~{expected:.0f} "
                f"(mix {weights})")

    def test_work_conserving_spill_when_a_tenant_drains(self):
        """Unused share spills: a tenant with fewer rows than its share
        frees the rest of the batch to the backlogged tenant."""
        book = WeightBook()
        book.rebuild({"big": {"authorino.tpu/qos-weight": "8"},
                      "small": None})
        cutter = FairCutter(book.weight)
        q = deque([P("big", i) for i in range(3)]
                  + [P("small", 100 + i) for i in range(40)])
        batch = cutter.cut(q, 16)
        assert len(batch) == 16
        assert sum(1 for p in batch if p.config_name == "big") == 3
        assert sum(1 for p in batch if p.config_name == "small") == 13

    def test_arrival_order_preserved_within_batch_and_remainder(self):
        cutter = FairCutter(lambda t: 1.0)
        items = []
        q = deque()
        for i in range(30):
            p = P("hot" if i % 3 else "cold", i)
            q.append(p)
            items.append(p)
        batch = cutter.cut(q, 10)
        assert [p.seq for p in batch] == sorted(p.seq for p in batch)
        assert [p.seq for p in q] == sorted(p.seq for p in q)
        # nothing duplicated or lost
        assert {id(p) for p in batch} | {id(p) for p in q} == \
            {id(p) for p in items}
        assert len(batch) + len(q) == 30

    def test_hot_tenant_cannot_starve_cold_rows(self):
        """The regression the fair cut exists to kill: a 10x hot tenant
        fills at most its share of each contended cut, so a cold tenant's
        lone rows ride the NEXT batch, not the end of the hot backlog."""
        cutter = FairCutter(lambda t: 1.0)
        q = deque([P("hot", i) for i in range(200)])
        q.append(P("cold", 999))
        batch = cutter.cut(q, 16)
        assert any(p.config_name == "cold" for p in batch)


# ---------------------------------------------------------------------------
# fairness must reorder, never re-decide: byte-identical verdicts
# ---------------------------------------------------------------------------


class TestFairnessExactness:
    def test_verdict_and_attribution_identical_fair_vs_unfair(self):
        """Property (ISSUE 15 satellite): the same multi-tenant workload
        through a fair-cut engine and an unfair (tenant_qos=False) engine
        produces byte-identical per-request (rule, skipped) columns."""
        fair = build_engine(n_tenants=3, tenant_qos=True)
        unfair = build_engine(n_tenants=3, tenant_qos=False)
        docs = [doc(i, allow=(i % 3 != 1)) for i in range(48)]
        names = [f"t{i % 3}" for i in range(48)]

        async def burst(engine):
            outs = await asyncio.gather(
                *(engine.submit(d, n) for d, n in zip(docs, names)))
            return outs

        got_fair = run(burst(fair))
        got_unfair = run(burst(unfair))
        for (r1, s1), (r2, s2) in zip(got_fair, got_unfair):
            assert np.array_equal(np.asarray(r1), np.asarray(r2))
            assert np.array_equal(np.asarray(s1), np.asarray(s2))

    def test_contended_cut_is_fair_in_the_engine(self):
        """Structural: with tenancy on, the engine's contended cuts run
        through the FairCutter (the cutter's counters move)."""
        engine = build_engine(n_tenants=2, max_batch=4)
        docs = [doc(i) for i in range(64)]

        async def burst():
            await asyncio.gather(*(
                engine.submit(d, f"t{i % 2}") for i, d in enumerate(docs)))

        run(burst())
        assert engine.tenancy.cutter.cuts > 0


# ---------------------------------------------------------------------------
# per-tenant quotas + tenant-aware doomed depth
# ---------------------------------------------------------------------------


class TestTenantQuota:
    def test_over_quota_tenant_rejected_typed_and_scoped(self):
        engine = build_engine(
            n_tenants=2,
            annotations={"t0": {"authorino.tpu/qos-quota-rps": "1"}})

        async def burst():
            codes = []
            ok = 0
            for i in range(40):
                try:
                    await engine.submit(doc(i), "t0")
                    ok += 1
                except CheckAbort as e:
                    codes.append((e.code, e.message))
            # the un-quota'd tenant keeps its full budget
            for i in range(8):
                await engine.submit(doc(i), "t1")
            return ok, codes

        ok, codes = run(burst())
        assert codes, "quota never fired"
        assert ok >= 1, "the burst allowance must admit the first arrivals"
        assert all(c == RESOURCE_EXHAUSTED for c, _ in codes)
        assert all("tenant t0" in m for _, m in codes)
        # tenant-scoped: the GLOBAL latch is untouched
        assert engine.admission.state == ADMIT
        rej = engine.tenancy.admission.rejected["t0"]
        assert rej[R_TENANT_QUOTA] == len(codes)
        assert "t1" not in engine.tenancy.admission.rejected

    def test_doom_depth_is_per_tenant(self):
        book = WeightBook()
        book.rebuild({"hot": None, "cold": None})
        adm = TenantAdmission(book)
        for _ in range(1000):
            adm.on_enqueue("hot")
        # the cold tenant waits behind ITS backlog (none), not the hot
        # tenant's 1000-deep standing queue
        assert adm.doom_depth("cold", 1000) == 0
        # the hot tenant's effective depth: backlog / its fair share (1/2)
        assert adm.doom_depth("hot", 1000) == 1000  # clamped to global
        adm.on_dequeue([P("hot") for _ in range(900)])
        assert adm.doom_depth("hot", 100) == 100

    def test_queue_share_bound_scopes_to_the_flooding_tenant(self):
        """Per-tenant queue-occupancy bound: once the shared queue is past
        half its cap, the tenant whose own backlog exceeds its GLOBAL
        weighted share of the cap is rejected typed — other tenants keep
        admitting, and below half-cap the bound never bites (work
        conservation)."""
        from authorino_tpu.tenancy.quota import R_TENANT_SHARE

        book = WeightBook()
        book.rebuild({f"t{i}": None for i in range(32)})
        adm = TenantAdmission(book)
        for _ in range(200):
            adm.on_enqueue("t0")
        for _ in range(3):
            adm.on_enqueue("t1")
        # queue past half the cap: the flooder is bounded...
        rej = adm.share_reject("t0", global_depth=203, effective_cap=256)
        assert rej is not None and rej[1] == R_TENANT_SHARE
        # ...its victims are not
        assert adm.share_reject("t1", 203, 256) is None
        # an idle queue absorbs bursts whole, whatever the occupancy
        assert adm.share_reject("t0", 100, 256) is None

    def test_global_share_ignores_backlog_composition(self):
        book = WeightBook()
        book.rebuild({f"t{i}": None for i in range(10)})
        assert book.global_share("t0") == pytest.approx(0.1)
        # unknown tenants ride the default weight against the known set
        assert book.global_share("stranger") == pytest.approx(1.0 / 11.0)

    def test_admission_controller_uses_doom_depth(self):
        ctrl = AdmissionController("x", target_s=0.01)
        ctrl._service_rate = 100.0  # 100 rows/s
        now = time.monotonic()
        deadline = now + 0.5
        # global depth 1000 -> predicted wait 10s: doomed
        assert ctrl.admit(1000, now=now, deadline=deadline) is not None
        # same global depth but a 0-deep tenant view: admitted (depth
        # bounds still read the REAL depth — min_cap floor admits here)
        assert ctrl.admit(0, now=now, deadline=deadline,
                          doom_depth=0) is None


# ---------------------------------------------------------------------------
# per-tenant stats folds + KeyedBurn
# ---------------------------------------------------------------------------


class _StubHeat:
    configs_per_shard = None

    def __init__(self, names):
        self.names = names

    def name(self, row, shard=None):
        return self.names[row] if 0 <= row < len(self.names) else ""


class TestTenantStats:
    def test_fold_is_vectorized_per_batch(self):
        stats = TenantStats("test-lane")
        heat = _StubHeat(["a", "b"])
        rows = np.array([0, 0, 0, 1, 0, 1])
        firing = np.array([-1, 0, -1, -1, 2, -1])
        waits = np.array([0.01, 0.02, 0.03, 0.001, 0.02, 0.002])
        stats.fold(heat, rows, firing=firing, waits=waits,
                   bad_mask=waits > 0.015)
        assert stats.fold_calls == 1
        j = stats.to_json()
        by = {r["tenant"]: r for r in j["top"]}
        assert by["a"]["requests"] == 4 and by["a"]["denies"] == 2
        assert by["b"]["requests"] == 2 and by["b"]["denies"] == 0
        assert by["a"]["slo_bad"] == 3 and by["b"]["slo_bad"] == 0

    def test_shares_decay_toward_live_traffic(self):
        stats = TenantStats("test-lane2")
        heat = _StubHeat(["hot", "cold"])
        t0 = time.monotonic()
        for k in range(10):
            stats.fold(heat, np.array([0] * 9 + [1]),
                       firing=np.full(10, -1), now=t0 + 0.1 * (k + 1))
        shares = stats.shares()
        assert shares["hot"] > 5 * shares["cold"]

    def test_keyed_burn_window(self):
        burn = KeyedBurn(window_s=10.0, objective=0.9)
        t0 = 1000.0
        burn.fold("t", 100, 50, now=t0)
        assert burn.burn("t", now=t0) == pytest.approx(5.0)
        # a full window later the old halves age out
        burn.fold("t", 100, 0, now=t0 + 11.0)
        assert burn.burn("t", now=t0 + 11.0) == pytest.approx(0.0)

    def test_top_k_bound_caps_minted_labels(self):
        from authorino_tpu.utils import metrics as metrics_mod

        stats = TenantStats("test-lane3", top_k=4)
        heat = _StubHeat([f"cfg{i}" for i in range(100)])
        stats.fold(heat, np.arange(100), firing=np.full(100, -1))
        stats.flush()
        bound = metrics_mod.TENANT_LABEL_BOUNDS[
            "auth_server_tenant_requests_total"]
        assert len(stats._label_of) <= bound


# ---------------------------------------------------------------------------
# noisy-neighbor containment: detect, contain, auto-release
# ---------------------------------------------------------------------------


class TestContainment:
    def _detector(self, wait=None):
        wait = [0.5] if wait is None else wait
        book = WeightBook()
        book.rebuild({"hot": None, "c1": None, "c2": None, "c3": None})
        stats = TenantStats("contain-lane")
        det = NoisyNeighborDetector(
            book, stats, wait_ewma=lambda: wait[0],
            target_s=lambda: 0.05, lane="contain-lane",
            threshold=2.0, sustain_s=0.0, release_s=0.0)
        return book, stats, det, wait

    def _feed(self, stats, hot_frac, t0, k0=0, n=10):
        heat = _StubHeat(["hot", "c1", "c2", "c3"])
        hot_n = int(16 * hot_frac)
        rows = np.array([0] * hot_n + [1, 2, 3] * ((16 - hot_n) // 3 + 1))
        for k in range(n):
            stats.fold(heat, rows[:16], firing=np.full(16, -1),
                       now=t0 + 0.1 * (k0 + k + 1))

    def test_contain_fires_and_auto_releases(self):
        book, stats, det, wait = self._detector()
        t0 = time.monotonic()
        self._feed(stats, hot_frac=0.9, t0=t0)
        ring0 = RECORDER.events_total
        det.check(now=t0 + 2.0)
        assert det.is_contained("hot")
        assert det.contain_total == 1
        assert RECORDER.events_total > ring0  # tenant-contained recorded
        # decay: traffic rebalances and the global wait clears
        self._feed(stats, hot_frac=0.25, t0=t0 + 2.0, k0=20, n=30)
        wait[0] = 0.0
        det.check(now=t0 + 10.0)
        assert not det.is_contained("hot")
        assert det.release_total == 1

    def test_no_containment_without_global_pressure(self):
        """A hot tenant on an idle box is just traffic: the fair cut
        already bounds its share — containment needs BOTH conditions."""
        book, stats, det, wait = self._detector(wait=[0.0])
        t0 = time.monotonic()
        self._feed(stats, hot_frac=0.9, t0=t0)
        det.check(now=t0 + 2.0)
        assert not det.has_contained()

    def test_contained_pacing_rejects_past_allowance(self):
        book, stats, det, wait = self._detector()
        det.allowance_rps = 1.0
        t0 = time.monotonic()
        self._feed(stats, hot_frac=0.9, t0=t0)
        det.check(now=t0 + 2.0)
        assert det.is_contained("hot")
        now = t0 + 2.001  # on the detector's own (synthetic) timeline
        allowed = sum(1 for _ in range(50)
                      if not det.pace_reject("hot", now=now))
        assert 1 <= allowed < 50  # the burst allowance, then paced drops

    def test_engine_wires_contained_rejection_typed(self):
        engine = build_engine(n_tenants=2)
        det = engine.tenancy.detector
        det._contained["t0"] = {"since": time.monotonic()}
        from authorino_tpu.tenancy.quota import TokenBucket

        det._pacers["t0"] = TokenBucket(0.000001, burst=0.000001)

        async def one():
            try:
                await engine.submit(doc(1), "t0")
                return None
            except CheckAbort as e:
                return e

        e = run(one())
        assert e is not None and e.code == RESOURCE_EXHAUSTED
        assert "tenant t0" in e.message
        assert engine.admission.state == ADMIT
        rej = engine.tenancy.admission.rejected["t0"]
        assert rej[R_TENANT_CONTAINED] == 1
        det._contained.clear()
        det._pacers.clear()


# ---------------------------------------------------------------------------
# lane parity (satellite): degraded batches still burn the right tenant
# ---------------------------------------------------------------------------


class TestLaneParity:
    def test_degrade_lane_feeds_tenant_fold(self):
        """Breaker OPEN -> whole batches decide via the host oracle: the
        tenant counters must move exactly like the device lane's."""
        engine = build_engine(n_tenants=2, breaker_threshold=1)
        for _ in range(3):
            engine.breaker.record_failure()

        async def burst():
            await asyncio.gather(*(
                engine.submit(doc(i, allow=False), f"t{i % 2}")
                for i in range(8)))

        run(burst())
        j = engine.tenancy.stats.to_json()
        by = {r["tenant"]: r for r in j["top"]}
        assert by["t0"]["requests"] == 4 and by["t1"]["requests"] == 4
        assert by["t0"]["denies"] == 4 and by["t1"]["denies"] == 4

    def test_device_and_host_lane_counts_agree(self):
        """The same workload with and without a forced-open breaker lands
        identical per-tenant request/deny counts (parity across lanes)."""
        counts = {}
        for mode, threshold in (("device", 5), ("degrade", 1)):
            engine = build_engine(n_tenants=2, breaker_threshold=threshold)
            if mode == "degrade":
                for _ in range(3):
                    engine.breaker.record_failure()

            async def burst(engine=engine):
                await asyncio.gather(*(
                    engine.submit(doc(i, allow=(i % 4 != 1)), f"t{i % 2}")
                    for i in range(16)))

            run(burst())
            j = engine.tenancy.stats.to_json()
            counts[mode] = {r["tenant"]: (r["requests"], r["denies"])
                            for r in j["top"]}
        assert counts["device"] == counts["degrade"]


# ---------------------------------------------------------------------------
# stratified decision sampling (satellite)
# ---------------------------------------------------------------------------


class TestStratifiedDecisions:
    def test_cold_tenant_records_survive_hot_flood(self):
        log = prov_mod.DecisionLog(capacity=8, sample_n=1,
                                   tenant_capacity=2)
        log.record(lane="l", host="h", authconfig="cold", verdict=True,
                   rule=None, rule_index=-1, latency_ms=1, generation=1)
        for i in range(50):
            log.record(lane="l", host="h", authconfig="hot", verdict=False,
                       rule="0:x", rule_index=0, latency_ms=1, generation=1)
        # the global ring is all hot now...
        assert all(r["authconfig"] == "hot"
                   for r in log.to_json()["records"])
        # ...but the cold tenant's sub-ring survives
        cold = log.to_json(tenant="cold")["records"]
        assert len(cold) == 1 and cold[0]["authconfig"] == "cold"

    def test_at_most_one_record_per_tenant_per_batch(self):
        saved = (prov_mod.DECISIONS.capacity, prov_mod.DECISIONS.sample_n)
        prov_mod.DECISIONS.configure(sample_n=1)
        try:
            heat = prov_mod.HeatMap(["hot", "cold"], [["r"], ["r"]], 1)
            rows = np.array([0] * 20 + [1])
            firing = np.full(21, -1)
            before = prov_mod.DECISIONS.records_total
            prov_mod.fold_and_sample(heat, rows, firing, 21, lane="l")
            got = prov_mod.DECISIONS.records_total - before
            # one batch, two tenants -> exactly two records at 1-in-1
            assert got == 2
            names = [r["authconfig"]
                     for r in prov_mod.DECISIONS.to_json(n=2)["records"]]
            assert set(names) == {"hot", "cold"}
        finally:
            prov_mod.DECISIONS.configure(capacity=saved[0],
                                         sample_n=saved[1])

    def test_single_tenant_batches_still_one_record_per_batch(self):
        """The perf-guard contract holds: one tenant -> at most one record
        per batch whatever the batch size."""
        saved = prov_mod.DECISIONS.sample_n
        prov_mod.DECISIONS.configure(sample_n=1)
        try:
            heat = prov_mod.HeatMap(["only"], [["r"]], 1)
            before = prov_mod.DECISIONS.records_total
            prov_mod.fold_and_sample(heat, np.zeros(64, dtype=int),
                                     np.full(64, -1), 64, lane="l")
            assert prov_mod.DECISIONS.records_total - before == 1
        finally:
            prov_mod.DECISIONS.configure(sample_n=saved)

    def test_cold_tenant_first_appearance_always_samples(self):
        saved = prov_mod.DECISIONS.sample_n
        prov_mod.DECISIONS.configure(sample_n=1000)
        try:
            log = prov_mod.DECISIONS
            assert log.should_sample_tenant("brand-new-tenant", 5)
            assert not log.should_sample_tenant("brand-new-tenant", 5)
        finally:
            prov_mod.DECISIONS.configure(sample_n=saved)


# ---------------------------------------------------------------------------
# tenant-label cardinality lint (satellite, wired as tier-1)
# ---------------------------------------------------------------------------


class TestCardinalityLint:
    def test_registry_lints_clean(self):
        from authorino_tpu.analysis.metrics_catalog import (
            tenant_cardinality_lint,
        )

        assert tenant_cardinality_lint() == []

    def test_planted_violation_is_caught(self):
        from authorino_tpu.analysis.metrics_catalog import (
            _PlantedTenantFamily,
            tenant_cardinality_lint,
            tenant_lint_self_test,
        )

        violations = tenant_cardinality_lint(
            extra=(_PlantedTenantFamily(),))
        assert any("planted_violation" in v for v in violations)
        # the combined self-test (what --verify-fixtures runs) is clean
        assert tenant_lint_self_test() == []

    def test_stale_bound_is_caught(self):
        from authorino_tpu.analysis.metrics_catalog import (
            tenant_cardinality_lint,
        )
        from authorino_tpu.utils import metrics as metrics_mod

        bounds = dict(metrics_mod.TENANT_LABEL_BOUNDS)
        bounds["auth_server_tenant_ghost_total"] = 8
        assert any("ghost" in v for v in tenant_cardinality_lint(bounds))

    def test_missing_bound_is_caught(self):
        from authorino_tpu.analysis.metrics_catalog import (
            tenant_cardinality_lint,
        )
        from authorino_tpu.utils import metrics as metrics_mod

        bounds = dict(metrics_mod.TENANT_LABEL_BOUNDS)
        bounds.pop("auth_server_tenant_requests_total")
        assert any("tenant_requests" in v
                   for v in tenant_cardinality_lint(bounds))


# ---------------------------------------------------------------------------
# per-tenant canary guard (tenant-rejection-rate)
# ---------------------------------------------------------------------------


class TestTenantCanaryGuard:
    def test_tenant_rejection_delta_breaches(self):
        from authorino_tpu.runtime.change_safety import CanaryGuard

        guard = CanaryGuard(changed={"t"}, check_interval_s=0.0)
        heat = _StubHeat(["t"])
        rows = np.zeros(16, dtype=int)
        firing = np.full(16, -1)
        for _ in range(4):
            guard.observe_batch(False, rows, firing, heat)
            guard.observe_batch(True, rows, firing, heat)
        # the canary cohort's tenant eats rejections the baseline doesn't
        guard.observe_tenant_rejection(True, "t", n=64)
        breach = guard.breach(force=True)
        assert breach is not None
        assert "tenant-rejection-rate" in breach["guards"]
        assert "t" in breach["suspects"]

    def test_unchanged_tenant_rejections_do_not_breach(self):
        from authorino_tpu.runtime.change_safety import CanaryGuard

        guard = CanaryGuard(changed={"other"}, check_interval_s=0.0)
        heat = _StubHeat(["t"])
        rows = np.zeros(16, dtype=int)
        firing = np.full(16, -1)
        for _ in range(4):
            guard.observe_batch(False, rows, firing, heat)
            guard.observe_batch(True, rows, firing, heat)
        guard.observe_tenant_rejection(True, "t", n=64)
        assert guard.breach(force=True) is None


# ---------------------------------------------------------------------------
# /debug/tenants + /debug/decisions?tenant=
# ---------------------------------------------------------------------------


class TestDebugSurfaces:
    def test_debug_tenants_endpoint(self):
        from aiohttp.test_utils import TestClient, TestServer

        from authorino_tpu.service.http_server import build_app

        engine = build_engine(n_tenants=2)

        async def body():
            await engine.submit(doc(1), "t0")
            client = TestClient(TestServer(build_app(engine)))
            await client.start_server()
            try:
                resp = await client.get("/debug/tenants")
                assert resp.status == 200
                plane = await resp.json()
                resp2 = await client.get("/debug/decisions?tenant=t0")
                assert resp2.status == 200
                dec = await resp2.json()
            finally:
                await client.close()
            return plane, dec

        plane, dec = run(body())
        assert plane["enabled"] is True
        assert plane["stats"]["requests_total"] >= 1
        assert dec["tenant"] == "t0"

    def test_engine_debug_vars_carry_tenancy(self):
        engine = build_engine(n_tenants=1)
        dv = engine.debug_vars()
        assert dv["tenancy"]["enabled"] is True
        assert "containment" in dv["tenancy"]
        assert "fair_cut" in dv["tenancy"]


# ---------------------------------------------------------------------------
# repo hygiene: the new subsystem stays clean
# ---------------------------------------------------------------------------


def test_tenancy_code_stays_clean():
    import os

    from authorino_tpu.analysis.code_lint import lint_paths

    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "authorino_tpu", "tenancy")
    assert [str(f) for f in lint_paths([root])] == []
